"""Dev tool: how matmul-bound is the bench train step?

Times a pure-GEMM replay of the training step's entire matmul schedule —
per layer and per direction (fwd, dx, dw at their true shapes), the
chunked-CE head's three GEMMs, and the actual flash-attention fwd+bwd
kernels — and compares that floor against the measured end-to-end step.

floor/step >= 0.90 means the remaining MFU gap is in the matmuls
themselves (shape/tiling limits), not in elementwise work, the optimizer,
or dispatch — the "provably done" criterion for the utilization ladder.
Timing method: per-op cost is the SLOPE between a long-scan and a
length-1 call — the tunnel's per-call round-trip is ~100 ms with +-30 ms
jitter, so amortizing one call is not enough (see timed()).

Usage: python profile_matmul_bound.py [model] [mbs]
"""
import dataclasses
import json
import os
import sys
import time

import jax
import jax.numpy as jnp

from deepspeed_tpu.models import GPT2_CONFIGS
from deepspeed_tpu.models.gpt2 import gpt2_flops_per_token

MODEL = sys.argv[1] if len(sys.argv) > 1 else "gpt2-large"
MBS = int(sys.argv[2]) if len(sys.argv) > 2 else 4
N = 256         # long-scan length: in-call work must dwarf tunnel jitter

cfg = dataclasses.replace(GPT2_CONFIGS[MODEL], max_seq_length=1024)
S, H, V = cfg.max_seq_length, cfg.hidden_size, cfg.vocab_size
I = cfg.intermediate_size or 4 * H    # 0 = derived 4H (models.transformer)
nH, D = cfg.num_heads, cfg.hidden_size // cfg.num_heads
L, BS = cfg.num_layers, MBS * cfg.max_seq_length
key = jax.random.PRNGKey(0)


def timed(fn, *args):
    """ms per op via a two-point scan slope.

    Tunnel measurement rules learned the hard way (see memory notes):
    - per-call round-trip is ~100 ms with +-30 ms jitter, so the work
      inside ONE call must dwarf it -> scan length N (large), and the
      N=1 call time is SUBTRACTED (slope), not amortized;
    - the keep-alive feedback must need the full output: a one-element
      read lets XLA rewrite slice-of-dot into a vector dot and the GEMM
      evaporates; jnp.max(out) cannot be simplified away.
    """
    def make(length):
        @jax.jit
        def many(x, *rest):
            def body(c, _):
                out = fn(c, *rest)
                # max BEFORE any cast: astype would materialize a full
                # f32 copy of the output every iteration
                fb = jnp.max(out).astype(c.dtype)
                return c + fb * 1e-12, None
            c, _ = jax.lax.scan(body, x, None, length=length)
            return c
        return many

    def best(fn_, reps=3):
        _ = jax.block_until_ready(fn_(*args))
        _ = float(jnp.max(fn_(*args).astype(jnp.float32)))
        b = 1e9
        for _i in range(reps):
            t0 = time.perf_counter()
            _ = float(jnp.max(fn_(*args).astype(jnp.float32)))
            b = min(b, time.perf_counter() - t0)
        return b * 1e3

    t_long, t_one = best(make(N)), best(make(1))
    return max(t_long - t_one, 1e-6) / (N - 1)


def gemm_ms(m, k, n):
    """One [m,k]@[k,n] bf16 GEMM, timed in-scan."""
    a = jax.random.normal(key, (m, k), jnp.bfloat16)
    b = jax.random.normal(jax.random.fold_in(key, 1), (k, n), jnp.bfloat16)
    return timed(lambda aa, bb: jnp.dot(aa, bb,
                                        preferred_element_type=jnp.bfloat16),
                 a, b)


def linear_triple_ms(m, k, n):
    """fwd [m,k]@[k,n] + dx [m,n]@[n,k] + dw [k,m]@[m,n]."""
    return gemm_ms(m, k, n) + gemm_ms(m, n, k) + gemm_ms(k, m, n)


def flash_ms():
    from deepspeed_tpu.ops.flash_attention import flash_attention
    q = jax.random.normal(key, (MBS, S, nH, D), jnp.bfloat16)

    def fwd(qq):
        return flash_attention(qq, q, q, causal=True)

    def fb(qq):
        return jax.grad(lambda x: jnp.sum(
            fwd(x).astype(jnp.float32) ** 2))(qq)

    return timed(fwd, q), timed(fb, q)


def elementwise_ms():
    """Fused LN / bias+GELU kernels at the model's true shapes (fwd and
    fwd+bwd) — the measured cost of the elementwise work the ISSUE-8
    kernels leave on the table. TPU only (interpret-mode Pallas times
    the interpreter)."""
    from deepspeed_tpu.ops.fused_elementwise import (fused_bias_gelu,
                                                     fused_layer_norm)
    x = jax.random.normal(key, (BS, H), jnp.bfloat16)
    sc = jnp.ones((H,), jnp.float32)
    bi = jnp.zeros((H,), jnp.float32)
    y = jax.random.normal(key, (BS, I), jnp.bfloat16)
    bf = jnp.zeros((I,), jnp.float32)

    ln_fb = timed(lambda xx: jax.grad(lambda v: jnp.sum(
        fused_layer_norm(v, sc, bi).astype(jnp.float32) ** 2))(xx), x)
    ge_fb = timed(lambda yy: jax.grad(lambda v: jnp.sum(
        fused_bias_gelu(v, bf).astype(jnp.float32) ** 2))(yy), y)
    return ln_fb, ge_fb


def optimizer_apply_ms():
    """Analytic one-pass vs two-pass optimizer apply at the model's
    param count (ops/fused_update.apply_hbm_bytes priced at the chip
    HBM ceiling) — valid on any backend, it is arithmetic."""
    from deepspeed_tpu.models.gpt2 import gpt2_num_params
    from deepspeed_tpu.monitor.peaks import chip_peaks
    from deepspeed_tpu.ops.fused_update import apply_hbm_bytes
    n = gpt2_num_params(cfg)
    # Bench flags: master-free bf16 (params bf16, f32 moments), no
    # gradient clipping, no fp16 — at these flags one-pass == two-pass
    # in bytes (the honest model; fp16/cast configs are where the
    # two-pass sequencing paid extra passes).
    fake = {"p": jax.ShapeDtypeStruct((n,), jnp.bfloat16)}
    pricing = apply_hbm_bytes(fake, one_pass=True, clip=False, fp16=False)
    hbm = chip_peaks().hbm_bytes_per_sec
    return (pricing["one_pass"] / hbm * 1e3,
            pricing["two_pass"] / hbm * 1e3)


def _recorded_tok_s():
    """Latest recorded bench round's tok/s (BENCH_r06 falls back r05).
    Parser and fallback come from ablate_fused_ln (one definition for
    both tools — they must derive the gap from the same baseline)."""
    import glob
    import re as _re
    from ablate_fused_ln import R05_DEFAULTS, parse_tok_s
    here = os.path.dirname(os.path.abspath(__file__))
    rounds = sorted(glob.glob(os.path.join(here, "BENCH_r*.json")))
    for path in reversed(rounds):
        if not _re.fullmatch(r"BENCH_r\d+\.json", os.path.basename(path)):
            continue
        try:
            with open(path) as f:
                parsed = json.load(f).get("parsed", {})
            tok_s = parse_tok_s(parsed.get("unit", ""))
            if tok_s:
                return (tok_s, os.path.basename(path),
                        bool(parsed.get("projected")))
        except Exception:
            continue
    return R05_DEFAULTS["tok_s"], "fallback(r05)", False


def main():
    print(f"{MODEL} mbs={MBS}: GEMM floor per train step", flush=True)
    per_layer = (linear_triple_ms(BS, H, 3 * H)     # qkv
                 + linear_triple_ms(BS, H, H)       # attn proj
                 + linear_triple_ms(BS, H, I)       # fc1
                 + linear_triple_ms(BS, I, H))      # fc2
    t_head = linear_triple_ms(BS, H, V)             # chunked-CE GEMMs
    t_attn_f, t_attn_fb = flash_ms()
    # remat "dots_flash" saves flash residuals: attention cost = fwd + the
    # fused bwd pass (which internally replays fwd once) = t_attn_fb.
    floor = per_layer * L + t_head + t_attn_fb * L
    print(f"  linear GEMMs x{L}: {per_layer * L:7.1f} ms "
          f"({per_layer:.3f}/layer)", flush=True)
    print(f"  CE-head GEMMs   : {t_head:7.1f} ms", flush=True)
    print(f"  flash attn x{L}  : {t_attn_fb * L:7.1f} ms "
          f"(fwd alone {t_attn_f * L:.1f})", flush=True)
    print(f"  GEMM floor      : {floor:7.1f} ms", flush=True)

    achieved_ms = None
    if len(sys.argv) > 3:
        achieved_ms = float(sys.argv[3])
        provenance = "cli"
    else:
        tok_s, provenance, projected = _recorded_tok_s()
        if projected:
            provenance += " (projected)"
        achieved_ms = MBS * S / tok_s * 1e3
    ratio = floor / achieved_ms
    flops = gpt2_flops_per_token(cfg, S) * MBS * S
    print(f"  achieved step   : {achieved_ms:7.1f} ms "
          f"({flops / achieved_ms / 1e9:.1f} TFLOPs) [{provenance}]",
          flush=True)
    print(f"  floor MFU       : {flops / floor / 1e9:7.1f} TFLOPs if "
          f"matmuls alone", flush=True)
    print(f"  matmul-bound ratio: {ratio:.2f} "
          f"({'>=0.90: matmul-bound' if ratio >= 0.9 else 'gap is non-GEMM work'})",
          flush=True)

    # --- ISSUE-8 non-GEMM decomposition: where the residual gap sits
    # with the fused kernels + one-pass optimizer in place. ---
    one_ms, two_ms = optimizer_apply_ms()
    print(f"  optimizer apply : {one_ms:7.1f} ms analytic one-pass "
          f"(two-pass {two_ms:.1f} at the bench flags — byte-equal "
          "here; fp16/cast configs are where two-pass paid more)",
          flush=True)
    if jax.devices()[0].platform == "tpu":
        ln_fb, ge_fb = elementwise_ms()
        elem = (ln_fb * 3 + ge_fb) * L   # 2 block LNs + ln_f share + GELU
        print(f"  fused LN/GELU   : {elem:7.1f} ms measured "
              f"(LN fwd+bwd {ln_fb:.3f}, GELU fwd+bwd {ge_fb:.3f} "
              f"per layer-instance)", flush=True)
    else:
        elem = None
        print("  fused LN/GELU   : skipped (CPU dev box — interpret-"
              "mode Pallas times the interpreter; see BENCH_r06's "
              "analytic model)", flush=True)
    residual = achieved_ms - floor - one_ms - (elem or 0.0)
    print(f"  residual non-GEMM gap: {residual:7.1f} ms "
          "(dispatch, remaining elementwise, grad-accum plumbing)",
          flush=True)


if __name__ == "__main__":
    main()
