"""Perf ablation for the fused elementwise kernels + one-pass optimizer
(ISSUE 8, dev tool).

Two modes, auto-selected by backend:

- **TPU**: measure.  Runs the engine-step ablation grid
  (``bench.bench_kernels_ablation``: fused/unfused elementwise x
  one-pass/two-pass optimizer) on the bench model and records the
  measured step times — the ladder evidence.
- **CPU dev box**: project.  Interpret-mode Pallas timings measure the
  interpreter, not the kernels, so the tool computes the ANALYTIC
  saving instead and prices it against the last measured TPU round
  (BENCH_r05): the one-pass optimizer removes the separate full-tree
  norm read (a structural f32 pass over every gradient element), and
  the fused elementwise kernels remove a conservative count of
  residual-stream round-trips the unfused chain makes (assumptions
  recorded in the artifact).  The resulting record is labeled
  ``"projected": true`` everywhere — it is a model, not a measurement.

``--record`` writes BENCH_r06.json in the driver-round shape
(``{"n": 6, "parsed": {bench record}}``) so ``tools/bench_gate.py``
diffs it against BENCH_r05 like any other round.

Usage: python ablate_fused_ln.py [model] [--record]
"""
import dataclasses
import json
import os
import re
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models import GPT2_CONFIGS
from deepspeed_tpu.models.gpt2 import gpt2_flops_per_token, gpt2_num_params
from deepspeed_tpu.monitor.peaks import chip_peaks, chip_peak_tflops

REPO = os.path.dirname(os.path.abspath(__file__))
RECORD = "--record" in sys.argv
ARGS = [a for a in sys.argv[1:] if not a.startswith("--")]
MODEL = ARGS[0] if ARGS else "gpt2-large"
R05 = os.path.join(REPO, "BENCH_r05.json")
OUT = os.path.join(REPO, "BENCH_r06.json")

# BENCH_r05's measured bench point (the projection baseline); re-read
# from the artifact when present so the numbers cannot drift apart.
# profile_matmul_bound.py imports BOTH of these — one definition of the
# fallback and one parser, so the two tools can never disagree on the
# baseline.
R05_DEFAULTS = {"tflops": 108.36, "tok_s": 20826.0, "mbs": 4}
_TOK_S_RE = re.compile(r"([\d,.]+)\s*tok/s")


def parse_tok_s(unit: str):
    """tok/s out of a bench record's unit string, thousands-separator
    safe ("... 20,826 tok/s, 55.0% of peak ..."); None when absent."""
    m = _TOK_S_RE.search(unit or "")
    return float(m.group(1).replace(",", "")) if m else None


def _r05_point():
    out = dict(R05_DEFAULTS)
    try:
        with open(R05) as f:
            parsed = json.load(f).get("parsed", {})
        out["tflops"] = float(parsed.get("value", out["tflops"]))
        tok_s = parse_tok_s(parsed.get("unit", ""))
        if tok_s:
            out["tok_s"] = tok_s
    except Exception:
        pass
    return out


def projected_record(model_name: str):
    """The CPU-dev-box analytic projection (see module docstring)."""
    cfg = dataclasses.replace(GPT2_CONFIGS[model_name],
                              max_seq_length=1024)
    base = _r05_point()
    mbs = base["mbs"]
    S, H, L = cfg.max_seq_length, cfg.hidden_size, cfg.num_layers
    F = cfg.ffn_size
    T = mbs * S                                # tokens per step
    n_params = gpt2_num_params(cfg)
    peaks = chip_peaks()                       # assumed v5e on CPU
    hbm = peaks.hbm_bytes_per_sec

    step_ms = mbs * S / base["tok_s"] * 1e3

    # (1) One-pass optimizer: priced by the HONEST model
    # (ops/fused_update.apply_hbm_bytes) at the r05 bench flags —
    # master-free bf16, no fp16, no gradient clipping, no cast cache.
    # That delta is ZERO bytes: the bench config never computed a norm
    # and its overflow select was already a folded compile-time
    # constant.  The one-pass machinery's byte wins live in fp16
    # (~2.5x: unscale + vote + real select) and cast-cache (~1.1x)
    # configs; its bench-config win is kernel-launch count, which this
    # byte model deliberately does not price.
    from deepspeed_tpu.ops.fused_update import apply_hbm_bytes
    fake = {"p": jax.ShapeDtypeStruct((n_params,), jnp.bfloat16)}
    pricing = apply_hbm_bytes(fake, one_pass=True, clip=False, fp16=False)
    opt_saved_bytes = pricing["two_pass"] - pricing["one_pass"]
    opt_saved_ms = opt_saved_bytes / hbm * 1e3

    # (2) Fused elementwise: CONSERVATIVE per-layer pass model — only
    # round-trips that are structural in the unfused chain and
    # provably absent in the fused kernels are claimed:
    #   fwd: the residual sum is re-READ by the next LN (fused: LN
    #        consumes it in-register)                      -> 1x T*H
    #   bwd: the LN backward re-reads the saved input for its second
    #        reduction (fused: one read, stats recomputed) -> 1x T*H
    #        the GELU backward re-reads dz for the dbias
    #        reduction (fused: partial in the same pass)   -> 1x T*F
    # XLA-fusable adjacencies (bias+gelu fwd, scale+shift) are NOT
    # claimed — XLA already fuses those.
    bpe = 2                                    # bf16 activations
    elem_saved_bytes = L * bpe * (T * H + T * H + T * F)
    elem_saved_ms = elem_saved_bytes / hbm * 1e3

    new_step_ms = step_ms - opt_saved_ms - elem_saved_ms
    tok_s = mbs * S / (new_step_ms / 1e3)
    flops_per_tok = gpt2_flops_per_token(cfg, S)
    tflops = tok_s * flops_per_tok / 1e12
    frac = tflops / chip_peak_tflops()
    ref_frac = 64.0 / 125.0

    return {
        "metric": f"GPT2({H}x{L}) train TFLOPs/chip",
        "value": round(tflops, 2),
        "unit": f"TFLOPs/chip (bf16, 1 chip(s), {tok_s:,.0f} tok/s, "
                f"{frac:.1%} of peak, PROJECTED)",
        "vs_baseline": round(frac / ref_frac, 3),
        "mfu": round(frac, 4),
        "fused_optimizer_apply": True,
        "projected": True,
        "kernels": {
            "model": f"{H}x{L}",
            "fused_speedup": round(step_ms / new_step_ms, 4),
            "projected": True,
            "baseline_round": "BENCH_r05",
            "baseline_step_ms": round(step_ms, 2),
            "projected_step_ms": round(new_step_ms, 2),
            "one_pass_optimizer_saved_ms": round(opt_saved_ms, 3),
            "fused_elementwise_saved_ms": round(elem_saved_ms, 3),
            "assumptions": {
                "hbm_gb_s": round(hbm / 1e9, 1),
                "optimizer_saved_bytes": int(opt_saved_bytes),
                "elementwise_saved_bytes": int(elem_saved_bytes),
                "elementwise_model": "per layer: fwd 1xT*H residual "
                                     "re-read + bwd 1xT*H LN re-read + "
                                     "1xT*F GELU dbias re-read, bf16",
            },
            "note": "PROJECTED on the CPU dev box from BENCH_r05's "
                    "measured step + the analytic HBM-byte model above; "
                    "interpret-mode Pallas cannot time the kernels. The "
                    "one-pass optimizer term is ZERO for this bench "
                    "config (master-free bf16, no clip/fp16 — its byte "
                    "wins live in fp16/cast-cache configs; here it only "
                    "cuts launches, unpriced). A TPU session re-records "
                    "this round measured (DS_BENCH_KERNELS=1 python "
                    "bench.py). The >=70%-of-peak target needs the "
                    "measured pass; this model claims only the "
                    "structural byte savings.",
        },
    }


def measured_record():
    """TPU: the real ablation grid + headline rerun."""
    import bench
    grid = bench.bench_kernels_ablation()
    cfg, mbs = bench.pick_model()
    S = cfg.max_seq_length
    step_ms = grid["step_ms"]["fused_ln+one_pass"]
    tok_s = mbs * jax.device_count() * S / (step_ms / 1e3)
    flops_per_tok = gpt2_flops_per_token(cfg, S)
    tflops = tok_s * flops_per_tok / jax.device_count() / 1e12
    frac = tflops / chip_peak_tflops()
    return {
        "metric": f"GPT2({cfg.hidden_size}x{cfg.num_layers}) train "
                  "TFLOPs/chip",
        "value": round(tflops, 2),
        "unit": f"TFLOPs/chip (bf16, {jax.device_count()} chip(s), "
                f"{tok_s:,.0f} tok/s, {frac:.1%} of peak)",
        "vs_baseline": round(frac / (64.0 / 125.0), 3),
        "mfu": round(frac, 4),
        "fused_optimizer_apply": True,
        "kernels": grid,
    }


def main():
    if jax.devices()[0].platform == "tpu":
        record = measured_record()
    else:
        record = projected_record(MODEL)
    print(json.dumps(record, indent=1))
    if RECORD:
        round_doc = {
            "n": 6,
            "cmd": "python ablate_fused_ln.py --record",
            "rc": 0,
            "tail": json.dumps(record),
            "parsed": record,
        }
        with open(OUT, "w") as f:
            json.dump(round_doc, f, indent=1)
        print(f"[ablate_fused_ln] wrote {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
