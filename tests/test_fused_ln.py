"""Fused elementwise Pallas kernels (ops/fused_elementwise) vs the jnp
reference chain — the parity contract for the reference's fused
transformer kernels (normalize_kernels.cu / gelu_kernels.cu class).

Numerics tiers (documented bounds, PR-1 precedent):

- fp32 tensors: fused and unfused agree to a few f32 ulp — both compute
  identical fp32 expressions; the residue is cross-program reduction
  association (the same limit PR 1 documented for FMA contraction).
- bf16 tensors: within ~2 bf16 ulp of each other. The fused path rounds
  ONCE at the kernel output where the unfused chain rounds per op, so
  the fused value is the more accurate one; gradients through deep
  bf16 chains compound per-op rounding and are compared at bf16
  tolerance against the same reference.
- The fused residual sum ``s = x + delta`` is BIT-equal to the unfused
  add (round(f32 sum) IS the bf16 add).

Engine tier: gpt2-tiny on the 8-device CPU mesh (interpret-mode Pallas)
— train-step parity kernels on/off, checkpoint resume-compatibility
across the knob, serving recompile-freedom, and the materialization +
dtype_flow lint passes clean with kernels ON.
"""
import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from capability import fused_elementwise_skip_reason
from deepspeed_tpu.models.gpt2 import (GPT2_CONFIGS, gpt2_apply, gpt2_init,
                                       gpt2_loss_fn)
from deepspeed_tpu.models.transformer import (TransformerConfig,
                                              init_block_params,
                                              layer_norm,
                                              transformer_block)
from deepspeed_tpu.ops.fused_elementwise import (fused_bias_gelu,
                                                 fused_elementwise_enabled,
                                                 fused_layer_norm,
                                                 fused_residual_layer_norm)
from deepspeed_tpu.parallel.topology import build_mesh
from deepspeed_tpu.runtime.engine import DeepSpeedEngine

pytestmark = pytest.mark.skipif(
    fused_elementwise_skip_reason() is not None,
    reason=fused_elementwise_skip_reason() or "")

F32_RTOL, F32_ATOL = 1e-5, 1e-6
BF16_RTOL, BF16_ATOL = 0.05, 0.05      # ~2 bf16 ulp at unit magnitude


def _tols(dtype):
    return (BF16_RTOL, BF16_ATOL) if dtype == jnp.bfloat16 \
        else (F32_RTOL, F32_ATOL)


def _rand(shape, seed, dtype=jnp.float32):
    r = np.random.default_rng(seed)
    return jnp.asarray(r.standard_normal(shape), jnp.float32).astype(dtype)


def _close(a, b, dtype, scale=1.0):
    rtol, atol = _tols(dtype)
    np.testing.assert_allclose(np.asarray(a, np.float32),
                               np.asarray(b, np.float32),
                               rtol=rtol * scale, atol=atol * scale)


# --------------------------------------------------------------------- #
# Kernel tier
# --------------------------------------------------------------------- #
class TestLayerNormParity:
    # H=100 exercises the lane-pad mask; 1600 the multi-of-128-but-not-
    # power-of-two width of gpt2-xl.
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    @pytest.mark.parametrize("H", [128, 100, 1600])
    def test_fwd_parity(self, dtype, H):
        x = _rand((2, 17, H), 0, dtype)
        sc, bi = _rand((H,), 1), _rand((H,), 2)
        y = jax.jit(lambda *a: fused_layer_norm(*a, 1e-5))(x, sc, bi)
        assert y.dtype == dtype and y.shape == x.shape
        _close(y, layer_norm(x, sc, bi, 1e-5), dtype)

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    @pytest.mark.parametrize("H", [128, 100])
    def test_bwd_parity(self, dtype, H):
        x = _rand((3, 9, H), 3, dtype)
        sc, bi = _rand((H,), 4), _rand((H,), 5)

        def loss(fn):
            def run(x, sc, bi):
                return jnp.sum(fn(x, sc, bi).astype(jnp.float32) ** 2)
            return jax.grad(run, argnums=(0, 1, 2))(x, sc, bi)

        gf = loss(lambda x, s, b: fused_layer_norm(x, s, b, 1e-5))
        gr = loss(lambda x, s, b: layer_norm(x, s, b, 1e-5))
        for a, b in zip(gf, gr):
            # dscale/dbias sum over all rows: scale tolerance with the
            # row count (reduction of per-element rounding residue).
            _close(a, b, dtype, scale=4.0)

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_residual_sum_bit_parity(self, dtype):
        """The fused s = x + delta is BITWISE the unfused add: one f32
        sum rounded once IS the dtype's add."""
        H = 256
        x, d = _rand((4, 8, H), 6, dtype), _rand((4, 8, H), 7, dtype)
        sc, bi = _rand((H,), 8), _rand((H,), 9)
        s, y = jax.jit(lambda *a: fused_residual_layer_norm(*a, 1e-5))(
            x, d, sc, bi)
        np.testing.assert_array_equal(
            np.asarray(s, np.float32), np.asarray(x + d, np.float32))
        _close(y, layer_norm(x + d, sc, bi, 1e-5), dtype)

    def test_residual_bwd_carries_both_cotangents(self):
        """grad flows through BOTH outputs (s continues the residual
        stream, y feeds the sublayer) and dx == ddelta."""
        H = 128
        x, d = _rand((2, 4, H), 10), _rand((2, 4, H), 11)
        sc, bi = _rand((H,), 12), _rand((H,), 13)

        def fused(x, d, sc, bi):
            s, y = fused_residual_layer_norm(x, d, sc, bi, 1e-5)
            return jnp.sum(y ** 2) + jnp.sum(jnp.sin(s))

        def ref(x, d, sc, bi):
            s = x + d
            return jnp.sum(layer_norm(s, sc, bi, 1e-5) ** 2) + \
                jnp.sum(jnp.sin(s))

        gf = jax.grad(fused, argnums=(0, 1, 2, 3))(x, d, sc, bi)
        gr = jax.grad(ref, argnums=(0, 1, 2, 3))(x, d, sc, bi)
        for a, b in zip(gf, gr):
            _close(a, b, jnp.float32, scale=4.0)
        np.testing.assert_array_equal(np.asarray(gf[0]), np.asarray(gf[1]))


class TestBiasGelu:
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    @pytest.mark.parametrize("exact", [False, True])
    def test_fwd_parity(self, dtype, exact):
        F = 512
        y, b = _rand((33, F), 20, dtype), _rand((F,), 21)
        out = jax.jit(lambda y, b: fused_bias_gelu(y, b, exact))(y, b)
        ref = jax.nn.gelu(y + b.astype(y.dtype), approximate=not exact)
        assert out.dtype == dtype
        _close(out, ref, dtype)

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_bwd_parity(self, dtype):
        F = 384
        y, b = _rand((16, F), 22, dtype), _rand((F,), 23)

        def loss(fn):
            def run(y, b):
                return jnp.sum(fn(y, b).astype(jnp.float32) ** 2)
            return jax.grad(run, argnums=(0, 1))(y, b)

        gf = loss(lambda y, b: fused_bias_gelu(y, b))
        gr = loss(lambda y, b: jax.nn.gelu(y + b.astype(y.dtype),
                                           approximate=True))
        _close(gf[0], gr[0], dtype, scale=4.0)
        # dbias sums dz over ALL rows — bf16 per-op rounding of the
        # unfused chain accumulates linearly with the row count.
        _close(gf[1], gr[1], dtype, scale=16.0)


class TestKnobResolution:
    def test_forced_values(self):
        assert fused_elementwise_enabled(True) is True
        assert fused_elementwise_enabled(False) is False

    def test_auto_follows_backend_and_env(self, monkeypatch):
        monkeypatch.delenv("DS_FUSED_ELEMENTWISE", raising=False)
        expect = jax.default_backend() == "tpu"
        assert fused_elementwise_enabled("auto") is expect
        monkeypatch.setenv("DS_FUSED_ELEMENTWISE", "1")
        assert fused_elementwise_enabled("auto") is True
        monkeypatch.setenv("DS_FUSED_ELEMENTWISE", "0")
        assert fused_elementwise_enabled("auto") is False
        # forced values beat the env override
        monkeypatch.setenv("DS_FUSED_ELEMENTWISE", "1")
        assert fused_elementwise_enabled(False) is False


# --------------------------------------------------------------------- #
# Block / model tier
# --------------------------------------------------------------------- #
def _block_cfg(**over):
    base = dict(hidden_size=128, num_heads=4, num_layers=2,
                max_seq_length=32, vocab_size=512, hidden_dropout=0.0,
                attn_dropout=0.0, dtype=jnp.float32, causal=True)
    base.update(over)
    return TransformerConfig(**base)


class TestBlockParity:
    @pytest.mark.parametrize("pre_ln", [True, False])
    def test_block_fwd_bwd_parity_fp32(self, pre_ln):
        cfg_on = _block_cfg(pre_layer_norm=pre_ln, fused_kernels=True)
        cfg_off = dataclasses.replace(cfg_on, fused_kernels=False)
        params = jax.tree_util.tree_map(
            lambda t: t[0], init_block_params(jax.random.PRNGKey(0),
                                              cfg_on, num_layers=1))
        x = _rand((2, 16, 128), 30)

        def run(cfg):
            def loss(p, x):
                return jnp.sum(transformer_block(p, x, cfg) ** 2)
            v, g = jax.value_and_grad(loss)(params, x)
            return v, g

        v_on, g_on = run(cfg_on)
        v_off, g_off = run(cfg_off)
        np.testing.assert_allclose(float(v_on), float(v_off), rtol=1e-5)
        for k in g_on:
            _close(g_on[k], g_off[k], jnp.float32, scale=10.0)

    def test_gpt2_apply_parity_bf16(self):
        cfg_off = dataclasses.replace(GPT2_CONFIGS["gpt2-tiny"],
                                      hidden_dropout=0.0, attn_dropout=0.0,
                                      fused_kernels=False)
        cfg_on = dataclasses.replace(cfg_off, fused_kernels=True)
        params = gpt2_init(jax.random.PRNGKey(0), cfg_off)
        toks = jnp.asarray(np.random.default_rng(0).integers(
            0, cfg_off.vocab_size, (2, 33)), jnp.int32)
        lo = jax.jit(lambda p, t: gpt2_apply(p, t, cfg_off))(params, toks)
        ln = jax.jit(lambda p, t: gpt2_apply(p, t, cfg_on))(params, toks)
        _close(ln, lo, jnp.bfloat16)


# --------------------------------------------------------------------- #
# Engine tier — 8-device CPU mesh
# --------------------------------------------------------------------- #
def _gpt2_cfg(fused, dtype=jnp.float32):
    return dataclasses.replace(
        GPT2_CONFIGS["gpt2-tiny"], hidden_dropout=0.0, attn_dropout=0.0,
        dtype=dtype, fused_kernels=fused)


def _ds_cfg(**over):
    cfg = {
        "train_batch_size": 8,
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "gradient_clipping": 1.0,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3,
                                                  "fused": True}},
        "zero_optimization": {"stage": 2},
        "steps_per_print": 10 ** 9,
    }
    cfg.update(over)
    return cfg


def _token_batch(i, cfg, n=8):
    r = np.random.default_rng(i)
    return jnp.asarray(r.integers(0, cfg.vocab_size, (n, 17)), jnp.int32)


def _train(model_cfg, steps=4, ds_over=None, seed=0):
    eng = DeepSpeedEngine(model=gpt2_loss_fn(model_cfg),
                          model_params=gpt2_init(jax.random.PRNGKey(seed),
                                                 model_cfg),
                          config=_ds_cfg(**(ds_over or {})),
                          mesh=build_mesh())
    losses = [float(jax.device_get(eng.train_batch(
        _token_batch(i, model_cfg)))) for i in range(steps)]
    return eng, losses


class TestEngineTier:
    def test_train_step_parity_kernels_on_off(self):
        """fp32 gpt2-tiny under ZeRO-2 + clipping + the one-pass fused
        optimizer on the dp=8 mesh: fused-kernel and reference
        trajectories agree to f32 accumulation tolerance."""
        eng_on, l_on = _train(_gpt2_cfg(True))
        eng_off, l_off = _train(_gpt2_cfg(False))
        np.testing.assert_allclose(l_on, l_off, rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(
            np.asarray(jax.device_get(
                eng_on.state.params["ln_f_scale"]), np.float32),
            np.asarray(jax.device_get(
                eng_off.state.params["ln_f_scale"]), np.float32),
            rtol=1e-4, atol=1e-5)

    def test_checkpoint_roundtrip_across_knob(self, tmp_path):
        """Runs with kernels on and off are RESUME-COMPATIBLE: the knob
        changes the program, not the state (params, moments, loss-scale
        machinery all identical structures)."""
        eng_on, _ = _train(_gpt2_cfg(True), steps=3)
        eng_on.save_checkpoint(str(tmp_path), tag="k3")
        eng_off, _ = _train(_gpt2_cfg(False), steps=1, seed=1)
        eng_off.load_checkpoint(str(tmp_path), tag="k3")
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(eng_on.state.opt_state.m[0])),
            np.asarray(jax.device_get(eng_off.state.opt_state.m[0])))
        cfg_on, cfg_off = _gpt2_cfg(True), _gpt2_cfg(False)
        l_on = float(jax.device_get(eng_on.train_batch(
            _token_batch(50, cfg_on))))
        l_off = float(jax.device_get(eng_off.train_batch(
            _token_batch(50, cfg_off))))
        np.testing.assert_allclose(l_on, l_off, rtol=2e-4, atol=2e-5)

    def test_lint_clean_with_kernels_on(self, tmp_path):
        """The acceptance gate's lint half: materialization + dtype_flow
        CLEAN (zero unwaived findings) on the dp=8 ZeRO-2 engine with
        the fused kernels AND the one-pass fused optimizer enabled —
        the kernels run inside the explicit shard_map gradient path
        where every operand is already device-local, so no activation
        gather materializes."""
        cfg = _gpt2_cfg(True)
        eng = DeepSpeedEngine(
            model=gpt2_loss_fn(cfg),
            model_params=gpt2_init(jax.random.PRNGKey(0), cfg),
            config=_ds_cfg(telemetry={
                "enabled": True, "output_path": str(tmp_path),
                "job_name": "fk", "report_steps": 10 ** 9}),
            mesh=build_mesh())
        for i in range(2):
            eng.train_batch(_token_batch(i, cfg))
        rep = eng.lint_audit(passes=("materialization", "dtype_flow"))
        assert not rep.errors, rep.errors
        assert rep.unwaived == [], [f.fingerprint for f in rep.unwaived]
        eng.telemetry.close()


class TestServingRecompiles:
    def test_zero_extra_recompiles_with_fused_ln(self, tmp_path):
        """The serving satellite: the decode/prefill paths pick up the
        fused LayerNorm through the SAME cfg-static dispatch as
        training — an open-loop stream under fail_on_recompile compiles
        each path once, kernels on."""
        from deepspeed_tpu.inference import (InferenceEngine,
                                             synthetic_requests)
        cfg = dataclasses.replace(GPT2_CONFIGS["gpt2-tiny"],
                                  fused_kernels=True)
        eng = InferenceEngine(
            cfg, gpt2_init(jax.random.PRNGKey(1), cfg),
            config={
                "inference": {"max_slots": 8, "max_seq_len": 32,
                              "prefill_chunk": 8},
                "telemetry": {"enabled": True,
                              "output_path": str(tmp_path),
                              "job_name": "serve_fk",
                              "report_steps": 10 ** 6,
                              "fail_on_recompile": True}})
        reqs = synthetic_requests(8, prompt_len=(4, 12), max_new_tokens=5,
                                  vocab_size=cfg.vocab_size, seed=5)
        report = eng.serve(reqs)
        assert report["completed"] == 8 and report["unfinished"] == 0
        assert report["recompiles"] == 0
        assert eng.telemetry.recompile_count == 0
        eng.close()
