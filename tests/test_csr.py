"""CSR tensor tests (reference tests/unit/test_csr.py parity)."""
import numpy as np

from deepspeed_tpu.runtime.csr_tensor import CSRTensor, all_gather_csr


def _dense(seed=0, rows=64, cols=8, nnz_rows=5):
    rng = np.random.default_rng(seed)
    d = np.zeros((rows, cols), np.float32)
    idx = rng.choice(rows, nnz_rows, replace=False)
    d[idx] = rng.standard_normal((nnz_rows, cols)).astype(np.float32)
    return d


def test_roundtrip():
    d = _dense()
    c = CSRTensor.from_dense(d)
    np.testing.assert_array_equal(c.to_dense(), d)
    assert c.sparse_size() < c.dense_size
    assert c.sparse_size() == 5 * 8 + 5


def test_add_and_coalesce():
    d1, d2 = _dense(1), _dense(2)
    c = CSRTensor.from_dense(d1).add(CSRTensor.from_dense(d2))
    np.testing.assert_allclose(c.to_dense(), d1 + d2, rtol=1e-6)
    cc = c.coalesce()
    np.testing.assert_allclose(cc.to_dense(), d1 + d2, rtol=1e-6)
    assert np.all(np.diff(cc.row_indices) > 0)   # sorted unique


def test_all_gather_matches_dense_sum():
    denses = [_dense(s) for s in range(4)]
    got = all_gather_csr([CSRTensor.from_dense(d) for d in denses])
    np.testing.assert_allclose(got.to_dense(), sum(denses), rtol=1e-6)
    # comm volume: 4 shards of ~5 rows vs 64-row dense
    assert got.sparse_size() < got.dense_size


def test_empty():
    c = CSRTensor.from_dense(np.zeros((16, 4), np.float32))
    assert c.sparse_size() == 0
    np.testing.assert_array_equal(c.to_dense(), np.zeros((16, 4)))


def test_comm_sparse_all_reduce():
    from deepspeed_tpu.parallel.comm import sparse_all_reduce
    denses = [_dense(s, rows=128, nnz_rows=6) for s in range(4)]
    total, shipped, dense_elems = sparse_all_reduce(denses)
    np.testing.assert_allclose(total, sum(denses), rtol=1e-6)
    assert shipped < dense_elems    # the point of the sparse path
