"""Test fixture models — parity with reference tests/unit/simple_model.py
(SimpleModel: one linear + CE; random_dataloader; args_from_dict)."""
import json

import jax
import jax.numpy as jnp
import numpy as np


def simple_model_params(rng, dim=8, num_classes=4, hidden=16):
    k1, k2 = jax.random.split(rng)
    return {
        "w1": jax.random.normal(k1, (dim, hidden)) * 0.1,
        "b1": jnp.zeros((hidden,)),
        "w2": jax.random.normal(k2, (hidden, num_classes)) * 0.1,
        "b2": jnp.zeros((num_classes,)),
    }


def simple_loss_fn(params, batch, rng):
    """Two-layer MLP with cross-entropy loss (SimpleModel analog)."""
    x, y = batch
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    logits = h @ params["w2"] + params["b2"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    onehot = jax.nn.one_hot(y, logits.shape[-1])
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


def random_dataset(n=64, dim=8, num_classes=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, dim)).astype(np.float32)
    # Learnable labels: y depends on x so loss can fall.
    y = (x.sum(axis=1) > 0).astype(np.int32) % num_classes
    from deepspeed_tpu.runtime.dataloader import ArrayDataset
    return ArrayDataset(x, y)


def random_batch(n=16, dim=8, num_classes=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32) % num_classes
    return (x, y)


def base_config(**overrides):
    cfg = {
        "train_batch_size": 16,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "steps_per_print": 1000,
    }
    cfg.update(overrides)
    return cfg
