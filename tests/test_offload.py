"""ZeRO-Offload: numeric parity, loss-scale machinery, checkpoint
round-trips, and host-state partitioning.

Reference test being matched: tests/unit/test_cpu_adam.py (DeepSpeedCPUAdam
vs torch.optim.AdamW numerically) + test_checkpointing.py offload cases +
test_fp16.py's cpu_offload matrix.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deepspeed_tpu.ops.cpu_adam import DeepSpeedCPUAdam, _native_lib, host_f32
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.zero.offload import ZeroOffloadOptimizer
from deepspeed_tpu.parallel.topology import build_mesh

from simple_model import simple_loss_fn, simple_model_params, random_batch


def simple_params(seed=0):
    return simple_model_params(jax.random.PRNGKey(seed))


def random_batches(n, bs, seed=0):
    return [random_batch(bs, seed=seed + i) for i in range(n)]


def _tree(seed=0):
    k = jax.random.PRNGKey(seed)
    k1, k2 = jax.random.split(k)
    return {"w": jax.random.normal(k1, (64, 32), jnp.float32),
            "b": jax.random.normal(k2, (32,), jnp.float32)}


# --------------------------------------------------------------------- #
# CPUAdam numerics: native C++ vs numpy fallback vs optax, 100 steps
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("adamw", [True, False])
def test_cpu_adam_matches_optax(adamw):
    lr, b1, b2, eps, wd = 1e-2, 0.9, 0.999, 1e-8, 0.01
    params = _tree()
    leaves, treedef = jax.tree_util.tree_flatten(params)
    masters = [host_f32(l) for l in leaves]
    opt = DeepSpeedCPUAdam(params, lr=lr, betas=(b1, b2), eps=eps,
                           weight_decay=wd, adamw_mode=adamw)

    if adamw:
        tx = optax.adamw(lr, b1=b1, b2=b2, eps=eps, weight_decay=wd)
    else:
        # coupled (L2) decay: grad += wd * param, plain adam
        tx = optax.chain(optax.add_decayed_weights(wd), optax.scale(1.0),
                         optax.adam(lr, b1=b1, b2=b2, eps=eps))
    ref_params = params
    opt_state = tx.init(ref_params)

    rng = np.random.default_rng(0)
    for step in range(100):
        g_leaves = [rng.standard_normal(m.shape).astype(np.float32)
                    for m in masters]
        grads = jax.tree_util.tree_unflatten(treedef, [jnp.asarray(g)
                                                       for g in g_leaves])
        opt.step(masters, g_leaves)
        updates, opt_state = tx.update(grads, opt_state, ref_params)
        ref_params = optax.apply_updates(ref_params, updates)

    ref_leaves = jax.tree_util.tree_leaves(ref_params)
    for m, r in zip(masters, ref_leaves):
        np.testing.assert_allclose(m, np.asarray(r), rtol=2e-4, atol=5e-5)


@pytest.mark.skipif(_native_lib() is None, reason="no C++ toolchain")
def test_native_matches_numpy_fallback():
    params = _tree(1)
    leaves, _ = jax.tree_util.tree_flatten(params)
    m_nat = [host_f32(l) for l in leaves]
    m_np = [a.copy() for a in m_nat]
    nat = DeepSpeedCPUAdam(params, lr=3e-3, weight_decay=0.01)
    fall = DeepSpeedCPUAdam(params, lr=3e-3, weight_decay=0.01)
    assert nat.native
    fall._lib = None    # force numpy path
    rng = np.random.default_rng(1)
    for _ in range(100):
        gs = [rng.standard_normal(a.shape).astype(np.float32) for a in m_nat]
        nat.step(m_nat, gs)
        fall.step(m_np, [g.copy() for g in gs])
    for a, b in zip(m_nat, m_np):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


# --------------------------------------------------------------------- #
# Engine-level offload parity + loss scaling
# --------------------------------------------------------------------- #
def _engine(cpu_offload, fp16=False, bf16=False, lr=1e-2, mesh=None, seed=0):
    mesh = mesh or build_mesh(devices=jax.devices()[:1])
    dp = int(mesh.shape.get("data", 1))
    cfg = {
        "train_batch_size": 8 * dp,
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 1,
        "zero_optimization": {"stage": 2 if cpu_offload else 0,
                              "cpu_offload": cpu_offload},
        "optimizer": {"type": "Adam", "params": {"lr": lr}},
        "steps_per_print": 10 ** 9,
    }
    if fp16:
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 8,
                       "hysteresis": 1, "loss_scale_window": 4}
    if bf16:
        cfg["bf16"] = {"enabled": True}
    return DeepSpeedEngine(model=simple_loss_fn,
                           model_params=simple_params(seed),
                           config=cfg, mesh=mesh)


def test_offload_loss_parity_vs_baseline():
    """5-step loss trajectory: offload engine == stage-0 fp32 engine."""
    base = _engine(False)
    off = _engine(True)
    assert off._offload is not None
    batches = random_batches(5, 8, seed=3)
    for b in batches:
        l0 = float(jax.device_get(base.train_batch(b)))
        l1 = float(jax.device_get(off.train_batch(b)))
        assert abs(l0 - l1) < 5e-5, (l0, l1)


def test_offload_dynamic_loss_scale_skips_on_inf():
    off = _engine(True, fp16=True)
    scaler = off._offload
    scale0 = scaler.loss_scale
    bad = [np.full(m.shape, np.inf, np.float32) for m in scaler.masters]
    metrics = scaler.host_step(
        jax.tree_util.tree_unflatten(scaler.treedef, bad))
    assert metrics["overflow"]
    assert scaler.skipped_steps == 1
    assert scaler.step_count == 0
    assert scaler.loss_scale == scale0 / 2    # hysteresis=1: immediate halve
    # growth after scale_window clean steps
    good = [np.zeros(m.shape, np.float32) for m in scaler.masters]
    for _ in range(4):
        m = scaler.host_step(
            jax.tree_util.tree_unflatten(scaler.treedef, good))
        assert not m["overflow"]
    assert scaler.loss_scale == scale0    # grew back after window


@pytest.mark.parametrize("load_optimizer_states", [True, False])
@pytest.mark.parametrize("bf16", [False, True])
def test_offload_checkpoint_roundtrip(tmp_path, load_optimizer_states, bf16):
    """Save, train further, load — device weights must match the checkpoint
    (regression: stale bf16 staging served after load when
    load_optimizer_states=False and step_count>0)."""
    eng = _engine(True, bf16=bf16, lr=5e-2)
    batches = random_batches(6, 8, seed=7)
    for b in batches[:3]:
        eng.train_batch(b)
    eng.save_checkpoint(str(tmp_path), tag="ck")
    saved_masters = [m.copy() for m in eng._offload.masters]
    for b in batches[3:]:     # drift past the checkpoint
        eng.train_batch(b)
    eng.load_checkpoint(str(tmp_path), tag="ck",
                        load_optimizer_states=load_optimizer_states)
    for a, b in zip(eng._offload.masters, saved_masters):
        np.testing.assert_array_equal(a, b)
    # device params must be the checkpoint weights, not the drifted ones
    dev = jax.device_get(eng.state.params)
    ref = jax.tree_util.tree_unflatten(
        eng._offload.treedef,
        [m.astype(np.float32) for m in saved_masters])
    for a, b in zip(jax.tree_util.tree_leaves(dev),
                    jax.tree_util.tree_leaves(ref)):
        np.testing.assert_allclose(np.asarray(a, np.float32), b,
                                   rtol=1e-2, atol=1e-2)  # bf16 cast
    if load_optimizer_states:
        assert eng._offload.step_count == 3
    # resume training works
    eng.train_batch(batches[0])


def test_offload_lr_scheduler_restored_on_load(tmp_path):
    cfg = {
        "train_batch_size": 8, "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 1,
        "zero_optimization": {"stage": 2, "cpu_offload": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_num_steps": 10}},
        "steps_per_print": 10 ** 9,
    }
    eng = DeepSpeedEngine(model=simple_loss_fn,
                          model_params=simple_params(0), config=cfg,
                          mesh=build_mesh(devices=jax.devices()[:1]))
    for b in random_batches(4, 8, seed=1):
        eng.train_batch(b)
    eng.lr_scheduler.last_batch_iteration = 4
    eng.save_checkpoint(str(tmp_path), tag="s")
    eng2 = DeepSpeedEngine(model=simple_loss_fn,
                           model_params=simple_params(1), config=cfg,
                           mesh=build_mesh(devices=jax.devices()[:1]))
    eng2.load_checkpoint(str(tmp_path), tag="s")
    assert eng2.lr_scheduler.last_batch_iteration == 4


# --------------------------------------------------------------------- #
# Host-state partitioning (stage2.py:326-342 parity)
# --------------------------------------------------------------------- #
def test_partitioned_offload_matches_full_and_halves_rss():
    params = _tree(2)
    mk = lambda r, n: ZeroOffloadOptimizer(
        params, "Adam", {"lr": 1e-2}, lambda s: 1e-2, jnp.float32,
        partition_rank=r, partition_num=n)
    full = mk(0, 1)
    shards = [mk(r, 2) for r in range(2)]

    state_bytes = lambda o: sum(m.nbytes for m in o.masters) + \
        sum(a.nbytes for a in o.opt.exp_avg) + \
        sum(a.nbytes for a in o.opt.exp_avg_sq)
    # w [64,32] shards on axis 0; b [32] shards too -> exactly half
    assert state_bytes(shards[0]) * 2 == state_bytes(full)

    rng = np.random.default_rng(5)
    for _ in range(10):
        g = {"w": rng.standard_normal((64, 32)).astype(np.float32),
             "b": rng.standard_normal((32,)).astype(np.float32)}
        full.host_step(g)
        for s in shards:
            s.host_step(g)    # full grads: sliced internally

    f_leaves = full.masters
    for i in range(len(f_leaves)):
        got = np.concatenate([s.masters[i] for s in shards], axis=0)
        np.testing.assert_allclose(got, f_leaves[i], rtol=1e-6, atol=1e-7)


# --------------------------------------------------------------------- #
# Multi-host partitioning glue (stage2.py:775-873 parity pieces)
# --------------------------------------------------------------------- #
def test_partitioned_offload_clip_needs_allreduce():
    with pytest.raises(RuntimeError):
        ZeroOffloadOptimizer(
            _tree(), "Adam", {"lr": 1e-2}, lambda s: 1e-2, jnp.float32,
            gradient_clipping=1.0, partition_rank=0, partition_num=2
        ).host_step({"w": np.ones((64, 32), np.float32),
                     "b": np.ones((32,), np.float32)})


def test_partitioned_offload_clip_parity_with_allreduce():
    """4-way partitioned ranks with the cross-rank sumsq reduction clip
    EXACTLY like the unpartitioned optimizer — the offload.py:157 landmine
    defused."""
    params = _tree(3)
    rng = np.random.default_rng(9)
    grads = [{"w": (rng.standard_normal((64, 32)) * 10).astype(np.float32),
              "b": (rng.standard_normal((32,)) * 10).astype(np.float32)}
             for _ in range(6)]

    full = ZeroOffloadOptimizer(params, "Adam", {"lr": 1e-2},
                                lambda s: 1e-2, jnp.float32,
                                gradient_clipping=1.0)

    # The real allreduce sums disjoint local sumsqs; with full grads handed
    # to every rank, that total equals the full partitioned-leaf sumsq.
    def mk_allreduce(n):
        def cb(local_sumsq):
            return local_sumsq * 0 + cb.total    # rank-independent total
        return cb

    ranks = []
    for r in range(4):
        cb = mk_allreduce(4)
        ranks.append((ZeroOffloadOptimizer(
            params, "Adam", {"lr": 1e-2}, lambda s: 1e-2, jnp.float32,
            gradient_clipping=1.0, partition_rank=r, partition_num=4,
            sumsq_allreduce=cb), cb))

    for g in grads:
        m_full = full.host_step(g)
        # compute the true partitioned-leaf sumsq (w shards; b shards too)
        total = sum(float(np.sum(np.square(np.asarray(v, np.float64))))
                    for v in g.values())
        metrics = []
        for off, cb in ranks:
            cb.total = total
            metrics.append(off.host_step(g))
        # every rank reports the SAME global norm as the full optimizer
        for m in metrics:
            np.testing.assert_allclose(m["grad_norm"], m_full["grad_norm"],
                                       rtol=1e-5)

    for i in range(len(full.masters)):
        got = np.concatenate([r[0].masters[i] for r in ranks],
                             axis=full._axes[i] or 0)
        np.testing.assert_allclose(got, full.masters[i], rtol=1e-5,
                                   atol=1e-6)


def test_axis_divisor_follows_dp_shard_rule():
    """axis_divisor=dp picks the SAME axis zero/partition.py would shard
    the device grads on, even when an earlier axis happens to divide the
    process count."""
    params = {"w": jnp.ones((6, 8), jnp.float32)}
    off = ZeroOffloadOptimizer(params, "Adam", {"lr": 1e-2},
                               lambda s: 1e-2, jnp.float32,
                               partition_rank=0, partition_num=2,
                               axis_divisor=8)
    assert off._axes[0] == 1          # axis 0 (6) divides 2 but not dp=8
    assert off.masters[0].shape == (6, 4)
    with pytest.raises(ValueError):
        ZeroOffloadOptimizer(params, "Adam", {"lr": 1e-2}, lambda s: 1e-2,
                             jnp.float32, partition_rank=0, partition_num=2,
                             axis_divisor=3)   # not a multiple of 2


def test_offload_partition_shardings_specs():
    """The engine's repartition shardings put 'proc' on the host partition
    axis and replicate everything else."""
    import types
    from jax.sharding import PartitionSpec as P
    params = _tree(4)
    off = ZeroOffloadOptimizer(params, "Adam", {"lr": 1e-2},
                               lambda s: 1e-2, jnp.float32,
                               partition_rank=0, partition_num=2)
    ns = types.SimpleNamespace(_offload=off)
    tree = DeepSpeedEngine._offload_partition_shardings(ns, procs=2)
    assert tree["w"].spec == P("proc", None)    # [64,32] partitioned axis 0
    assert tree["b"].spec == P("proc")          # [32] partitioned axis 0
    # replicated leaf: odd shape with no divisible axis
    params2 = {"v": jnp.ones((7, 5), jnp.float32)}
    off2 = ZeroOffloadOptimizer(params2, "Adam", {"lr": 1e-2},
                                lambda s: 1e-2, jnp.float32,
                                partition_rank=0, partition_num=2)
    ns2 = types.SimpleNamespace(_offload=off2)
    tree2 = DeepSpeedEngine._offload_partition_shardings(ns2, procs=2)
    assert tree2["v"].spec == P()
    # the shardings are usable: repartition a grads tree through them
    g = {"w": jnp.ones((64, 32)), "b": jnp.ones((32,))}
    out = jax.jit(lambda t: t, out_shardings=tree)(g)
    shard = out["w"].addressable_shards[0]
    assert shard.data.shape == (32, 32)


def test_host_allreduce_sum_single_process():
    from deepspeed_tpu.parallel.comm import host_allreduce_sum
    assert host_allreduce_sum(2.5) == 2.5
