"""Multi-slice scale-out: hierarchical ICI/DCN gradient sync.

The tier-1 gates of the multislice round:

- **Audited collective hierarchy** (the acceptance gate): on the
  slices=2 x dp=4 CPU mesh, grads reduce-scatter IN-SLICE (groups of
  dp, inside the gas scan), the inter-slice all-reduce moves only the
  1/dp-sharded residual (groups of `slices`, once per step, outside the
  scan), never a grad-sized flat collective spanning the slice axis —
  and the compiled wire matches the two-tier analytic model on both
  tiers to 5%.
- **Bit-parity of hierarchical vs flat sync from identical state**: a
  2-slice run on a slice-DUPLICATED batch is BIT-identical to the
  1-slice run — every cross-slice float op is either the identical
  in-slice collective or an exact power-of-two scaling (the psum of two
  bitwise-equal partials, the /replicas mean correction).
- **DCN compression**: the priced DCN bytes drop >= 8x while the ICI
  bytes are unchanged; the error-feedback buffers live in EngineState
  and update per taken step.

Emulation honesty: "slices" on this box are virtual mesh axes over
XLA's host devices — everything asserted here is STRUCTURAL (which
collectives, what groups, what payloads) or NUMERIC (bit-parity);
nothing here measures DCN.
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.parallel import comm, hlo_audit
from deepspeed_tpu.parallel.multislice import (SliceTopology,
                                               classify_two_tier,
                                               dcn_comm_bytes,
                                               dcn_compression_ratio,
                                               two_tier_wire_summary)
from deepspeed_tpu.parallel.topology import (DP_AXIS, SLICE_AXIS,
                                             build_mesh)


# ------------------------------------------------------------------ #
# Fixture model (tests/simple_model.py shape, kept local)
# ------------------------------------------------------------------ #
def _params(seed=0, dim=8, hidden=16, classes=4):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    return {"w1": jax.random.normal(k1, (dim, hidden)) * 0.1,
            "b1": jnp.zeros((hidden,)),
            "w2": jax.random.normal(k2, (hidden, classes)) * 0.1,
            "b2": jnp.zeros((classes,))}


def _loss_fn(params, batch, rng):
    x, y = batch
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    logits = h @ params["w2"] + params["b2"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    onehot = jax.nn.one_hot(y, logits.shape[-1])
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


def _batch(n=16, dim=8, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32) % classes
    return (x, y)


def _engine(overrides=None, gas=1, slices=2, batch=16, devices=None,
            fp16=False, **kw):
    cfg = {"train_batch_size": batch * gas,
           "gradient_accumulation_steps": gas,
           "optimizer": {"type": "Adam",
                         "params": {"lr": 1e-2, "fused": False}},
           "zero_optimization": {"stage": 2},
           "steps_per_print": 10 ** 9}
    if slices > 1:
        cfg["mesh"] = {"slices": slices}
    if fp16:
        cfg["fp16"] = {"enabled": True, "loss_scale": 128.0}
    for k, v in (overrides or {}).items():
        if isinstance(v, dict) and isinstance(cfg.get(k), dict):
            cfg[k].update(v)
        else:
            cfg[k] = v
    mesh = build_mesh(devices=devices) if devices is not None else None
    engine, *_ = deepspeed_tpu.initialize(
        model=_loss_fn, model_params=_params(), config=cfg, mesh=mesh,
        **kw)
    return engine


def _audit(engine, gas=1, n=16):
    batch = _batch(n=n * gas)
    mb = engine._stack_micro_batches(batch)
    mb = jax.device_put(mb, engine._batch_sharding(mb, leading_dims=2))
    return hlo_audit.audit_jit(engine._build_train_step(), engine.state,
                               mb, engine._base_rng)


# ------------------------------------------------------------------ #
# Mesh / topology
# ------------------------------------------------------------------ #
class TestSliceMesh:
    def test_slice_axis_outermost_and_contiguous(self):
        mesh = build_mesh(slices=2)
        assert mesh.axis_names[0] == SLICE_AXIS
        assert int(mesh.shape[SLICE_AXIS]) == 2
        assert int(mesh.shape[DP_AXIS]) == 4
        # Slice 0 holds the first contiguous half of the devices (they
        # really share an ICI domain; DCN is the boundary between
        # halves).
        devs = mesh.devices
        ids0 = sorted(d.id for d in devs[0].reshape(-1))
        ids1 = sorted(d.id for d in devs[1].reshape(-1))
        assert max(ids0) < min(ids1)

    def test_dp_inferred_within_slice(self):
        mesh = build_mesh(slices=4)
        assert int(mesh.shape[DP_AXIS]) == 2

    def test_slice_topology_from_mesh(self):
        topo = SliceTopology.from_mesh(build_mesh(slices=2))
        assert (topo.num_slices, topo.dp_per_slice, topo.replicas) == \
            (2, 4, 8)

    def test_default_mesh_single_slice(self, mesh8):
        assert int(mesh8.shape.get(SLICE_AXIS, 1)) == 1


class TestSliceEmulationIdentity:
    """DS_PROC_INDEX / DS_PROC_COUNT / DS_NUM_SLICES -> (slice_id,
    rank-in-slice) — the PR-10 multi-host machinery grown a slice tier."""

    def test_mapping_two_slice_world(self, monkeypatch):
        from deepspeed_tpu.monitor.hostinfo import slice_identity
        monkeypatch.setenv("DS_PROC_COUNT", "4")
        monkeypatch.setenv("DS_NUM_SLICES", "2")
        seen = {}
        for p in range(4):
            monkeypatch.setenv("DS_PROC_INDEX", str(p))
            seen[p] = slice_identity()
        assert seen == {0: (0, 0, 2), 1: (0, 1, 2),
                        2: (1, 0, 2), 3: (1, 1, 2)}

    def test_explicit_num_slices_overrides_env(self, monkeypatch):
        from deepspeed_tpu.monitor.hostinfo import slice_identity
        monkeypatch.setenv("DS_PROC_INDEX", "5")
        monkeypatch.setenv("DS_PROC_COUNT", "8")
        monkeypatch.setenv("DS_NUM_SLICES", "2")
        assert slice_identity(4) == (2, 1, 4)

    def test_single_slice_default(self, monkeypatch):
        from deepspeed_tpu.monitor.hostinfo import slice_identity
        monkeypatch.setenv("DS_PROC_INDEX", "3")
        monkeypatch.setenv("DS_PROC_COUNT", "4")
        monkeypatch.delenv("DS_NUM_SLICES", raising=False)
        assert slice_identity() == (0, 3, 1)

    def test_indivisible_world_raises(self, monkeypatch):
        from deepspeed_tpu.monitor.hostinfo import slice_identity
        monkeypatch.setenv("DS_PROC_INDEX", "0")
        monkeypatch.setenv("DS_PROC_COUNT", "3")
        with pytest.raises(ValueError, match="not divisible"):
            slice_identity(2)

    def test_writer_resolution_unchanged_by_slices(self, monkeypatch):
        """Slice membership does not change WHO writes: global rank 0
        writes the primary stream; other ranks write their own shard
        iff per_host — even when they lead their own slice."""
        from deepspeed_tpu.monitor.hostinfo import (resolve_writer,
                                                    shard_path,
                                                    slice_identity)
        monkeypatch.setenv("DS_PROC_COUNT", "4")
        monkeypatch.setenv("DS_NUM_SLICES", "2")
        # Process 2 is slice 1's rank 0 — still NOT the global writer.
        monkeypatch.setenv("DS_PROC_INDEX", "2")
        assert slice_identity()[:2] == (1, 0)
        writes, rank, world = resolve_writer()
        assert (writes, rank, world) == (False, 2, 4)
        writes, rank, _ = resolve_writer(per_host=True)
        assert writes and shard_path("runs/job.jsonl", rank) == \
            "runs/job.rank2.jsonl"
        monkeypatch.setenv("DS_PROC_INDEX", "0")
        assert resolve_writer()[0] is True

    def test_per_host_telemetry_shards_two_slice_world(self, tmp_path,
                                                       monkeypatch):
        """A slice-1 host (global rank 2 of the 2x2 emulated world)
        writes its own telemetry shard; the records land in
        job.rank2.jsonl with the full meta."""
        from deepspeed_tpu.monitor.telemetry import Telemetry
        from deepspeed_tpu.runtime.config import DeepSpeedConfig

        cfg = DeepSpeedConfig({
            "train_batch_size": 8,
            "telemetry": {"enabled": True, "output_path": str(tmp_path),
                          "job_name": "job", "report_steps": 2,
                          "per_host_shards": True}}).telemetry_config
        monkeypatch.setenv("DS_PROC_INDEX", "2")
        monkeypatch.setenv("DS_PROC_COUNT", "4")
        monkeypatch.setenv("DS_NUM_SLICES", "2")
        tl = Telemetry(cfg, meta={"slices": 2})
        for s in range(2):
            tl.record_step(s, {"loss": jnp.asarray(0.5)}, wall_ms=1.0)
            tl.maybe_drain(s)
        tl.close()
        shard = tmp_path / "job.rank2.jsonl"
        assert shard.exists()
        recs = [json.loads(l) for l in
                shard.read_text().splitlines() if l.strip()]
        kinds = {r.get("kind") for r in recs}
        assert "meta" in kinds and "step" in kinds
        meta = [r for r in recs if r.get("kind") == "meta"][0]
        assert meta["slices"] == 2 and meta["process_index"] == 2


class TestSliceParallelAliasDeprecation:
    """Satellite: the reference's `slice parallel` accessors alias MODEL
    (tensor-slicing) parallelism — with a real `slice` mesh axis in
    play they warn, delegate, and point at the model-parallel names."""

    def test_old_names_warn_and_delegate(self):
        from deepspeed_tpu.parallel.topology import (
            PipeModelDataParallelTopology, PipelineParallelGrid)
        grid = PipelineParallelGrid(
            PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2),
            global_rank=3)
        for name, expect in [
                ("get_slice_parallel_rank", grid.get_model_parallel_rank()),
                ("get_slice_parallel_world_size",
                 grid.get_model_parallel_world_size()),
                ("get_slice_parallel_group",
                 grid.get_model_parallel_group())]:
            with pytest.warns(DeprecationWarning,
                              match="tensor-slicing"):
                assert getattr(grid, name)() == expect
        with pytest.warns(DeprecationWarning, match="tensor-slicing"):
            assert grid.slice_parallel_size == \
                grid.get_model_parallel_world_size()

    def test_model_parallel_names_do_not_warn(self, recwarn):
        from deepspeed_tpu.parallel.topology import (
            PipeModelDataParallelTopology, PipelineParallelGrid)
        grid = PipelineParallelGrid(
            PipeModelDataParallelTopology(num_pp=1, num_mp=2, num_dp=4))
        grid.get_model_parallel_rank()
        grid.get_model_parallel_world_size()
        grid.get_model_parallel_group()
        assert not [w for w in recwarn.list
                    if issubclass(w.category, DeprecationWarning)]


# ------------------------------------------------------------------ #
# The two-tier wire model
# ------------------------------------------------------------------ #
class TestTwoTierWireModel:
    def test_hierarchical_terms(self):
        params = _params()
        dp, slices = 4, 2
        m = hlo_audit.grad_sync_wire_model(params, dp, slices=slices)
        scat_el = sum(int(np.prod(l.shape)) for l in
                      jax.tree_util.tree_leaves(params))
        # Toy tree: every leaf's dim divides dp=4 -> all scatterable.
        assert m["scatterable_bytes"] == scat_el * 4
        assert m["ici_wire_bytes"] == m["reduce_scatter_wire_bytes"]
        dcn_payload = scat_el // dp * 4
        assert m["dcn_payload_bytes"] == dcn_payload
        assert m["dcn_wire_bytes"] == hlo_audit.ring_wire_bytes(
            "all-reduce", dcn_payload, slices)
        assert m["flat_dcn_link_bytes"] == m["scatterable_bytes"]
        # Hierarchy divides the DCN traffic by dp vs the flat joint sync.
        assert m["flat_dcn_link_bytes"] // m["dcn_payload_bytes"] == dp
        assert m["hierarchical_wire_bytes"] == \
            m["ici_wire_bytes"] + m["dcn_wire_bytes"]

    def test_compression_prices_8x_down_and_flagship_32x(self):
        params = _params()
        m = hlo_audit.grad_sync_wire_model(params, 4, slices=2,
                                           dcn_compression=True)
        assert m["dcn_compression"] is True
        assert m["dcn_wire_bytes"] >= 8 * m["dcn_wire_bytes_compressed"]
        assert m["hierarchical_wire_bytes"] == \
            m["ici_wire_bytes"] + m["dcn_wire_bytes_compressed"]
        # Flagship shard sizes approach the 1-bit format's ~32x.
        assert dcn_compression_ratio(1 << 20, 2) > 28.0
        assert dcn_comm_bytes(64, compressed=True, num_slices=2) == \
            (64 + 7) // 8 + 4 * 2

    def test_classify_two_tier_signature(self):
        class Op:
            def __init__(self, kind, payload, group):
                self.kind = kind
                self.payload_bytes = payload
                self.group_size = group
                self.wire_bytes = payload
        ops = [Op("reduce-scatter", 1024, 4), Op("all-reduce", 256, 2),
               Op("all-reduce", 1024, 8), Op("all-reduce", 4, 2)]
        tiers = classify_two_tier(ops, num_slices=2, dp=4)
        assert [o.group_size for o in tiers["ici"]] == [4]
        assert [o.group_size for o in tiers["dcn"]] == [2]
        assert [o.group_size for o in tiers["flat"]] == [8]
        with pytest.raises(ValueError, match="ambiguous"):
            classify_two_tier(ops, num_slices=4, dp=4)


# ------------------------------------------------------------------ #
# Engine: resolution, validation, audited hierarchy
# ------------------------------------------------------------------ #
class TestMultisliceEngine:
    def test_resolves_explicit_and_prices_two_tiers(self):
        e = _engine()
        assert (e.slice_size, e.dp_size, e.replica_size) == (2, 4, 8)
        assert e._grad_sync_mode == "explicit"
        assert e._wire_bytes_dcn > 0
        assert e._wire_bytes > e._wire_bytes_dcn
        assert e.telemetry.meta["slices"] == 2 \
            if e.telemetry.enabled else True
        m = e._wire_model
        assert m["dcn_wire_bytes"] == e._wire_bytes_dcn

    def test_wire_tiers_are_per_step(self):
        """Both tiers in the same per-STEP units: the in-slice scatter
        repeats per micro-step (x gas), the DCN hop runs once — mixing
        a per-micro ICI figure with a per-step DCN figure would
        misreport the binding tier."""
        e1 = _engine(gas=1)
        e2 = _engine(gas=2)
        m = e1._wire_model
        assert e1._wire_bytes - e1._wire_bytes_dcn == \
            m["ici_wire_bytes"]
        assert e2._wire_bytes - e2._wire_bytes_dcn == \
            2 * m["ici_wire_bytes"]
        assert e2._wire_bytes_dcn == e1._wire_bytes_dcn

    def test_stage1_raises(self):
        with pytest.raises(ValueError, match="stage >= 2"):
            _engine({"zero_optimization": {"stage": 1}})

    def test_declarative_pin_raises(self):
        with pytest.raises(ValueError, match="hierarchical"):
            _engine({"zero_optimization": {"stage": 2,
                                           "grad_sync": "declarative"}})

    def test_dcn_compression_needs_slices(self):
        with pytest.raises(ValueError, match="multi.?slice"):
            _engine({"zero_optimization": {"stage": 2,
                                           "dcn_compression": True}},
                    slices=1)

    def test_dcn_compression_config_needs_stage2(self):
        from deepspeed_tpu.runtime.config import DeepSpeedConfig
        with pytest.raises(ValueError, match="stage >= 2"):
            DeepSpeedConfig({"train_batch_size": 8,
                             "zero_optimization": {
                                 "stage": 1, "dcn_compression": True}})

    def test_audited_collective_hierarchy_gate(self):
        """THE acceptance gate: in-slice reduce-scatter inside the gas
        scan, the inter-slice all-reduce on 1/dp shards only (once,
        outside the scan), no grad-sized collective spanning the slice
        axis, and both tiers within 5% of the analytic model."""
        gas = 2
        e = _engine(gas=gas)
        dp, slices = e.dp_size, e.slice_size
        audit = _audit(e, gas=gas)
        model = hlo_audit.grad_sync_wire_model(
            jax.device_get(e.state.params), dp, slices=slices)

        rs = audit.of_kind("reduce-scatter")
        assert rs, "no reduce-scatter compiled"
        assert all(o.group_size == dp for o in rs)
        assert all(o.in_loop for o in rs), \
            "in-slice scatter must sit inside the gas scan"
        assert sum(o.payload_bytes for o in rs) == \
            model["scatterable_bytes"]

        # Inter-slice hop: groups of `slices`, shard payloads, outside
        # the scan (ONE DCN exchange per step, not per micro-step).
        dcn_ars = [o for o in audit.of_kind("all-reduce")
                   if o.group_size == slices and o.payload_bytes >= 16]
        assert dcn_ars
        assert all(not o.in_loop for o in dcn_ars)
        shard_sizes = {int(np.prod(l.shape)) // dp * 4 for l in
                       jax.tree_util.tree_leaves(
                           jax.device_get(e.state.params))}
        for o in dcn_ars:
            assert o.payload_bytes in shard_sizes, \
                (o.payload_bytes, shard_sizes)

        # Never a grad-sized flat collective over the joint axes.
        flat = [o for o in audit.ops
                if o.kind in ("all-reduce", "reduce-scatter")
                and o.payload_bytes >= model["scatterable_bytes"] // 8
                and o.group_size > dp]
        assert not flat, [(o.kind, o.payload_bytes, o.group_size)
                          for o in flat]

        # Two-tier wire vs the analytic model, 5% on both tiers.
        tiers = two_tier_wire_summary(audit.ops, slices, dp,
                                      min_payload_bytes=1)
        assert abs(sum(o.wire_bytes for o in rs)
                   - model["ici_wire_bytes"]) <= \
            0.05 * model["ici_wire_bytes"]
        assert abs(tiers["dcn"] - model["dcn_wire_bytes"]) <= \
            0.05 * max(1, model["dcn_wire_bytes"])
        assert tiers["flat"] == 0

    def test_lint_collective_placement_clean(self, tmp_path):
        """The multislice flagship's compiled paths audit clean — the
        shard-payload DCN hop is whitelisted, nothing else fires."""
        e = _engine(gas=2, overrides={"telemetry": {
            "enabled": True, "output_path": str(tmp_path),
            "job_name": "msl", "report_steps": 10 ** 9}})
        for i in range(2):
            e.train_batch(batch=_batch(n=32, seed=i))
        report = e.lint_audit()
        cp = [f for f in report.findings
              if f.lint == "collective_placement"]
        assert not cp, [f.fingerprint for f in cp]
        e.telemetry.close()

    def test_whitelisted_dcn_hop_not_flagged_when_slices_gt_dp(self):
        """slices > dp with a byte collision (a 1/dp shard the size of a
        smaller leaf's full tensor): the legal inter-slice hop has
        groups wider than dp and a payload in the scatterable set — it
        must ride the dcn_shard_bytes whitelist through BOTH the
        grad-allreduce and the grad-spans-dcn checks."""
        from deepspeed_tpu.analysis.findings import LintContext
        from deepspeed_tpu.analysis.passes import \
            collective_placement_pass
        from deepspeed_tpu.parallel.hlo_audit import (CollectiveOp,
                                                      CommAudit)

        def op(kind, payload, group, in_loop=False):
            return CollectiveOp(
                kind=kind, name="x", computation="", out_bytes=payload,
                in_bytes=payload, out_shapes=[f"f32[{payload // 4}]"],
                in_shapes=[], group_size=group, num_groups=1,
                source_target_pairs=None, op_name="", in_loop=in_loop)

        # dp=2, slices=4; leaf A full 1024 B (shard 512), leaf B full
        # 512 B — B's full size == A's shard size.
        legal = [op("reduce-scatter", 1024, 2, in_loop=True),
                 op("reduce-scatter", 512, 2, in_loop=True),
                 op("all-reduce", 512, 4),    # A's shard over slices
                 op("all-reduce", 256, 4)]    # B's shard over slices
        meta = {"grad_sync_path": True, "grad_sync_mode": "explicit",
                "gas": 2, "scatterable_leaf_bytes": [1024, 512],
                "slices": 4, "dp": 2, "dcn_shard_bytes": [512, 256]}
        ctx = LintContext(name="hier", jaxpr=None, donated_invars=(),
                          in_avals=(), hlo_text="",
                          audit=CommAudit(legal), meta=meta)
        assert collective_placement_pass(ctx) == []
        # A genuinely flat grad-sized collective (full payload, joint
        # group) still fires.
        flat_ctx = LintContext(
            name="flat", jaxpr=None, donated_invars=(), in_avals=(),
            hlo_text="",
            audit=CommAudit(legal + [op("reduce-scatter", 1024, 8,
                                        in_loop=True)]), meta=meta)
        keys = [f.key for f in collective_placement_pass(flat_ctx)]
        assert any(k.startswith("grad-spans-dcn") for k in keys), keys

    def test_moe_ep1_stats_reduce_over_slices(self):
        """An ep=1 MoE model on a multislice mesh: the per-rank expert
        stats must reduce over (slice, data) — routed counts sum to
        top_k x the GLOBAL token count, not one slice's share."""
        import dataclasses as dc
        from deepspeed_tpu.models.gpt2 import (GPT2_CONFIGS, gpt2_init,
                                               gpt2_loss_fn)
        from deepspeed_tpu.moe import MoEConfig
        moe = MoEConfig(num_experts=4, top_k=2, capacity_factor=10.0,
                        expert_parallel_size=1)
        cfg = dc.replace(GPT2_CONFIGS["gpt2-tiny"], vocab_size=64,
                         max_seq_length=17, hidden_dropout=0.0,
                         attn_dropout=0.0, dtype=jnp.float32,
                         fused_kernels=False, moe=moe)
        engine, *_ = deepspeed_tpu.initialize(
            model=gpt2_loss_fn(cfg),
            model_params=gpt2_init(jax.random.PRNGKey(0), cfg),
            config={"train_batch_size": 16,
                    "gradient_accumulation_steps": 1,
                    "zero_optimization": {"stage": 2},
                    "mesh": {"slices": 2},
                    "optimizer": {"type": "Adam",
                                  "params": {"lr": 1e-3,
                                             "fused": False}},
                    "moe": {"num_experts": 4, "top_k": 2,
                            "capacity_factor": 10.0,
                            "expert_parallel_size": 1},
                    "steps_per_print": 10 ** 9})
        assert engine.slice_size == 2 and \
            engine._grad_sync_mode == "explicit"
        tokens = np.random.default_rng(0).integers(
            0, 64, size=(16, 18)).astype(np.int32)
        mb = engine._stack_micro_batches(tokens)
        mb = jax.device_put(mb,
                            engine._batch_sharding(mb, leading_dims=2))
        engine.state, metrics = engine._build_train_step()(
            engine.state, mb, engine._base_rng)
        # 16 samples x 17 routed tokens x top_k=2, summed over BOTH
        # replica axes (cf=10 => nothing drops, every token routes).
        total = float(jnp.sum(metrics["moe_expert_tokens"]))
        assert total == 16 * 17 * 2, total

    def test_seeded_flat_joint_sync_caught(self, mesh8):
        """A grad-sized collective whose groups span the slice axis (the
        flat joint sync the hierarchy exists to avoid) is flagged by the
        collective_placement slice check."""
        from deepspeed_tpu.analysis.auditor import lint_jit
        mesh = build_mesh(slices=2)
        n = 512

        def per_rank(w, x):
            g = w * x.sum()
            # FLAT: one psum_scatter over the JOINT (slice, data) group
            # — grad-sized traffic across the DCN boundary.
            return lax.psum_scatter(g, (SLICE_AXIS, DP_AXIS),
                                    scatter_dimension=0, tiled=True)

        fn = comm.shard_map(
            per_rank, mesh=mesh,
            in_specs=(P(), P((SLICE_AXIS, DP_AXIS))),
            out_specs=P((SLICE_AXIS, DP_AXIS)), check_vma=False)
        w = jnp.ones((n,), jnp.float32)
        x = jnp.ones((8, 4), jnp.float32)
        meta = {"grad_sync_path": True, "grad_sync_mode": "explicit",
                "gas": 1, "scatterable_leaf_bytes": [n * 4],
                "slices": 2, "dp": 4,
                "dcn_shard_bytes": [n * 4 // 4]}
        with mesh:
            res = lint_jit(jax.jit(fn), w, x, name="seeded_flat",
                           meta=meta, passes=["collective_placement"])
        assert not res.errors, res.errors
        keys = [f.key for f in res.findings]
        assert any(k.startswith("grad-spans-dcn") for k in keys), keys


# ------------------------------------------------------------------ #
# Bit-parity: hierarchical vs flat single-slice sync
# ------------------------------------------------------------------ #
class TestHierarchicalBitParity:
    """A 2-slice engine fed a slice-duplicated batch against the
    1-slice engine on the base batch: the HIERARCHICAL SYNC adds no
    rounding at all — the in-slice collectives run over the same
    values, and every cross-slice op is an exact power-of-two operation
    (x + x, /2^k). ONE step from identical state is therefore
    BIT-identical (params, moments, loss). Multi-step trajectories
    agree to a few f32 ulp only: the two engines are distinct XLA
    programs (different meshes), and FMA/fusion association across
    programs is the documented PR-1/PR-3 cross-program limit — not a
    property of the sync."""

    def _run_pair(self, gas=1, fp16=False, steps=1):
        base_n = 8 * gas
        flat = _engine(slices=1, devices=jax.devices()[:4],
                       batch=8, gas=gas, fp16=fp16)
        hier = _engine(slices=2, batch=16, gas=gas, fp16=fp16)
        assert flat.dp_size == hier.dp_size == 4
        for step in range(steps):
            x, y = _batch(n=base_n, seed=step)
            lf = flat.train_batch(batch=(x, y))
            lh = hier.train_batch(
                batch=(np.concatenate([x, x]), np.concatenate([y, y])))
        return flat, hier, lf, lh

    @pytest.mark.parametrize("gas", [1, 2])
    def test_one_step_bitwise(self, gas):
        flat, hier, lf, lh = self._run_pair(gas=gas, steps=1)
        assert float(lf) == float(lh)
        pf = jax.device_get(flat.state.params)
        ph = jax.device_get(hier.state.params)
        for k in pf:
            assert np.array_equal(np.asarray(pf[k]), np.asarray(ph[k])), k
        # Moments too: the optimizer consumed bitwise-equal grads.
        of = jax.device_get(flat.state.opt_state)
        oh = jax.device_get(hier.state.opt_state)
        for a, b in zip(jax.tree_util.tree_leaves(of),
                        jax.tree_util.tree_leaves(oh)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_fp16_scaled_path_one_step_bitwise(self):
        flat, hier, lf, lh = self._run_pair(fp16=True, steps=1)
        assert float(lf) == float(lh)
        pf = jax.device_get(flat.state.params)
        ph = jax.device_get(hier.state.params)
        for k in pf:
            assert np.array_equal(np.asarray(pf[k]), np.asarray(ph[k])), k

    def test_trajectory_within_ulp(self):
        """Three steps: losses stay exactly equal on this backend and
        params within a few f32 ulp (the cross-program FMA limit — the
        sync itself contributes zero of this, per the one-step bitwise
        gate above)."""
        flat, hier, lf, lh = self._run_pair(steps=3)
        assert float(lf) == pytest.approx(float(lh), abs=1e-6)
        pf = jax.device_get(flat.state.params)
        ph = jax.device_get(hier.state.params)
        for k in pf:
            np.testing.assert_allclose(np.asarray(pf[k]),
                                       np.asarray(ph[k]), atol=2e-7,
                                       rtol=0)


# ------------------------------------------------------------------ #
# DCN compression: numerics + state
# ------------------------------------------------------------------ #
class TestDcnCompression:
    def test_error_feedback_state_lives_and_updates(self):
        e = _engine({"zero_optimization": {"stage": 2,
                                           "dcn_compression": True}})
        assert e.state.dcn_error is not None
        err0 = jax.device_get(e.state.dcn_error)
        shapes = {k: v.shape for k, v in err0.items()}
        assert shapes["w1"] == (2, 8, 16)     # [slices, *leaf]
        e.train_batch(batch=_batch(16))
        err1 = jax.device_get(e.state.dcn_error)
        assert any(not np.array_equal(np.asarray(err0[k]),
                                      np.asarray(err1[k]))
                   for k in err0)
        # The two slices carry DIFFERENT residuals (genuinely
        # per-slice state, like onebit's worker_error).
        assert not np.array_equal(np.asarray(err1["w1"][0]),
                                  np.asarray(err1["w1"][1]))

    def test_error_feedback_in_unscaled_units_under_fp16(self):
        """fp16 + dynamic-capable scaling: the carried residual is
        denominated in TRUE gradient units, not the loss scale — the
        error magnitudes must sit at gradient scale (<< the 128x-scaled
        grads), or a scale change would mis-weight every subsequent
        compensation."""
        e = _engine({"zero_optimization": {"stage": 2,
                                           "dcn_compression": True}},
                    fp16=True)
        for i in range(3):
            e.train_batch(batch=_batch(16, seed=i))
        err = jax.device_get(e.state.dcn_error)
        scale = float(jax.device_get(e.state.loss_scale))
        assert scale == 128.0
        # A scaled-units residual would carry ~scale-sized magnitudes;
        # true-units residuals for this toy sit well under 1.
        worst = max(float(np.abs(np.asarray(v)).max())
                    for v in err.values())
        assert 0 < worst < 1.0, worst

    def test_priced_dcn_drops_8x_ici_unchanged(self):
        dense = _engine()
        comp = _engine({"zero_optimization": {"stage": 2,
                                              "dcn_compression": True}})
        ici_d = dense._wire_bytes - dense._wire_bytes_dcn
        ici_c = comp._wire_bytes - comp._wire_bytes_dcn
        assert ici_d == ici_c
        assert dense._wire_bytes_dcn >= 8 * comp._wire_bytes_dcn

    @pytest.mark.slow
    def test_compressed_training_converges(self):
        """Error-feedback 1-bit DCN sync still trains the toy task: the
        loss drops markedly from its start (lossy sync, no bit-parity
        claim — the claim is the error feedback keeps it unbiased)."""
        e = _engine({"zero_optimization": {"stage": 2,
                                           "dcn_compression": True}})
        first = last = None
        for i in range(40):
            loss = float(e.train_batch(batch=_batch(32, seed=i % 4)))
            first = loss if first is None else first
            last = loss
        assert last < 0.6 * first, (first, last)

    def test_forward_backward_trio_refuses(self):
        e = _engine({"zero_optimization": {"stage": 2,
                                           "dcn_compression": True}})
        with pytest.raises(NotImplementedError, match="train_batch"):
            e.forward(_batch(16))

    def test_error_feedback_checkpoint_roundtrip(self, tmp_path):
        """ISSUE 15 / ROADMAP 6(c): the carried residuals persist in
        the optim shards (``dcnN`` keys) and restore bit-exactly — a
        resume no longer restarts the feedback at zero, and the
        post-resume step matches the uninterrupted run bitwise."""
        e = _engine({"zero_optimization": {"stage": 2,
                                           "dcn_compression": True}})
        for i in range(3):
            e.train_batch(batch=_batch(16, seed=i))
        err0 = jax.device_get(e.state.dcn_error)
        assert any(np.any(np.asarray(v) != 0) for v in err0.values())
        e.save_checkpoint(str(tmp_path), tag="dcn")
        e2 = _engine({"zero_optimization": {"stage": 2,
                                            "dcn_compression": True}})
        p, _ = e2.load_checkpoint(str(tmp_path), tag="dcn")
        assert p is not None
        err1 = jax.device_get(e2.state.dcn_error)
        for k in err0:
            np.testing.assert_array_equal(np.asarray(err0[k]),
                                          np.asarray(err1[k]))
        la = e.train_batch(batch=_batch(16, seed=9))
        lb = e2.train_batch(batch=_batch(16, seed=9))
        assert float(jax.device_get(la)) == float(jax.device_get(lb))
        for a, b in zip(
                jax.tree_util.tree_leaves(jax.device_get(e.state.params)),
                jax.tree_util.tree_leaves(jax.device_get(e2.state.params))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_dcn_buffers_skipped_when_compression_off(self, tmp_path):
        """Skip-fetch both ways: an uncompressed engine neither saves
        dcn keys nor chokes loading a checkpoint that carries them."""
        e = _engine({"zero_optimization": {"stage": 2,
                                           "dcn_compression": True}})
        e.train_batch(batch=_batch(16, seed=0))
        e.save_checkpoint(str(tmp_path), tag="dcn")
        plain = _engine()
        assert plain.state.dcn_error is None
        p, _ = plain.load_checkpoint(str(tmp_path), tag="dcn")
        assert p is not None
        assert plain.state.dcn_error is None
        plain.save_checkpoint(str(tmp_path), tag="plain")
        import json as _json
        meta = _json.load(
            open(tmp_path / "plain" / "engine_meta.json"))
        assert "dcn_error_shard_axes" not in meta

    def test_pre_resilience_checkpoint_warns_and_zeroes(self, tmp_path):
        """Loading an old checkpoint (no dcn buffers) into a compressed
        engine keeps the documented one-step-bias behavior: feedback
        restarts at zero, loudly."""
        import logging
        plain = _engine()
        plain.train_batch(batch=_batch(16, seed=0))
        plain.save_checkpoint(str(tmp_path), tag="old")
        e = _engine({"zero_optimization": {"stage": 2,
                                           "dcn_compression": True}})
        # The repo logger sets propagate=False, so pytest's caplog never
        # sees it — attach a handler directly.
        records = []

        class H(logging.Handler):
            def emit(self, r):
                records.append(r.getMessage())

        lg = logging.getLogger("deepspeed_tpu")
        h = H()
        lg.addHandler(h)
        try:
            p, _ = e.load_checkpoint(str(tmp_path), tag="old")
        finally:
            lg.removeHandler(h)
        assert p is not None
        assert any("dcn_error" in m for m in records)
        for v in jax.device_get(e.state.dcn_error).values():
            assert not np.any(np.asarray(v))


# ------------------------------------------------------------------ #
# Cost model / gate plumbing
# ------------------------------------------------------------------ #
class TestTwoTierCostModel:
    def test_roofline_dcn_tier(self):
        from deepspeed_tpu.monitor.cost_model import BOUND_DCN, roofline
        from deepspeed_tpu.monitor.peaks import peaks_for_kind
        peaks = peaks_for_kind("v5e")
        # Tiny DCN bytes dominate because the DCN ceiling is ~32x below
        # ICI: a step can be DCN-bound while ICI idles.
        r = roofline(flops_per_device=1e6, hbm_bytes_per_device=1e3,
                     comm_bytes=1e6, peaks=peaks, dcn_bytes=1e6)
        assert r["bound"] == BOUND_DCN
        assert r["t_dcn_ms"] > r["t_comm_ms"]
        r0 = roofline(1e12, 1e9, 0.0, peaks)
        assert r0["t_dcn_ms"] == 0.0 and r0["bound"] != BOUND_DCN

    def test_peaks_two_tier_column(self):
        from deepspeed_tpu.monitor.peaks import (TPU_DCN_GBS,
                                                 peaks_for_kind)
        pk = peaks_for_kind("TPU v5e")
        assert pk.dcn_gbs == TPU_DCN_GBS["v5e"] and not pk.assumed
        assert pk.ici_gbs / pk.dcn_gbs > 10
        assert "dcn_gbs" in pk.as_dict()
        assert peaks_for_kind("cpu").assumed

    def test_bench_gate_dcn_shapes(self, tmp_path):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "bench_gate", os.path.join(os.path.dirname(__file__), "..",
                                       "tools", "bench_gate.py"))
        bg = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bg)

        def write(name, dcn):
            p = tmp_path / name
            p.write_text(json.dumps(
                {"multislice": {"available": True,
                                "dcn_bytes_per_step": dcn}}))
            return str(p)

        old = write("old.json", 1000)
        assert bg.gate(old, write("ok.json", 1050), 0.1, 0.05) == 0
        assert bg.gate(old, write("bad.json", 1200), 0.1, 0.05) == 1
        # Pre-multislice rounds skip, never fail.
        pre = tmp_path / "pre.json"
        pre.write_text(json.dumps({"mfu": 0.5}))
        assert bg.gate(str(pre), write("new.json", 900), 0.1, 0.05) == 0
        m = bg.extract_metrics(
            {"roofline": {"comm_tiers": {"wire_bytes_dcn": 77}}})
        assert m["dcn_bytes"] == 77.0

    def test_bench_gate_zero3_shapes(self, tmp_path):
        """The stage-3-across-slices gate: DCN bytes rise beyond the
        relative ceiling fails; the param-bytes ceiling over a
        structural 0 is 0, so ANY param byte leaking onto DCN fails;
        pre-composition rounds (no zero3 record) skip, never fail."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "bench_gate", os.path.join(os.path.dirname(__file__), "..",
                                       "tools", "bench_gate.py"))
        bg = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bg)

        def write(name, dcn, param):
            p = tmp_path / name
            p.write_text(json.dumps(
                {"zero3": {"available": True,
                           "dcn_bytes_per_step": dcn,
                           "dcn_param_bytes_per_step": param}}))
            return str(p)

        old = write("old.json", 1000, 0)
        assert bg.gate(old, write("ok.json", 1050, 0), 0.1, 0.05) == 0
        assert bg.gate(old, write("rise.json", 1200, 0), 0.1, 0.05) == 1
        # One param byte on the slow tier = regression (0 * 1.1 = 0).
        assert bg.gate(old, write("leak.json", 1000, 1), 0.1, 0.05) == 1
        pre = tmp_path / "pre.json"
        pre.write_text(json.dumps({"mfu": 0.5}))
        assert bg.gate(str(pre), write("new.json", 900, 0),
                       0.1, 0.05) == 0
        # The ZERO3_BENCH.json shape (overlap_fraction) still resolves
        # independently of the multislice zero3 record.
        m = bg.extract_metrics({"zero3": {"overlap_fraction": 0.5}})
        assert m["zero3_overlap"] == 0.5
        assert m["z3_dcn_bytes"] is None and m["z3_dcn_param"] is None

    def test_ablate_record_shape(self, tmp_path):
        import subprocess
        import sys
        out = tmp_path / "MSL.json"
        r = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..",
                          "ablate_multislice.py"),
             "--record", "--model", "gpt2-tiny", "--dp", "8",
             "--out", str(out)],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stderr[-2000:]
        rec = json.loads(out.read_text())
        ms = rec["multislice"]
        assert ms["available"] and ms["dcn_bytes_per_step"] > 0
        assert ms["flat_dcn_bytes_per_step"] > ms["dcn_bytes_per_step"]
        assert ms["dcn_reduction_compressed_vs_dense"] >= 8
        assert "PROJECTION" in rec["methodology"]
        scheds = rec["projection"]["schedules"]
        assert set(scheds) == {"flat", "hierarchical",
                               "hierarchical_1bit_dcn"}

    def test_ablate_zero3_record_shape(self, tmp_path):
        import subprocess
        import sys
        out = tmp_path / "MSL.json"
        r = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..",
                          "ablate_multislice.py"),
             "--record", "--zero3", "--model", "gpt2-tiny", "--dp", "8",
             "--out", str(out)],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stderr[-2000:]
        rec = json.loads(out.read_text())
        z3 = rec["zero3"]
        assert z3["available"] and z3["dcn_param_bytes_per_step"] == 0
        assert z3["flat_dcn_link_bytes_per_step"] > \
            z3["dcn_bytes_per_step"]
        assert z3["ici_wire_bytes_per_step"] > 0
        assert "PROJECTION" in rec["methodology"]


# ------------------------------------------------------------------ #
# The axis-algebra planner (ISSUE 18 tentpole): one derivation for
# scope, schedule, tier, and group classification.
# ------------------------------------------------------------------ #
class TestAxisAlgebraPlanner:
    def test_factorization_from_mesh(self):
        from deepspeed_tpu.parallel.axis_algebra import MeshFactorization
        fact = MeshFactorization.from_mesh(build_mesh(slices=2))
        assert (fact.slices, fact.dp, fact.replicas) == (2, 4, 8)
        assert fact.tier(SLICE_AXIS) == "dcn"
        assert fact.tier(DP_AXIS) == "ici"
        assert fact.outer_axis == SLICE_AXIS
        assert fact.grad_shard_scope == (SLICE_AXIS, DP_AXIS)

    def test_plain_dp_mesh_has_no_outer(self):
        from deepspeed_tpu.parallel.axis_algebra import MeshFactorization
        fact = MeshFactorization.from_sizes(data=8)
        assert fact.outer_axis is None
        assert fact.grad_shard_scope == (DP_AXIS,)
        assert fact.replicas == 8

    def test_expert_outer_axis_rides_ici(self):
        """ep > 1 on a single slice: the residual hop binds `expert`,
        which is an in-slice axis — the planner derives the tier the
        MoE explicit path has always used."""
        from deepspeed_tpu.parallel.axis_algebra import (
            MeshFactorization, plan_grad_sync)
        fact = MeshFactorization.from_sizes(expert=2, data=4)
        assert fact.outer_axis == "expert"
        plan = plan_grad_sync(fact)
        assert plan.residual.tier == "ici"
        assert plan.residual.placement == "per-step"

    def test_unknown_axis_rejected(self):
        from deepspeed_tpu.parallel.axis_algebra import MeshFactorization
        with pytest.raises(ValueError, match="unknown mesh axis"):
            MeshFactorization.from_sizes(rows=2)

    def test_slice_x_expert_raises_with_structural_reason(self):
        from deepspeed_tpu.parallel.axis_algebra import MeshFactorization
        fact = MeshFactorization.from_sizes(slice=2, expert=2, data=2)
        with pytest.raises(ValueError,
                           match="one outer replica axis"):
            fact.outer_axis

    def test_classify_group_signatures(self):
        from deepspeed_tpu.parallel.axis_algebra import MeshFactorization
        fact = MeshFactorization.from_sizes(slice=2, data=4)
        assert fact.classify_group(4) == "ici"
        assert fact.classify_group(2) == "dcn"
        assert fact.classify_group(8) == "flat"
        assert fact.classify_group(3) == "other"
        amb = MeshFactorization.from_sizes(slice=4, data=4)
        with pytest.raises(ValueError, match="ambiguous"):
            amb.classify_group(4)

    def test_plan_zero3_multislice_headline(self):
        """THE derivation the PR is about: stage 3 on a (slice, data)
        mesh plans its param gathers on `data`/ICI in-scan and only the
        residual on `slice`/DCN — zero param bytes on the slow tier,
        by algebra rather than by special case."""
        from deepspeed_tpu.parallel.axis_algebra import (
            MeshFactorization, plan_grad_sync)
        fact = MeshFactorization.from_sizes(slice=2, data=4)
        plan = plan_grad_sync(fact, zero3=True)
        assert [s.op for s in plan.steps] == \
            ["all-gather", "reduce-scatter", "all-reduce"]
        assert plan.gather.axis == DP_AXIS
        assert plan.gather.tier == "ici"
        assert plan.gather.placement == "in-scan"
        assert plan.scatter.tier == "ici"
        assert plan.residual.axis == SLICE_AXIS
        assert plan.residual.tier == "dcn"
        assert plan.residual.placement == "per-step"
        # No zero3: no gather step, same residual.
        p2 = plan_grad_sync(fact)
        assert p2.gather is None and p2.residual.tier == "dcn"
        # Compression annotates only the DCN residual's wire format.
        p3 = plan_grad_sync(fact, zero3=True, dcn_compression=True)
        assert "1-bit" in p3.residual.payload
        assert "1-bit" not in p3.scatter.payload

    def test_plan_meta_roundtrips_to_json(self):
        from deepspeed_tpu.parallel.axis_algebra import (
            MeshFactorization, plan_grad_sync)
        plan = plan_grad_sync(MeshFactorization.from_sizes(slice=2,
                                                           data=4),
                              zero3=True)
        meta = json.loads(json.dumps(plan.to_meta()))
        assert [m["op"] for m in meta] == \
            ["all-gather", "reduce-scatter", "all-reduce"]
        assert all(set(m) == {"op", "axis", "tier", "placement",
                              "payload"} for m in meta)
        assert "all-gather[data/ici" in plan.describe()


# ------------------------------------------------------------------ #
# ZeRO-3 across slices (ISSUE 18 headline composition)
# ------------------------------------------------------------------ #
def _z3_engine(gas=1, slices=2, batch=16, devices=None, fp16=False,
               overrides=None):
    ov = {"zero_optimization": {"stage": 3}}
    for k, v in (overrides or {}).items():
        if isinstance(v, dict) and isinstance(ov.get(k), dict):
            ov[k].update(v)
        else:
            ov[k] = v
    return _engine(ov, gas=gas, slices=slices, batch=batch,
                   devices=devices, fp16=fp16)


class TestZero3Multislice:
    """Stage-3 params born dp-sharded WITHIN each slice and replicated
    across slices: every param all-gather binds `data` (ICI only), the
    grads reduce-scatter in-slice per micro-step, and the only DCN
    traffic is the accumulated 1/dp residual — once per step."""

    def test_resolves_and_prices_zero_param_bytes_on_dcn(self):
        e = _z3_engine()
        assert e._zero3 and (e.slice_size, e.dp_size) == (2, 4)
        assert e._grad_sync_mode == "explicit"
        m = e._wire_model
        assert m["dcn_param_bytes"] == 0
        assert m["param_gather_wire_bytes"] > 0
        # The ICI term carries scatter + both gathers; DCN carries the
        # residual only — same as stage 2 with the same tree.
        assert m["ici_wire_bytes"] == m["reduce_scatter_wire_bytes"] + \
            m["param_gather_wire_bytes"]
        s2 = _engine()
        assert m["dcn_wire_bytes"] == s2._wire_model["dcn_wire_bytes"]
        # The flat lowering would put both gathers on the DCN link too.
        assert m["flat_dcn_link_bytes"] == \
            s2._wire_model["flat_dcn_link_bytes"] + \
            2 * m["param_gather_payload_bytes"]
        plan = m["collective_plan"]
        assert [p["op"] for p in plan] == \
            ["all-gather", "reduce-scatter", "all-reduce"]
        assert plan[0]["tier"] == "ici" and plan[2]["tier"] == "dcn"

    def test_params_born_sharded_in_slice_replicated_across(self):
        e = _z3_engine()
        spec = e.state.params["w1"].sharding.spec
        assert DP_AXIS in str(spec) and SLICE_AXIS not in str(spec)

    def test_telemetry_meta_splits_wire_terms_by_tier(self, tmp_path):
        e = _z3_engine(overrides={"telemetry": {
            "enabled": True, "output_path": str(tmp_path),
            "job_name": "z3", "report_steps": 10 ** 9}})
        meta = e.telemetry.meta
        assert meta["wire_bytes_dcn"] == e._wire_bytes_dcn
        terms = meta["wire_terms"]
        assert terms["param_gather"]["tier"] == "ici"
        assert terms["grad_reduce_scatter"]["tier"] == "ici"
        assert terms["inter_slice_residual"]["tier"] == "dcn"
        assert terms["inter_slice_residual"]["bytes"] == \
            e._wire_bytes_dcn
        ici = sum(t["bytes"] for t in terms.values()
                  if t["tier"] == "ici")
        assert ici == e._wire_bytes - e._wire_bytes_dcn
        e.telemetry.close()

    def test_audited_zero3_collective_hierarchy_gate(self):
        """The stage-3 acceptance gate: in-slice gathers AND scatters
        inside the gas scan (groups of dp), ONE inter-slice all-reduce
        of residual size outside it, no param- or grad-sized collective
        spanning the slice axis, both tiers within 5% of the wire
        model (gather CSE tolerance: XLA may merge the fwd/bwd remat
        pair into one buffer — both counts accepted, priced as
        compiled)."""
        gas = 2
        e = _z3_engine(gas=gas)
        dp, slices = e.dp_size, e.slice_size
        audit = _audit(e, gas=gas)
        params = jax.device_get(e.state.params)
        model = hlo_audit.grad_sync_wire_model(
            params, dp, slices=slices, zero3=True, param_bytes_per_el=4,
            gas=1, param_specs=e._stage3_specs, mesh=e.mesh)

        ag = [o for o in audit.of_kind("all-gather")
              if o.payload_bytes >= 16]
        assert ag, "no param all-gather compiled"
        assert all(o.group_size == dp for o in ag), \
            [(o.payload_bytes, o.group_size) for o in ag]
        # Placement honesty: the DECLARED schedule re-gathers per
        # micro-step inside the gas scan; on this toy (params loop-
        # invariant across micro-steps) XLA hoists the gathers out via
        # LICM — once per step, strictly cheaper, still `data`-bound.
        # The in-scan claim is pinned where it is load-bearing: the
        # layer-scan program (params differ per layer, not hoistable —
        # tools/comm_audit.py zero3_multislice flagship).
        ag_payload = sum(o.payload_bytes for o in ag)
        ag_wire = sum(o.wire_bytes for o in ag)
        one_gather = hlo_audit.ring_wire_bytes(
            "all-gather", model["param_gather_payload_bytes"], dp)
        gathers = round(ag_payload /
                        max(1, model["param_gather_payload_bytes"]))
        assert gathers in (1, 2), (ag_payload,
                                   model["param_gather_payload_bytes"])
        assert abs(ag_wire - gathers * one_gather) <= 0.05 * ag_wire

        rs = audit.of_kind("reduce-scatter")
        assert rs and all(o.group_size == dp for o in rs)
        assert all(o.in_loop for o in rs)
        assert sum(o.payload_bytes for o in rs) == \
            model["scatterable_bytes"]
        assert abs(sum(o.wire_bytes for o in rs)
                   - model["reduce_scatter_wire_bytes"]) <= \
            0.05 * model["reduce_scatter_wire_bytes"]

        # ONE residual-sized DCN exchange per step, outside the scan.
        dcn_ars = [o for o in audit.of_kind("all-reduce")
                   if o.group_size == slices and o.payload_bytes >= 16]
        assert dcn_ars
        assert all(not o.in_loop for o in dcn_ars)
        shard_sizes = {int(np.prod(l.shape)) // dp * 4 for l in
                       jax.tree_util.tree_leaves(params)}
        for o in dcn_ars:
            assert o.payload_bytes in shard_sizes, \
                (o.payload_bytes, shard_sizes)
        tiers = two_tier_wire_summary(audit.ops, slices, dp,
                                      min_payload_bytes=1)
        assert abs(tiers["dcn"] - model["dcn_wire_bytes"]) <= \
            0.05 * max(1, model["dcn_wire_bytes"])
        assert tiers["flat"] == 0

        # Never a param- or grad-sized collective spanning `slice`.
        smallest_leaf = min(int(np.prod(l.shape)) * 4 for l in
                            jax.tree_util.tree_leaves(params))
        spanning = [o for o in audit.ops
                    if o.kind in ("all-gather", "all-reduce",
                                  "reduce-scatter")
                    and o.group_size > dp
                    and o.payload_bytes >= smallest_leaf]
        assert not spanning, [(o.kind, o.payload_bytes, o.group_size)
                              for o in spanning]

    def test_seeded_joint_axis_gather_caught(self, mesh8):
        """The seeded violation for the new lint check: a param-sized
        all-gather over the JOINT (slice, data) group ships param bytes
        across DCN every micro-step — collective_placement flags it as
        param-spans-dcn. The same gather bound to `data` alone audits
        clean."""
        from deepspeed_tpu.analysis.auditor import lint_jit
        mesh = build_mesh(slices=2)
        n = 512

        def flat_rank(w, x):
            full = lax.all_gather(w, (SLICE_AXIS, DP_AXIS), axis=0,
                                  tiled=True)
            return full * x.sum()

        def hier_rank(w, x):
            full = lax.all_gather(w, DP_AXIS, axis=0, tiled=True)
            return full * x.sum()

        w = jnp.ones((n,), jnp.float32)
        x = jnp.ones((8, 4), jnp.float32)
        # scatterable_leaf_bytes must be non-empty for the pass to run
        # at all (a grad-sync path with no scatterable leaves has no
        # gathers either); a size absent from the program keeps the
        # grad checks quiet.
        meta = {"grad_sync_path": True, "grad_sync_mode": "explicit",
                "gas": 1, "scatterable_leaf_bytes": [n * 16],
                "slices": 2, "dp": 4, "dcn_shard_bytes": [n * 4],
                "zero3_gather_leaf_bytes": [n * 4]}
        flat_fn = comm.shard_map(
            flat_rank, mesh=mesh,
            in_specs=(P((SLICE_AXIS, DP_AXIS)), P((SLICE_AXIS, DP_AXIS))),
            out_specs=P((SLICE_AXIS, DP_AXIS)), check_vma=False)
        with mesh:
            res = lint_jit(jax.jit(flat_fn), w, x, name="seeded_z3_flat",
                           meta=meta, passes=["collective_placement"])
        assert not res.errors, res.errors
        keys = [f.key for f in res.findings]
        assert any(k.startswith("param-spans-dcn") for k in keys), keys

        hier_fn = comm.shard_map(
            hier_rank, mesh=mesh,
            in_specs=(P((SLICE_AXIS, DP_AXIS)), P((SLICE_AXIS, DP_AXIS))),
            out_specs=P(DP_AXIS), check_vma=False)
        with mesh:
            ok = lint_jit(jax.jit(hier_fn), w, x, name="seeded_z3_hier",
                          meta=meta, passes=["collective_placement"])
        assert not ok.errors, ok.errors
        assert not [f for f in ok.findings
                    if f.key.startswith("param-spans-dcn")], \
            [f.key for f in ok.findings]

    def test_lint_collective_placement_clean(self, tmp_path):
        e = _z3_engine(gas=2, overrides={"telemetry": {
            "enabled": True, "output_path": str(tmp_path),
            "job_name": "z3l", "report_steps": 10 ** 9}})
        for i in range(2):
            e.train_batch(batch=_batch(n=32, seed=i))
        report = e.lint_audit()
        cp = [f for f in report.findings
              if f.lint == "collective_placement"]
        assert not cp, [f.fingerprint for f in cp]
        e.telemetry.close()

    def test_stage1_refusal_quotes_planner_reason(self):
        with pytest.raises(ValueError, match="no 1/dp residual"):
            _engine({"zero_optimization": {"stage": 1}})


class TestZero3MultisliceBitParity:
    """A 2-slice stage-3 engine on a slice-duplicated batch against the
    1-slice stage-3 engine on the base batch: the gathers run over the
    same in-slice values and every cross-slice float op is exact
    (x + x, /2^k) — ONE step is BIT-identical in params, moments, and
    loss, fp32 and fp16, gas 1 and 2."""

    def _run_pair(self, gas=1, fp16=False):
        flat = _z3_engine(slices=1, devices=jax.devices()[:4],
                          batch=8, gas=gas, fp16=fp16)
        hier = _z3_engine(slices=2, batch=16, gas=gas, fp16=fp16)
        assert flat.dp_size == hier.dp_size == 4
        x, y = _batch(n=8 * gas)
        lf = flat.train_batch(batch=(x, y))
        lh = hier.train_batch(
            batch=(np.concatenate([x, x]), np.concatenate([y, y])))
        return flat, hier, lf, lh

    @pytest.mark.parametrize("fp16", [False, True],
                             ids=["fp32", "fp16"])
    @pytest.mark.parametrize("gas", [1, 2])
    def test_one_step_bitwise(self, gas, fp16):
        flat, hier, lf, lh = self._run_pair(gas=gas, fp16=fp16)
        assert float(lf) == float(lh)
        pf = jax.device_get(flat.state.params)
        ph = jax.device_get(hier.state.params)
        for k in pf:
            assert np.array_equal(np.asarray(pf[k]), np.asarray(ph[k])), k
        of = jax.device_get(flat.state.opt_state)
        oh = jax.device_get(hier.state.opt_state)
        for a, b in zip(jax.tree_util.tree_leaves(of),
                        jax.tree_util.tree_leaves(oh)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_zero3_with_dcn_compression_trains(self):
        """zero3 x slices x dcn_compression: the composed engine builds,
        steps, and keeps its per-slice error feedback (lossy DCN wire —
        no bit-parity claim, same as stage 2)."""
        e = _z3_engine(overrides={"zero_optimization": {
            "stage": 3, "dcn_compression": True}})
        assert e.state.dcn_error is not None
        l0 = float(e.train_batch(batch=_batch(16, seed=0)))
        l1 = float(e.train_batch(batch=_batch(16, seed=1)))
        assert np.isfinite(l0) and np.isfinite(l1)
        err = jax.device_get(e.state.dcn_error)
        assert any(np.any(np.asarray(v) != 0) for v in err.values())
