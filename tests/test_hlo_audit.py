"""HLO communication audit: parser units + the wire-model regression tests
that turn the repo's scaling claims into machine-checked invariants.

The load-bearing assertions (ISSUE 3 acceptance):
- ZeRO-2 gradient sync compiles to reduce-scatter with wire bytes ON the
  analytic model — and grads never materialize unpartitioned (no
  grad-sized all-reduce). The engine's grad_sync=auto guarantees this via
  the explicit lax.psum_scatter path when the declarative GSPMD lowering
  regresses to all-reduce + slice (this backend does regress: the probe
  is part of the test).
- The explicit path is BIT-identical (params and moments) to the
  declarative path on the dp=8 mesh.
- 1-bit Adam's compression-phase wire format is ~1/32 of dense.
- 1F1B boundary traffic = 2 directions x boundary x ticks.
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.parallel import hlo_audit
from deepspeed_tpu.parallel.topology import build_mesh

from simple_model import (simple_model_params, simple_loss_fn, random_batch,
                          base_config)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------ #
# Parser units (synthetic HLO text)
# ------------------------------------------------------------------ #
SYNTH = """
HloModule jit_step

%add.clone (x: f32[], y: f32[]) -> f32[] {
  ROOT %add.2 = f32[] add(f32[] %x, f32[] %y)
}

%body.1 (p: (s32[], f32[4,16])) -> (s32[], f32[4,16]) {
  %cp = f32[4,16]{1,0} collective-permute(f32[4,16]{1,0} %gte.1), channel_id=3, source_target_pairs={{0,1},{1,2},{2,3}}, metadata={op_name="scan/permute"}
  ROOT %t = (s32[], f32[4,16]{1,0}) tuple(s32[] %i, f32[4,16]{1,0} %cp)
}

%cond.1 (p: (s32[], f32[4,16])) -> pred[] {
  ROOT %lt = pred[] compare(s32[] %a, s32[] %b), direction=LT
}

ENTRY %main.1 (arg: f32[8,16]) -> f32[2,4] {
  %ar = f32[4,16]{1,0} all-reduce(f32[4,16]{1,0} %dot.1), channel_id=1, replica_groups=[1,8]<=[8], use_global_device_ids=true, to_apply=%add.clone, metadata={op_name="jit(step)/psum"}
  %rs = f32[2,16]{1,0} reduce-scatter(f32[16,16]{1,0} %b.3), channel_id=2, replica_groups={{0,1,2,3,4,5,6,7}}, use_global_device_ids=true, dimensions={0}, to_apply=%add.clone
  %w = (s32[], f32[4,16]{1,0}) while((s32[], f32[4,16]{1,0}) %tp), condition=%cond.1, body=%body.1
  %ag = (f32[2]{0}, f32[4]{0}) all-gather(f32[1]{0} %s1, f32[2]{0} %s2), channel_id=4, replica_groups={{0,1}}, dimensions={0}
  ROOT %out = f32[2,4]{1,0} bitcast(f32[2,16]{1,0} %rs)
}
"""


class TestParser:
    def test_kinds_and_bytes(self):
        ops = hlo_audit.parse_hlo_collectives(SYNTH)
        by = {o.kind: o for o in ops}
        assert set(by) == {"all-reduce", "reduce-scatter",
                           "collective-permute", "all-gather"}
        ar = by["all-reduce"]
        assert ar.out_bytes == 4 * 16 * 4 and ar.group_size == 8
        assert ar.num_groups == 1 and ar.op_name == "jit(step)/psum"
        rs = by["reduce-scatter"]
        assert rs.in_bytes == 16 * 16 * 4 and rs.out_bytes == 2 * 16 * 4
        assert rs.payload_bytes == rs.in_bytes      # wire prices the input
        ag = by["all-gather"]                       # tuple-shaped variadic
        assert ag.out_bytes == (2 + 4) * 4 and ag.group_size == 2

    def test_wire_model(self):
        ops = {o.kind: o for o in hlo_audit.parse_hlo_collectives(SYNTH)}
        # ring all-reduce: 2(g-1)/g * B
        assert ops["all-reduce"].wire_bytes == 2 * 7 * 256 // 8
        # ring reduce-scatter: (g-1)/g * full input
        assert ops["reduce-scatter"].wire_bytes == 7 * 1024 // 8
        assert ops["collective-permute"].wire_bytes == 4 * 16 * 4

    def test_loop_attribution(self):
        ops = hlo_audit.parse_hlo_collectives(SYNTH)
        cp = next(o for o in ops if o.kind == "collective-permute")
        assert cp.in_loop and cp.computation == "body.1"
        assert cp.source_target_pairs == [(0, 1), (1, 2), (2, 3)]
        ar = next(o for o in ops if o.kind == "all-reduce")
        assert not ar.in_loop

    def test_summary(self):
        audit = hlo_audit.audit_text(SYNTH)
        s = audit.summary()
        assert s["all-reduce"]["count"] == 1
        assert audit.total_wire("reduce-scatter") == 7 * 1024 // 8

    def test_async_start_does_not_double_count(self):
        """A `-start` result tuple aliases the input buffer next to the
        output (plus u32 context scalars) — payload must be the largest
        component, not the tuple sum (TPU emits async collectives by
        default)."""
        text = """
ENTRY %main (p: f32[4,16]) -> f32[4,16] {
  %cps = (f32[4,16]{1,0}, f32[4,16]{1,0}, u32[], u32[]) collective-permute-start(f32[4,16]{1,0} %p), channel_id=1, source_target_pairs={{0,1},{1,0}}
  %ags = (f32[1,16]{1,0}, f32[8,16]{1,0}) all-gather-start(f32[1,16]{1,0} %p2), channel_id=2, replica_groups=[1,8]<=[8], dimensions={0}
  ROOT %cpd = f32[4,16]{1,0} collective-permute-done((f32[4,16]{1,0}, f32[4,16]{1,0}, u32[], u32[]) %cps)
}
"""
        ops = hlo_audit.parse_hlo_collectives(text)
        by = {o.kind: o for o in ops}
        assert len(ops) == 2            # -done carries no new traffic
        assert by["collective-permute"].out_bytes == 4 * 16 * 4
        # all-gather-start: output is the larger (gathered) component
        assert by["all-gather"].out_bytes == 8 * 16 * 4
        assert by["all-gather"].wire_bytes == 7 * (8 * 16 * 4) // 8

    def test_while_trip_counts(self):
        counts = hlo_audit.while_trip_counts(SYNTH)
        assert counts == []             # SYNTH's cond has no constants
        text = SYNTH.replace(
            "ROOT %lt = pred[] compare(s32[] %a, s32[] %b), direction=LT",
            "%c9 = s32[] constant(9)\n"
            "  ROOT %lt = pred[] compare(s32[] %a, s32[] %c9), direction=LT")
        assert 9 in hlo_audit.while_trip_counts(text)


class TestProbe:
    def test_lowering_probe_known_value(self, mesh8):
        """This backend's partitioner lowers the declared ZeRO-2 grad
        sharding to all-reduce + slice — the exact regression the
        explicit path exists for. (On a backend that honors the
        declaration this returns 'reduce-scatter' and auto mode keeps
        the declarative path — both are valid outcomes; 'none' is not.)"""
        got = hlo_audit.zero2_grad_sync_lowering(mesh8, "data")
        assert got in ("reduce-scatter", "all-reduce")
        # cached: second call must not recompile (same object back)
        assert hlo_audit.zero2_grad_sync_lowering(mesh8, "data") == got


# ------------------------------------------------------------------ #
# ZeRO-2: the guaranteed reduce-scatter gradient path
# ------------------------------------------------------------------ #
def _engine(gas=1, seed=0, **zero_overrides):
    zero = {"stage": 2}
    zero.update(zero_overrides)
    params = simple_model_params(jax.random.PRNGKey(seed))
    cfg = base_config(
        zero_optimization=zero, gradient_accumulation_steps=gas,
        train_batch_size=16 * gas,
        # fused=False keeps the optimizer apply's own collectives (the
        # chunked front-end gather, see COMM_AUDIT.json findings) out of
        # the grad-sync assertions.
        optimizer={"type": "Adam", "params": {"lr": 1e-2, "fused": False}})
    engine, *_ = deepspeed_tpu.initialize(
        model=simple_loss_fn, model_params=params, config=cfg)
    return engine


def _audit_step(engine, gas=1):
    batch = random_batch(n=16 * gas)
    mb = engine._stack_micro_batches(batch)
    mb = jax.device_put(mb, engine._batch_sharding(mb, leading_dims=2))
    fn = engine._build_train_step()
    return hlo_audit.audit_jit(fn, engine.state, mb, engine._base_rng)


class TestZero2ReduceScatterRegression:
    """Tier-1 gate: fails if ZeRO-2 gradient sync compiles to a full
    all-reduce (wire bytes off the analytic reduce-scatter model)."""

    def test_grad_sync_is_reduce_scattered(self):
        e = _engine()
        audit = _audit_step(e)
        model = hlo_audit.grad_sync_wire_model(
            jax.device_get(e.state.params), e.dp_size)
        rs = audit.of_kind("reduce-scatter")
        # Every scatterable grad leaf is reduce-scattered: the summed
        # reduce-scatter payload equals the model's scatterable bytes
        # exactly (w1 [8,16] + b1 [16] + w2 [16,4] in f32).
        assert sum(o.payload_bytes for o in rs) == \
            model["scatterable_bytes"], audit.summary()
        # Wire bytes on the analytic reduce-scatter model.
        repl_wire = hlo_audit.ring_wire_bytes(
            "all-reduce", model["replicated_bytes"], e.dp_size)
        assert sum(o.wire_bytes for o in rs) + repl_wire == \
            model["reduce_scatter_wire_bytes"]
        # ~half the all-reduce wire (the ZeRO-2 claim).
        assert model["reduce_scatter_wire_bytes"] <= \
            0.52 * model["all_reduce_wire_bytes"]

    def test_grads_never_materialize_unpartitioned(self):
        """No all-reduce in the step carries a scatterable-grad-sized
        payload: the fallback lowering (full all-reduce + slice) is the
        failure this test exists to catch."""
        from deepspeed_tpu.runtime.zero.partition import _leaf_spec
        e = _engine()
        audit = _audit_step(e)
        scatterable_leaf_bytes = {
            int(np.prod(l.shape)) * 4
            for l in jax.tree_util.tree_leaves(
                jax.device_get(e.state.params))
            if any(s is not None
                   for s in _leaf_spec(l.shape, e.dp_size, "data"))}
        for o in audit.of_kind("all-reduce"):
            assert o.payload_bytes not in scatterable_leaf_bytes, \
                (o.out_shapes, o.op_name)

    def test_gas2_scatters_inside_the_scan(self):
        """Per-micro-step scatter: the accumulation carry holds 1/dp
        shards only, and the reduce-scatter lives in the scan body."""
        e = _engine(gas=2)
        audit = _audit_step(e, gas=2)
        rs = audit.of_kind("reduce-scatter")
        assert rs and all(o.in_loop for o in rs), \
            [(o.computation, o.in_loop) for o in rs]

    def test_auto_mode_matches_probe(self, mesh8):
        e = _engine()
        lowering = hlo_audit.zero2_grad_sync_lowering(mesh8, "data")
        want = "declarative" if lowering == "reduce-scatter" else "explicit"
        assert e._grad_sync_mode == want


class TestExplicitDeclarativeParity:
    """Explicit psum_scatter vs declarative GSPMD parity on the dp=8 mesh.

    ONE step from identical state is bit-identical (params, moments, and
    loss — asserted below): the local per-rank computation is the same
    program modulo exact power-of-two loss-mean scaling. Across a
    multi-step trajectory the two lowerings' cross-dp reductions sum
    partials in different orders (ring reduce-scatter rotates each
    shard's start rank; all-reduce+slice does not), so strict bitwise
    equality across programs is impossible on generic values — the same
    cross-program limit PR 1 documented for FMA contraction in the fused
    optimizer (tests/test_fused_update.py). The drift is ulp-level in the
    GRADS; Adam's normalized update turns that into an absolute
    (lr-scaled) wiggle on params, so the trajectory bound below is
    absolute: observed <= 7.5e-9 after 3 steps at lr=1e-2, asserted at
    1e-7."""

    def test_single_step_bit_identical(self):
        engines = {m: _engine(seed=7, grad_sync=m)
                   for m in ("declarative", "explicit")}
        batch = random_batch(n=16, seed=11)
        losses = {m: e.train_batch(batch=batch)
                  for m, e in engines.items()}
        assert float(losses["declarative"]) == float(losses["explicit"])
        for field in ("params", "opt_state"):
            a = jax.tree_util.tree_leaves(
                jax.device_get(getattr(engines["declarative"].state, field)))
            b = jax.tree_util.tree_leaves(
                jax.device_get(getattr(engines["explicit"].state, field)))
            for x, y in zip(a, b):
                np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    @pytest.mark.parametrize("gas", [1, 2])
    def test_trajectory_ulp_bounded(self, gas):
        engines = {m: _engine(gas=gas, seed=7, grad_sync=m)
                   for m in ("declarative", "explicit")}
        assert engines["explicit"]._grad_sync_mode == "explicit"
        assert engines["declarative"]._grad_sync_mode == "declarative"
        batch = random_batch(n=16 * gas, seed=11)
        for _ in range(3):
            losses = {m: e.train_batch(batch=batch)
                      for m, e in engines.items()}
        assert float(losses["declarative"]) == float(losses["explicit"])
        for field in ("params", "opt_state"):
            a = jax.tree_util.tree_leaves(
                jax.device_get(getattr(engines["declarative"].state, field)))
            b = jax.tree_util.tree_leaves(
                jax.device_get(getattr(engines["explicit"].state, field)))
            for x, y in zip(a, b):
                x, y = np.asarray(x, np.float64), np.asarray(y, np.float64)
                np.testing.assert_allclose(x, y, rtol=0, atol=1e-7,
                                           err_msg=field)

    def test_explicit_grads_stay_dp_sharded(self):
        e = _engine(grad_sync="explicit")
        fn = e._build_train_step()
        batch = random_batch(n=16)
        mb = e._stack_micro_batches(batch)
        mb = jax.device_put(mb, e._batch_sharding(mb, leading_dims=2))
        txt = fn.lower(e.state, mb, e._base_rng).compile().as_text()
        assert "reduce-scatter" in txt


class TestHonestKnobs:
    def test_reduce_scatter_false_selects_dense_allreduce(self):
        e = _engine(reduce_scatter=False)
        assert e._grad_sync_mode == "allreduce"
        assert e._grad_shardings() is None
        audit = _audit_step(e)
        assert not audit.of_kind("reduce-scatter"), audit.summary()
        assert audit.of_kind("all-reduce")

    def test_reduce_scatter_false_trains_to_parity(self):
        batch = random_batch(n=16, seed=5)
        e_rs = _engine(seed=3)
        e_ar = _engine(seed=3, reduce_scatter=False)
        for _ in range(3):
            l_rs = e_rs.train_batch(batch=batch)
            l_ar = e_ar.train_batch(batch=batch)
        np.testing.assert_allclose(float(l_rs), float(l_ar), rtol=1e-5)

    @staticmethod
    def _capture_logs(fn):
        # The repo logger sets propagate=False, so pytest's caplog (root
        # handler) never sees it — attach a handler directly.
        import logging
        records = []

        class H(logging.Handler):
            def emit(self, r):
                records.append(r.getMessage())

        lg = logging.getLogger("deepspeed_tpu")
        h = H()
        lg.addHandler(h)
        try:
            fn()
        finally:
            lg.removeHandler(h)
        return records

    def test_overlap_comm_notice_logged(self):
        msgs = self._capture_logs(lambda: _engine(overlap_comm=True))
        assert any("latency-hiding scheduler" in m for m in msgs), msgs

    def test_init_logs_audited_lowering_and_wire_bytes(self):
        msgs = [m for m in self._capture_logs(lambda: _engine())
                if "ZeRO-2 grad sync" in m]
        assert msgs and "wire bytes/step" in msgs[0], msgs

    def test_explicit_requires_pure_dp(self):
        """grad_sync='explicit' on an ineligible config is a loud error,
        not a silent declarative fallback."""
        params = simple_model_params(jax.random.PRNGKey(0))
        cfg = base_config(
            zero_optimization={"stage": 2, "grad_sync": "explicit"},
            mesh={"model_parallel_size": 2})   # dp=4 x mp=2: not pure dp
        with pytest.raises(ValueError, match="explicit"):
            deepspeed_tpu.initialize(model=simple_loss_fn,
                                     model_params=params, config=cfg)


# ------------------------------------------------------------------ #
# 1-bit Adam wire model
# ------------------------------------------------------------------ #
class TestOnebitWire:
    def test_compression_phase_is_about_one_32th_dense(self):
        """Tier-1 gate: fails if 1-bit exceeds ~1/32 dense wire (sign bit
        per element + one f32 scale per chunk, dp=8 chunks)."""
        from deepspeed_tpu.ops.onebit import comm_bytes, compression_ratio
        n = 1 << 20
        dense = comm_bytes(n, compressed=False)
        compressed = comm_bytes(n, compressed=True, chunks=8)
        assert compressed <= dense / 28, (compressed, dense)
        assert compression_ratio(n, chunks=8) >= 28
        # asymptotically exactly 32x minus the scale overhead
        assert abs(compression_ratio(1 << 26, chunks=8) - 32.0) < 0.1

    def test_comm_audit_record_consistent(self):
        """The recorded COMM_AUDIT.json (tools/run_comm_audit.sh) must
        exist and pass its own checks — the artifact form of these
        invariants."""
        path = os.path.join(REPO, "COMM_AUDIT.json")
        assert os.path.exists(path), "run tools/run_comm_audit.sh"
        rec = json.load(open(path))
        assert rec["all_pass"] is True
        for name in ("zero1", "zero2", "zero3", "onebit",
                     "pipeline_1f1b", "ring_attention"):
            assert rec["configs"][name]["pass"] is True, name
        # ISSUE-8 satellite: the fused-chunk-gather finding is RESOLVED
        # (shard-local V-interleaved layout) — the recorded artifact must
        # show zero chunk-sized collectives on the fused apply.
        chunk = rec["findings"]["fused_chunk_gather"]
        assert chunk["resolved"] is True
        assert chunk["fused_chunk_gather_collectives"] == []


# ------------------------------------------------------------------ #
# 1F1B boundary-permute bytes
# ------------------------------------------------------------------ #
class Test1F1BPermuteBytes:
    def test_permute_bytes_equal_boundary_times_ticks(self):
        """Tier-1 gate: fails if 1F1B permute bytes != boundary x ticks.
        The scan body must hold exactly two boundary-sized
        collective-permutes (activations up, cotangents down); per-step
        traffic is 2 x boundary x (M + 2(P-1)) with ticks from the
        schedule oracle."""
        from deepspeed_tpu.runtime.pipe.spmd_1f1b import (
            spmd_pipeline_1f1b_grads, tick_table)
        Pstages, M, mb, H, S, V = 4, 3, 2, 16, 4, 32
        mesh = build_mesh(pp=Pstages, dp=1,
                          devices=jax.devices()[:Pstages])
        k = jax.random.PRNGKey(0)
        params = {"shared": {"wte": jax.random.normal(k, (V, H)) * 0.1},
                  "blocks": {"w": jax.random.normal(k, (Pstages, H, H))}}

        def embed_fn(shared, tokens, rng):
            return shared["wte"][tokens]

        def stage_fn(blocks, x, rng):
            return jnp.tanh(x @ blocks["w"][0])

        def head_fn(shared, y, targets, rng):
            logits = y @ shared["wte"].T
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            onehot = jax.nn.one_hot(targets, logits.shape[-1])
            return -jnp.mean(jnp.sum(onehot * logp, axis=-1))

        gfn = spmd_pipeline_1f1b_grads(embed_fn, stage_fn, head_fn,
                                       num_stages=Pstages,
                                       num_micro_batches=M, mesh=mesh)
        batch = jnp.zeros((M * mb, S + 1), jnp.int32)
        with mesh:
            audit = hlo_audit.audit_jit(jax.jit(gfn), params, batch,
                                        jax.random.PRNGKey(1))
        boundary = mb * S * H * 4                      # [mb, S, H] f32
        ticks = len(tick_table(M, Pstages))            # M + 2(P-1)
        assert ticks == M + 2 * (Pstages - 1)
        loop_perms = audit.in_loops("collective-permute")
        assert len(loop_perms) == 2, audit.summary()
        assert all(o.out_bytes == boundary for o in loop_perms), \
            [o.out_shapes for o in loop_perms]
        # The COMPILED scan bound must equal the oracle's tick count —
        # per-step permute bytes = 2 x boundary x ticks then follows
        # from the two checks above (asserting the product again would
        # be a tautology: ticks would cancel).
        assert ticks in audit.while_trip_counts(), \
            (ticks, audit.while_trip_counts())


# ------------------------------------------------------------------ #
# All-to-all: parsing + wire-model pricing on a synthetic MoE dispatch
# ------------------------------------------------------------------ #
class TestAllToAllDispatch:
    """hlo_audit parses all-to-all but, pre-MoE, nothing in the engine
    emits one — this synthetic shard_map dispatch keeps the parser and
    the wire model tested ground for ROADMAP item 4 (expert-parallel
    all-to-all dispatch/combine)."""

    E, C, H = 8, 4, 16          # experts (= dp ranks), capacity, hidden

    def _audit(self, mesh8):
        from deepspeed_tpu.parallel import comm

        def dispatch(x):        # per-rank expert blocks [E, C, H]
            return comm.all_to_all(x, "data", split_axis=0, concat_axis=0)

        fn = comm.shard_map(dispatch, mesh=mesh8, in_specs=(P("data"),),
                            out_specs=P("data"), check_vma=False)
        x = jnp.ones((self.E * self.E, self.C, self.H), jnp.float32)
        return hlo_audit.audit_jit(jax.jit(fn), x)

    def test_parses_variadic_all_to_all(self, mesh8):
        """XLA lowers the tiled all_to_all to ONE variadic instruction
        whose 8-way operand/result tuples carry `/*index=N*/` comments —
        the tuple form the shared INSTR_RE must survive (a `[^=]*`-style
        shape alternative dies on the `=` inside the comment)."""
        a2a = self._audit(mesh8).of_kind("all-to-all")
        assert len(a2a) == 1, self._audit(mesh8).summary()
        op = a2a[0]
        assert op.group_size == 8 and op.num_groups == 1
        assert len(op.in_shapes) == self.E
        assert set(op.in_shapes) == {f"f32[1,{self.C},{self.H}]"}
        assert op.out_shapes == op.in_shapes
        assert not op.in_loop
        assert "all_to_all" in op.op_name

    def test_wire_model_prices_full_block(self, mesh8):
        """Ring pricing over the FULL per-device block B = E*C*H*4:
        each rank keeps its own 1/E slice, so (g-1)/g x B crosses the
        wire — the MoE dispatch budget ROADMAP item 4 will be gated on."""
        op = self._audit(mesh8).of_kind("all-to-all")[0]
        full = self.E * self.C * self.H * 4
        assert op.payload_bytes == full
        assert op.wire_bytes == hlo_audit.ring_wire_bytes(
            "all-to-all", full, 8)
        assert op.wire_bytes == (8 - 1) * full // 8


class TestNestedTupleAsync:
    def test_nested_tuple_async_variadic_parses(self):
        """XLA's all-gather combiner merges per-leaf gathers into ONE
        variadic async op whose -start result wraps operand/result
        tuples in an outer pair — the shared INSTR_RE must allow that
        one nesting level (a flat `[^()]*` tuple alternative drops the
        collective from the audit entirely)."""
        synth = """
HloModule m

ENTRY %main (a: f32[128], b: f32[64]) -> f32[192] {
  %ag-start = ((f32[128]{0}, f32[64]{0}), (f32[1024]{0}, f32[512]{0})) all-gather-start(f32[128]{0} %a, f32[64]{0} %b), channel_id=9, replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  ROOT %done = f32[192]{0} bitcast(f32[128]{0} %a)
}
"""
        ops = hlo_audit.parse_hlo_collectives(synth)
        assert len(ops) == 1, ops
        op = ops[0]
        assert op.kind == "all-gather" and op.group_size == 8
        assert op.out_bytes == 1024 * 4     # largest nested component
        assert op.in_bytes == (128 + 64) * 4
