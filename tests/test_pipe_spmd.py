"""SPMD pipeline tests: pp>1 loss/grads must match the pp=1 computation.

The reference's equivalent is test_pipe.py's loss-parity runs of (pp, dp)
topologies against pure DP — here on the virtual 8-device CPU mesh.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPT2_CONFIGS
from deepspeed_tpu.models.gpt2 import gpt2_loss_fn
from deepspeed_tpu.models.gpt2_pipe import gpt2_pipe_spec
from deepspeed_tpu.parallel.topology import build_mesh

from capability import (PARTIAL_AUTO_SKIP_REASON,
                        partial_auto_shard_map_supported)


@pytest.fixture(scope="module")
def cfg():
    # dropout off so pp=1 vs pp=4 comparisons are exact-ish
    return dataclasses.replace(GPT2_CONFIGS["gpt2-tiny"], num_layers=4,
                               hidden_dropout=0.0, attn_dropout=0.0)


def _flat_params(spec):
    """PipeSpec params → models.gpt2 flat params layout."""
    return {**spec.params["shared"], "blocks": spec.params["blocks"]}


class TestSpmdPipeline:
    @pytest.mark.skipif(not partial_auto_shard_map_supported(),
                        reason=PARTIAL_AUTO_SKIP_REASON)
    def test_pipeline_loss_matches_sequential(self, cfg):
        """pp=4 pipelined loss == plain gpt2 loss on identical params."""
        spec = gpt2_pipe_spec(cfg, rng=jax.random.PRNGKey(0))
        mesh = build_mesh(pp=4, dp=2)
        M = 4
        loss_fn = spec.loss_fn(num_stages=4, num_micro=M, mesh=mesh)
        batch = jax.random.randint(jax.random.PRNGKey(1), (M * 2, 17), 0,
                                   cfg.vocab_size)
        with jax.set_mesh(mesh):
            got = float(loss_fn(spec.params, batch, jax.random.PRNGKey(2)))
        want = float(gpt2_loss_fn(cfg)(_flat_params(spec), batch,
                                       jax.random.PRNGKey(2)))
        np.testing.assert_allclose(got, want, rtol=2e-2)

    @pytest.mark.slow
    def test_pipeline_grads_match_sequential(self, cfg):
        spec = gpt2_pipe_spec(cfg, rng=jax.random.PRNGKey(0))
        mesh = build_mesh(pp=4, dp=2)
        M = 4
        loss_fn = spec.loss_fn(num_stages=4, num_micro=M, mesh=mesh)
        batch = jax.random.randint(jax.random.PRNGKey(1), (M * 2, 17), 0,
                                   cfg.vocab_size)
        with jax.set_mesh(mesh):
            g_pipe = jax.jit(jax.grad(loss_fn))(spec.params, batch,
                                                jax.random.PRNGKey(2))
        g_seq = jax.grad(gpt2_loss_fn(cfg))(_flat_params(spec), batch,
                                            jax.random.PRNGKey(2))
        # blocks grads
        for k in g_seq["blocks"]:
            np.testing.assert_allclose(
                np.asarray(g_pipe["blocks"][k], np.float32),
                np.asarray(g_seq["blocks"][k], np.float32),
                rtol=5e-2, atol=5e-3, err_msg=f"blocks/{k}")
        # tied embedding grad: contributions from stage 0 (embed) AND last
        # stage (unembed) must both arrive (ReduceTiedGrads parity).
        np.testing.assert_allclose(
            np.asarray(g_pipe["shared"]["wte"], np.float32),
            np.asarray(g_seq["wte"], np.float32), rtol=5e-2, atol=5e-3)

    @pytest.mark.slow
    def test_engine_end_to_end_pp2_dp2_mp2(self, cfg):
        """Full 3D: PipelineEngine trains and the loss falls (pp2 dp2 mp2)."""
        spec = gpt2_pipe_spec(cfg, rng=jax.random.PRNGKey(0))
        mesh = build_mesh(pp=2, dp=2, mp=2)
        ds = {"train_batch_size": 16,            # micro 2 × dp 2 × gas 4
              "train_micro_batch_size_per_gpu": 2,
              "gradient_accumulation_steps": 4,
              "bf16": {"enabled": True},
              "zero_optimization": {"stage": 1},
              "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
              "steps_per_print": 1000}
        engine, *_ = deepspeed_tpu.initialize(config=ds, model=spec, mesh=mesh)
        batch = np.random.default_rng(0).integers(
            0, cfg.vocab_size, size=(16, 17)).astype(np.int32)
        losses = [float(jax.device_get(engine.train_batch(batch)))
                  for _ in range(10)]
        assert losses[-1] < losses[0], losses

    def test_layer_divisibility_enforced(self, cfg):
        spec = gpt2_pipe_spec(dataclasses.replace(cfg, num_layers=3))
        mesh = build_mesh(pp=4, dp=2)
        ds = {"train_batch_size": 4, "train_micro_batch_size_per_gpu": 2,
              "gradient_accumulation_steps": 2,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
        with pytest.raises(ValueError):
            deepspeed_tpu.initialize(config=ds, model=spec, mesh=mesh)
