"""utils/timer.py coverage: SynchronizedWallClockTimer mid-run elapsed()
count restoration, ThroughputTimer windowed (non-synchronized) mode, and
the no-samples signal (0.0 + has_samples(), replacing the old
``float("-1")`` sentinel)."""
import time

import deepspeed_tpu.utils.timer as timer_mod
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, \
    ThroughputTimer


class TestSynchronizedWallClockTimer:
    def test_basic_cycle(self):
        t = SynchronizedWallClockTimer.Timer("t")
        t.start(synchronize=False)
        time.sleep(0.002)
        t.stop(synchronize=False)
        assert t.count == 1
        assert t.elapsed_ > 0
        assert t.mean() == t.elapsed_

    def test_mid_run_elapsed_restores_count(self):
        """elapsed() while running must not inflate count: mean() should
        reflect only real start/stop cycles."""
        t = SynchronizedWallClockTimer.Timer("t")
        t.start(synchronize=False)
        t.stop(synchronize=False)
        first = t.elapsed_
        t.start(synchronize=False)
        time.sleep(0.002)
        mid = t.elapsed(reset=False)     # query mid-run
        assert mid >= first              # includes the running interval
        assert t.started_                # still running afterwards
        assert t.count == 1              # the mid-run stop didn't count
        t.stop(synchronize=False)
        assert t.count == 2
        assert t.mean() == t.elapsed_ / 2

    def test_mid_run_elapsed_with_reset(self):
        t = SynchronizedWallClockTimer.Timer("t")
        t.start(synchronize=False)
        time.sleep(0.001)
        val = t.elapsed(reset=True)
        assert val > 0
        assert t.started_           # restarted after the reset
        assert t.count == 0         # reset cleared it; restore kept 0
        t.stop(synchronize=False)
        assert t.count == 1

    def test_group_log(self):
        timers = SynchronizedWallClockTimer()
        timers("a").start(synchronize=False)
        timers("a").stop(synchronize=False)
        out = timers.log(["a", "missing"], reset=True)
        assert "a:" in out and "missing" not in out


class TestThroughputTimer:
    def _spin(self, t, n, sleep=0.001):
        for _ in range(n):
            t.start()
            time.sleep(sleep)
            t.stop(report_speed=False)

    def test_no_samples_signal(self):
        """Before any measurement window closes, the timer reports 0.0
        with an explicit has_samples() == False — NOT the old
        float("-1") sentinel that read as a plausible rate."""
        t = ThroughputTimer(batch_size=8, start_step=2, steps_per_output=4,
                            synchronized=False)
        assert not t.has_samples()
        assert t.avg_samples_per_sec() == 0.0
        self._spin(t, 3)        # warmup only; window not closed yet
        assert not t.has_samples()
        assert t.avg_samples_per_sec() == 0.0

    def test_windowed_mode_measures(self):
        """Non-synchronized mode fences only at window boundaries and
        averages over the window."""
        t = ThroughputTimer(batch_size=8, start_step=2, steps_per_output=4,
                            synchronized=False)
        self._spin(t, 12)
        assert t.has_samples()
        rate = t.avg_samples_per_sec()
        assert rate > 0
        # Sanity bound: each counted step slept >= 1 ms, so the rate
        # cannot exceed batch_size / 1ms.
        assert rate < 8 / 0.001 * 1.5
        # Window accounting: counted steps cover only closed windows.
        assert t.counted_steps > 0
        assert t.total_elapsed_time > 0

    def test_windowed_syncs_only_at_boundaries(self):
        t = ThroughputTimer(batch_size=8, start_step=0, steps_per_output=5,
                            synchronized=False)
        before = timer_mod.device_sync_count()
        self._spin(t, 5)
        # one fence to open the window + one to close it
        assert timer_mod.device_sync_count() - before == 2

    def test_synchronized_mode_fences_every_step(self):
        t = ThroughputTimer(batch_size=8, start_step=0, steps_per_output=100,
                            synchronized=True)
        before = timer_mod.device_sync_count()
        self._spin(t, 3)
        assert timer_mod.device_sync_count() - before == 2 * 3
        assert t.has_samples()


def test_device_sync_counter_increments():
    before = timer_mod.device_sync_count()
    timer_mod._device_sync()
    assert timer_mod.device_sync_count() == before + 1
