"""Partitioning + runtime util tests — parity with reference
tests/unit/test_partition.py (partition_balanced, PartitionedTensor) and the
CheckOverflow/norm helpers."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.utils import (partition_uniform, partition_balanced,
                                         PartitionedTensor, tree_has_inf_or_nan,
                                         global_norm, clip_grad_norm_)


class TestPartitionUniform:
    def test_even(self):
        assert partition_uniform(8, 4) == [0, 2, 4, 6, 8]

    def test_residual(self):
        parts = partition_uniform(10, 4)
        assert parts[0] == 0 and parts[-1] == 10
        sizes = [b - a for a, b in zip(parts, parts[1:])]
        assert max(sizes) - min(sizes) <= 1

    def test_fewer_items_than_parts(self):
        parts = partition_uniform(2, 4)
        assert parts == [0, 1, 2, 2, 2]


class TestPartitionBalanced:
    def test_uniform_weights(self):
        parts = partition_balanced([1.0] * 8, 4)
        assert parts == [0, 2, 4, 6, 8]

    def test_skewed(self):
        weights = [10.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0]
        parts = partition_balanced(weights, 2)
        assert parts[0] == 0 and parts[-1] == 8
        # Heavy first item should be alone-ish: max part weight near 10.
        loads = [sum(weights[a:b]) for a, b in zip(parts, parts[1:])]
        assert max(loads) <= 11.0

    def test_monotone_boundaries(self):
        parts = partition_balanced([3, 1, 4, 1, 5, 9, 2, 6], 3)
        assert all(b >= a for a, b in zip(parts, parts[1:]))
        assert parts[0] == 0 and parts[-1] == 8


class TestPartitionedTensor:
    def test_round_trip(self):
        x = jnp.arange(23, dtype=jnp.float32).reshape(23)
        world = 4
        parts = [PartitionedTensor(x, world, r) for r in range(world)]
        full = parts[0].full([p.local_data for p in parts])
        np.testing.assert_allclose(np.asarray(full), np.asarray(x))

    def test_2d_round_trip(self):
        x = jnp.arange(30, dtype=jnp.bfloat16).reshape(5, 6)
        world = 4
        parts = [PartitionedTensor(x, world, r) for r in range(world)]
        full = parts[0].full([p.local_data for p in parts])
        assert full.shape == (5, 6)
        assert full.dtype == jnp.bfloat16


class TestOverflowAndNorms:
    def test_no_overflow(self):
        tree = {"a": jnp.ones((4,)), "b": jnp.zeros((2, 2))}
        assert not bool(tree_has_inf_or_nan(tree))

    def test_nan(self):
        tree = {"a": jnp.array([1.0, jnp.nan])}
        assert bool(tree_has_inf_or_nan(tree))

    def test_inf(self):
        tree = {"a": jnp.array([1.0, jnp.inf])}
        assert bool(tree_has_inf_or_nan(tree))

    def test_jittable(self):
        f = jax.jit(tree_has_inf_or_nan)
        assert bool(f({"a": jnp.array([jnp.inf])}))

    def test_global_norm(self):
        tree = {"a": jnp.array([3.0]), "b": jnp.array([4.0])}
        assert float(global_norm(tree)) == pytest.approx(5.0)

    def test_clip(self):
        tree = {"a": jnp.array([3.0]), "b": jnp.array([4.0])}
        clipped, norm = clip_grad_norm_(tree, max_norm=1.0)
        assert float(norm) == pytest.approx(5.0)
        assert float(global_norm(clipped)) == pytest.approx(1.0, rel=1e-4)
