"""Sharded checkpoint layout + elastic dp-resize-on-load.

Reference: engine.py:1472-1572 save layout (mp_rank_XX model files,
zero_pp_rank_D per-dp-rank optim shards), stage1.py:848-1106 elastic
re-partitioning on a changed dp world size.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.parallel.topology import build_mesh

from simple_model import simple_loss_fn, simple_model_params, random_batch


def _engine(dp, lr=1e-2, seed=0, stage=2, slices=1):
    if slices > 1:
        # slices x dp must cover all 8 virtual devices (slice is the
        # outermost mesh axis; dp is the per-slice remainder).
        mesh = build_mesh(slices=slices)
        assert int(mesh.shape["data"]) == dp
    else:
        mesh = build_mesh(devices=jax.devices()[:dp])
    cfg = {
        "train_batch_size": 8 * dp * slices,
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 1,
        "zero_optimization": {"stage": stage},
        "optimizer": {"type": "Adam", "params": {"lr": lr}},
        "steps_per_print": 10 ** 9,
    }
    return DeepSpeedEngine(model=simple_loss_fn,
                           model_params=simple_model_params(
                               jax.random.PRNGKey(seed)),
                           config=cfg, mesh=mesh)


def test_save_writes_per_rank_shard_files(tmp_path):
    eng = _engine(dp=4)
    eng.train_batch(random_batch(32, seed=0))
    eng.save_checkpoint(str(tmp_path), tag="t")
    files = sorted(os.listdir(tmp_path / "t"))
    for d in range(4):
        assert f"zero_pp_rank_{d}_mp_rank_00_optim_states.msgpack" in files
    assert "mp_rank_00_model_states.msgpack" in files
    # shard files are ~1/dp of the total moment bytes: rank>0 files hold
    # only sharded leaves
    sizes = [os.path.getsize(tmp_path / "t" /
                             f"zero_pp_rank_{d}_mp_rank_00_optim_states.msgpack")
             for d in range(4)]
    assert sizes[1] < sizes[0]            # rank0 carries scalars+replicated
    assert sizes[1] == sizes[2] == sizes[3]


@pytest.mark.parametrize("dp_load", [2, 8])
def test_dp_resize_on_load(tmp_path, dp_load):
    """Save at dp=4, load at dp=2 and dp=8 — optimizer state re-partitions
    and the loss trajectory continues."""
    eng = _engine(dp=4, lr=5e-2)
    for i in range(5):
        eng.train_batch(random_batch(32, seed=i))
    eng.save_checkpoint(str(tmp_path), tag="r")
    # continue the original engine one step for a reference trajectory
    ref_loss_next = float(jax.device_get(
        eng.train_batch(random_batch(32, seed=100))))

    eng2 = _engine(dp=dp_load, lr=5e-2, seed=1)
    p, _ = eng2.load_checkpoint(str(tmp_path), tag="r")
    assert p is not None
    b = jax.device_get(eng2.state.params)
    # compare against the SAVED state: reload into a third engine at dp=4
    eng3 = _engine(dp=4, lr=5e-2, seed=2)
    eng3.load_checkpoint(str(tmp_path), tag="r")
    for x, y in zip(jax.tree_util.tree_leaves(jax.device_get(eng3.state.params)),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)
    # optimizer moments identical post-load (full assembly equality)
    for x, y in zip(jax.tree_util.tree_leaves(jax.device_get(eng3.state.opt_state)),
                    jax.tree_util.tree_leaves(jax.device_get(eng2.state.opt_state))):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)
    # training continues at the new dp size with a comparable loss
    l2 = float(jax.device_get(eng2.train_batch(
        random_batch(8 * dp_load, seed=100))))
    assert np.isfinite(l2)
    assert abs(l2 - ref_loss_next) < 0.5, (l2, ref_loss_next)


@pytest.mark.parametrize("dp_load,stage_load", [(2, 3), (8, 3), (4, 2)])
def test_stage3_checkpoint_elastic(tmp_path, dp_load, stage_load):
    """Stage-3 checkpoints are elastic BOTH ways: save under dp=4 /
    stage 3 (params dp-sharded on device, full arrays in the files),
    load under dp=2 and dp=8 — and under stage 2 — with bit-identical
    params and moments. The save path assembles full leaves from the
    shards; _place_state re-partitions for whatever layout the loading
    engine declares (extends the dp-resize pattern above to the
    parameter tree itself)."""
    eng = _engine(dp=4, lr=5e-2, stage=3)
    for i in range(4):
        eng.train_batch(random_batch(32, seed=i))
    eng.save_checkpoint(str(tmp_path), tag="z3")

    eng2 = _engine(dp=dp_load, lr=5e-2, seed=1, stage=stage_load)
    p, _ = eng2.load_checkpoint(str(tmp_path), tag="z3")
    assert p is not None
    if stage_load == 3 and dp_load > 1:
        assert "data" in str(eng2.state.params["w1"].sharding.spec)
    for x, y in zip(
            jax.tree_util.tree_leaves(jax.device_get(eng.state.params)),
            jax.tree_util.tree_leaves(jax.device_get(eng2.state.params))):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for x, y in zip(
            jax.tree_util.tree_leaves(jax.device_get(eng.state.opt_state)),
            jax.tree_util.tree_leaves(jax.device_get(eng2.state.opt_state))):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    # training continues at the new world size / stage
    l2 = float(jax.device_get(eng2.train_batch(
        random_batch(8 * dp_load, seed=100))))
    assert np.isfinite(l2)


@pytest.mark.parametrize("direction", ["slices2_to_flat8",
                                       "flat8_to_slices2"])
def test_slice_elastic_stage3_checkpoint(tmp_path, direction):
    """ISSUE 18: the `slice` axis is checkpoint-elastic under stage 3.
    Save from a slices=2 x dp=4 stage-3 engine and resume on a flat
    dp=8 mesh — and vice versa — with params AND moments bit-identical.
    The save path assembles full leaves from the in-slice shards (the
    across-slice copies are replicas, so assembly is layout-free);
    _place_state re-partitions for whatever factorization the loading
    engine declares."""
    if direction == "slices2_to_flat8":
        src = _engine(dp=4, lr=5e-2, stage=3, slices=2)
        dst = _engine(dp=8, lr=5e-2, seed=1, stage=3)
    else:
        src = _engine(dp=8, lr=5e-2, stage=3)
        dst = _engine(dp=4, lr=5e-2, seed=1, stage=3, slices=2)
    for i in range(3):
        src.train_batch(random_batch(64, seed=i))
    src.save_checkpoint(str(tmp_path), tag="z3s")

    p, _ = dst.load_checkpoint(str(tmp_path), tag="z3s")
    assert p is not None
    spec = str(dst.state.params["w1"].sharding.spec)
    assert "data" in spec and "slice" not in spec
    for x, y in zip(
            jax.tree_util.tree_leaves(jax.device_get(src.state.params)),
            jax.tree_util.tree_leaves(jax.device_get(dst.state.params))):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for x, y in zip(
            jax.tree_util.tree_leaves(
                jax.device_get(src.state.opt_state)),
            jax.tree_util.tree_leaves(
                jax.device_get(dst.state.opt_state))):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    l2 = float(jax.device_get(dst.train_batch(
        random_batch(64, seed=100))))
    assert np.isfinite(l2)


def test_stage2_checkpoint_loads_into_stage3(tmp_path):
    """The reverse migration: a stage-2 checkpoint restores into a
    stage-3 engine bit-exactly (params re-partition on load)."""
    eng = _engine(dp=4, lr=5e-2, stage=2)
    for i in range(3):
        eng.train_batch(random_batch(32, seed=i))
    eng.save_checkpoint(str(tmp_path), tag="s2")
    eng3 = _engine(dp=4, lr=5e-2, seed=2, stage=3)
    p, _ = eng3.load_checkpoint(str(tmp_path), tag="s2")
    assert p is not None
    assert "data" in str(eng3.state.params["w1"].sharding.spec)
    for x, y in zip(
            jax.tree_util.tree_leaves(jax.device_get(eng.state.params)),
            jax.tree_util.tree_leaves(jax.device_get(eng3.state.params))):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_legacy_single_file_checkpoint_still_loads(tmp_path):
    """Old-layout checkpoints (single optim blob, no shard meta) load."""
    eng = _engine(dp=2)
    eng.train_batch(random_batch(16, seed=0))
    # write old layout by hand
    import json
    from flax import serialization
    path = tmp_path / "old"
    os.makedirs(path, exist_ok=True)
    host = jax.device_get(eng.state)
    with open(path / "mp_rank_00_model_states.msgpack", "wb") as f:
        f.write(serialization.to_bytes(
            {"module": jax.tree_util.tree_map(np.asarray, host.params)}))
    with open(path / "zero_pp_rank_0_mp_rank_00_optim_states.msgpack", "wb") as f:
        f.write(serialization.to_bytes({
            "opt_state": jax.tree_util.tree_map(np.asarray, host.opt_state),
            "step": np.asarray(host.step),
            "loss_scale": np.asarray(host.loss_scale),
            "growth_count": np.asarray(host.growth_count),
            "hysteresis": np.asarray(host.hysteresis),
            "skipped": np.asarray(host.skipped_steps)}))
    with open(path / "engine_meta.json", "w") as f:
        # fused_moment_layout=2: the blob above snapshots the CURRENT
        # engine's (V-interleaved) moment buffers — the legacy part
        # under test is the single-blob FILE layout, not the moment
        # layout (a truly pre-interleave moment blob is refused; see
        # test_fused_update.test_pre_interleave_checkpoint_refused).
        json.dump({"global_steps": 1, "global_samples": 16,
                   "skipped_steps": 0, "dp_world_size": 2,
                   "fused_moment_layout": 2,
                   "client_state": {}}, f)
    eng2 = _engine(dp=2, seed=3)
    p, _ = eng2.load_checkpoint(str(tmp_path), tag="old")
    assert p is not None
    for x, y in zip(jax.tree_util.tree_leaves(jax.device_get(eng.state.params)),
                    jax.tree_util.tree_leaves(jax.device_get(eng2.state.params))):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)


def test_mp_sharded_model_files(tmp_path):
    """TP runs write one model file per mp rank, each holding slices."""
    from jax.sharding import PartitionSpec as P
    mesh = build_mesh(mp=2, devices=jax.devices()[:4])   # dp=2 x mp=2
    params = {"w": jnp.arange(64 * 8, dtype=jnp.float32).reshape(64, 8),
              "b": jnp.zeros((8,), jnp.float32)}

    def loss_fn(p, batch, rng):
        x, y = batch
        h = x @ p["w"][:x.shape[-1], :]
        return jnp.mean((h.sum(-1) - y) ** 2)

    eng = DeepSpeedEngine(
        model=loss_fn, model_params=params,
        config={"train_batch_size": 16, "train_micro_batch_size_per_gpu": 8,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "steps_per_print": 10 ** 9},
        mesh=mesh, param_shardings={"w": P("model", None), "b": P(None)})
    eng.train_batch(random_batch(16, seed=0))
    eng.save_checkpoint(str(tmp_path), tag="mp")
    files = os.listdir(tmp_path / "mp")
    assert "mp_rank_00_model_states.msgpack" in files
    assert "mp_rank_01_model_states.msgpack" in files
    eng2 = DeepSpeedEngine(
        model=loss_fn, model_params=jax.tree_util.tree_map(jnp.zeros_like,
                                                           params),
        config={"train_batch_size": 16, "train_micro_batch_size_per_gpu": 8,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "steps_per_print": 10 ** 9},
        mesh=mesh, param_shardings={"w": P("model", None), "b": P(None)})
    p, _ = eng2.load_checkpoint(str(tmp_path), tag="mp")
    assert p is not None
    for x, y in zip(jax.tree_util.tree_leaves(jax.device_get(eng.state.params)),
                    jax.tree_util.tree_leaves(jax.device_get(eng2.state.params))):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)


def test_pipeline_per_layer_files(tmp_path):
    """PipelineModule checkpoints write layer_NN-model_states files (tied
    params once) and reload through a PipelineEngine."""
    from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
    from deepspeed_tpu.runtime.pipe.module import PipelineModule

    def make_layer(dim):
        def layer(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])
        layer.init = lambda key: {
            "w": jax.random.normal(key, (dim, dim)) * 0.3,
            "b": jnp.zeros((dim,))}
        return layer

    layers = [make_layer(8) for _ in range(3)]

    def loss_head(x, labels):
        return jnp.mean((x.sum(-1) - labels) ** 2)

    model = PipelineModule(layers, num_stages=1, loss_fn=loss_head,
                           partition_method="uniform")
    params = {f"layer_{i}": layers[i].init(jax.random.PRNGKey(i))
              for i in range(3)}
    mesh = build_mesh(devices=jax.devices()[:1])
    cfg = {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 8,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "steps_per_print": 10 ** 9}
    eng = PipelineEngine(model=model, model_params=params, config=cfg,
                         mesh=mesh)
    eng.train_batch(random_batch(8, seed=0))
    eng.save_checkpoint(str(tmp_path), tag="pp")
    files = os.listdir(tmp_path / "pp")
    for i in range(3):
        assert f"layer_{i:02d}-model_states.msgpack" in files
    assert "mp_rank_00_model_states.msgpack" not in files

    eng2 = PipelineEngine(model=model,
                          model_params=jax.tree_util.tree_map(
                              jnp.zeros_like, params),
                          config=cfg, mesh=mesh)
    p, _ = eng2.load_checkpoint(str(tmp_path), tag="pp")
    assert p is not None
    for x, y in zip(jax.tree_util.tree_leaves(jax.device_get(eng.state.params)),
                    jax.tree_util.tree_leaves(jax.device_get(eng2.state.params))):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)


@pytest.mark.slow
def test_resume_continues_training_trajectory(tmp_path):
    """Save mid-run, load into a FRESH engine, keep training: the resumed
    run must land exactly where the uninterrupted run does (step counter,
    rng stream, optimizer moments and loss-scale state all restored) —
    the reference's checkpoint tier asserts this continuity, not just
    file round-trips."""
    batches = [random_batch(n=16, seed=100 + i) for i in range(40)]

    eng_a = _engine(dp=2)
    for b in batches:
        la = eng_a.train_batch(b)

    eng_b1 = _engine(dp=2)
    for b in batches[:20]:
        eng_b1.train_batch(b)
    eng_b1.save_checkpoint(str(tmp_path), tag="mid")

    eng_b2 = _engine(dp=2, seed=7)      # different init: load must win
    eng_b2.load_checkpoint(str(tmp_path), tag="mid")
    assert int(jax.device_get(eng_b2.state.step)) == 20
    for b in batches[20:]:
        lb = eng_b2.train_batch(b)

    np.testing.assert_allclose(float(jax.device_get(la)),
                               float(jax.device_get(lb)), rtol=1e-6)
    for pa, pb in zip(jax.tree_util.tree_leaves(
                          jax.device_get(eng_a.state.params)),
                      jax.tree_util.tree_leaves(
                          jax.device_get(eng_b2.state.params))):
        np.testing.assert_allclose(pa, pb, rtol=1e-6, atol=1e-7)
