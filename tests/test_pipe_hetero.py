"""Non-uniform pipeline stages: unequal layers-per-stage execute at pp>1
via identity-padded stages (reference pipe/module.py:348-404 builds
non-uniform per-rank layer ranges; here pad slots lax.cond-skip so the
SPMD stage program stays uniform)."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPT2_CONFIGS
from deepspeed_tpu.models.gpt2 import gpt2_loss_fn
from deepspeed_tpu.models.gpt2_pipe import gpt2_pipe_spec
from deepspeed_tpu.parallel.topology import build_mesh
from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule

pytestmark = pytest.mark.slow  # whole-module slow tier (see conftest)


@pytest.fixture(scope="module")
def cfg():
    return dataclasses.replace(GPT2_CONFIGS["gpt2-tiny"], num_layers=6,
                               hidden_dropout=0.0, attn_dropout=0.0)


def _flat_params_unpadded(cfg, rng):
    from deepspeed_tpu.models.gpt2 import gpt2_init
    return gpt2_init(rng, cfg)


class TestNonUniformGPT2:
    def test_uneven_cuts_match_sequential(self, cfg):
        """6 layers over 4 stages as [2, 2, 1, 1]."""
        rng0 = jax.random.PRNGKey(0)
        spec = gpt2_pipe_spec(cfg, rng=rng0, stage_layers=[2, 2, 1, 1])
        assert spec.num_layers == 8          # 4 stages padded to 2
        mesh = build_mesh(pp=4, dp=2)
        M = 4
        loss_fn = spec.loss_fn(num_stages=4, num_micro=M, mesh=mesh)
        batch = jax.random.randint(jax.random.PRNGKey(1), (M * 2, 17), 0,
                                   cfg.vocab_size)
        with jax.set_mesh(mesh):
            got = float(loss_fn(spec.params, batch, jax.random.PRNGKey(2)))
        flat = _flat_params_unpadded(cfg, rng0)
        want = float(gpt2_loss_fn(cfg)(flat, batch, jax.random.PRNGKey(2)))
        np.testing.assert_allclose(got, want, rtol=2e-2)

    def test_uneven_cuts_grads_match_sequential(self, cfg):
        rng0 = jax.random.PRNGKey(0)
        spec = gpt2_pipe_spec(cfg, rng=rng0, stage_layers=[2, 2, 1, 1])
        mesh = build_mesh(pp=4, dp=2)
        M = 4
        loss_fn = spec.loss_fn(num_stages=4, num_micro=M, mesh=mesh)
        batch = jax.random.randint(jax.random.PRNGKey(1), (M * 2, 17), 0,
                                   cfg.vocab_size)
        with jax.set_mesh(mesh):
            g_pipe = jax.jit(jax.grad(loss_fn))(spec.params, batch,
                                                jax.random.PRNGKey(2))
        flat = _flat_params_unpadded(cfg, rng0)
        g_seq = jax.grad(gpt2_loss_fn(cfg))(flat, batch,
                                            jax.random.PRNGKey(2))
        # Padded layout: stage s slot l holds real layer bounds[s]+l.
        got_qkv = np.asarray(g_pipe["blocks"]["qkv_kernel"], np.float32)
        want_qkv = np.asarray(g_seq["blocks"]["qkv_kernel"], np.float32)
        slot_of = [0, 1, 2, 3, 4, 6]         # layer idx -> padded slot
        for li, slot in enumerate(slot_of):
            np.testing.assert_allclose(got_qkv[slot], want_qkv[li],
                                       rtol=5e-2, atol=5e-3,
                                       err_msg=f"layer {li}")
        # Pad slots got zero grads (identity layers touch nothing).
        for pad_slot in (5, 7):
            assert np.abs(got_qkv[pad_slot]).max() == 0.0
        np.testing.assert_allclose(
            np.asarray(g_pipe["shared"]["wte"], np.float32),
            np.asarray(g_seq["wte"], np.float32), rtol=5e-2, atol=5e-3)

    def test_uneven_cuts_1f1b(self, cfg):
        """The 1F1B schedule composes with padded stages."""
        rng0 = jax.random.PRNGKey(0)
        spec = gpt2_pipe_spec(cfg, rng=rng0, stage_layers=[2, 2, 1, 1])
        mesh = build_mesh(pp=4, dp=2)
        M = 4
        gfn = spec.grads_fn(num_stages=4, num_micro=M, mesh=mesh)
        batch = jax.random.randint(jax.random.PRNGKey(1), (M * 2, 17), 0,
                                   cfg.vocab_size)
        with jax.set_mesh(mesh):
            loss, grads = jax.jit(gfn)(spec.params, batch,
                                       jax.random.PRNGKey(2))
        flat = _flat_params_unpadded(cfg, rng0)
        want = float(gpt2_loss_fn(cfg)(flat, batch, jax.random.PRNGKey(2)))
        np.testing.assert_allclose(float(loss), want, rtol=2e-2)

    def test_engine_trains_uneven(self, cfg):
        spec = gpt2_pipe_spec(cfg, rng=jax.random.PRNGKey(0),
                              stage_layers=[2, 2, 1, 1])
        ds = {"train_batch_size": 16,
              "train_micro_batch_size_per_gpu": 2,
              "gradient_accumulation_steps": 4,
              "bf16": {"enabled": True},
              "mesh": {"pipe_parallel_size": 4, "data_parallel_size": 2},
              "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
              "steps_per_print": 10 ** 9}
        engine, _, _, _ = deepspeed_tpu.initialize(config=ds, model=spec)
        batch = np.random.default_rng(0).integers(
            0, cfg.vocab_size, size=(16, 18), dtype=np.int32)
        losses = [float(engine.train_batch(jnp.asarray(batch)))
                  for _ in range(10)]
        assert np.isfinite(losses).all()
        assert min(losses[-3:]) < losses[0] - 0.2, losses


def _mlp_layer(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


class TestNonUniformPipelineModule:
    def test_parameters_partition_pads_and_runs_pp2(self):
        """partition_method='parameters' over layers with unequal widths
        gives non-uniform cuts; to_pipe_spec pads and runs pp=2."""
        D = 8
        module = PipelineModule(
            layers=[_mlp_layer] * 3, num_stages=2,
            partition_method="uniform",
            loss_fn=lambda x, t: jnp.mean((x - t) ** 2))
        # 3 layers over 2 stages -> [2, 1]: non-uniform by construction.
        assert module.parts in ([0, 2, 3], [0, 1, 3])
        rng = np.random.default_rng(0)
        params = {f"layer_{i}":
                  {"w": jnp.asarray(rng.normal(size=(D, D)) * 0.3,
                                    jnp.float32),
                   "b": jnp.zeros((D,), jnp.float32)} for i in range(3)}
        spec = module.to_pipe_spec(params)
        mesh = build_mesh(pp=2, dp=4)
        M = 2
        loss_fn = spec.loss_fn(num_stages=2, num_micro=M, mesh=mesh)
        x = jnp.asarray(rng.normal(size=(8, D)), jnp.float32)
        t = jnp.asarray(rng.normal(size=(8, D)), jnp.float32)
        with jax.set_mesh(mesh):
            got = float(loss_fn(spec.params, (x, t), jax.random.PRNGKey(0)))
        h = x
        for i in range(3):
            h = _mlp_layer(params[f"layer_{i}"], h)
        want = float(jnp.mean((h - t) ** 2))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
