"""Paged prefix-shared KV cache + speculative decoding + multi-replica
routing (PR-12 tentpole).

The load-bearing invariants:

1. **Parity** — block-table decode produces the same logits as the PR-7
   slot-major decode (and as the full batch forward) at fp32 tolerance;
   prefix-shared admissions see bit-identical prefill logits.
2. **Bit-identity** — speculative greedy decode emits exactly the same
   token streams as non-speculative greedy decode (the acceptance-rule
   guarantee), whatever the n-gram drafter proposes.
3. **Safety** — pool exhaustion rejects admission and never corrupts a
   live slot; copy-on-write forks before the first divergent write;
   refcounts return blocks on evict (with LRU retention for prefix
   blocks).
4. **Static shapes** — the paged serve (decode, batched chunk prefill,
   verify, block copy) runs under ``fail_on_recompile`` with zero
   post-warmup retraces.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference import (InferenceEngine, NGramDrafter,
                                     PagedKVCacheSpec, PoolExhausted,
                                     ReplicaRouter,
                                     shared_prefix_requests,
                                     synthetic_requests)
from deepspeed_tpu.inference import kv_cache
from deepspeed_tpu.models.gpt2 import GPT2_CONFIGS, gpt2_apply, gpt2_init
from deepspeed_tpu.monitor.serving import ServingAggregator

CFG32 = dataclasses.replace(GPT2_CONFIGS["gpt2-tiny"], dtype=jnp.float32)
CFG = GPT2_CONFIGS["gpt2-tiny"]


@pytest.fixture(scope="module")
def params32():
    return gpt2_init(jax.random.PRNGKey(0), CFG32)


@pytest.fixture(scope="module")
def params():
    return gpt2_init(jax.random.PRNGKey(1), CFG)


def _prompt(n, seed=0, vocab=None):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab or CFG32.vocab_size,
                        size=n).astype(np.int32)


def _engine(params, *, paged=True, slots=8, max_len=64, chunk=8,
            block_size=16, num_blocks=0, spec_k=0, cfg=CFG32, **tel):
    config = {"inference": {"max_slots": slots, "max_seq_len": max_len,
                            "prefill_chunk": chunk,
                            "block_size": block_size if paged else 0,
                            "num_blocks": num_blocks,
                            "spec_k": spec_k}}
    config.update(tel)
    return InferenceEngine(cfg, params, config=config)


# --------------------------------------------------------------------- #
# Paged primitives (device units)
# --------------------------------------------------------------------- #
class TestPagedPrimitives:
    def test_positions_to_blocks_resolves_and_deadens(self):
        bt = jnp.asarray([[3, 7, kv_cache.DEAD_BLOCK]], jnp.int32)
        pos = jnp.asarray([[0, 5, 9, 11, 13]], jnp.int32)   # bs=4, J=3
        bt_rows = jnp.broadcast_to(bt[:, None, :], (1, 5, 3))
        blk, off = kv_cache.positions_to_blocks(bt_rows[0], pos[0], 4)
        assert blk.tolist() == [3, 7, kv_cache.DEAD_BLOCK,
                                kv_cache.DEAD_BLOCK, kv_cache.DEAD_BLOCK]
        assert off.tolist() == [0, 1, 1, 3, 1]
        # Past the table entirely (pos // bs >= J) is dead too.
        blk2, _ = kv_cache.positions_to_blocks(
            jnp.asarray([5, 6, 7], jnp.int32), jnp.int32(13), 4)
        assert int(blk2) == kv_cache.DEAD_BLOCK

    def test_paged_write_rows_lands_and_dead_rows_dont(self):
        pool = jnp.zeros((2, 4, 2, 4, 3), jnp.float32)  # [G,B,nH,bs,D]
        new = jnp.ones((2, 2, 2, 3), jnp.float32) * \
            jnp.asarray([1.0, 2.0])[None, :, None, None]
        blk = jnp.asarray([[1, kv_cache.DEAD_BLOCK], [3, 0]], jnp.int32)
        off = jnp.asarray([[2, 0], [0, 3]], jnp.int32)
        out = np.array(kv_cache.paged_write_rows(pool, new, blk, off))
        assert (out[0, 1, :, 2] == 1.0).all()       # row 0 of group 0
        assert (out[1, 3, :, 0] == 1.0).all()       # row 0 of group 1
        assert (out[1, 0, :, 3] == 2.0).all()       # row 1 of group 1
        # Dead row wrote nowhere; everything else untouched.
        out[0, 1, :, 2] = 0
        out[1, 3, :, 0] = 0
        out[1, 0, :, 3] = 0
        assert (out == 0).all()

    def test_copy_block_copies_one_group_only(self):
        pool = jnp.arange(2 * 2 * 3 * 1 * 2 * 2, dtype=jnp.float32
                          ).reshape(2, 2, 3, 1, 2, 2)  # [L,G,B,nH,bs,D]
        spec = PagedKVCacheSpec(num_layers=2, num_slots=2, num_blocks=6,
                                block_size=2, max_len=4, num_heads=1,
                                head_dim=2, num_groups=2,
                                dtype=jnp.float32)
        src, dst = kv_cache.copy_block_onehots(spec, group=1, src=0,
                                               dst=2)
        out = np.array(kv_cache.paged_copy_block(pool, jnp.asarray(src),
                                                 jnp.asarray(dst)))
        ref = np.asarray(pool)
        np.testing.assert_array_equal(out[:, 1, 2], ref[:, 1, 0])
        out[:, 1, 2] = ref[:, 1, 2]
        np.testing.assert_array_equal(out, ref)

    def test_spec_validation(self):
        with pytest.raises(ValueError, match="divide"):
            PagedKVCacheSpec(num_layers=1, num_slots=4, num_blocks=8,
                             block_size=3, max_len=8, num_heads=2,
                             head_dim=4).validate()
        with pytest.raises(ValueError, match="divisible"):
            PagedKVCacheSpec(num_layers=1, num_slots=4, num_blocks=7,
                             block_size=2, max_len=8, num_heads=2,
                             head_dim=4, num_groups=2).validate()


# --------------------------------------------------------------------- #
# Host allocator: refcounts, prefix cache, CoW, exhaustion
# --------------------------------------------------------------------- #
class TestBlockAllocator:
    SPEC = PagedKVCacheSpec(num_layers=1, num_slots=4, num_blocks=8,
                            block_size=4, max_len=16, num_heads=2,
                            head_dim=4, num_groups=1, dtype=jnp.float32)

    def test_share_then_refcount_return_on_release(self):
        alloc = kv_cache.BlockAllocator(self.SPEC)
        prompt = _prompt(9, seed=1)                 # 2 full blocks + 1
        a = alloc.admit_prompt(0, 0, prompt, max_new=2)
        assert len(a.table) == 3 and a.matched == 0
        b = alloc.admit_prompt(1, 0, prompt, max_new=2)
        assert b.table[:2] == a.table[:2], "full blocks shared"
        assert b.table[2] != a.table[2], "partial block private"
        assert b.matched == 8 and b.cow_src is None
        assert alloc.blocks_in_use() == 4
        alloc.release(1, b.table)
        # Shared refs dropped; a's blocks still live.
        assert alloc.blocks_in_use() == 3
        alloc.release(0, a.table)
        assert alloc.blocks_in_use() == 0
        # Prefix blocks are LRU-retained (still matchable), private
        # partial block went back to the free list.
        assert alloc.available(0) == 8
        assert len(alloc.match_prefix(0, prompt)[0]) == 2

    def test_exact_match_forks_copy_on_write(self):
        alloc = kv_cache.BlockAllocator(self.SPEC)
        prompt = _prompt(8, seed=2)                 # exactly 2 blocks
        a = alloc.admit_prompt(0, 0, prompt, max_new=2)
        b = alloc.admit_prompt(1, 0, prompt, max_new=2)
        assert b.cow_src == a.table[1] and b.cow_dst == b.table[1]
        assert b.table[0] == a.table[0] and b.table[1] != a.table[1]
        assert b.matched == 7, "last token always re-prefills"
        assert alloc.cow_copies == 1

    def test_exhaustion_rejects_without_touching_live_state(self):
        alloc = kv_cache.BlockAllocator(self.SPEC)   # 8 blocks
        a = alloc.admit_prompt(0, 0, _prompt(13, seed=3), max_new=2)
        alloc.admit_prompt(1, 0, _prompt(13, seed=4), max_new=2)
        assert alloc.available(0) == 0 and alloc.blocks_in_use() == 8
        with pytest.raises(PoolExhausted):
            alloc.admit_prompt(2, 0, _prompt(13, seed=5), max_new=2)
        assert not alloc.can_admit(0, _prompt(13, seed=5), 2)
        # The reject changed nothing for the live slots.
        assert alloc.available(0) == 0 and alloc.blocks_in_use() == 8
        # An evict returns capacity and the queued request admits.
        alloc.release(0, a.table)
        c = alloc.admit_prompt(2, 0, _prompt(13, seed=5), max_new=2)
        assert len(c.table) == 4

    def test_lru_reclaim_under_pressure(self):
        alloc = kv_cache.BlockAllocator(self.SPEC)
        p1 = _prompt(8, seed=5)
        a = alloc.admit_prompt(0, 0, p1, max_new=0)
        alloc.release(0, a.table)
        assert len(alloc.match_prefix(0, p1)[0]) == 2   # retained
        # A request needing all 8 blocks reclaims the retained ones.
        b = alloc.admit_prompt(1, 0, _prompt(15, seed=6), max_new=1)
        assert len(b.table) == 4
        alloc.admit_prompt(2, 0, _prompt(15, seed=7), max_new=1)
        assert alloc.match_prefix(0, p1)[0] == [], "reclaimed"
        assert alloc.reclaimed > 0


# --------------------------------------------------------------------- #
# Paged vs slot-major logit parity (fp32) — the PR-7 diff
# --------------------------------------------------------------------- #
class TestPagedParity:
    def test_block_table_decode_matches_slot_major(self, params32):
        paged = _engine(params32, paged=True, block_size=16)
        slot_major = _engine(params32, paged=False)
        prompt = _prompt(11, seed=8)
        tok_p, lg_p = paged.prefill(prompt, slot=0, return_logits=True)
        tok_s, lg_s = slot_major.prefill(prompt, slot=0,
                                         return_logits=True)
        np.testing.assert_allclose(lg_p, lg_s, atol=1e-4)
        assert tok_p == tok_s
        paged.activate_slot(0, len(prompt), tok_p)
        slot_major.activate_slot(0, len(prompt), tok_s)
        seq = list(prompt) + [tok_p]
        for _ in range(6):
            sp, lp = paged.decode_once(return_logits=True)
            ss, ls = slot_major.decode_once(return_logits=True)
            np.testing.assert_allclose(lp[0], ls[0], atol=1e-4)
            ref = np.asarray(gpt2_apply(
                params32, jnp.asarray(np.asarray(seq, np.int32))[None],
                CFG32))[0, -1]
            np.testing.assert_allclose(lp[0], ref, atol=1e-4)
            assert int(sp[0]) == int(ss[0])
            seq.append(int(sp[0]))
        paged.close()
        slot_major.close()

    def test_cow_fork_isolates_divergent_decode(self, params32):
        """The copy-on-write fork: two identical prompts share all full
        blocks; the forked slot's decode appends must not leak into the
        original's attention."""
        eng = _engine(params32, slots=16, block_size=8)
        prompt = _prompt(16, seed=9)                # exactly 2 blocks
        tok_a, lg_a = eng.prefill(prompt, slot=0, return_logits=True)
        eng.activate_slot(0, len(prompt), tok_a)
        tok_b, lg_b = eng.prefill(prompt, slot=1, return_logits=True)
        eng.activate_slot(1, len(prompt), tok_b)
        assert eng.allocator.cow_copies == 1
        assert eng.block_tables[0][0] == eng.block_tables[1][0]
        assert eng.block_tables[0][1] != eng.block_tables[1][1]
        np.testing.assert_allclose(lg_a, lg_b, atol=1e-5)
        # Force divergence: feed slot 1 a DIFFERENT pending token (the
        # first divergent token goes through the forked private block).
        eng.last_tokens[1] = (tok_b + 1) % CFG32.vocab_size
        seq_a = list(prompt) + [tok_a]
        seq_b = list(prompt) + [int(eng.last_tokens[1])]
        for _ in range(5):
            sampled, lg = eng.decode_once(return_logits=True)
            for slot, seq in ((0, seq_a), (1, seq_b)):
                ref = np.asarray(gpt2_apply(
                    params32,
                    jnp.asarray(np.asarray(seq, np.int32))[None],
                    CFG32))[0, -1]
                np.testing.assert_allclose(lg[slot], ref, atol=1e-4)
                seq.append(int(sampled[slot]))
        assert seq_a[len(prompt) + 1:] != seq_b[len(prompt) + 1:] or \
            seq_a != seq_b
        eng.close()

    def test_prefill_many_matches_sequential(self, params32):
        """Batched one-slot-per-group admission == one-at-a-time."""
        batched = _engine(params32, slots=8, block_size=16)
        seq = _engine(params32, slots=8, block_size=16)
        prompts = [_prompt(7 + i, seed=20 + i) for i in range(4)]
        # Slots 0..3 live in distinct groups (slots_per_group == 1).
        results = batched.prefill_many(
            [(i, p, 4) for i, p in enumerate(prompts)],
            return_logits=True)
        for i, p in enumerate(prompts):
            tok, lg = seq.prefill(p, slot=i, return_logits=True)
            assert results[i][0] == tok
            np.testing.assert_allclose(results[i][1], lg, atol=1e-5)
        batched.close()
        seq.close()

    def test_whole_prompt_prefill_paged(self, params32):
        eng = _engine(params32, max_len=32, chunk=0, block_size=16)
        prompt = _prompt(9, seed=10)
        tok, logits = eng.prefill(prompt, slot=2, return_logits=True)
        ref = np.asarray(gpt2_apply(
            params32, jnp.asarray(prompt)[None], CFG32))[0, -1]
        np.testing.assert_allclose(logits, ref, atol=1e-4)
        eng.activate_slot(2, len(prompt), tok)
        sampled, lg = eng.decode_once(return_logits=True)
        ref2 = np.asarray(gpt2_apply(
            params32, jnp.asarray(np.asarray(list(prompt) + [tok],
                                             np.int32))[None],
            CFG32))[0, -1]
        np.testing.assert_allclose(lg[2], ref2, atol=1e-4)
        eng.close()


# --------------------------------------------------------------------- #
# Pool exhaustion through the scheduler: reject, queue, recover
# --------------------------------------------------------------------- #
class TestAdmissionGate:
    def test_exhaustion_queues_and_recovers(self, params):
        """A pool sized for ~2 concurrent requests serves 4: the third
        admission is REJECTED while two run (free-block accounting),
        then admitted once a slot evicts and returns its blocks. Every
        request completes; zero recompiles."""
        eng = _engine(params, cfg=CFG, slots=16, max_len=64, chunk=8,
                      block_size=8, num_blocks=16,
                      telemetry={"enabled": True,
                                 "output_path": "/tmp/_paged_gate",
                                 "job_name": "gate",
                                 "report_steps": 10 ** 6,
                                 "fail_on_recompile": True})
        # 16 blocks over 8 groups = 2/group; slots_per_group = 2. Each
        # request needs ceil((12 + 4)/8) = 2 blocks -> one per group at
        # a time; 16 slots but HBM for only 8 concurrent requests.
        reqs = synthetic_requests(12, prompt_len=(10, 12),
                                  max_new_tokens=4,
                                  vocab_size=CFG.vocab_size, seed=11)
        report = eng.serve(reqs)
        assert report["completed"] == 12 and report["unfinished"] == 0
        assert report["recompiles"] == 0
        assert not eng.active.any()
        assert eng.allocator.blocks_in_use() == 0
        eng.close()

    def test_never_admittable_raises_instead_of_hanging(self, params):
        eng = _engine(params, cfg=CFG, slots=8, max_len=64, chunk=8,
                      block_size=8, num_blocks=8)   # 1 block/group
        reqs = synthetic_requests(1, prompt_len=(20, 20),
                                  max_new_tokens=8,
                                  vocab_size=CFG.vocab_size, seed=12)
        with pytest.raises(RuntimeError, match="never be admitted"):
            eng.serve(reqs)
        eng.close()

    def test_select_slot_prefers_prefix_affinity_group(self, params32):
        eng = _engine(params32, slots=16, block_size=8)
        prompt = _prompt(17, seed=13)
        tok, _ = eng.prefill(prompt, slot=5, max_new_tokens=4,
                             return_logits=False)
        eng.activate_slot(5, len(prompt), tok)
        # Slot 5 lives in group 2 (slots_per_group=2); a same-prefix
        # admission must land there.
        slot = eng.select_slot(prompt, max_new_tokens=4)
        assert slot is not None and eng.group_of(slot) == \
            eng.group_of(5)
        assert eng.prefix_match_tokens(prompt) == 16
        eng.close()


# --------------------------------------------------------------------- #
# Speculative decoding
# --------------------------------------------------------------------- #
class TestSpeculativeDecoding:
    def test_drafter_proposes_continuation_of_repeats(self):
        d = NGramDrafter(k=3, ngram=2)
        d.begin(0, [1, 2, 3, 9, 1, 2])
        assert d.propose(0).tolist() == [3, 9, 1]
        d2 = NGramDrafter(k=2, ngram=3)
        d2.begin(1, [5])
        assert d2.propose(1).tolist() == [5, 5], "repeat-last fallback"
        assert d.match_rate() == 1.0 and d2.match_rate() == 0.0

    def test_greedy_streams_bit_identical(self, params):
        """THE spec-decode acceptance gate: same checkpoint, same
        stream, spec_k 0 vs 4 — token streams must be exactly equal,
        and the spec run must do it in fewer iterations."""
        def run(spec_k):
            eng = _engine(params, cfg=CFG, spec_k=spec_k)
            reqs = synthetic_requests(16, prompt_len=(5, 14),
                                      max_new_tokens=12,
                                      vocab_size=CFG.vocab_size, seed=2)
            rep = eng.serve(reqs)
            snap = eng.serving.snapshot()
            eng.close()
            return rep, snap

        rep0, _ = run(0)
        rep4, snap4 = run(4)
        s0 = {r["rid"]: r["tokens"] for r in rep0["requests"]}
        s4 = {r["rid"]: r["tokens"] for r in rep4["requests"]}
        assert s0 == s4, "speculative greedy diverged from baseline"
        assert rep4["iterations"] < rep0["iterations"]
        assert rep0["recompiles"] == 0 and rep4["recompiles"] == 0
        spec = snap4["spec"]
        assert spec["proposed"] > 0
        assert 0.0 <= spec["acceptance_rate"] <= 1.0

    def test_verify_near_slot_capacity_caps_cleanly(self, params):
        """Speculation at the slot boundary: accepted tokens past
        max_len are dropped, lengths never exceed capacity, and the
        stream still matches baseline."""
        def run(spec_k):
            eng = _engine(params, cfg=CFG, max_len=32, spec_k=spec_k,
                          block_size=16)
            reqs = synthetic_requests(4, prompt_len=(24, 26),
                                      max_new_tokens=16,
                                      vocab_size=CFG.vocab_size,
                                      seed=14)
            rep = eng.serve(reqs)
            assert (eng.lengths == 0).all()
            eng.close()
            return {r["rid"]: r["tokens"] for r in rep["requests"]}

        assert run(0) == run(4)

    def test_spec_requires_paged(self, params32):
        from deepspeed_tpu.runtime.config import DeepSpeedConfigError
        with pytest.raises(DeepSpeedConfigError, match="paged"):
            _engine(params32, paged=False, spec_k=4)

    def test_temperature_falls_back_to_plain_decode(self, params):
        eng = _engine(params, cfg=CFG, spec_k=4)
        with pytest.raises(ValueError, match="greedy-only"):
            eng.spec_decode_once(temperature=0.7)
        reqs = synthetic_requests(4, prompt_len=(5, 8),
                                  max_new_tokens=4,
                                  vocab_size=CFG.vocab_size, seed=15)
        rep = eng.serve(reqs, temperature=1.0)
        assert rep["completed"] == 4
        assert "spec" not in eng.serving.snapshot(), \
            "sampling stream must not use the greedy acceptance rule"
        eng.close()


# --------------------------------------------------------------------- #
# Multi-replica router
# --------------------------------------------------------------------- #
class TestReplicaRouter:
    def test_two_replicas_balance_and_stay_labeled(self, params):
        engines = [InferenceEngine(CFG, params, config={
            "inference": {"max_slots": 8, "max_seq_len": 64,
                          "prefill_chunk": 8, "spec_k": 4,
                          "replica": f"r{i}"}}) for i in range(2)]
        reqs = shared_prefix_requests(20, prefix_len=32,
                                      tail_len=(4, 10),
                                      max_new_tokens=8,
                                      vocab_size=CFG.vocab_size, seed=3)
        rep = ReplicaRouter(engines, temperature=0.0).serve(reqs)
        assert rep["completed"] == 20 and rep["unfinished"] == 0
        assert rep["recompiles"] == 0
        assert sorted(r["replica"] for r in rep["replicas"]) == \
            ["r0", "r1"]
        assert sum(rep["router"]["routed"]) == 20
        assert min(rep["router"]["routed"]) > 0, "load balanced"
        # Every request names its replica; aggregate pools them.
        assert {r["replica"] for r in rep["requests"]} == {0, 1}
        assert rep["ttft_ms"]["n"] == 20
        assert rep["prefix"]["hit_rate"] > 0, "shared prefixes hit"
        for e in engines:
            e.close()

    def test_affinity_routes_to_prefix_holder(self, params32):
        engines = [_engine(params32, slots=8, block_size=8)
                   for _ in range(2)]
        prompt = _prompt(24, seed=16)
        tok, _ = engines[1].prefill(prompt, slot=0, return_logits=False)
        engines[1].activate_slot(0, len(prompt), tok)
        router = ReplicaRouter(engines, affinity_weight=1.0)
        from deepspeed_tpu.inference import Request
        from collections import deque
        req = Request(rid=0, prompt=prompt, max_new_tokens=4)
        assert router.route(req, [deque(), deque()]) == 1
        for e in engines:
            e.close()

    def test_router_never_admittable_raises_instead_of_hanging(
            self, params32):
        engines = [_engine(params32, slots=8, max_len=64, block_size=8,
                           num_blocks=8) for _ in range(2)]
        from deepspeed_tpu.inference import Request
        reqs = [Request(rid=0, prompt=_prompt(20, seed=30),
                        max_new_tokens=8)]
        with pytest.raises(RuntimeError, match="never be admitted"):
            ReplicaRouter(engines).serve(reqs)
        for e in engines:
            e.close()

    def test_aggregator_merged_pools_raw_samples(self):
        a = ServingAggregator(8, label="r0")
        b = ServingAggregator(8, label="r1")
        for ms in (10, 20, 30):
            a.note_request(ms / 1e3, None, 4)
        for ms in (100, 200, 300):
            b.note_request(ms / 1e3, None, 4)
        a.note_iteration(8, 0.01, cache_bytes=1000, context_tokens=10)
        b.note_iteration(4, 0.01, cache_bytes=3000, context_tokens=10)
        m = ServingAggregator.merged([a, b])
        snap = m.snapshot(wall_s=1.0)
        assert snap["replica"] == "aggregate"
        assert snap["completed"] == 6
        assert snap["ttft_ms"]["n"] == 6
        # Pooled median sits between the two replicas' medians.
        assert 20 <= snap["ttft_ms"]["p50"] <= 200
        assert snap["occupancy_mean"] == pytest.approx(0.75)
        assert snap["hbm_bytes_per_token"]["n"] == 2
        assert a.snapshot()["replica"] == "r0"


# --------------------------------------------------------------------- #
# Workloads and config knobs
# --------------------------------------------------------------------- #
class TestWorkloadsAndConfig:
    def test_shared_prefix_requests_share_exactly_the_prefix(self):
        reqs = shared_prefix_requests(6, prefix_len=16, tail_len=(2, 5),
                                      seed=4)
        p0 = reqs[0].prompt[:16]
        for r in reqs:
            assert (r.prompt[:16] == p0).all()
            assert 18 <= len(r.prompt) <= 21
        again = shared_prefix_requests(6, prefix_len=16,
                                       tail_len=(2, 5), seed=4)
        assert all((a.prompt == b.prompt).all()
                   for a, b in zip(reqs, again))

    def test_new_inference_knobs_validate(self):
        from deepspeed_tpu.runtime.config import (DeepSpeedConfigError,
                                                  InferenceConfig)
        inf = InferenceConfig(None)
        assert inf.block_size == 16 and inf.num_blocks == 0
        assert inf.spec_k == 0 and inf.kv_cache_dtype == "model"
        for bad in ({"block_size": -1}, {"spec_k": -2},
                    {"spec_k": 2, "block_size": 0},
                    {"kv_cache_dtype": "fp8"}, {"replica": 3},
                    {"num_blocks": -4}, {"spec_ngram": 0}):
            with pytest.raises(DeepSpeedConfigError):
                InferenceConfig({"inference": bad})

    def test_engine_geometry_validation(self, params32):
        with pytest.raises(ValueError, match="block_size"):
            _engine(params32, max_len=40, block_size=16)
        with pytest.raises(ValueError, match="divisible"):
            _engine(params32, block_size=16, num_blocks=12)

    def test_bf16_kv_pool_serves(self, params32):
        eng = InferenceEngine(CFG32, params32, config={
            "inference": {"max_slots": 8, "max_seq_len": 32,
                          "prefill_chunk": 8,
                          "kv_cache_dtype": "bf16"}})
        assert eng.cache["k"].dtype == jnp.bfloat16
        prompt = _prompt(9, seed=17)
        tok, logits = eng.prefill(prompt, slot=0, return_logits=True)
        ref = np.asarray(gpt2_apply(
            params32, jnp.asarray(prompt)[None], CFG32))[0, -1]
        assert np.isfinite(logits).all()
        assert np.corrcoef(logits, ref)[0, 1] > 0.999
        eng.close()


# --------------------------------------------------------------------- #
# The paged serving stream under the sentinel + lint (tier-1 gate)
# --------------------------------------------------------------------- #
class TestPagedServingStream:
    def test_shared_prefix_stream_zero_recompiles_and_lint_clean(
            self, tmp_path, params):
        eng = InferenceEngine(CFG, params, config={
            "inference": {"max_slots": 8, "max_seq_len": 64,
                          "prefill_chunk": 8, "block_size": 8,
                          "spec_k": 3},
            "telemetry": {"enabled": True, "output_path": str(tmp_path),
                          "job_name": "paged_serve",
                          "report_steps": 10 ** 6,
                          "fail_on_recompile": True}})
        # Deterministic copy-on-write exercise first: admit a 4-full-
        # block prompt, evict (blocks LRU-retained), re-admit the SAME
        # prompt — the exact-chain match forks its last block, so the
        # copy_block path compiles and registers with the sentinel.
        p32 = _prompt(32, seed=50, vocab=CFG.vocab_size)
        tok, _ = eng.prefill(p32, slot=0)
        eng.activate_slot(0, 32, tok)
        eng.release_slot(0)
        tok, _ = eng.prefill(p32, slot=0)
        eng.activate_slot(0, 32, tok)
        eng.release_slot(0)
        assert eng.allocator.cow_copies == 1
        reqs = shared_prefix_requests(16, prefix_len=24,
                                      tail_len=(3, 9),
                                      max_new_tokens=6,
                                      vocab_size=CFG.vocab_size, seed=5)
        report = eng.serve(reqs)
        assert report["completed"] == 16 and report["unfinished"] == 0
        assert report["recompiles"] == 0
        assert eng.telemetry.recompile_count == 0
        snap = eng.serving.snapshot()
        assert snap["prefix"]["hit_rate"] > 0
        assert snap["hbm_bytes_per_token"]["n"] > 0
        assert snap["spec"]["proposed"] > 0
        # Every compiled path this serve used registered (a spec-k
        # engine decodes THROUGH the verify step, so plain decode_step
        # never compiles); host_sync + materialization CLEAN — no
        # full-pool gather, no in-step host transfer, even through the
        # verify and CoW-copy paths.
        lint = eng.lint_audit(passes=("host_sync", "materialization"))
        assert {p.name for p in lint.paths} == \
            {"prefill_step", "verify_step", "copy_block"}
        assert not lint.unwaived and \
            not any(p.errors for p in lint.paths)
        eng.close()
