"""The examples/ scripts executed end-to-end — the reference's model-test
tier drives its example trainers as whole programs
(tests/model/run_func_test.py invokes the Megatron/BingBert scripts);
here each example runs as a real subprocess on the virtual CPU mesh and
must train to a finite, decreasing loss.

Kept honest by parsing the script's own stdout contract ("final loss:"),
not by importing its internals.
"""
import os
import re
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # whole-module slow tier (see conftest)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_example(rel, *args, timeout=420):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = flags + \
            " --xla_force_host_platform_device_count=8"
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, rel), *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)
    assert p.returncode == 0, f"{rel} failed:\n{p.stdout}\n{p.stderr}"
    m = re.search(r"final (?:MLM )?loss:\s*([0-9.]+)", p.stdout)
    assert m, f"{rel} printed no final loss:\n{p.stdout[-2000:]}"
    return float(m.group(1))


def test_cifar_example_runs_and_learns():
    loss = run_example("examples/cifar/train.py", "--steps", "60")
    assert loss < 2.3, loss            # below the ln(10) random floor


def test_bert_example_runs():
    loss = run_example("examples/bert/train.py", "--steps", "12")
    assert loss > 0.0                  # finite, parsed from the script


def test_gpt2_example_zero2():
    loss = run_example("examples/gpt2/train.py",
                       "--config", "ds_config_zero2.json", "--steps", "12")
    assert loss > 0.0


def test_gpt2_example_onebit():
    loss = run_example("examples/gpt2/train.py",
                       "--config", "ds_config_onebit.json", "--steps", "12")
    assert loss > 0.0


def test_gpt2_example_pipeline_1f1b():
    loss = run_example("examples/gpt2/train.py",
                       "--config", "ds_config_pipeline.json",
                       "--pipeline", "--steps", "8")
    assert loss > 0.0
