"""The examples/ scripts executed end-to-end — the reference's model-test
tier drives its example trainers as whole programs
(tests/model/run_func_test.py invokes the Megatron/BingBert scripts);
here each example runs as a real subprocess on the virtual CPU mesh and
must train to a finite, decreasing loss.

Kept honest by parsing the scripts' own stdout contract ("losses: ..." +
"final loss:"), not by importing their internals. Every example must not
just run — the first-quarter vs last-quarter window means of its printed
loss curve must DECREASE (the module's "finite, decreasing loss" claim;
the reference's func tests compare full loss curves).

The gpt2 flagship configs (ZeRO-2, ZeRO-Offload, 1-bit Adam, 1F1B
pipeline) train on REAL text — byte-level LM over the vendored
license-clean corpus (examples/data/corpus.txt, see its README) — with
loss-curve gates, closing VERDICT.md's top gap (every e2e example used
to train on synthetic random tokens). A byte-level model starts at the
ln(256) ~= 5.5 uniform floor and must cut into genuine English
statistics to pass.
"""
import os
import re
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # whole-module slow tier (see conftest)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS = os.path.join("examples", "data", "corpus.txt")


def run_example(rel, *args, timeout=420):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = flags + \
            " --xla_force_host_platform_device_count=8"
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, rel), *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)
    assert p.returncode == 0, f"{rel} failed:\n{p.stdout}\n{p.stderr}"
    m = re.search(r"final (?:MLM )?loss:\s*([0-9.]+)", p.stdout)
    assert m, f"{rel} printed no final loss:\n{p.stdout[-2000:]}"
    c = re.search(r"losses:\s*([0-9. eE+-]+)", p.stdout)
    assert c, f"{rel} printed no loss curve:\n{p.stdout[-2000:]}"
    return float(m.group(1)), [float(x) for x in c.group(1).split()]


def assert_decreasing(losses, factor=0.97):
    """First-k vs last-k window means must drop by at least (1-factor):
    per-step curves are noisy, window means are the honest signal."""
    k = max(1, len(losses) // 4)
    first, last = np.mean(losses[:k]), np.mean(losses[-k:])
    assert last < factor * first, (first, last, losses)


def test_cifar_example_runs_and_learns():
    loss, curve = run_example("examples/cifar/train.py", "--steps", "60")
    assert loss < 2.3, loss            # below the ln(10) random floor
    assert_decreasing(curve)


def test_bert_example_learns():
    _, curve = run_example("examples/bert/train.py", "--steps", "48")
    assert_decreasing(curve)


def test_gpt2_example_zero2_real_text():
    loss, curve = run_example("examples/gpt2/train.py",
                              "--config", "ds_config_zero2.json",
                              "--data", CORPUS, "--steps", "24")
    assert curve[0] < 7.0                 # near the ln(256)~5.5 start
    assert loss < 5.0, loss               # well under the uniform floor
    assert_decreasing(curve, factor=0.85)


def test_gpt2_example_offload_real_text():
    loss, curve = run_example("examples/gpt2/train.py",
                              "--config", "ds_config_offload.json",
                              "--data", CORPUS, "--steps", "24")
    assert loss < 5.0, loss
    assert_decreasing(curve, factor=0.85)


def test_gpt2_example_onebit_real_text():
    loss, curve = run_example("examples/gpt2/train.py",
                              "--config", "ds_config_onebit.json",
                              "--data", CORPUS, "--steps", "48")
    assert loss < 5.0, loss
    assert_decreasing(curve, factor=0.85)


def test_gpt2_example_pipeline_1f1b_real_text():
    from capability import partial_auto_skip_reason
    reason = partial_auto_skip_reason()
    if reason:
        # pp=2 x dp=4 lowers to a partially-manual shard_map this jax
        # cannot compile — the same capability gate the pipe tier uses.
        pytest.skip(reason)
    loss, curve = run_example("examples/gpt2/train.py",
                              "--config", "ds_config_pipeline.json",
                              "--pipeline", "--data", CORPUS,
                              "--steps", "24")
    assert loss < 5.0, loss
    assert_decreasing(curve, factor=0.85)
