"""End-to-end convergence: a real model learns a real task through the
full engine stack — the reference's model-test tier (tests/model/
run_func_test.py: train runs compared by loss curve across configs)
re-done TPU-style on the virtual CPU mesh.

Task: copy language modeling. Each sequence is ``prefix | SEP | prefix``;
predicting the second half requires content-based attention (induction),
so loss well below the random-prefix floor proves the transformer stack,
engine step, optimizer, and ZeRO sharding actually learn — not just that
loss is finite. The second-half token loss of a trained model approaches
0; an untrained model sits at ln(V) ≈ 3.9.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPT2_CONFIGS
from deepspeed_tpu.models.gpt2 import gpt2_init, gpt2_loss_fn
from deepspeed_tpu.parallel.topology import build_mesh

VOCAB = 64          # tokens 0..61 data, 62 = SEP
SEP = VOCAB - 2
HALF = 16
S = 2 * HALF + 1    # prefix HALF | SEP | copy HALF


def copy_batches(n_batches: int, batch: int, seed: int):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        prefix = rng.integers(0, SEP, size=(batch, HALF), dtype=np.int32)
        sep = np.full((batch, 1), SEP, np.int32)
        seq = np.concatenate([prefix, sep, prefix], axis=1)   # [B, S]
        # engine batches are [B, S+1]: inputs [:, :-1], targets [:, 1:]
        pad = np.full((batch, 1), SEP, np.int32)
        out.append(np.concatenate([seq, pad], axis=1))
    return out


def model_cfg():
    return dataclasses.replace(
        GPT2_CONFIGS["gpt2-tiny"], vocab_size=VOCAB, max_seq_length=S,
        hidden_size=128, num_heads=4, num_layers=2,
        hidden_dropout=0.0, attn_dropout=0.0, dtype=jnp.float32)


def second_half_loss(engine, cfg, batch):
    """Mean NLL on the copy half only — the capability metric."""
    from deepspeed_tpu.models.gpt2 import gpt2_apply
    params = jax.device_get(engine.state.params)
    if "shared" in params and "blocks" in params:   # pipeline layout
        params = {**params["shared"], "blocks": params["blocks"]}
    params = jax.tree_util.tree_map(jnp.asarray, params)
    tokens, targets = batch[:, :-1], batch[:, 1:]
    logits = gpt2_apply(params, jnp.asarray(tokens), cfg)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, jnp.asarray(targets)[..., None],
                               axis=-1)[..., 0]
    return float(jnp.mean(nll[:, HALF + 1:]))   # tokens after SEP


def train(ds_config, steps, seed=0, dp=2):
    cfg = model_cfg()
    mesh = build_mesh(devices=jax.devices()[:dp])
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt2_loss_fn(cfg),
        model_params=gpt2_init(jax.random.PRNGKey(seed), cfg),
        config=ds_config, mesh=mesh)
    batches = copy_batches(steps, ds_config["train_batch_size"], seed=seed)
    losses = []
    for b in batches:
        losses.append(float(engine.train_batch(jnp.asarray(b))))
    return engine, cfg, losses, batches[0]


def zero2_config(lr=3e-3):
    return {
        "train_batch_size": 32,
        "train_micro_batch_size_per_gpu": 16,
        "gradient_accumulation_steps": 1,
        "zero_optimization": {"stage": 2},
        "gradient_clipping": 1.0,
        "optimizer": {"type": "AdamW", "params": {"lr": lr}},
        "steps_per_print": 10 ** 9,
    }


def train_pipe(ds_config, steps, seed=0, pp=2, dp=2):
    """Same workload through the compiled SPMD pipeline (PipeSpec)."""
    from deepspeed_tpu.models.gpt2_pipe import gpt2_pipe_spec
    cfg = model_cfg()
    mesh = build_mesh(pp=pp, dp=dp, devices=jax.devices()[:pp * dp])
    spec = gpt2_pipe_spec(cfg, rng=jax.random.PRNGKey(seed))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=spec, config=ds_config, mesh=mesh)
    batches = copy_batches(steps, ds_config["train_batch_size"], seed=seed)
    losses = [float(engine.train_batch(jnp.asarray(b))) for b in batches]
    return engine, cfg, losses, batches[0]


def pipe_config(schedule, lr=3e-3):
    # pp=2 x dp=2, M=4 micro-batches, ZeRO-1: the flagship 1F1B combo.
    return {
        "train_batch_size": 32,
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 4,
        "zero_optimization": {"stage": 1},
        "gradient_clipping": 1.0,
        "optimizer": {"type": "AdamW", "params": {"lr": lr}},
        "pipeline": {"schedule": schedule},
        "steps_per_print": 10 ** 9,
    }


@pytest.mark.slow
def test_gpt2_learns_copy_task_1f1b_pipeline():
    """The 1F1B interleaved pipeline (x ZeRO-1, pp=2 x dp=2) LEARNS the
    copy task end-to-end — the reference's TrainSchedule is its default
    train path (runtime/pipe/schedule.py:182-290); this is the TPU
    equivalent proven at the capability level, not just grad parity."""
    engine, cfg, losses, probe = train_pipe(pipe_config("1f1b"), steps=220)
    assert losses[-1] < 2.6, f"final LM loss {losses[-1]} did not converge"
    copy_nll = second_half_loss(engine, cfg, probe)
    assert copy_nll < 0.9, f"copy-half NLL {copy_nll}: induction not learned"


@pytest.mark.slow
def test_convergence_1f1b_matches_gpipe_curve():
    """Two schedules, one pipeline: identical loss curves (dropout off)."""
    _, _, l_1f1b, _ = train_pipe(pipe_config("1f1b"), steps=50)
    _, _, l_gpipe, _ = train_pipe(pipe_config("gpipe"), steps=50)
    np.testing.assert_allclose(l_1f1b, l_gpipe, rtol=0.05, atol=0.05)
    assert l_1f1b[-1] < l_1f1b[0] - 0.3


@pytest.mark.slow
def test_gpt2_learns_copy_task_zero2():
    engine, cfg, losses, probe = train(zero2_config(), steps=220)
    # Loss must fall decisively from the ~ln(64)=4.16 floor...
    assert losses[-1] < 2.6, f"final LM loss {losses[-1]} did not converge"
    # ...and the copy half specifically must be LEARNED (random = 3.9+).
    copy_nll = second_half_loss(engine, cfg, probe)
    assert copy_nll < 0.9, f"copy-half NLL {copy_nll}: induction not learned"


@pytest.mark.slow
def test_convergence_parity_across_configs():
    """The reference's run_func_test pattern: the same workload under
    different engine configs produces matching loss curves."""
    base = zero2_config()
    zero0 = dict(base, zero_optimization={"stage": 0})
    _, _, l_base, _ = train(base, steps=60)
    _, _, l_zero0, _ = train(zero0, steps=60)
    np.testing.assert_allclose(l_base, l_zero0, rtol=0.05, atol=0.05)
    assert l_base[-1] < l_base[0] - 0.3


@pytest.mark.slow
def test_gpt2_learns_copy_task_onebit_adam():
    """1-bit Adam completes the convergence matrix: warmup (plain Adam)
    then error-feedback sign-compressed momentum steps must still learn
    the copy task (reference tests/onebit/test_com_reduce_host.py only
    checks the collective; this is the capability-level claim)."""
    cfg = {
        "train_batch_size": 32,
        "train_micro_batch_size_per_gpu": 16,
        "gradient_accumulation_steps": 1,
        "gradient_clipping": 1.0,
        "optimizer": {"type": "OneBitAdam",
                      "params": {"lr": 3e-3, "freeze_step": 60}},
        "steps_per_print": 10 ** 9,
    }
    engine, mcfg, losses, probe = train(cfg, steps=220)
    assert losses[-1] < 2.6, f"final LM loss {losses[-1]} did not converge"
    copy_nll = second_half_loss(engine, mcfg, probe)
    assert copy_nll < 0.9, f"copy-half NLL {copy_nll}: induction not learned"


@pytest.mark.slow
def test_convergence_offload_matches_device():
    """ZeRO-Offload host optimizer follows the in-graph optimizer's curve
    on the same data (fp32 host masters vs fp32 device params)."""
    base = zero2_config()
    off = dict(base, train_batch_size=16, train_micro_batch_size_per_gpu=16,
               zero_optimization={"stage": 2, "cpu_offload": True})
    dev = dict(base, train_batch_size=16, train_micro_batch_size_per_gpu=16,
               zero_optimization={"stage": 2})
    _, _, l_off, _ = train(off, steps=40, dp=1)
    _, _, l_dev, _ = train(dev, steps=40, dp=1)
    np.testing.assert_allclose(l_off, l_dev, rtol=0.08, atol=0.08)
