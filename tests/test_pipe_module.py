"""PipelineModule tests — parity with reference tests/unit/test_pipe_module.py
(partitioning) plus tied-layer weight sharing."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.pipe.module import (PipelineModule, LayerSpec,
                                               TiedLayerSpec)

from capability import (PARTIAL_AUTO_SKIP_REASON,
                        partial_auto_shard_map_supported)


class Dense:
    """Minimal flax-style layer for tests."""

    def __init__(self, din, dout):
        self.din, self.dout = din, dout

    def init(self, rng, x):
        return {"w": jax.random.normal(rng, (self.din, self.dout)) * 0.1}

    def apply(self, p, x, rngs=None):
        return jnp.tanh(x @ p["w"])

    def param_count(self):
        return self.din * self.dout


class TestPartitioning:
    def test_uniform(self):
        m = PipelineModule([LayerSpec(Dense, 4, 4) for _ in range(8)],
                           num_stages=4, partition_method="uniform")
        assert m.parts == [0, 2, 4, 6, 8]

    def test_parameters_balanced(self):
        # One huge layer + small ones: huge layer gets its own stage.
        specs = [LayerSpec(Dense, 64, 64)] + [LayerSpec(Dense, 4, 4)] * 7
        m = PipelineModule(specs, num_stages=2, partition_method="parameters")
        assert m.parts[1] == 1  # stage 0 holds only the big layer

    def test_type_regex(self):
        m = PipelineModule([LayerSpec(Dense, 4, 4) for _ in range(4)],
                           num_stages=2, partition_method="type:dense")
        assert m.parts[0] == 0 and m.parts[-1] == 4

    def test_stage_owner(self):
        m = PipelineModule([LayerSpec(Dense, 4, 4) for _ in range(8)],
                           num_stages=4, partition_method="uniform")
        assert m.stage_owner(0) == 0
        assert m.stage_owner(7) == 3

    def test_unknown_method(self):
        with pytest.raises(KeyError):
            PipelineModule([LayerSpec(Dense, 4, 4)], num_stages=1,
                           partition_method="bogus")


class TestTiedLayers:
    def test_tied_params_shared(self):
        def unembed_fwd(layer, p, x):
            return x @ p["w"].T

        specs = [
            TiedLayerSpec("embed", Dense, 4, 8),
            LayerSpec(Dense, 8, 8),
            TiedLayerSpec("embed", Dense, 4, 8, forward_fn=unembed_fwd),
        ]
        m = PipelineModule(specs, num_stages=1,
                           loss_fn=lambda logits, y: jnp.mean(logits ** 2))
        assert m.tied_specs == {"embed": [0, 2]}
        assert m.param_key(0) == m.param_key(2) == "tied_embed"
        assert m.param_key(1) == "layer_1"

    def test_tied_training_single_param_set(self):
        def unembed_fwd(layer, p, x):
            return x @ p["w"].T

        def loss_head(logits, y):
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            return -jnp.mean(jnp.sum(jax.nn.one_hot(y, 4) * logp, -1))

        specs = [
            TiedLayerSpec("embed", Dense, 4, 8),
            TiedLayerSpec("embed", Dense, 4, 8, forward_fn=unembed_fwd),
        ]
        from deepspeed_tpu.runtime.dataloader import ArrayDataset
        rng = np.random.default_rng(0)
        x = rng.normal(size=(32, 4)).astype(np.float32)
        y = (x.sum(1) > 0).astype(np.int32)
        ds = ArrayDataset(x, y)

        model = PipelineModule(specs, num_stages=1, loss_fn=loss_head)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, config={"train_batch_size": 16,
                                 "optimizer": {"type": "Adam",
                                               "params": {"lr": 1e-2}}},
            training_data=ds)
        # exactly one param set for the tied pair
        assert set(jax.device_get(engine.state.params).keys()) == {"tied_embed"}
        losses = [float(engine.train_batch()) for _ in range(10)]
        assert losses[-1] < losses[0]


class TestPipelineEngineSingleStage:
    def test_trains(self):
        def loss_head(logits, y):
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            return -jnp.mean(jnp.sum(jax.nn.one_hot(y, 2) * logp, -1))

        specs = [LayerSpec(Dense, 8, 16), LayerSpec(Dense, 16, 2)]
        from deepspeed_tpu.runtime.dataloader import ArrayDataset
        rng = np.random.default_rng(1)
        x = rng.normal(size=(64, 8)).astype(np.float32)
        y = (x.sum(1) > 0).astype(np.int32)
        model = PipelineModule(specs, num_stages=2, loss_fn=loss_head)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, config={"train_batch_size": 16,
                                 "optimizer": {"type": "Adam",
                                               "params": {"lr": 1e-2}}},
            training_data=ArrayDataset(x, y))
        losses = [float(engine.train_batch()) for _ in range(10)]
        assert losses[-1] < losses[0]


class TestToPipeSpec:
    @pytest.mark.skipif(not partial_auto_shard_map_supported(),
                        reason=PARTIAL_AUTO_SKIP_REASON)
    def test_uniform_module_runs_pp2(self):
        """to_pipe_spec: a uniform PipelineModule trains on a pp=2 mesh via
        the compiled SPMD pipeline and matches the pp=1 fused trajectory."""
        import numpy as np
        from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
        from deepspeed_tpu.parallel.topology import build_mesh

        def block(p, x):
            return x + jnp.tanh(x @ p["w"] + p["b"])

        L, D = 4, 8
        params = {
            f"layer_{i}": {
                "w": jax.random.normal(jax.random.PRNGKey(i), (D, D)) * 0.3,
                "b": jnp.zeros((D,))}
            for i in range(L)}

        def loss_head(x, labels):
            return jnp.mean((x.sum(-1) - labels) ** 2)

        module = PipelineModule([block] * L, num_stages=2,
                                loss_fn=loss_head,
                                partition_method="uniform")
        spec = module.to_pipe_spec(params)
        assert spec.num_layers == L

        cfg = {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 2,
               "gradient_accumulation_steps": 2,
               "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
               "steps_per_print": 10 ** 9}
        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 4, D)).astype(np.float32)
        y = x.sum(axis=(-1, -2))

        mesh_pp = build_mesh(pp=2, devices=jax.devices()[:4])   # pp2 x dp2
        eng = PipelineEngine(model=spec, config=cfg, mesh=mesh_pp)
        losses = [float(jax.device_get(eng.train_batch((x, y))))
                  for _ in range(5)]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    def test_nonuniform_module_rejected(self):
        def block_a(p, x):
            return x + x @ p["w"]

        def block_b(p, x):
            return x - x @ p["w"]

        module = PipelineModule([block_a, block_b], num_stages=2,
                                loss_fn=lambda x, y: jnp.mean(x),
                                partition_method="uniform")
        params = {f"layer_{i}": {"w": jnp.eye(4)} for i in range(2)}
        with pytest.raises(ValueError, match="uniform stages"):
            module.to_pipe_spec(params)


class TestProfilePartitioning:
    """partition_method='profile': XLA cost-model-driven cuts. The
    reference never implemented this (module.py:374-375 raises); here a
    FLOPs-skewed model must get non-uniform cuts that beat uniform."""

    @staticmethod
    def _skewed_layers():
        def make(width, seed):
            a = jax.random.normal(jax.random.PRNGKey(seed), (64, width)) * .1
            b = jax.random.normal(jax.random.PRNGKey(seed + 1),
                                  (width, 64)) * .1
            return lambda x: jnp.tanh(x @ a) @ b
        # Two heavy layers up front, six light ones behind.
        return [make(1024, 2 * i) for i in range(2)] + \
               [make(8, 100 + 2 * i) for i in range(6)]

    def test_requires_sample_input(self):
        with pytest.raises(ValueError):
            PipelineModule(self._skewed_layers(), num_stages=2,
                           partition_method="profile")

    def test_skewed_model_beats_uniform(self):
        layers = self._skewed_layers()
        x = jnp.ones((4, 64), jnp.float32)
        m = PipelineModule(layers, num_stages=2, partition_method="profile",
                           profile_input=x)
        mu = PipelineModule(layers, num_stages=2, partition_method="uniform")
        assert mu.parts == [0, 4, 8]
        # Profile must cut earlier than uniform: the two heavy layers
        # dominate, so stage 0 ends at or before layer 2.
        assert m.parts[1] <= 2, m.parts
        costs = m._profile_layer_costs(x)

        def stage_max(parts):
            return max(sum(costs[parts[s]:parts[s + 1]])
                       for s in range(len(parts) - 1))
        assert stage_max(m.parts) < stage_max(mu.parts)

    def test_profile_flax_layers(self):
        layers = [Dense(64, 64) for _ in range(4)]
        x = jnp.ones((4, 64), jnp.float32)
        m = PipelineModule(layers, num_stages=2, partition_method="profile",
                           profile_input=x)
        # Equal-cost layers: profile degrades to the uniform cut.
        assert m.parts == [0, 2, 4]
