"""Dataloader tests — parity with reference tests/unit/test_data.py,
plus the fetch-wait instrumentation the goodput ledger reads."""
import time

import numpy as np

from deepspeed_tpu.runtime.dataloader import (DeepSpeedDataLoader, RepeatingLoader,
                                              ArrayDataset, default_collate)


def make_ds(n=32, dim=4):
    x = np.arange(n * dim, dtype=np.float32).reshape(n, dim)
    y = np.arange(n, dtype=np.int32)
    return ArrayDataset(x, y)


class TestDeepSpeedDataLoader:
    def test_batching(self):
        dl = DeepSpeedDataLoader(make_ds(32), batch_size=8,
                                 data_parallel_world_size=1, data_parallel_rank=0)
        batches = list(dl)
        assert len(batches) == 4 == len(dl)
        xb, yb = batches[0]
        assert xb.shape == (8, 4) and yb.shape == (8,)

    def test_sharding_disjoint(self):
        seen = []
        for rank in range(4):
            dl = DeepSpeedDataLoader(make_ds(32), batch_size=4,
                                     data_parallel_world_size=4,
                                     data_parallel_rank=rank)
            for _, yb in dl:
                seen.extend(yb.tolist())
        assert sorted(seen) == list(range(32))

    def test_shuffle_reproducible_across_ranks(self):
        # Same epoch+seed ⇒ same permutation ⇒ shards stay disjoint.
        all_ids = []
        for rank in range(2):
            dl = DeepSpeedDataLoader(make_ds(16), batch_size=8, shuffle=True,
                                     seed=3, data_parallel_world_size=2,
                                     data_parallel_rank=rank)
            for _, yb in dl:
                all_ids.extend(yb.tolist())
        assert sorted(all_ids) == list(range(16))

    def test_drop_last(self):
        dl = DeepSpeedDataLoader(make_ds(30), batch_size=8,
                                 data_parallel_world_size=1, data_parallel_rank=0)
        assert len(list(dl)) == 3

    def test_epoch_reshuffles(self):
        dl = DeepSpeedDataLoader(make_ds(16), batch_size=16, shuffle=True, seed=0,
                                 data_parallel_world_size=1, data_parallel_rank=0)
        first = next(iter(dl))[1].tolist()
        second = next(iter(dl))[1].tolist()
        assert first != second  # epoch advanced → different order


class TestRepeatingLoader:
    def test_wraps(self):
        dl = DeepSpeedDataLoader(make_ds(16), batch_size=8,
                                 data_parallel_world_size=1, data_parallel_rank=0)
        rl = RepeatingLoader(dl)
        got = [next(rl) for _ in range(5)]
        assert len(got) == 5


class SlowDataset:
    """Indexable dataset whose item access sleeps."""

    def __init__(self, n=16, dim=4, delay_s=0.001):
        self.inner = make_ds(n, dim)
        self.delay_s = delay_s

    def __len__(self):
        return len(self.inner)

    def __getitem__(self, i):
        time.sleep(self.delay_s)
        return self.inner[i]


class TestFetchWait:
    """Host-side fetch-wait accounting (monotonic clock only — feeds the
    goodput ledger's data_stall bucket)."""

    def test_deepspeed_loader_counts_dataset_access(self):
        delay = 0.001
        dl = DeepSpeedDataLoader(SlowDataset(n=16, delay_s=delay),
                                 batch_size=8,
                                 data_parallel_world_size=1,
                                 data_parallel_rank=0)
        assert dl.cumulative_fetch_wait_s() == 0.0
        list(dl)   # 2 batches x 8 samples, each sleeping `delay`
        # sleep() only overshoots, so the floor is exact; the ceiling
        # just catches runaway accounting.
        assert dl.cumulative_fetch_wait_s() >= 16 * delay
        assert dl.cumulative_fetch_wait_s() < 100 * 16 * delay

    def test_fetch_wait_accumulates_across_epochs(self):
        dl = DeepSpeedDataLoader(SlowDataset(n=8, delay_s=0.001),
                                 batch_size=8,
                                 data_parallel_world_size=1,
                                 data_parallel_rank=0)
        list(dl)
        first = dl.cumulative_fetch_wait_s()
        list(dl)
        assert dl.cumulative_fetch_wait_s() > first

    def test_repeating_loader_includes_wrapped_wait(self):
        delay = 0.001
        dl = DeepSpeedDataLoader(SlowDataset(n=16, delay_s=delay),
                                 batch_size=8,
                                 data_parallel_world_size=1,
                                 data_parallel_rank=0)
        rl = RepeatingLoader(dl)
        for _ in range(4):      # 2 epochs: restart cost counted too
            next(rl)
        assert rl.cumulative_fetch_wait_s() >= 32 * delay
        # the wrapper's wall INCLUDES the inner loader's own fetch time
        assert rl.cumulative_fetch_wait_s() >= \
            dl.cumulative_fetch_wait_s() * 0.99

    def test_fast_path_overhead_is_negligible(self):
        dl = DeepSpeedDataLoader(make_ds(32), batch_size=8,
                                 data_parallel_world_size=1,
                                 data_parallel_rank=0)
        list(dl)
        # instrumentation itself must not report phantom stalls
        assert dl.cumulative_fetch_wait_s() < 0.5


class TestCollate:
    def test_tuple(self):
        out = default_collate([(np.ones(2), 1), (np.zeros(2), 2)])
        assert out[0].shape == (2, 2)
        assert out[1].tolist() == [1, 2]

    def test_dict(self):
        out = default_collate([{"a": np.ones(3)}, {"a": np.zeros(3)}])
        assert out["a"].shape == (2, 3)
