"""1-bit Adam: warmup==Adam parity, post-warmup convergence, frozen
variance, error-feedback compression properties, comm-volume accounting.

Reference: runtime/fp16/onebit_adam.py (warmup -> compression phase switch,
error-compensated sign compression) and the 5x/16x volume claims in
BASELINE.md.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deepspeed_tpu.ops.onebit import (OnebitState, comm_bytes,
                                      compression_ratio, init_state,
                                      onebit_adam_update)
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.parallel.topology import build_mesh

from simple_model import simple_loss_fn, simple_model_params, random_batch


def _params(seed=0):
    return simple_model_params(jax.random.PRNGKey(seed))


@pytest.mark.slow
def test_warmup_matches_plain_adam():
    """Steps <= freeze_step are bias-corrected Adam on the averaged grads."""
    lr, b1, b2, eps = 1e-2, 0.9, 0.999, 1e-8
    params = _params()
    st = init_state(params)
    tx = optax.adam(lr, b1=b1, b2=b2, eps=eps)
    ref = params
    ref_st = tx.init(ref)
    rng = np.random.default_rng(0)
    for _ in range(10):
        g = jax.tree_util.tree_map(
            lambda p: jnp.asarray(
                rng.standard_normal(p.shape).astype(np.float32)), params)
        params, st, _ = onebit_adam_update(g, st, params, lr=lr, b1=b1, b2=b2,
                                        eps=eps, freeze_step=100)
        u, ref_st = tx.update(g, ref_st, ref)
        ref = optax.apply_updates(ref, u)
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_variance_frozen_after_warmup():
    params = _params()
    st = init_state(params)
    rng = np.random.default_rng(1)
    mk_g = lambda: jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.standard_normal(p.shape).astype(np.float32)),
        params)
    for _ in range(3):
        params, st, _ = onebit_adam_update(mk_g(), st, params, lr=1e-3,
                                        freeze_step=3)
    v_frozen = jax.tree_util.tree_map(np.asarray, st.v)
    for _ in range(5):
        params, st, _ = onebit_adam_update(mk_g(), st, params, lr=1e-3,
                                        freeze_step=3)
    for a, b in zip(jax.tree_util.tree_leaves(v_frozen),
                    jax.tree_util.tree_leaves(st.v)):
        np.testing.assert_array_equal(a, np.asarray(b))


@pytest.mark.slow
def test_error_feedback_bounded_and_unbiased():
    """Error feedback: cumulative transmitted momentum tracks cumulative
    true momentum — the error buffer stays bounded rather than growing."""
    params = {"w": jnp.zeros((128,), jnp.float32)}
    st = init_state(params)
    rng = np.random.default_rng(2)
    errs = []
    for _ in range(50):
        g = {"w": jnp.asarray(rng.standard_normal(128).astype(np.float32))}
        params, st, _ = onebit_adam_update(g, st, params, lr=0.0, freeze_step=0)
        errs.append(float(jnp.linalg.norm(st.worker_error["w"])))
    # bounded: last-10 average no bigger than ~2x the first-10 average
    assert np.mean(errs[-10:]) < 2.0 * np.mean(errs[:10]) + 1e-3


def test_comm_bytes_accounting():
    n = 1_000_000
    full = comm_bytes(n, compressed=False)
    comp = comm_bytes(n, compressed=True)
    assert full == 4 * n
    assert comp == n // 8 + 4
    # the reference's "16x in compression phase" claim territory
    assert compression_ratio(n) > 16


def _engine(mesh, freeze_step, lr=5e-3, gas=1, micro=4):
    dp = int(mesh.shape.get("data", 1))
    cfg = {
        "train_batch_size": micro * gas * dp,
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "OneBitAdam",
                      "params": {"lr": lr, "freeze_step": freeze_step}},
        "steps_per_print": 10 ** 9,
    }
    return DeepSpeedEngine(model=simple_loss_fn, model_params=_params(),
                           config=cfg, mesh=mesh)


def test_engine_onebit_trains_past_freeze():
    """Loss-parity-after-warmup: the compressed phase keeps converging and
    stays close to plain Adam's trajectory."""
    mesh = build_mesh()    # 8-way dp
    eng = _engine(mesh, freeze_step=5)
    cfg_adam = {
        "train_batch_size": 32, "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
        "steps_per_print": 10 ** 9,
    }
    ref = DeepSpeedEngine(model=simple_loss_fn, model_params=_params(),
                          config=cfg_adam, mesh=mesh)
    losses, ref_losses = [], []
    for i in range(30):
        b = random_batch(32, seed=i)
        losses.append(float(jax.device_get(eng.train_batch(b))))
        ref_losses.append(float(jax.device_get(ref.train_batch(b))))
    assert losses[-1] < losses[4], "no progress after freeze_step"
    # same trajectory during warmup
    np.testing.assert_allclose(losses[:4], ref_losses[:4], rtol=1e-4)
    # compressed phase still converges (the reference's claim is same
    # accuracy at lower comm volume, not identical trajectories)
    assert losses[-1] < 0.5 * losses[0]


def test_engine_onebit_rejects_zero_and_fp16():
    mesh = build_mesh()
    cfg = {
        "train_batch_size": 32, "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "OneBitAdam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 10 ** 9,
    }
    with pytest.raises(ValueError):
        DeepSpeedEngine(model=simple_loss_fn, model_params=_params(),
                        config=cfg, mesh=mesh)


def test_engine_onebit_grad_accum():
    mesh = build_mesh()
    eng = _engine(mesh, freeze_step=2, gas=2, micro=2)
    for i in range(6):
        b = random_batch(32, seed=i)
        loss = eng.train_batch(b)
    assert np.isfinite(float(jax.device_get(loss)))


def test_engine_onebit_checkpoint_preserves_per_rank_error(tmp_path):
    """worker_error is per-rank state with a leading dp-sharded axis: a
    save/load roundtrip must restore EVERY rank's error buffer, not
    broadcast rank 0's."""
    mesh = build_mesh()
    eng = _engine(mesh, freeze_step=2, lr=5e-3)
    for i in range(8):     # past freeze -> error buffers populated
        eng.train_batch(random_batch(32, seed=i))
    werr_before = jax.device_get(eng.state.opt_state.worker_error)
    leaves = jax.tree_util.tree_leaves(werr_before)
    assert leaves[0].shape[0] == 8     # leading dp axis
    # ranks diverge (different data shards -> different errors)
    assert np.abs(leaves[0][0] - leaves[0][1]).max() > 0
    eng.save_checkpoint(str(tmp_path), tag="ob")
    eng2 = _engine(mesh, freeze_step=2, lr=5e-3)
    eng2.load_checkpoint(str(tmp_path), tag="ob")
    werr_after = jax.device_get(eng2.state.opt_state.worker_error)
    for a, b in zip(leaves, jax.tree_util.tree_leaves(werr_after)):
        np.testing.assert_array_equal(a, b)
    eng2.train_batch(random_batch(32, seed=99))


def test_compress_per_chunk_scale():
    """The compression scale is per worker-chunk (reference splits the flat
    tensor into world_size chunks, each with its own L1 scale —
    onebit_adam.py:141-168), not one scale per tensor."""
    from deepspeed_tpu.ops.onebit import _compress
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((7, 5)).astype(np.float32))
    err = jnp.zeros_like(x)
    t, new_err = _compress(x, err, chunks=4)
    flat = np.asarray(x).reshape(-1)
    rows = np.pad(flat, (0, 1)).reshape(4, 9)   # 35 -> pad 1 -> 4 chunks of 9
    got = np.abs(np.asarray(t).reshape(-1))
    np.testing.assert_allclose(got[:9], np.abs(rows[0]).mean(), rtol=1e-6)
    np.testing.assert_allclose(got[27:], np.abs(rows[3]).sum() / 8, rtol=1e-6)
    # error feedback identity: x + 0 = transmitted + new_error
    np.testing.assert_allclose(np.asarray(x),
                               np.asarray(t) + np.asarray(new_err), atol=1e-6)
    # chunks=1 keeps the single-scale behavior
    t1, _ = _compress(x, err, chunks=1)
    np.testing.assert_allclose(np.abs(np.asarray(t1)),
                               np.abs(flat).mean(), rtol=1e-6)


def test_onebit_overflow_skips_and_preserves_error_feedback():
    """Non-finite grads skip the step in BOTH phases: params, m, v, error
    buffers and the Adam step count are untouched (reference keeps the fp16
    overflow machinery through compression, onebit_adam.py:104-228)."""
    params = {"w": jnp.ones((64,), jnp.float32)}
    st = init_state(params)
    rng = np.random.default_rng(7)
    mk = lambda: {"w": jnp.asarray(rng.standard_normal(64).astype(np.float32))}
    for _ in range(4):     # into the compressed phase, errors populated
        params, st, aux = onebit_adam_update(mk(), st, params, lr=1e-2,
                                             freeze_step=2)
    assert not bool(aux["overflow"]) and np.isfinite(float(aux["grad_norm"]))
    snap = jax.tree_util.tree_map(np.asarray, (params, st))
    bad = {"w": jnp.full((64,), jnp.nan, jnp.float32)}
    params2, st2, aux2 = onebit_adam_update(bad, st, params, lr=1e-2,
                                            freeze_step=2)
    assert bool(aux2["overflow"])
    for a, b in zip(jax.tree_util.tree_leaves(snap),
                    jax.tree_util.tree_leaves(
                        jax.tree_util.tree_map(np.asarray, (params2, st2)))):
        np.testing.assert_array_equal(a, b)


def test_engine_onebit_fp16_dynamic_scale_recovers():
    """fp16 + OnebitAdam: dynamic loss scale halves on an injected overflow,
    the step is skipped, and training resumes."""
    mesh = build_mesh()
    cfg = {
        "train_batch_size": 32, "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "OneBitAdam",
                      "params": {"lr": 5e-3, "freeze_step": 2}},
        "fp16": {"enabled": True, "initial_scale_power": 4,
                 "hysteresis": 1},
        "steps_per_print": 10 ** 9,
    }
    eng = DeepSpeedEngine(model=simple_loss_fn, model_params=_params(),
                          config=cfg, mesh=mesh)
    losses = []
    for i in range(8):
        losses.append(float(jax.device_get(
            eng.train_batch(random_batch(32, seed=i)))))
    assert all(np.isfinite(losses))
    scale0 = eng.loss_scale()
    skipped0 = int(jax.device_get(eng.state.skipped_steps))
    # Inject a real overflow: NaN inputs make the grads non-finite.
    bad = jax.tree_util.tree_map(
        lambda x: (x * np.nan if x.dtype.kind == "f" else x),
        random_batch(32, seed=0))
    eng.train_batch(bad)
    assert eng.loss_scale() == scale0 / 2, \
        f"hysteresis=1 overflow must halve the scale ({scale0} -> " \
        f"{eng.loss_scale()})"
    assert int(jax.device_get(eng.state.skipped_steps)) == skipped0 + 1
    after = [float(jax.device_get(eng.train_batch(random_batch(32, seed=i))))
             for i in range(8, 12)]
    assert all(np.isfinite(after))
    assert after[-1] <= losses[0]
