"""Topology tests — parity with reference tests/unit/test_topology.py."""
import pytest

from deepspeed_tpu.parallel.topology import (ProcessTopology, PipeDataParallelTopology,
                                             PipeModelDataParallelTopology,
                                             PipelineParallelGrid, build_mesh)


class TestProcessTopology:
    def test_rank_coord_roundtrip(self):
        topo = ProcessTopology(axes=["x", "y"], dims=[2, 3])
        assert topo.world_size() == 6
        for r in range(6):
            coord = topo.get_coord(r)
            assert topo.get_rank(x=coord.x, y=coord.y) == r

    def test_row_major(self):
        topo = ProcessTopology(axes=["x", "y"], dims=[2, 2])
        assert topo.get_rank(x=0, y=0) == 0
        assert topo.get_rank(x=0, y=1) == 1
        assert topo.get_rank(x=1, y=0) == 2
        assert topo.get_rank(x=1, y=1) == 3

    def test_axis_comm_lists(self):
        topo = ProcessTopology(axes=["pipe", "data"], dims=[2, 2])
        data_lists = topo.get_axis_comm_lists("data")
        assert [0, 1] in data_lists and [2, 3] in data_lists
        pipe_lists = topo.get_axis_comm_lists("pipe")
        assert [0, 2] in pipe_lists and [1, 3] in pipe_lists

    def test_filter_match(self):
        topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
        ranks = topo.filter_match(pipe=0)
        assert len(ranks) == 4
        assert all(topo.get_coord(r).pipe == 0 for r in ranks)

    def test_get_axis_list(self):
        topo = ProcessTopology(axes=["a", "b"], dims=[2, 4])
        assert topo.get_axis_list("a", 1) == [4, 5, 6, 7]

    def test_rank_repr(self):
        topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=1)
        # model axis survives default omission of data/pipe
        assert "model" in topo.get_rank_repr(0)

    def test_missing_axis_dim_zero(self):
        topo = ProcessTopology(axes=["x"], dims=[4])
        assert topo.get_dim("nope") == 0


class Test3DTopology:
    def test_3d_sizes(self):
        topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
        assert topo.world_size() == 8
        assert topo.get_dim("pipe") == 2
        assert topo.get_dim("model") == 2
        assert topo.get_dim("data") == 2

    def test_model_axis_innermost(self):
        topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
        # ranks 0 and 1 should differ only in the model coordinate
        c0, c1 = topo.get_coord(0), topo.get_coord(1)
        assert c0.pipe == c1.pipe and c0.data == c1.data and c0.model != c1.model


class TestGrid:
    def test_mpu_contract(self):
        topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
        grid = PipelineParallelGrid(topology=topo, global_rank=3)
        coord = topo.get_coord(3)
        assert grid.get_pipe_parallel_rank() == coord.pipe
        assert grid.get_data_parallel_rank() == coord.data
        assert grid.get_model_parallel_rank() == coord.model
        assert grid.get_data_parallel_world_size() == 2
        assert grid.get_model_parallel_world_size() == 2
        assert grid.get_pipe_parallel_world_size() == 2
        # The reference's "slice parallel" alias for model parallelism
        # still answers, but DEPRECATED since the real `slice` mesh axis
        # (multi-slice DCN scale-out) landed — it must warn and point at
        # the model-parallel accessors (tests/test_multislice.py holds
        # the full alias suite).
        import pytest
        with pytest.warns(DeprecationWarning, match="tensor-slicing"):
            assert grid.get_slice_parallel_rank() == \
                grid.get_model_parallel_rank()

    def test_stage_mapping(self):
        topo = PipeDataParallelTopology(num_pp=4, num_dp=2)
        grid = PipelineParallelGrid(topology=topo, global_rank=0)
        assert grid.is_first_stage()
        assert not grid.is_last_stage()
        # all stage ranks share this rank's data coord
        for s in range(4):
            r = grid.stage_to_global_rank(s)
            assert topo.get_coord(r).pipe == s
            assert topo.get_coord(r).data == 0


class TestMesh:
    def test_build_8dp(self):
        mesh = build_mesh()
        assert mesh.shape["data"] == 8
        assert mesh.shape["model"] == 1

    def test_build_2x2x2(self):
        mesh = build_mesh(dp=2, mp=2, pp=2)
        assert mesh.shape["data"] == 2
        assert mesh.shape["model"] == 2
        assert mesh.shape["pipe"] == 2

    def test_bad_factorization(self):
        with pytest.raises(AssertionError):
            build_mesh(dp=3, mp=3)
