"""Roofline cost model (monitor/cost_model.py), the shared chip-peak
table (monitor/peaks.py), and the goodput ledger (monitor/goodput.py).

Tier-1 correctness gates from the PR issue:

- the jaxpr-walk flops profiler and XLA's ``Compiled.cost_analysis()``
  must agree on a STRAIGHT-LINE gpt2 block within a documented tolerance
  (cross-validating both counters: drift in the per-primitive table
  fails here);
- on a scanned program XLA undercounts by the trip count (the scan body
  is costed once) and the cost model must detect and correct it;
- the ledger's buckets must sum to the window wall-clock within 1%, and
  double-attribution must be SURFACED (consistent=False), not clamped.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bench
from deepspeed_tpu.monitor.cost_model import (BOUND_COMPUTE, BOUND_HBM,
                                              BOUND_INTERCONNECT,
                                              abstract_args_of,
                                              analytic_flops,
                                              build_cost_model, mfu,
                                              path_cost, roofline,
                                              xla_cost_analysis)
from deepspeed_tpu.monitor.goodput import (BUCKETS, GoodputLedger,
                                           extract_step_info)
from deepspeed_tpu.monitor.peaks import (TPU_HBM_GBS, TPU_ICI_GBS,
                                         TPU_PEAK_TFLOPS, ChipPeaks,
                                         chip_peak_tflops, peaks_for_kind)
from deepspeed_tpu.monitor.recompile import RecompileSentinel


# --------------------------------------------------------------------- #
# Shared peak table
# --------------------------------------------------------------------- #
class TestPeakTable:
    def test_every_generation_fully_specified(self):
        assert set(TPU_PEAK_TFLOPS) == set(TPU_HBM_GBS) == set(TPU_ICI_GBS)
        for table in (TPU_PEAK_TFLOPS, TPU_HBM_GBS, TPU_ICI_GBS):
            assert all(v > 0 for v in table.values())

    @pytest.mark.parametrize("kind,gen", [
        ("TPU v4", "v4"), ("TPU v5e", "v5e"), ("TPU v5p", "v5p"),
        ("TPU v6e", "v6e")])
    def test_kind_resolution(self, kind, gen):
        pk = peaks_for_kind(kind)
        assert pk.name == gen and not pk.assumed
        assert pk.bf16_tflops == TPU_PEAK_TFLOPS[gen]
        assert pk.hbm_gbs == TPU_HBM_GBS[gen]
        assert pk.ici_gbs == TPU_ICI_GBS[gen]

    def test_unknown_kind_is_assumed_v5e(self):
        for kind in ("cpu", "", "NVIDIA H100", None):
            pk = peaks_for_kind(kind or "")
            assert pk.name == "v5e" and pk.assumed

    def test_unit_conversions(self):
        pk = peaks_for_kind("TPU v4")
        assert pk.flops_per_sec == pk.bf16_tflops * 1e12
        assert pk.hbm_bytes_per_sec == pk.hbm_gbs * 1e9
        assert pk.ici_bytes_per_sec == pk.ici_gbs * 1e9

    def test_bench_reexports_the_shared_table(self):
        """bench.py's historical API now IS the shared table — one source
        of truth for every MFU denominator."""
        assert bench.TPU_PEAK_TFLOPS is TPU_PEAK_TFLOPS
        assert bench.chip_peak_tflops is chip_peak_tflops
        assert chip_peak_tflops() > 0


# --------------------------------------------------------------------- #
# Roofline + MFU math
# --------------------------------------------------------------------- #
PEAKS = ChipPeaks(name="v5e", bf16_tflops=200.0, hbm_gbs=1000.0,
                  ici_gbs=100.0)


class TestRoofline:
    def test_compute_bound(self):
        # 1e12 flops / 200 TF = 5 ms; 1e6 bytes HBM = 1 us; no comm.
        r = roofline(1e12, 1e6, 0.0, PEAKS)
        assert r["bound"] == BOUND_COMPUTE
        assert r["floor_ms"] == pytest.approx(5.0)
        assert r["floor_ms"] == max(r["t_compute_ms"], r["t_hbm_ms"],
                                    r["t_comm_ms"])

    def test_hbm_bound(self):
        # 1e9 bytes / 1000 GB/s = 1 ms; 1e9 flops = 5 us.
        r = roofline(1e9, 1e9, 0.0, PEAKS)
        assert r["bound"] == BOUND_HBM
        assert r["floor_ms"] == pytest.approx(1.0)

    def test_interconnect_bound(self):
        # 1e9 wire bytes / 100 GB/s = 10 ms.
        r = roofline(1e9, 1e6, 1e9, PEAKS)
        assert r["bound"] == BOUND_INTERCONNECT
        assert r["floor_ms"] == pytest.approx(10.0)

    def test_operational_intensity(self):
        r = roofline(2e9, 1e9, 0.0, PEAKS)
        assert r["intensity_flops_per_byte"] == pytest.approx(2.0)
        assert r["machine_balance_flops_per_byte"] == pytest.approx(
            PEAKS.flops_per_sec / PEAKS.hbm_bytes_per_sec)


class TestMfu:
    def test_formula(self):
        # 8 devices, 1.6e9 total flops, 1 ms step: 2e11 flops/s/device
        # over a 2e14 peak = 1e-3.
        assert mfu(1.6e9, 1e-3, 8, PEAKS) == pytest.approx(1e-3)
        # perfect utilisation pins at 1.0: one step exactly at peak.
        assert mfu(8 * 2e14, 1.0, 8, PEAKS) == pytest.approx(1.0)

    def test_degenerate_inputs(self):
        assert mfu(1e12, 0.0, 8, PEAKS) == 0.0
        assert mfu(1e12, 1.0, 0, PEAKS) == 0.0


# --------------------------------------------------------------------- #
# Tier-1 gate: analytic profiler vs XLA cost analysis on the gpt2 block
# --------------------------------------------------------------------- #
def _gpt2_fixture(scan_layers, num_layers=2):
    from deepspeed_tpu.models import GPT2_CONFIGS
    from deepspeed_tpu.models.gpt2 import gpt2_apply, gpt2_init
    cfg = dataclasses.replace(
        GPT2_CONFIGS["gpt2-tiny"], scan_layers=scan_layers,
        num_layers=num_layers, hidden_dropout=0.0, attn_dropout=0.0)
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((2, 64), dtype=jnp.int32)
    fn = jax.jit(lambda p, t: gpt2_apply(p, t, cfg))
    return fn, (params, tokens)


class TestFlopsCrossValidation:
    # Documented tolerance: the analytic jaxpr-walk count follows the
    # model-flops convention (2mnk matmuls + elementwise), while XLA's
    # optimized-HLO count also prices transcendentals (softmax exp,
    # layernorm rsqrt, gelu tanh) — so XLA sits a few percent ABOVE the
    # analytic figure on this block (measured ~5% here). 10% catches
    # per-primitive-table drift without flaking on XLA version noise.
    TOLERANCE = 0.10

    def test_gpt2_block_straight_line_agreement(self):
        fn, args = _gpt2_fixture(scan_layers=False)
        a_args, a_kwargs = abstract_args_of(args, {})
        analytic = analytic_flops(fn, a_args, a_kwargs)
        xla = xla_cost_analysis(fn, a_args, a_kwargs)
        assert analytic and analytic > 0
        assert xla is not None and xla["flops"] > 0
        assert xla["bytes_accessed"] > 0
        ratio = analytic / xla["flops"]
        assert abs(ratio - 1.0) <= self.TOLERANCE, (
            f"flops counters drifted: analytic={analytic} "
            f"xla={xla['flops']} ratio={ratio:.4f}")

    def test_scan_undercount_detected_and_corrected(self):
        """XLA costs a scan body ONCE; the analytic walk multiplies by
        the trip count. path_cost must detect the ratio and scale the
        HBM bytes by the same factor."""
        fn, args = _gpt2_fixture(scan_layers=True, num_layers=4)
        a_args, a_kwargs = abstract_args_of(args, {})
        p = path_cost("train", fn, a_args, a_kwargs, comm_bytes=0.0,
                      n_devices=1, peaks=PEAKS)
        assert p["available"]
        # 4 scanned layers dominate: analytic/XLA sits well above the
        # 1.5 detection threshold and below the layer count (embedding +
        # head run outside the scan).
        assert 1.5 < p["scan_scale"] <= 4.0
        # scan_scale is rounded for the record; the bytes use the exact
        # ratio — compare loosely.
        assert p["hbm_bytes_per_device"] == pytest.approx(
            p["xla_bytes_per_device"] * p["scan_scale"], rel=1e-3)
        # flops estimate is the analytic (scan-aware) one.
        assert p["flops_per_device"] == pytest.approx(p["analytic_flops"])


# --------------------------------------------------------------------- #
# path_cost / build_cost_model plumbing
# --------------------------------------------------------------------- #
class TestBuildCostModel:
    def _sentinel_with_matmul(self):
        sentinel = RecompileSentinel(warmup_calls=1)
        fn = jax.jit(lambda a, b: a @ b)
        wrapped = sentinel.instrument("mm_step", fn)
        a = jnp.ones((64, 64), jnp.float32)
        wrapped(a, a)   # compile -> registry records the signature
        return sentinel

    def test_sentinel_registry_feeds_the_model(self):
        sentinel = self._sentinel_with_matmul()
        st = sentinel._fns["mm_step"]
        assert st["fn"] is not None and st["abstract_args"] is not None
        out = build_cost_model(sentinel, comm_bytes_by_path={"mm_step": 512},
                               step_paths={"mm_step": 1.0}, n_devices=1,
                               peaks=PEAKS)
        p = out["paths"]["mm_step"]
        assert p["available"]
        # 64x64x64 matmul: 2mnk = 524288 flops.
        assert p["analytic_flops"] == 2 * 64 ** 3
        assert p["comm_bytes"] == 512
        assert p["bound"] in (BOUND_COMPUTE, BOUND_HBM, BOUND_INTERCONNECT)
        step = out["step"]
        assert step["flops_per_step"] == pytest.approx(2 * 64 ** 3)
        assert step["missing_paths"] == []
        assert out["chip"]["name"] == "v5e"

    def test_step_fusion_weights_and_missing(self):
        """gas-style weighting: a path invoked k times contributes k x
        flops and k x floor; unregistered paths are surfaced."""
        sentinel = self._sentinel_with_matmul()
        out1 = build_cost_model(sentinel, {}, {"mm_step": 1.0}, 1,
                                peaks=PEAKS)
        out3 = build_cost_model(sentinel, {},
                                {"mm_step": 3.0, "ghost": 1.0}, 1,
                                peaks=PEAKS)
        assert out3["step"]["flops_per_step"] == pytest.approx(
            3 * out1["step"]["flops_per_step"])
        assert out3["step"]["floor_ms"] == pytest.approx(
            3 * out1["step"]["floor_ms"], rel=1e-6)
        assert out3["step"]["missing_paths"] == ["ghost"]

    def test_extra_paths(self):
        """Paths outside the sentinel registry (e.g. an eval fn) can be
        priced via extra_paths."""
        sentinel = RecompileSentinel()
        fn = jax.jit(lambda a: a * 2.0)
        a_args, a_kwargs = abstract_args_of(
            (jnp.ones((8, 8), jnp.float32),), {})
        out = build_cost_model(sentinel, {}, {"scale": 1.0}, 1,
                               peaks=PEAKS,
                               extra_paths={"scale": (fn, a_args, a_kwargs)})
        assert out["paths"]["scale"]["available"]

    def test_abstract_leaf_survives_donation(self):
        """abstract_args_of mirrors shapes/dtypes as ShapeDtypeStructs —
        usable after the live buffers are donated/deleted."""
        x = jnp.ones((4, 2), jnp.bfloat16)
        a_args, _ = abstract_args_of((x, 3), {})
        x.delete()
        leaf = a_args[0]
        assert leaf.shape == (4, 2) and leaf.dtype == jnp.bfloat16
        assert a_args[1] == 3   # non-array leaves pass through


# --------------------------------------------------------------------- #
# Goodput ledger
# --------------------------------------------------------------------- #
class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class TestGoodputLedger:
    def test_window_settlement_math(self):
        clk = FakeClock(10.0)
        led = GoodputLedger(clock=clk)
        led.note("data_stall", 0.1)
        led.note("recompile", 0.05)
        led.note("checkpoint", 0.2)
        steps = [(0.5, False, 0.0), (0.2, True, 0.0), (0.4, False, 0.1)]
        clk.t = 12.0
        w = led.close_window(steps)
        assert w["window_s"] == pytest.approx(2.0)
        assert w["steps"] == 3
        # useful = non-overflow step wall (0.9) minus in-step stalls
        # (0.1 + 0.05) minus exposed offload host time (0.1).
        assert w["useful_compute_s"] == pytest.approx(0.65)
        assert w["data_stall_s"] == pytest.approx(0.1)
        assert w["recompile_s"] == pytest.approx(0.05)
        assert w["overflow_skipped_s"] == pytest.approx(0.2)
        assert w["checkpoint_s"] == pytest.approx(0.2)
        assert w["offload_exposed_s"] == pytest.approx(0.1)
        assert w["other_s"] == pytest.approx(2.0 - 1.3)
        # The acceptance identity: buckets sum to window wall within 1%.
        total = sum(w[f"{b}_s"] for b in BUCKETS)
        assert total == pytest.approx(w["window_s"], rel=0.01)
        assert w["accounted_fraction"] == pytest.approx(1.0)
        assert w["consistent"]

    def test_stall_inside_overflow_step_reattributed(self):
        """A step can both cold-compile AND overflow (high initial loss
        scale): the compile wall is inside the overflow step's wall, so
        it must move OUT of the overflow bucket — counted once, under
        recompile — and the window must stay consistent."""
        clk = FakeClock(0.0)
        led = GoodputLedger(clock=clk)
        led.note("recompile", 0.8)
        clk.t = 2.0
        w = led.close_window([(1.0, True, 0.0)])   # the only step overflowed
        assert w["recompile_s"] == pytest.approx(0.8)
        assert w["overflow_skipped_s"] == pytest.approx(0.2)
        assert w["useful_compute_s"] == 0.0
        assert w["other_s"] == pytest.approx(1.0)
        assert w["consistent"]

    def test_spill_beyond_overflow_wall_is_surfaced(self):
        """Measured stalls exceeding ALL step wall is genuine
        double-attribution: overflow goes negative and consistent flips
        — surfaced, never clamped."""
        clk = FakeClock(0.0)
        led = GoodputLedger(clock=clk)
        led.note("recompile", 0.9)
        clk.t = 2.0
        w = led.close_window([(0.5, True, 0.0)])
        assert w["overflow_skipped_s"] < 0
        assert not w["consistent"]

    def test_double_attribution_is_surfaced_not_clamped(self):
        """Steps claiming more wall than the window exists -> negative
        residual -> consistent=False. The ledger never invents time."""
        clk = FakeClock(0.0)
        led = GoodputLedger(clock=clk)
        clk.t = 1.0
        w = led.close_window([(2.0, False, 0.0)])
        assert w["other_s"] < 0
        assert not w["consistent"]

    def test_windows_are_contiguous(self):
        clk = FakeClock(0.0)
        led = GoodputLedger(clock=clk)
        clk.t = 2.0
        w1 = led.close_window([])
        clk.t = 3.5
        w2 = led.close_window([])
        assert w1["window_s"] == pytest.approx(2.0)
        assert w2["window_s"] == pytest.approx(1.5)   # opened at t=2.0
        s = led.summary()
        assert s["windows"] == 2
        assert s["total_window_s"] == pytest.approx(3.5)

    def test_noted_buckets_reset_per_window(self):
        clk = FakeClock(0.0)
        led = GoodputLedger(clock=clk)
        led.note("data_stall", 0.5)
        clk.t = 1.0
        assert led.close_window([])["data_stall_s"] == pytest.approx(0.5)
        clk.t = 2.0
        assert led.close_window([])["data_stall_s"] == 0.0

    def test_summary_goodput_fraction(self):
        clk = FakeClock(0.0)
        led = GoodputLedger(clock=clk)
        clk.t = 1.0
        led.close_window([(0.6, False, 0.0)])
        s = led.summary()
        assert s["goodput_fraction"] == pytest.approx(0.6)

    def test_extract_step_info(self):
        assert extract_step_info({"wall_ms": 500.0, "overflow": False}) \
            == (0.5, False, 0.0)
        rec = {"wall_ms": 1000.0, "overflow": True,
               "offload": {"wall_ms": 1000.0, "device_step_ms": 400.0}}
        wall, ovf, exposed = extract_step_info(rec)
        assert wall == 1.0 and ovf
        assert exposed == pytest.approx(0.6)
        # missing device timing -> no exposed attribution (not negative)
        assert extract_step_info(
            {"wall_ms": 10.0, "offload": {"wall_ms": 10.0}})[2] == 0.0


# --------------------------------------------------------------------- #
# Bench gate (tools/bench_gate.py)
# --------------------------------------------------------------------- #
import importlib.util  # noqa: E402
import json  # noqa: E402
import os  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_bench_gate():
    spec = importlib.util.spec_from_file_location(
        "bench_gate", os.path.join(REPO, "tools", "bench_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestBenchGate:
    def _write(self, tmp_path, name, doc):
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    def test_extract_metrics_all_shapes(self):
        bg = load_bench_gate()
        none_srv = {"serve_tps": None, "ttft_p95": None,
                    "kernel_speedup": None, "tile_speedup": None,
                    "zero3_overlap": None,
                    "health": None, "hbm_per_token": None,
                    "accept_rate": None, "moe_drop": None,
                    "dcn_bytes": None, "ckpt_share": None,
                    "ckpt_every": None, "attend_ratio": None,
                    "z3_dcn_bytes": None, "z3_dcn_param": None,
                    "slo_attainment": None, "ledger_consistent": None}
        # driver round file wrapping a bench record
        m = bg.extract_metrics({"n": 6, "parsed": {"mfu": 0.55}})
        assert m == {"mfu": 0.55, "goodput": None, **none_srv}
        # raw bench record
        assert bg.extract_metrics({"mfu": 0.5})["mfu"] == 0.5
        # TELEMETRY.json: fenced window figure wins
        m = bg.extract_metrics({
            "mfu": {"window_mfu": 0.4, "per_step_p50": 0.3},
            "goodput": {"goodput_fraction": 0.9}})
        assert m == {"mfu": 0.4, "goodput": 0.9, **none_srv}
        # SERVE_BENCH.json / serving-mode TELEMETRY.json
        m = bg.extract_metrics({"serving": {
            "tokens_per_s": 85.3, "ttft_ms": {"p50": 10.0, "p95": 20.0}}})
        assert m["serve_tps"] == 85.3 and m["ttft_p95"] == 20.0
        # pre-MFU / pre-serving round: nothing extractable
        assert bg.extract_metrics({"parsed": {"value": 100.0}}) == \
            {"mfu": None, "goodput": None, **none_srv}

    def test_gate_serving_rounds(self, tmp_path):
        """Serving tokens/s drop and TTFT p95 rise gate; pre-serving
        rounds skip, never fail."""
        bg = load_bench_gate()
        old = self._write(tmp_path, "old.json", {"serving": {
            "tokens_per_s": 100.0, "ttft_ms": {"p95": 100.0}}})
        ok = self._write(tmp_path, "ok.json", {"serving": {
            "tokens_per_s": 95.0, "ttft_ms": {"p95": 110.0}}})
        slow = self._write(tmp_path, "slow.json", {"serving": {
            "tokens_per_s": 80.0, "ttft_ms": {"p95": 100.0}}})
        laggy = self._write(tmp_path, "laggy.json", {"serving": {
            "tokens_per_s": 100.0, "ttft_ms": {"p95": 200.0}}})
        pre = self._write(tmp_path, "pre.json", {"mfu": 0.5})
        assert bg.main([old, ok]) == 0
        assert bg.main([old, slow]) == 1
        assert bg.main([old, laggy]) == 1
        assert bg.main([pre, old]) == 0        # pre-serving round skips

    def test_gate_checkpoint_exposed_share(self, tmp_path):
        """Resilience rounds gate the checkpoint-EXPOSED goodput share
        (new side, absolute ceiling); pre-resilience rounds skip, never
        fail. Both carrier shapes parse: RESILIENCE_BENCH.json's
        top-level record and a TELEMETRY.json goodput sub-dict."""
        bg = load_bench_gate()
        m = bg.extract_metrics({"checkpoint": {
            "snapshot_every": 50, "exposed_share": 0.008,
            "exposed_s": 0.01}})
        assert m["ckpt_share"] == 0.008 and m["ckpt_every"] == 50
        m = bg.extract_metrics({"goodput": {
            "goodput_fraction": 0.96,
            "checkpoint": {"exposed_share": 0.01, "exposed_s": 0.02,
                           "snapshot_every": 50}}})
        assert m["ckpt_share"] == 0.01
        # A non-checkpointing run (zero exposed wall) carries no gateable
        # share — it must skip, not trivially pass forever.
        m = bg.extract_metrics({"goodput": {
            "goodput_fraction": 0.96,
            "checkpoint": {"exposed_share": 0.0, "exposed_s": 0.0}}})
        assert m["ckpt_share"] is None
        old = self._write(tmp_path, "old.json", {"mfu": 0.5})
        ok = self._write(tmp_path, "ck_ok.json", {"checkpoint": {
            "snapshot_every": 50, "exposed_share": 0.008,
            "exposed_s": 0.01}})
        bad = self._write(tmp_path, "ck_bad.json", {"checkpoint": {
            "snapshot_every": 50, "exposed_share": 0.12,
            "exposed_s": 0.5}})
        assert bg.main([old, ok]) == 0
        assert bg.main([old, bad]) == 1
        assert bg.main([ok, old]) == 0         # pre-resilience new side

    def test_extract_paged_serving_fields(self):
        bg = load_bench_gate()
        m = bg.extract_metrics({"serving": {
            "tokens_per_s": 900.0,
            "ttft_ms": {"p95": 50.0},
            "hbm_bytes_per_token": {"p50": 1200.0, "p95": 1400.0},
            "spec": {"proposed": 100, "accepted": 80,
                     "acceptance_rate": 0.8}}})
        assert m["hbm_per_token"] == 1200.0
        assert m["accept_rate"] == 0.8
        # Slot-major serving record: paged fields absent -> None.
        m = bg.extract_metrics({"serving": {"tokens_per_s": 50.0}})
        assert m["hbm_per_token"] is None and m["accept_rate"] is None

    def test_gate_hbm_bytes_per_token(self, tmp_path):
        """HBM/token regresses on a RISE; pre-paging rounds skip."""
        bg = load_bench_gate()
        old = self._write(tmp_path, "old.json", {"serving": {
            "hbm_bytes_per_token": {"p50": 1000.0}}})
        ok = self._write(tmp_path, "ok.json", {"serving": {
            "hbm_bytes_per_token": {"p50": 1100.0}}})
        fat = self._write(tmp_path, "fat.json", {"serving": {
            "hbm_bytes_per_token": {"p50": 1300.0}}})
        pre = self._write(tmp_path, "pre.json", {"serving": {
            "tokens_per_s": 50.0}})
        assert bg.main([old, ok]) == 0
        assert bg.main([old, fat]) == 1
        assert bg.main([old, fat, "--hbm-rise", "0.5"]) == 0
        assert bg.main([pre, old]) == 0        # pre-paging round skips
        assert bg.main([old, pre]) == 0

    def test_gate_spec_acceptance(self, tmp_path):
        """Acceptance gates on the new-side floor and on a relative
        drop vs the previous round; pre-spec rounds skip."""
        bg = load_bench_gate()

        def srv(rate):
            return {"serving": {"spec": {"acceptance_rate": rate}}}

        old = self._write(tmp_path, "old.json", srv(0.8))
        ok = self._write(tmp_path, "ok.json", srv(0.75))
        collapsed = self._write(tmp_path, "collapsed.json", srv(0.02))
        dropped = self._write(tmp_path, "dropped.json", srv(0.5))
        pre = self._write(tmp_path, "pre.json", {"serving": {
            "tokens_per_s": 50.0}})
        assert bg.main([old, ok]) == 0
        assert bg.main([old, collapsed]) == 1      # under the floor
        assert bg.main([old, dropped]) == 1        # >10% rel drop
        assert bg.main([pre, ok]) == 0             # floor-only check
        assert bg.main([old, pre]) == 0            # pre-spec skips

    def test_gate_passes_within_threshold(self, tmp_path):
        bg = load_bench_gate()
        old = self._write(tmp_path, "old.json", {"mfu": 0.50})
        new = self._write(tmp_path, "new.json", {"mfu": 0.47})
        assert bg.main([old, new, "--mfu-drop", "0.10"]) == 0

    def test_gate_fails_on_mfu_regression(self, tmp_path):
        bg = load_bench_gate()
        old = self._write(tmp_path, "old.json", {"mfu": 0.50})
        new = self._write(tmp_path, "new.json", {"mfu": 0.40})
        assert bg.main([old, new, "--mfu-drop", "0.10"]) == 1

    def test_gate_tile_speedup(self, tmp_path):
        """--tile-drop gates kernels.tile_speedup (ablate_autotune.py);
        pre-autotune rounds skip, never fail."""
        bg = load_bench_gate()
        old = self._write(tmp_path, "old.json",
                          {"kernels": {"tile_speedup": 1.20}})
        bad = self._write(tmp_path, "bad.json",
                          {"kernels": {"tile_speedup": 1.00}})
        ok = self._write(tmp_path, "ok.json",
                         {"kernels": {"tile_speedup": 1.15}})
        pre = self._write(tmp_path, "pre.json", {"mfu": 0.5})
        assert bg.extract_metrics(
            {"kernels": {"tile_speedup": 1.2}})["tile_speedup"] == 1.2
        assert bg.main([old, ok, "--tile-drop", "0.10"]) == 0
        assert bg.main([old, bad, "--tile-drop", "0.10"]) == 1
        assert bg.main([old, bad, "--tile-drop", "0.20"]) == 0
        # Pre-autotune rounds on either side: skipped, never failed.
        assert bg.main([pre, pre]) == 0

    def test_gate_attend_work_ratio(self, tmp_path):
        """--attend-drop gates serving.attend_work_ratio (the paged-
        attention kernel's structural one-hot/kernel HBM ratio — a DROP
        means decode attend work crept back toward pool capacity);
        pre-kernel rounds on either side skip, never fail."""
        bg = load_bench_gate()

        def srv(ratio):
            return {"serving": {"attend_work_ratio": ratio,
                                "tokens_per_s": 50.0}}

        old = self._write(tmp_path, "old.json", srv(3.5))
        ok = self._write(tmp_path, "ok.json", srv(3.3))
        bad = self._write(tmp_path, "bad.json", srv(2.0))
        pre = self._write(tmp_path, "pre.json",
                          {"serving": {"tokens_per_s": 50.0}})
        assert bg.extract_metrics(srv(3.5))["attend_ratio"] == 3.5
        assert bg.extract_metrics(
            {"serving": {"tokens_per_s": 1.0}})["attend_ratio"] is None
        assert bg.main([old, ok]) == 0
        assert bg.main([old, bad]) == 1
        assert bg.main([old, bad, "--attend-drop", "0.60"]) == 0
        assert bg.main([pre, old]) == 0        # pre-kernel old side
        assert bg.main([old, pre]) == 0        # pre-kernel new side

    def test_gate_fails_on_goodput_regression(self, tmp_path):
        bg = load_bench_gate()
        old = self._write(tmp_path, "old.json",
                          {"goodput": {"goodput_fraction": 0.90}})
        new = self._write(tmp_path, "new.json",
                          {"goodput": {"goodput_fraction": 0.80}})
        assert bg.main([old, new, "--goodput-drop", "0.05"]) == 1
        assert bg.main([old, new, "--goodput-drop", "0.15"]) == 0

    def test_missing_metric_skips_never_fails(self, tmp_path):
        """Rounds recorded before the mfu field existed must pass."""
        bg = load_bench_gate()
        old = self._write(tmp_path, "old.json", {"parsed": {"value": 1.0}})
        new = self._write(tmp_path, "new.json", {"mfu": 0.5})
        assert bg.main([old, new]) == 0

    def test_latest_rounds_discovery(self, tmp_path):
        bg = load_bench_gate()
        for name in ("BENCH_r01.json", "BENCH_r02.json",
                     "BENCH_r10.json", "BENCH_r04_builder.json"):
            self._write(tmp_path, name, {})
        pair = bg.latest_rounds(str(tmp_path))
        assert [os.path.basename(p) for p in pair] == \
            ["BENCH_r02.json", "BENCH_r10.json"]   # numeric, no _builder
        assert bg.main(["--dir", str(tmp_path)]) == 0   # nothing comparable


# --------------------------------------------------------------------- #
# Optimizer-apply analytic pricing (one-pass vs two-pass HBM bytes)
# --------------------------------------------------------------------- #
class TestOptimizerApplyPricing:
    def test_fp16_two_pass_is_over_double(self):
        """The ISSUE-8 acceptance arithmetic, HONEST accounting: under
        fp16 the historical two-pass sequencing really paid the unscale
        read+write, the tree_has_inf_or_nan re-read, AND a traced
        overflow select over old+new p/m/v — >2x the one-pass bytes.
        (For non-fp16 the select was a folded constant; no saving is
        claimed there.)"""
        from deepspeed_tpu.ops.fused_update import apply_hbm_bytes
        params = {"w": jnp.zeros((1000, 1000), jnp.float32),
                  "b": jnp.zeros((1000,), jnp.float32)}
        pricing = apply_hbm_bytes(params, one_pass=True, fp16=True,
                                  cast_dtype=jnp.bfloat16, clip=True)
        assert pricing["active"] == pricing["one_pass"]
        assert pricing["ratio_two_over_one"] >= 2.0, pricing
        n = 1000 * 1000 + 1000
        # one-pass: apply kernel (g4 + p4 + mv8 read, p4 + mv8 write,
        # cast2 write) + the sqnorm re-read of g (the norm is NOT free
        # in one-pass mode — it is a wash with two-pass's norm read).
        assert pricing["one_pass"] == n * (4 + 12 + 12 + 2 + 4)

    def test_norm_wash_and_foldable_select_claim_nothing(self):
        """clip toggles the norm read on BOTH sides (a wash); non-fp16
        overflow select is priced at zero (XLA folds it); master-free
        bf16 without clip is byte-NEUTRAL between the modes."""
        from deepspeed_tpu.ops.fused_update import apply_hbm_bytes
        params = {"w": jnp.zeros((512, 512), jnp.bfloat16)}
        n = 512 * 512
        off = apply_hbm_bytes(params, clip=False)
        on = apply_hbm_bytes(params, clip=True)
        assert on["one_pass"] - off["one_pass"] == 4 * n
        assert on["two_pass"] - off["two_pass"] == 4 * n
        # the r05 bench shape: no clip, no fp16, no cast — modes equal
        assert off["ratio_two_over_one"] == 1.0, off

    def test_cast_pass_prices_only_the_reread(self):
        from deepspeed_tpu.ops.fused_update import apply_hbm_bytes
        params = {"w": jnp.zeros((512, 512), jnp.float32)}
        n = 512 * 512
        base = apply_hbm_bytes(params, clip=True)
        cast = apply_hbm_bytes(params, clip=True, cast_dtype=jnp.bfloat16)
        # cast write (2B) exists in BOTH modes; two-pass adds only the
        # updated-param re-read (4B) of the standalone cast pass.
        assert cast["one_pass"] - base["one_pass"] == 2 * n
        assert cast["two_pass"] - base["two_pass"] == (2 + 4) * n

    def test_engine_payload_carries_one_pass_mode(self, tmp_path):
        """The dp=8 ZeRO-2 fused engine's cost model payload reports the
        apply path at one-pass pricing with the ~2x alternative ratio —
        the roofline acceptance record for the halved optimizer bytes."""
        from deepspeed_tpu.runtime.engine import DeepSpeedEngine
        from deepspeed_tpu.parallel.topology import build_mesh

        def loss_fn(params, batch, rng):
            x, y = batch
            return jnp.mean((x @ params["w"] - y) ** 2)

        params = {"w": jnp.zeros((32, 8), jnp.float32)}
        eng = DeepSpeedEngine(
            model=loss_fn, model_params=params,
            config={
                "train_batch_size": 16,
                "train_micro_batch_size_per_gpu": 2,
                "gradient_clipping": 1.0,
                "optimizer": {"type": "AdamW",
                              "params": {"lr": 1e-3, "fused": True}},
                "zero_optimization": {"stage": 2},
                "bf16": {"enabled": True},
                "steps_per_print": 10 ** 9,
                "telemetry": {"enabled": True,
                              "output_path": str(tmp_path),
                              "job_name": "oap",
                              "report_steps": 10 ** 9},
            }, mesh=build_mesh())
        r = np.random.default_rng(0)
        batch = (jnp.asarray(r.standard_normal((16, 32)), jnp.float32),
                 jnp.asarray(r.standard_normal((16, 8)), jnp.float32))
        eng.train_batch(batch)
        eng._maybe_build_cost_model()
        payload = eng.telemetry.cost_model_payload
        assert payload is not None
        oap = payload.get("optimizer_apply")
        assert oap is not None and oap["mode"] == "one_pass"
        # bf16 + fp32 masters + clip: the honest delta is the standalone
        # cast pass's param re-read — a modest >1.0 ratio (the ~2.5x
        # class is fp16-only; master-free bf16 is 1.0).
        assert oap["per_replica"]["ratio_two_over_one"] > 1.05
        assert oap["per_replica"]["active"] == \
            oap["per_replica"]["one_pass"]
        assert oap["zero_shard_divisor"] == 8
        assert oap["active_bytes_per_device"] * 8 <= \
            oap["per_replica"]["active"] + 8
        eng.telemetry.close()


class TestBenchGateKernels:
    def _write(self, tmp_path, name, doc):
        import json as _json
        p = tmp_path / name
        p.write_text(_json.dumps(doc))
        return str(p)

    def test_kernel_speedup_extracted_and_gated(self, tmp_path):
        bg = load_bench_gate()
        assert bg.extract_metrics(
            {"kernels": {"fused_speedup": 1.2}})["kernel_speedup"] == 1.2
        assert bg.extract_metrics(
            {"parsed": {"kernels": {"fused_speedup": 1.1}}}
        )["kernel_speedup"] == 1.1
        old = self._write(tmp_path, "old.json",
                          {"kernels": {"fused_speedup": 1.20}})
        bad = self._write(tmp_path, "bad.json",
                          {"kernels": {"fused_speedup": 1.00}})
        ok = self._write(tmp_path, "ok.json",
                         {"kernels": {"fused_speedup": 1.15}})
        assert bg.main([old, bad]) == 1          # -17% rel: regression
        assert bg.main([old, ok]) == 0           # -4% rel: within floor

    def test_pre_kernel_rounds_skip_never_fail(self, tmp_path):
        bg = load_bench_gate()
        old = self._write(tmp_path, "old.json", {"mfu": 0.5})
        new = self._write(tmp_path, "new.json",
                          {"mfu": 0.5,
                           "kernels": {"fused_speedup": 1.03}})
        assert bg.main([old, new]) == 0

    def test_recorded_r06_gates_against_r05(self):
        """The in-tree BENCH_r05 -> BENCH_r06 pair must pass the gate
        (r06 is the honestly-labeled projected kernel round)."""
        import json as _json
        bg = load_bench_gate()
        r5 = os.path.join(REPO, "BENCH_r05.json")
        r6 = os.path.join(REPO, "BENCH_r06.json")
        assert os.path.exists(r6), "run ablate_fused_ln.py --record"
        assert bg.main([r5, r6]) == 0
        rec = _json.load(open(r6))["parsed"]
        assert rec.get("projected") is True      # honesty label
        assert rec["kernels"]["fused_speedup"] > 1.0
