"""Block-sparse attention tests.

Mirrors reference tests/unit/test_sparse_attention.py: layout generators'
invariants + numerical comparison of the sparse kernel against a dense
masked softmax reference.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, SparseSelfAttention, VariableSparsityConfig,
    sparse_attention)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import \
    sparse_attention_reference

BLOCK = 16  # small blocks so CPU tests stay fast; TPU default is 128


class TestLayouts:
    def test_dense_all_ones(self):
        lay = DenseSparsityConfig(num_heads=2, block=BLOCK).make_layout(64)
        assert lay.shape == (2, 4, 4) and lay.all()

    @pytest.mark.parametrize("attention", ["bidirectional", "unidirectional"])
    def test_fixed_diagonal_and_locality(self, attention):
        cfg = FixedSparsityConfig(num_heads=2, block=BLOCK,
                                  num_local_blocks=2, attention=attention)
        lay = cfg.make_layout(BLOCK * 8)
        # every query block sees itself (softmax never empty)
        assert all(lay[0, i, i] for i in range(8))
        if attention == "unidirectional":
            assert not np.triu(lay[0], k=1).any(), "causal layout leaked future"

    def test_fixed_global_patterns_per_head(self):
        cfg = FixedSparsityConfig(num_heads=4, block=BLOCK,
                                  different_layout_per_head=True,
                                  num_local_blocks=4, num_global_blocks=1,
                                  num_different_global_patterns=4)
        lay = cfg.make_layout(BLOCK * 8)
        # heads must not all share one layout
        assert not all((lay[0] == lay[h]).all() for h in range(1, 4))

    def test_variable_windows_and_globals(self):
        cfg = VariableSparsityConfig(num_heads=2, block=BLOCK,
                                     local_window_blocks=[1, 2],
                                     global_block_indices=[0])
        lay = cfg.make_layout(BLOCK * 8)
        assert lay[0, :, 0].all(), "global column 0 missing"
        assert all(lay[0, i, i] for i in range(8))

    def test_bigbird_window_global_random(self):
        cfg = BigBirdSparsityConfig(num_heads=2, block=BLOCK,
                                    num_random_blocks=1,
                                    num_sliding_window_blocks=3,
                                    num_global_blocks=1)
        lay = cfg.make_layout(BLOCK * 8)
        assert lay[0, :, 0].all() and lay[0, 0, :].all()
        for i in range(1, 7):
            assert lay[0, i, i - 1] and lay[0, i, i] and lay[0, i, i + 1]

    def test_bslongformer(self):
        cfg = BSLongformerSparsityConfig(num_heads=2, block=BLOCK,
                                         num_sliding_window_blocks=3,
                                         global_block_indices=[0])
        lay = cfg.make_layout(BLOCK * 8)
        assert lay[0, :, 0].all() and lay[0, 0, :].all()

    def test_indivisible_seq_rejected(self):
        with pytest.raises(ValueError):
            DenseSparsityConfig(num_heads=1, block=BLOCK).make_layout(BLOCK + 3)


class TestSparseKernel:
    """Numerical parity with the dense masked reference. block=16 layouts
    take the dense fallback; block=128 layouts drive the REAL layout-gated
    Pallas kernel (interpret mode on CPU) — see TestSparsePallasPath."""

    def _qkv(self, B=2, S=128, nH=2, D=32):
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        return [jax.random.normal(k, (B, S, nH, D), jnp.float32) * 0.5
                for k in ks]

    @pytest.mark.parametrize("cfg_cls,kw", [
        (FixedSparsityConfig, dict(num_local_blocks=2)),
        (BigBirdSparsityConfig, dict(num_random_blocks=1,
                                     num_sliding_window_blocks=3,
                                     num_global_blocks=1)),
        (BSLongformerSparsityConfig, dict(num_sliding_window_blocks=3)),
    ])
    def test_matches_dense_reference(self, cfg_cls, kw):
        q, k, v = self._qkv()
        layout = cfg_cls(num_heads=2, block=BLOCK, **kw).make_layout(128)
        got = sparse_attention(q, k, v, jnp.asarray(layout))
        want = sparse_attention_reference(q, k, v, layout)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-2, atol=2e-2)

    def test_dense_layout_equals_full_attention(self):
        from deepspeed_tpu.models.transformer import dense_attention
        q, k, v = self._qkv()
        layout = DenseSparsityConfig(num_heads=2, block=BLOCK).make_layout(128)
        got = sparse_attention(q, k, v, jnp.asarray(layout))
        want = dense_attention(q, k, v, None, False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-2, atol=2e-2)

    def test_grads_flow(self):
        q, k, v = self._qkv(B=1, S=64, nH=2, D=16)
        layout = FixedSparsityConfig(num_heads=2, block=BLOCK,
                                     num_local_blocks=2).make_layout(64)

        def loss(q, k, v):
            return jnp.sum(sparse_attention(q, k, v, jnp.asarray(layout)) ** 2)
        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        assert all(np.isfinite(np.asarray(g)).all() for g in grads)

    def test_module_with_padding_mask(self):
        q, k, v = self._qkv()
        attn = SparseSelfAttention(
            FixedSparsityConfig(num_heads=2, block=BLOCK, num_local_blocks=2))
        mask = jnp.ones((2, 128), jnp.int32).at[:, 100:].set(0)
        out = attn(q, k, v, key_padding_mask=mask)
        assert out.shape == q.shape
        assert np.isfinite(np.asarray(out)).all()

    def test_mismatched_layout_rejected(self):
        from deepspeed_tpu.ops.flash_attention import flash_attention
        q, k, v = self._qkv(S=256, nH=4)
        bad = FixedSparsityConfig(num_heads=2, block=128,
                                  num_local_blocks=2).make_layout(256)
        with pytest.raises(ValueError):
            flash_attention(q, k, v, layout=jnp.asarray(bad))


class TestSparsePallasPath:
    """Exercise the REAL layout-gated Pallas kernels (block=128, so the
    128-alignment guard passes; runs in interpret mode on CPU)."""

    def _qkv(self, B=1, S=512, nH=2, D=64):
        ks = jax.random.split(jax.random.PRNGKey(1), 3)
        return [jax.random.normal(k, (B, S, nH, D), jnp.float32) * 0.5
                for k in ks]

    @pytest.mark.parametrize("causal", [False, True])
    def test_kernel_matches_reference(self, causal):
        q, k, v = self._qkv()
        layout = FixedSparsityConfig(
            num_heads=2, block=128, num_local_blocks=2,
            attention="unidirectional" if causal else "bidirectional"
        ).make_layout(512)
        got = sparse_attention(q, k, v, jnp.asarray(layout), causal=causal)
        from deepspeed_tpu.models.transformer import dense_attention
        from deepspeed_tpu.ops.flash_attention import _layout_to_mask
        want = dense_attention(q, k, v, _layout_to_mask(layout, 512, None),
                               causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-2, atol=2e-2)

    @pytest.mark.slow
    def test_kernel_grads_match_reference(self):
        q, k, v = self._qkv()
        layout = FixedSparsityConfig(num_heads=2, block=128,
                                     num_local_blocks=2).make_layout(512)
        jl = jnp.asarray(layout)

        def loss_sparse(q, k, v):
            return jnp.sum(sparse_attention(q, k, v, jl) ** 2)

        def loss_ref(q, k, v):
            from deepspeed_tpu.models.transformer import dense_attention
            from deepspeed_tpu.ops.flash_attention import _layout_to_mask
            return jnp.sum(dense_attention(
                q, k, v, _layout_to_mask(layout, 512, None), False) ** 2)

        gs = jax.grad(loss_sparse, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gs, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-2, atol=5e-2)

    def test_per_head_layouts(self):
        q, k, v = self._qkv()
        layout = FixedSparsityConfig(
            num_heads=2, block=128, num_local_blocks=2, num_global_blocks=1,
            different_layout_per_head=True,
            num_different_global_patterns=2).make_layout(512)
        assert not (layout[0] == layout[1]).all()
        got = sparse_attention(q, k, v, jnp.asarray(layout))
        from deepspeed_tpu.models.transformer import dense_attention
        from deepspeed_tpu.ops.flash_attention import _layout_to_mask
        want = dense_attention(q, k, v, _layout_to_mask(layout, 512, None),
                               False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-2, atol=2e-2)


class TestWidenedKBlocks:
    """K-widened LUT kernels (one grid step covers `widen` adjacent
    k-blocks, dead sub-blocks softmax-masked) must match the 1-wide path
    exactly, for outputs AND grads, with and without causal."""

    @pytest.mark.parametrize("widen,causal", [(2, False), (2, True),
                                              (4, True)])
    @pytest.mark.slow
    def test_widened_matches_unwidened(self, widen, causal):
        import math
        from deepspeed_tpu.ops.sparse_flash import sparse_flash_attention
        rng = np.random.default_rng(3)
        nH, S, D, block = 2, 1024, 64, 128
        nB = S // block
        lay = (rng.random((nH, nB, nB)) < 0.3)
        lay |= np.eye(nB, dtype=bool)[None]          # no empty rows/cols
        lay[:, :, 0] = True
        layout = lay.astype(np.int32)
        q = jnp.asarray(rng.standard_normal((nH, S, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((nH, S, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((nH, S, D)), jnp.float32)
        scale = 1.0 / math.sqrt(D)

        def loss(w):
            def f(q, k, v):
                o = sparse_flash_attention(q, k, v, layout, causal=causal,
                                           scale=scale, widen=w)
                return jnp.sum(o.astype(jnp.float32) ** 2)
            return f

        l1, g1 = jax.value_and_grad(loss(1), argnums=(0, 1, 2))(q, k, v)
        lw, gw = jax.value_and_grad(loss(widen), argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(float(lw), float(l1), rtol=1e-5)
        for a, b, name in zip(gw, g1, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4,
                                       err_msg=f"d{name} widen={widen}")


class TestSuperTiles:
    """q x k super-tiled LUT kernels (2-D widening: one grid step covers a
    qwiden x widen block tile, dead sub-blocks softmax-masked via the 2-D
    bitmask) must match the 1x1 path exactly for outputs AND grads."""

    @pytest.mark.parametrize("qw,kw,causal", [(2, 1, False), (2, 2, True),
                                              (4, 2, False), (2, 4, True)])
    @pytest.mark.slow
    def test_supertile_matches_base(self, qw, kw, causal):
        import math
        from deepspeed_tpu.ops.sparse_flash import sparse_flash_attention
        rng = np.random.default_rng(5)
        nH, S, D, block = 2, 1024, 64, 128
        nB = S // block
        lay = (rng.random((nH, nB, nB)) < 0.3)
        lay |= np.eye(nB, dtype=bool)[None]
        lay[:, :, 0] = True
        layout = lay.astype(np.int32)
        q = jnp.asarray(rng.standard_normal((nH, S, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((nH, S, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((nH, S, D)), jnp.float32)
        scale = 1.0 / math.sqrt(D)

        def loss(w, q_w):
            def f(q, k, v):
                o = sparse_flash_attention(q, k, v, layout, causal=causal,
                                           scale=scale, widen=w, qwiden=q_w)
                return jnp.sum(o.astype(jnp.float32) ** 2)
            return f

        l1, g1 = jax.value_and_grad(loss(1, 1), argnums=(0, 1, 2))(q, k, v)
        lw, gw = jax.value_and_grad(loss(kw, qw), argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(float(lw), float(l1), rtol=1e-5)
        for a, b, name in zip(gw, g1, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4,
                                       err_msg=f"d{name} tile={qw}x{kw}")

    def test_pick_tile_prefers_supertiles_on_banded_layouts(self):
        from deepspeed_tpu.ops.sparse_flash import pick_tile
        nB = 64
        band = np.zeros((1, nB, nB), np.int32)
        for i in range(nB):
            band[0, i, max(0, i - 3): i + 1] = 1
        band[0, :, 0] = 1
        qw, kw = pick_tile(band, block=128)
        assert qw * kw > 1, (qw, kw)   # fixed cost dominates 1x1 on bands
