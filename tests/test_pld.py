"""Progressive layer drop: theta schedule + actual stochastic layer skip.

Reference: progressive_layer_drop.py:29-37 (theta(t) schedule) +
engine.py:826-827 (state injected into every forward). The depth test
builds blocks whose only effect is adding proj_bias=1 to the stream, so
(output - input) counts exactly how many layers EXECUTED.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.transformer import (TransformerConfig, apply_blocks,
                                              init_block_params)
from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop


def _counting_blocks(L, H):
    """Blocks where an executed layer adds exactly +1 everywhere: all
    kernels zero, proj_bias one, dropout off."""
    cfg = TransformerConfig(hidden_size=H, num_heads=2, num_layers=L,
                            hidden_dropout=0.0, attn_dropout=0.0,
                            max_seq_length=8, pre_layer_norm=True)
    p = init_block_params(jax.random.PRNGKey(0), cfg)
    zeros = {k: jnp.zeros_like(v) for k, v in p.items()}
    zeros["ln1_scale"] = p["ln1_scale"]
    zeros["ln2_scale"] = p["ln2_scale"]
    zeros["proj_bias"] = jnp.ones_like(p["proj_bias"])
    return zeros, cfg


import functools


@functools.lru_cache(maxsize=4)
def _depth_fn(cfg):
    @jax.jit
    def run(stacked, theta, rng):
        x = jnp.zeros((1, 4, cfg.hidden_size), jnp.float32)
        out = apply_blocks(stacked, x, cfg, rng=rng, deterministic=False,
                           pld_theta=theta)
        return out.mean()
    return run


def _depth(stacked, cfg, theta, rng):
    run = _depth_fn(cfg)
    return float(run(stacked, jnp.asarray(theta, jnp.float32), rng))


def test_theta_schedule():
    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
    assert abs(pld.theta_at(0) - 1.0) < 1e-6
    assert pld.theta_at(10) < pld.theta_at(1) <= 1.0
    assert abs(pld.theta_at(10 ** 6) - 0.5) < 1e-6


def test_theta_one_keeps_all_layers():
    stacked, cfg = _counting_blocks(L=8, H=16)
    for seed in range(3):
        d = _depth(stacked, cfg, 1.0, jax.random.PRNGKey(seed))
        assert abs(d - 8.0) < 1e-5, d


def test_expected_depth_tracks_theta():
    """keep_prob_l = 1 - (l+1)/L (1-theta) -> E[depth] = L - (L+1)/2 (1-theta)."""
    stacked, cfg = _counting_blocks(L=8, H=16)
    for theta, expect in [(0.0, 8 - 4.5), (0.5, 8 - 2.25)]:
        depths = [_depth(stacked, cfg, theta, jax.random.PRNGKey(s))
                  for s in range(60)]
        assert abs(np.mean(depths) - expect) < 0.7, (theta, np.mean(depths))


def test_pld_off_is_default():
    stacked, cfg = _counting_blocks(L=4, H=16)
    x = jnp.zeros((1, 4, cfg.hidden_size), jnp.float32)
    out = apply_blocks(stacked, x, cfg, rng=jax.random.PRNGKey(0),
                       deterministic=False)    # no pld_theta
    assert abs(float(out.mean()) - 4.0) < 1e-5


@pytest.mark.slow
def test_engine_pld_trains():
    """Engine with PLD enabled: theta threads into gpt2_loss_fn and the
    model still trains."""
    from deepspeed_tpu.models import GPT2_CONFIGS, gpt2_init, gpt2_loss_fn
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.parallel.topology import build_mesh
    cfg = GPT2_CONFIGS["gpt2-tiny"]
    mesh = build_mesh(devices=jax.devices()[:1])
    eng = DeepSpeedEngine(
        model=gpt2_loss_fn(cfg),
        model_params=gpt2_init(jax.random.PRNGKey(0), cfg),
        config={"train_batch_size": 4, "train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "progressive_layer_drop": {"enabled": True, "theta": 0.5,
                                           "gamma": 0.01},
                "steps_per_print": 10 ** 9}, mesh=mesh)
    assert eng.progressive_layer_drop is not None
    rng = np.random.default_rng(0)
    batch = rng.integers(0, cfg.vocab_size,
                         size=(4, cfg.max_seq_length + 1)).astype(np.int32)
    losses = [float(jax.device_get(eng.train_batch(batch)))
              for _ in range(10)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
