"""LR schedule tests — parity with reference tests/unit/test_lr_schedulers.py."""
import math

import jax.numpy as jnp
import pytest

from deepspeed_tpu.runtime.lr_schedules import (LRRangeTest, OneCycle, WarmupLR,
                                                WarmupDecayLR, get_lr_schedule,
                                                VALID_LR_SCHEDULES)


class TestWarmupLR:
    def test_endpoints(self):
        s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=0.1, warmup_num_steps=100)
        assert s.lr_at(0) < 0.02
        assert s.lr_at(100) == pytest.approx(0.1)
        assert s.lr_at(10_000) == pytest.approx(0.1)

    def test_monotone(self):
        s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=0.1, warmup_num_steps=50)
        lrs = [s.lr_at(t) for t in range(0, 60)]
        assert all(b >= a - 1e-9 for a, b in zip(lrs, lrs[1:]))

    def test_linear(self):
        s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=1.0, warmup_num_steps=10,
                     warmup_type="linear")
        assert s.lr_at(5) == pytest.approx(0.5)

    def test_traced_matches_python(self):
        s = WarmupLR(warmup_min_lr=0.01, warmup_max_lr=0.1, warmup_num_steps=100)
        for t in [0, 1, 50, 99, 100, 500]:
            assert float(s.lr_at(jnp.array(t, jnp.float32))) == pytest.approx(
                s.lr_at(t), rel=1e-5)


class TestWarmupDecayLR:
    def test_decays_to_zero(self):
        s = WarmupDecayLR(total_num_steps=100, warmup_min_lr=0.0,
                          warmup_max_lr=0.1, warmup_num_steps=10)
        assert s.lr_at(10) == pytest.approx(0.1)
        assert s.lr_at(55) == pytest.approx(0.1 * 0.5, rel=1e-6)
        assert s.lr_at(100) == pytest.approx(0.0)
        assert s.lr_at(200) == pytest.approx(0.0)  # clamped, never negative

    def test_traced_matches_python(self):
        s = WarmupDecayLR(total_num_steps=100, warmup_max_lr=0.1, warmup_num_steps=10)
        for t in [0, 5, 10, 50, 100, 150]:
            assert float(s.lr_at(jnp.array(t, jnp.float32))) == pytest.approx(
                s.lr_at(t), rel=1e-5, abs=1e-8)

    def test_decays_to_min_lr_not_zero(self):
        # Reference decays lr to warmup_min_lr, never below
        # (lr_schedules.py:802-809).
        s = WarmupDecayLR(total_num_steps=100, warmup_min_lr=0.02,
                          warmup_max_lr=0.1, warmup_num_steps=10)
        assert s.lr_at(100) == pytest.approx(0.02)
        assert s.lr_at(1000) == pytest.approx(0.02)
        assert all(s.lr_at(t) >= 0.02 - 1e-9 for t in range(0, 200, 7))


class TestLRRangeTest:
    def test_continuous(self):
        s = LRRangeTest(lr_range_test_min_lr=0.01, lr_range_test_step_size=10,
                        lr_range_test_step_rate=1.0)
        assert s.lr_at(0) == pytest.approx(0.01)
        assert s.lr_at(10) == pytest.approx(0.02)

    def test_staircase(self):
        s = LRRangeTest(lr_range_test_min_lr=0.01, lr_range_test_step_size=10,
                        lr_range_test_step_rate=1.0, lr_range_test_staircase=True)
        assert s.lr_at(9) == pytest.approx(0.01)
        assert s.lr_at(10) == pytest.approx(0.02)

    def test_bad_step_size(self):
        with pytest.raises(ValueError):
            LRRangeTest(lr_range_test_step_size=0)


class TestOneCycle:
    def test_triangle(self):
        s = OneCycle(cycle_min_lr=0.01, cycle_max_lr=0.1, cycle_first_step_size=100)
        assert s.lr_at(0) == pytest.approx(0.01)
        assert s.lr_at(100) == pytest.approx(0.1)
        assert s.lr_at(200) == pytest.approx(0.01)

    def test_momentum_inverse(self):
        s = OneCycle(cycle_min_lr=0.01, cycle_max_lr=0.1, cycle_first_step_size=100,
                     cycle_min_mom=0.85, cycle_max_mom=0.99)
        assert s.mom_at(0) == pytest.approx(0.99)
        assert s.mom_at(100) == pytest.approx(0.85)
        assert s.mom_at(200) == pytest.approx(0.99)


class TestFactory:
    def test_by_name(self):
        s = get_lr_schedule("WarmupLR", {"warmup_max_lr": 0.1, "warmup_num_steps": 10})
        assert isinstance(s, WarmupLR)

    def test_unknown(self):
        with pytest.raises(ValueError):
            get_lr_schedule("Bogus", {})

    def test_stateful_step_api(self):
        s = get_lr_schedule("WarmupLR", {"warmup_max_lr": 0.1, "warmup_num_steps": 10})
        s.step()
        s.step()
        assert s.last_batch_iteration == 1
        sd = s.state_dict()
        s2 = get_lr_schedule("WarmupLR", {"warmup_max_lr": 0.1, "warmup_num_steps": 10})
        s2.load_state_dict(sd)
        assert s2.last_batch_iteration == 1
        assert len(VALID_LR_SCHEDULES) == 4
