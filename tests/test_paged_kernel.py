"""Pallas paged-attention kernel (PR-17 tentpole).

The load-bearing invariants:

1. **Parity** — the table-sliced Pallas kernel (interpret mode on this
   CPU mesh — the same program a TPU compiles) matches the one-hot
   ``kv_cache.paged_attend`` baseline: fp32 logits at tight tolerance,
   bf16 pools at ulp-bounded tolerance (the baseline combines values in
   bf16, the kernel accumulates fp32 — the kernel is the MORE accurate
   side), across ragged contexts, partial final blocks, dead streams,
   CoW-shared block ids, and the K=k+1 verify-row variant.
2. **Bit-identity** — greedy token streams (plain and speculative) are
   identical with the kernel on and off; the PR-12 shared-prefix
   acceptance stream runs kernel-on under ``fail_on_recompile`` with
   zero post-warmup retraces.
3. **Gating** — ``paged_kernel_enabled`` honours True/False force, the
   ``DS_PAGED_KERNEL`` env override, and "auto" = TPU-on/CPU-off.
4. **Cost model** — analytic attend FLOPs / HBM bytes scale with
   ceil(context/bs)*bs on the kernel side and with pool CAPACITY on the
   one-hot side, and the engine feeds both into the serving aggregator.
"""
import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference import InferenceEngine, shared_prefix_requests
from deepspeed_tpu.inference import kv_cache
from deepspeed_tpu.models.gpt2 import GPT2_CONFIGS, gpt2_init
from deepspeed_tpu.ops import paged_attention as pa
from deepspeed_tpu.ops.flash_attention import NEG_INF

CFG32 = dataclasses.replace(GPT2_CONFIGS["gpt2-tiny"], dtype=jnp.float32)


@pytest.fixture(scope="module")
def params32():
    return gpt2_init(jax.random.PRNGKey(0), CFG32)


# --------------------------------------------------------------------- #
# Direct kernel-vs-one-hot parity
# --------------------------------------------------------------------- #
def _ref_attend(q, pool_k, pool_v, bt, pos, scale):
    """The one-hot baseline exactly as inference/decode.py builds it."""
    J, bs = bt.shape[2], pool_k.shape[3]
    sel = kv_cache.block_select(bt, pool_k.shape[1])
    grid = jnp.arange(J * bs, dtype=jnp.int32)[None, None, None, :]
    pos_mask = grid <= pos[..., None]
    return kv_cache.paged_attend(q, pool_k, pool_v, sel, pos_mask,
                                 scale, NEG_INF)


def _case(seed, lengths, *, K=1, nH=4, D=16, B=12, bs=8, J=4,
          kv_dtype=jnp.float32, shared_prefix_blocks=0):
    """Build a [G, Q, ...] case from per-stream context lengths.

    ``lengths[g][q]`` <= 0 marks a dead stream (DEAD_BLOCK table row).
    ``shared_prefix_blocks`` aliases the first blocks of every live
    stream in a group to the same ids — the post-CoW-fork layout where
    read-only prefix blocks stay shared.
    """
    rng = np.random.default_rng(seed)
    G, Q = len(lengths), len(lengths[0])
    pool_k = rng.standard_normal((G, B, nH, bs, D)).astype(np.float32)
    pool_v = rng.standard_normal((G, B, nH, bs, D)).astype(np.float32)
    q = rng.standard_normal((G, Q, K, nH, D)).astype(np.float32)
    bt = np.full((G, Q, J), kv_cache.DEAD_BLOCK, np.int32)
    pos = np.zeros((G, Q, K), np.int32)
    for g in range(G):
        free = list(range(B))
        shared = [free.pop() for _ in range(shared_prefix_blocks)]
        for s in range(Q):
            ctx = lengths[g][s]
            if ctx <= 0:
                continue                    # dead stream
            # K query rows sit at positions ctx-1 .. ctx-1+K-1 (the
            # verify step's per-row causal offsets).
            nblk = (ctx - 1 + K - 1) // bs + 1
            assert nblk <= J, "case exceeds table width"
            ids = (shared[:nblk] + [free.pop() for _ in
                                    range(max(0, nblk - len(shared)))])
            bt[g, s, :nblk] = ids[:nblk]
            pos[g, s] = ctx - 1 + np.arange(K)
    to_dev = lambda a: jnp.asarray(a, kv_dtype)  # noqa: E731
    return (jnp.asarray(q), to_dev(pool_k), to_dev(pool_v),
            jnp.asarray(bt), jnp.asarray(pos), 1.0 / math.sqrt(D))


class TestKernelParity:
    def test_fp32_ragged_contexts_and_partial_blocks(self):
        # Lengths straddle block boundaries: full final block (16),
        # one-row final block (17), mid-block (13), single token (1),
        # and a dead stream — the shapes the serving batch actually has.
        q, pk, pv, bt, pos, sc = _case(0, [[16, 17, 13, 1], [25, 0, 8, 5]])
        out = pa.paged_attention(q, pk, pv, bt, pos, scale=sc)
        ref = _ref_attend(q, pk, pv, bt, pos, sc)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_verify_rows_per_row_causal_offsets(self):
        # K=4 (spec_k=3 verify): row k of a stream attends through
        # position ctx-1+k — the final row can spill into a block the
        # earlier rows must not see.
        q, pk, pv, bt, pos, sc = _case(1, [[7, 15, 21], [3, 12, 0]], K=4)
        out = pa.paged_attention(q, pk, pv, bt, pos, scale=sc)
        ref = _ref_attend(q, pk, pv, bt, pos, sc)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_bf16_pool_dequant_ulp_bounded(self):
        # bf16 pools: the kernel upcasts tiles in-VMEM and accumulates
        # fp32; the baseline's value combine runs in bf16. They agree to
        # bf16 resolution (the kernel side is the more accurate one).
        q, pk, pv, bt, pos, sc = _case(2, [[9, 18, 24, 2]],
                                       kv_dtype=jnp.bfloat16)
        out = pa.paged_attention(q, pk, pv, bt, pos, scale=sc)
        ref = _ref_attend(q, pk, pv, bt, pos, sc)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32),
                                   atol=2e-2, rtol=2e-2)

    def test_cow_shared_prefix_blocks(self):
        # Post-fork layout: every live stream's first two blocks are the
        # SAME pool blocks (refcounted prefix), tails diverge.
        q, pk, pv, bt, pos, sc = _case(
            3, [[17, 20, 25]], shared_prefix_blocks=2)
        assert (np.asarray(bt)[0, :, :2] ==
                np.asarray(bt)[0, 0, :2]).all()
        out = pa.paged_attention(q, pk, pv, bt, pos, scale=sc)
        ref = _ref_attend(q, pk, pv, bt, pos, sc)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_dead_streams_emit_exact_zeros(self):
        q, pk, pv, bt, pos, sc = _case(4, [[11, 0, 0, 6]])
        out = np.asarray(pa.paged_attention(q, pk, pv, bt, pos, scale=sc))
        assert (out[0, 1] == 0.0).all() and (out[0, 2] == 0.0).all()
        assert np.abs(out[0, 0]).sum() > 0

    def test_head_block_tilings_agree(self):
        # The autotuner's candidates are tilings of the SAME math: any
        # bh dividing nH must reproduce bh=1 bit-for-bit (fp32 scratch
        # accumulation order per head is unchanged by head grouping).
        q, pk, pv, bt, pos, sc = _case(5, [[14, 22, 5, 0]])
        outs = [np.asarray(pa.paged_attention(q, pk, pv, bt, pos,
                                              scale=sc, block_heads=bh))
                for bh in (1, 2, 4)]
        np.testing.assert_array_equal(outs[0], outs[1])
        np.testing.assert_array_equal(outs[0], outs[2])


# --------------------------------------------------------------------- #
# Gating contract
# --------------------------------------------------------------------- #
class TestGating:
    def test_forced_flags_win(self, monkeypatch):
        monkeypatch.setenv("DS_PAGED_KERNEL", "1")
        assert pa.paged_kernel_enabled(False) is False
        monkeypatch.setenv("DS_PAGED_KERNEL", "0")
        assert pa.paged_kernel_enabled(True) is True

    def test_env_overrides_auto(self, monkeypatch):
        monkeypatch.setenv("DS_PAGED_KERNEL", "1")
        assert pa.paged_kernel_enabled("auto") is True
        monkeypatch.setenv("DS_PAGED_KERNEL", "0")
        assert pa.paged_kernel_enabled("auto") is False

    def test_auto_is_backend_gated(self, monkeypatch):
        monkeypatch.delenv("DS_PAGED_KERNEL", raising=False)
        expected = jax.default_backend() == "tpu"   # False on this mesh
        assert pa.paged_kernel_enabled("auto") is expected

    def test_config_validation(self, params32):
        from deepspeed_tpu.runtime.config import DeepSpeedConfigError
        with pytest.raises(DeepSpeedConfigError, match="paged_kernel"):
            InferenceEngine(CFG32, params32, config={
                "inference": {"max_slots": 2, "max_seq_len": 32,
                              "block_size": 8, "paged_kernel": "yes"}})


# --------------------------------------------------------------------- #
# Analytic cost model
# --------------------------------------------------------------------- #
class TestAttendCostModel:
    def test_kernel_bytes_scale_with_block_rounded_context(self):
        bs, nH, D = 8, 4, 16
        f = lambda ctx: pa.attend_hbm_bytes_per_token(   # noqa: E731
            nH, D, bs, context=ctx)
        # Within one block the cost is flat; crossing a boundary adds
        # exactly one block's K+V bytes.
        assert f(1) == f(8) == 2 * 8 * nH * D * 4
        assert f(9) == f(16) == 2 * f(8)
        assert f(17) - f(16) == 2 * bs * nH * D * 4
        # ceil(ctx/bs)*bs rows exactly, never pool-sized.
        assert f(25) == 2 * 32 * nH * D * 4

    def test_onehot_bytes_are_pool_capacity_flat(self):
        bs, nH, D, B = 8, 4, 16, 64
        b = pa.attend_hbm_bytes_per_token(nH, D, bs, pool_blocks=B)
        assert b == 2 * B * bs * nH * D * 4
        # Independent of any context — it streams the whole pool.
        assert b > pa.attend_hbm_bytes_per_token(nH, D, bs, context=B * bs
                                                 - bs + 1) - 1

    def test_flops_and_arg_validation(self):
        assert pa.attend_flops_per_token(4, 16, 8, context=8) \
            == 4 * 4 * 16 * 8
        assert pa.attend_flops_per_token(4, 16, 8, pool_blocks=2,
                                         num_layers=3) \
            == 4 * 4 * 16 * 16 * 3
        with pytest.raises(ValueError, match="exactly one"):
            pa.attend_flops_per_token(4, 16, 8)
        with pytest.raises(ValueError, match="exactly one"):
            pa.attend_hbm_bytes_per_token(4, 16, 8, context=4,
                                          pool_blocks=2)


# --------------------------------------------------------------------- #
# Engine-level: kernel on vs off on the dp=8 mesh
# --------------------------------------------------------------------- #
def _engine(params, *, kernel, slots=8, max_len=64, chunk=8,
            block_size=8, spec_k=0, **tel):
    config = {"inference": {"max_slots": slots, "max_seq_len": max_len,
                            "prefill_chunk": chunk,
                            "block_size": block_size,
                            "spec_k": spec_k, "paged_kernel": kernel}}
    config.update(tel)
    return InferenceEngine(CFG32, params, config=config)


def _prompt(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, CFG32.vocab_size, size=n).astype(np.int32)


def paged_attn_bytes(sp_):
    """The engine's own live-ctx_max quote, recomputed independently."""
    return pa.attend_hbm_bytes_per_token(
        sp_.num_heads, sp_.head_dim, sp_.block_size, context=sp_.max_len,
        kv_itemsize=jnp.dtype(sp_.dtype).itemsize,
        num_layers=sp_.num_layers)


class TestEngineKernelOn:
    def test_decode_logit_parity_and_greedy_bit_identity(self, params32):
        streams, logits = {}, {}
        for kernel in (False, True):
            e = _engine(params32, kernel=kernel)
            assert e.paged_kernel is kernel
            toks, logs = [], []
            for s, n in ((0, 11), (1, 17)):   # partial + cross-block ctx
                tok, lg = e.prefill(_prompt(n, seed=s), slot=s,
                                    return_logits=True)
                e.activate_slot(s, n, tok)
                toks.append([tok])
                logs.append([np.asarray(lg)])
            for _ in range(6):
                tok, lg = e.decode_once(return_logits=True)
                for i, s in enumerate((0, 1)):
                    toks[i].append(int(np.asarray(tok)[s]))
                    logs[i].append(np.asarray(lg)[s])
            e.close()
            streams[kernel] = toks
            logits[kernel] = logs
        assert streams[True] == streams[False]      # greedy bit-identity
        for a, b in zip(logits[True], logits[False]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5)

    def test_spec_decode_streams_bit_identical(self, params32):
        emitted = {}
        for kernel in (False, True):
            e = _engine(params32, kernel=kernel, spec_k=3)
            n = 13
            tok, _ = e.prefill(_prompt(n, seed=7), slot=0,
                               return_logits=True)
            e.activate_slot(0, n, tok)
            out = [tok]
            for _ in range(4):
                toks, n_new = e.spec_decode_once()
                k = int(np.asarray(n_new)[0])
                out.extend(int(t) for t in np.asarray(toks)[0][:k])
            e.close()
            emitted[kernel] = out
        assert emitted[True] == emitted[False]

    def test_acceptance_stream_kernel_on_zero_recompiles(
            self, params32, tmp_path):
        # The PR-12 acceptance workload, kernel forced ON, retrace =
        # hard failure: proves the static-shape discipline (grid sized
        # by table WIDTH, predication for liveness) holds across chunked
        # prefill, CoW forks, spec verify, and ragged completion.
        e = _engine(params32, kernel=True, spec_k=3,
                    telemetry={"enabled": True,
                               "output_path": str(tmp_path),
                               "job_name": "pk_accept",
                               "report_steps": 10 ** 9,
                               "fail_on_recompile": True})
        report = e.serve(shared_prefix_requests(
            6, prefix_len=16, tail_len=(3, 8), max_new_tokens=4,
            vocab_size=CFG32.vocab_size))
        assert report["recompiles"] == 0
        assert report["completed"] == 6
        # The serving aggregator priced the attend both ways: the
        # structural ratio exists and the kernel side is strictly less
        # work than streaming the pool.
        assert report["attend"]["mode"] == "kernel"
        assert report["attend_work_ratio"] > 1.0
        e.close()

    def test_attend_telemetry_meta_labeled_projection(self, params32):
        e = _engine(params32, kernel=True)
        meta = e.telemetry.meta
        assert meta["paged_kernel"] is True
        for key in ("attend_flops_per_token", "attend_hbm_bytes_per_token"):
            assert meta[key]["projection"] == "analytic"
            assert meta[key]["pool_capacity"] >= meta[key]["live_ctx_max"]
        # live-ctx bound is the block-rounded max context, never pool-
        # sized: blocks_per_group * bs >= ceil(max_len/bs) * bs here.
        sp_ = e.cache_spec
        assert meta["attend_hbm_bytes_per_token"]["live_ctx_max"] == \
            paged_attn_bytes(sp_)
        e.close()
