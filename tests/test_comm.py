"""Collective API tests on the virtual 8-device CPU mesh."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from jax.experimental.shard_map import shard_map

from deepspeed_tpu.parallel import comm
from deepspeed_tpu.parallel.topology import build_mesh


def run_on_axis(mesh, fn, x, in_spec, out_spec):
    return shard_map(fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec)(x)


class TestCollectives:
    def test_all_reduce_sum(self, mesh8):
        x = jnp.arange(8.0)
        out = run_on_axis(mesh8, lambda v: comm.all_reduce(v, "data"),
                          x, P("data"), P("data"))
        np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))

    def test_all_reduce_max(self, mesh8):
        x = jnp.arange(8.0)
        out = run_on_axis(mesh8, lambda v: comm.all_reduce(v, "data", op="max"),
                          x, P("data"), P("data"))
        np.testing.assert_allclose(np.asarray(out), np.full(8, 7.0))

    def test_reduce_scatter(self, mesh8):
        # Each shard holds 8 elements; psum_scatter leaves 1 per member.
        x = jnp.ones((8, 8))
        def f(v):
            return comm.reduce_scatter(v.reshape(-1), "data")
        out = shard_map(f, mesh=mesh8, in_specs=(P("data", None),),
                        out_specs=P("data"))(x)
        np.testing.assert_allclose(np.asarray(out), np.full(8, 8.0))

    def test_all_gather(self, mesh8):
        x = jnp.arange(8.0)
        def f(v):
            return comm.all_gather(v, "data")
        out = shard_map(f, mesh=mesh8, in_specs=(P("data"),), out_specs=P("data"))(x)
        assert out.shape == (64,)
        np.testing.assert_allclose(np.asarray(out)[:8], np.arange(8.0))

    def test_broadcast(self, mesh8):
        x = jnp.arange(8.0)
        out = run_on_axis(mesh8, lambda v: comm.broadcast(v, "data", src=3),
                          x, P("data"), P("data"))
        np.testing.assert_allclose(np.asarray(out), np.full(8, 3.0))

    def test_ring_permute(self, mesh8):
        x = jnp.arange(8.0)
        out = run_on_axis(mesh8, lambda v: comm.send_to_next(v, "data", 8),
                          x, P("data"), P("data"))
        np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), 1))

    def test_send_prev_inverts_next(self, mesh8):
        x = jnp.arange(8.0)
        def f(v):
            return comm.send_to_prev(comm.send_to_next(v, "data", 8), "data", 8)
        out = run_on_axis(mesh8, f, x, P("data"), P("data"))
        np.testing.assert_allclose(np.asarray(out), np.arange(8.0))


class TestReduceScatterAllGatherVariants:
    """Tiled vs untiled and scatter_dimension edge cases — the knobs the
    ZeRO-2 explicit grad path and the audit's wire model rely on."""

    def test_reduce_scatter_tiled_dim0(self, mesh8):
        # Replicated [16, 4] input: member r keeps rows [2r, 2r+2) summed
        # over the 8 members.
        x = jnp.arange(64.0).reshape(16, 4)
        out = shard_map(
            lambda v: comm.reduce_scatter(v, "data", scatter_dimension=0),
            mesh=mesh8, in_specs=(P(),), out_specs=P("data"))(x)
        assert out.shape == (16, 4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 8.0)

    def test_reduce_scatter_tiled_dim1(self, mesh8):
        # scatter_dimension=1: the second axis splits 16 -> 2 per member.
        x = jnp.ones((4, 16))
        out = shard_map(
            lambda v: comm.reduce_scatter(v, "data", scatter_dimension=1),
            mesh=mesh8, in_specs=(P(),), out_specs=P(None, "data"))(x)
        assert out.shape == (4, 16)
        np.testing.assert_allclose(np.asarray(out), np.full((4, 16), 8.0))

    def test_reduce_scatter_untiled_drops_the_dim(self, mesh8):
        # Untiled: the scatter dim must equal the axis size and is
        # REMOVED — member r receives row r of the sum.
        x = jnp.arange(64.0).reshape(8, 8)
        out = shard_map(
            lambda v: comm.reduce_scatter(v, "data", scatter_dimension=0,
                                          tiled=False),
            mesh=mesh8, in_specs=(P(),), out_specs=P("data"))(x)
        assert out.shape == (64,)   # 8 members x [8] rows
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(x).reshape(-1) * 8.0)

    def test_all_gather_untiled_stacks_new_axis(self, mesh8):
        # Untiled all_gather stacks a fresh leading axis (vs tiled's
        # concatenate): per-member [1] -> [8, 1].
        x = jnp.arange(8.0)
        out = shard_map(
            lambda v: comm.all_gather(v, "data", tiled=False),
            mesh=mesh8, in_specs=(P("data"),), out_specs=P("data"))(x)
        assert out.shape == (64, 1)
        np.testing.assert_allclose(np.asarray(out)[:8, 0], np.arange(8.0))

    def test_all_gather_tiled_axis1(self, mesh8):
        x = jnp.arange(16.0).reshape(8, 2)
        out = shard_map(
            lambda v: comm.all_gather(v, "data", axis=1),
            mesh=mesh8, in_specs=(P("data"),), out_specs=P("data"))(x)
        # per-member [1, 2] -> [1, 16]; global [8, 16]
        assert out.shape == (8, 16)

    def test_reduce_scatter_all_gather_roundtrip(self, mesh8):
        """all_gather(reduce_scatter(x)) == psum(x) — the decomposition
        identity the ZeRO schedule is built on."""
        x = jnp.arange(128.0).reshape(16, 8)

        def f(v):
            shard = comm.reduce_scatter(v, "data", scatter_dimension=0)
            return comm.all_gather(shard, "data", axis=0)

        got = shard_map(f, mesh=mesh8, in_specs=(P(),),
                        out_specs=P("data"))(x)
        # every member ends with the full 8x-summed tensor; the global
        # view stacks 8 copies -> compare member 0's slice
        np.testing.assert_allclose(np.asarray(got)[:16], np.asarray(x) * 8.0)

    def test_reduce_scatter_indivisible_dim_raises(self, mesh8):
        # 6 % 8 != 0: the collective must refuse, not silently pad —
        # zero/partition.py routes such leaves to psum instead.
        x = jnp.ones((6, 4))
        with np.testing.assert_raises(Exception):
            shard_map(
                lambda v: comm.reduce_scatter(v, "data",
                                              scatter_dimension=0),
                mesh=mesh8, in_specs=(P(),), out_specs=P("data"))(x)


class TestAllToAll:
    """comm.all_to_all — the MoE dispatch/combine collective (the one
    wrapper that had zero direct coverage before the moe/ subsystem
    became its first real producer)."""

    def test_tiled_same_axis_is_involution(self, mesh8):
        # split == concat: applying the exchange twice is the identity —
        # the combine path of the MoE layer.
        x = jnp.arange(8 * 8 * 2.0).reshape(64, 2)

        def once(v):
            return comm.all_to_all(v, "data", 0, 0)

        def twice(v):
            return once(once(v))

        out = shard_map(twice, mesh=mesh8, in_specs=(P("data"),),
                        out_specs=P("data"))(x)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x))

    def test_tiled_exchange_layout(self, mesh8):
        # Member r's local [8, 1] block encodes r*10 + row; after the
        # exchange, member r holds row r of every source, in source
        # order — the MoE dispatch layout contract.
        x = jnp.asarray([[r * 10 + c for c in range(8)]
                         for r in range(8)], jnp.float32).reshape(64, 1)

        def f(v):
            return comm.all_to_all(v.reshape(8, 1), "data", 0, 0) \
                .reshape(8, 1)

        out = np.asarray(shard_map(f, mesh=mesh8, in_specs=(P("data"),),
                                   out_specs=P("data"))(x)).reshape(8, 8)
        for r in range(8):
            np.testing.assert_array_equal(out[r],
                                          [s * 10 + r for s in range(8)])

    def test_tiled_split_ne_concat_axis(self, mesh8):
        # split axis 0, concat axis 1: local [8, 2] -> [1, 16].
        x = jnp.ones((64, 2))

        def f(v):
            return comm.all_to_all(v, "data", 0, 1)

        out = shard_map(f, mesh=mesh8, in_specs=(P("data"),),
                        out_specs=P("data"))(x)
        assert out.shape == (8, 16)
        np.testing.assert_array_equal(np.asarray(out), np.ones((8, 16)))

    def test_untiled_unstacks_the_axis(self, mesh8):
        # Untiled: split dim must equal the axis size and is REMOVED;
        # member r receives element r of every source stacked on a
        # fresh leading axis.
        x = jnp.arange(64.0).reshape(8, 8)   # member r holds row r

        def f(v):
            return comm.all_to_all(v[0], "data", 0, 0, tiled=False)

        out = np.asarray(shard_map(f, mesh=mesh8, in_specs=(P("data"),),
                                   out_specs=P("data"))(x))
        # member r's block is column r of the global matrix
        np.testing.assert_array_equal(out[:8], np.asarray(x)[:, 0])

    def test_grad_of_alltoall_is_alltoall(self, mesh8):
        # The vjp of an all-to-all is an all-to-all (what makes the MoE
        # backward re-exchange): grad of sum(w * a2a(x)) w.r.t. x is
        # a2a^{-1}(w) == a2a(w) for the symmetric exchange.
        w = jnp.arange(64.0)

        def loss(x):
            def f(v, wv):
                part = jnp.sum(comm.all_to_all(v, "data", 0, 0) * wv)
                return jax.lax.psum(part, "data")
            return shard_map(
                f, mesh=mesh8, in_specs=(P("data"), P("data")),
                out_specs=P(), check_rep=False)(x, w)

        g = jax.grad(loss)(jnp.zeros((64,)))
        expect = shard_map(lambda v: comm.all_to_all(v, "data", 0, 0),
                           mesh=mesh8, in_specs=(P("data"),),
                           out_specs=P("data"))(w)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(expect))


class TestEnvironment:
    def test_eight_virtual_devices(self):
        assert jax.device_count() == 8

    def test_world_helpers(self):
        assert comm.get_world_size() == 8
        assert comm.get_process_index() == 0
