"""Collective API tests on the virtual 8-device CPU mesh."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from jax.experimental.shard_map import shard_map

from deepspeed_tpu.parallel import comm
from deepspeed_tpu.parallel.topology import build_mesh


def run_on_axis(mesh, fn, x, in_spec, out_spec):
    return shard_map(fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec)(x)


class TestCollectives:
    def test_all_reduce_sum(self, mesh8):
        x = jnp.arange(8.0)
        out = run_on_axis(mesh8, lambda v: comm.all_reduce(v, "data"),
                          x, P("data"), P("data"))
        np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))

    def test_all_reduce_max(self, mesh8):
        x = jnp.arange(8.0)
        out = run_on_axis(mesh8, lambda v: comm.all_reduce(v, "data", op="max"),
                          x, P("data"), P("data"))
        np.testing.assert_allclose(np.asarray(out), np.full(8, 7.0))

    def test_reduce_scatter(self, mesh8):
        # Each shard holds 8 elements; psum_scatter leaves 1 per member.
        x = jnp.ones((8, 8))
        def f(v):
            return comm.reduce_scatter(v.reshape(-1), "data")
        out = shard_map(f, mesh=mesh8, in_specs=(P("data", None),),
                        out_specs=P("data"))(x)
        np.testing.assert_allclose(np.asarray(out), np.full(8, 8.0))

    def test_all_gather(self, mesh8):
        x = jnp.arange(8.0)
        def f(v):
            return comm.all_gather(v, "data")
        out = shard_map(f, mesh=mesh8, in_specs=(P("data"),), out_specs=P("data"))(x)
        assert out.shape == (64,)
        np.testing.assert_allclose(np.asarray(out)[:8], np.arange(8.0))

    def test_broadcast(self, mesh8):
        x = jnp.arange(8.0)
        out = run_on_axis(mesh8, lambda v: comm.broadcast(v, "data", src=3),
                          x, P("data"), P("data"))
        np.testing.assert_allclose(np.asarray(out), np.full(8, 3.0))

    def test_ring_permute(self, mesh8):
        x = jnp.arange(8.0)
        out = run_on_axis(mesh8, lambda v: comm.send_to_next(v, "data", 8),
                          x, P("data"), P("data"))
        np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), 1))

    def test_send_prev_inverts_next(self, mesh8):
        x = jnp.arange(8.0)
        def f(v):
            return comm.send_to_prev(comm.send_to_next(v, "data", 8), "data", 8)
        out = run_on_axis(mesh8, f, x, P("data"), P("data"))
        np.testing.assert_allclose(np.asarray(out), np.arange(8.0))


class TestEnvironment:
    def test_eight_virtual_devices(self):
        assert jax.device_count() == 8

    def test_world_helpers(self):
        assert comm.get_world_size() == 8
        assert comm.get_process_index() == 0
