"""Capability probes for jax-version-dependent features.

Some tier-1 tests need a *partially-manual* shard_map — a manual pipe/seq
axis wrapped around GSPMD-auto dp/mp axes of size > 1. Old jax (< the
`jax.shard_map` API, e.g. 0.4.37) cannot compile these programs: its
experimental `auto=` path CHECK-fails inside XLA, so `parallel/comm.py`
raises NotImplementedError instead of aborting the interpreter.

These probes TRY the feature once (build + trace + compile a minimal
program) and cache the answer, so the skip tracks actual capability, not a
version string — upgrading jax un-skips the tests with no edits here.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


@functools.lru_cache(maxsize=None)
def partial_auto_skip_reason():
    """None when this jax can compile a shard_map with one manual axis and
    one auto (GSPMD) axis of size > 1 — the shape every pp>1 x dp>1 /
    sp>1 x dp>1 program in this repo lowers to. Otherwise the skip reason,
    naming the ACTUAL blocker (device count vs jax capability)."""
    if len(jax.devices()) < 4:
        return ("partial-auto shard_map probe needs >= 4 devices (a 2x2 "
                f"manual x auto mesh); only {len(jax.devices())} visible — "
                "run under the 8-device CPU mesh (tests/conftest.py)")
    from deepspeed_tpu.parallel import comm

    devs = np.asarray(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("manual", "auto"))
    x = jnp.arange(8, dtype=jnp.float32).reshape(2, 4)
    try:
        f = comm.shard_map(
            lambda a: jax.lax.psum(a, "manual"), mesh=mesh,
            in_specs=P("manual"), out_specs=P(),
            axis_names={"manual"}, check_vma=False)
        jax.jit(f).lower(x).compile()
        return None
    except NotImplementedError:
        return ("this jax cannot compile a partially-manual shard_map "
                "(manual pipe/seq axis + auto dp/mp axes > 1); capability "
                "probe failed — upgrade jax (the newer jax.shard_map API) "
                "to run this test")
    except Exception as e:   # pragma: no cover - any other failure
        return ("partial-auto shard_map capability probe failed with "
                f"{type(e).__name__}: {e}")


def partial_auto_shard_map_supported() -> bool:
    return partial_auto_skip_reason() is None


PARTIAL_AUTO_SKIP_REASON = partial_auto_skip_reason() or ""


@functools.lru_cache(maxsize=None)
def fused_elementwise_skip_reason():
    """None when this backend can compile the fused elementwise Pallas
    kernels (interpret mode on CPU, native on TPU) — probed by building
    a minimal fused LayerNorm program, so the skip tracks actual
    capability, not a platform string."""
    try:
        import jax.numpy as jnp
        from deepspeed_tpu.ops.fused_elementwise import fused_layer_norm
        x = jnp.ones((8, 128), jnp.float32)
        s = jnp.ones((128,), jnp.float32)
        b = jnp.zeros((128,), jnp.float32)
        jax.jit(lambda x, s, b: fused_layer_norm(x, s, b)) \
            .lower(x, s, b).compile()
        return None
    except Exception as e:   # pragma: no cover - exotic backends only
        return ("fused elementwise Pallas kernels cannot compile on this "
                f"backend: {type(e).__name__}: {e}")


def fused_elementwise_supported() -> bool:
    return fused_elementwise_skip_reason() is None
