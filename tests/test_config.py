"""Config system tests — parity with reference tests/unit/test_config.py and
test_ds_config.py (batch triple inference, duplicate keys, zero config)."""
import json

import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.runtime.config_utils import loads_config_json


def make_cfg(d, world_size=1):
    return DeepSpeedConfig(d, world_size=world_size)


class TestBatchConfig:
    def test_all_three_given(self):
        cfg = make_cfg({"train_batch_size": 32, "train_micro_batch_size_per_gpu": 4,
                        "gradient_accumulation_steps": 2}, world_size=4)
        assert cfg.train_batch_size == 32
        assert cfg.train_micro_batch_size_per_gpu == 4
        assert cfg.gradient_accumulation_steps == 2

    def test_infer_grad_acc(self):
        cfg = make_cfg({"train_batch_size": 32, "train_micro_batch_size_per_gpu": 4},
                       world_size=4)
        assert cfg.gradient_accumulation_steps == 2

    def test_infer_micro_batch(self):
        cfg = make_cfg({"train_batch_size": 32, "gradient_accumulation_steps": 2},
                       world_size=4)
        assert cfg.train_micro_batch_size_per_gpu == 4

    def test_infer_train_batch(self):
        cfg = make_cfg({"train_micro_batch_size_per_gpu": 4,
                        "gradient_accumulation_steps": 2}, world_size=4)
        assert cfg.train_batch_size == 32

    def test_only_train_batch(self):
        cfg = make_cfg({"train_batch_size": 32}, world_size=4)
        assert cfg.train_micro_batch_size_per_gpu == 8
        assert cfg.gradient_accumulation_steps == 1

    def test_only_micro_batch(self):
        cfg = make_cfg({"train_micro_batch_size_per_gpu": 4}, world_size=4)
        assert cfg.train_batch_size == 16
        assert cfg.gradient_accumulation_steps == 1

    def test_inconsistent_triple_raises(self):
        with pytest.raises(AssertionError):
            make_cfg({"train_batch_size": 33, "train_micro_batch_size_per_gpu": 4,
                      "gradient_accumulation_steps": 2}, world_size=4)

    def test_no_batch_info_raises(self):
        with pytest.raises(DeepSpeedConfigError):
            make_cfg({}, world_size=1)


class TestJsonHandling:
    def test_duplicate_keys_rejected(self):
        with pytest.raises(ValueError):
            loads_config_json('{"train_batch_size": 1, "train_batch_size": 2}')

    def test_file_loading(self, tmp_ds_config):
        path = tmp_ds_config({"train_batch_size": 8})
        cfg = DeepSpeedConfig(path, world_size=1)
        assert cfg.train_batch_size == 8


class TestPrecision:
    def test_fp16(self):
        cfg = make_cfg({"train_batch_size": 8, "fp16": {"enabled": True}})
        assert cfg.fp16_enabled and not cfg.bf16_enabled
        assert cfg.precision_dtype == "float16"

    def test_bf16(self):
        cfg = make_cfg({"train_batch_size": 8, "bf16": {"enabled": True}})
        assert cfg.precision_dtype == "bfloat16"

    def test_both_raises(self):
        with pytest.raises(DeepSpeedConfigError):
            make_cfg({"train_batch_size": 8, "fp16": {"enabled": True},
                      "bf16": {"enabled": True}})

    def test_fp16_defaults(self):
        cfg = make_cfg({"train_batch_size": 8, "fp16": {"enabled": True}})
        assert cfg.fp16_initial_scale_power == 32
        assert cfg.fp16_loss_scale_window == 1000
        assert cfg.fp16_hysteresis == 2
        assert cfg.fp16_min_loss_scale == 1

    def test_amp_maps_to_bf16(self):
        """amp must act, never silently no-op (reference engine.py:630-668
        wraps apex; the TPU equivalent of amp O1 is the bf16 path)."""
        cfg = make_cfg({"train_batch_size": 8, "amp": {"enabled": True}})
        assert cfg.amp_enabled and cfg.bf16_enabled
        assert cfg.precision_dtype == "bfloat16"

    def test_amp_with_bf16_is_idempotent(self):
        cfg = make_cfg({"train_batch_size": 8, "amp": {"enabled": True},
                        "bf16": {"enabled": True}})
        assert cfg.precision_dtype == "bfloat16"

    def test_amp_with_fp16_raises(self):
        with pytest.raises(DeepSpeedConfigError, match="bf16|fp16"):
            make_cfg({"train_batch_size": 8, "amp": {"enabled": True},
                      "fp16": {"enabled": True}})

    def test_amp_disabled_is_inert(self):
        cfg = make_cfg({"train_batch_size": 8, "amp": {"enabled": False}})
        assert not cfg.amp_enabled and not cfg.bf16_enabled


class TestFusedOptimizer:
    def test_fused_default_on(self):
        cfg = make_cfg({"train_batch_size": 8,
                        "optimizer": {"type": "AdamW",
                                      "params": {"lr": 1e-3}}})
        assert cfg.optimizer_fused

    def test_fused_off(self):
        cfg = make_cfg({"train_batch_size": 8,
                        "optimizer": {"type": "AdamW",
                                      "params": {"lr": 1e-3,
                                                 "fused": False}}})
        assert not cfg.optimizer_fused

    def test_build_optimizer_honors_knob(self):
        from deepspeed_tpu.ops.optimizers import build_optimizer
        fused = build_optimizer("adamw", {"lr": 1e-3})
        assert getattr(fused, "fused_apply", None) is not None
        plain = build_optimizer("adamw", {"lr": 1e-3, "fused": False})
        assert getattr(plain, "fused_apply", None) is None
        # fused never hijacks non-Adam or onebit paths
        lamb = build_optimizer("lamb", {"lr": 1e-3})
        assert getattr(lamb, "fused_apply", None) is None
        onebit = build_optimizer("onebitadam", {"lr": 1e-3})
        assert getattr(onebit, "fused_apply", None) is None


class TestZeroConfig:
    def test_defaults(self):
        cfg = make_cfg({"train_batch_size": 8})
        assert cfg.zero_optimization_stage == 0
        assert not cfg.zero_enabled

    def test_stage2_with_offload(self):
        cfg = make_cfg({"train_batch_size": 8,
                        "zero_optimization": {"stage": 2, "cpu_offload": True,
                                              "reduce_bucket_size": 1000}})
        assert cfg.zero_optimization_stage == 2
        assert cfg.zero_config.cpu_offload
        assert cfg.zero_config.reduce_bucket_size == 1000

    def test_legacy_bool(self):
        cfg = make_cfg({"train_batch_size": 8, "zero_optimization": True})
        assert cfg.zero_optimization_stage == 1

    def test_offload_overlap_knobs(self):
        from deepspeed_tpu import constants as C
        cfg = make_cfg({"train_batch_size": 8,
                        "zero_optimization": {
                            "stage": 2, "cpu_offload": True,
                            "overlap_comm": True,
                            "offload_bucket_size": 1 << 20,
                            "offload_host_threads": 3}})
        assert cfg.zero_config.overlap_comm
        assert cfg.zero_config.offload_bucket_size == 1 << 20
        assert cfg.zero_config.offload_host_threads == 3
        # defaults: serial off, ~64 MB buckets, auto threads
        dflt = make_cfg({"train_batch_size": 8,
                         "zero_optimization": {"stage": 2,
                                               "cpu_offload": True}})
        assert not dflt.zero_config.overlap_comm
        assert dflt.zero_config.offload_bucket_size == \
            C.ZERO_OFFLOAD_BUCKET_SIZE_DEFAULT
        assert dflt.zero_config.offload_host_threads == 0
        for bad in [{"offload_bucket_size": 0},
                    {"offload_bucket_size": -4},
                    {"offload_host_threads": -1}]:
            with pytest.raises(ValueError):
                make_cfg({"train_batch_size": 8,
                          "zero_optimization": {"stage": 2, **bad}})

    def test_invalid_stage(self):
        with pytest.raises(ValueError):
            make_cfg({"train_batch_size": 8, "zero_optimization": {"stage": 9}})

    def test_grad_sync_knob(self):
        from deepspeed_tpu import constants as C
        dflt = make_cfg({"train_batch_size": 8,
                         "zero_optimization": {"stage": 2}})
        assert dflt.zero_config.grad_sync == C.ZERO_GRAD_SYNC_DEFAULT == "auto"
        assert dflt.zero_config.reduce_scatter   # default on
        for mode in C.ZERO_GRAD_SYNC_MODES:
            cfg = make_cfg({"train_batch_size": 8,
                            "zero_optimization": {"stage": 2,
                                                  "grad_sync": mode}})
            assert cfg.zero_config.grad_sync == mode

    def test_grad_sync_invalid_value_raises(self):
        with pytest.raises(ValueError):
            make_cfg({"train_batch_size": 8,
                      "zero_optimization": {"stage": 2,
                                            "grad_sync": "hopeful"}})

    def test_reduce_scatter_false_conflicts_with_explicit(self):
        """reduce_scatter: false selects the dense all-reduce path — an
        explicit psum_scatter request alongside it is a contradiction,
        rejected at config parse."""
        with pytest.raises(ValueError):
            make_cfg({"train_batch_size": 8,
                      "zero_optimization": {"stage": 2,
                                            "reduce_scatter": False,
                                            "grad_sync": "explicit"}})
        # but the dense path itself parses fine
        cfg = make_cfg({"train_batch_size": 8,
                        "zero_optimization": {"stage": 2,
                                              "reduce_scatter": False}})
        assert not cfg.zero_config.reduce_scatter


class TestInferenceConfig:
    """The serving tier's `inference` block: every knob is static
    compiled-program shape, so bad values must die at config parse, not
    as a shape error three compiles deep."""

    def test_defaults(self):
        from deepspeed_tpu import constants as C
        cfg = make_cfg({"train_batch_size": 8})
        inf = cfg.inference_config
        assert inf.max_slots == C.INFERENCE_MAX_SLOTS_DEFAULT == 8
        assert inf.max_seq_len == 0          # 0 = model max
        assert inf.quantize == "none"
        assert inf.prefill_chunk == C.INFERENCE_PREFILL_CHUNK_DEFAULT

    def test_explicit_values(self):
        cfg = make_cfg({"train_batch_size": 8,
                        "inference": {"max_slots": 16, "max_seq_len": 256,
                                      "quantize": "int8",
                                      "prefill_chunk": 64}})
        inf = cfg.inference_config
        assert inf.max_slots == 16
        assert inf.max_seq_len == 256
        assert inf.quantize == "int8"
        assert inf.prefill_chunk == 64

    def test_standalone_parse(self):
        """InferenceEngine parses the block from a raw dict without the
        training batch keys — the serving config needs no batch triple."""
        from deepspeed_tpu.runtime.config import InferenceConfig
        inf = InferenceConfig({"inference": {"max_slots": 4,
                                             "quantize": "bf16"}})
        assert inf.max_slots == 4 and inf.quantize == "bf16"
        assert InferenceConfig(None).max_slots == 8
        assert InferenceConfig({}).prefill_chunk == 32

    @pytest.mark.parametrize("bad", [
        {"max_slots": 0}, {"max_slots": -2}, {"max_slots": 2.5},
        {"max_seq_len": -1},
        {"quantize": "fp4"}, {"quantize": True},
        {"prefill_chunk": -8}, {"prefill_chunk": "auto"},
    ])
    def test_invalid_values_raise(self, bad):
        with pytest.raises(DeepSpeedConfigError):
            make_cfg({"train_batch_size": 8, "inference": bad})

    def test_chunk_zero_is_whole_prompt(self):
        cfg = make_cfg({"train_batch_size": 8,
                        "inference": {"prefill_chunk": 0}})
        assert cfg.inference_config.prefill_chunk == 0


class TestOptimizerScheduler:
    def test_optimizer_params(self):
        cfg = make_cfg({"train_batch_size": 8,
                        "optimizer": {"type": "Adam", "params": {"lr": 0.001}}})
        assert cfg.optimizer_name == "adam"
        assert cfg.optimizer_params["lr"] == 0.001

    def test_scheduler_params(self):
        cfg = make_cfg({"train_batch_size": 8,
                        "scheduler": {"type": "WarmupLR",
                                      "params": {"warmup_num_steps": 10}}})
        assert cfg.scheduler_name == "WarmupLR"
        assert cfg.scheduler_params["warmup_num_steps"] == 10


class TestMisc:
    def test_gradient_clipping(self):
        cfg = make_cfg({"train_batch_size": 8, "gradient_clipping": 1.0})
        assert cfg.gradient_clipping == 1.0

    def test_wall_clock_breakdown(self):
        cfg = make_cfg({"train_batch_size": 8, "wall_clock_breakdown": True})
        assert cfg.wall_clock_breakdown

    def test_pld(self):
        cfg = make_cfg({"train_batch_size": 8,
                        "progressive_layer_drop": {"enabled": True, "gamma": 0.01}})
        assert cfg.pld_config.enabled
        assert cfg.pld_config.gamma == 0.01


class TestExampleConfigs:
    def test_all_example_configs_parse(self):
        """examples/ ship runnable ds_configs; keep them valid against the
        config system (batch triple, known keys)."""
        import glob
        import json
        import os
        from deepspeed_tpu.runtime.config import DeepSpeedConfig
        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = glob.glob(os.path.join(here, "examples", "**", "*.json"),
                          recursive=True)
        assert paths, "no example configs found"
        for p in paths:
            with open(p) as f:
                d = json.load(f)
            world = 1
            if "mesh" in d:
                world = (d["mesh"].get("pipe_parallel_size", 1) or 1) * 4
            micro = d.get("train_micro_batch_size_per_gpu")
            if micro:
                world = max(1, d["train_batch_size"] //
                            (micro * d.get("gradient_accumulation_steps", 1)))
            # configs without an explicit micro batch are world-size
            # agnostic: the batch-triple solver derives it (the examples
            # run on 1 real chip or the 8-device CPU mesh unchanged)
            cfg = DeepSpeedConfig(d, world_size=world)
            assert cfg.train_batch_size == d["train_batch_size"], p
