"""Config-matrix training smoke tests — the reference's test_fp16.py
pattern (797 LoC of Adam/Lamb x fp16/fp32 x zero-stage x cpu_offload
combinations, each asserting the engine trains): every supported
combination constructs, runs 3 steps, and produces finite falling loss.
Plus the argparse integration (test_ds_arguments parity)."""
import itertools

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.parallel.topology import build_mesh

from simple_model import simple_loss_fn, simple_model_params, random_batch


MATRIX = [
    # (optimizer, precision, zero_stage, cpu_offload)
    ("Adam", "fp32", 0, False),
    ("Adam", "fp16", 0, False),
    ("Adam", "bf16", 1, False),
    ("Adam", "bf16", 2, False),
    ("Adam", "fp32", 2, True),
    ("Adam", "bf16", 2, True),
    ("AdamW", "bf16", 2, False),
    ("AdamW", "fp16", 1, False),
    ("Lamb", "bf16", 0, False),
    ("Lamb", "fp32", 1, False),
    ("SGD", "bf16", 0, False),
    ("OneBitAdam", "bf16", 0, False),
    # ZeRO-3 (params born dp-sharded, gathered at use — ISSUE 11)
    ("Adam", "fp32", 3, False),
    ("Adam", "fp16", 3, False),
    ("AdamW", "bf16", 3, False),
    ("Adam", "bf16", 3, True),
]


@pytest.mark.slow
@pytest.mark.parametrize("opt,prec,stage,offload", MATRIX)
def test_config_combination_trains(opt, prec, stage, offload):
    dp = 1 if offload else 2
    mesh = build_mesh(devices=jax.devices()[:dp])
    cfg = {
        "train_batch_size": 8 * dp,
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 2,
        "zero_optimization": {"stage": stage, "cpu_offload": offload},
        "gradient_clipping": 1.0,
        "optimizer": {"type": opt, "params": {"lr": 1e-2}},
        "steps_per_print": 10 ** 9,
    }
    if prec == "fp16":
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 8}
    elif prec == "bf16":
        cfg["bf16"] = {"enabled": True}
    eng = DeepSpeedEngine(model=simple_loss_fn,
                          model_params=simple_model_params(
                              jax.random.PRNGKey(0)),
                          config=cfg, mesh=mesh)
    losses = []
    for i in range(3):
        b = random_batch(8 * dp, seed=i)
        losses.append(float(jax.device_get(eng.train_batch(b))))
    assert np.isfinite(losses).all(), (opt, prec, stage, offload, losses)
    assert losses[-1] < losses[0] * 1.2, (opt, prec, stage, offload, losses)


SLICES_MATRIX = [
    # (precision, zero_stage) — multi-slice rows: 2 slices x dp=4.
    # Stage 2 shards grads/moments in-slice; stage 3 additionally
    # births params dp-sharded within each slice (replicated across
    # slices) with ICI-only gathers — the ISSUE-18 composition rows.
    ("fp32", 2),
    ("bf16", 2),
    ("fp16", 2),
    ("fp32", 3),
    ("bf16", 3),
    ("fp16", 3),
]


@pytest.mark.slow
@pytest.mark.parametrize("prec,stage", SLICES_MATRIX)
def test_slices_combination_trains(prec, stage):
    """Multi-slice rows of the matrix: the hierarchical grad sync
    (stage 2) and the axis-algebra stage-3 schedule (in-slice param
    gathers + 1/dp DCN residual) each construct, run 3 steps, and
    produce finite falling loss."""
    mesh = build_mesh(slices=2)
    dp = int(mesh.shape["data"])
    cfg = {
        "train_batch_size": 8 * dp * 2,
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 2,
        "zero_optimization": {"stage": stage},
        "mesh": {"slices": 2},
        "gradient_clipping": 1.0,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "steps_per_print": 10 ** 9,
    }
    if prec == "fp16":
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 8}
    elif prec == "bf16":
        cfg["bf16"] = {"enabled": True}
    eng = DeepSpeedEngine(model=simple_loss_fn,
                          model_params=simple_model_params(
                              jax.random.PRNGKey(0)),
                          config=cfg, mesh=mesh)
    losses = []
    for i in range(3):
        b = random_batch(8 * dp * 2, seed=i)
        losses.append(float(jax.device_get(eng.train_batch(b))))
    assert np.isfinite(losses).all(), (prec, stage, losses)
    assert losses[-1] < losses[0] * 1.2, (prec, stage, losses)


MOE_MATRIX = [
    # (precision, zero_stage, ep) — MoE gpt2-tiny through the engine.
    ("fp32", 0, 4),
    ("fp32", 1, 4),
    ("fp32", 2, 4),
    ("bf16", 2, 4),
    ("fp16", 1, 4),
    ("fp32", 2, 1),    # single expert group: no expert axis, no a2a
    ("fp32", 3, 4),
]


@pytest.mark.slow
@pytest.mark.parametrize("prec,stage,ep", MOE_MATRIX)
def test_moe_combination_trains(prec, stage, ep):
    """MoE rows of the matrix: 8-expert top-2 gpt2-tiny x precision x
    ZeRO stage x expert-parallel size constructs, runs 3 steps, and
    produces finite loss (the dense rows' contract, on the expert-
    parallel path)."""
    import dataclasses
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import (GPT2_CONFIGS, gpt2_init,
                                           gpt2_loss_fn)
    from deepspeed_tpu.moe import MoEConfig, gpt2_moe_param_shardings

    mesh = build_mesh(ep=ep)
    moe = MoEConfig(num_experts=8, top_k=2, capacity_factor=1.5,
                    expert_parallel_size=ep)
    dtype = {"fp32": jnp.float32, "bf16": jnp.bfloat16,
             "fp16": jnp.float16}[prec]
    mcfg = dataclasses.replace(
        GPT2_CONFIGS["gpt2-tiny"], vocab_size=64, max_seq_length=33,
        hidden_dropout=0.0, attn_dropout=0.0, dtype=dtype,
        fused_kernels=False, moe=moe)
    cfg = {
        "train_batch_size": 32,
        "train_micro_batch_size_per_gpu": 4 if ep > 1 else 32 // 8,
        "gradient_accumulation_steps": 1,
        "zero_optimization": {"stage": stage},
        "gradient_clipping": 1.0,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "moe": {"num_experts": 8, "top_k": 2, "capacity_factor": 1.5,
                "expert_parallel_size": ep},
        "steps_per_print": 10 ** 9,
    }
    if prec == "fp16":
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 8}
    elif prec == "bf16":
        cfg["bf16"] = {"enabled": True}
    eng, *_ = deepspeed_tpu.initialize(
        model=gpt2_loss_fn(mcfg, mesh=mesh),
        model_params=gpt2_init(jax.random.PRNGKey(0), mcfg),
        config=cfg, mesh=mesh,
        param_shardings=gpt2_moe_param_shardings(mcfg))
    rng = np.random.default_rng(0)
    losses = []
    for i in range(3):
        b = rng.integers(0, 64, size=(32, 34)).astype(np.int32)
        losses.append(float(jax.device_get(eng.train_batch(b))))
    assert np.isfinite(losses).all(), (prec, stage, ep, losses)


def test_add_config_arguments_roundtrip(tmp_path):
    """--deepspeed/--deepspeed_config flags incl. --deepscale aliases
    (reference __init__.py:142-206 + test_ds_arguments)."""
    import argparse
    import json
    p = tmp_path / "c.json"
    p.write_text(json.dumps({
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}))
    parser = deepspeed_tpu.add_config_arguments(argparse.ArgumentParser())
    args = parser.parse_args(["--deepspeed", "--deepspeed_config", str(p)])
    assert args.deepspeed and args.deepspeed_config == str(p)
    # deprecated alias still accepted
    args2 = parser.parse_args(["--deepscale", "--deepscale_config", str(p)])
    assert args2.deepspeed_config == str(p) or \
        getattr(args2, "deepscale_config", None) == str(p)


def test_initialize_from_args_namespace(tmp_path):
    """initialize(args=...) consumes the argparse namespace the reference
    way (engine built from args.deepspeed_config)."""
    import argparse
    import json
    p = tmp_path / "c.json"
    p.write_text(json.dumps({
        "train_batch_size": 8,
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "steps_per_print": 10 ** 9}))
    parser = deepspeed_tpu.add_config_arguments(argparse.ArgumentParser())
    args = parser.parse_args(["--deepspeed", "--deepspeed_config", str(p)])
    engine, _, _, _ = deepspeed_tpu.initialize(
        args=args, model=simple_loss_fn,
        model_params=simple_model_params(jax.random.PRNGKey(0)),
        mesh=build_mesh(devices=jax.devices()[:1]))
    loss = engine.train_batch(random_batch(8, seed=0))
    assert np.isfinite(float(jax.device_get(loss)))
