"""Crash matrix for the two-phase atomic checkpoint commit: a REAL
process SIGKILLed at exact protocol offsets (runtime/async_ckpt.py's
DS_CKPT_CRASH_POINT injection — the process kills ITSELF with SIGKILL at
the named byte offset, so there is no cleanup, no atexit, no flush), and
an external kill landing mid-write. After every kill, ``latest`` must
name a FULLY loadable checkpoint — the previous one when the kill
preceded the atomic rename/flip, either one at the flip boundary — and
the exit code must be the honest ``-SIGKILL`` (PR-10 discipline).

Matrix (ISSUE 15): kill during snapshot, during blob write, between the
meta seal and the ``latest`` flip, and during idle — each
subprocess-tested with a loadable-``latest`` assertion.
"""
import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from deepspeed_tpu.runtime.async_ckpt import is_complete
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.parallel.topology import build_mesh

from simple_model import simple_loss_fn, simple_model_params, random_batch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Child contract: train 2 steps, commit a GOOD checkpoint (latest ->
# "good"), train 1 more step, arm the crash point, attempt a second
# save ("bad") and die INSIDE it. The parent then asserts what survived.
CHILD = """
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {tests!r})
sys.path.insert(0, {repo!r})
from simple_model import simple_model_params, simple_loss_fn, random_batch
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.parallel.topology import build_mesh

d = {ckdir!r}
mesh = build_mesh(devices=jax.devices()[:2])
cfg = {{"train_batch_size": 16, "train_micro_batch_size_per_gpu": 8,
       "gradient_accumulation_steps": 1,
       "zero_optimization": {{"stage": 2}},
       "optimizer": {{"type": "Adam", "params": {{"lr": 1e-2}}}},
       "steps_per_print": 10 ** 9,
       "checkpoint": {{"async": {use_async}}}}}
eng = DeepSpeedEngine(model=simple_loss_fn,
                      model_params=simple_model_params(
                          jax.random.PRNGKey(0)), config=cfg, mesh=mesh)
eng.train_batch(random_batch(16, seed=0))
eng.train_batch(random_batch(16, seed=1))
eng.save_checkpoint(d, tag="good")
if eng._async_ckpt is not None:
    assert eng._async_ckpt.wait(timeout=60)
open(os.path.join(d, "GOOD_DONE"), "w").write("1")
eng.train_batch(random_batch(16, seed=2))
os.environ["DS_CKPT_CRASH_POINT"] = {point!r}
eng.save_checkpoint(d, tag="bad")
if eng._async_ckpt is not None:
    eng._async_ckpt.wait(timeout=60)
print("SURVIVED_THE_CRASH_POINT")
"""


def _run_child(ckdir, point, use_async=False, timeout=240):
    script = os.path.join(ckdir, "child.py")
    with open(script, "w") as f:
        f.write(CHILD.format(tests=os.path.join(REPO, "tests"), repo=REPO,
                             ckdir=ckdir, point=point,
                             use_async=use_async))
    p = subprocess.run([sys.executable, script], capture_output=True,
                       text=True, timeout=timeout)
    return p


def _load_latest(ckdir, seed=9):
    mesh = build_mesh(devices=jax.devices()[:2])
    cfg = {"train_batch_size": 16, "train_micro_batch_size_per_gpu": 8,
           "gradient_accumulation_steps": 1,
           "zero_optimization": {"stage": 2},
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "steps_per_print": 10 ** 9}
    eng = DeepSpeedEngine(model=simple_loss_fn,
                          model_params=simple_model_params(
                              jax.random.PRNGKey(seed)),
                          config=cfg, mesh=mesh)
    path, client = eng.load_checkpoint(ckdir)
    return eng, path


@pytest.mark.parametrize("point,expected_steps", [
    # Half of a blob file is on disk inside bad.tmp; the rename never
    # ran, latest still says "good".
    ("mid_blob_write", {2}),
    # Every blob landed, the seal (engine_meta.json) did not: bad.tmp is
    # unsealed garbage, latest says "good".
    ("pre_seal", {2}),
    # Sealed tmp dir, not yet renamed: latest says "good".
    ("pre_commit", {2}),
    # Renamed ("bad" is complete on disk) but latest never flipped:
    # loading latest gives "good" — the older-but-consistent outcome.
    ("pre_latest", {2}),
    # latest tmp file written, os.replace not reached: latest still
    # "good"; "bad" exists sealed. Either target is loadable.
    ("mid_latest", {2, 3}),
])
def test_kill_at_protocol_offset_leaves_latest_loadable(
        tmp_path, point, expected_steps):
    ckdir = str(tmp_path)
    p = _run_child(ckdir, point)
    assert p.returncode == -signal.SIGKILL, (p.returncode, p.stderr[-2000:])
    assert "SURVIVED_THE_CRASH_POINT" not in p.stdout
    assert os.path.exists(os.path.join(ckdir, "GOOD_DONE")), \
        p.stderr[-2000:]
    # The good tag is intact and sealed no matter where the kill landed.
    assert is_complete(os.path.join(ckdir, "good"))
    eng, path = _load_latest(ckdir)
    assert path is not None, f"latest unloadable after kill at {point}"
    assert eng.global_steps in expected_steps, \
        (point, eng.global_steps)
    # The resumed engine trains on.
    loss = float(jax.device_get(eng.train_batch(random_batch(16, seed=7))))
    assert np.isfinite(loss)


def test_kill_after_async_snapshot_before_write(tmp_path):
    """Async path: the kill lands after the snapshot fetch, before any
    byte is written — the checkpoint is simply lost, latest intact."""
    ckdir = str(tmp_path)
    p = _run_child(ckdir, "after_snapshot", use_async=True)
    assert p.returncode == -signal.SIGKILL, (p.returncode, p.stderr[-2000:])
    eng, path = _load_latest(ckdir)
    assert path is not None and path.endswith("good")
    assert eng.global_steps == 2
    assert not os.path.exists(os.path.join(ckdir, "bad"))


def test_external_kill_mid_background_write(tmp_path):
    """The idle/external case: SIGKILL from OUTSIDE while the slowed
    background writer is mid-commit. No crash-point cooperation — the
    honest preemption. latest must still name the good checkpoint."""
    ckdir = str(tmp_path)
    script = os.path.join(ckdir, "child.py")
    with open(script, "w") as f:
        f.write(CHILD.format(tests=os.path.join(REPO, "tests"), repo=REPO,
                             ckdir=ckdir, point="", use_async=True))
    env = dict(os.environ)
    env["DS_CKPT_TEST_WRITE_DELAY_S"] = "0.5"
    p = subprocess.Popen([sys.executable, script],
                         stdout=subprocess.DEVNULL,
                         stderr=subprocess.DEVNULL, env=env)
    try:
        marker = os.path.join(ckdir, "GOOD_DONE")
        t0 = time.time()
        while not os.path.exists(marker):
            time.sleep(0.05)
            assert p.poll() is None, "child died before the good save"
            assert time.time() - t0 < 180, "child never reached GOOD_DONE"
        # The second (bad) save's write is slowed to >= 1.5s; killing
        # shortly after the marker lands mid-write of either save's
        # successor with high probability — and wherever it lands, the
        # protocol owes us a loadable latest.
        time.sleep(0.7)
        p.kill()
        p.wait(timeout=60)
    finally:
        if p.poll() is None:
            p.kill()
    assert p.returncode == -signal.SIGKILL
    eng, path = _load_latest(ckdir)
    assert path is not None
    assert eng.global_steps in (2, 3)
    loss = float(jax.device_get(eng.train_batch(random_batch(16, seed=7))))
    assert np.isfinite(loss)
