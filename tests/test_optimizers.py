"""Optimizer selection matrix details (reference ops/lamb/fused_lamb.py,
test via trust-ratio clamp semantics)."""
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.optimizers import build_optimizer


def test_lamb_trust_ratio_clamped():
    """max_coeff/min_coeff must clamp the per-tensor trust ratio
    (fused_lamb_cuda_kernel.cu); configs that set them get clamped math,
    not silently-ignored knobs."""
    # Large params: post-Adam updates are ~unit-norm, so the raw trust
    # ratio |p|/|u| ~= 1000 exceeds both clamp settings.
    p = {"w": jnp.full((16, 16), 1000.0, jnp.float32)}
    g = {"w": jnp.full((16, 16), 1e-3, jnp.float32)}

    def upd(max_coeff):
        tx = build_optimizer("lamb", {"lr": 1.0, "weight_decay": 0.0,
                                      "max_coeff": max_coeff,
                                      "min_coeff": 0.01})
        st = tx.init(p)
        u, _ = tx.update(g, st, p)
        return np.asarray(u["w"])

    u_small = upd(2.0)
    u_big = upd(200.0)
    # ratio of the two updates reflects the clamp values
    r = np.abs(u_big).mean() / np.abs(u_small).mean()
    assert 50 < r < 150, r    # 200/2 = 100x


def test_lamb_min_coeff_clamp():
    p = {"w": jnp.full((8, 8), 1e-6, jnp.float32)}   # tiny params
    g = {"w": jnp.ones((8, 8), jnp.float32)}          # big update
    tx = build_optimizer("lamb", {"lr": 1.0, "min_coeff": 0.5,
                                  "max_coeff": 10.0})
    st = tx.init(p)
    u, _ = tx.update(g, st, p)
    # unclamped ratio would be ~1e-6; min_coeff forces >= 0.5
    assert np.abs(np.asarray(u["w"])).mean() > 0.4


def test_cpu_adam_bf16_grad_kernel_parity():
    """The bf16-gradient Adam kernels (no host-side cast pass) match the
    fp32 fallback math exactly, and the bf16 norm matches f64."""
    import ml_dtypes
    from deepspeed_tpu.ops.cpu_adam import DeepSpeedCPUAdam
    rng = np.random.default_rng(0)
    g32 = rng.standard_normal(4097).astype(np.float32)
    g16 = g32.astype(ml_dtypes.bfloat16)          # bf16-representable grads
    g32 = g16.astype(np.float32)
    p_a = np.ones(4097, np.float32)
    p_b = np.ones(4097, np.float32)
    opt_a = DeepSpeedCPUAdam({"w": p_a}, lr=1e-3, weight_decay=0.01)
    opt_b = DeepSpeedCPUAdam({"w": p_b}, lr=1e-3, weight_decay=0.01)
    opt_b._lib = None                             # numpy reference path
    bo = [np.zeros(4097, np.uint16)]
    for _ in range(3):
        opt_a.step([p_a], [g16], grad_scale=0.5, bf16_out=bo)
        opt_b.step([p_b], [g32], grad_scale=0.5)
    np.testing.assert_allclose(p_a, p_b, rtol=1e-6, atol=1e-7)
    if opt_a.native:
        n_a = opt_a.grad_norm([g16], 0.5)
        n_ref = float(np.sqrt(np.sum((g32.astype(np.float64) * 0.5) ** 2)))
        np.testing.assert_allclose(n_a, n_ref, rtol=1e-6)
