"""Fused Pallas multi-tensor optimizer apply (ops/fused_update.py) vs the
optax reference apply — the parity contract for the reference's
``csrc/adam/multi_tensor_adam.cu`` equivalent.

Parity tiers:
- moments: BIT-equal with optax (same association order, f32 throughout);
- params (deterministic path): equal to within ~2 f32 ulp — strict bitwise
  equality across two separately-compiled XLA programs is not achievable
  because XLA contracts ``p + u*lr`` into an FMA inside one fusion and not
  the other (verified: one jit of ``p + u*lr`` vs staged mul/add differs in
  the last ulp on CPU); the FMA result is the *more* accurate one;
- params (stochastic-rounding path, seeded): both engines land within one
  bf16 ulp of the same f32 trajectory, so trajectories agree to bf16
  tolerance.

Engine tier runs on the 8-device CPU mesh under ZeRO-2, covering the
fp32-master, master-free bf16+SR, and gas>1 scan paths, plus the
``optimizer.params.fused`` config knob in both positions.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deepspeed_tpu.ops.fused_update import (fused_adam, FusedAdamState,
                                            leaf_moment_views)
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.parallel.topology import build_mesh

B1, B2, EPS, WD = 0.9, 0.999, 1e-8, 0.01


def _tree(seed=0, dtype=np.float32):
    r = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(r.standard_normal((37, 5)).astype(dtype)),
        "big": jnp.asarray(r.standard_normal(140001).astype(dtype)),
        "b": jnp.asarray(r.standard_normal(()).astype(dtype)),
    }


def _grads(i, like):
    r = np.random.default_rng(1000 + i)
    return jax.tree_util.tree_map(
        lambda x: jnp.asarray(
            r.standard_normal(x.shape).astype(np.float32)).astype(x.dtype),
        like)


def _sched(c):
    return jnp.asarray(1e-3, jnp.float32)


def _assert_moments_bitexact(ref_state, fs, params, step=0):
    """optax mu/nu vs the fused V-interleaved buffers, per leaf via
    leaf_moment_views (the buffer layout interleaves every leaf over
    virtual-shard rows, so raw prefix slices are meaningless)."""
    mv, vv = leaf_moment_views(fs, params)
    for k in params:
        np.testing.assert_array_equal(
            np.asarray(ref_state.mu[k]), np.asarray(mv[k]),
            err_msg=f"first moment diverged at step {step} leaf {k}")
        np.testing.assert_array_equal(
            np.asarray(ref_state.nu[k]), np.asarray(vv[k]),
            err_msg=f"second moment diverged at step {step} leaf {k}")


class TestTransformParity:
    def test_adamw_moments_bitexact_params_ulp(self):
        params = _tree()
        ref = optax.adamw(_sched, b1=B1, b2=B2, eps=EPS, weight_decay=WD)
        fus = fused_adam(_sched, B1, B2, EPS, WD, adam_w_mode=True)
        rs, fs = ref.init(params), fus.init(params)
        p_ref = p_fus = params
        upd_ref = jax.jit(ref.update)
        upd_fus = jax.jit(fus.fused_apply)
        n = sum(int(l.size) for l in jax.tree_util.tree_leaves(params))
        for i in range(4):
            g = _grads(i, params)
            u, rs = upd_ref(g, rs, p_ref)
            p_ref = optax.apply_updates(p_ref, u)
            p_fus, fs = upd_fus(g, fs, p_fus)
            _assert_moments_bitexact(rs[0], fs, params, step=i)
            for k in params:
                np.testing.assert_allclose(
                    np.asarray(p_ref[k]), np.asarray(p_fus[k]),
                    rtol=1e-6, atol=1e-7, err_msg=f"step {i} leaf {k}")
        # the pad regions of the fused buffers stay exactly zero: the
        # buffer can hold at most n nonzero (real-element) entries
        assert np.count_nonzero(np.asarray(fs.m[0])) <= n

    def test_coupled_adam_parity(self):
        """adam_w_mode=False folds decay into the grad BEFORE the moments
        (the engine's classic-Adam chain)."""
        params = _tree(3)
        ref = optax.chain(optax.add_decayed_weights(WD),
                          optax.scale_by_adam(b1=B1, b2=B2, eps=EPS),
                          optax.scale_by_learning_rate(_sched))
        fus = fused_adam(_sched, B1, B2, EPS, WD, adam_w_mode=False)
        rs, fs = ref.init(params), fus.init(params)
        p_ref = p_fus = params
        for i in range(3):
            g = _grads(i, params)
            u, rs = jax.jit(ref.update)(g, rs, p_ref)
            p_ref = optax.apply_updates(p_ref, u)
            p_fus, fs = jax.jit(fus.fused_apply)(g, fs, p_fus)
        for k in params:
            np.testing.assert_allclose(np.asarray(p_ref[k]),
                                       np.asarray(p_fus[k]),
                                       rtol=1e-6, atol=1e-7)

    def test_clip_coeff_folded_in_kernel(self):
        """fused_apply(clip_coeff=c) == fused_apply on pre-scaled grads."""
        params = _tree(4)
        fus = fused_adam(_sched, B1, B2, EPS, WD)
        fs = fus.init(params)
        g = _grads(0, params)
        c = jnp.asarray(0.37, jnp.float32)
        p_a, _ = jax.jit(fus.fused_apply)(
            jax.tree_util.tree_map(lambda x: x * c, g), fs, params)
        p_b, _ = jax.jit(lambda g, s, p: fus.fused_apply(
            g, s, p, clip_coeff=c))(g, fs, params)
        for k in params:
            np.testing.assert_allclose(np.asarray(p_a[k]),
                                       np.asarray(p_b[k]),
                                       rtol=1e-6, atol=1e-7)

    def test_optax_update_contract(self):
        """The generic optax-style update (delta + apply_updates) lands on
        the fused_apply params (generic callers keep working)."""
        params = _tree(5)
        fus = fused_adam(_sched, B1, B2, EPS, WD)
        fs = fus.init(params)
        g = _grads(0, params)
        u, _ = jax.jit(fus.update)(g, fs, params)
        via_update = optax.apply_updates(params, u)
        direct, _ = jax.jit(fus.fused_apply)(g, fs, params)
        for k in params:
            np.testing.assert_allclose(np.asarray(via_update[k]),
                                       np.asarray(direct[k]),
                                       rtol=1e-6, atol=1e-7)

    def test_per_leaf_mode_matches_chunked(self):
        params = _tree(6)
        chunked = fused_adam(_sched, B1, B2, EPS, WD)
        per_leaf = fused_adam(_sched, B1, B2, EPS, WD, multi_tensor=False)
        cs, ps = chunked.init(params), per_leaf.init(params)
        g = _grads(0, params)
        p_c, _ = jax.jit(chunked.fused_apply)(g, cs, params)
        p_l, _ = jax.jit(per_leaf.fused_apply)(g, ps, params)
        for k in params:
            np.testing.assert_allclose(np.asarray(p_c[k]),
                                       np.asarray(p_l[k]),
                                       rtol=1e-6, atol=1e-7)

    def test_bf16_params_keep_f32_grads(self):
        """Master-free regression: the front end must flatten grads in f32
        — the engine accumulates them in f32 over bf16 params, and a cast
        to the param-group dtype would truncate them before the kernel's
        f32 moment update ever sees them."""
        g_val = 1.0 + 1 / 4096            # NOT bf16-representable
        params = {"w": jnp.full((64,), 0.5, jnp.bfloat16)}
        g = {"w": jnp.full((64,), g_val, jnp.float32)}
        fus = fused_adam(_sched, B1, B2, EPS, 0.0)
        _, fs = jax.jit(fus.fused_apply)(g, fus.init(params), params)
        mv, vv = leaf_moment_views(fs, params)
        np.testing.assert_allclose(np.asarray(mv["w"]),
                                   np.float32((1 - B1) * g_val), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(vv["w"]),
                                   np.float32((1 - B2) * g_val ** 2),
                                   rtol=1e-5)

    def test_stochastic_rounding_in_kernel(self):
        """bf16 params + sr_key: the write lands on a bf16 neighbor of the
        f32 result (within one bf16 ulp), moments stay f32, and distinct
        seeds produce distinct roundings."""
        params = _tree(7, dtype=jnp.bfloat16)
        fus = fused_adam(_sched, B1, B2, EPS, WD)
        fs = fus.init(params)
        g = _grads(0, params)
        gb = jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), g)
        apply = jax.jit(lambda g, s, p, k: fus.fused_apply(g, s, p,
                                                           sr_key=k))
        p_sr, fs_sr = apply(gb, fs, params, jax.random.PRNGKey(0))
        p_sr2, _ = apply(gb, fs, params, jax.random.PRNGKey(1))
        # deterministic f32 reference of the same update
        p32 = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32), params)
        f32 = fused_adam(_sched, B1, B2, EPS, WD)
        p_ref, _ = jax.jit(f32.fused_apply)(gb, f32.init(p32), p32)
        any_diff = False
        for k in params:
            assert p_sr[k].dtype == jnp.bfloat16
            a = np.asarray(p_sr[k], np.float32)
            r = np.asarray(p_ref[k], np.float32)
            # one bf16 ulp at the reference's magnitude
            ulp = np.maximum(np.abs(r), 1e-30) * 2 ** -7
            assert np.all(np.abs(a - r) <= ulp + 1e-7), k
            any_diff |= not np.array_equal(
                np.asarray(p_sr[k], np.float32),
                np.asarray(p_sr2[k], np.float32))
        assert any_diff, "distinct seeds must round differently somewhere"
        assert fs_sr.m[0].dtype == jnp.float32


class TestOnePassStep:
    """fused_step: norm + clip + overflow + cast all inside the single
    HBM pass, vs the historical two-pass sequencing."""

    def test_matches_two_pass_clip(self):
        """fused_step(clip=c) == global_norm + clip_coefficient +
        fused_apply(clip_coeff=...) — the two paths share the clip
        expression textually, so parity is tight."""
        from deepspeed_tpu.runtime.utils import clip_coefficient, global_norm
        params = _tree(8)
        clip = 0.5
        fus = fused_adam(_sched, B1, B2, EPS, WD)
        fs = fus.init(params)
        g = _grads(0, params)
        out = jax.jit(lambda g, s, p: fus.fused_step(g, s, p, clip=clip))(
            g, fs, params)
        norm = global_norm(g)
        coeff = clip_coefficient(norm, clip)
        p_two, fs_two = jax.jit(lambda g, s, p, c: fus.fused_apply(
            g, s, p, clip_coeff=c))(g, fs, params, coeff)
        np.testing.assert_allclose(float(out.grad_norm), float(norm),
                                   rtol=1e-6)
        assert not bool(out.overflow)
        for k in params:
            np.testing.assert_allclose(np.asarray(out.params[k]),
                                       np.asarray(p_two[k]),
                                       rtol=1e-6, atol=1e-7)
        # moments track g*coeff; the one-pass norm sums chunk partials in
        # a different association than per-leaf global_norm, so coeff (and
        # hence m) agrees to f32 ulp, not bitwise (PR-1 precedent).
        np.testing.assert_allclose(np.asarray(out.state.m[0]),
                                   np.asarray(fs_two.m[0]),
                                   rtol=1e-6, atol=1e-9)
        assert int(out.state.count) == 1

    def test_fp16_overflow_holds_step_in_kernel(self):
        """An inf gradient under fp16: the in-pass vote (non-finite sum
        of squares) holds params/moments bit-identically and the count
        does not advance — no separate tree_has_inf_or_nan read."""
        params = _tree(9)
        fus = fused_adam(_sched, B1, B2, EPS, WD)
        fs = fus.init(params)
        g = _grads(0, params)
        g = dict(g, b=jnp.asarray(np.inf, jnp.float32))
        out = jax.jit(lambda g, s, p: fus.fused_step(
            g, s, p, clip=1.0, inv_scale=jnp.float32(1 / 128.0),
            fp16=True))(g, fs, params)
        assert bool(out.overflow)
        assert int(out.state.count) == 0
        for k in params:
            np.testing.assert_array_equal(np.asarray(out.params[k]),
                                          np.asarray(params[k]))
        np.testing.assert_array_equal(np.asarray(out.state.m[0]),
                                      np.asarray(fs.m[0]))

    def test_fp16_unscale_in_kernel(self):
        """fused_step(inv_scale=1/s) on scale-multiplied grads equals
        fused_step on the unscaled grads (norm included: ||g*s||/s)."""
        params = _tree(10)
        fus = fused_adam(_sched, B1, B2, EPS, WD)
        fs = fus.init(params)
        g = _grads(0, params)
        s = 1024.0
        g_scaled = jax.tree_util.tree_map(lambda x: x * s, g)
        a = jax.jit(lambda g, st, p: fus.fused_step(
            g, st, p, clip=1.0, inv_scale=jnp.float32(1.0 / s),
            fp16=True))(g_scaled, fs, params)
        b = jax.jit(lambda g, st, p: fus.fused_step(g, st, p, clip=1.0))(
            g, fs, params)
        np.testing.assert_allclose(float(a.grad_norm), float(b.grad_norm),
                                   rtol=1e-6)
        for k in params:
            np.testing.assert_allclose(np.asarray(a.params[k]),
                                       np.asarray(b.params[k]),
                                       rtol=1e-5, atol=1e-7)

    def test_cast_refresh_in_pass(self):
        """cast_dtype=bf16: the compute-dtype copy comes out of the same
        kernel write and equals an explicit post-apply cast; non-float
        leaves pass through untouched."""
        params = dict(_tree(11), idx=jnp.arange(3, dtype=jnp.int32))
        fus = fused_adam(_sched, B1, B2, EPS, WD)
        fs = fus.init(params)
        g = dict(_grads(0, {k: v for k, v in params.items() if k != "idx"}),
                 idx=jnp.zeros((3,), jnp.int32))
        out = jax.jit(lambda g, s, p: fus.fused_step(
            g, s, p, clip=1.0, cast_dtype=jnp.bfloat16))(g, fs, params)
        assert out.cast_params is not None
        for k in ("w", "big", "b"):
            assert out.cast_params[k].dtype == jnp.bfloat16
            np.testing.assert_array_equal(
                np.asarray(out.cast_params[k], np.float32),
                np.asarray(out.params[k].astype(jnp.bfloat16), np.float32))
        np.testing.assert_array_equal(np.asarray(out.cast_params["idx"]),
                                      np.asarray(params["idx"]))

    def test_no_norm_requested(self):
        """clip=0, fp16 off, compute_norm off: grad_norm reports -1 (the
        no-extra-HBM-pass sentinel) and the update is the plain apply."""
        params = _tree(12)
        fus = fused_adam(_sched, B1, B2, EPS, WD)
        fs = fus.init(params)
        g = _grads(0, params)
        out = jax.jit(lambda g, s, p: fus.fused_step(
            g, s, p, compute_norm=False))(g, fs, params)
        assert float(out.grad_norm) == -1.0
        p_ref, _ = jax.jit(fus.fused_apply)(g, fs, params)
        for k in params:
            np.testing.assert_array_equal(np.asarray(out.params[k]),
                                          np.asarray(p_ref[k]))

    def test_per_leaf_mode_has_no_one_pass(self):
        fus = fused_adam(_sched, B1, B2, EPS, WD, multi_tensor=False)
        assert fus.fused_step is None


# ------------------------------------------------------------------ #
# Engine tier — 8-device CPU mesh, ZeRO-2
# ------------------------------------------------------------------ #
DIM = 32
_W_TRUE = np.random.default_rng(0).standard_normal(DIM).astype(np.float32)


def loss_fn(params, batch, rng):
    pred = batch["x"] @ params["w"] + params["b"]
    return jnp.mean((pred - batch["y"]) ** 2)


def make_batch(i, n=64):
    r = np.random.default_rng(i)
    x = r.standard_normal((n, DIM)).astype(np.float32)
    return {"x": jnp.asarray(x), "y": jnp.asarray(x @ _W_TRUE)}


def _params():
    return {"w": jnp.zeros((DIM,), jnp.float32),
            "b": jnp.zeros((), jnp.float32)}


def _cfg(fused, gas=1, **over):
    cfg = {
        "train_batch_size": 64,
        "train_micro_batch_size_per_gpu": 64 // (8 * gas),
        "gradient_accumulation_steps": gas,
        "gradient_clipping": 1.0,
        "optimizer": {"type": "AdamW",
                      "params": {"lr": 1e-2, "fused": fused}},
        "zero_optimization": {"stage": 2},
        "bf16": {"enabled": True},
        "steps_per_print": 10 ** 9,
    }
    cfg.update(over)
    return cfg


def _run(cfg, steps=6):
    eng = DeepSpeedEngine(model=loss_fn, model_params=_params(),
                          config=cfg, mesh=build_mesh())
    losses = [float(jax.device_get(eng.train_batch(make_batch(i))))
              for i in range(steps)]
    return eng, losses


def test_config_knob_selects_path():
    eng_f, _ = _run(_cfg(True), steps=1)
    eng_o, _ = _run(_cfg(False), steps=1)
    assert eng_f._fused_apply is not None
    assert isinstance(eng_f.state.opt_state, FusedAdamState)
    assert eng_o._fused_apply is None
    assert not isinstance(eng_o.state.opt_state, FusedAdamState)
    # default is ON for the Adam family
    cfg = _cfg(True)
    del cfg["optimizer"]["params"]["fused"]
    eng_d, _ = _run(cfg, steps=1)
    assert eng_d._fused_apply is not None
    assert eng_d.config.optimizer_fused


def test_engine_parity_fp32_master():
    """bf16 compute + fp32 masters + clipping + ZeRO-2 over dp=8: fused and
    optax trajectories agree to f32-ulp accumulation tolerance."""
    eng_f, l_f = _run(_cfg(True))
    eng_o, l_o = _run(_cfg(False))
    np.testing.assert_allclose(l_f, l_o, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(eng_f.state.params["w"]),
        np.asarray(eng_o.state.params["w"]), rtol=1e-5, atol=1e-6)


def test_engine_parity_gas_scan_path():
    eng_f, l_f = _run(_cfg(True, gas=2))
    eng_o, l_o = _run(_cfg(False, gas=2))
    np.testing.assert_allclose(l_f, l_o, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(eng_f.state.params["w"]),
        np.asarray(eng_o.state.params["w"]), rtol=1e-5, atol=1e-6)


def test_engine_parity_master_free_sr():
    """Master-free bf16 + stochastic rounding (seeded): both paths round
    the same f32 trajectory, so params agree to bf16 tolerance and the
    state really is bf16 (no fp32 master anywhere)."""
    bf16 = {"enabled": True, "stochastic_rounding": True}
    eng_f, l_f = _run(_cfg(True, bf16=bf16))
    eng_o, l_o = _run(_cfg(False, bf16=bf16))
    assert eng_f.state.params["w"].dtype == jnp.bfloat16
    assert eng_o.state.params["w"].dtype == jnp.bfloat16
    np.testing.assert_allclose(l_f, l_o, rtol=0.2, atol=0.05)
    np.testing.assert_allclose(
        np.asarray(eng_f.state.params["w"], np.float32),
        np.asarray(eng_o.state.params["w"], np.float32),
        rtol=0.05, atol=0.05)
    # and the run learns (the SR mode's whole point)
    assert l_f[-1] < 0.5 * l_f[0]


def test_pre_interleave_checkpoint_refused(tmp_path):
    """A fused-optimizer checkpoint WITHOUT the fused_moment_layout=2
    marker (pre-ISSUE-8: end-to-end leaf concatenation) must be refused
    loudly — the flat sizes can coincide and a structural restore would
    silently scramble moments across leaves."""
    import json as _json
    import os as _os
    eng, _ = _run(_cfg(True), steps=1)
    eng.save_checkpoint(str(tmp_path), tag="t")
    mf = _os.path.join(str(tmp_path), "t", "engine_meta.json")
    with open(mf) as f:
        meta = _json.load(f)
    assert meta["fused_moment_layout"] == 2
    del meta["fused_moment_layout"]
    with open(mf, "w") as f:
        _json.dump(meta, f)
    eng2, _ = _run(_cfg(True), steps=1)
    with pytest.raises(ValueError, match="fused_moment_layout"):
        eng2.load_checkpoint(str(tmp_path), tag="t")
    # params-only restore stays available
    eng2.load_checkpoint(str(tmp_path), tag="t",
                         load_optimizer_states=False)


def test_engine_fused_checkpoint_roundtrip(tmp_path):
    """Fused opt state (flat chunk buffers) survives the sharded
    checkpoint save/load with the trajectory intact."""
    eng, _ = _run(_cfg(True), steps=3)
    eng.save_checkpoint(str(tmp_path), tag="t3")
    eng2, _ = _run(_cfg(True), steps=1)
    eng2.load_checkpoint(str(tmp_path), tag="t3")
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(eng.state.opt_state.m[0])),
        np.asarray(jax.device_get(eng2.state.opt_state.m[0])))
    l1 = float(jax.device_get(eng.train_batch(make_batch(100))))
    l2 = float(jax.device_get(eng2.train_batch(make_batch(100))))
    assert abs(l1 - l2) < 1e-6, (l1, l2)
