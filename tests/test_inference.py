"""Serving tier tests: KV cache, incremental decode parity, continuous
batching under the recompile-sentinel gate, quantization, and the
training-checkpoint handoff.

The two load-bearing invariants:

1. **Exactness** — decode against the slot cache produces the SAME
   logits as the full forward at the growing sequence's final position,
   asserted per step (fp32 config, float tolerance: the incremental
   path contracts in a different order).
2. **Static shapes** — a synthetic open-loop arrival stream with
   requests joining and leaving mid-flight (varying active counts,
   varying prompt lengths, varying generation lengths) compiles the
   decode and prefill programs ONCE each; ``fail_on_recompile`` is
   armed, so any shape polymorphism dies loudly here.
"""
import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference import (ContinuousBatchingScheduler,
                                     InferenceEngine, synthetic_requests)
from deepspeed_tpu.inference import kv_cache
from deepspeed_tpu.inference.quantize import (dequantize,
                                              quantize_leaf_int8,
                                              quantize_params)
from deepspeed_tpu.models.gpt2 import (GPT2_CONFIGS, gpt2_apply, gpt2_init,
                                       gpt2_logits_at, gpt2_param_shardings)
from deepspeed_tpu.parallel.topology import build_mesh

CFG32 = dataclasses.replace(GPT2_CONFIGS["gpt2-tiny"], dtype=jnp.float32)


@pytest.fixture(scope="module")
def params32():
    return gpt2_init(jax.random.PRNGKey(0), CFG32)


def _prompt(n, seed=0, vocab=None):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab or CFG32.vocab_size,
                        size=n).astype(np.int32)


def _ref_last_logits(params, seq):
    toks = jnp.asarray(np.asarray(seq, np.int32))[None]
    return np.asarray(gpt2_apply(params, toks, CFG32))[0, -1]


# --------------------------------------------------------------------- #
# Satellite: last-position-only logits
# --------------------------------------------------------------------- #
class TestGpt2LogitsAt:
    def test_matches_full_apply_final_position(self, params32):
        toks = jnp.asarray(_prompt(9, seed=1).reshape(1, 9))
        full = gpt2_apply(params32, toks, CFG32)
        at = gpt2_logits_at(params32, toks, CFG32)
        np.testing.assert_allclose(np.asarray(at), np.asarray(full[:, -1]),
                                   atol=1e-5)

    def test_traced_index(self, params32):
        """The prefill path indexes the prompt's final token inside a
        jitted program — the index must be traceable."""
        toks = jnp.asarray(_prompt(9, seed=2).reshape(1, 9))
        full = np.asarray(gpt2_apply(params32, toks, CFG32))
        fn = jax.jit(lambda t, i: gpt2_logits_at(params32, t, CFG32,
                                                 index=i))
        for i in (0, 4, 8):
            np.testing.assert_allclose(np.asarray(fn(toks, jnp.int32(i))),
                                       full[:, i], atol=1e-5)

    def test_traced_negative_index_normalizes(self, params32):
        """dynamic_index_in_dim CLAMPS a negative traced index to 0 —
        the from-the-end semantics must survive tracing."""
        toks = jnp.asarray(_prompt(9, seed=2).reshape(1, 9))
        full = np.asarray(gpt2_apply(params32, toks, CFG32))
        fn = jax.jit(lambda t, i: gpt2_logits_at(params32, t, CFG32,
                                                 index=i))
        np.testing.assert_allclose(np.asarray(fn(toks, jnp.int32(-1))),
                                   full[:, -1], atol=1e-5)

    def test_never_materializes_full_logits(self, params32):
        """The [B, S, vocab] tensor must not appear in the jaxpr."""
        toks = jnp.asarray(_prompt(16, seed=3).reshape(1, 16))
        jaxpr = jax.make_jaxpr(
            lambda t: gpt2_logits_at(params32, t, CFG32))(toks)
        full_shape = (1, 16, CFG32.vocab_size)
        assert all(getattr(v.aval, "shape", None) != full_shape
                   for eqn in jaxpr.jaxpr.eqns for v in eqn.outvars)


# --------------------------------------------------------------------- #
# KV cache units
# --------------------------------------------------------------------- #
class TestKVCache:
    SPEC = kv_cache.KVCacheSpec(num_layers=1, num_slots=4, num_heads=2,
                                max_len=8, head_dim=3, dtype=jnp.float32)

    def test_write_token_at_per_slot_lengths(self):
        kc = jnp.zeros(self.SPEC.shape[1:], jnp.float32)   # [S,nH,T,D]
        new = jnp.ones((4, 2, 3), jnp.float32) * \
            jnp.arange(1, 5, dtype=jnp.float32)[:, None, None]
        lengths = jnp.asarray([0, 3, 7, 5], jnp.int32)
        out = np.asarray(kv_cache.write_token(kc, new, lengths))
        for s, l in enumerate([0, 3, 7, 5]):
            assert (out[s, :, l] == s + 1).all()
            mask = np.ones(8, bool)
            mask[l] = False
            assert (out[s][:, mask] == 0).all(), "only one row written"

    def test_write_token_full_slot_is_noop(self):
        """length == max_len (slot full): the write lands nowhere."""
        kc = jnp.zeros(self.SPEC.shape[1:], jnp.float32)
        new = jnp.ones((4, 2, 3), jnp.float32)
        out = kv_cache.write_token(kc, new,
                                   jnp.full((4,), 8, jnp.int32))
        assert (np.asarray(out) == 0).all()

    def test_write_chunk_is_slot_isolated(self):
        kc = jnp.zeros(self.SPEC.shape[1:], jnp.float32)
        chunk = jnp.ones((4, 2, 3), jnp.float32) * 7.0     # C=4 tokens
        out = np.asarray(kv_cache.write_chunk(
            kc, chunk, jnp.int32(2), jnp.int32(3)))
        assert (out[2, :, 3:7] == 7.0).all()
        assert (out[2, :, :3] == 0).all() and (out[2, :, 7:] == 0).all()
        assert (out[[0, 1, 3]] == 0).all(), "other slots untouched"

    def test_length_mask_inclusive(self):
        m = np.asarray(kv_cache.length_mask(
            jnp.asarray([0, 2], jnp.int32), 4))
        assert m.tolist() == [[True, False, False, False],
                              [True, True, True, False]]

    def test_spec_validation(self, mesh8):
        with pytest.raises(ValueError, match="divisible"):
            dataclasses.replace(self.SPEC, num_slots=6).validate(mesh8)
        with pytest.raises(ValueError, match="positive"):
            dataclasses.replace(self.SPEC, max_len=0).validate()
        spec = dataclasses.replace(self.SPEC, num_slots=8)
        cache = kv_cache.init_cache(spec, mesh8)
        assert cache["k"].shape == spec.shape
        assert str(cache["k"].sharding.spec) == \
            str(kv_cache.cache_partition_spec())


# --------------------------------------------------------------------- #
# Decode-vs-full-forward parity (the exactness gate)
# --------------------------------------------------------------------- #
class TestDecodeParity:
    @pytest.fixture(scope="class")
    def engine(self, params32):
        eng = InferenceEngine(CFG32, params32, config={
            "inference": {"max_slots": 8, "max_seq_len": 64,
                          "prefill_chunk": 8}})
        yield eng
        eng.close()

    def test_prefill_then_decode_matches_full_forward(self, engine,
                                                      params32):
        """Per-step: incremental logits == full forward's final
        position, for a prompt that does NOT divide the chunk."""
        prompt = _prompt(11, seed=4)
        tok, logits = engine.prefill(prompt, slot=0, return_logits=True)
        ref = _ref_last_logits(params32, prompt)
        np.testing.assert_allclose(logits, ref, atol=1e-4)
        assert tok == int(ref.argmax())
        engine.activate_slot(0, len(prompt), tok)
        seq = list(prompt) + [tok]
        for _ in range(6):
            sampled, lg = engine.decode_once(return_logits=True)
            np.testing.assert_allclose(lg[0],
                                       _ref_last_logits(params32, seq),
                                       atol=1e-4)
            seq.append(int(sampled[0]))
        engine.release_slot(0)

    def test_concurrent_slots_are_isolated(self, engine, params32):
        """Two slots with different prompts decode independently —
        each matches its own full forward."""
        p_a, p_b = _prompt(7, seed=5), _prompt(13, seed=6)
        tok_a, _ = engine.prefill(p_a, slot=1)
        tok_b, _ = engine.prefill(p_b, slot=5)
        engine.activate_slot(1, len(p_a), tok_a)
        engine.activate_slot(5, len(p_b), tok_b)
        seq_a, seq_b = list(p_a) + [tok_a], list(p_b) + [tok_b]
        for _ in range(4):
            sampled, lg = engine.decode_once(return_logits=True)
            np.testing.assert_allclose(
                lg[1], _ref_last_logits(params32, seq_a), atol=1e-4)
            np.testing.assert_allclose(
                lg[5], _ref_last_logits(params32, seq_b), atol=1e-4)
            seq_a.append(int(sampled[1]))
            seq_b.append(int(sampled[5]))
        engine.release_slot(1)
        engine.release_slot(5)

    def test_whole_prompt_prefill_matches(self, params32):
        """prefill_chunk: 0 — the single-shot long-context path."""
        eng = InferenceEngine(CFG32, params32, config={
            "inference": {"max_slots": 8, "max_seq_len": 32,
                          "prefill_chunk": 0}})
        prompt = _prompt(9, seed=7)
        tok, logits = eng.prefill(prompt, slot=2, return_logits=True)
        np.testing.assert_allclose(logits,
                                   _ref_last_logits(params32, prompt),
                                   atol=1e-4)
        eng.activate_slot(2, len(prompt), tok)
        seq = list(prompt) + [tok]
        sampled, lg = eng.decode_once(return_logits=True)
        np.testing.assert_allclose(lg[2], _ref_last_logits(params32, seq),
                                   atol=1e-4)
        eng.close()

    def test_temperature_sampling_reproducible(self, engine):
        """Threaded PRNG: temperature > 0 samples; the in-graph
        categorical is deterministic given the engine's key stream."""
        prompt = _prompt(6, seed=8)
        tok, logits = engine.prefill(prompt, slot=3, temperature=1.0,
                             return_logits=True)
        assert 0 <= tok < CFG32.vocab_size
        assert np.isfinite(logits).all()
        engine.release_slot(3)

    def test_engine_geometry_validation(self, params32):
        with pytest.raises(ValueError, match="divide"):
            InferenceEngine(CFG32, params32, config={
                "inference": {"max_slots": 8, "max_seq_len": 60,
                              "prefill_chunk": 8}})
        with pytest.raises(ValueError, match="position table"):
            InferenceEngine(CFG32, params32, config={
                "inference": {"max_slots": 8, "max_seq_len": 4096}})

    def test_prompt_too_long_raises(self, engine):
        with pytest.raises(ValueError, match="no room"):
            engine.prefill(_prompt(64), slot=0)


# --------------------------------------------------------------------- #
# The serving acceptance gate: continuous batching on the dp=8 mesh
# --------------------------------------------------------------------- #
class TestServingStream:
    def test_open_loop_stream_occupancy_and_zero_recompiles(self, tmp_path):
        """The ROADMAP item-3 acceptance: a synthetic open-loop stream
        with varying prompt lengths AND varying generation lengths
        (requests join/leave mid-flight, so the active-slot count walks
        all over) — occupancy > 80%, ZERO post-warmup recompiles under
        fail_on_recompile, TTFT/TPOT p50/p95 recorded and surfaced by
        the telemetry report's serving section."""
        cfg = GPT2_CONFIGS["gpt2-tiny"]
        eng = InferenceEngine(cfg, gpt2_init(jax.random.PRNGKey(1), cfg),
                              config={
            "inference": {"max_slots": 8, "max_seq_len": 64,
                          "prefill_chunk": 8},
            "telemetry": {"enabled": True, "output_path": str(tmp_path),
                          "job_name": "serve",
                          # Larger than the whole serve: the scheduler's
                          # END-of-serve drain must carry the aggregator
                          # snapshot on its own (a run shorter than
                          # report_steps must not lose tokens/s).
                          "report_steps": 10 ** 6,
                          "fail_on_recompile": True}})
        reqs = synthetic_requests(24, prompt_len=(5, 14),
                                  max_new_tokens=8,
                                  vocab_size=cfg.vocab_size, seed=2)
        # Vary generation length too: slots free at different iterations
        # (a 3-deep saturation backlog keeps refills instant, so the
        # drain tail doesn't swamp the occupancy average).
        for i, r in enumerate(reqs):
            r.max_new_tokens = 6 + (i % 3)
        report = eng.serve(reqs)

        assert report["completed"] == 24 and report["unfinished"] == 0
        assert report["occupancy_mean"] > 0.8, report["occupancy_mean"]
        assert report["recompiles"] == 0
        assert eng.telemetry.recompile_count == 0
        for sec in ("ttft_ms", "tpot_ms"):
            assert report[sec]["n"] > 0
            assert report[sec]["p95"] >= report[sec]["p50"] > 0
        for r in report["requests"]:
            assert r["new_tokens"] == 6 + (r["rid"] % 3)
        # Every slot drained.
        assert not eng.active.any() and (eng.lengths == 0).all()

        # The compile-time serving contract: host_sync + materialization
        # clean over both compiled paths (no full-cache gather, no
        # in-step host transfer).
        lint = eng.lint_audit(passes=("host_sync", "materialization"))
        assert {p.name for p in lint.paths} == \
            {"decode_step", "prefill_step"}
        assert not lint.unwaived and not any(p.errors for p in lint.paths)

        eng.close()
        # JSONL → serving section of the report pipeline.
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        from telemetry_report import summarize
        summary = summarize(str(tmp_path / "serve.jsonl"))
        srv = summary["serving"]
        assert srv["available"] and srv["completed"] == 24
        assert srv["occupancy_mean"] > 0.8
        assert srv["ttft_ms"]["n"] == 24
        assert summary["recompiles"]["count"] == 0
        assert srv["tokens_per_s"] > 0

    def test_timeout_releases_mid_flight_slots(self):
        """A max_wall_s abort must hand mid-flight slots back — a leak
        here leaves the engine's next serve() with zero capacity. Uses a
        duck-typed fake engine (scheduler logic only, no compiles)."""
        import time as _time

        class _FakeTelemetry:
            enabled = False
            recompile_count = 0

            def span(self, *a, **k):
                import contextlib
                return contextlib.nullcontext()

        class _FakeEngine:
            max_slots, max_len = 2, 1000
            telemetry = _FakeTelemetry()

            def __init__(self):
                self.active = np.zeros(2, bool)
                from deepspeed_tpu.monitor.serving import ServingAggregator
                self.serving = ServingAggregator(2)

            def prefill(self, prompt, slot, temperature=0.0, **kw):
                return 1, None

            def activate_slot(self, slot, n, tok):
                self.active[slot] = True

            def release_slot(self, slot):
                self.active[slot] = False

            def context_len(self, slot):
                return 10

            def decode_once(self, temperature=0.0):
                self.serving.note_iteration(int(self.active.sum()), 1e-4)
                _time.sleep(0.001)
                return np.ones(2, np.int32), None

            def complete_request(self, *a, **k):
                self.serving.note_request(0.01, None, 1)

        eng = _FakeEngine()
        reqs = [dataclasses.replace(r, max_new_tokens=10 ** 6)
                for r in synthetic_requests(4, prompt_len=(4, 4))]
        sched = ContinuousBatchingScheduler(eng, max_wall_s=0.05)
        report = sched.serve(reqs)
        assert report["unfinished"] > 0          # the abort really hit
        assert not eng.active.any(), "timeout leaked active slots"

    def test_poisson_arrivals_are_open_loop(self):
        reqs = synthetic_requests(10, rate_rps=100.0, seed=3)
        arr = [r.arrival_s for r in reqs]
        assert arr == sorted(arr) and arr[0] == 0.0 and arr[-1] > 0.0
        # Reproducible stream.
        again = synthetic_requests(10, rate_rps=100.0, seed=3)
        assert [r.arrival_s for r in again] == arr
        assert all((r.prompt == a.prompt).all()
                   for r, a in zip(reqs, again))


# --------------------------------------------------------------------- #
# Tensor-parallel serving (TP head-sharded cache)
# --------------------------------------------------------------------- #
class TestTensorParallelServing:
    def test_mp2_decode_matches_full_forward(self, params32):
        mesh = build_mesh(mp=2)           # dp=4 x mp=2
        eng = InferenceEngine(CFG32, params32, config={
            "inference": {"max_slots": 8, "max_seq_len": 32,
                          "prefill_chunk": 8}},
            mesh=mesh, param_shardings=gpt2_param_shardings(CFG32))
        prompt = _prompt(9, seed=9)
        tok, logits = eng.prefill(prompt, slot=0, return_logits=True)
        np.testing.assert_allclose(logits,
                                   _ref_last_logits(params32, prompt),
                                   atol=1e-4)
        eng.activate_slot(0, len(prompt), tok)
        sampled, lg = eng.decode_once(return_logits=True)
        np.testing.assert_allclose(
            lg[0], _ref_last_logits(params32, list(prompt) + [tok]),
            atol=1e-4)
        eng.close()


# --------------------------------------------------------------------- #
# Quantization
# --------------------------------------------------------------------- #
class TestQuantize:
    def test_int8_roundtrip_error_bounded_by_scale(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (16, 24),
                              jnp.float32) * 0.05
        q = quantize_leaf_int8(w, jax.random.PRNGKey(1))
        assert q["q"].dtype == jnp.int8
        dq = np.asarray(q["q"].astype(jnp.float32) * q["scale"])
        scale = np.asarray(q["scale"])
        assert (np.abs(dq - np.asarray(w)) <= scale + 1e-7).all(), \
            "stochastic rounding moves at most one grid step"

    def test_int8_tree_quantizes_matrices_only(self, params32):
        q = quantize_params(params32, "int8", jax.random.PRNGKey(2))
        assert q["blocks"]["qkv_kernel"]["q"].dtype == jnp.int8
        assert q["ln_f_scale"].dtype == jnp.float32, "vectors untouched"
        dq = dequantize(q, jnp.float32)
        w, w0 = np.asarray(dq["wte"]), np.asarray(params32["wte"])
        assert np.abs(w - w0).max() < np.abs(w0).max() / 64

    def test_bf16_mode_uses_stochastic_rounding_machinery(self, params32):
        q = quantize_params(params32, "bf16", jax.random.PRNGKey(3))
        assert all(l.dtype == jnp.bfloat16
                   for l in jax.tree_util.tree_leaves(q))

    def test_int8_engine_serves(self, params32):
        eng = InferenceEngine(CFG32, params32, config={
            "inference": {"max_slots": 8, "max_seq_len": 32,
                          "prefill_chunk": 8, "quantize": "int8"}})
        assert eng.param_bytes < 2 * sum(
            l.size * 4 for l in jax.tree_util.tree_leaves(params32)) / 3
        prompt = _prompt(9, seed=10)
        tok, logits = eng.prefill(prompt, slot=0, return_logits=True)
        assert np.isfinite(logits).all()
        ref = _ref_last_logits(params32, prompt)
        assert np.corrcoef(logits, ref)[0, 1] > 0.99
        eng.close()


# --------------------------------------------------------------------- #
# Training-checkpoint → serving handoff
# --------------------------------------------------------------------- #
class TestCheckpointHandoff:
    def test_from_train_checkpoint_greedy_parity(self, tmp_path,
                                                 params32):
        import deepspeed_tpu
        from deepspeed_tpu.models.gpt2 import gpt2_loss_fn
        trainer, *_ = deepspeed_tpu.initialize(
            model=gpt2_loss_fn(CFG32), model_params=params32,
            config={"train_batch_size": 8,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "steps_per_print": 10 ** 9})
        trainer.save_checkpoint(str(tmp_path), tag="handoff")
        trained = jax.device_get(trainer.state.params)

        eng = InferenceEngine.from_train_checkpoint(
            str(tmp_path), CFG32, config={
                "inference": {"max_slots": 8, "max_seq_len": 32,
                              "prefill_chunk": 8}})
        prompt = _prompt(7, seed=11)
        tok, logits = eng.prefill(prompt, slot=0, return_logits=True)
        ref = np.asarray(gpt2_apply(
            trained, jnp.asarray(prompt)[None], CFG32))[0, -1]
        np.testing.assert_allclose(logits, ref, atol=1e-4)
        assert tok == int(ref.argmax())
        eng.close()

    def test_missing_checkpoint_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            InferenceEngine.from_train_checkpoint(str(tmp_path), CFG32)
