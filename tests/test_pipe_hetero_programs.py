"""Heterogeneous per-stage PROGRAMS (conv stem + transformer-style body)
through the compiled SPMD pipeline — the reference partitions arbitrary
layer lists per rank (runtime/pipe/module.py:348-404); here the
run-all-and-select construction (runtime/pipe/hetero.py) gives each stage
its own program while keeping one uniform SPMD tick."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel.topology import build_mesh
from deepspeed_tpu.runtime.pipe.hetero import hetero_pipe_spec

pytestmark = pytest.mark.slow  # whole-module slow tier (see conftest)

H, V, S = 16, 48, 12


def conv_prog(p, x, rng):
    """Causal depthwise conv stem, kernel 3: a genuinely different program
    from the body (no matmul over H)."""
    x1 = jnp.pad(x, ((0, 0), (1, 0), (0, 0)))[:, :-1]
    x2 = jnp.pad(x, ((0, 0), (2, 0), (0, 0)))[:, :-2]
    return jnp.tanh(x * p["w0"] + x1 * p["w1"] + x2 * p["w2"])


def mlp_prog(p, x, rng):
    return x + jnp.tanh(x @ p["a"]) @ p["b"]


def make_params(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 6)
    conv = {"w0": jax.random.normal(ks[0], (H,)) * 0.5,
            "w1": jax.random.normal(ks[1], (H,)) * 0.5,
            "w2": jax.random.normal(ks[2], (H,)) * 0.5}
    mlp = {"a": jax.random.normal(ks[3], (H, H)) * 0.3,
           "b": jax.random.normal(ks[4], (H, H)) * 0.3}
    shared = {"wte": jax.random.normal(ks[5], (V, H)) * 0.3}
    return conv, mlp, shared


def embed_fn(shared, tokens, rng):
    return shared["wte"][tokens]


def head_fn(shared, x, targets, rng):
    logits = x @ shared["wte"].T
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, targets[..., None],
                                         axis=-1))


def build_spec(seed=0):
    conv, mlp, shared = make_params(seed)
    return hetero_pipe_spec(
        embed_fn, head_fn, [conv_prog, mlp_prog], [0, 1], [conv, mlp],
        shared_params=shared, sample_x=jnp.zeros((2, S, H)))


def sequential_loss(params, batch, rng):
    tokens, targets = batch[:, :-1], batch[:, 1:]
    x = embed_fn(params["shared"], tokens, rng)
    x = conv_prog(jax.tree_util.tree_map(lambda a: a[0],
                                         params["blocks"]["prog0"]), x, rng)
    x = mlp_prog(jax.tree_util.tree_map(lambda a: a[1],
                                        params["blocks"]["prog1"]), x, rng)
    return head_fn(params["shared"], x, targets, rng)


@pytest.fixture(scope="module")
def batch():
    return jax.random.randint(jax.random.PRNGKey(9), (4, S + 1), 0, V)


def test_build_validates_shapes_and_programs():
    conv, mlp, shared = make_params()
    with pytest.raises(ValueError):       # program index gap
        hetero_pipe_spec(embed_fn, head_fn, [conv_prog, mlp_prog],
                         [0, 0], [conv, conv], shared_params=shared)
    bad_mlp = dict(mlp, a=jnp.zeros((H, 2 * H)))

    def widen(p, x, rng):                 # breaks the boundary shape
        return jnp.tanh(x @ p["a"])

    with pytest.raises(ValueError):
        hetero_pipe_spec(embed_fn, head_fn, [conv_prog, widen],
                         [0, 1], [conv, bad_mlp], shared_params=shared,
                         sample_x=jnp.zeros((2, S, H)))


def downcast(p, x, rng):                  # breaks the boundary dtype
    return jnp.tanh(x @ p["a"]).astype(jnp.bfloat16) @ \
        p["b"].astype(jnp.bfloat16)


def test_build_validates_dtype_with_sample():
    conv, mlp, shared = make_params()
    with pytest.raises(ValueError, match="boundary dtype"):
        hetero_pipe_spec(embed_fn, head_fn, [conv_prog, downcast],
                         [0, 1], [conv, mlp], shared_params=shared,
                         sample_x=jnp.zeros((2, S, H)))


def test_build_validates_without_sample_x():
    """No ``sample_x``: the check still fires the first time the stage
    program is traced (pipeline build), shape- and dtype-changing modes
    alike — a real message, not an opaque select_n mismatch."""
    conv, mlp, shared = make_params()
    bad_mlp = dict(mlp, a=jnp.zeros((H, 2 * H)))

    def widen(p, x, rng):
        return jnp.tanh(x @ p["a"])

    x = jnp.zeros((2, S, H))
    rng = jax.random.PRNGKey(0)

    spec = hetero_pipe_spec(embed_fn, head_fn, [conv_prog, widen],
                            [0, 1], [conv, bad_mlp], shared_params=shared)
    with pytest.raises(ValueError, match="boundary shape"):
        jax.eval_shape(spec.stage_fn, spec.params["blocks"], x, rng)

    spec = hetero_pipe_spec(embed_fn, head_fn, [conv_prog, downcast],
                            [0, 1], [conv, mlp], shared_params=shared)
    with pytest.raises(ValueError, match="boundary dtype"):
        jax.eval_shape(spec.stage_fn, spec.params["blocks"], x, rng)


class TestParity:
    def test_gpipe_loss_and_grads_match_sequential(self, batch):
        spec = build_spec()
        mesh = build_mesh(pp=2, dp=1, devices=jax.devices()[:2])
        loss_fn = spec.loss_fn(num_stages=2, num_micro=2, mesh=mesh)
        rng = jax.random.PRNGKey(3)
        with jax.set_mesh(mesh):
            l_pipe, g_pipe = jax.jit(jax.value_and_grad(loss_fn))(
                spec.params, batch, rng)
        l_seq, g_seq = jax.value_and_grad(sequential_loss)(
            spec.params, batch, rng)
        np.testing.assert_allclose(float(l_pipe), float(l_seq), rtol=1e-5)
        # Owned slices match; unowned zero-padded slices get ZERO grads.
        for k in ("w0", "w1", "w2"):
            np.testing.assert_allclose(
                np.asarray(g_pipe["blocks"]["prog0"][k]),
                np.asarray(g_seq["blocks"]["prog0"][k]),
                rtol=1e-4, atol=1e-6)
            assert not np.any(np.asarray(g_pipe["blocks"]["prog0"][k][1]))
        for k in ("a", "b"):
            np.testing.assert_allclose(
                np.asarray(g_pipe["blocks"]["prog1"][k]),
                np.asarray(g_seq["blocks"]["prog1"][k]),
                rtol=1e-4, atol=1e-6)
            assert not np.any(np.asarray(g_pipe["blocks"]["prog1"][k][0]))
        np.testing.assert_allclose(
            np.asarray(g_pipe["shared"]["wte"]),
            np.asarray(g_seq["shared"]["wte"]), rtol=1e-4, atol=1e-6)

    def test_1f1b_grads_match_sequential(self, batch):
        spec = build_spec(seed=1)
        mesh = build_mesh(pp=2, dp=1, devices=jax.devices()[:2])
        gfn = spec.grads_fn(num_stages=2, num_micro=2, mesh=mesh)
        rng = jax.random.PRNGKey(4)
        with jax.set_mesh(mesh):
            l_pipe, g_pipe = jax.jit(gfn)(spec.params, batch, rng)
        l_seq, g_seq = jax.value_and_grad(sequential_loss)(
            spec.params, batch, rng)
        np.testing.assert_allclose(float(l_pipe), float(l_seq), rtol=1e-5)
        for prog, keys in (("prog0", ("w0", "w1", "w2")),
                           ("prog1", ("a", "b"))):
            for k in keys:
                np.testing.assert_allclose(
                    np.asarray(g_pipe["blocks"][prog][k]),
                    np.asarray(g_seq["blocks"][prog][k]),
                    rtol=1e-4, atol=1e-6, err_msg=f"{prog}/{k}")


def test_engine_trains_hetero_pipeline_pp2_dp2():
    """Full engine path: conv-stem + MLP-body pipeline under the 1F1B
    schedule x ZeRO-1 on a pp=2 x dp=2 mesh, loss falls on a fixed batch."""
    spec = build_spec(seed=2)
    mesh = build_mesh(pp=2, dp=2, devices=jax.devices()[:4])
    ds = {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 2,
          "gradient_accumulation_steps": 2,
          "zero_optimization": {"stage": 1},
          "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
          "pipeline": {"schedule": "1f1b"}, "steps_per_print": 10 ** 9}
    engine, _, _, _ = deepspeed_tpu.initialize(model=spec, config=ds,
                                               mesh=mesh)
    b = np.asarray(jax.random.randint(jax.random.PRNGKey(11), (8, S + 1),
                                      0, V))
    losses = [float(engine.train_batch(jnp.asarray(b))) for _ in range(30)]
    assert losses[-1] < losses[0] - 0.5, losses[:: 10]
    # The zero-padded (unowned) program slices must stay exactly zero:
    # their grads are zero, so the optimizer never moves them.
    blocks = jax.device_get(engine.state.params)["blocks"]
    assert not np.any(np.asarray(blocks["prog0"]["w0"][1]))
    assert not np.any(np.asarray(blocks["prog1"]["a"][0]))
