"""ZeRO-2 "grads born sharded" tests.

The reference's stage 2 guarantees gradients are never materialized
unpartitioned: hooks copy them into an IPG bucket and reduce each slice to
its owner rank (stage2.py:613-738). Here that property is declarative — the
grad-accumulation carry is constrained dp-sharded — and these tests pin it
at the compiled-program level:

- the jitted backward's gradient outputs carry a dp ('data') sharding, with
  per-chip shard bytes = full/dp;
- the train step's scan carry holds only the SHARDED grad buffer (the
  full-size fp32 grad tensor never appears in the loop state);
- the cross-dp reduction compiles to reduce-scatter (TPU) or its
  all-reduce+slice CPU lowering — either way consuming sharded outputs.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from simple_model import (simple_model_params, simple_loss_fn, random_batch,
                          base_config)


def _stage2_engine(gas=2):
    params = simple_model_params(jax.random.PRNGKey(0))
    cfg = base_config(zero_optimization={"stage": 2},
                      gradient_accumulation_steps=gas,
                      train_batch_size=16 * gas)
    engine, *_ = deepspeed_tpu.initialize(
        model=simple_loss_fn, model_params=params, config=cfg)
    return engine


class TestZero2GradSharding:
    def test_backward_grads_born_sharded(self):
        """Grads leave the jitted backward already partitioned over dp."""
        engine = _stage2_engine()
        engine._build_grad_paths()
        g, _ = engine._grad_step_fn(engine.state.params, random_batch(n=8),
                                    jax.random.PRNGKey(1),
                                    engine.state.loss_scale)
        # w1 is [8,16]; with dp=8 each chip must hold a [1,16] shard.
        assert "data" in str(g["w1"].sharding.spec)
        shard = g["w1"].addressable_shards[0].data
        assert shard.shape == (1, 16), shard.shape
        # numerical parity with the unsharded gradient (the engine's grad
        # path pre-divides by gas for accumulation averaging)
        gas = engine.gradient_accumulation_steps()
        dense = jax.grad(lambda p: simple_loss_fn(
            p, random_batch(n=8), jax.random.PRNGKey(1)))(
                jax.device_get(engine.state.params))
        np.testing.assert_allclose(np.asarray(g["w1"], np.float32) * gas,
                                   np.asarray(dense["w1"], np.float32),
                                   rtol=1e-5, atol=1e-6)

    def test_train_step_carry_holds_sharded_grads_only(self):
        """The scan carry contains the 1/dp grad shard, never the full
        fp32 grad tensor (per-chip grad memory = size/dp)."""
        engine = _stage2_engine()
        fn = engine._build_train_step()
        mb = engine._stack_micro_batches(random_batch(n=32))
        mb = jax.device_put(mb, engine._batch_sharding(mb, leading_dims=2))
        txt = fn.lower(engine.state, mb, engine._base_rng).compile().as_text()
        while_lines = [l for l in txt.splitlines() if " while(" in l]
        assert while_lines, "no scan loop found in compiled HLO"
        carry = while_lines[0]
        # sharded grad buffers for w1 [8,16]->[1,16] and w2 [16,4]->[2,4]
        assert "f32[1,16]" in carry, carry
        assert "f32[2,4]" in carry, carry
        # the dp-sharded cross-chip reduction exists: reduce-scatter on TPU,
        # or XLA:CPU's all-reduce (+slice into the sharded carry) lowering.
        assert ("reduce-scatter" in txt) or ("all-reduce" in txt)

    def test_reduce_scatter_false_keeps_replicated_grads(self):
        """``reduce_scatter: false`` honestly selects the dense all-reduce
        path (reference semantics): no grad shardings, grads materialize
        replicated — the knob acts instead of being docstring-advisory."""
        params = simple_model_params(jax.random.PRNGKey(0))
        cfg = base_config(zero_optimization={"stage": 2,
                                             "reduce_scatter": False},
                          gradient_accumulation_steps=2,
                          train_batch_size=32)
        engine, *_ = deepspeed_tpu.initialize(
            model=simple_loss_fn, model_params=params, config=cfg)
        assert engine._grad_sync_mode == "allreduce"
        assert engine._grad_shardings() is None
        engine._build_grad_paths()
        g, _ = engine._grad_step_fn(engine.state.params, random_batch(n=8),
                                    jax.random.PRNGKey(1),
                                    engine.state.loss_scale)
        assert "data" not in str(g["w1"].sharding.spec)

    def test_stage1_keeps_replicated_grads(self):
        """Contrast: stage 1 shards optimizer state but not the grad buffer
        (reference stage1 reduces full grads then scatters ownership)."""
        params = simple_model_params(jax.random.PRNGKey(0))
        cfg = base_config(zero_optimization={"stage": 1},
                          gradient_accumulation_steps=2,
                          train_batch_size=32)
        engine, *_ = deepspeed_tpu.initialize(
            model=simple_loss_fn, model_params=params, config=cfg)
        assert engine._grad_shardings() is None

    def test_stage2_trains_to_parity(self):
        """Same seed + batch: stage 2 loss trajectory == stage 0's."""
        batch = random_batch(n=32, seed=5)
        p0 = simple_model_params(jax.random.PRNGKey(3))
        e0, *_ = deepspeed_tpu.initialize(
            model=simple_loss_fn, model_params=p0,
            config=base_config(train_batch_size=32,
                               gradient_accumulation_steps=2))
        e2 = _stage2_engine()
        # reset to identical params
        e2.state = e2._place_state(e2.state.replace(
            params=jax.device_get(e0.state.params)))
        for _ in range(5):
            l0 = e0.train_batch(batch=batch)
            l2 = e2.train_batch(batch=batch)
        np.testing.assert_allclose(float(l0), float(l2), rtol=1e-4)
