"""Module injection: HF Flax tiny-BERT / tiny-GPT-2 forward parity through
the in-repo transformer blocks, and bidirectional weight-copy identity.

Reference: module_inject/inject.py (qkv concat copy :27-41, reverse copy)
and its test pattern (HF BertEncoder vs DeepSpeedTransformerLayer outputs,
tests/unit/test_cuda_forward.py).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

transformers = pytest.importorskip("transformers")

from deepspeed_tpu.models.transformer import apply_blocks, dense_attention
from deepspeed_tpu.module_inject import (bert_config_from_hf,
                                         extract_bert_encoder,
                                         gpt2_config_from_hf,
                                         extract_gpt2_blocks,
                                         restore_bert_encoder,
                                         restore_gpt2_blocks)

pytestmark = pytest.mark.slow  # whole-module slow tier (see conftest)


@pytest.fixture(scope="module")
def tiny_bert():
    from transformers import BertConfig
    from transformers.models.bert.modeling_flax_bert import FlaxBertModel
    cfg = BertConfig(hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=128,
                     vocab_size=100, max_position_embeddings=32,
                     hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    return FlaxBertModel(cfg, seed=0), cfg


@pytest.fixture(scope="module")
def tiny_gpt2():
    from transformers import GPT2Config
    from transformers.models.gpt2.modeling_flax_gpt2 import FlaxGPT2Model
    cfg = GPT2Config(n_embd=64, n_layer=2, n_head=4, vocab_size=100,
                     n_positions=32, resid_pdrop=0.0, attn_pdrop=0.0,
                     embd_pdrop=0.0)
    return FlaxGPT2Model(cfg, seed=0), cfg


def test_bert_encoder_forward_parity(tiny_bert):
    model, hf_cfg = tiny_bert
    ds_cfg = bert_config_from_hf(hf_cfg)
    stacked = extract_bert_encoder(model.params)

    tokens = np.arange(2 * 16).reshape(2, 16) % 100
    hf_out = model(input_ids=tokens, output_hidden_states=True)
    # embeddings output = encoder input
    emb = np.asarray(hf_out.hidden_states[0])

    ours = apply_blocks(stacked, jnp.asarray(emb), ds_cfg,
                        deterministic=True, attention_fn=dense_attention)
    np.testing.assert_allclose(np.asarray(ours),
                               np.asarray(hf_out.last_hidden_state),
                               rtol=2e-5, atol=2e-5)


def test_gpt2_blocks_forward_parity(tiny_gpt2):
    model, hf_cfg = tiny_gpt2
    ds_cfg = gpt2_config_from_hf(hf_cfg)
    stacked = extract_gpt2_blocks(model.params)

    tokens = (np.arange(2 * 16).reshape(2, 16) * 7) % 100
    hf_out = model(input_ids=tokens, output_hidden_states=True)
    emb = np.asarray(hf_out.hidden_states[0])

    ours = apply_blocks(stacked, jnp.asarray(emb), ds_cfg,
                        deterministic=True, attention_fn=dense_attention)
    # GPT-2's final hidden state has ln_f applied; compare pre-ln_f
    # hidden_states[-1]... HF hidden_states[-1] == last_hidden_state
    # (post ln_f), so apply ln_f ourselves.
    lnf = model.params["ln_f"]
    x32 = np.asarray(ours, np.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    normed = (x32 - mu) / np.sqrt(var + hf_cfg.layer_norm_epsilon)
    ours_f = normed * np.asarray(lnf["scale"]) + np.asarray(lnf["bias"])
    np.testing.assert_allclose(ours_f, np.asarray(hf_out.last_hidden_state),
                               rtol=2e-5, atol=2e-5)


def test_bert_weight_copy_roundtrip(tiny_bert):
    model, _ = tiny_bert
    stacked = extract_bert_encoder(model.params)
    restored = restore_bert_encoder(stacked, model.params)
    for (pa, a), (pb, b) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(model.params),
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_leaves_with_path(restored),
                   key=lambda kv: str(kv[0]))):
        assert str(pa) == str(pb)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(pa))


def test_gpt2_weight_copy_roundtrip(tiny_gpt2):
    model, _ = tiny_gpt2
    stacked = extract_gpt2_blocks(model.params)
    restored = restore_gpt2_blocks(stacked, model.params)
    for (pa, a), (pb, b) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(model.params),
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_leaves_with_path(restored),
                   key=lambda kv: str(kv[0]))):
        assert str(pa) == str(pb)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(pa))


def test_injected_weights_modified_then_restored(tiny_bert):
    """Train-like mutation on the stacked side flows back to HF form."""
    model, _ = tiny_bert
    stacked = extract_bert_encoder(model.params)
    stacked2 = {k: v + 0.5 for k, v in stacked.items()}
    restored = restore_bert_encoder(stacked2, model.params)
    q0 = np.asarray(
        restored["encoder"]["layer"]["0"]["attention"]["self"]["query"]["kernel"])
    q0_orig = np.asarray(
        model.params["encoder"]["layer"]["0"]["attention"]["self"]["query"]["kernel"])
    np.testing.assert_allclose(q0, q0_orig + 0.5, rtol=1e-6)


# --------------------------------------------------------------------- #
# Policy registry (reference replace_module.py:160-192 mechanism)
# --------------------------------------------------------------------- #
@pytest.fixture(scope="module")
def tiny_roberta():
    from transformers import RobertaConfig
    from transformers.models.roberta.modeling_flax_roberta import \
        FlaxRobertaModel
    cfg = RobertaConfig(hidden_size=64, num_hidden_layers=2,
                        num_attention_heads=4, intermediate_size=128,
                        vocab_size=100, max_position_embeddings=34,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
    return FlaxRobertaModel(cfg, seed=0), cfg


def test_policy_registry_builtins(tiny_bert, tiny_gpt2, tiny_roberta):
    from deepspeed_tpu.module_inject import detect_policy, registered_policies
    assert {"bert", "roberta", "gpt2"} <= set(registered_policies())
    assert detect_policy(tiny_bert[1]).name == "bert"
    assert detect_policy(tiny_gpt2[1]).name == "gpt2"
    assert detect_policy(tiny_roberta[1]).name == "roberta"


def test_replace_module_generic_entry_roundtrip(tiny_bert):
    from deepspeed_tpu.module_inject import replace_module
    model, hf_cfg = tiny_bert
    cfg, stacked, restore_fn = replace_module(hf_cfg, model.params)
    assert cfg.num_layers == hf_cfg.num_hidden_layers
    restored = restore_fn(stacked)
    for (pa, a), (pb, b) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(model.params),
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_leaves_with_path(restored),
                   key=lambda kv: str(kv[0]))):
        assert str(pa) == str(pb)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_register_custom_policy(tiny_gpt2):
    """A user-registered policy is picked up by name and by detection —
    the extensibility the reference's policy dict provides."""
    from deepspeed_tpu.module_inject import (InjectionPolicy, get_policy,
                                             register_policy, replace_module)
    calls = []

    pol = InjectionPolicy(
        name="my-arch",
        detect=lambda c: getattr(c, "model_type", "") == "my-arch",
        config_from_hf=lambda c: "CFG",
        extract=lambda p: (calls.append("extract"), {"w": p["x"]})[1],
        restore=lambda s, p: {"x": s["w"]})
    register_policy(pol)
    try:
        assert get_policy("my-arch") is pol
        cfg, stacked, restore_fn = replace_module(
            object(), {"x": np.ones(3)}, policy="my-arch")
        assert cfg == "CFG" and calls == ["extract"]
        np.testing.assert_array_equal(restore_fn(stacked)["x"], np.ones(3))
        with pytest.raises(ValueError):
            register_policy(pol)          # duplicate name rejected
    finally:
        from deepspeed_tpu.module_inject import policy as _policy_mod
        _policy_mod._REGISTRY.pop("my-arch", None)


def test_replace_subtrees_walker():
    from deepspeed_tpu.module_inject import replace_subtrees
    tree = {"a": {"attn": {"w": 1}}, "b": {"attn": {"w": 2}}, "c": 3}
    out = replace_subtrees(
        tree, [(lambda p, t: p.endswith("attn"),
                lambda t: {"w": t["w"] * 10})])
    assert out == {"a": {"attn": {"w": 10}}, "b": {"attn": {"w": 20}},
                   "c": 3}
    assert tree["a"]["attn"]["w"] == 1    # input unmutated


def test_roberta_forward_parity_via_registry(tiny_roberta):
    """RoBERTa end-to-end through the registry: replace_module detects the
    roberta policy, and the stacked blocks reproduce the HF encoder."""
    from deepspeed_tpu.module_inject import replace_module
    model, hf_cfg = tiny_roberta
    ds_cfg, stacked, _ = replace_module(hf_cfg, model.params)
    tokens = np.arange(2 * 16).reshape(2, 16) % 100
    hf_out = model(input_ids=tokens, output_hidden_states=True)
    emb = np.asarray(hf_out.hidden_states[0])
    ours = apply_blocks(stacked, jnp.asarray(emb), ds_cfg,
                        deterministic=True, attention_fn=dense_attention)
    np.testing.assert_allclose(np.asarray(ours),
                               np.asarray(hf_out.last_hidden_state),
                               rtol=2e-5, atol=2e-5)


def test_roberta_sparse_swap_via_registry(tiny_roberta):
    """The sparse self-attention swap resolves RoBERTa through the policy
    registry (reference sparse_attention_utils.py:96-107 type dispatch)."""
    from deepspeed_tpu.ops.sparse_attention import SparseAttentionUtils
    from deepspeed_tpu.ops.sparse_attention.sparsity_config import \
        FixedSparsityConfig
    model, hf_cfg = tiny_roberta
    sp = FixedSparsityConfig(num_heads=4, block=16)
    encoder_fn, stacked, ds_cfg = \
        SparseAttentionUtils.replace_model_self_attention_with_sparse_self_attention(
            hf_cfg, model.params, sparsity_config=sp)
    x = np.random.default_rng(0).standard_normal((2, 32, 64)).astype(
        np.float32)
    out = encoder_fn(stacked, jnp.asarray(x))
    assert out.shape == (2, 32, 64)
    assert np.all(np.isfinite(np.asarray(out)))
