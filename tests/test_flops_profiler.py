"""Flops profiler tests — analytic counts + engine auto-run.

Mirrors reference tests/unit/test_flops_profiler.py (asserts measured flops
within tolerance of the analytic model formula).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.profiling.flops_profiler import (FlopsProfiler,
                                                    get_model_profile,
                                                    profile_fn)


def test_matmul_exact_count():
    a = jnp.ones((8, 32), jnp.float32)
    b = jnp.ones((32, 16), jnp.float32)
    res = profile_fn(lambda x, y: x @ y, a, b, run=False)
    assert res.total_macs == 8 * 32 * 16
    assert res.total_flops == 2 * 8 * 32 * 16


def test_scan_multiplies_body():
    def body(c, _):
        return jnp.tanh(c @ c), None

    def fn(x):
        y, _ = jax.lax.scan(body, x, None, length=5)
        return y

    x = jnp.ones((16, 16), jnp.float32)
    res = profile_fn(fn, x, run=False)
    assert res.total_macs == 5 * 16 * 16 * 16


def test_gpt2_tiny_counts_match_analytic():
    from deepspeed_tpu.models import GPT2_CONFIGS
    from deepspeed_tpu.models.gpt2 import gpt2_apply, gpt2_init, gpt2_num_params
    cfg = GPT2_CONFIGS["gpt2-tiny"]
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    B, S = 2, cfg.max_seq_length
    tokens = jnp.zeros((B, S), jnp.int32)
    res = profile_fn(lambda p, t: gpt2_apply(p, t, cfg), params, tokens,
                     run=False)
    # Exact forward MACs: per-block matmuls + attention + unembedding.
    n_tok = B * S
    H, L, F, V = cfg.hidden_size, cfg.num_layers, cfg.ffn_size, cfg.vocab_size
    per_block = 3 * H * H + H * H + 2 * H * F        # qkv, proj, fc, fc_out
    expected_macs = n_tok * (L * per_block + L * 2 * S * H + H * V)
    assert res.total_macs == expected_macs
    assert res.total_params == sum(int(np.prod(l.shape))
                                   for l in jax.tree_util.tree_leaves(params))
    # Module tree attributes the bulk to the blocks.
    top = dict((p, f) for p, f, _ in res.aggregate_by_depth(0))
    assert "gpt2_apply" in top
    assert top["gpt2_apply"] >= 0.99 * res.total_flops


def test_top_modules_and_format():
    a = jnp.ones((8, 8), jnp.float32)

    def mm(x):
        return x @ x

    res = profile_fn(mm, a, run=False)
    text = res.format_profile()
    assert "Flops Profiler" in text and "FLOPs" in text
    assert res.top_modules(1)


def test_get_model_profile_strings():
    a = jnp.ones((4, 4), jnp.float32)
    flops, macs, params = get_model_profile(
        lambda x: x @ x, (a,), print_profile=False, as_string=True)
    assert flops.endswith("FLOPs") and macs.endswith("MACs")


def test_engine_auto_profile(tmp_path, capsys):
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    def loss_fn(params, batch, rng):
        x, y = batch
        pred = x @ params["w"]
        return jnp.mean((pred - y) ** 2)

    n = jax.device_count()
    params = {"w": jnp.ones((8, 4), jnp.float32)}
    engine = DeepSpeedEngine(
        model=loss_fn, model_params=params,
        config={
            "train_batch_size": 2 * n,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "flops_profiler": {"enabled": True, "profile_step": 1},
            "steps_per_print": 10 ** 9,
        })
    batch = (jnp.ones((2 * n, 8)), jnp.zeros((2 * n, 4)))
    engine.train_batch(batch)          # step 0
    assert engine.flops_profiler.result is None
    engine.train_batch(batch)          # step 1 → profiled
    assert engine.flops_profiler.result is not None
    assert engine.flops_profiler.result.total_flops > 0
    out = capsys.readouterr().out
    assert "Flops Profiler" in out
