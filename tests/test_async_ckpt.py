"""Async preemption-safe checkpointing (runtime/async_ckpt.py + the
engine's snapshot/commit split).

Acceptance gates from the PR issue covered here (the subprocess crash
matrix lives in test_crash_matrix.py, the end-to-end kill/resume
trajectory in test_elastic.py):

- the async and sync save paths write BYTE-IDENTICAL artifacts (they
  share the snapshot builder and the commit);
- the snapshot phase performs exactly ONE batched device fetch
  (fence-asserted by counting jax.device_get calls);
- the background write OVERLAPS training: save_checkpoint returns in
  snapshot time, the writer's wall lands in the goodput ledger's
  background figure, not the exposed checkpoint bucket;
- ``latest`` flips atomically (no partial pointer, no tmp residue);
- SIGTERM triggers a final snapshot+commit and CHAINS to the previous
  handler;
- a failed background write surfaces on the next save instead of dying
  silently in the writer thread.
"""
import json
import os
import signal

import jax
import numpy as np
import pytest

from deepspeed_tpu.runtime import async_ckpt
from deepspeed_tpu.runtime.async_ckpt import (AsyncCheckpointer,
                                              CheckpointSnapshot,
                                              commit_snapshot, is_complete)
from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                          DeepSpeedConfigError)
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.parallel.topology import build_mesh

from simple_model import simple_loss_fn, simple_model_params, random_batch


def _engine(tmp_path, dp=2, ckpt=None, telemetry=None, seed=0, lr=1e-2):
    mesh = build_mesh(devices=jax.devices()[:dp])
    cfg = {
        "train_batch_size": 8 * dp,
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 1,
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam", "params": {"lr": lr}},
        "steps_per_print": 10 ** 9,
    }
    if ckpt is not None:
        cfg["checkpoint"] = ckpt
    if telemetry is not None:
        cfg["telemetry"] = telemetry
    return DeepSpeedEngine(model=simple_loss_fn,
                           model_params=simple_model_params(
                               jax.random.PRNGKey(seed)),
                           config=cfg, mesh=mesh)


def _leaves(eng):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(
        jax.device_get(eng.state.params))] + \
        [np.asarray(x) for x in jax.tree_util.tree_leaves(
            jax.device_get(eng.state.opt_state))]


# --------------------------------------------------------------------- #
# Config plumbing
# --------------------------------------------------------------------- #
class TestCheckpointConfig:
    def test_defaults(self):
        cfg = DeepSpeedConfig({"train_batch_size": 8})
        ck = cfg.checkpoint_config
        assert ck.async_save is False
        assert ck.snapshot_every == 0
        assert ck.save_dir == ""
        assert ck.preempt_save is True
        assert ck.max_pending_snapshots == 1
        assert ck.writer_timeout_s == 300.0
        assert ck.fsync is False

    def test_knobs_parse(self):
        cfg = DeepSpeedConfig({
            "train_batch_size": 8,
            "checkpoint": {"async": True, "snapshot_every": 50,
                           "save_dir": "/tmp/ck", "preempt_save": False,
                           "max_pending_snapshots": 2,
                           "writer_timeout_s": 10.5, "fsync": True}})
        ck = cfg.checkpoint_config
        assert ck.async_save and ck.fsync and not ck.preempt_save
        assert ck.snapshot_every == 50 and ck.save_dir == "/tmp/ck"
        assert ck.max_pending_snapshots == 2
        assert ck.writer_timeout_s == 10.5

    @pytest.mark.parametrize("bad", [
        {"async": "yes"},
        {"snapshot_every": -1},
        {"snapshot_every": 10},              # > 0 without save_dir
        {"max_pending_snapshots": 0, "save_dir": "/tmp/x"},
        {"writer_timeout_s": 0},
        {"fsync": 1},
    ])
    def test_invalid_raises(self, bad):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig({"train_batch_size": 8, "checkpoint": bad})


# --------------------------------------------------------------------- #
# Commit protocol (host-only units)
# --------------------------------------------------------------------- #
class TestCommitProtocol:
    def _snap(self, tmp_path, tag="t", payload=b"x" * 64):
        return CheckpointSnapshot(
            save_dir=str(tmp_path), tag=tag, save_latest=True,
            meta={"global_steps": 1},
            blobs=[("blob.bin", payload), ("lazy.bin", lambda: payload)])

    def test_commit_seals_and_flips_latest(self, tmp_path):
        commit_snapshot(self._snap(tmp_path))
        assert is_complete(tmp_path / "t")
        assert (tmp_path / "t" / "blob.bin").read_bytes() == b"x" * 64
        assert (tmp_path / "t" / "lazy.bin").read_bytes() == b"x" * 64
        assert (tmp_path / "latest").read_text() == "t"
        # No tmp residue of any phase of the protocol.
        assert sorted(os.listdir(tmp_path)) == ["latest", "t"]

    def test_same_tag_overwrite(self, tmp_path):
        commit_snapshot(self._snap(tmp_path, payload=b"old" * 10))
        commit_snapshot(self._snap(tmp_path, payload=b"new" * 10))
        assert (tmp_path / "t" / "blob.bin").read_bytes() == b"new" * 10
        assert sorted(os.listdir(tmp_path)) == ["latest", "t"]

    def test_stale_tmp_dir_cleared(self, tmp_path):
        stale = tmp_path / "t.tmp"
        stale.mkdir()
        (stale / "garbage").write_text("torn")
        commit_snapshot(self._snap(tmp_path))
        assert is_complete(tmp_path / "t")
        assert not stale.exists()

    def test_writer_error_surfaces_on_next_save(self, tmp_path):
        ck = AsyncCheckpointer(writer_timeout_s=5.0)
        try:
            def boom():
                raise OSError("disk gone")
            ck.submit(CheckpointSnapshot(
                save_dir=str(tmp_path), tag="bad", save_latest=True,
                meta={}, blobs=[("b.bin", boom)]))
            assert ck.wait(timeout=10)
            assert isinstance(ck.last_error, OSError)
            # latest untouched: the failed write never reached the flip.
            assert not (tmp_path / "latest").exists()
            assert not is_complete(tmp_path / "bad")
        finally:
            ck.close()

    def test_engine_raises_failed_background_write(self, tmp_path,
                                                   monkeypatch):
        eng = _engine(tmp_path, ckpt={"async": True})
        eng.train_batch(random_batch(16, seed=0))
        eng._async_ckpt.last_error = OSError("disk gone")
        with pytest.raises(RuntimeError, match="background checkpoint"):
            eng.save_checkpoint(str(tmp_path / "ck"))
        # The error is consumed: the retry goes through.
        assert eng.save_checkpoint(str(tmp_path / "ck"))
        assert eng._async_ckpt.wait(timeout=30)
        eng._async_ckpt.close()


# --------------------------------------------------------------------- #
# Snapshot discipline + artifact identity
# --------------------------------------------------------------------- #
class TestSnapshotAndIdentity:
    def test_snapshot_is_one_batched_fetch(self, tmp_path, monkeypatch):
        """The fence: the whole snapshot (params + moments + scalars)
        rides ONE jax.device_get — the telemetry drain's batched-fetch
        discipline applied to checkpointing."""
        eng = _engine(tmp_path)
        eng.train_batch(random_batch(16, seed=0))
        calls = []
        real = jax.device_get
        monkeypatch.setattr(jax, "device_get",
                            lambda x: calls.append(1) or real(x))
        snap = eng._snapshot_checkpoint(str(tmp_path), None, None, True)
        assert len(calls) == 1
        monkeypatch.undo()
        # The snapshot is complete: committing it yields a loadable tag.
        commit_snapshot(snap)
        eng2 = _engine(tmp_path, seed=3)
        p, _ = eng2.load_checkpoint(str(tmp_path))
        assert p is not None

    def test_async_and_sync_artifacts_bit_identical(self, tmp_path):
        eng = _engine(tmp_path, ckpt={"async": True})
        for i in range(3):
            eng.train_batch(random_batch(16, seed=i))
        eng.save_checkpoint(str(tmp_path / "a"), tag="t")
        assert eng._async_ckpt.wait(timeout=60)
        eng._async_ckpt.close()
        eng._async_ckpt = None          # reroute through the sync path
        eng.save_checkpoint(str(tmp_path / "s"), tag="t")
        files_a = sorted(os.listdir(tmp_path / "a" / "t"))
        files_s = sorted(os.listdir(tmp_path / "s" / "t"))
        assert files_a == files_s
        for fn in files_a:
            assert (tmp_path / "a" / "t" / fn).read_bytes() == \
                (tmp_path / "s" / "t" / fn).read_bytes(), fn

    def test_async_roundtrip_restores_state(self, tmp_path):
        eng = _engine(tmp_path, ckpt={"async": True}, lr=5e-2)
        for i in range(4):
            eng.train_batch(random_batch(16, seed=i))
        eng.save_checkpoint(str(tmp_path))
        assert eng._async_ckpt.wait(timeout=60)
        eng2 = _engine(tmp_path, seed=9, lr=5e-2)
        p, _ = eng2.load_checkpoint(str(tmp_path))
        assert p is not None
        for a, b in zip(_leaves(eng), _leaves(eng2)):
            np.testing.assert_array_equal(a, b)
        eng._async_ckpt.close()


# --------------------------------------------------------------------- #
# Auto-save cadence + overlap + goodput pricing
# --------------------------------------------------------------------- #
class TestAutoSaveAndOverlap:
    def test_snapshot_every_auto_saves(self, tmp_path):
        d = str(tmp_path / "auto")
        eng = _engine(tmp_path, ckpt={"async": True, "snapshot_every": 2,
                                      "save_dir": d})
        for i in range(5):
            eng.train_batch(random_batch(16, seed=i))
        assert eng._async_ckpt.wait(timeout=60)
        tags = sorted(t for t in os.listdir(d) if t.startswith("global"))
        assert tags == ["global_step2", "global_step4"]
        assert (tmp_path / "auto" / "latest").read_text() == "global_step4"
        for t in tags:
            assert is_complete(os.path.join(d, t))
        eng._async_ckpt.close()

    def test_trio_step_honors_snapshot_every(self, tmp_path):
        """The forward/backward/step driver hits the same auto-save
        cadence as train_batch — snapshot_every is a property of the
        optimizer-step boundary, not of one entry point."""
        d = str(tmp_path / "auto")
        eng = _engine(tmp_path, ckpt={"snapshot_every": 2, "save_dir": d})
        for i in range(4):
            loss = eng.forward(random_batch(16, seed=i))
            eng.backward(loss)
            eng.step()
        tags = sorted(t for t in os.listdir(d) if t.startswith("global"))
        assert tags == ["global_step2", "global_step4"]
        assert (tmp_path / "auto" / "latest").read_text() == "global_step4"

    def test_concurrent_same_tag_commits_stay_whole(self, tmp_path):
        """The preemption-save-races-wedged-writer scenario: two commits
        of the SAME tag from different threads stage in their own tmp
        dirs; whichever publishes last wins WHOLE (never a sealed dir
        missing the other commit's blobs)."""
        import threading as _t
        payload_a = {"blob0.bin": b"A" * 4096, "blob1.bin": b"a" * 4096}
        payload_b = {"blob0.bin": b"B" * 4096, "blob1.bin": b"b" * 4096}

        def snap(payload):
            return CheckpointSnapshot(
                save_dir=str(tmp_path), tag="t", save_latest=True,
                meta={"who": payload["blob0.bin"][:1].decode()},
                blobs=list(payload.items()))

        for _ in range(5):
            ts = [_t.Thread(target=commit_snapshot, args=(snap(pl),))
                  for pl in (payload_a, payload_b)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert is_complete(tmp_path / "t")
            meta = json.load(open(tmp_path / "t" / "engine_meta.json"))
            blobs = {fn: (tmp_path / "t" / fn).read_bytes()
                     for fn in ("blob0.bin", "blob1.bin")}
            # Whole = every file from ONE commit, matching the seal.
            want = payload_a if meta["who"] == "A" else payload_b
            assert blobs == want

    def test_background_write_overlaps_and_is_priced(self, tmp_path,
                                                     monkeypatch):
        """With a slowed writer, save_checkpoint returns in snapshot
        time; the writer's wall lands in the ledger's BACKGROUND figure
        and the exposed checkpoint bucket stays a fraction of it."""
        import time as _time
        monkeypatch.setenv("DS_CKPT_TEST_WRITE_DELAY_S", "0.15")
        eng = _engine(tmp_path, ckpt={"async": True},
                      telemetry={"enabled": True,
                                 "output_path": str(tmp_path / "runs"),
                                 "job_name": "run",
                                 "report_steps": 1000})
        eng.train_batch(random_batch(16, seed=0))
        t0 = _time.perf_counter()
        eng.save_checkpoint(str(tmp_path / "ck"))
        exposed = _time.perf_counter() - t0
        # 3 blobs x 0.15s delay: an inline write would take >= 0.45s.
        assert exposed < 0.40, exposed
        assert eng._async_ckpt.wait(timeout=60)
        eng.telemetry.drain()
        summ = eng.telemetry.ledger.summary()
        assert summ["checkpoint_write_bg_s"] >= 0.40
        assert summ["checkpoint_snapshot_s"] > 0.0
        assert summ["checkpoint_s"] < summ["checkpoint_write_bg_s"]
        assert 0.0 <= summ["checkpoint_exposed_share"] < 1.0
        # The commit event carries the background write wall.
        eng._async_ckpt.close()
        evs = [e for e in eng.telemetry.events
               if e.get("event") == "checkpoint_commit"]
        assert evs and evs[0]["write_s"] >= 0.40
        eng.telemetry.close()

    def test_max_pending_bounds_host_copies(self, tmp_path, monkeypatch):
        """The NEXT save blocks (exposed) until the writer has room —
        host memory is bounded at max_pending full-state copies."""
        import time as _time
        monkeypatch.setenv("DS_CKPT_TEST_WRITE_DELAY_S", "0.1")
        eng = _engine(tmp_path, ckpt={"async": True})
        eng.train_batch(random_batch(16, seed=0))
        t0 = _time.perf_counter()
        eng.save_checkpoint(str(tmp_path / "ck"), tag="a")
        first = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        eng.save_checkpoint(str(tmp_path / "ck"), tag="b")
        second = _time.perf_counter() - t0
        assert second > first + 0.1, (first, second)
        assert eng._async_ckpt.wait(timeout=60)
        assert is_complete(tmp_path / "ck" / "a")
        assert is_complete(tmp_path / "ck" / "b")
        assert (tmp_path / "ck" / "latest").read_text() == "b"
        eng._async_ckpt.close()

    def test_goodput_window_carries_ckpt_fields(self, tmp_path):
        eng = _engine(tmp_path, ckpt={"async": True},
                      telemetry={"enabled": True,
                                 "output_path": str(tmp_path / "runs"),
                                 "job_name": "run", "report_steps": 1000})
        eng.train_batch(random_batch(16, seed=0))
        eng.save_checkpoint(str(tmp_path / "ck"))
        assert eng._async_ckpt.wait(timeout=60)
        eng.telemetry.drain()
        eng.telemetry.close()
        eng._async_ckpt.close()
        recs = [json.loads(l) for l in
                open(tmp_path / "runs" / "run.jsonl")]
        reports = [r for r in recs if r["kind"] == "report"]
        gp = next(r["goodput"] for r in reports if "goodput" in r)
        assert "checkpoint_snapshot_s" in gp
        assert "checkpoint_write_bg_s" in gp
        # The background figure is OUTSIDE the accounted sum: the
        # window's bucket sum must still reconcile to the window wall.
        assert gp["consistent"]


# --------------------------------------------------------------------- #
# Preemption handler
# --------------------------------------------------------------------- #
class TestPreemptSave:
    def test_sigterm_saves_final_and_chains(self, tmp_path):
        d = str(tmp_path / "auto")
        seen = []
        prev = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
        try:
            eng = _engine(tmp_path,
                          ckpt={"snapshot_every": 100, "save_dir": d})
            for i in range(3):
                eng.train_batch(random_batch(16, seed=i))
            os.kill(os.getpid(), signal.SIGTERM)
            assert seen == [signal.SIGTERM]     # chained to prior handler
            assert (tmp_path / "auto" / "latest").read_text() == \
                "global_step3"
            assert is_complete(os.path.join(d, "global_step3"))
            # Handler uninstalled after firing: disposition is back on
            # the previous handler, not ours.
            assert signal.getsignal(signal.SIGTERM) not in \
                (eng._preempt_saver._on_signal,)
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_preempt_skips_when_step_already_saved(self, tmp_path):
        d = str(tmp_path / "auto")
        eng = _engine(tmp_path, ckpt={"snapshot_every": 2, "save_dir": d})
        for i in range(4):
            eng.train_batch(random_batch(16, seed=i))
        # Step 4 auto-saved; a preemption NOW has nothing new to write.
        before = os.path.getmtime(os.path.join(d, "global_step4"))
        assert eng.preempt_save() is True
        assert os.path.getmtime(os.path.join(d, "global_step4")) == before
        tags = sorted(t for t in os.listdir(d) if t.startswith("global"))
        assert tags == ["global_step2", "global_step4"]

    def test_preempt_waits_for_inflight_write(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DS_CKPT_TEST_WRITE_DELAY_S", "0.1")
        d = str(tmp_path / "auto")
        eng = _engine(tmp_path, ckpt={"async": True, "snapshot_every": 1,
                                      "save_dir": d})
        eng.train_batch(random_batch(16, seed=0))   # auto-save queues
        assert eng.preempt_save() is True           # waits, no double save
        assert not eng._async_ckpt.in_flight
        assert (tmp_path / "auto" / "latest").read_text() == "global_step1"
        assert is_complete(os.path.join(d, "global_step1"))
        eng._async_ckpt.close()

    def test_preempt_falls_through_after_failed_background_write(
            self, tmp_path):
        """_last_saved_step is stamped at SUBMIT time; when the
        background write failed, the preemption handler must NOT trust
        it — it saves inline and clears the stale error (the inline
        commit superseded the lost write)."""
        d = str(tmp_path / "auto")
        eng = _engine(tmp_path, ckpt={"async": True, "snapshot_every": 1,
                                      "save_dir": d})
        eng.train_batch(random_batch(16, seed=0))
        assert eng._async_ckpt.wait(timeout=60)
        # Simulate the auto-save's write having failed after submit.
        eng._async_ckpt.last_error = OSError("disk gone")
        assert eng.preempt_save() is True
        assert (tmp_path / "auto" / "latest").read_text() == "global_step1"
        assert is_complete(os.path.join(d, "global_step1"))
        assert eng._async_ckpt.last_error is None
        eng._async_ckpt.close()

    def test_wedged_writer_fails_save_loudly(self, tmp_path, monkeypatch):
        """A writer still busy after writer_timeout_s fails the NEXT
        save instead of queueing another full-state host copy past the
        max_pending_snapshots bound."""
        monkeypatch.setenv("DS_CKPT_TEST_WRITE_DELAY_S", "0.4")
        eng = _engine(tmp_path, ckpt={"async": True,
                                      "writer_timeout_s": 0.2})
        eng.train_batch(random_batch(16, seed=0))
        eng.save_checkpoint(str(tmp_path / "ck"), tag="a")
        with pytest.raises(RuntimeError, match="writer still busy"):
            eng.save_checkpoint(str(tmp_path / "ck"), tag="b")
        assert eng._async_ckpt.wait(timeout=60)
        assert is_complete(tmp_path / "ck" / "a")
        eng._async_ckpt.close()

    def test_no_handler_without_save_dir(self, tmp_path):
        before = signal.getsignal(signal.SIGTERM)
        eng = _engine(tmp_path, ckpt={"async": True})
        assert eng._preempt_saver is None
        assert signal.getsignal(signal.SIGTERM) == before
        eng._async_ckpt.close()


# --------------------------------------------------------------------- #
# Load-side hardening (atomic latest + torn-tag refusal)
# --------------------------------------------------------------------- #
class TestLoadHardening:
    def test_latest_written_atomically_no_residue(self, tmp_path):
        eng = _engine(tmp_path)
        eng.train_batch(random_batch(16, seed=0))
        eng.save_checkpoint(str(tmp_path), tag="t")
        names = os.listdir(tmp_path)
        assert "latest" in names
        assert not [n for n in names if n.startswith("latest.tmp")]
        assert (tmp_path / "latest").read_text() == "t"

    def test_torn_tag_refused_with_state_untouched(self, tmp_path):
        eng = _engine(tmp_path, lr=5e-2)
        eng.train_batch(random_batch(16, seed=0))
        eng.save_checkpoint(str(tmp_path), tag="t")
        os.remove(tmp_path / "t" / "engine_meta.json")   # tear the seal
        eng2 = _engine(tmp_path, seed=7)
        before = _leaves(eng2)
        p, client = eng2.load_checkpoint(str(tmp_path))
        assert p is None and client == {}
        assert eng2.global_steps == 0
        for a, b in zip(before, _leaves(eng2)):
            np.testing.assert_array_equal(a, b)

    def test_latest_to_missing_dir_refused(self, tmp_path):
        (tmp_path / "latest").write_text("ghost")
        eng = _engine(tmp_path)
        p, client = eng.load_checkpoint(str(tmp_path))
        assert p is None and client == {}
