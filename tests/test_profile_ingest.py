"""Trace-truth profiling: ingestion, reconciliation, and the honesty
machinery around them.

- **Classification**: HLO/kernel names land in the right measurement
  bucket (GEMM, Pallas family, ICI vs DCN collective, host transfer),
  with the documented precedences (collective beats a Pallas name
  match; ``sparse_flash`` is not shadowed by ``flash_attention``).
- **Decomposition**: the sweep line partitions covered time exactly
  under the bucket priority; buckets + idle + unattributed sum to the
  window wall (``explained_frac == 1.0``); runtime scaffold spans are
  dropped instead of double-covering real ops.
- **Perfetto validity**: TraceWriter's closed file is strict JSON, its
  pre-close file is the unterminated array form, lanes/pids are
  consistent, flow arrows are well-formed — and both forms round-trip
  through ``parse_trace_events`` with span counts preserved.
- **ProfilerWindow**: failed start/stop surface as structured
  ``profile_window`` events; a reused capture dir is refused, never
  silently overwritten.
- **Reconciliation**: measured-over-floor ratios, boundedness verdicts,
  and the seeded-divergence path — an injected host-sync stall is
  attributed to the ``host`` bucket and fires ``reconcile_divergence``.
- **Label ratchet** (tools/bench_gate.py): measured stays measured.
"""
import glob
import gzip
import importlib.util
import json
import os

import pytest

from deepspeed_tpu.monitor.cost_model import (BOUND_DCN, BOUND_HBM,
                                              BOUND_INTERCONNECT)
from deepspeed_tpu.monitor.profile_ingest import (BUCKET_PRIORITY,
                                                  classify_op,
                                                  ingest,
                                                  ingest_events,
                                                  ingest_from_telemetry,
                                                  parse_trace_events)
from deepspeed_tpu.monitor.reconcile import (DEFAULT_HOST_FRAC,
                                             divergence_events,
                                             reconcile)
from deepspeed_tpu.monitor.trace import _LANES, ProfilerWindow, TraceWriter
from deepspeed_tpu.runtime.config import (DeepSpeedConfigError,
                                          TelemetryProfileConfig)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _ev(name, ts, dur, pid=1, tid=1, **args):
    """One complete trace event carrying an hlo_op arg (so its lane is
    recognized as a device lane)."""
    return {"name": name, "ph": "X", "pid": pid, "tid": tid,
            "ts": float(ts), "dur": float(dur),
            "args": dict({"hlo_op": name}, **args)}


# --------------------------------------------------------------------- #
# Classification
# --------------------------------------------------------------------- #
class TestClassifyOp:
    def test_gemm_ops(self):
        assert classify_op("dot.5")[0] == "gemm"
        assert classify_op("convolution.2")[0] == "gemm"
        # Fusions keep the root op identity through args["hlo_op"].
        assert classify_op("fusion.12", {"hlo_op": "dot.3"})[0] == "gemm"

    def test_collective_tiers(self):
        assert classify_op("all-reduce.1") == ("collective_ici", None)
        assert classify_op("reduce-scatter.4")[0] == "collective_ici"
        # A DCN axis name or dcn marker moves the op to the DCN tier.
        assert classify_op("all-reduce.1",
                           {"hlo_module": "dcn"})[0] == "collective_dcn"
        assert classify_op("all-gather.2 slice")[0] == "collective_dcn"

    def test_host_ops(self):
        assert classify_op("TfrtCpuBuffer::Await")[0] == "host"
        assert classify_op("infeed.1")[0] == "host"
        assert classify_op("copy-start.3")[0] == "host"

    def test_pallas_families(self):
        cases = {"_ln_fwd_kernel": "fused_ln",
                 "_gelu_bwd_kernel": "fused_gelu",
                 "_fwd_kernel": "flash_attention",
                 "_gg_kernel": "grouped_gemm",
                 "_pattn_kernel": "paged_attention",
                 "_fused_adam_kernel": "fused_update"}
        for name, family in cases.items():
            assert classify_op(name) == ("pallas", family), name

    def test_sparse_flash_not_shadowed(self):
        # _sfwd_kernel must hit sparse_flash, not flash_attention's
        # broader pattern (registry-order shadowing hazard).
        assert classify_op("_sfwd_kernel") == ("pallas", "sparse_flash")
        assert classify_op("_sdkv_kernel")[1] == "sparse_flash"

    def test_collective_beats_pallas_name(self):
        # An op that names both is wire time, not kernel time.
        assert classify_op("all_to_all_grouped_gemm")[0] == \
            "collective_ici"

    def test_unattributed_fallback(self):
        assert classify_op("transpose.7") == ("unattributed", None)


# --------------------------------------------------------------------- #
# Sweep-line decomposition
# --------------------------------------------------------------------- #
class TestDecomposition:
    def test_overlap_owned_by_higher_priority(self):
        # gemm [0,100), all-reduce [50,150): the overlap [50,100) is
        # wire time under the documented priority.
        out = ingest_events([_ev("dot.1", 0, 100),
                             _ev("all-reduce.1", 50, 100)])
        b = out["buckets_ms"]
        assert b["gemm"] == pytest.approx(0.050)
        assert b["collective_ici"] == pytest.approx(0.100)
        assert b["idle"] == pytest.approx(0.0)

    def test_buckets_plus_idle_sum_to_wall(self):
        out = ingest_events([_ev("dot.1", 0, 10),
                             _ev("all-reduce.2", 30, 20),
                             _ev("transpose.3", 90, 10)])
        sc = out["sum_check"]
        assert sc["explained_frac"] == pytest.approx(1.0)
        assert sc["decomposed_ms"] == pytest.approx(sc["wall_ms"])
        assert out["buckets_ms"]["idle"] == pytest.approx(0.060)

    def test_unattributed_is_never_clamped(self):
        out = ingest_events([_ev("mystery_op.9", 0, 50)])
        assert out["buckets_ms"]["unattributed"] == pytest.approx(0.050)
        assert out["sum_check"]["unattributed_ms"] == pytest.approx(0.050)

    def test_scaffold_spans_do_not_double_cover(self):
        # A runtime container span wrapping the whole program must not
        # count as busy time on top of the ops inside it.
        ev = [_ev("dot.1", 10, 20)]
        ev.append({"name": "ThunkExecutor::Execute", "ph": "X",
                   "pid": 1, "tid": 1, "ts": 0.0, "dur": 100.0})
        out = ingest_events(ev)
        assert out["buckets_ms"]["unattributed"] == pytest.approx(0.0)
        assert out["buckets_ms"]["gemm"] == pytest.approx(0.020)

    def test_per_step_division(self):
        out = ingest_events([_ev("dot.1", 0, 100)], n_steps=2)
        assert out["per_step_ms"]["gemm"] == pytest.approx(0.050)
        assert out["per_step_wall_ms"] == pytest.approx(out["wall_ms"] / 2)

    def test_pallas_family_attribution(self):
        out = ingest_events([_ev("_gg_kernel", 0, 40),
                             _ev("_pattn_kernel", 40, 10)])
        fams = out["pallas_families_ms"]
        assert fams["grouped_gemm"] == pytest.approx(0.040)
        assert fams["paged_attention"] == pytest.approx(0.010)
        assert out["buckets_ms"]["pallas"] == pytest.approx(0.050)

    def test_bucket_priority_is_total(self):
        assert set(BUCKET_PRIORITY) == {
            "collective_dcn", "collective_ici", "host", "pallas",
            "gemm", "unattributed"}


# --------------------------------------------------------------------- #
# Trace parsing forms + Perfetto validity
# --------------------------------------------------------------------- #
class TestParseForms:
    def test_dict_form(self):
        text = json.dumps({"traceEvents": [_ev("dot.1", 0, 1)]})
        assert len(parse_trace_events(text)) == 1

    def test_strict_array_form(self):
        assert len(parse_trace_events(json.dumps([_ev("a", 0, 1)]))) == 1

    def test_unterminated_array_form(self):
        text = "[\n" + json.dumps(_ev("a", 0, 1)) + ",\n" + \
            json.dumps(_ev("b", 1, 1)) + ",\n"
        assert len(parse_trace_events(text)) == 2

    def test_garbage_raises(self):
        with pytest.raises(json.JSONDecodeError):
            parse_trace_events("not json at all")


class TestTraceWriterPerfetto:
    def _write(self, path, close):
        tw = TraceWriter(path, is_writer=True)
        with tw.span("train_batch", step=1):
            pass
        tw.add_span("grad_sync", 0.001, 0.002)
        tw.add_span("optimizer_apply", 0.003, 0.001)
        tw.instant("nan_guard", {"step": 1})
        t = 0.004
        tw.flow("req", 7, "s", t, tid=0)
        tw.flow("req", 7, "t", t + 0.001, tid=1)
        tw.flow("req", 7, "f", t + 0.002, tid=2)
        tw.flush()
        if close:
            tw.close()
        return tw

    def test_closed_file_is_strict_json(self, tmp_path):
        path = str(tmp_path / "host.trace.json")
        tw = self._write(path, close=True)
        with open(tw.path) as f:
            doc = json.load(f)   # strict parse — no repair step
        assert isinstance(doc, list)
        # One pid throughout; span lanes follow the stable map.
        pids = {e["pid"] for e in doc}
        assert len(pids) == 1
        spans = [e for e in doc if e.get("ph") == "X"]
        by_name = {e["name"]: e for e in spans}
        assert by_name["grad_sync"]["tid"] == _LANES["grad_sync"]
        assert by_name["train_batch"]["tid"] == _LANES["train_batch"]
        # Flow arrows: s/t/f triple sharing one id; the finish binds to
        # the enclosing slice.
        flows = [e for e in doc if e.get("ph") in ("s", "t", "f")]
        assert [e["ph"] for e in flows] == ["s", "t", "f"]
        assert len({e["id"] for e in flows}) == 1
        assert flows[-1]["bp"] == "e"

    def test_preclose_file_is_unterminated_form(self, tmp_path):
        path = str(tmp_path / "host.trace.json")
        tw = self._write(path, close=False)
        with open(tw.path) as f:
            text = f.read()
        with pytest.raises(json.JSONDecodeError):
            json.loads(text)     # by design: crash-tolerant form
        assert len(parse_trace_events(text)) > 0
        tw.close()

    @pytest.mark.parametrize("close", [True, False])
    def test_round_trip_preserves_span_count(self, tmp_path, close):
        path = str(tmp_path / "host.trace.json")
        tw = self._write(path, close=close)
        with open(tw.path) as f:
            events = parse_trace_events(f.read())
        spans = [e for e in events if e.get("ph") == "X"]
        assert len(spans) == 3   # train_batch, grad_sync, optimizer_apply
        out = ingest_events(events)
        assert out["n_events"] == 3
        if not close:
            tw.close()


# --------------------------------------------------------------------- #
# ProfilerWindow: structured events + overwrite refusal
# --------------------------------------------------------------------- #
class TestProfilerWindow:
    def _window(self, tmp_path, start=4, n=2, sub="w"):
        events = []
        w = ProfilerWindow(start, n, str(tmp_path / sub),
                           on_event=lambda k, p: events.append((k, p)))
        return w, events

    def test_capture_dir_carries_step_range(self, tmp_path):
        w, _ = self._window(tmp_path, start=4, n=2)
        assert w.capture_dir.endswith("step_4_6")

    def test_failed_start_emits_structured_event(self, tmp_path):
        # out_dir is a FILE: the capture dir cannot be created.
        blocker = tmp_path / "blocked"
        blocker.write_text("x")
        events = []
        w = ProfilerWindow(4, 2, str(blocker),
                           on_event=lambda k, p: events.append((k, p)))
        w.tick(4)
        assert w.failed
        kind, p = events[-1]
        assert kind == "profile_window"
        assert p["phase"] == "start" and p["ok"] is False
        assert "reason" in p and p["start_step"] == 4
        # A failed window stays failed — no retry storm on later ticks.
        w.tick(5)
        assert len(events) == 1

    def test_failed_stop_emits_structured_event(self, tmp_path,
                                                monkeypatch):
        import jax
        w, events = self._window(tmp_path)
        w._active = True         # simulate an armed window

        def boom():
            raise RuntimeError("profiler backend gone")
        monkeypatch.setattr(jax.profiler, "stop_trace", boom)
        w.stop()
        kind, p = events[-1]
        assert p["phase"] == "stop" and p["ok"] is False
        assert "profiler backend gone" in p["reason"]
        assert w.failed

    def test_duplicate_capture_dir_refused(self, tmp_path):
        w1, _ = self._window(tmp_path, sub="shared")
        w1._claim_dir()
        w2, events = self._window(tmp_path, sub="shared")
        with pytest.raises(RuntimeError, match="duplicate"):
            w2._claim_dir()
        # Through tick(): the refusal surfaces as a failed-start event,
        # never a silent overwrite.
        w3, events3 = self._window(tmp_path, sub="shared")
        w3.tick(4)
        assert w3.failed
        assert events3[-1][1]["ok"] is False
        assert "duplicate" in events3[-1][1]["reason"]

    def test_nonempty_dir_on_disk_refused(self, tmp_path):
        w, _ = self._window(tmp_path, sub="prior")
        os.makedirs(w.capture_dir)
        with open(os.path.join(w.capture_dir, "old.trace.json"), "w") as f:
            f.write("[]")
        with pytest.raises(RuntimeError, match="not empty"):
            w._claim_dir()


# --------------------------------------------------------------------- #
# Reconciliation + the seeded divergence
# --------------------------------------------------------------------- #
def _cost_model(bound=BOUND_HBM, t_compute=1.0, t_hbm=2.0, t_comm=0.5,
                t_dcn=0.0):
    path = {"available": True, "t_compute_ms": t_compute,
            "t_hbm_ms": t_hbm, "t_comm_ms": t_comm, "t_dcn_ms": t_dcn,
            "floor_ms": max(t_compute, t_hbm) + t_comm + t_dcn,
            "bound": bound}
    return {"paths": {"train_step": path},
            "step": {"paths": {"train_step": 1}, "bound": bound}}


def _decomp(gemm=0.0, pallas=0.0, ici=0.0, dcn=0.0, host=0.0,
            unattributed=0.0, idle=0.0):
    per_step = {"gemm": gemm, "pallas": pallas, "collective_ici": ici,
                "collective_dcn": dcn, "host": host,
                "unattributed": unattributed, "idle": idle}
    return {"per_step_ms": per_step,
            "per_step_wall_ms": sum(per_step.values())}


class TestReconcile:
    def test_match_when_dominant_confirms_bound(self):
        r = reconcile(_decomp(gemm=4.0, ici=0.6), _cost_model(BOUND_HBM))
        assert r["verdict"] == "match"
        assert r["dominant_bucket"] == "gemm"
        assert r["predicted_bound"] == BOUND_HBM
        assert r["paths"]["train_step"]["verdict"] == "match"

    def test_mismatch_when_wire_dominates_a_compute_prediction(self):
        r = reconcile(_decomp(gemm=0.5, ici=6.0), _cost_model(BOUND_HBM))
        assert r["verdict"] == "mismatch"
        assert r["dominant_bucket"] == "collective_ici"

    def test_dcn_bucket_confirms_dcn_bound(self):
        r = reconcile(_decomp(dcn=5.0, gemm=1.0),
                      _cost_model(BOUND_DCN, t_dcn=2.0))
        assert r["verdict"] == "match"

    def test_measured_over_floor_ratio(self):
        # compute-side busy 6ms vs max(1,2)=2ms floor -> 3.0x.
        r = reconcile(_decomp(gemm=5.0, unattributed=1.0),
                      _cost_model(BOUND_HBM), threshold=10.0)
        comp = r["components"]["compute"]
        assert comp["measured_ms"] == pytest.approx(6.0)
        assert comp["floor_ms"] == pytest.approx(2.0)
        assert comp["measured_over_floor"] == pytest.approx(3.0)
        assert not comp["diverged"]

    def test_threshold_fires_divergence(self):
        r = reconcile(_decomp(ici=5.0, gemm=2.5),
                      _cost_model(BOUND_INTERCONNECT), threshold=3.0)
        assert r["components"]["collective_ici"]["diverged"]
        evs = divergence_events(r)
        assert evs and evs[0]["event"] == "reconcile_divergence"
        assert evs[0]["component"] == "collective_ici"

    def test_seeded_host_stall_fires_divergence(self):
        """The acceptance seed: an injected host-sync stall must land
        in the host bucket and fire reconcile_divergence — end to end
        through the real ingest path, not a hand-built decomposition."""
        events = [
            _ev("dot.1", 0, 2000),                       # 2ms compute
            # The stall: a blocking host wait for 8ms of a ~10ms step.
            _ev("TfrtCpuBuffer::Await", 2000, 8000),
        ]
        decomp = ingest_events(events, n_steps=1)
        assert decomp["per_step_ms"]["host"] == pytest.approx(8.0)
        r = reconcile(decomp, _cost_model(BOUND_HBM),
                      host_frac=DEFAULT_HOST_FRAC)
        host = r["components"]["host"]
        assert host["diverged"] and host["wall_frac"] > 0.5
        assert any(d["component"] == "host" for d in r["divergences"])
        assert any(e["event"] == "reconcile_divergence"
                   and e["component"] == "host"
                   for e in divergence_events(r))

    def test_unavailable_path_gets_unavailable_verdict(self):
        cm = _cost_model()
        cm["paths"]["eval_step"] = {"available": False}
        r = reconcile(_decomp(gemm=1.0), cm)
        assert r["paths"]["eval_step"]["verdict"] == "unavailable"


# --------------------------------------------------------------------- #
# telemetry.profile config block
# --------------------------------------------------------------------- #
class TestTelemetryProfileConfig:
    def test_defaults(self):
        c = TelemetryProfileConfig()
        assert c.start_step == -1 and c.window_steps == 2
        assert c.divergence_threshold == pytest.approx(3.0)
        assert c.host_frac == pytest.approx(0.10)

    def test_block_overrides(self):
        c = TelemetryProfileConfig({"start_step": 7, "window_steps": 3,
                                    "divergence_threshold": 1.5,
                                    "host_frac": 0.25,
                                    "out_dir": "/tmp/x"})
        assert (c.start_step, c.window_steps) == (7, 3)
        assert c.divergence_threshold == pytest.approx(1.5)
        assert c.out_dir == "/tmp/x"

    def test_legacy_flat_aliases(self):
        c = TelemetryProfileConfig(None, legacy_start=5, legacy_steps=4,
                                   legacy_dir="/tmp/legacy")
        assert (c.start_step, c.window_steps) == (5, 4)
        assert c.out_dir == "/tmp/legacy"

    def test_block_wins_over_legacy(self):
        c = TelemetryProfileConfig({"start_step": 9}, legacy_start=5)
        assert c.start_step == 9

    @pytest.mark.parametrize("bad", [
        {"start_step": "soon"},
        {"window_steps": 0},
        {"window_steps": True},
        {"divergence_threshold": -1.0},
        {"host_frac": "lots"},
    ])
    def test_validation_rejects(self, bad):
        with pytest.raises(DeepSpeedConfigError):
            TelemetryProfileConfig(bad)


# --------------------------------------------------------------------- #
# JSONL-only ingestion + the label ratchet
# --------------------------------------------------------------------- #
class TestIngestFromTelemetry:
    def _jsonl(self, tmp_path, trace_dir, ok=True, reason=None):
        rec = {"kind": "event", "event": "profile_window",
               "phase": "stop", "path": str(trace_dir),
               "start_step": 4, "stop_step": 6, "ok": ok, "step": 6,
               "ts": 0.0}
        if reason:
            rec["reason"] = reason
        path = tmp_path / "run.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps({"kind": "meta"}) + "\n")
            f.write(json.dumps(rec) + "\n")
        return str(path)

    def test_locates_and_ingests_from_jsonl_alone(self, tmp_path):
        trace_dir = tmp_path / "cap"
        os.makedirs(trace_dir)
        doc = {"traceEvents": [_ev("dot.1", 0, 100),
                               _ev("all-reduce.1", 100, 50)]}
        with gzip.open(trace_dir / "host.trace.json.gz", "wt") as f:
            f.write(json.dumps(doc))
        out = ingest_from_telemetry(self._jsonl(tmp_path, trace_dir))
        assert out["n_device_ops"] == 2
        assert out["steps"] == 2          # stop_step - start_step
        assert out["profile_window"]["path"] == str(trace_dir)

    def test_failed_window_reports_not_ingests(self, tmp_path):
        out = ingest_from_telemetry(self._jsonl(
            tmp_path, tmp_path / "nope", ok=False, reason="boom"))
        assert "error" in out and "boom" in out["error"]
        assert out["n_device_ops"] == 0

    def test_missing_window_is_an_error(self, tmp_path):
        path = tmp_path / "empty.jsonl"
        path.write_text(json.dumps({"kind": "meta"}) + "\n")
        assert "error" in ingest_from_telemetry(str(path))

    def test_ingest_empty_dir_is_an_error(self, tmp_path):
        out = ingest(str(tmp_path / "missing"))
        assert "error" in out and out["n_device_ops"] == 0


class TestLabelRatchet:
    @pytest.fixture(scope="class")
    def bg(self):
        return _load_tool("bench_gate")

    def _truth(self, **arts):
        return {"artifacts": {
            name: ({"label": label, "reconciliation": {"verdict": "match"}}
                   if reconciled else {"label": label})
            for name, (label, reconciled) in arts.items()}}

    def test_extract_labels_truth_doc(self, bg):
        labels = bg.extract_labels(self._truth(
            a=("measured", True), b=("cpu-structural", False)))
        assert labels == {"a": {"label": "measured", "reconciled": True},
                          "b": {"label": "cpu-structural",
                                "reconciled": False}}

    def test_extract_labels_single_artifact_doc(self, bg):
        labels = bg.extract_labels({"artifact": "X", "label": "measured"})
        assert labels == {"X": {"label": "measured", "reconciled": False}}

    def test_extract_labels_pre_truth_doc_is_none(self, bg):
        assert bg.extract_labels({"parsed": {"mfu": 0.4}}) is None

    def test_pre_truth_rounds_skip(self, bg):
        assert bg.label_ratchet({}, self._truth(a=("measured", True))) \
            is None

    def test_measured_stays_measured(self, bg):
        old = self._truth(a=("measured", True))
        assert bg.label_ratchet(old, self._truth(a=("measured", True))) \
            == []

    def test_downgrade_fails(self, bg):
        old = self._truth(a=("measured", False))
        fails = bg.label_ratchet(old, self._truth(a=("projected", False)))
        assert fails and "regressed" in fails[0]
        fails = bg.label_ratchet(
            old, self._truth(a=("cpu-structural", False)))
        assert fails

    def test_dropped_measured_artifact_fails(self, bg):
        old = self._truth(a=("measured", True))
        fails = bg.label_ratchet(old, self._truth(b=("measured", True)))
        assert fails and "dropped" in fails[0]

    def test_dropped_reconciliation_fails(self, bg):
        old = self._truth(a=("measured", True))
        fails = bg.label_ratchet(old, self._truth(a=("measured", False)))
        assert fails and "reconciliation" in fails[0]

    def test_upgrades_are_free(self, bg):
        old = self._truth(a=("projected", False),
                          b=("cpu-structural", False))
        assert bg.label_ratchet(old, self._truth(
            a=("measured", True), b=("measured", True))) == []

    def test_repo_truth_json_parses(self, bg):
        path = os.path.join(REPO, "TRUTH.json")
        with open(path) as f:
            truth = json.load(f)
        labels = bg.extract_labels(truth)
        assert labels, "TRUTH.json must carry extractable labels"
        for rec in labels.values():
            assert rec["label"] in ("projected", "cpu-structural",
                                    "measured")
        # On a CPU-built TRUTH.json there must be no measured labels.
        if truth.get("backend") != "tpu":
            assert all(r["label"] != "measured" for r in labels.values())
        # The ratchet against itself is clean.
        assert bg.label_ratchet(truth, truth) == []


# --------------------------------------------------------------------- #
# jax.profiler round trip on this box (one real capture)
# --------------------------------------------------------------------- #
class TestRealCaptureRoundTrip:
    def test_profiler_window_capture_ingests(self, tmp_path):
        """A real (tiny) jax.profiler window: arm, run two trivial
        device programs, stop, ingest from the capture dir."""
        import jax
        import jax.numpy as jnp
        events = []
        w = ProfilerWindow(0, 1, str(tmp_path / "cap"),
                           on_event=lambda k, p: events.append(p))
        w.tick(0)
        f = jax.jit(lambda x: (x @ x).sum())
        for _ in range(3):
            f(jnp.ones((64, 64))).block_until_ready()
        w.tick(1)
        assert [p["phase"] for p in events] == ["start", "stop"]
        assert all(p["ok"] for p in events)
        out = ingest(events[-1]["path"], n_steps=1)
        assert out.get("n_device_ops", 0) > 0
        assert out["sum_check"]["explained_frac"] == pytest.approx(
            1.0, abs=0.05)
        assert glob.glob(os.path.join(
            events[-1]["path"], "plugins", "profile", "*", "*"))
