"""Request-scoped serving observability (PR-19 tentpole).

The load-bearing invariants:

1. **Contiguity** — every finished request's span timeline tiles
   [0, total_ms] exactly (queued → prefill → decode share boundary
   instants by construction), and ``queue_wait + service_ttft == ttft``
   to the microsecond, so a TTFT regression is attributable to queuing
   vs prefill from the record alone.
2. **Ledger identity** — each replica's serving goodput buckets
   (prefill / decode_useful / spec_wasted / admission_blocked / idle)
   sum to the serve wall within tolerance; a NEGATIVE residual (double
   attribution) flips ``consistent`` to False instead of being clamped.
3. **Explainability** — the router records every candidate's
   occupancy / queue-depth / prefix-affinity scores at route time, and
   the chosen replica maximizes the recorded score for EVERY decision.
4. **Honest accounting** — admission rejections are counted per request
   and surfaced (first rejection emits a structured event); zero
   completed requests is a reported condition in the report tool, never
   a crash.
"""
import json
import os
import sys

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference import (ContinuousBatchingScheduler,
                                     InferenceEngine, ReplicaRouter,
                                     Request, shared_prefix_requests,
                                     synthetic_requests)
from deepspeed_tpu.models.gpt2 import GPT2_CONFIGS, gpt2_init
from deepspeed_tpu.monitor import (SERVING_BUCKETS, RequestTrace,
                                   ServingGoodputLedger, SLOTracker,
                                   validate_timeline)
from deepspeed_tpu.monitor.serving import ServingAggregator

CFG = GPT2_CONFIGS["gpt2-tiny"]

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


@pytest.fixture(scope="module")
def params():
    return gpt2_init(jax.random.PRNGKey(1), CFG)


class _FakeTelemetry:
    enabled = True
    tracer = None

    def __init__(self):
        self.events = []

    def event(self, kind, payload):
        self.events.append((kind, dict(payload)))


# --------------------------------------------------------------------- #
# Serving goodput ledger
# --------------------------------------------------------------------- #
class TestServingGoodputLedger:
    def test_buckets_sum_to_wall_with_residual(self):
        led = ServingGoodputLedger(label="r0")
        led.note("prefill", 0.2)
        led.note("decode_useful", 0.5)
        led.note("spec_wasted", 0.1)
        led.note("idle", 0.15)
        s = led.snapshot(wall_s=1.0)
        assert s["label"] == "r0"
        total = sum(s[f"{b}_s"] for b in SERVING_BUCKETS) + s["other_s"]
        assert total == pytest.approx(1.0)
        assert s["other_s"] == pytest.approx(0.05)
        assert s["consistent"] and s["accounted_fraction"] == 1.0

    def test_double_attribution_flips_consistent(self):
        led = ServingGoodputLedger()
        led.note("prefill", 0.8)
        led.note("decode_useful", 0.8)      # 1.6s noted in a 1s wall
        s = led.snapshot(wall_s=1.0)
        assert s["other_s"] < 0, "negative residual surfaced, not clamped"
        assert not s["consistent"]

    def test_unknown_bucket_raises_and_nonpositive_ignored(self):
        led = ServingGoodputLedger()
        with pytest.raises(ValueError, match="bucket"):
            led.note("training", 1.0)
        led.note("idle", 0.0)
        led.note("idle", -5.0)
        assert led.noted_total() == 0.0

    def test_merged_sums_buckets_and_walls(self):
        a = ServingGoodputLedger(label="r0")
        b = ServingGoodputLedger(label="r1")
        a.note("prefill", 0.3)
        b.note("decode_useful", 0.6)
        m = ServingGoodputLedger.merged(
            [a.snapshot(wall_s=1.0), b.snapshot(wall_s=1.0)])
        assert m["wall_s"] == pytest.approx(2.0)
        assert m["prefill_s"] == pytest.approx(0.3)
        assert m["decode_useful_s"] == pytest.approx(0.6)
        assert m["consistent"]


# --------------------------------------------------------------------- #
# SLO tracker
# --------------------------------------------------------------------- #
class TestSLOTracker:
    def test_attainment_and_burn_rate(self):
        tr = SLOTracker(ttft_ms=100.0, tpot_ms=50.0, availability=0.9)
        assert tr.enabled
        assert tr.observe(0.05, 0.01)           # good
        assert not tr.observe(0.5, 0.01)        # ttft miss
        assert not tr.observe(0.05, 0.2)        # tpot miss
        tr.observe_failure()                    # aborted request
        s = tr.snapshot()
        assert s["total"] == 4 and s["good"] == 1
        assert s["ttft_misses"] == 1 and s["tpot_misses"] == 1
        assert s["attainment"] == pytest.approx(0.25)
        # burn = (1 - attainment) / (1 - availability) = 0.75 / 0.1
        assert s["burn_rate"] == pytest.approx(7.5)

    def test_unset_target_always_passes(self):
        tr = SLOTracker(ttft_ms=100.0)          # tpot unset
        assert tr.observe(0.05, 100.0)          # huge tpot: still good
        assert SLOTracker().enabled is False

    def test_window_prunes_old_outcomes(self):
        t = [0.0]
        tr = SLOTracker(ttft_ms=100.0, window_s=10.0, clock=lambda: t[0])
        tr.observe(1.0, None, t=0.0)            # miss, will age out
        t[0] = 100.0
        tr.observe(0.01, None, t=100.0)         # good, in window
        s = tr.snapshot(now=100.0)
        assert s["total"] == 2 and s["attainment"] == pytest.approx(0.5)
        assert s["window"]["n"] == 1
        assert s["window"]["attainment"] == pytest.approx(1.0)

    def test_merged_pools_trackers(self):
        a = SLOTracker(ttft_ms=100.0)
        b = SLOTracker(ttft_ms=100.0)
        a.observe(0.05, None)
        b.observe(0.5, None)
        m = SLOTracker.merged([a, b])
        assert m["total"] == 2 and m["good"] == 1
        assert m["attainment"] == pytest.approx(0.5)
        assert SLOTracker.merged([]) is None

    def test_validation(self):
        with pytest.raises(ValueError):
            SLOTracker(ttft_ms=100.0, availability=1.5)
        with pytest.raises(ValueError):
            SLOTracker(ttft_ms=100.0, window_s=0.0)


# --------------------------------------------------------------------- #
# Request trace (host-side unit: no engine, no device)
# --------------------------------------------------------------------- #
class TestRequestTrace:
    def test_lifecycle_timeline_is_contiguous(self):
        tr, tel = RequestTrace(), _FakeTelemetry()
        tr.enqueue(7, t=100.0)
        tr.route(7, 1, [{"replica": 0, "score": -1.0},
                        {"replica": 1, "score": 0.5}], t=100.001)
        assert tr.admit_reject(7, reason="reservation", t=100.002)
        assert not tr.admit_reject(7, reason="reservation", t=100.003)
        tr.admit(7, slot=2, t=100.01, replica="r1")
        tr.prefill(7, 0.02, tokens=16, chunks=2, cached_tokens=8)
        tr.first_token(7, t=100.03)
        tr.tick(7, 3, 1, t=100.05)
        tr.tick(7, 3, 4, proposed=4, accepted=3, t=100.09)
        tr.complete(7, t=100.09, telemetry=tel)
        kind, tl = tel.events[0]
        assert kind == "request_trace"
        assert validate_timeline(tl) == []
        assert tl["outcome"] == "complete"
        assert tl["replica"] == "r1" and tl["admission_attempts"] == 2
        assert [s["phase"] for s in tl["spans"]] == \
            ["queued", "prefill", "decode"]
        assert tl["queue_wait_ms"] + tl["service_ttft_ms"] == \
            pytest.approx(tl["ttft_ms"])
        # The decode span accumulated the per-tick marks.
        assert tl["spans"][2]["ticks"] == 2
        assert tl["spans"][2]["emitted"] == 5

    def test_abort_paths_still_tile(self):
        tr, tel = RequestTrace(), _FakeTelemetry()
        # Aborted after admit, before first token: prefill extends to
        # the end, no decode span, no gap.
        tr.enqueue(1, t=10.0)
        tr.admit(1, slot=0, t=10.01, replica="r0")
        tr.abort(1, "max_wall", t=10.05, telemetry=tel)
        tl = tel.events[0][1]
        assert tl["outcome"] == "abort" and tl["abort_reason"] == "max_wall"
        assert [s["phase"] for s in tl["spans"]] == ["queued", "prefill"]
        assert validate_timeline(tl) == []
        # Never admitted (starved in queue): one queued span.
        tr.enqueue(2, t=20.0)
        tr.abort(2, "starved", t=20.5, telemetry=tel)
        tl2 = tel.events[1][1]
        assert [s["phase"] for s in tl2["spans"]] == ["queued"]
        assert validate_timeline(tl2) == []

    def test_ring_caps_count_drops(self):
        tr, tel = RequestTrace(capacity=2, tick_capacity=3), \
            _FakeTelemetry()
        for rid in range(4):
            tr.enqueue(rid, t=float(rid))
        assert tr.summary()["records_dropped"] == 2
        tr.admit(0, slot=0, t=0.01, replica="r0")
        tr.first_token(0, t=0.02)
        for i in range(5):
            tr.tick(0, 1, 1, t=0.03 + i * 0.01)
        tr.complete(0, t=0.1, telemetry=tel)
        tl = tel.events[0][1]
        assert len(tl["ticks"]) == 3, "ring kept the newest tick marks"
        assert tl["ticks_dropped"] == 2
        assert tr.summary()["ticks_dropped"] == 2


# --------------------------------------------------------------------- #
# inference.slo config block
# --------------------------------------------------------------------- #
class TestInferenceSloConfig:
    def test_defaults_disabled(self):
        from deepspeed_tpu.runtime.config import InferenceConfig
        inf = InferenceConfig(None)
        assert inf.slo.ttft_ms == 0.0 and inf.slo.tpot_ms == 0.0
        assert inf.slo.availability == 0.99 and inf.slo.window_s == 60.0
        assert not inf.slo.enabled

    def test_block_parses_and_enables(self):
        from deepspeed_tpu.runtime.config import InferenceConfig
        inf = InferenceConfig({"inference": {
            "slo": {"ttft_ms": 250.0, "tpot_ms": 20,
                    "availability": 0.999, "window_s": 30}}})
        assert inf.slo.enabled
        assert inf.slo.ttft_ms == 250.0 and inf.slo.tpot_ms == 20.0
        assert inf.slo.availability == 0.999

    def test_invalid_values_raise(self):
        from deepspeed_tpu.runtime.config import (DeepSpeedConfigError,
                                                  InferenceConfig)
        for bad in ({"ttft_ms": -1}, {"tpot_ms": True},
                    {"availability": 0.0}, {"availability": 1.0},
                    {"window_s": 0}, {"window_s": -2.0}):
            with pytest.raises(DeepSpeedConfigError):
                InferenceConfig({"inference": {"slo": bad}})
        with pytest.raises(DeepSpeedConfigError):
            InferenceConfig({"inference": {"slo": 5}})


# --------------------------------------------------------------------- #
# Aggregator: queue-wait split + admission accounting (satellites 1, 2)
# --------------------------------------------------------------------- #
class TestAggregatorSplitAndAdmission:
    def test_queue_wait_and_service_ttft_surface(self):
        agg = ServingAggregator(8, label="r0")
        for i in range(4):
            agg.note_request(0.030, 0.002, 8, queue_wait_s=0.010,
                             service_ttft_s=0.020,
                             admission_attempts=1 + i % 2)
        agg.note_reject()
        agg.note_reject()
        snap = agg.snapshot(wall_s=1.0)
        assert snap["queue_wait_ms"]["p50"] == pytest.approx(10.0)
        assert snap["service_ttft_ms"]["p50"] == pytest.approx(20.0)
        assert snap["queue_wait_ms"]["p50"] + \
            snap["service_ttft_ms"]["p50"] == \
            pytest.approx(snap["ttft_ms"]["p50"])
        assert snap["admission"]["reservations_rejected"] == 2
        assert snap["admission"]["attempts"]["p95"] == 2

    def test_merged_pools_split_and_rejections(self):
        a, b = ServingAggregator(8, label="r0"), \
            ServingAggregator(8, label="r1")
        a.note_request(0.03, None, 4, queue_wait_s=0.01,
                       service_ttft_s=0.02)
        b.note_request(0.05, None, 4, queue_wait_s=0.02,
                       service_ttft_s=0.03)
        a.note_reject()
        m = ServingAggregator.merged([a, b])
        snap = m.snapshot(wall_s=1.0)
        assert snap["queue_wait_ms"]["n"] == 2
        assert snap["admission"]["reservations_rejected"] == 1

    def test_ledger_and_slo_ride_the_snapshot(self):
        agg = ServingAggregator(8, label="r0")
        agg.ledger = ServingGoodputLedger(label="r0")
        agg.ledger.note("decode_useful", 0.4)
        agg.slo = SLOTracker(ttft_ms=100.0)
        agg.slo.observe(0.05, None)
        snap = agg.snapshot(wall_s=1.0)
        assert snap["ledger"]["decode_useful_s"] == pytest.approx(0.4)
        assert snap["ledger"]["wall_s"] == pytest.approx(1.0)
        assert snap["slo"]["attainment"] == 1.0
        # No slo attached -> section omitted (skip-never-fail).
        assert "slo" not in ServingAggregator(8).snapshot(wall_s=1.0)


# --------------------------------------------------------------------- #
# Router decision explainability (satellite 3)
# --------------------------------------------------------------------- #
class TestRoutingExplainability:
    def test_recorded_scores_explain_every_choice(self, params):
        """Skewed two-replica shared-prefix stream: after a first wave
        populates one replica's prefix cache, a second wave's routing
        decisions must (a) be argmax of the RECORDED candidate scores,
        decision by decision, and (b) show nonzero recorded prefix
        affinity."""
        engines = [InferenceEngine(CFG, params, config={
            "inference": {"max_slots": 8, "max_seq_len": 64,
                          "prefill_chunk": 8, "block_size": 16,
                          "replica": f"r{i}"}}) for i in range(2)]
        router = ReplicaRouter(engines, affinity_weight=1.0)
        wave1 = shared_prefix_requests(6, prefix_len=32, tail_len=(4, 8),
                                       max_new_tokens=4,
                                       vocab_size=CFG.vocab_size, seed=5)
        router.serve(wave1)
        # Second wave shares the same prefix: its blocks are resident
        # now, so route-time affinity scores must be nonzero.
        wave2 = shared_prefix_requests(6, prefix_len=32, tail_len=(4, 8),
                                       max_new_tokens=4,
                                       vocab_size=CFG.vocab_size, seed=5)
        for r in wave2:
            r.rid += 100
        router.serve(wave2)
        assert len(router.decisions) == 12
        for d in router.decisions:
            scores = [c["score"] for c in d["candidates"]]
            assert len(scores) == 2
            assert scores[d["chosen"]] == max(scores), \
                f"decision for rid={d['rid']} not explained by scores"
            for c in d["candidates"]:
                assert {"replica", "occupancy", "queue_depth",
                        "affinity_tokens"} <= set(c)
        wave2_decisions = [d for d in router.decisions
                           if d["rid"] >= 100]
        assert any(c["affinity_tokens"] > 0
                   for d in wave2_decisions for c in d["candidates"]), \
            "no recorded prefix affinity in the second wave"
        for e in engines:
            e.close()


# --------------------------------------------------------------------- #
# End-to-end: scheduler stream -> JSONL -> report (the acceptance gate)
# --------------------------------------------------------------------- #
class TestServingObservabilityStream:
    def test_traced_stream_jsonl_validates(self, tmp_path, params):
        """dp=8 shared-prefix stream under fail_on_recompile: every
        completed request's timeline re-validates from the JSONL alone,
        the ledger is consistent, the report's serving_slo section
        carries verdicts, and admission pressure is surfaced."""
        eng = InferenceEngine(CFG, params, config={
            "inference": {"max_slots": 8, "max_seq_len": 64,
                          "prefill_chunk": 8, "block_size": 16,
                          "spec_k": 4,
                          "slo": {"ttft_ms": 60000.0,
                                  "tpot_ms": 60000.0}},
            "telemetry": {"enabled": True, "output_path": str(tmp_path),
                          "job_name": "obs", "report_steps": 10 ** 6,
                          "fail_on_recompile": True}})
        # 3x oversubscription (24 requests, 8 slots, saturation
        # arrivals): later requests queue, so queue_wait > 0 and
        # head-of-queue admission rejections occur and must be counted.
        reqs = shared_prefix_requests(24, prefix_len=24, tail_len=(4, 8),
                                      max_new_tokens=6,
                                      vocab_size=CFG.vocab_size, seed=7)
        report = eng.serve(reqs)
        assert report["completed"] == 24 and report["recompiles"] == 0
        # Ledger: buckets sum to the serve wall within tolerance.
        led = report["ledger"]
        assert led["consistent"], led
        total = sum(led[f"{b}_s"] for b in SERVING_BUCKETS) \
            + led["other_s"]
        assert total == pytest.approx(led["wall_s"], rel=1e-6)
        assert led["decode_useful_s"] > 0 and led["prefill_s"] > 0
        # SLO: loose targets -> full attainment, burn 0.
        assert report["slo"]["attainment"] == 1.0
        assert report["slo"]["burn_rate"] == 0.0
        # Queue split: oversubscribed saturation stream waits.
        assert report["queue_wait_ms"]["n"] == 24
        assert report["queue_wait_ms"]["p95"] > 0
        assert report["admission"]["reservations_rejected"] >= 0
        # Trace summary rode the report.
        assert report["trace"]["completed"] == 24
        assert report["trace"]["records_dropped"] == 0
        eng.close()

        # JSONL replay: timelines + events, with no engine state.
        events = []
        with open(tmp_path / "obs.jsonl") as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("kind") == "event":
                    events.append(rec)
        traces = [e for e in events if e["event"] == "request_trace"]
        assert len(traces) == 24
        for tl in traces:
            assert validate_timeline(tl) == [], \
                (tl["rid"], validate_timeline(tl))
        # First-rejection events (if any pool-gate rejections happened)
        # carry rid + reason + queue depth.
        for e in events:
            if e["event"] == "admission_rejected":
                assert {"rid", "reason", "queue_depth"} <= set(e)

        # Report tool: serving_slo section parses from the stream.
        sys.path.insert(0, TOOLS)
        from telemetry_report import summarize
        summary = summarize(str(tmp_path / "obs.jsonl"))
        ss = summary["serving_slo"]
        assert ss["available"]
        assert ss["ledger"]["consistent"]
        assert ss["slo"]["burn"]["default"]["verdict"] == "ok"
        assert ss["traces"]["recorded"] == 24
        assert ss["traces"]["contiguity_violations"] == 0
        worst = ss["traces"]["worst_ttft"]
        assert worst and worst[0]["spans"], "exemplars carry timelines"
        assert worst[0]["ttft_ms"] >= worst[-1]["ttft_ms"]
        srv = summary["serving"]
        assert srv["queue_wait_ms"]["n"] == 24
        assert srv["service_ttft_ms"]["n"] == 24

    def test_zero_completed_requests_report_null_slo(self, tmp_path):
        """Satellite 6 regression: a serving stream that completed
        nothing (all aborted/starved) must summarize with slo: null and
        a reason, not a crash."""
        stream = tmp_path / "empty.jsonl"
        recs = [
            {"kind": "meta", "mode": "serving", "ts": 1.0},
            {"kind": "report", "step": 1,
             "serving": {"replica": "r0", "completed": 0,
                         "ledger": {"wall_s": 1.0, "prefill_s": 0.0,
                                    "decode_useful_s": 0.0,
                                    "spec_wasted_s": 0.0,
                                    "admission_blocked_s": 0.9,
                                    "idle_s": 0.0, "other_s": 0.1,
                                    "accounted_fraction": 1.0,
                                    "consistent": True}}},
        ]
        with open(stream, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        sys.path.insert(0, TOOLS)
        from telemetry_report import summarize
        summary = summarize(str(stream))
        ss = summary["serving_slo"]
        assert ss["available"]
        assert ss["slo"] is None
        assert "no completed requests" in ss["slo_unavailable_reason"]
        assert ss["ledger"]["consistent"]
        assert summary["serving"]["completed"] == 0

    def test_fake_engine_scheduler_path_still_works(self):
        """The duck-typed fake-engine path (telemetry disabled) must not
        trip over the new tracing hooks — trace stays None, no new
        attribute is required of the engine."""
        import time as _time

        class _FakeTel:
            enabled = False
            recompile_count = 0

            def span(self, *a, **k):
                import contextlib
                return contextlib.nullcontext()

        class _FakeEngine:
            max_slots, max_len = 2, 1000
            telemetry = _FakeTel()

            def __init__(self):
                self.active = np.zeros(2, bool)
                self.serving = ServingAggregator(2)

            def prefill(self, prompt, slot, temperature=0.0, **kw):
                return 1, None

            def activate_slot(self, slot, n, tok):
                self.active[slot] = True

            def release_slot(self, slot):
                self.active[slot] = False

            def context_len(self, slot):
                return 10

            def decode_once(self, temperature=0.0):
                self.serving.note_iteration(int(self.active.sum()), 1e-4)
                _time.sleep(0.001)
                return np.ones(2, np.int32), None

            def complete_request(self, *a, **k):
                self.serving.note_request(0.01, None, 1)

        eng = _FakeEngine()
        reqs = synthetic_requests(4, prompt_len=(4, 4),
                                  max_new_tokens=3)
        sched = ContinuousBatchingScheduler(eng)
        assert sched.trace is None
        report = sched.serve(reqs)
        assert report["completed"] == 4
        assert "trace" not in report
