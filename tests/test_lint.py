"""Compile-time program auditor (analysis/): the lint suite's tier-1
gates.

The load-bearing assertions (ISSUE 6 acceptance):
- Each lint pass has a seeded-violation test — a deliberately unaliased
  donated buffer, an injected full all-gather under declared ZeRO
  sharding, a forced bf16->f32 round-trip, an in-step pure_callback, and
  a mis-placed collective — each caught by EXACTLY the intended pass.
- The clean engine paths (main/offload/trio on the dp=8 CPU mesh)
  produce zero unwaived findings, and the audit itself issues zero
  device fences (device_sync_count-asserted).
- The waiver machinery: bracket-safe glob matching, stale-waiver
  detection, and LINT_AUDIT.json consistency (every finding priced or
  explicitly unpriced, every waiver matched to a live finding).
"""
import json
import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.analysis import hlo_text
from deepspeed_tpu.analysis.auditor import lint_jit
from deepspeed_tpu.analysis.findings import (LintConfig, LintFinding,
                                             Waiver, apply_waivers,
                                             load_waivers)
from deepspeed_tpu.parallel import comm
from deepspeed_tpu.utils import timer as timer_mod

from simple_model import (simple_model_params, simple_loss_fn, random_batch,
                          base_config)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WAIVER_FILE = os.path.join(REPO, "tools", "lint_waivers.json")


def _tel(tmp_path, name="lint"):
    return {"enabled": True, "output_path": str(tmp_path),
            "job_name": name, "report_steps": 10 ** 9}


def _engine(tmp_path, name="lint", seed=0, **overrides):
    cfg = base_config(telemetry=_tel(tmp_path, name), **overrides)
    params = simple_model_params(jax.random.PRNGKey(seed))
    engine, *_ = deepspeed_tpu.initialize(
        model=simple_loss_fn, model_params=params, config=cfg)
    return engine


def _lints(result):
    return sorted({f.lint for f in result.findings})


# --------------------------------------------------------------------- #
# Seeded violations: one per pass, caught by exactly the intended pass
# --------------------------------------------------------------------- #
class TestSeededViolations:
    def test_unaliased_donated_buffer_caught_by_donation_pass(self):
        """A donated f32 input returned only as bf16 has no same-aval
        output to alias — the donation freed nothing."""
        def step(state, x):
            return (state * 2.0).astype(jnp.bfloat16), x.sum()

        fn = jax.jit(step, donate_argnums=(0,))
        res = lint_jit(fn, jnp.zeros((256, 256), jnp.float32),
                       jnp.ones((8,), jnp.float32), name="seeded_donation")
        assert not res.errors, res.errors
        assert _lints(res) == ["donation"], [f.fingerprint
                                             for f in res.findings]
        f = res.findings[0]
        assert f.bytes == 256 * 256 * 4
        assert f.priced is False
        assert "alias" in f.summary

    def test_full_gather_under_declared_sharding_caught_by_materialization(
            self, mesh8):
        """Two dp-sharded leaves gathered and concatenated into one
        replicated tree-sized buffer: the ZeRO-3 'XLA materialized the
        full tree' failure, injected."""
        sh = NamedSharding(mesh8, P("data"))
        a = jax.device_put(jnp.ones((1024,), jnp.float32), sh)
        b = jax.device_put(jnp.ones((1024,), jnp.float32), sh)

        def gather_all(a, b):
            full = jnp.concatenate([
                lax.with_sharding_constraint(a, NamedSharding(mesh8, P())),
                lax.with_sharding_constraint(b, NamedSharding(mesh8, P()))])
            # The tree-sized buffer must be a live value (a bare .sum()
            # lets XLA fold the gather into shard-local partials and the
            # injected materialization never happens).
            return full * 2.0

        # declared per-device state: two 1/8 shards; largest single
        # (unsharded) leaf is exempt — the 2-leaf concat is not.
        meta = {"declared_state_bytes": 2 * 1024 * 4 // 8,
                "largest_leaf_bytes": 1024 * 4}
        res = lint_jit(jax.jit(gather_all), a, b, name="seeded_gather",
                       meta=meta)
        assert not res.errors, res.errors
        assert _lints(res) == ["materialization"], \
            [f.fingerprint for f in res.findings]
        assert all(f.bytes >= 2 * 1024 * 4 for f in res.findings)
        assert all(f.priced is False for f in res.findings)

    def test_full_pool_gather_in_serving_path_fires_despite_score_budget(
            self):
        """A serving-shaped path that materializes a per-stream copy of
        the WHOLE block pool ([Q, B, nH, bs, D] — the naive gather the
        one-hot contraction exists to avoid): fires even though the
        engine's ``paged_score_bytes`` budget is declared, because a
        K/V gather is head_dim times the budgeted score transient."""
        B, nH, bs, D, Q, J, K = 32, 2, 8, 16, 4, 4, 1
        pool_k = jnp.ones((B, nH, bs, D), jnp.float32)
        sel = jnp.zeros((Q, J, B), jnp.float32)
        meta = {"declared_state_bytes": 4096,
                "largest_leaf_bytes": 2048,
                "paged_score_bytes": Q * K * nH * B * bs * 4}

        def full_pool_gather(pool_k, sel):
            gathered = pool_k[None] * sel.sum(1)[:, :, None, None, None]
            return gathered * 2.0           # live pool-sized value

        res = lint_jit(jax.jit(full_pool_gather), pool_k, sel,
                       name="seeded_pool_gather", meta=meta)
        assert not res.errors, res.errors
        assert _lints(res) == ["materialization"], \
            [f.fingerprint for f in res.findings]
        assert all(f.bytes >= Q * B * nH * bs * D * 4
                   for f in res.findings)

    def test_onehot_score_transient_rides_its_declared_budget(self):
        """The flip side: the one-hot attend's [Q, K, nH, B, bs] fp32
        score transient passes WITH the ``paged_score_bytes`` budget the
        engine declares on one-hot paths, and fires WITHOUT it — the
        budget is load-bearing, not decorative."""
        # Q*K > D so the [Q,K,nH,B,bs] score transient outweighs the
        # declared pool (the regime the budget exists for: pool growth
        # and wide verify batches inflate the transient past state).
        B, nH, bs, D, Q, K = 32, 2, 8, 4, 8, 2
        q = jnp.ones((Q, K, nH, D), jnp.float32)
        pool_k = jnp.ones((B, nH, bs, D), jnp.float32)

        def score(q, pool_k):
            s = jnp.einsum("qknd,bntd->qknbt", q, pool_k)
            return s * 2.0                  # live score-sized value

        pool_bytes = B * nH * bs * D * 4
        base = {"declared_state_bytes": 2 * pool_bytes,   # K + V pools
                "largest_leaf_bytes": pool_bytes}
        budget = Q * K * nH * B * bs * 4
        clean = lint_jit(jax.jit(score), q, pool_k, name="seeded_score",
                         meta={**base, "paged_score_bytes": budget})
        assert not clean.errors and not clean.findings, \
            [f.fingerprint for f in clean.findings]
        fires = lint_jit(jax.jit(score), q, pool_k,
                         name="seeded_score_nobudget", meta=base)
        assert _lints(fires) == ["materialization"]

    def test_bf16_f32_round_trip_caught_by_dtype_flow(self):
        def loss(x):
            wide = x.astype(jnp.float32)          # forced upcast...
            back = wide.astype(jnp.bfloat16)      # ...cast straight back
            return (back * back).sum()

        res = lint_jit(jax.jit(loss), jnp.ones((64, 64), jnp.bfloat16),
                       name="seeded_roundtrip")
        assert not res.errors, res.errors
        assert _lints(res) == ["dtype_flow"], [f.fingerprint
                                               for f in res.findings]
        f = res.findings[0]
        assert f.key.startswith("bfloat16->float32->bfloat16")
        assert f.bytes == 64 * 64 * 4              # the widened transient

    def test_in_step_pure_callback_caught_by_host_sync(self):
        def step(x):
            y = x.sum()
            return jax.pure_callback(
                lambda v: v, jax.ShapeDtypeStruct((), jnp.float32), y)

        res = lint_jit(jax.jit(step), jnp.ones((16,), jnp.float32),
                       name="seeded_callback")
        assert not res.errors, res.errors
        assert _lints(res) == ["host_sync"], [f.fingerprint
                                              for f in res.findings]
        assert any(f.key == "pure_callback" for f in res.findings)

    def test_hoisted_scatter_caught_by_collective_placement(self, mesh8):
        """gas=2 accumulation carrying FULL gradients with the
        psum_scatter AFTER the scan — contrary to the declared explicit
        mode whose invariant is the in-scan scatter (the carry only ever
        holds 1/dp shards)."""
        n = 1024

        def per_rank(w, xs):
            def accum(g, x):
                return g + w * x.sum(), None
            g, _ = lax.scan(accum, jnp.zeros((n,), jnp.float32), xs)
            return lax.psum_scatter(g, "data", scatter_dimension=0,
                                    tiled=True)

        fn = comm.shard_map(per_rank, mesh=mesh8,
                            in_specs=(P(), P(None, "data")),
                            out_specs=P("data"), check_vma=False)
        w = jnp.ones((n,), jnp.float32)
        xs = jnp.ones((2, 8, 4), jnp.float32)
        meta = {"grad_sync_path": True, "grad_sync_mode": "explicit",
                "gas": 2, "scatterable_leaf_bytes": [n * 4]}
        with mesh8:
            res = lint_jit(jax.jit(fn), w, xs, name="seeded_hoist",
                           meta=meta)
        assert not res.errors, res.errors
        assert _lints(res) == ["collective_placement"], \
            [f.fingerprint for f in res.findings]
        f = res.findings[0]
        assert f.key.startswith("rs-hoisted")
        assert f.priced and f.wire_bytes == \
            deepspeed_tpu.parallel.hlo_audit.ring_wire_bytes(
                "reduce-scatter", n * 4, 8)

    def test_allreduce_trapped_in_gas_scan_caught_dense(self, mesh8):
        """Dense mode's misplacement: the gradient all-reduce INSIDE the
        gas=2 accumulation scan pays gas x the wire it needs (accumulate
        locally, reduce once) — the else-branch of collective_placement,
        reachable on dense engines now that _lint_path_meta populates
        scatterable_leaf_bytes for stage < 2 too."""
        n = 512

        def per_rank(w, xs):
            def accum(g, x):
                gi = lax.psum(w * x.sum(), "data")   # per-micro-step sync
                return g + gi, None
            g, _ = lax.scan(accum, jnp.zeros((n,), jnp.float32), xs)
            return g

        fn = comm.shard_map(per_rank, mesh=mesh8,
                            in_specs=(P(), P(None, "data")),
                            out_specs=P(), check_vma=False)
        w = jnp.ones((n,), jnp.float32)
        xs = jnp.ones((2, 8, 4), jnp.float32)
        meta = {"grad_sync_path": True, "grad_sync_mode": "none",
                "gas": 2, "scatterable_leaf_bytes": [n * 4]}
        with mesh8:
            res = lint_jit(jax.jit(fn), w, xs, name="seeded_trapped",
                           meta=meta)
        assert not res.errors, res.errors
        assert _lints(res) == ["collective_placement"], \
            [f.fingerprint for f in res.findings]
        f = res.findings[0]
        assert f.key.startswith("ar-in-scan") and f.in_loop
        assert f.wire_bytes == 2 * \
            deepspeed_tpu.parallel.hlo_audit.ring_wire_bytes(
                "all-reduce", n * 4, 8)            # gas x per-trip wire

    def test_dense_engine_meta_exposes_grad_payloads(self, tmp_path):
        """Stage-0 dp=8 engines must hand the pass their grad leaf sizes
        (dense all-reduce payloads) — else the placement checks are
        unreachable exactly where the trapped-in-scan defect lives."""
        engine = _engine(tmp_path, "dense")
        meta = engine._lint_path_meta("train_step")
        assert meta["zero_stage"] < 2 and meta["dp"] == 8
        sizes = {int(l.size) * 4 for l in
                 jax.tree_util.tree_leaves(engine.state.params)}
        assert sizes <= set(meta["scatterable_leaf_bytes"])

    def test_grad_allreduce_under_declared_sharding_caught(self, mesh8):
        """The GSPMD declarative fallback, synthesized: a declared
        dp-sharded gradient this backend lowers to all-reduce + slice.
        The matmul matters — grad(w) sums over the dp-sharded batch, so
        the sync MUST move gradient-sized payload (an elementwise loss
        shards away and emits nothing)."""
        d = 16
        w_sh = NamedSharding(mesh8, P("data"))
        x_sh = NamedSharding(mesh8, P("data"))

        def probe(w, x):
            g = jax.grad(lambda w_, x_: jnp.mean((x_ @ w_) ** 2))(w, x)
            return lax.with_sharding_constraint(g, w_sh)

        w = jax.ShapeDtypeStruct((d, d), jnp.float32,
                                 sharding=NamedSharding(mesh8, P()))
        x = jax.ShapeDtypeStruct((d, d), jnp.float32, sharding=x_sh)
        meta = {"grad_sync_path": True, "grad_sync_mode": "declarative",
                "gas": 1, "scatterable_leaf_bytes": [d * d * 4]}
        res = lint_jit(jax.jit(probe), w, x, name="seeded_regression",
                       meta=meta)
        assert not res.errors, res.errors
        by_lint = {f.lint: f for f in res.findings}
        # This backend regresses the declaration (the hlo_audit probe is
        # part of tier-1); if a future backend honors it, the program has
        # a legal reduce-scatter and nothing may fire.
        from deepspeed_tpu.parallel import hlo_audit
        lowering = hlo_audit.zero2_grad_sync_lowering(mesh8, "data")
        if lowering == "all-reduce":
            assert "collective_placement" in by_lint
            assert by_lint["collective_placement"].key.startswith(
                "grad-allreduce")
        else:                      # pragma: no cover - honest backend
            assert "collective_placement" not in by_lint


# --------------------------------------------------------------------- #
# Clean engine paths: zero unwaived findings, zero added fences
# --------------------------------------------------------------------- #
class TestCleanEnginePaths:
    def test_zero2_engine_clean_and_fence_free(self, tmp_path):
        engine = _engine(tmp_path, "z2",
                         zero_optimization={"stage": 2})
        for i in range(2):
            engine.train_batch(batch=random_batch(n=16, seed=i))
        before = timer_mod.device_sync_count()
        rep = engine.lint_audit(waivers=load_waivers(WAIVER_FILE))
        assert timer_mod.device_sync_count() == before, \
            "the lint audit must be pure host work"
        assert not rep.errors, rep.errors
        assert rep.unwaived == [], [f.fingerprint for f in rep.unwaived]
        # The fused-chunk materialization finding is GONE, not waived:
        # the V-interleaved shard-local chunk layout keeps every flat
        # buffer dp-sharded through the kernels (ops/fused_update
        # docstring), so no full-chunk transient exists to flag.
        assert not any(f.lint == "materialization" for f, _ in rep.waived)
        assert not any(f.lint == "materialization" for f in rep.findings)

    def test_offload_engine_clean_and_fence_free(self, tmp_path):
        engine = _engine(tmp_path, "off",
                         zero_optimization={"stage": 2,
                                            "cpu_offload": True},
                         optimizer={"type": "Adam",
                                    "params": {"lr": 1e-2}})
        for i in range(2):
            engine.train_batch(batch=random_batch(n=16, seed=i))
        before = timer_mod.device_sync_count()
        rep = engine.lint_audit(waivers=load_waivers(WAIVER_FILE))
        assert timer_mod.device_sync_count() == before
        assert not rep.errors, rep.errors
        assert rep.unwaived == [], [f.fingerprint for f in rep.unwaived]
        # Since ISSUE 11 the offload grad pass takes the explicit
        # psum_scatter builder, so the declarative-regression finding
        # (and the waiver that covered it — the last one) is GONE, not
        # waived: the offload engine audits completely clean.
        assert rep.waived == []
        assert not any(f.lint == "collective_placement"
                       for f in rep.findings), \
            [f.fingerprint for f in rep.findings]

    def test_main_step_donations_all_aliased(self, tmp_path):
        """Regression for the donated-but-unaliased finding the linter
        surfaced on the ZeRO train step: without declared out_shardings,
        jax paired donated params to same-aval dp-sharded moments and the
        partitioner dropped the aliases — every param-sized buffer leaked
        one step of lifetime. The fix (state+metrics out_shardings on all
        donating step programs) must keep the donation pass silent."""
        engine = _engine(tmp_path, "don",
                         zero_optimization={"stage": 2},
                         optimizer={"type": "Adam",
                                    "params": {"lr": 1e-2,
                                               "fused": False}})
        engine.train_batch(batch=random_batch(n=16))
        rep = engine.lint_audit()
        assert not any(f.lint == "donation" for f in rep.findings), \
            [f.summary for f in rep.findings]
        # And structurally: every donated entry param is in the compiled
        # alias table.
        fn, a, kw = engine.telemetry.sentinel.registered_paths()[
            "train_step"]
        hlo = fn.lower(*a, **kw).compile().as_text()
        aliased = set(hlo_text.input_output_alias_params(hlo))
        n_params = len(hlo_text.entry_parameter_shapes(hlo))
        # 19 state leaves donated; batch + rng are not.
        assert len(aliased) == n_params - 2

    def test_trio_grad_step_uses_guaranteed_reduce_scatter(self, tmp_path):
        """Regression for the second true positive: the trio's
        ``grad_step`` declared sharded out_shardings, which this
        backend's GSPMD lowers to a full all-reduce + slice. Resolved-
        explicit engines now route it through the psum_scatter path:
        the compiled program must reduce-scatter and never all-reduce a
        gradient-sized payload."""
        engine = _engine(tmp_path, "trio",
                         zero_optimization={"stage": 2},
                         optimizer={"type": "Adam",
                                    "params": {"lr": 1e-2,
                                               "fused": False}})
        assert engine._grad_sync_mode == "explicit"
        batch = random_batch(n=16)
        engine.forward(batch)
        engine.backward()
        engine.step()
        rep = engine.lint_audit(waivers=load_waivers(WAIVER_FILE))
        assert rep.unwaived == [], [f.fingerprint for f in rep.unwaived]
        assert {"grad_step", "apply_grads"} <= \
            {p.name for p in rep.paths}
        fn, a, kw = engine.telemetry.sentinel.registered_paths()[
            "grad_step"]
        from deepspeed_tpu.parallel import hlo_audit
        audit = hlo_audit.audit_jit(fn, *a, **kw)
        assert audit.of_kind("reduce-scatter"), audit.summary()

    def test_trio_explicit_matches_declarative_values(self, tmp_path):
        """The explicit trio backward is numerically the declarative one:
        same loss, grads within f32 ulp (collective reduction order is
        the only difference — the PR-3 cross-program precedent)."""
        engines = {}
        for mode in ("declarative", "explicit"):
            engines[mode] = _engine(
                tmp_path, f"trio_{mode}", seed=3,
                zero_optimization={"stage": 2, "grad_sync": mode},
                optimizer={"type": "Adam",
                           "params": {"lr": 1e-2, "fused": False}})
        batch = random_batch(n=16, seed=5)
        losses, grads = {}, {}
        for mode, e in engines.items():
            losses[mode] = float(e.forward(batch))
            grads[mode] = jax.device_get(e._stashed_grads)
        assert losses["declarative"] == pytest.approx(
            losses["explicit"], rel=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(grads["declarative"]),
                        jax.tree_util.tree_leaves(grads["explicit"])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=0, atol=1e-6)


# --------------------------------------------------------------------- #
# Degraded-mapping fallback: count-only judgment must still report
# --------------------------------------------------------------------- #
class TestDonationFallback:
    def test_unattributable_mapping_still_finds_unaliased(self):
        """When the kept-parameter mapping is unavailable (len(kept) !=
        len(param_shapes): exotic backend / API drift), the pass judges
        by count only — and its unpriced (0-byte) finding must not be
        swallowed by the default donation_floor_bytes=0 guard."""
        from deepspeed_tpu.analysis.findings import LintContext
        from deepspeed_tpu.analysis.passes import donation_pass
        synth = ("HloModule m, entry_computation_layout="
                 "{(f32[4]{0}, f32[4]{0}, f32[4]{0})->f32[4]{0}}\n")
        ctx = LintContext(name="degraded", jaxpr=None,
                          donated_invars=(True, True), in_avals=(),
                          hlo_text=synth, audit=None)   # kept=[0,1] vs 3
        out = donation_pass(ctx)
        assert len(out) == 1 and out[0].lint == "donation"
        assert out[0].count == 2 and out[0].bytes == 0
        assert "unattributable" in str(out[0].details["unaliased_params"])

    def test_attributable_zero_bytes_stays_suppressed(self):
        """The floor guard still applies when bytes ARE attributable."""
        from deepspeed_tpu.analysis.findings import LintContext
        from deepspeed_tpu.analysis.passes import donation_pass
        synth = ("HloModule m, entry_computation_layout="
                 "{(f32[0]{0}, f32[4]{0})->f32[4]{0}}\n")
        ctx = LintContext(name="zero", jaxpr=None,
                          donated_invars=(True, False), in_avals=(),
                          hlo_text=synth, audit=None)
        assert donation_pass(ctx) == []

    def test_degraded_fallback_ignores_dropped_donated_args(self):
        """A donated arg jit DROPPED (keep_unused=False) is trivially
        honored and must not inflate the count-only expectation: with
        kept_var_idx in hand the kept donated args are counted exactly,
        so one aliased kept donation + one dropped donation is clean —
        not a spurious unwaivable finding."""
        from deepspeed_tpu.analysis.findings import LintContext
        from deepspeed_tpu.analysis.passes import donation_pass
        # 2 entry params vs len(kept)=1 -> mapping unattributable.
        synth = ("HloModule m, entry_computation_layout="
                 "{(f32[4]{0}, f32[4]{0})->f32[4]{0}}, "
                 "input_output_alias={ {}: (0, {}) }\n")
        ctx = LintContext(name="dropped", jaxpr=None,
                          donated_invars=(True, True), in_avals=(),
                          hlo_text=synth, audit=None, kept_var_idx=(0,))
        assert donation_pass(ctx) == []
        # The same kept mapping with NO alias entry still reports the
        # one genuinely kept-but-unaliased donation.
        bare = synth.replace(", input_output_alias={ {}: (0, {}) }", "")
        ctx = LintContext(name="dropped", jaxpr=None,
                          donated_invars=(True, True), in_avals=(),
                          hlo_text=bare, audit=None, kept_var_idx=(0,))
        out = donation_pass(ctx)
        assert len(out) == 1 and out[0].count == 1

    def test_degraded_fallback_without_kept_mapping_bounds_drops(self):
        """No kept_var_idx at all: at most n_args - n_entry_params
        inputs were dropped, so 2 donated args against 1 entry param and
        1 alias cannot prove an unhonored donation -> clean."""
        from deepspeed_tpu.analysis.findings import LintContext
        from deepspeed_tpu.analysis.passes import donation_pass
        synth = ("HloModule m, entry_computation_layout="
                 "{(f32[4]{0})->f32[4]{0}}, "
                 "input_output_alias={ {}: (0, {}) }\n")
        ctx = LintContext(name="bounded", jaxpr=None,
                          donated_invars=(True, True), in_avals=(),
                          hlo_text=synth, audit=None)
        assert donation_pass(ctx) == []


# --------------------------------------------------------------------- #
# Waiver machinery
# --------------------------------------------------------------------- #
class TestWaivers:
    def _finding(self, key="f32[131076]", lint="materialization",
                 path="train_step"):
        return LintFinding(lint=lint, path=path, key=key, summary="s")

    def test_glob_is_bracket_safe(self):
        """HLO shapes contain ``[...]`` — fnmatch character classes would
        swallow them; only ``*`` may be a wildcard."""
        w = Waiver(match="materialization:train_step:f32[131076]")
        assert w.matches(self._finding())
        assert not w.matches(self._finding(key="f32[1]"))
        star = Waiver(match="materialization:*:f32[131076]")
        assert star.matches(self._finding())
        assert not star.matches(self._finding(lint="donation"))

    def test_apply_waivers_splits_and_reports_stale(self):
        f1, f2 = self._finding(), self._finding(key="f32[9]",
                                                lint="dtype_flow")
        live = Waiver(match="materialization:*")
        stale = Waiver(match="host_sync:*", reason="gone")
        unwaived, waived, stales = apply_waivers([f1, f2], [live, stale])
        assert unwaived == [f2]
        assert [(f.fingerprint, w.match) for f, w in waived] == \
            [(f1.fingerprint, live.match)]
        assert stales == [stale]

    def test_load_waivers_missing_file_is_empty_baseline(self, tmp_path):
        assert load_waivers(str(tmp_path / "nope.json")) == []

    def test_repo_waiver_file_loads_with_roadmap_pointers(self):
        assert os.path.isfile(WAIVER_FILE), \
            "tools/lint_waivers.json must exist"
        waivers = load_waivers(WAIVER_FILE)
        # The baseline is EMPTY since the ZeRO-3 round retired the last
        # waiver (the offload grad pass now takes the explicit
        # psum_scatter builder); any future waiver needs a ROADMAP
        # pointer (waivers are debts).
        assert all(w.roadmap for w in waivers), \
            "every waiver needs a ROADMAP pointer (waivers are debts)"


# --------------------------------------------------------------------- #
# LINT_AUDIT.json: the recorded artifact's consistency contract
# --------------------------------------------------------------------- #
class TestLintAuditArtifact:
    @pytest.fixture(scope="class")
    def record(self):
        path = os.path.join(REPO, "LINT_AUDIT.json")
        assert os.path.exists(path), "run tools/ds_lint.py"
        return json.load(open(path))

    def test_all_pass_and_zero_fences(self, record):
        assert record["all_pass"] is True
        assert record["audit_device_fences"] == 0
        for name in ("zero1", "zero2", "zero3", "onebit", "offload",
                     "pipeline_1f1b", "serving"):
            assert record["configs"][name]["pass"] is True, name

    def test_every_finding_priced_or_explicitly_unpriced(self, record):
        for cfg in record["configs"].values():
            for f in cfg.get("findings", []):
                assert "priced" in f, f
                if f["priced"]:
                    assert isinstance(f.get("wire_bytes"), int), f
                else:
                    assert "bytes" in f, f

    def test_every_waiver_matches_a_live_finding(self, record):
        assert record["stale_waivers"] == []
        live = {f["fingerprint"] for c in record["configs"].values()
                for f in c.get("findings", [])}
        for entry in record["waived"]:
            assert entry["finding"]["fingerprint"] in live

    def test_ds_report_prints_lint_summary(self, record, capsys):
        from deepspeed_tpu import env_report
        lines = env_report.lint_report(
            [], path=os.path.join(REPO, "LINT_AUDIT.json"))
        assert lines and "static lint" in lines[-1]
        assert "waived" in lines[-1] and "newest" in lines[-1]

    def test_ds_report_silent_without_artifact(self, tmp_path,
                                               monkeypatch):
        from deepspeed_tpu import env_report
        monkeypatch.chdir(tmp_path)
        monkeypatch.delenv("DS_LINT_AUDIT", raising=False)
        monkeypatch.setattr(env_report, "find_lint_audit",
                            lambda path=None: "")
        assert env_report.lint_report([]) == []

    def test_explicit_missing_audit_never_falls_back(self, tmp_path,
                                                     monkeypatch):
        """An explicitly requested artifact ($DS_LINT_AUDIT or the path
        arg) that does not exist must be reported missing — never
        silently replaced by a stale fallback from cwd/repo root."""
        from deepspeed_tpu import env_report
        stale = tmp_path / "LINT_AUDIT.json"
        stale.write_text(json.dumps({"all_pass": True, "configs": {},
                                     "waived": []}))
        monkeypatch.chdir(tmp_path)   # stale artifact sits in cwd
        missing = str(tmp_path / "fresh" / "LINT_AUDIT.json")
        monkeypatch.delenv("DS_LINT_AUDIT", raising=False)
        assert env_report.find_lint_audit(missing) == ""
        lines = env_report.lint_report([], path=missing)
        assert lines == [f"static lint: requested audit missing: {missing}"]
        monkeypatch.setenv("DS_LINT_AUDIT", missing)
        assert env_report.find_lint_audit() == ""
        lines = env_report.lint_report([])
        assert lines == [f"static lint: requested audit missing: {missing}"]
        # The unrequested fallback chain still finds the cwd artifact.
        monkeypatch.delenv("DS_LINT_AUDIT", raising=False)
        assert env_report.find_lint_audit() == str(stale)

    @pytest.mark.slow
    def test_configs_subset_does_not_fail_on_foreign_waivers(self,
                                                             tmp_path):
        """--configs zero1 must not read the offload waiver as stale
        (findings.apply_waivers contract: a waiver for config B is not
        stale while auditing config A) nor overwrite a failing artifact."""
        import subprocess
        out = str(tmp_path / "subset.json")
        r = subprocess.run(
            [os.sys.executable, os.path.join(REPO, "tools", "ds_lint.py"),
             "--configs", "zero1", "--check", "--out", out],
            capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
        rec = json.load(open(out))
        assert rec["subset"] is True
        assert rec["stale_waivers"] == []
        assert rec["all_pass"] is True


# --------------------------------------------------------------------- #
# Registry handoff (monitor/recompile.py)
# --------------------------------------------------------------------- #
class TestRegistryHandoff:
    def test_registered_paths_after_one_step(self, tmp_path):
        engine = _engine(tmp_path, "reg")
        engine.train_batch(batch=random_batch(n=16))
        reg = engine.telemetry.sentinel.registered_paths()
        assert "train_step" in reg
        fn, a_args, a_kwargs = reg["train_step"]
        assert hasattr(fn, "lower")
        assert isinstance(a_args, tuple) and isinstance(a_kwargs, dict)
        # The recorded signature is abstract: re-lowering it must not
        # touch device buffers.
        before = timer_mod.device_sync_count()
        fn.lower(*a_args, **a_kwargs)
        assert timer_mod.device_sync_count() == before
