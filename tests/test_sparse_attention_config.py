"""The ds_config ``sparse_attention`` section, live end-to-end.

Reference: runtime/config.py:192-362 (mode-string → normalized section),
ops/sparse_attention/sparse_attention_utils.py:13-210 (SparseAttentionUtils)
and softmax.py:259-291 (RPE input).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, SparseAttentionUtils, SparseSelfAttention,
    VariableSparsityConfig, normalize_sparse_attention,
    sparsity_config_from_dict, sparse_attention)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import \
    sparse_attention_reference


BASE = {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1}


def _cfg(section):
    return DeepSpeedConfig({**BASE, "sparse_attention": section},
                           world_size=8)


def test_config_normalizes_defaults_per_mode():
    cfg = _cfg({"mode": "fixed", "block": 32})
    sa = cfg.sparse_attention
    assert sa["mode"] == "fixed" and sa["block"] == 32
    assert sa["num_local_blocks"] == 4 and sa["num_global_blocks"] == 1
    assert sa["attention"] == "bidirectional"
    cfg = _cfg({"mode": "bigbird"})
    sa = cfg.sparse_attention
    assert sa["num_sliding_window_blocks"] == 3 and sa["block"] == 16
    cfg = _cfg({"mode": "bslongformer"})
    assert cfg.sparse_attention["global_block_indices"] == [0]
    cfg = _cfg({"mode": "dense"})
    assert set(cfg.sparse_attention) == {"mode", "block"}
    assert DeepSpeedConfig(dict(BASE), world_size=8).sparse_attention is None


def test_config_rejects_unknown_mode_and_keys():
    with pytest.raises(NotImplementedError):
        _cfg({"mode": "strided"})
    with pytest.raises(ValueError):
        _cfg({"mode": "dense", "num_local_blocks": 4})


def test_factory_builds_every_mode():
    cases = [
        ({"mode": "dense"}, DenseSparsityConfig),
        ({"mode": "fixed", "num_local_blocks": 8}, FixedSparsityConfig),
        ({"mode": "variable", "num_random_blocks": 1,
          "local_window_blocks": [2, 4]}, VariableSparsityConfig),
        ({"mode": "bigbird", "num_random_blocks": 2}, BigBirdSparsityConfig),
        ({"mode": "bslongformer", "num_sliding_window_blocks": 5},
         BSLongformerSparsityConfig),
    ]
    for section, cls in cases:
        sc = sparsity_config_from_dict({**section, "block": 16}, num_heads=4)
        assert isinstance(sc, cls), section
        layout = sc.make_layout(256)
        assert layout.shape == (4, 16, 16)
        assert layout.sum() > 0


def test_sparse_self_attention_from_config_runs():
    ssa = SparseSelfAttention.from_config(
        {"mode": "fixed", "block": 16, "num_local_blocks": 2}, num_heads=2)
    rng = jax.random.PRNGKey(0)
    q, k, v = [jax.random.normal(jax.random.fold_in(rng, i), (1, 64, 2, 8))
               for i in range(3)]
    out = ssa(q, k, v)
    assert out.shape == (1, 64, 2, 8)
    assert np.isfinite(np.asarray(out)).all()


def test_rpe_bias_matches_dense_reference():
    """Additive RPE changes the scores exactly like adding it to the dense
    mask (reference softmax.py RPE semantics)."""
    rng = jax.random.PRNGKey(1)
    B, S, nH, dH = 2, 64, 2, 8
    q, k, v = [jax.random.normal(jax.random.fold_in(rng, i), (B, S, nH, dH))
               for i in range(3)]
    sc = FixedSparsityConfig(num_heads=nH, block=16, num_local_blocks=2)
    layout = sc.make_layout(S)
    rpe = jax.random.normal(jax.random.fold_in(rng, 9), (nH, S, S)) * 0.5
    got = sparse_attention(q, k, v, layout, rpe=rpe)
    from deepspeed_tpu.ops.flash_attention import _layout_to_mask
    from deepspeed_tpu.models.transformer import dense_attention
    want = dense_attention(q, k, v, causal=False,
                           mask=_layout_to_mask(layout, S, rpe[None]))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)


def test_pad_and_unpad_to_block_size():
    ids = jnp.ones((2, 50), jnp.int32)
    mask = jnp.ones((2, 50), jnp.int32)
    tt = jnp.zeros((2, 50), jnp.int32)
    pad_len, ids2, mask2, tt2, pos2, emb2 = \
        SparseAttentionUtils.pad_to_block_size(
            16, input_ids=ids, attention_mask=mask, token_type_ids=tt,
            pad_token_id=7)
    assert pad_len == 14 and ids2.shape == (2, 64)
    assert int(ids2[0, -1]) == 7 and int(mask2[0, -1]) == 0
    assert pos2 is None and emb2 is None
    out = jnp.ones((2, 64, 4))
    assert SparseAttentionUtils.unpad_sequence_output(pad_len, out).shape \
        == (2, 50, 4)
    # already-aligned: no-op
    pad_len, ids3, *_ = SparseAttentionUtils.pad_to_block_size(
        16, input_ids=jnp.ones((2, 64), jnp.int32))
    assert pad_len == 0 and ids3.shape == (2, 64)


def test_pad_inputs_embeds_via_model_embeddings():
    emb_table = jnp.arange(10 * 4, dtype=jnp.float32).reshape(10, 4)
    embeds = emb_table[jnp.ones((2, 30), jnp.int32)]
    pad_len, _, _, _, _, out = SparseAttentionUtils.pad_to_block_size(
        16, inputs_embeds=embeds, pad_token_id=3,
        model_embeddings=lambda ids: emb_table[ids])
    assert pad_len == 2 and out.shape == (2, 32, 4)
    np.testing.assert_allclose(np.asarray(out[0, -1]),
                               np.asarray(emb_table[3]))


@pytest.fixture(scope="module")
def tiny_bert():
    transformers = pytest.importorskip("transformers")
    from transformers import BertConfig, FlaxBertModel
    cfg = BertConfig(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=2, intermediate_size=64,
                     max_position_embeddings=64)
    return cfg, FlaxBertModel(cfg, seed=0)


def test_extend_position_embedding(tiny_bert):
    cfg, model = tiny_bert
    params = model.params
    new = SparseAttentionUtils.extend_position_embedding(params, 128)
    tbl = np.asarray(new["embeddings"]["position_embeddings"]["embedding"])
    old = np.asarray(
        params["embeddings"]["position_embeddings"]["embedding"])
    assert tbl.shape == (128, 32)
    np.testing.assert_array_equal(tbl[:64], old)
    np.testing.assert_array_equal(tbl[64:], old)
    with pytest.raises(ValueError):
        SparseAttentionUtils.extend_position_embedding(params, 32)


def test_replace_bert_attention_with_sparse(tiny_bert):
    """The functional module swap: HF weights through the fused blocks with
    block-sparse attention; parity with a dense-masked reference softmax
    over the same layout."""
    cfg, model = tiny_bert
    sc = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2)
    encoder_fn, stacked, tcfg = \
        SparseAttentionUtils.replace_model_self_attention_with_sparse_self_attention(
            cfg, model.params, sparsity_config=sc)
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 64, 32))
    out = encoder_fn(stacked, x)
    assert out.shape == (2, 64, 32)

    # parity: same blocks with a dense attention_fn masked to the layout
    from deepspeed_tpu.models.transformer import apply_blocks, dense_attention
    from deepspeed_tpu.ops.flash_attention import _layout_to_mask
    layout = sc.make_layout(64)

    def dense_masked(q, k, v, mask=None, causal=False, attn_dropout=0.0,
                     rng=None, deterministic=True):
        return dense_attention(q, k, v,
                               mask=_layout_to_mask(layout, 64, mask),
                               causal=causal)

    want = apply_blocks(stacked, x, tcfg, deterministic=True,
                        attention_fn=dense_masked)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-2, atol=2e-2)


def test_replace_rejects_mismatched_heads(tiny_bert):
    cfg, model = tiny_bert
    with pytest.raises(ValueError):
        SparseAttentionUtils.replace_model_self_attention_with_sparse_self_attention(
            cfg, model.params,
            sparsity_config=FixedSparsityConfig(num_heads=8, block=16))
