"""Pallas block-size autotuner (ops/autotune): the determinism contract.

Three independent guarantees, each load-bearing for tier-1:

1. GATING — ``DS_AUTOTUNE=0`` reproduces today's heuristic tiles
   bit-for-bit (no registry read, no search), and a plain CPU process
   never searches even with autotuning on: ``search_allowed()`` is the
   single gate, and ``DS_AUTOTUNE_FORCE=1`` is the explicit test-only
   override these tests use to exercise the search path off-TPU.

2. REGISTRY — first resolve of a key times the candidate grid once and
   persists the winner atomically (tmp + os.replace, no torn files);
   the second resolve — same process or a fresh one — returns the
   winner with ZERO measure calls.  A corrupt registry degrades to
   empty with a warning; a stale entry outside today's legal candidate
   grid is ignored rather than trusted.

3. NUMERICS — tiles move the schedule, not the arithmetic: the fused
   LN/GELU kernels produce bitwise-identical outputs under different
   pinned row blocks, which is what makes a shared on-disk tile cache
   safe at all.
"""
import json
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from capability import fused_elementwise_skip_reason
from deepspeed_tpu.ops import autotune


@pytest.fixture
def registry(tmp_path, monkeypatch):
    """Fresh on-disk registry + force-enabled search, zeroed counters."""
    path = str(tmp_path / "autotune.json")
    monkeypatch.setenv("DS_AUTOTUNE_REGISTRY", path)
    monkeypatch.setenv("DS_AUTOTUNE_FORCE", "1")
    monkeypatch.delenv("DS_AUTOTUNE", raising=False)
    autotune.reset()
    yield path
    autotune.reset()


class CountingMeasure:
    """measure(tile) stub: deterministic timings, call accounting."""

    def __init__(self, timings):
        self.timings = dict(timings)
        self.calls = []

    def __call__(self, tile):
        self.calls.append(tile)
        try:
            return self.timings[tile]
        except KeyError:
            raise RuntimeError(f"candidate {tile} does not compile")


class TestGating:
    def test_disabled_returns_heuristic(self, registry, monkeypatch):
        monkeypatch.setenv("DS_AUTOTUNE", "0")
        meas = CountingMeasure({32: 0.1, 64: 0.5})
        got = autotune.resolve("k", (8, 128), "float32", 64,
                               (32, 64), meas)
        assert got == 64
        assert meas.calls == []          # no search
        assert not os.path.exists(registry)   # no registry write
        assert autotune.counters["heuristic"] == 1
        assert not autotune.enabled() and not autotune.search_allowed()

    def test_cpu_without_force_never_searches(self, registry, monkeypatch):
        monkeypatch.delenv("DS_AUTOTUNE_FORCE", raising=False)
        if jax.default_backend() == "tpu":
            pytest.skip("gate under test is the off-TPU default")
        assert autotune.enabled() and not autotune.search_allowed()
        meas = CountingMeasure({32: 0.1, 64: 0.5})
        got = autotune.resolve("k", (8, 128), "float32", 64,
                               (32, 64), meas)
        assert got == 64 and meas.calls == []
        assert not os.path.exists(registry)

    def test_disabled_geom_matches_budget_loop(self, registry, monkeypatch):
        """DS_AUTOTUNE=0 -> _geom reproduces the static VMEM budget loop
        (today's tiles, bit-for-bit) for every kernel'd call site."""
        monkeypatch.setenv("DS_AUTOTUNE", "0")
        from deepspeed_tpu.ops.fused_elementwise import (_LANE, _VMEM_BUDGET,
                                                         _geom)
        for rows, H, n_bufs in [(64, 768, 5), (512, 3072, 4),
                                (8, 65536, 7), (1024, 128, 6)]:
            Hpad = -(-H // _LANE) * _LANE
            rb = 128
            while rb > 16 and rb * Hpad * 4 * n_bufs > _VMEM_BUDGET:
                rb //= 2
            got = _geom(rows, H, n_bufs, kernel="fused_ln_fwd",
                        dtype=jnp.float32, runner=None)
            assert got == (-(-rows // rb) * rb, Hpad, rb)

    def test_disabled_flash_blocks_match_pick_block(self, registry,
                                                    monkeypatch):
        monkeypatch.setenv("DS_AUTOTUNE", "0")
        from deepspeed_tpu.ops.flash_attention import (_BLOCK_TARGET,
                                                       _pick_block)
        for s in (128, 512, 1024, 4096):
            b = _pick_block(s)
            assert s % b == 0 and b <= max(s, _BLOCK_TARGET)


class TestRegistry:
    def test_search_once_then_registry_hit(self, registry):
        meas = CountingMeasure({32: 0.01, 64: 0.05, 128: 0.03})
        got = autotune.resolve("fused_ln_fwd", (512, 768, 5), "float32",
                               64, (32, 64, 128), meas)
        assert got == 32                     # fastest, not the heuristic
        assert sorted(meas.calls) == [32, 64, 128]
        assert autotune.counters["search"] == 1

        # Second resolve, same process: zero measure calls.
        meas2 = CountingMeasure({})
        got2 = autotune.resolve("fused_ln_fwd", (512, 768, 5), "float32",
                                64, (32, 64, 128), meas2)
        assert got2 == 32 and meas2.calls == []
        assert autotune.counters["hit"] == 1

        # Fresh process (in-memory cache dropped): served from disk.
        autotune._CACHE.clear()
        got3 = autotune.resolve("fused_ln_fwd", (512, 768, 5), "float32",
                                64, (32, 64, 128), meas2)
        assert got3 == 32 and meas2.calls == []

    def test_registry_file_shape_and_atomicity(self, registry):
        meas = CountingMeasure({(128, 128): 0.02, (256, 128): 0.01})
        got = autotune.resolve("grouped_gemm", (8, 256, 512, 1024),
                               "bfloat16", (128, 128),
                               [(128, 128), (256, 128)], meas)
        assert got == (256, 128)
        with open(registry) as f:
            reg = json.load(f)
        key = f"grouped_gemm|bfloat16[8,256,512,1024]|{autotune.chip_kind()}"
        ent = reg[key]
        assert ent["tile"] == [256, 128]
        assert ent["heuristic"] == [128, 128]
        assert ent["speedup_vs_heuristic"] == 2.0
        assert set(ent["timings_s"]) == {"(128, 128)", "(256, 128)"}
        # Atomic write: no temp droppings next to the registry.
        leftovers = [p for p in os.listdir(os.path.dirname(registry))
                     if p.startswith(".autotune_")]
        assert leftovers == []

        # Tuple roundtrip through JSON back to the call-site type.
        autotune._CACHE.clear()
        got2 = autotune.resolve("grouped_gemm", (8, 256, 512, 1024),
                                "bfloat16", (128, 128),
                                [(128, 128), (256, 128)],
                                CountingMeasure({}))
        assert got2 == (256, 128) and isinstance(got2, tuple)

    def test_corrupt_registry_degrades_to_empty(self, registry):
        with open(registry, "w") as f:
            f.write("{ this is not json")
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            got = autotune.resolve("k", (4, 4), "float32", 64, (64,), None)
        assert got == 64
        assert any("unreadable" in str(x.message) for x in w)
        # And a search afterwards rewrites a VALID file over the wreck.
        meas = CountingMeasure({32: 0.01, 64: 0.02})
        assert autotune.resolve("k", (4, 4), "float32", 64,
                                (32, 64), meas) == 32
        with open(registry) as f:
            assert json.load(f)  # parses again

    def test_stale_entry_outside_grid_is_ignored(self, registry):
        with open(registry, "w") as f:
            json.dump({f"k|float32[4,4]|{autotune.chip_kind()}":
                       {"tile": 999}}, f)
        meas = CountingMeasure({32: 0.02, 64: 0.01})
        got = autotune.resolve("k", (4, 4), "float32", 64, (32, 64), meas)
        assert got == 64                 # re-searched, 999 not trusted
        assert sorted(meas.calls) == [32, 64]

    def test_failing_candidate_is_discarded(self, registry):
        meas = CountingMeasure({64: 0.02})   # 32 raises (no compile)
        got = autotune.resolve("k", (9, 9), "float32", 64, (32, 64), meas)
        assert got == 64
        assert sorted(meas.calls) == [32, 64]

    def test_no_measure_returns_heuristic_without_record(self, registry):
        got = autotune.resolve("k", (3, 3), "float32", 64, (32, 64), None)
        assert got == 64
        assert not os.path.exists(registry)
        assert autotune.counters["heuristic"] == 1

    def test_pow2_candidates_respects_budget(self):
        assert autotune.pow2_candidates(16, 256) == (16, 32, 64, 128, 256)
        assert autotune.pow2_candidates(16, 256, lambda c: c <= 64) == \
            (16, 32, 64)
        assert autotune.pow2_candidates(200, 100) == ()


@pytest.mark.skipif(fused_elementwise_skip_reason() is not None,
                    reason=fused_elementwise_skip_reason() or "")
class TestTileBitIdentity:
    """Tiles move the schedule, not the arithmetic — the property that
    makes a shared tile registry safe."""

    def _rand(self, shape, seed, dtype=jnp.float32):
        r = np.random.default_rng(seed)
        return jnp.asarray(r.standard_normal(shape),
                           jnp.float32).astype(dtype)

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_ln_forward_bitwise_across_row_blocks(self, dtype):
        from deepspeed_tpu.ops.fused_elementwise import _ln_forward
        x = self._rand((256, 384), 0, dtype)
        sc = self._rand((384,), 1)
        bi = self._rand((384,), 2)
        outs = [_ln_forward(x, None, sc, bi, 1e-5, _rb=rb)[1]
                for rb in (32, 128)]
        np.testing.assert_array_equal(np.asarray(outs[0]),
                                      np.asarray(outs[1]))

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_gelu_forward_bitwise_across_row_blocks(self, dtype):
        from deepspeed_tpu.ops.fused_elementwise import _gelu_apply
        y = self._rand((256, 256), 3, dtype)
        b = self._rand((256,), 4)
        outs = [_gelu_apply(y, b, False, _rb=rb) for rb in (32, 128)]
        np.testing.assert_array_equal(np.asarray(outs[0]),
                                      np.asarray(outs[1]))

    def test_grouped_gemm_bitwise_across_tiles(self):
        from deepspeed_tpu.ops.grouped_gemm import _grouped_matmul
        a = self._rand((4, 64, 96), 5)
        b = self._rand((4, 96, 256), 6)
        outs = [np.asarray(_grouped_matmul(a, b, _tile=t))
                for t in ((32, 128), (64, 256))]
        np.testing.assert_array_equal(outs[0], outs[1])
