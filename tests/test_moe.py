"""MoE expert parallelism (deepspeed_tpu/moe/): the load-bearing claims.

- **Dense parity**: ``num_experts=1, top_k=1`` with unbounded capacity is
  BIT-identical to the dense FFN (same matmuls, gate exactly 1.0, no
  drops) — the MoE layer is a strict generalization, not an
  approximation.
- **Collectives by construction**: the compiled ep=4 train step contains
  the dispatch + combine ``all-to-all`` pair per MoE layer (x2 for
  backward — the vjp of an all-to-all is an all-to-all), priced within
  5% of ``hlo_audit.moe_alltoall_wire_model``; expert-weight gradients
  all-reduce over ``data`` within their expert group ONLY, and the
  seeded cross-expert all-reduce is caught by the collective_placement
  lint pass.
- **Convergence**: an 8-expert top-2 gpt2-tiny LEARNS the copy task
  through the full engine stack on the ep=4 x dp=2 CPU mesh (the
  tests/test_convergence.py workload), and the expert-sharded state
  roundtrips through checkpoint save/load.
- **Telemetry**: per-expert routed counts / drop fraction / aux loss
  ride the batched drain with zero added hot-path device syncs
  (``device_sync_count``-fenced, the PR-10 idiom).
"""
import dataclasses
import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.analysis.auditor import lint_jit
from deepspeed_tpu.models.gpt2 import (GPT2_CONFIGS, gpt2_apply, gpt2_init,
                                       gpt2_loss_fn)
from deepspeed_tpu.moe import (MoEConfig, expert_capacity,
                               gpt2_moe_param_shardings, is_expert_spec,
                               moe_layer_indices)
from deepspeed_tpu.moe.layer import _dispatch_plan, router_topk
from deepspeed_tpu.parallel import comm, hlo_audit
from deepspeed_tpu.parallel.topology import build_mesh, DP_AXIS, EP_AXIS
from deepspeed_tpu.utils import timer as timer_mod

VOCAB = 64
SEP = VOCAB - 2
HALF = 16
S = 2 * HALF + 1


def copy_batches(n_batches, batch, seed):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        prefix = rng.integers(0, SEP, size=(batch, HALF), dtype=np.int32)
        sep = np.full((batch, 1), SEP, np.int32)
        seq = np.concatenate([prefix, sep, prefix], axis=1)
        pad = np.full((batch, 1), SEP, np.int32)
        out.append(np.concatenate([seq, pad], axis=1))
    return out


def tiny_cfg(**kw):
    return dataclasses.replace(
        GPT2_CONFIGS["gpt2-tiny"], vocab_size=VOCAB, max_seq_length=S,
        hidden_size=128, num_heads=4, num_layers=2, hidden_dropout=0.0,
        attn_dropout=0.0, dtype=jnp.float32, fused_kernels=False, **kw)


def moe8(ep=4, **kw):
    base = dict(num_experts=8, top_k=2, capacity_factor=1.5,
                expert_parallel_size=ep)
    base.update(kw)
    return MoEConfig(**base)


def moe_ds_config(moe: MoEConfig, stage=2, lr=3e-3, gas=1, **extra):
    cfg = {
        "train_batch_size": 32 * gas,
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": gas,
        "zero_optimization": {"stage": stage},
        "gradient_clipping": 1.0,
        "optimizer": {"type": "AdamW", "params": {"lr": lr}},
        "moe": {"num_experts": moe.num_experts, "top_k": moe.top_k,
                "capacity_factor": moe.capacity_factor,
                "aux_loss_weight": moe.aux_loss_weight,
                "z_loss_weight": moe.z_loss_weight,
                "expert_parallel_size": moe.expert_parallel_size,
                "grouped_gemm": moe.grouped_gemm},
        "steps_per_print": 10 ** 9,
    }
    cfg.update(extra)
    return cfg


def build_engine(moe: MoEConfig, stage=2, gas=1, seed=0, **extra):
    mesh = build_mesh(ep=moe.expert_parallel_size)
    cfg = tiny_cfg(moe=moe)
    engine, *_ = deepspeed_tpu.initialize(
        model=gpt2_loss_fn(cfg, mesh=mesh),
        model_params=gpt2_init(jax.random.PRNGKey(seed), cfg),
        config=moe_ds_config(moe, stage=stage, gas=gas, **extra),
        mesh=mesh, param_shardings=gpt2_moe_param_shardings(cfg))
    return engine, cfg, mesh


# --------------------------------------------------------------------- #
# Unit: capacity / routing / dispatch plan
# --------------------------------------------------------------------- #
class TestRouting:
    def test_expert_capacity(self):
        assert expert_capacity(128, 8, 2, 1.0) == 32
        assert expert_capacity(128, 8, 2, 1.25) == 40
        assert expert_capacity(128, 8, 1, float("inf")) == 128
        assert expert_capacity(128, 8, 2, 100.0) == 128   # clamped to T
        assert expert_capacity(4, 8, 1, 0.1) == 1          # floor 1

    def test_moe_layer_indices(self):
        assert moe_layer_indices(4, 1) == [0, 1, 2, 3]
        assert moe_layer_indices(4, 2) == [1, 3]
        assert moe_layer_indices(5, 3) == [2]

    def test_topk_gates_renormalize(self):
        x = jnp.asarray(np.random.default_rng(0).normal(size=(16, 8)),
                        jnp.float32)
        w = jnp.asarray(np.random.default_rng(1).normal(size=(8, 4)),
                        jnp.float32)
        gates, idx, probs, _ = router_topk(x, w, 2)
        np.testing.assert_allclose(np.asarray(gates.sum(-1)),
                                   np.ones(16), rtol=1e-6)
        assert (np.asarray(idx[:, 0]) != np.asarray(idx[:, 1])).all()
        # k=1: the single gate is EXACTLY 1.0 (x/x) — the dense-parity
        # anchor.
        g1, _, _, _ = router_topk(x, w, 1)
        assert (np.asarray(g1) == 1.0).all()

    def test_dispatch_plan_drops_beyond_capacity(self):
        # All 6 tokens choose expert 0; capacity 4 -> 2 drop, positions
        # are the running count in priority order.
        idx = jnp.zeros((6, 1), jnp.int32)
        dest, keep, counts = _dispatch_plan(idx, num_experts=2, capacity=4)
        np.testing.assert_array_equal(np.asarray(keep),
                                      [True] * 4 + [False] * 2)
        np.testing.assert_array_equal(np.asarray(dest[:4]), [0, 1, 2, 3])
        assert (np.asarray(dest[4:]) == 2 * 4).all()       # the drop bin
        np.testing.assert_array_equal(np.asarray(counts), [6.0, 0.0])


# --------------------------------------------------------------------- #
# Dense parity: num_experts=1 == the dense FFN, bitwise
# --------------------------------------------------------------------- #
class TestDenseParity:
    def test_single_expert_bit_identical_to_dense(self):
        dense_cfg = tiny_cfg()
        moe_cfg = tiny_cfg(moe=MoEConfig(
            num_experts=1, top_k=1, capacity_factor=float("inf"),
            aux_loss_weight=0.0, z_loss_weight=0.0,
            expert_parallel_size=1))
        dp = gpt2_init(jax.random.PRNGKey(0), dense_cfg)
        mp = gpt2_init(jax.random.PRNGKey(0), moe_cfg)
        blocks = dict(mp["blocks"])
        # The single expert IS the dense FFN's weights.
        blocks["moe_fc_kernel"] = dp["blocks"]["fc_kernel"][:, None]
        blocks["moe_fc_bias"] = dp["blocks"]["fc_bias"][:, None]
        blocks["moe_out_kernel"] = dp["blocks"]["fc_out_kernel"][:, None]
        blocks["moe_out_bias"] = dp["blocks"]["fc_out_bias"][:, None]
        mp = {**{k: dp[k] for k in dp if k != "blocks"}, "blocks": blocks}
        tokens = jnp.asarray(np.random.default_rng(0).integers(
            0, VOCAB, size=(4, S - 1)), jnp.int32)
        ld = np.asarray(gpt2_apply(dp, tokens, dense_cfg))
        lm = np.asarray(gpt2_apply(mp, tokens, moe_cfg))
        np.testing.assert_array_equal(ld, lm)

    def test_unrolled_freq2_mixes_dense_and_moe(self):
        # moe_layer_freq=2 on 2 layers: layer 0 dense, layer 1 MoE —
        # separate stacks, each covering only its own layers.
        cfg = tiny_cfg(moe=moe8(ep=1), moe_layer_freq=2,
                       scan_layers=False)
        params = gpt2_init(jax.random.PRNGKey(0), cfg)
        assert params["blocks"]["fc_kernel"].shape[0] == 1
        assert params["blocks"]["moe_fc_kernel"].shape[0] == 1
        loss_fn = gpt2_loss_fn(cfg)
        batch = np.random.default_rng(0).integers(
            0, VOCAB, size=(8, S + 1)).astype(np.int32)
        loss, aux = jax.jit(loss_fn)(params, jnp.asarray(batch),
                                     jax.random.PRNGKey(1))
        assert np.isfinite(float(loss))
        assert "moe" in aux

    def test_scan_freq2_raises(self):
        cfg = tiny_cfg(moe=moe8(ep=1), moe_layer_freq=2, scan_layers=True)
        params = gpt2_init(jax.random.PRNGKey(0), cfg)
        batch = np.zeros((8, S + 1), np.int32)
        with pytest.raises(ValueError, match="scan_layers=False"):
            gpt2_loss_fn(cfg)(params, jnp.asarray(batch),
                              jax.random.PRNGKey(0))


# --------------------------------------------------------------------- #
# The wire pattern: all-to-all dispatch/combine, priced
# --------------------------------------------------------------------- #
class TestMoECollectives:
    def test_train_step_emits_priced_alltoalls(self):
        moe = moe8(ep=4)
        engine, cfg, mesh = build_engine(moe, stage=1)
        # Unrolled layers so every collective appears literally (no
        # scan-trip multiplication needed).
        ucfg = dataclasses.replace(cfg, scan_layers=False)
        engine2, *_ = deepspeed_tpu.initialize(
            model=gpt2_loss_fn(ucfg, mesh=mesh),
            model_params=gpt2_init(jax.random.PRNGKey(0), ucfg),
            config=moe_ds_config(moe, stage=1), mesh=mesh,
            param_shardings=gpt2_moe_param_shardings(ucfg))
        batch = np.random.default_rng(0).integers(
            0, VOCAB, size=(32, S + 1)).astype(np.int32)
        mb = engine2._stack_micro_batches(batch)
        mb = jax.device_put(mb, engine2._batch_sharding(mb, leading_dims=2))
        audit = hlo_audit.audit_jit(engine2._build_train_step(),
                                    engine2.state, mb, engine2._base_rng)
        a2a = audit.of_kind("all-to-all")
        n_moe = cfg.num_layers
        # >= 2 per MoE layer (dispatch + combine); exactly 4 with the
        # backward re-exchanges.
        assert len(a2a) >= 2 * n_moe
        assert len(a2a) == 4 * n_moe
        tokens_per_device = (32 // engine2.replica_size) * S
        model = hlo_audit.moe_alltoall_wire_model(
            hidden=cfg.hidden_size, num_experts=moe.num_experts,
            top_k=moe.top_k, capacity_factor=moe.capacity_factor,
            ep=4, n_moe_layers=n_moe, bytes_per_el=4,
            tokens_per_device=tokens_per_device)
        compiled_wire = sum(o.wire_bytes for o in a2a)
        assert abs(compiled_wire - model["wire_bytes_per_step"]) <= \
            0.05 * model["wire_bytes_per_step"], \
            (compiled_wire, model["wire_bytes_per_step"])
        # Every dispatch/combine moves exactly the [E, C, H] buffer over
        # the 4-member expert groups.
        assert all(o.payload_bytes == model["dispatch_buffer_bytes"]
                   for o in a2a)
        assert all(o.group_size == 4 for o in a2a)
        # Expert grads: any all-reduce of an expert-kernel payload stays
        # within the data axis (group <= dp) — experts are not replicas.
        meta = engine2._lint_path_meta("train_step")
        expert_bytes = set(meta["expert_leaf_bytes"])
        assert expert_bytes, "engine reported no expert leaf payloads"
        offenders = [o for o in audit.of_kind("all-reduce")
                     if o.payload_bytes in expert_bytes
                     and o.group_size > engine2.dp_size]
        assert not offenders, [(o.payload_bytes, o.group_size)
                               for o in offenders]
        # And no collective GATHERS token buffers across the expert
        # groups (the all-to-all degenerating to all-gather — gathers
        # over the data axis are the legal ZeRO param pattern).
        gathered = [o for o in audit.of_kind("all-gather")
                    if o.group_size > engine2.dp_size
                    and o.payload_bytes >= model["dispatch_buffer_bytes"]]
        assert not gathered

    def test_wire_model_shapes(self):
        m = hlo_audit.moe_alltoall_wire_model(
            hidden=128, num_experts=8, top_k=2, capacity_factor=1.25,
            ep=4, n_moe_layers=2, bytes_per_el=4, tokens_per_device=132)
        c = expert_capacity(132, 8, 2, 1.25)
        buf = 8 * c * 128 * 4
        assert m["dispatch_buffer_bytes"] == buf
        assert m["wire_bytes_per_step"] == \
            4 * 2 * hlo_audit.ring_wire_bytes("all-to-all", buf, 4)
        # ep=1 prices to zero — no collective exists.
        z = hlo_audit.moe_alltoall_wire_model(
            hidden=128, num_experts=8, top_k=2, capacity_factor=1.25,
            ep=1, tokens_per_device=132)
        assert z["wire_bytes_per_step"] == 0

    def test_grad_sync_wire_model_grows_moe_term(self):
        params = {"w": jnp.zeros((64, 64), jnp.float32)}
        out = hlo_audit.grad_sync_wire_model(
            params, 2, moe=dict(hidden=128, num_experts=8, top_k=2,
                                capacity_factor=1.25, ep=4,
                                n_moe_layers=2, bytes_per_el=4,
                                tokens_per_device=132))
        assert out["moe_alltoall_wire_bytes"] == \
            out["moe"]["wire_bytes_per_step"] > 0


# --------------------------------------------------------------------- #
# Seeded violation: cross-expert all-reduce caught by the lint pass
# --------------------------------------------------------------------- #
class TestSeededExpertViolation:
    N = 64 * 1024   # elements; payload 256 KiB clears the 64 KiB floor

    def _program(self, mesh, cross_expert: bool):
        n = self.N

        def per_device(w, x):
            g = w * jnp.sum(x)
            # The legal sync: expert grads all-reduce over data within
            # their expert group. The seeded violation psums over BOTH
            # axes — experts treated as replicas.
            axes = (EP_AXIS, DP_AXIS) if cross_expert else (DP_AXIS,)
            return lax.psum(g, axes)

        return comm.shard_map(
            per_device, mesh=mesh,
            in_specs=(P(EP_AXIS), P((EP_AXIS, DP_AXIS))),
            out_specs=P(EP_AXIS) if not cross_expert else P(EP_AXIS),
            check_vma=False)

    def _lint(self, cross_expert: bool):
        mesh = build_mesh(ep=4)
        fn = self._program(mesh, cross_expert)
        w = jnp.ones((4 * self.N,), jnp.float32)      # [E*n] over expert
        x = jnp.ones((8, 4), jnp.float32)
        meta = {"expert_leaf_bytes": [self.N * 4],
                "expert_group_size": 2}
        with mesh:
            res = lint_jit(jax.jit(fn), w, x, name="seeded_expert",
                           meta=meta)
        assert not res.errors, res.errors
        return [f for f in res.findings
                if f.lint == "collective_placement"]

    def test_cross_expert_allreduce_fires(self):
        findings = self._lint(cross_expert=True)
        assert findings, "seeded cross-expert all-reduce not caught"
        f = findings[0]
        assert f.key.startswith("expert-grad-allreduce")
        assert f.priced and f.details["group_size"] > 2

    def test_within_group_allreduce_clean(self):
        assert self._lint(cross_expert=False) == []


# --------------------------------------------------------------------- #
# Telemetry: stats ride the drain, zero added hot-path syncs
# --------------------------------------------------------------------- #
class TestMoETelemetry:
    def _sync_delta(self, tmp_path, telemetry: bool):
        extra = {}
        if telemetry:
            extra["telemetry"] = {"enabled": True,
                                  "output_path": str(tmp_path),
                                  "job_name": "moe", "report_steps": 100}
        engine, cfg, _ = build_engine(moe8(ep=4), stage=1, **extra)
        rng = np.random.default_rng(0)
        batch = rng.integers(0, VOCAB, size=(32, S + 1)).astype(np.int32)
        engine.train_batch(batch)          # compile outside the fence
        before = timer_mod.device_sync_count()
        for _ in range(3):
            engine.train_batch(batch)
        delta = timer_mod.device_sync_count() - before
        engine.telemetry.close()
        return delta

    def test_stats_ride_drain_fence_free(self, tmp_path):
        # The PR-10 fence idiom: collecting MoE stats adds ZERO device
        # syncs over the telemetry-off baseline (stats ride the batched
        # drain as futures; report_steps=100 means no drain in-window).
        off = self._sync_delta(tmp_path / "off", telemetry=False)
        on = self._sync_delta(tmp_path / "on", telemetry=True)
        assert on == off, (on, off)
        recs = [json.loads(l) for l in
                open(os.path.join(tmp_path, "on", "moe.jsonl"))]
        meta = next(r for r in recs if r["kind"] == "meta")
        assert meta["ep"] == 4 and meta["moe"]["num_experts"] == 8
        steps = [r for r in recs if r["kind"] == "step"]
        assert len(steps) == 4
        for r in steps:
            assert len(r["moe_expert_tokens"]) == 8
            assert 0.0 <= r["moe_drop_fraction"] <= 1.0
            assert np.isfinite(r["moe_aux_loss"])
        # Routed counts conserve: sum over experts == k * tokens/step.
        total = sum(steps[0]["moe_expert_tokens"])
        assert total == pytest.approx(2 * 32 * S, rel=1e-6)
        # tools/telemetry_report.py grows the moe section from the same
        # stream.
        import importlib.util
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "telemetry_report", os.path.join(repo, "tools",
                                             "telemetry_report.py"))
        tr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(tr)
        summary = tr.summarize(os.path.join(tmp_path, "on", "moe.jsonl"))
        sec = summary["moe"]
        assert sec["available"] and sec["steps"] == 4
        assert sec["config"]["num_experts"] == 8 and sec["ep"] == 4
        assert 0.0 <= sec["drop_fraction"]["p95"] <= 1.0
        assert sec["expert_imbalance"]["p50"] >= 1.0

    def test_dense_model_with_moe_block_raises(self):
        mesh = build_mesh(ep=1)
        cfg = tiny_cfg()                    # dense model...
        moe = moe8(ep=1)
        engine, *_ = deepspeed_tpu.initialize(
            model=gpt2_loss_fn(cfg),
            model_params=gpt2_init(jax.random.PRNGKey(0), cfg),
            config=moe_ds_config(moe, stage=0), mesh=mesh)
        batch = np.zeros((32, S + 1), np.int32)
        with pytest.raises(ValueError, match="moe"):
            engine.train_batch(batch)


# --------------------------------------------------------------------- #
# Engine composition: ZeRO stages, grad accumulation, checkpoints
# --------------------------------------------------------------------- #
class TestMoEEngine:
    @pytest.mark.parametrize("stage,gas", [(0, 1), (1, 1), (2, 1), (2, 2),
                                           (3, 1)])
    def test_trains_finite(self, stage, gas):
        engine, cfg, _ = build_engine(moe8(ep=4), stage=stage, gas=gas)
        rng = np.random.default_rng(0)
        losses = []
        for i in range(3):
            b = rng.integers(0, VOCAB, size=(32 * gas, S + 1)) \
                .astype(np.int32)
            losses.append(float(jax.device_get(engine.train_batch(b))))
        assert np.isfinite(losses).all(), (stage, gas, losses)

    def test_expert_params_born_sharded(self):
        engine, cfg, _ = build_engine(moe8(ep=4), stage=2)
        spec = engine.state.params["blocks"]["moe_fc_kernel"].sharding.spec
        assert is_expert_spec(spec), spec
        # The moments mirror the expert layout (element-aligned apply).
        opt_leaves = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda x: x.sharding.spec,
                                   engine.state.opt_state,
                                   is_leaf=lambda x: hasattr(x, "sharding")))
        assert any(is_expert_spec(sp) for sp in opt_leaves
                   if isinstance(sp, P))

    def test_checkpoint_roundtrip_expert_sharded(self, tmp_path):
        engine, cfg, mesh = build_engine(moe8(ep=4), stage=2)
        rng = np.random.default_rng(0)
        batch = rng.integers(0, VOCAB, size=(32, S + 1)).astype(np.int32)
        for _ in range(2):
            engine.train_batch(batch)
        engine.save_checkpoint(str(tmp_path), tag="moe")
        want = jax.device_get(engine.state.params)
        want_opt = jax.device_get(engine.state.opt_state)

        engine2, *_ = build_engine(moe8(ep=4), stage=2, seed=1)
        engine2.load_checkpoint(str(tmp_path), tag="moe")
        got = jax.device_get(engine2.state.params)
        jax.tree_util.tree_map(np.testing.assert_array_equal, want, got)
        jax.tree_util.tree_map(np.testing.assert_array_equal, want_opt,
                               jax.device_get(engine2.state.opt_state))
        # Restored leaves keep the expert sharding.
        assert is_expert_spec(
            engine2.state.params["blocks"]["moe_fc_kernel"].sharding.spec)
        # And the restored engine still trains.
        assert np.isfinite(float(jax.device_get(
            engine2.train_batch(batch))))

    def test_ep_mesh_mismatch_raises(self):
        moe = moe8(ep=4)
        cfg = tiny_cfg(moe=moe)
        with pytest.raises(ValueError, match="expert_parallel_size"):
            deepspeed_tpu.initialize(
                model=gpt2_loss_fn(cfg),
                model_params=gpt2_init(jax.random.PRNGKey(0), cfg),
                config=moe_ds_config(moe), mesh=build_mesh(ep=1))

    def test_moe_config_validation(self):
        from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                                  DeepSpeedConfigError)
        base = {"train_batch_size": 8, "optimizer": {
            "type": "Adam", "params": {"lr": 1e-3}}}
        with pytest.raises(DeepSpeedConfigError, match="divisible"):
            DeepSpeedConfig({**base, "moe": {"num_experts": 6,
                                            "expert_parallel_size": 4}},
                            world_size=1)
        with pytest.raises(DeepSpeedConfigError, match="top_k"):
            DeepSpeedConfig({**base, "moe": {"num_experts": 4,
                                            "top_k": 3}}, world_size=1)
        with pytest.raises(DeepSpeedConfigError, match="num_experts"):
            DeepSpeedConfig({**base, "moe": {"num_experts": 0,
                                            "expert_parallel_size": 2}},
                            world_size=1)


# --------------------------------------------------------------------- #
# Tooling: bench_gate parses and gates the MoE drop fraction
# --------------------------------------------------------------------- #
class TestFactoredExplicitStage2:
    """ROADMAP 4(b), closed: dense grads on the (expert, data) mesh
    reduce-scatter over `data` instead of regressing to the declarative
    all-reduce + slice — the explicit psum_scatter builder extended to
    factored meshes (the same outer-axis machinery the multislice
    hierarchical sync uses; tools/comm_audit.py's moe flagship records
    the closure)."""

    def test_stage2_resolves_explicit_and_reduce_scatters(self):
        engine, cfg, mesh = build_engine(moe8(), stage=2)
        assert engine._grad_sync_mode == "explicit"
        batch = copy_batches(1, 32, seed=0)[0]
        mb = engine._stack_micro_batches(batch)
        mb = jax.device_put(mb,
                            engine._batch_sharding(mb, leading_dims=2))
        audit = hlo_audit.audit_jit(engine._build_train_step(),
                                    engine.state, mb, engine._base_rng)
        rs = audit.of_kind("reduce-scatter")
        assert rs, "stage-2 factored path compiled no reduce-scatter"
        assert all(o.group_size == engine.dp_size for o in rs)
        # The regression's signature — a DIVISIBLE dense leaf's full-
        # size all-reduce — must be gone. (Shard-size collisions are
        # excluded, as in the comm_audit flagship.)
        from deepspeed_tpu.runtime.zero.partition import _leaf_spec
        spec_leaves = jax.tree_util.tree_structure(
            engine.state.params).flatten_up_to(engine._param_specs)
        dense_div, shards = set(), set()
        for l, sp in zip(jax.tree_util.tree_leaves(engine.state.params),
                         spec_leaves):
            if is_expert_spec(sp):
                continue
            n = int(l.size) * 4
            if any(s is not None for s in
                   _leaf_spec(l.shape, engine.dp_size, DP_AXIS)):
                dense_div.add(n)
                shards.add(n // engine.dp_size)
        bad = [o for o in audit.of_kind("all-reduce")
               if o.payload_bytes in (dense_div - shards)]
        assert not bad, [(o.payload_bytes, o.group_size) for o in bad]
        # The a2a family is untouched by the grad-path change (the
        # scanned-layer model carries the fwd pair + bwd transposes
        # once inside the loop body).
        a2a = audit.of_kind("all-to-all")
        assert len(a2a) == 4 and all(o.in_loop for o in a2a)

    def test_explicit_matches_declarative_stage1_first_step(self):
        """Same model, same batch: the factored explicit stage-2 step
        produces the same loss and near-identical params as the
        stage-1 declarative step (different collective associations —
        the usual few-ulp cross-program limit; the mean-correction
        arithmetic must agree exactly at f32 display precision)."""
        batch = copy_batches(1, 32, seed=3)[0]
        e2, *_ = build_engine(moe8(), stage=2, seed=1)
        e1, *_ = build_engine(moe8(), stage=1, seed=1)
        assert e2._grad_sync_mode == "explicit"
        l2 = float(e2.train_batch(batch=batch))
        l1 = float(e1.train_batch(batch=batch))
        assert l2 == pytest.approx(l1, rel=1e-5)
        p2 = jax.device_get(e2.state.params)
        p1 = jax.device_get(e1.state.params)
        flat2 = jax.tree_util.tree_leaves(p2)
        flat1 = jax.tree_util.tree_leaves(p1)
        for a, b in zip(flat2, flat1):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       atol=5e-5, rtol=0)


def test_bench_gate_moe_drop_extraction():
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_gate", os.path.join(repo, "tools", "bench_gate.py"))
    bg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bg)
    # TELEMETRY.json shape and the MOE_BENCH.json shape both parse.
    m = bg.extract_metrics({"moe": {"available": True,
                                    "drop_fraction": {"p95": 0.12}}})
    assert m["moe_drop"] == 0.12
    m = bg.extract_metrics({"moe": {"drop_fraction": 0.07}})
    assert m["moe_drop"] == 0.07
    # Pre-MoE rounds carry nothing -> None -> the gate skips.
    assert bg.extract_metrics({"mfu": 0.5})["moe_drop"] is None


# --------------------------------------------------------------------- #
# Grouped-GEMM expert kernel (ops/grouped_gemm) vs the einsum pair
# --------------------------------------------------------------------- #
class TestGroupedGEMM:
    """One Pallas kernel over [E,C,H]x[E,H,F] replaces the two einsums in
    ``_moe_tokens`` — cfg-static dispatch mirroring fused_kernels.

    Numerics tiers are the fused-elementwise contract: fp32 within a few
    f32 ulp (cross-program MXU accumulation association is the residue —
    the PR-1 limit), bf16 within ~2 bf16 ulp (the kernel rounds ONCE per
    stage where the einsum chain rounds per op)."""

    def _ffn_ref(self, x, w1, b1, w2, b2, exact):
        h = jnp.einsum("ech,ehf->ecf", x, w1) + b1[:, None, :]
        h = jax.nn.gelu(h, approximate=not exact)
        return jnp.einsum("ecf,efh->ech", h, w2) + b2[:, None, :]

    def _mats(self, E, C, H, F, dtype, seed=0):
        r = np.random.default_rng(seed)
        def t(shape, scale=1.0):
            return jnp.asarray(r.standard_normal(shape) * scale,
                               jnp.float32).astype(dtype)
        return (t((E, C, H)), t((E, H, F), H ** -0.5), t((F,)),
                t((E, F, H), F ** -0.5), t((H,)))

    @pytest.mark.parametrize("dtype,exact", [
        (jnp.float32, False), (jnp.float32, True), (jnp.bfloat16, False)])
    def test_kernel_matches_einsum_fwd_and_bwd(self, dtype, exact):
        from deepspeed_tpu.ops.grouped_gemm import grouped_ffn
        rtol, atol = ((0.05, 0.05) if dtype == jnp.bfloat16
                      else (1e-5, 1e-6))
        x, w1, b1, w2, b2 = self._mats(4, 48, 96, 160, dtype)
        b1e, b2e = b1[None, :].repeat(4, 0), b2[None, :].repeat(4, 0)
        y_k = grouped_ffn(x, w1, b1e, w2, b2e, exact)
        y_r = self._ffn_ref(x, w1, b1e, w2, b2e, exact)
        assert y_k.dtype == dtype
        np.testing.assert_allclose(np.asarray(y_k, np.float32),
                                   np.asarray(y_r, np.float32),
                                   rtol=rtol, atol=atol)

        def loss_k(*a):
            return jnp.sum(grouped_ffn(*a, exact).astype(jnp.float32) ** 2)

        def loss_r(*a):
            return jnp.sum(self._ffn_ref(*a, exact)
                           .astype(jnp.float32) ** 2)

        gk = jax.grad(loss_k, argnums=(0, 1, 2, 3, 4))(x, w1, b1e, w2, b2e)
        gr = jax.grad(loss_r, argnums=(0, 1, 2, 3, 4))(x, w1, b1e, w2, b2e)
        # Gradients compound one more matmul; scale atol to grad magnitude.
        for a, b in zip(gk, gr):
            bound = atol * max(1.0, float(jnp.max(jnp.abs(
                b.astype(jnp.float32)))))
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       rtol=rtol, atol=bound)

    def test_single_expert_kernel_matches_dense_ffn(self):
        """num_experts=1 with the kernel FORCED on: the grouped FFN is the
        dense FFN to ulp class (bit-parity is the default path's property
        — 'auto' keeps the einsum on CPU, covered by TestDenseParity)."""
        from deepspeed_tpu.ops.grouped_gemm import grouped_ffn
        x, w1, b1, w2, b2 = self._mats(1, 64, 96, 160, jnp.float32, seed=3)
        y_k = grouped_ffn(x, w1, b1[None], w2, b2[None], False)
        h = jax.nn.gelu(x[0] @ w1[0] + b1, approximate=True)
        y_d = h @ w2[0] + b2
        np.testing.assert_allclose(np.asarray(y_k[0]), np.asarray(y_d),
                                   rtol=1e-5, atol=1e-6)

    def test_dispatch_is_cfg_static(self, monkeypatch):
        from deepspeed_tpu.ops.grouped_gemm import grouped_gemm_enabled
        monkeypatch.delenv("DS_GROUPED_GEMM", raising=False)
        assert grouped_gemm_enabled(True) is True
        assert grouped_gemm_enabled(False) is False
        # "auto" follows the backend (TPU on / CPU off) ...
        assert grouped_gemm_enabled("auto") == \
            (jax.default_backend() == "tpu")
        # ... and the env override wins over "auto" only.
        monkeypatch.setenv("DS_GROUPED_GEMM", "1")
        assert grouped_gemm_enabled("auto") is True
        assert grouped_gemm_enabled(False) is False
        monkeypatch.setenv("DS_GROUPED_GEMM", "0")
        assert grouped_gemm_enabled("auto") is False
        assert grouped_gemm_enabled(True) is True

    def test_engine_step_grouped_on_vs_off(self):
        """ep=4 x dp=2 engine: one train step with the kernel forced on
        matches the einsum path at fp32 tolerance (shard-local under the
        expert shard_map — no new collectives, same routing)."""
        losses = {}
        for knob in (False, True):
            engine, _, _ = build_engine(moe8(ep=4, grouped_gemm=knob),
                                        stage=2)
            b = np.random.default_rng(7).integers(
                0, VOCAB, size=(32, S + 1)).astype(np.int32)
            losses[knob] = float(jax.device_get(engine.train_batch(b)))
        assert np.isfinite(list(losses.values())).all()
        np.testing.assert_allclose(losses[True], losses[False],
                                   rtol=1e-5, atol=1e-6)

    def test_checkpoint_roundtrip_across_knob(self, tmp_path):
        """Resume-compatibility: the knob changes the schedule, not the
        state tree — a checkpoint written with the einsum path loads and
        trains under the kernel (the PR-8 fused_kernels precedent)."""
        engine, _, _ = build_engine(moe8(ep=4, grouped_gemm=False),
                                    stage=2)
        rng = np.random.default_rng(0)
        batch = rng.integers(0, VOCAB, size=(32, S + 1)).astype(np.int32)
        engine.train_batch(batch)
        engine.save_checkpoint(str(tmp_path), tag="knob")
        want = jax.device_get(engine.state.params)

        engine2, *_ = build_engine(moe8(ep=4, grouped_gemm=True),
                                   stage=2, seed=1)
        engine2.load_checkpoint(str(tmp_path), tag="knob")
        jax.tree_util.tree_map(np.testing.assert_array_equal, want,
                               jax.device_get(engine2.state.params))
        assert np.isfinite(float(jax.device_get(
            engine2.train_batch(batch))))

    def test_moe_config_grouped_gemm_validation(self):
        from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                                  DeepSpeedConfigError)
        base = moe_ds_config(moe8(ep=4))
        base["moe"]["grouped_gemm"] = "sometimes"
        with pytest.raises(DeepSpeedConfigError, match="grouped_gemm"):
            DeepSpeedConfig(base)
        for ok in (True, False, "auto"):
            base["moe"]["grouped_gemm"] = ok
            assert DeepSpeedConfig(base).moe_config.grouped_gemm == ok


# --------------------------------------------------------------------- #
# Convergence: the 8-expert top-2 model LEARNS the copy task
# --------------------------------------------------------------------- #
@pytest.mark.slow
def test_moe_learns_copy_task():
    engine, cfg, _ = build_engine(moe8(ep=4), stage=2)
    batches = copy_batches(220, 32, seed=0)
    losses = [float(engine.train_batch(jnp.asarray(b))) for b in batches]
    assert np.isfinite(losses).all()
    # Decisive fall from the ~ln(62) = 4.1 floor.
    assert losses[-1] < 2.6, f"final LM loss {losses[-1]} did not converge"
    # The copy half specifically must be LEARNED (random = 3.9+).
    params = jax.tree_util.tree_map(jnp.asarray,
                                    jax.device_get(engine.state.params))
    b = batches[0]
    tokens, targets = b[:, :-1], b[:, 1:]
    logits = gpt2_apply(params, jnp.asarray(tokens), cfg)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, jnp.asarray(targets)[..., None],
                               axis=-1)[..., 0]
    copy_nll = float(jnp.mean(nll[:, HALF + 1:]))
    assert copy_nll < 0.9, f"copy-half NLL {copy_nll}: not learned"
