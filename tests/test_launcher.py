"""Launcher tests — parity with reference tests/unit/test_run.py (hostfile
and include/exclude parsing; no accelerators needed) plus what the reference
never had: a real single-host multi-process launch smoke test with
kill-all-on-failure supervision.
"""
import os
import subprocess
import sys
import textwrap

import pytest

from deepspeed_tpu.launcher.runner import (decode_world_info,
                                           encode_world_info, fetch_hostfile,
                                           parse_inclusion_exclusion,
                                           parse_resource_filter)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def hostfile(tmp_path):
    def write(content):
        p = tmp_path / "hostfile"
        p.write_text(textwrap.dedent(content))
        return str(p)
    return write


class TestHostfile:
    def test_parse(self, hostfile):
        p = hostfile("""\
            worker-0 slots=4
            worker-1 slots=4

            # comment
            worker-2 slots=8
        """)
        pool = fetch_hostfile(p)
        assert pool == {"worker-0": 4, "worker-1": 4, "worker-2": 8}
        assert list(pool.keys()) == ["worker-0", "worker-1", "worker-2"]

    def test_duplicate_host_raises(self, hostfile):
        p = hostfile("worker-0 slots=4\nworker-0 slots=2\n")
        with pytest.raises(ValueError):
            fetch_hostfile(p)

    def test_bad_format_raises(self, hostfile):
        with pytest.raises(ValueError):
            fetch_hostfile(hostfile("worker-0 slots=four\n"))
        with pytest.raises(ValueError):
            fetch_hostfile(hostfile("worker-0\n"))

    def test_missing_returns_none(self):
        assert fetch_hostfile("/nonexistent/hostfile") is None


class TestResourceFilter:
    POOL = {"worker-0": 4, "worker-1": 4}

    def test_include_whole_and_slots(self):
        active = parse_inclusion_exclusion(self.POOL,
                                           "worker-0@worker-1:0,2", "")
        assert active == {"worker-0": [0, 1, 2, 3], "worker-1": [0, 2]}

    def test_exclude_slot(self):
        active = parse_inclusion_exclusion(self.POOL, "", "worker-1:0")
        assert active == {"worker-0": [0, 1, 2, 3], "worker-1": [1, 2, 3]}

    def test_exclude_whole_host(self):
        active = parse_inclusion_exclusion(self.POOL, "", "worker-1")
        assert active == {"worker-0": [0, 1, 2, 3]}

    def test_mutually_exclusive(self):
        with pytest.raises(ValueError):
            parse_inclusion_exclusion(self.POOL, "worker-0", "worker-1")

    def test_unknown_host_raises(self):
        with pytest.raises(ValueError):
            parse_inclusion_exclusion(self.POOL, "worker-9", "")

    def test_unknown_slot_raises(self):
        with pytest.raises(ValueError):
            parse_inclusion_exclusion(self.POOL, "worker-0:7", "")

    def test_ordering_preserved(self):
        active = parse_resource_filter(
            {"a": [0, 1], "b": [0, 1], "c": [0, 1]}, include_str="c@a")
        assert list(active.keys()) == ["a", "c"]

    def test_world_info_roundtrip(self):
        world = {"worker-0": [0, 1], "worker-1": [0]}
        assert decode_world_info(encode_world_info(world)) == world


class TestLaunchSmoke:
    """Single-host multi-process launches through the real runner CLI."""

    def _run_launch(self, tmp_path, script_body, procs=2, timeout=60):
        script = tmp_path / "user_script.py"
        script.write_text(textwrap.dedent(script_body))
        hostfile = tmp_path / "hostfile"
        hostfile.write_text("localhost slots=2\n")
        env = os.environ.copy()
        env["PYTHONPATH"] = REPO + ":" + env.get("PYTHONPATH", "")
        env["DS_OUT_DIR"] = str(tmp_path)
        cmd = [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
               "--hostfile", str(hostfile),
               "--procs_per_node", str(procs),
               "--coordinator_addr", "127.0.0.1",
               str(script)]
        return subprocess.run(cmd, env=env, cwd=str(tmp_path),
                              capture_output=True, text=True, timeout=timeout)

    @pytest.mark.slow
    def test_two_process_launch_env_contract(self, tmp_path):
        """Both children run with the DS_* env contract populated."""
        res = self._run_launch(tmp_path, """\
            import os, sys
            out = os.environ["DS_OUT_DIR"]
            pid = os.environ["DS_PROCESS_ID"]
            with open(f"{out}/proc_{pid}.txt", "w") as f:
                f.write(":".join([
                    os.environ["DS_COORDINATOR_ADDRESS"],
                    os.environ["DS_NUM_PROCESSES"],
                    os.environ["DS_LOCAL_RANK"],
                    os.environ["DS_NODE_RANK"],
                    os.environ["TPU_VISIBLE_CHIPS"],
                ]))
        """)
        assert res.returncode == 0, res.stderr
        got = {}
        for pid in (0, 1):
            f = tmp_path / f"proc_{pid}.txt"
            assert f.exists(), (res.stdout, res.stderr)
            got[pid] = f.read_text().split(":")
        # coordinator addr:port shared; DS_NUM_PROCESSES=2; distinct ranks
        assert got[0][0] == got[1][0] == "127.0.0.1"
        assert got[0][2] == got[1][2] == "2"
        assert {got[0][3], got[1][3]} == {"0", "1"}
        assert got[0][5] == "0,1"  # slot visibility from the hostfile

    @pytest.mark.slow
    def test_failed_child_kills_siblings(self, tmp_path):
        """One child exiting nonzero must take the node down (reference
        launch.py:151-167 sigkill_handler semantics)."""
        res = self._run_launch(tmp_path, """\
            import os, sys, time
            if os.environ["DS_PROCESS_ID"] == "1":
                sys.exit(3)
            time.sleep(300)   # would hang forever if not killed
        """, timeout=120)
        assert res.returncode != 0


class TestGcloudRunner:
    """Managed Cloud-TPU pod dispatch (the reference's MPI-runner slot,
    multinode_runner.py:78,118, re-done TPU-native)."""

    def _make_args(self, **kw):
        import argparse
        ns = argparse.Namespace(
            user_args=["--flag"], user_script="train.py",
            coordinator_port=29500, procs_per_node=4,
            launcher_args="", tpu_name="my-pod", tpu_zone="us-central2-b")
        for k, v in kw.items():
            setattr(ns, k, v)
        return ns

    def test_command_construction(self):
        from deepspeed_tpu.launcher.multinode_runner import GcloudTPURunner
        r = GcloudTPURunner(self._make_args(), "V0RMRA==")
        r.add_export("JAX_PLATFORMS", "tpu")
        cmd = r.get_cmd({}, {"w0": [0], "w1": [0]}, "10.0.0.2")
        assert cmd[:5] == ["gcloud", "compute", "tpus", "tpu-vm", "ssh"]
        assert "my-pod" in cmd
        assert "--zone=us-central2-b" in cmd
        assert "--worker=0,1" in cmd
        remote = [c for c in cmd if c.startswith("--command=")][0]
        assert "export JAX_PLATFORMS=tpu" in remote
        assert "--node_rank=-1" in remote
        assert "--world_info=V0RMRA==" in remote
        assert "--coordinator_addr=10.0.0.2" in remote
        assert "train.py" in remote and "--flag" in remote

    def test_requires_tpu_name(self):
        from deepspeed_tpu.launcher.multinode_runner import GcloudTPURunner
        r = GcloudTPURunner(self._make_args(tpu_name=None), "x")
        with pytest.raises(ValueError, match="tpu_name"):
            r.get_cmd({}, {"w0": [0]}, "10.0.0.2")

    def test_worker_identity_vars_never_forwarded(self):
        """Forwarding the controller's TPU_WORKER_ID would rank every pod
        worker 0 (the controller is often pod worker 0 itself)."""
        from deepspeed_tpu.launcher.multinode_runner import GcloudTPURunner
        r = GcloudTPURunner(self._make_args(), "x")
        r.add_export("TPU_WORKER_ID", "0")
        r.add_export("TPU_WORKER_HOSTNAMES", "a,b")
        r.add_export("TPU_NAME", "keepme")
        cmd = r.get_cmd({}, {"w0": [0]}, "10.0.0.2")
        remote = [c for c in cmd if c.startswith("--command=")][0]
        assert "TPU_WORKER_ID" not in remote
        assert "TPU_WORKER_HOSTNAMES" not in remote
        assert "TPU_NAME=keepme" in remote

    def test_filtered_subset_keeps_pod_indices(self):
        """--include'd subset dispatches --worker with the TRUE pod
        indices parsed from the hostnames, not positional ones."""
        from deepspeed_tpu.launcher.multinode_runner import GcloudTPURunner
        r = GcloudTPURunner(self._make_args(), "x")
        cmd = r.get_cmd({}, {"worker-1": [0], "worker-3": [0]}, "10.0.0.2")
        assert "--worker=1,3" in cmd

    def test_launcher_args_passthrough(self):
        from deepspeed_tpu.launcher.multinode_runner import GcloudTPURunner
        r = GcloudTPURunner(self._make_args(
            launcher_args="--project=my-proj"), "x")
        cmd = r.get_cmd({}, {"w0": [0]}, "10.0.0.2")
        assert "--project=my-proj" in cmd

    def test_tpu_worker_id_rank_fallback(self, monkeypatch):
        """Pod workers resolve node rank from TPU_WORKER_ID when their
        hostname is not in the world info."""
        from deepspeed_tpu.launcher.launch import _infer_node_rank
        world = {"w0": [0], "w1": [0], "w2": [0]}
        monkeypatch.setenv("TPU_WORKER_ID", "2")
        assert _infer_node_rank(world) == 2
        monkeypatch.setenv("TPU_WORKER_ID", "7")   # out of range
        with pytest.raises(ValueError):
            _infer_node_rank(world)
        monkeypatch.delenv("TPU_WORKER_ID")
        with pytest.raises(ValueError):
            _infer_node_rank(world)

    def test_rank_matches_trailing_pod_index_for_subsets(self, monkeypatch):
        """Filtered launches: TPU_WORKER_ID=3 on a {worker-1, worker-3}
        world is RANK 1, not positional 3."""
        from deepspeed_tpu.launcher.launch import _infer_node_rank
        world = {"worker-1": [0], "worker-3": [0]}
        monkeypatch.setenv("TPU_WORKER_ID", "3")
        assert _infer_node_rank(world) == 1
        monkeypatch.setenv("TPU_WORKER_ID", "2")   # not dispatched
        with pytest.raises(ValueError, match="not part of the filtered"):
            _infer_node_rank(world)

    def test_pod_coordinator_sentinel(self, monkeypatch):
        from deepspeed_tpu.launcher.launch import _resolve_pod_coordinator
        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "t1v-0,t1v-1,t1v-2")
        assert _resolve_pod_coordinator({"worker-0": [0],
                                         "worker-1": [0]}) == "t1v-0"
        # Filtered launch excluding worker 0: rank 0 lives on pod worker 1,
        # so the sentinel must resolve to ITS address, not peers[0].
        assert _resolve_pod_coordinator({"worker-1": [0],
                                         "worker-2": [0]}) == "t1v-1"
        monkeypatch.delenv("TPU_WORKER_HOSTNAMES")
        with pytest.raises(ValueError, match="coordinator_addr"):
            _resolve_pod_coordinator({"worker-0": [0]})

    def test_no_positional_rank_for_digit_tailed_subset(self, monkeypatch):
        """A filtered-out worker (wid not among the tails) must raise, not
        silently take a duplicate positional rank."""
        from deepspeed_tpu.launcher.launch import _infer_node_rank
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        with pytest.raises(ValueError, match="not part of the filtered"):
            _infer_node_rank({"worker-1": [0], "worker-3": [0]})
