"""Core engine tests — parity with reference tests/unit/test_fp16.py (the
optimizer × precision × zero-stage matrix on SimpleModel) and
test_dynamic_loss_scale.py (NaN injection → scale halving, overflow skip)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from simple_model import (simple_model_params, simple_loss_fn, random_dataset,
                          random_batch, base_config)


def make_engine(config, seed=0, **kw):
    params = simple_model_params(jax.random.PRNGKey(seed))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=simple_loss_fn, model_params=params, config=config, **kw)
    return engine


class TestTrainBatch:
    def test_loss_decreases(self):
        engine = make_engine(base_config())
        batch = random_batch(n=16)
        losses = [float(engine.train_batch(batch=batch)) for _ in range(20)]
        assert losses[-1] < losses[0] * 0.8, losses

    def test_counters(self):
        engine = make_engine(base_config(gradient_accumulation_steps=2,
                                         train_batch_size=32))
        batch = random_batch(n=32)
        engine.train_batch(batch=batch)
        assert engine.global_steps == 1
        assert engine.micro_steps == 2
        assert engine.global_samples == 32
        assert int(jax.device_get(engine.state.step)) == 1

    def test_grad_accum_equivalence(self):
        """gas=2 over batch B must equal gas=1 over the same batch B."""
        b = random_batch(n=32, seed=3)
        e1 = make_engine(base_config(train_batch_size=32,
                                     gradient_accumulation_steps=1), seed=7)
        e2 = make_engine(base_config(train_batch_size=32,
                                     gradient_accumulation_steps=2), seed=7)
        e1.train_batch(batch=b)
        e2.train_batch(batch=b)
        p1 = jax.device_get(e1.state.params)
        p2 = jax.device_get(e2.state.params)
        for k in p1:
            np.testing.assert_allclose(p1[k], p2[k], rtol=2e-5, atol=2e-6)

    def test_dataloader_driven(self):
        ds = random_dataset(n=64)
        engine = make_engine(base_config(train_batch_size=16), training_data=ds)
        l0 = float(engine.train_batch())
        for _ in range(10):
            loss = engine.train_batch()
        assert float(loss) < l0

    def test_scheduler_advances_lr(self):
        cfg = base_config()
        cfg["scheduler"] = {"type": "WarmupLR",
                            "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 0.01,
                                       "warmup_num_steps": 100}}
        engine = make_engine(cfg)
        batch = random_batch()
        engine.train_batch(batch=batch)
        lr_early = engine.get_lr()[0]
        for _ in range(20):
            engine.train_batch(batch=batch)
        assert engine.get_lr()[0] > lr_early


class TestPrecision:
    def test_bf16(self):
        engine = make_engine(base_config(bf16={"enabled": True}))
        batch = random_batch()
        losses = [float(engine.train_batch(batch=batch)) for _ in range(15)]
        assert losses[-1] < losses[0]
        # master weights stay fp32
        assert jax.device_get(engine.state.params)["w1"].dtype == np.float32

    @pytest.mark.slow
    def test_fp16_trains(self):
        engine = make_engine(base_config(
            fp16={"enabled": True, "initial_scale_power": 8}))
        batch = random_batch()
        losses = [float(engine.train_batch(batch=batch)) for _ in range(15)]
        assert losses[-1] < losses[0]

    @pytest.mark.slow
    def test_fp16_overflow_skips_step(self):
        """NaN injection parity with test_dynamic_loss_scale.py."""
        engine = make_engine(base_config(
            fp16={"enabled": True, "initial_scale_power": 8, "hysteresis": 1}))
        x, y = random_batch()
        before = jax.device_get(engine.state.params)
        scale_before = engine.loss_scale()
        bad = (np.full_like(x, np.nan), y)
        engine.train_batch(batch=bad)
        after = jax.device_get(engine.state.params)
        for k in before:
            np.testing.assert_array_equal(before[k], after[k])
        assert engine.loss_scale() == scale_before / 2
        assert int(jax.device_get(engine.state.skipped_steps)) == 1
        assert int(jax.device_get(engine.state.step)) == 0

    @pytest.mark.slow
    def test_fp16_hysteresis(self):
        engine = make_engine(base_config(
            fp16={"enabled": True, "initial_scale_power": 8, "hysteresis": 2}))
        x, y = random_batch()
        bad = (np.full_like(x, np.nan), y)
        s0 = engine.loss_scale()
        engine.train_batch(batch=bad)   # consumes hysteresis credit
        assert engine.loss_scale() == s0
        engine.train_batch(batch=bad)   # now halves
        assert engine.loss_scale() == s0 / 2

    def test_fp16_scale_growth(self):
        engine = make_engine(base_config(
            fp16={"enabled": True, "initial_scale_power": 4,
                  "loss_scale_window": 4}))
        batch = random_batch()
        s0 = engine.loss_scale()
        for _ in range(4):
            engine.train_batch(batch=batch)
        assert engine.loss_scale() == s0 * 2

    def test_static_loss_scale(self):
        engine = make_engine(base_config(
            fp16={"enabled": True, "loss_scale": 128}))
        batch = random_batch()
        engine.train_batch(batch=batch)
        assert engine.loss_scale() == 128


class TestZero:
    @pytest.mark.slow
    @pytest.mark.parametrize("stage", [0, 1, 2])
    def test_zero_matches_stage0(self, stage):
        """Loss-curve parity across ZeRO stages (reference test style)."""
        batch = random_batch(n=16, seed=5)
        ref = make_engine(base_config(), seed=11)
        z = make_engine(base_config(zero_optimization={"stage": stage}), seed=11)
        for _ in range(5):
            lr_ = ref.train_batch(batch=batch)
            lz = z.train_batch(batch=batch)
        np.testing.assert_allclose(float(lr_), float(lz), rtol=1e-4)
        pr = jax.device_get(ref.state.params)
        pz = jax.device_get(z.state.params)
        for k in pr:
            np.testing.assert_allclose(pr[k], pz[k], rtol=1e-4, atol=1e-6)

    def test_zero_opt_state_sharded(self):
        engine = make_engine(base_config(zero_optimization={"stage": 1}))
        # at least one moment leaf sharded over the data axis
        shardings = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda x: x.sharding, engine.state.opt_state))
        assert any("data" in str(s.spec) for s in shardings
                   if hasattr(s, "spec")), shardings

    def test_stage3_accepted_params_sharded(self):
        # The reference raises for stage > 2 (engine.py:707-708); since
        # ISSUE 11 stage 3 shards the param tree itself (full coverage
        # in tests/test_zero3.py). Stage 4 stays rejected.
        engine = make_engine(base_config(zero_optimization={"stage": 3}))
        shardings = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda x: x.sharding,
                                   engine.state.params))
        assert any("data" in str(s.spec) for s in shardings
                   if hasattr(s, "spec")), shardings
        with pytest.raises(Exception):
            make_engine(base_config(zero_optimization={"stage": 4}))


class TestOptimizers:
    @pytest.mark.parametrize("name", ["Adam", "AdamW", "Lamb", "SGD"])
    def test_optimizer_matrix(self, name):
        cfg = base_config()
        cfg["optimizer"] = {"type": name, "params": {"lr": 1e-2}}
        engine = make_engine(cfg)
        batch = random_batch()
        losses = [float(engine.train_batch(batch=batch)) for _ in range(10)]
        assert losses[-1] < losses[0]

    def test_client_optimizer(self):
        import optax
        params = simple_model_params(jax.random.PRNGKey(0))
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=simple_loss_fn, model_params=params,
            optimizer=optax.sgd(1e-2), config=base_config())
        batch = random_batch()
        losses = [float(engine.train_batch(batch=batch)) for _ in range(10)]
        assert losses[-1] < losses[0]

    def test_gradient_clipping(self):
        # SGD: update magnitude is proportional to the clipped grad norm
        # (Adam would renormalize, hiding the clip).
        cfg = base_config(gradient_clipping=1e-6)
        cfg["optimizer"] = {"type": "SGD", "params": {"lr": 1.0}}
        engine = make_engine(cfg)
        batch = random_batch()
        before = jax.device_get(engine.state.params)["w1"]
        engine.train_batch(batch=batch)
        after = jax.device_get(engine.state.params)["w1"]
        assert np.abs(after - before).max() < 1e-5


class TestCompatibilityTrio:
    def test_forward_backward_step(self):
        engine = make_engine(base_config(train_batch_size=16,
                                         gradient_accumulation_steps=2))
        x, y = random_batch(n=16)
        halves = [(x[:8], y[:8]), (x[8:], y[8:])]
        l0 = None
        for _ in range(10):
            for mb in halves:
                loss = engine.forward(mb)
                engine.backward(loss)
                engine.step()
            if l0 is None:
                l0 = float(loss)
        assert engine.global_steps == 10
        assert float(loss) < l0

    def test_boundary_gating(self):
        engine = make_engine(base_config(train_batch_size=16,
                                         gradient_accumulation_steps=2))
        mb = random_batch(n=8)
        engine.forward(mb)
        engine.backward(None)
        engine.step()  # not at boundary: no-op
        assert engine.global_steps == 0
        engine.forward(mb)
        engine.backward(None)
        engine.step()
        assert engine.global_steps == 1


class TestCheckpoint:
    def test_round_trip(self, tmp_path):
        engine = make_engine(base_config())
        batch = random_batch()
        for _ in range(3):
            engine.train_batch(batch=batch)
        engine.save_checkpoint(str(tmp_path), client_state={"foo": 7})
        p_saved = jax.device_get(engine.state.params)

        # diverge, then restore
        for _ in range(3):
            engine.train_batch(batch=batch)
        path, client = engine.load_checkpoint(str(tmp_path))
        assert path is not None
        assert client["foo"] == 7
        assert engine.global_steps == 3
        p_loaded = jax.device_get(engine.state.params)
        for k in p_saved:
            np.testing.assert_array_equal(p_saved[k], p_loaded[k])

    def test_latest_pointer(self, tmp_path):
        engine = make_engine(base_config())
        batch = random_batch()
        engine.train_batch(batch=batch)
        engine.save_checkpoint(str(tmp_path), tag="tagA")
        engine.train_batch(batch=batch)
        engine.save_checkpoint(str(tmp_path), tag="tagB")
        assert (tmp_path / "latest").read_text() == "tagB"

    def test_fresh_engine_resume(self, tmp_path):
        cfg = base_config()
        e1 = make_engine(cfg, seed=0)
        batch = random_batch()
        for _ in range(5):
            e1.train_batch(batch=batch)
        e1.save_checkpoint(str(tmp_path))
        # brand-new engine, different init seed; loads into same state
        e2 = make_engine(cfg, seed=99)
        e2.load_checkpoint(str(tmp_path))
        l1 = float(e1.train_batch(batch=batch))
        l2 = float(e2.train_batch(batch=batch))
        assert l1 == pytest.approx(l2, rel=1e-5)

    def test_missing_checkpoint(self, tmp_path):
        engine = make_engine(base_config())
        path, client = engine.load_checkpoint(str(tmp_path))
        assert path is None


class TestEval:
    def test_eval_batch(self):
        engine = make_engine(base_config())
        batch = random_batch()
        loss = engine.eval_batch(batch)
        assert np.isfinite(float(loss))
        # eval does not advance counters
        assert engine.global_steps == 0


class TestCastParamsCache:
    """The persistent compute-dtype param cache (EngineState.cast_params)
    must track params through EVERY mutation path — the fused train step,
    the manual backward()+step() pair, and checkpoint load — or a later
    train_batch silently trains against stale weights."""

    def _assert_cache_fresh(self, engine):
        import jax.numpy as jnp
        cast = engine.state.cast_params
        assert cast is not None
        want = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.bfloat16), engine.state.params)
        for a, b in zip(jax.tree_util.tree_leaves(cast),
                        jax.tree_util.tree_leaves(want)):
            np.testing.assert_array_equal(np.asarray(a, np.float32),
                                          np.asarray(b, np.float32))

    def test_train_batch_refreshes_cache(self):
        engine = make_engine(dict(base_config(), bf16={"enabled": True}))
        for _ in range(3):
            engine.train_batch(batch=random_batch(n=16))
        self._assert_cache_fresh(engine)

    def test_manual_backward_step_refreshes_cache(self):
        engine = make_engine(dict(base_config(), bf16={"enabled": True}))
        for _ in range(3):
            engine.forward(random_batch(n=16))
            engine.backward()
            engine.step()
        self._assert_cache_fresh(engine)

    def test_checkpoint_load_refreshes_cache(self, tmp_path):
        engine = make_engine(dict(base_config(), bf16={"enabled": True}))
        engine.train_batch(batch=random_batch(n=16))
        engine.save_checkpoint(str(tmp_path), tag="t1")
        engine2 = make_engine(dict(base_config(), bf16={"enabled": True}),
                              seed=7)
        engine2.load_checkpoint(str(tmp_path), tag="t1")
        self._assert_cache_fresh(engine2)
