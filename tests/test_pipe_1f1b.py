"""1F1B interleaved SPMD pipeline: parity with sequential/GPipe and the
O(P)-not-O(M) activation-memory contract (reference TrainSchedule,
runtime/pipe/schedule.py:182-290)."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPT2_CONFIGS
from deepspeed_tpu.models.gpt2 import gpt2_loss_fn
from deepspeed_tpu.models.gpt2_pipe import gpt2_pipe_spec
from deepspeed_tpu.parallel.topology import build_mesh

pytestmark = pytest.mark.slow  # whole-module slow tier (see conftest)


@pytest.fixture(scope="module")
def cfg():
    return dataclasses.replace(GPT2_CONFIGS["gpt2-tiny"], num_layers=4,
                               hidden_dropout=0.0, attn_dropout=0.0)


def _flat_params(spec):
    return {**spec.params["shared"], "blocks": spec.params["blocks"]}


class Test1F1BParity:
    def test_loss_and_grads_match_sequential(self, cfg):
        spec = gpt2_pipe_spec(cfg, rng=jax.random.PRNGKey(0))
        mesh = build_mesh(pp=4, dp=2)
        M = 4
        gfn = spec.grads_fn(num_stages=4, num_micro=M, mesh=mesh)
        batch = jax.random.randint(jax.random.PRNGKey(1), (M * 2, 17), 0,
                                   cfg.vocab_size)
        with jax.set_mesh(mesh):
            loss, grads = jax.jit(gfn)(spec.params, batch,
                                       jax.random.PRNGKey(2))
        want_loss = float(gpt2_loss_fn(cfg)(_flat_params(spec), batch,
                                            jax.random.PRNGKey(2)))
        np.testing.assert_allclose(float(loss), want_loss, rtol=2e-2)

        g_seq = jax.grad(gpt2_loss_fn(cfg))(_flat_params(spec), batch,
                                            jax.random.PRNGKey(2))
        for k in g_seq["blocks"]:
            np.testing.assert_allclose(
                np.asarray(grads["blocks"][k], np.float32),
                np.asarray(g_seq["blocks"][k], np.float32),
                rtol=5e-2, atol=5e-3, err_msg=f"blocks/{k}")
        # Tied wte: embed (stage 0) + unembed (last stage) contributions
        # both arrive through the end-of-scan psum (ReduceTiedGrads).
        np.testing.assert_allclose(
            np.asarray(grads["shared"]["wte"], np.float32),
            np.asarray(g_seq["wte"], np.float32), rtol=5e-2, atol=5e-3)
        np.testing.assert_allclose(
            np.asarray(grads["shared"]["wpe"], np.float32),
            np.asarray(g_seq["wpe"], np.float32), rtol=5e-2, atol=5e-3)

    def test_matches_gpipe_grads(self, cfg):
        """Same pipeline, two schedules, identical grads (dropout off)."""
        spec = gpt2_pipe_spec(cfg, rng=jax.random.PRNGKey(3))
        mesh = build_mesh(pp=2, dp=1, devices=jax.devices()[:2])
        M = 3
        batch = jax.random.randint(jax.random.PRNGKey(4), (M * 2, 17), 0,
                                   cfg.vocab_size)
        loss_fn = spec.loss_fn(num_stages=2, num_micro=M, mesh=mesh)
        gfn = spec.grads_fn(num_stages=2, num_micro=M, mesh=mesh)
        with jax.set_mesh(mesh):
            l_g, g_g = jax.jit(jax.value_and_grad(loss_fn))(
                spec.params, batch, jax.random.PRNGKey(5))
            l_i, g_i = jax.jit(gfn)(spec.params, batch, jax.random.PRNGKey(5))
        np.testing.assert_allclose(float(l_i), float(l_g), rtol=1e-2)
        for k in g_g["blocks"]:
            np.testing.assert_allclose(
                np.asarray(g_i["blocks"][k], np.float32),
                np.asarray(g_g["blocks"][k], np.float32),
                rtol=5e-2, atol=5e-3, err_msg=k)


class Test1F1BMemory:
    def test_boundary_buffers_O_P_not_O_M(self, cfg):
        """The compiled 1F1B program must carry NO micro-batch-count-sized
        activation bank. GPipe banks [M, mb, S, H]; 1F1B's largest
        activation carry is the (2P+1)-slot ring — independent of M."""
        spec = gpt2_pipe_spec(cfg, rng=jax.random.PRNGKey(0))
        P_, M, mb, S, H = 4, 16, 2, 17, cfg.hidden_size
        mesh = build_mesh(pp=P_, dp=1, devices=jax.devices()[:P_])
        batch = jax.random.randint(jax.random.PRNGKey(1), (M * mb, S), 0,
                                   cfg.vocab_size)
        rng = jax.random.PRNGKey(2)

        def hlo(fn):
            with jax.set_mesh(mesh):
                return jax.jit(fn).lower(spec.params, batch, rng) \
                    .compile().as_text()

        bank = f"{M},{mb},{S - 1},{H}"       # [M, mb, S, H] activation bank
        ring = f"{2 * P_ + 1},{mb},{S - 1},{H}"

        txt_1f1b = hlo(spec.grads_fn(num_stages=P_, num_micro=M, mesh=mesh))
        assert bank not in txt_1f1b, \
            f"1F1B program still carries an O(M) activation bank [{bank}]"
        assert ring in txt_1f1b, \
            f"expected the O(P) saved-input ring [{ring}] in the program"

        txt_gpipe = hlo(jax.value_and_grad(
            spec.loss_fn(num_stages=P_, num_micro=M, mesh=mesh)))
        assert bank in txt_gpipe, \
            "sanity: the GPipe program should bank [M, mb, S, H]"


class Test1F1BScheduleOracle:
    """TrainSchedule (runtime/pipe/schedule.py) is the reference's
    instruction-list specification of 1F1B; the production scan
    (spmd_1f1b) runs a closed-form clock. These tests generate the
    expected tick table FROM TrainSchedule and assert the scan's schedule
    against it — the schedule module is the oracle, not a test-only
    artifact.

    The mapping: TrainSchedule alternates forward/backward family ticks
    (one instruction family per stage per tick), the scan fuses both
    families into one tick (forward sub-tick + backward sub-tick), so a
    schedule tick ``u`` compresses 2:1 onto a scan tick ``t``:

        forward  of micro m on stage s:  u = 2m + s        t = m + s
                                         =>  u = 2t - s
        backward of micro m on stage s:  u = 2m + 2P-1-s   t = m + 2(P-1)-s
                                         =>  t = (u + 2P - 3 - s) / 2
    """

    @pytest.mark.parametrize("M,P", [(4, 2), (3, 2), (4, 4), (8, 4),
                                     (6, 3), (2, 2)])
    def test_scan_clock_matches_train_schedule(self, M, P):
        from deepspeed_tpu.runtime.pipe.schedule import train_schedule_events
        from deepspeed_tpu.runtime.pipe.spmd_1f1b import tick_table

        events = train_schedule_events(M, P)
        table = tick_table(M, P)
        assert len(table) == M + 2 * (P - 1)       # scan tick count
        # schedule tick count: 2 per micro + fill/drain
        assert 1 + max(u for evs in events.values() for u, _, _ in evs) \
            == 2 * (M + P - 1)

        # Build the EXPECTED tick table from TrainSchedule's instruction
        # stream via the 2:1 compression, then require the scan's table to
        # match it exactly (modulo the head entries, asserted separately).
        expected = [[[] for _ in range(P)] for _ in range(M + 2 * (P - 1))]
        for s in range(P):
            for u, kind, m in events[s]:
                if kind == "F":
                    t = (u + s) // 2
                    assert (u + s) % 2 == 0, (u, s)
                else:
                    t = (u + 2 * P - 3 - s) // 2
                    assert (u + 2 * P - 3 - s) % 2 == 0, (u, s)
                expected[t][s].append((kind, m))
        got = [[[e for e in table[t][s] if e[0] != "H"] for s in range(P)]
               for t in range(len(table))]
        # Within a scan tick the body runs the forward sub-tick first;
        # normalize the oracle to the same intra-tick order (the pair is
        # dataflow-independent: B consumes last tick's ppermuted cotangent).
        expected = [[sorted(cell) for cell in row] for row in expected]
        got = [[sorted(cell) for cell in row] for row in got]
        assert got == expected

    @pytest.mark.parametrize("M,P", [(4, 2), (8, 4), (6, 3)])
    def test_scan_clock_structural_claims(self, M, P):
        """Claims the executor enforces imperatively, restated against the
        closed-form table: each send lands exactly one tick before its
        recv (ppermute latency 1), and the head runs in the SAME tick as
        the last stage's forward of that micro (backward starts the tick
        its forward chain allows)."""
        from deepspeed_tpu.runtime.pipe.spmd_1f1b import tick_table
        table = tick_table(M, P)

        def tick_of(kind, m, s):
            hits = [t for t in range(len(table))
                    if (kind, m) in table[t][s]]
            assert len(hits) == 1, (kind, m, s, hits)
            return hits[0]

        for m in range(M):
            for s in range(P - 1):
                assert tick_of("F", m, s + 1) == tick_of("F", m, s) + 1
            for s in range(P - 1, 0, -1):
                assert tick_of("B", m, s - 1) == tick_of("B", m, s) + 1
            assert tick_of("H", m, P - 1) == tick_of("F", m, P - 1)
            # 1F1B: the last stage's backward shares its forward's tick
            assert tick_of("B", m, P - 1) == tick_of("F", m, P - 1)


def _1f1b_ds_config(**over):
    ds = {"train_batch_size": 32,
          "train_micro_batch_size_per_gpu": 2,
          "gradient_accumulation_steps": 4,
          "bf16": {"enabled": True},
          "pipeline": {"schedule": "1f1b"},
          "mesh": {"pipe_parallel_size": 2, "data_parallel_size": 4},
          "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
          "steps_per_print": 10 ** 9}
    ds.update(over)
    return ds


class Test1F1BEngine:
    def test_engine_schedule_1f1b_trains(self, cfg):
        spec = gpt2_pipe_spec(cfg, rng=jax.random.PRNGKey(0))
        ds = _1f1b_ds_config()
        engine, _, _, _ = deepspeed_tpu.initialize(config=ds, model=spec)
        rng = np.random.default_rng(0)
        losses = []
        for _ in range(8):
            batch = rng.integers(0, cfg.vocab_size, size=(32, 18),
                                 dtype=np.int32)
            losses.append(float(engine.train_batch(jnp.asarray(batch))))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses

    def test_engine_fp16_1f1b_trains_with_loss_scaling(self, cfg):
        """fp16 + 1F1B: the scale rides the head cotangent through the
        manual backward; the engine's unscale/overflow machinery applies."""
        spec = gpt2_pipe_spec(cfg, rng=jax.random.PRNGKey(0))
        ds = _1f1b_ds_config(
            fp16={"enabled": True, "initial_scale_power": 8,
                  "loss_scale_window": 4},
            optimizer={"type": "AdamW", "params": {"lr": 5e-3}})
        del ds["bf16"]
        engine, _, _, _ = deepspeed_tpu.initialize(config=ds, model=spec)
        batch = np.random.default_rng(0).integers(
            0, cfg.vocab_size, size=(32, 18), dtype=np.int32)
        losses = [float(engine.train_batch(jnp.asarray(batch)))
                  for _ in range(8)]
        assert np.isfinite(losses).all(), losses
        assert min(losses[-3:]) < losses[0] - 0.2, losses
        # The reported loss must be UNSCALED (scale starts at 2^8; a
        # scaled report would sit around ln(V)*256).
        assert losses[0] < 20.0, losses

    def test_engine_1f1b_composes_with_zero1(self, cfg):
        """1F1B direct grads + ZeRO-1 (dp-sharded optimizer state): the
        grads come from the manual scan, the optimizer update still runs
        on born-sharded moments."""
        spec = gpt2_pipe_spec(cfg, rng=jax.random.PRNGKey(0))
        ds = _1f1b_ds_config(zero_optimization={"stage": 1},
                             optimizer={"type": "AdamW",
                                        "params": {"lr": 5e-3}})
        engine, _, _, _ = deepspeed_tpu.initialize(config=ds, model=spec)
        # The moments must actually BE dp-sharded (a config regression
        # that drops zero_optimization would still converge identically).
        mu_shardings = [l.sharding.spec for l
                        in jax.tree_util.tree_leaves(engine.state.opt_state)
                        if hasattr(l, "ndim") and l.ndim >= 2]
        assert any("data" in str(s) for s in mu_shardings), mu_shardings
        batch = np.random.default_rng(0).integers(
            0, cfg.vocab_size, size=(32, 18), dtype=np.int32)
        losses = [float(engine.train_batch(jnp.asarray(batch)))
                  for _ in range(8)]
        assert np.isfinite(losses).all()
        assert min(losses[-3:]) < losses[0] - 0.2, losses
