"""In-kernel attention dropout: the flash path must run (no dense fallback)
under real training configs (dropout 0.1), and its forward/backward must
match a dense reference that applies the *identical* regenerated mask.

Reference behavior being matched: the fused kernel keeps dropout inside the
attention computation and replays the same mask in backward
(ops/transformer/transformer.py:330-466, csrc/transformer/
dropout_kernels.cu) — here the mask is regenerated from the seed instead of
saved.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops import flash_attention as fa

pytestmark = pytest.mark.slow  # whole-module slow tier (see conftest)


def _make_qkv(key, B, S, nH, D, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    shape = (B, S, nH, D)
    return tuple(jax.random.normal(k, shape, dtype) * 0.3 for k in ks)


def _keep_mask(seed, BH, S, rate):
    """Elementwise replica of the kernel's _dropout_keep hash over the full
    [BH, S, S] score grid (block decomposition is irrelevant: the hash is a
    pure function of (seed, bh, q_pos, k_pos))."""
    bh = jnp.arange(BH, dtype=jnp.uint32)[:, None, None]
    qpos = jnp.arange(S, dtype=jnp.uint32)[None, :, None]
    kpos = jnp.arange(S, dtype=jnp.uint32)[None, None, :]
    stream = jnp.uint32(np.uint32(seed)) ^ (bh * jnp.uint32(0x85EBCA6B))
    x = qpos * jnp.uint32(0x9E3779B9) + kpos + stream
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    u = (x >> 8).astype(jnp.int32).astype(jnp.float32) * (1.0 / 16777216.0)
    return u >= rate


def _dense_dropped(q, k, v, keep, rate, causal):
    """softmax(qk/sqrt d) -> apply exact keep mask -> @v. q,k,v [B,S,nH,D];
    keep [B*nH, S, S]."""
    B, S, nH, D = q.shape
    qt = jnp.einsum("bsnd,btnd->bnst", q, k).astype(jnp.float32)
    qt = qt / np.sqrt(D)
    if causal:
        cm = jnp.tril(jnp.ones((S, S), jnp.bool_))
        qt = jnp.where(cm[None, None], qt, -1e30)
    w = jax.nn.softmax(qt, axis=-1)
    w = jnp.where(keep.reshape(B, nH, S, S), w / (1.0 - rate), 0.0)
    return jnp.einsum("bnst,btnd->bsnd", w.astype(v.dtype), v)


@pytest.mark.parametrize("causal", [False, True])
def test_dropout_fwd_matches_masked_dense(causal):
    B, S, nH, D = 2, 256, 2, 64
    rate = 0.1
    q, k, v = _make_qkv(jax.random.PRNGKey(0), B, S, nH, D)
    rng = jax.random.PRNGKey(7)

    out = fa.flash_attention(q, k, v, causal=causal, attn_dropout=rate,
                             rng=rng, deterministic=False)

    seed = int(jax.random.bits(rng, (), jnp.uint32))
    keep = _keep_mask(seed, B * nH, S, rate)
    ref = _dense_dropped(q, k, v, keep, rate, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_dropout_grads_match_masked_dense(causal):
    B, S, nH, D = 1, 256, 2, 64
    rate = 0.15
    q, k, v = _make_qkv(jax.random.PRNGKey(1), B, S, nH, D)
    rng = jax.random.PRNGKey(11)
    seed = int(jax.random.bits(rng, (), jnp.uint32))
    keep = _keep_mask(seed, B * nH, S, rate)

    def loss_flash(q, k, v):
        o = fa.flash_attention(q, k, v, causal=causal, attn_dropout=rate,
                               rng=rng, deterministic=False)
        return jnp.sum(o * jnp.cos(jnp.arange(o.size).reshape(o.shape) * 0.01))

    def loss_ref(q, k, v):
        o = _dense_dropped(q, k, v, keep, rate, causal)
        return jnp.sum(o * jnp.cos(jnp.arange(o.size).reshape(o.shape) * 0.01))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=2e-3, atol=2e-3,
                                   err_msg=f"d{name} mismatch")


def test_flash_path_taken_with_dropout(monkeypatch):
    """The default training config (dropout 0.1, 128-aligned seq) must run
    the kernel — the silent dense fallback for dropout is gone."""
    import deepspeed_tpu.models.transformer as mt

    def boom(*a, **kw):
        raise AssertionError("dense fallback used despite dropout>0")

    monkeypatch.setattr(mt, "dense_attention", boom)
    q, k, v = _make_qkv(jax.random.PRNGKey(2), 1, 128, 2, 64)
    out = fa.flash_attention(q, k, v, causal=True, attn_dropout=0.1,
                             rng=jax.random.PRNGKey(3), deterministic=False)
    assert out.shape == q.shape


def test_dropout_deterministic_given_rng():
    q, k, v = _make_qkv(jax.random.PRNGKey(4), 1, 128, 2, 64)
    rng = jax.random.PRNGKey(5)
    o1 = fa.flash_attention(q, k, v, attn_dropout=0.2, rng=rng,
                            deterministic=False)
    o2 = fa.flash_attention(q, k, v, attn_dropout=0.2, rng=rng,
                            deterministic=False)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    o3 = fa.flash_attention(q, k, v, attn_dropout=0.2,
                            rng=jax.random.PRNGKey(6), deterministic=False)
    assert np.abs(np.asarray(o1) - np.asarray(o3)).max() > 1e-6


def test_dropout_fraction_and_scaling():
    """Dropped fraction ~= rate; kept weights scaled by 1/(1-rate):
    E[out] ~= dropout-free out."""
    B, S, nH, D = 2, 256, 4, 64
    rate = 0.3
    q, k, v = _make_qkv(jax.random.PRNGKey(8), B, S, nH, D)
    seeds = [int(jax.random.bits(jax.random.PRNGKey(i), (), jnp.uint32))
             for i in range(4)]
    fracs = [float(jnp.mean(~_keep_mask(s, B * nH, S, rate)))
             for s in seeds]
    assert abs(np.mean(fracs) - rate) < 0.01

    outs = [fa.flash_attention(q, k, v, attn_dropout=rate,
                               rng=jax.random.PRNGKey(i),
                               deterministic=False) for i in range(8)]
    mean_out = np.mean([np.asarray(o) for o in outs], axis=0)
    base = fa.flash_attention(q, k, v, attn_dropout=0.0, deterministic=True)
    # Monte-Carlo over 8 masks: loose tolerance, catches missing 1/(1-p).
    err = np.abs(mean_out - np.asarray(base)).mean()
    scale_err = np.abs(np.asarray(base)).mean()
    assert err < 0.25 * scale_err, (err, scale_err)
