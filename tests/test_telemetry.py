"""Unified telemetry subsystem (monitor/): ring-buffered per-step JSONL
records, Chrome-trace spans, the recompile sentinel, memory watermarks,
and the zero-added-hot-path-syncs design rule (asserted via the
instrumented fence counter, not trusted).

Acceptance gates from the PR issue:
- the recompile sentinel catches an induced retrace (shape-changing batch
  after warmup) and can raise under fail_on_recompile;
- a telemetry-enabled dp=8 run produces a JSONL + Chrome-trace pair that
  tools/telemetry_report.py turns into TELEMETRY.json whose step-time,
  wire-bytes, and memory fields check out against the hlo_audit wire
  model and memory_stats() ground truth;
- telemetry-enabled runs add no per-step device fences.
"""
import importlib.util
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu.utils.timer as timer_mod
from deepspeed_tpu.monitor import (GOODPUT_BUCKETS, JsonlSink,
                                   MemoryWatermark, RecompileError,
                                   RecompileSentinel,
                                   analytic_state_bytes,
                                   device_memory_stats)
from deepspeed_tpu.monitor.recompile import signature_delta
from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                          DeepSpeedConfigError)
from deepspeed_tpu.runtime.engine import DeepSpeedEngine

from simple_model import (simple_model_params, simple_loss_fn, random_batch,
                          base_config)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_report_tool():
    spec = importlib.util.spec_from_file_location(
        "telemetry_report", os.path.join(REPO, "tools",
                                         "telemetry_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def telemetry_config(tmp_path, **knobs):
    tel = {"enabled": True, "output_path": str(tmp_path), "job_name": "run"}
    tel.update(knobs)
    return tel


def make_engine(tmp_path, seed=0, tel_knobs=None, **cfg_overrides):
    cfg = base_config(**cfg_overrides)
    cfg["telemetry"] = telemetry_config(tmp_path, **(tel_knobs or {}))
    params = simple_model_params(jax.random.PRNGKey(seed))
    return DeepSpeedEngine(model=simple_loss_fn, model_params=params,
                           config=cfg)


def read_jsonl(tmp_path, job="run"):
    with open(os.path.join(str(tmp_path), f"{job}.jsonl")) as f:
        return [json.loads(line) for line in f if line.strip()]


# --------------------------------------------------------------------- #
# Config surface
# --------------------------------------------------------------------- #
class TestTelemetryConfig:
    def test_defaults_off(self):
        cfg = DeepSpeedConfig(base_config())
        assert not cfg.telemetry_config.enabled

    def test_knobs_parse(self):
        cfg = DeepSpeedConfig(base_config(telemetry={
            "enabled": True, "output_path": "/tmp/x", "job_name": "j",
            "report_steps": 7, "buffer_size": 32,
            "trace_path": "/tmp/t.json", "fail_on_recompile": True,
            "recompile_warmup_calls": 3, "watermark_ratio": 1.5}))
        t = cfg.telemetry_config
        assert t.enabled and t.report_steps == 7 and t.buffer_size == 32
        assert t.trace_path == "/tmp/t.json" and t.fail_on_recompile
        assert t.recompile_warmup_calls == 3 and t.watermark_ratio == 1.5

    def test_tensorboard_alias(self):
        """A tensorboard-only config gets an enabled telemetry sink with
        the tensorboard block's output_path/job_name."""
        cfg = DeepSpeedConfig(base_config(tensorboard={
            "enabled": True, "output_path": "/tmp/tb", "job_name": "tb_job"}))
        t = cfg.telemetry_config
        assert t.enabled and t.tensorboard
        assert t.output_path == "/tmp/tb" and t.job_name == "tb_job"

    def test_explicit_telemetry_wins_over_alias(self):
        cfg = DeepSpeedConfig(base_config(
            tensorboard={"enabled": True, "job_name": "tb"},
            telemetry={"enabled": False}))
        assert not cfg.telemetry_config.enabled

    @pytest.mark.parametrize("bad", [
        {"buffer_size": 0}, {"buffer_size": "big"}, {"report_steps": -1},
        {"recompile_warmup_calls": -2}, {"watermark_ratio": 0}])
    def test_invalid_raises(self, bad):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig(base_config(telemetry=bad))


# --------------------------------------------------------------------- #
# Ring buffer -> JSONL
# --------------------------------------------------------------------- #
class TestStepRecords:
    def test_records_drain_at_boundaries(self, tmp_path):
        engine = make_engine(tmp_path, tel_knobs={"report_steps": 5})
        batch = random_batch(n=16)
        for _ in range(11):
            engine.train_batch(batch=batch)
        engine.telemetry.close()
        recs = read_jsonl(tmp_path)
        kinds = [r["kind"] for r in recs]
        assert kinds[0] == "meta"
        steps = [r for r in recs if r["kind"] == "step"]
        reports = [r for r in recs if r["kind"] == "report"]
        assert [s["step"] for s in steps] == list(range(1, 12))
        assert len(reports) == 3      # step 5, step 10, close()
        for s in steps:
            assert s["wall_ms"] > 0
            assert isinstance(s["loss"], float)
            assert isinstance(s["lr"], float)
            assert isinstance(s["loss_scale"], float)
            assert isinstance(s["overflow"], bool)
            assert s["wire_bytes"] == recs[0]["wire_bytes_per_step"]
        assert reports[0]["skipped_steps"] == 0

    def test_ring_overflow_is_reported(self, tmp_path):
        engine = make_engine(tmp_path, tel_knobs={"report_steps": 8,
                                                  "buffer_size": 3})
        batch = random_batch(n=16)
        for _ in range(8):
            engine.train_batch(batch=batch)
        engine.telemetry.close()
        recs = read_jsonl(tmp_path)
        steps = [r for r in recs if r["kind"] == "step"]
        report = next(r for r in recs if r["kind"] == "report")
        # Ring kept the newest 3 of 8; the drop count is explicit.
        assert [s["step"] for s in steps] == [6, 7, 8]
        assert report["dropped_records"] == 5

    def test_disabled_is_inert(self, tmp_path):
        cfg = base_config()
        cfg["telemetry"] = {"enabled": False,
                            "output_path": str(tmp_path)}
        engine = DeepSpeedEngine(
            model=simple_loss_fn,
            model_params=simple_model_params(jax.random.PRNGKey(0)),
            config=cfg)
        engine.train_batch(batch=random_batch(n=16))
        engine.telemetry.close()
        assert not os.path.exists(os.path.join(str(tmp_path), "run.jsonl"))
        assert engine.telemetry.sentinel is None


# --------------------------------------------------------------------- #
# Recompile sentinel
# --------------------------------------------------------------------- #
class TestRecompileSentinel:
    def test_steady_state_is_clean(self, tmp_path):
        engine = make_engine(tmp_path, tel_knobs={"report_steps": 10 ** 9})
        batch = random_batch(n=16)
        for _ in range(6):
            engine.train_batch(batch=batch)
        assert engine.telemetry.recompile_count == 0

    def test_induced_retrace_is_caught(self, tmp_path):
        """The acceptance gate: a shape-changing batch after warmup is a
        structured recompile event naming the function and the
        abstract-signature delta."""
        engine = make_engine(tmp_path, tel_knobs={"report_steps": 10 ** 9})
        for _ in range(4):
            engine.train_batch(batch=random_batch(n=16))
        engine.train_batch(batch=random_batch(n=32))   # induced retrace
        assert engine.telemetry.recompile_count == 1
        event = engine.telemetry.sentinel.events[-1]
        assert event["fn"] == "train_step"
        delta = " ".join(event["signature_delta"])
        assert "16" in delta and "32" in delta
        engine.telemetry.close()
        jsonl_events = [r for r in read_jsonl(tmp_path)
                        if r["kind"] == "event" and r["event"] == "recompile"]
        assert len(jsonl_events) == 1
        assert jsonl_events[0]["fn"] == "train_step"

    def test_fail_on_recompile_raises(self, tmp_path):
        engine = make_engine(tmp_path,
                             tel_knobs={"fail_on_recompile": True,
                                        "report_steps": 10 ** 9})
        for _ in range(4):
            engine.train_batch(batch=random_batch(n=16))
        with pytest.raises(RecompileError, match="train_step"):
            engine.train_batch(batch=random_batch(n=32))
        # The raise is deferred past the donated-state assignment: a
        # caller that catches it must still hold a USABLE engine (e.g.
        # to checkpoint before dying), not deleted buffers.
        assert float(jax.device_get(engine.state.loss_scale)) == 1.0
        engine.train_batch(batch=random_batch(n=32))   # now cached: fine

    def test_sentinel_standalone(self):
        sent = RecompileSentinel(warmup_calls=1)
        fn = sent.instrument("f", jax.jit(lambda x: x + 1))
        fn(jnp.ones(3))                  # cold compile: warmup
        fn(jnp.ones(3))                  # cache hit
        assert sent.recompile_count == 0
        fn(jnp.ones(4))                  # retrace
        assert sent.recompile_count == 1
        assert "float32[3]" in " ".join(sent.events[0]["signature_delta"])
        assert "float32[4]" in " ".join(sent.events[0]["signature_delta"])

    def test_signature_delta_no_change(self):
        sig = (("a", "float32[3]"),)
        assert "no abstract-signature change" in \
            signature_delta(sig, sig)[0]


# --------------------------------------------------------------------- #
# Zero added hot-path device fences (tier-1 gate)
# --------------------------------------------------------------------- #
class TestNoAddedSyncs:
    def _syncs_per_run(self, tmp_path, enabled, n=5):
        cfg = base_config()
        cfg["telemetry"] = {"enabled": enabled,
                            "output_path": str(tmp_path),
                            "job_name": f"sync_{enabled}",
                            # trace spans on: they must cost no fences
                            "trace_path": os.path.join(
                                str(tmp_path), f"trace_{enabled}.json"),
                            "report_steps": 10 ** 9}
        engine = DeepSpeedEngine(
            model=simple_loss_fn,
            model_params=simple_model_params(jax.random.PRNGKey(0)),
            config=cfg)
        batch = random_batch(n=16)
        engine.train_batch(batch=batch)       # compile
        before = timer_mod.device_sync_count()
        for _ in range(n):
            engine.train_batch(batch=batch)
        return timer_mod.device_sync_count() - before

    def test_telemetry_adds_no_per_step_fences(self, tmp_path):
        disabled = self._syncs_per_run(tmp_path, False)
        enabled = self._syncs_per_run(tmp_path, True)
        assert enabled == disabled, (
            f"telemetry-enabled run issued {enabled} device fences vs "
            f"{disabled} disabled — the hot path must not fence")


# --------------------------------------------------------------------- #
# Memory watermarks
# --------------------------------------------------------------------- #
class TestMemoryWatermark:
    def test_analytic_bytes_respects_sharding(self, mesh8):
        from jax.sharding import NamedSharding, PartitionSpec as P
        x = jax.device_put(jnp.zeros((16, 4), jnp.float32),
                           NamedSharding(mesh8, P("data")))
        r = jax.device_put(jnp.zeros((16, 4), jnp.float32),
                           NamedSharding(mesh8, P()))
        assert analytic_state_bytes({"x": x}) == 16 * 4 * 4 // 8
        assert analytic_state_bytes({"r": r}) == 16 * 4 * 4
        assert analytic_state_bytes({"x": x, "r": r}) == \
            16 * 4 * 4 + 16 * 4 * 4 // 8

    def test_engine_zero2_analytic_smaller_than_replicated(self, tmp_path):
        engine = make_engine(tmp_path, **{
            "zero_optimization": {"stage": 2}})
        analytic = engine.telemetry.meta["analytic_state_bytes"]
        full = sum(np.prod(l.shape) * l.dtype.itemsize
                   for l in jax.tree_util.tree_leaves(engine.state)
                   if hasattr(l, "shape"))
        assert 0 < analytic < full   # moments are dp-sharded

    def test_watermark_event_fires_and_clears(self):
        fake = {"num_devices": 2, "per_device": [],
                "bytes_in_use_max": 100, "bytes_in_use_sum": 150,
                "peak_bytes_in_use_max": 100, "peak_bytes_in_use_sum": 150,
                "bytes_limit_max": 1000, "bytes_limit_sum": 2000}
        wm = MemoryWatermark(analytic_bytes=40, ratio=2.0, slack_bytes=10,
                             sampler=lambda: dict(fake))
        stats, event = wm.check()      # threshold = 40*2+10 = 90 < 100
        assert stats is not None and event is not None
        assert event["peak_bytes_in_use_max"] == 100
        assert event["threshold_bytes"] == 90
        assert event["ratio"] == 2.5
        fake["peak_bytes_in_use_max"] = 80
        stats, event = wm.check()
        assert stats is not None and event is None
        assert len(wm.events) == 1

    def test_unavailable_backend_is_graceful(self):
        wm = MemoryWatermark(analytic_bytes=40, sampler=lambda: None)
        assert wm.check() == (None, None)

    def test_engine_drain_writes_watermark_event(self, tmp_path):
        engine = make_engine(tmp_path,
                             tel_knobs={"report_steps": 2,
                                        "watermark_slack_bytes": 0})
        analytic = engine.telemetry.watermark.analytic_bytes
        engine.telemetry.watermark.sampler = lambda: {
            "num_devices": 1, "per_device": [],
            "bytes_in_use_max": analytic, "bytes_in_use_sum": analytic,
            "peak_bytes_in_use_max": analytic * 100,
            "peak_bytes_in_use_sum": analytic * 100,
            "bytes_limit_max": 0, "bytes_limit_sum": 0}
        batch = random_batch(n=16)
        engine.train_batch(batch=batch)
        engine.train_batch(batch=batch)    # drain boundary
        engine.telemetry.close()
        recs = read_jsonl(tmp_path)
        events = [r for r in recs if r["kind"] == "event"
                  and r["event"] == "memory_watermark"]
        assert events and events[0]["analytic_state_bytes"] == analytic
        report = next(r for r in recs if r["kind"] == "report")
        assert report["memory"]["peak_bytes_in_use_max"] == analytic * 100

    def test_see_memory_usage_uses_shared_sampler(self, monkeypatch,
                                                  capsys):
        import deepspeed_tpu.runtime.utils as rutils
        from deepspeed_tpu.utils.logging import logger
        msgs = []
        monkeypatch.setattr(logger, "info", lambda m: msgs.append(m))
        monkeypatch.setattr(
            "deepspeed_tpu.monitor.memory.device_memory_stats",
            lambda: {"num_devices": 8,
                     "bytes_in_use_max": 2 ** 30, "bytes_in_use_sum":
                     8 * 2 ** 30, "peak_bytes_in_use_max": 2 ** 31,
                     "peak_bytes_in_use_sum": 8 * 2 ** 31,
                     "bytes_limit_max": 16 * 2 ** 30,
                     "bytes_limit_sum": 0, "per_device": []})
        rutils.see_memory_usage("tag")
        assert msgs and "8 device(s)" in msgs[0]
        assert "max=1.00GB" in msgs[0] and "sum=8.00GB" in msgs[0]

    def test_device_memory_stats_matches_backend(self):
        """Sampler truth vs the backend: on backends with no
        memory_stats() (CPU) it must be None; where stats exist the
        aggregates must bound the per-device values."""
        raw = jax.local_devices()[0].memory_stats()
        stats = device_memory_stats()
        if raw is None:
            assert stats is None
        else:
            assert stats["bytes_in_use_max"] >= raw.get("bytes_in_use", 0)
            assert stats["bytes_in_use_sum"] >= stats["bytes_in_use_max"]


# --------------------------------------------------------------------- #
# JSONL sink resource story (the old _Monitor bugs)
# --------------------------------------------------------------------- #
class TestJsonlSink:
    def test_non_writer_process_opens_nothing(self, tmp_path):
        sink = JsonlSink(str(tmp_path), "job", is_writer=False)
        sink.write({"kind": "step", "step": 1})
        sink.close()
        assert not os.path.exists(os.path.join(str(tmp_path), "job.jsonl"))

    def test_writer_process_and_idempotent_close(self, tmp_path):
        sink = JsonlSink(str(tmp_path), "job", is_writer=True)
        sink.write({"kind": "step", "step": 1})
        sink.close()
        sink.close()                      # double close is safe
        sink.write({"kind": "step", "step": 2})   # post-close is a no-op
        recs = read_jsonl(tmp_path, job="job")
        assert len(recs) == 1 and recs[0]["step"] == 1


# --------------------------------------------------------------------- #
# Honesty regressions (from review)
# --------------------------------------------------------------------- #
class TestWireHonesty:
    def test_sparse_engine_wire_excludes_csr_leaves(self, tmp_path):
        """Sparse embedding grads travel the data-dependent CSR exchange;
        pricing them at the dense wire model would overstate wire by
        orders of magnitude."""
        import jax.numpy as jnp

        def loss_fn(params, batch, rng):
            x, y = batch
            h = jnp.tanh(params["embed"][y] @ params["w"])
            return jnp.mean(h * x[:, :4])

        params = {
            "embed": jax.random.normal(jax.random.PRNGKey(0), (64, 8)),
            "w": jax.random.normal(jax.random.PRNGKey(1), (8, 4)),
        }
        cfg = base_config(sparse_gradients=True)
        cfg["telemetry"] = telemetry_config(tmp_path)
        engine = DeepSpeedEngine(model=loss_fn, model_params=params,
                                 config=cfg)
        assert engine._sparse_mask is not None and engine.dp_size == 8
        from deepspeed_tpu.parallel import hlo_audit
        dense_only = hlo_audit.grad_sync_wire_model([params["w"]], 8)
        full = hlo_audit.grad_sync_wire_model(params, 8)
        assert engine._wire_bytes == dense_only["all_reduce_wire_bytes"]
        assert engine._wire_bytes < full["all_reduce_wire_bytes"]
        assert "CSR" in engine._wire_detail
        assert engine.telemetry.meta["wire_bytes_per_step"] == \
            engine._wire_bytes

    def test_report_tool_summarizes_latest_run_only(self, tmp_path):
        """The sink appends; the report must not conflate runs."""
        for run in range(2):
            engine = make_engine(tmp_path, tel_knobs={"report_steps": 2})
            batch = random_batch(n=16)
            for _ in range(2 + run * 2):
                engine.train_batch(batch=batch)
            engine.telemetry.close()
        tool = load_report_tool()
        summary = tool.summarize(os.path.join(str(tmp_path), "run.jsonl"))
        assert summary["steps_recorded"] == 4     # second run only

    def test_trio_wall_covers_forward(self, tmp_path):
        """fwd/bwd/step path: wall_ms spans the whole accumulation
        window, not just the optimizer apply."""
        import time as _time
        engine = make_engine(tmp_path, tel_knobs={"report_steps": 1})
        batch = random_batch(n=16)
        engine.forward(batch)
        t_mid = _time.perf_counter()
        _time.sleep(0.05)          # forward->step gap must be included
        engine.backward()
        engine.step()
        assert engine._trio_t0 is None
        engine.telemetry.close()
        step = next(r for r in read_jsonl(tmp_path) if r["kind"] == "step")
        assert step["wall_ms"] >= 50.0

    def test_non_writer_process_collects_nothing(self, tmp_path):
        from deepspeed_tpu.monitor import Telemetry
        cfg = DeepSpeedConfig(base_config(telemetry=telemetry_config(
            tmp_path))).telemetry_config
        tl = Telemetry(cfg, default_report_steps=1, is_writer=False)
        tl.record_step(1, {"loss": 1.0})
        assert len(tl._ring) == 0
        tl.drain()                       # no fetch, no write, no crash
        tl.close()
        assert not os.path.exists(os.path.join(str(tmp_path), "run.jsonl"))


# --------------------------------------------------------------------- #
# Resource/lifetime regressions (from review)
# --------------------------------------------------------------------- #
class TestLifetime:
    def test_closed_telemetry_releases_engine(self, tmp_path):
        """atexit keeps the Telemetry alive; a closed one must not pin
        the engine's device state (weakref step_provider + unregister)."""
        import gc
        import weakref
        engine = make_engine(tmp_path)
        engine.train_batch(batch=random_batch(n=16))
        engine.telemetry.close()
        ref = weakref.ref(engine)
        del engine
        gc.collect()
        assert ref() is None

    def test_trace_writer_incremental_flush(self, tmp_path):
        from deepspeed_tpu.monitor import TraceWriter
        import time as _time
        path = os.path.join(str(tmp_path), "t.json")
        tw = TraceWriter(path, is_writer=True)
        t = _time.perf_counter()
        tw.add_span("a", t, 0.001)
        tw.flush()
        assert tw._events == []          # buffer cleared, not rewritten
        tw.add_span("b", t, 0.001)
        tw.close()
        evs = json.load(open(path))
        assert [e["name"] for e in evs[:2]] == ["a", "b"]

    def test_trace_writer_non_writer_buffers_nothing(self, tmp_path):
        from deepspeed_tpu.monitor import TraceWriter
        import time as _time
        path = os.path.join(str(tmp_path), "t.json")
        tw = TraceWriter(path, is_writer=False)
        tw.add_span("a", _time.perf_counter(), 0.001)
        tw.instant("b")
        assert tw._events == []
        tw.close()
        assert not os.path.exists(path)

    def test_profiler_window_resume_mid_window(self, monkeypatch):
        from deepspeed_tpu.monitor import ProfilerWindow
        calls = []
        import jax
        monkeypatch.setattr(jax.profiler, "start_trace",
                            lambda d: calls.append(("start", d)))
        monkeypatch.setattr(jax.profiler, "stop_trace",
                            lambda: calls.append(("stop",)))
        w = ProfilerWindow(start_step=500, num_steps=5, out_dir="/tmp/x")
        w.tick(503)        # checkpoint resume landed mid-window
        assert calls and calls[0][0] == "start"
        w.tick(505)
        assert calls[-1] == ("stop",)


# --------------------------------------------------------------------- #
# Offload path: timings surfaced in record + log line (satellite)
# --------------------------------------------------------------------- #
class TestOffloadTelemetry:
    def make_offload_engine(self, tmp_path, overlap):
        from deepspeed_tpu.parallel.topology import build_mesh
        cfg = base_config(**{
            "train_batch_size": 4,
            "zero_optimization": {"stage": 2, "cpu_offload": True,
                                  "overlap_comm": overlap},
            "steps_per_print": 1})
        cfg["telemetry"] = telemetry_config(
            tmp_path, report_steps=1,
            trace_path=os.path.join(str(tmp_path), "trace.json"))
        return DeepSpeedEngine(
            model=simple_loss_fn,
            model_params=simple_model_params(jax.random.PRNGKey(0)),
            config=cfg, mesh=build_mesh(devices=jax.devices()[:1]))

    @pytest.mark.parametrize("overlap", [False, True])
    def test_offload_record_and_log_line(self, tmp_path, overlap,
                                         monkeypatch):
        import deepspeed_tpu.runtime.engine as engine_mod
        lines = []
        monkeypatch.setattr(engine_mod, "log_dist",
                            lambda msg, ranks=None: lines.append(msg))
        engine = self.make_offload_engine(tmp_path, overlap)
        engine.train_batch(batch=random_batch(n=4))
        engine.telemetry.close()
        # steps_per_print line surfaces the offload breakdown
        step_lines = [l for l in lines if l.startswith("step=")]
        assert step_lines and "offload[" in step_lines[-1]
        assert "overlap=" in step_lines[-1]
        # the step record carries the phase timings + overlap_fraction
        recs = read_jsonl(tmp_path)
        step = next(r for r in recs if r["kind"] == "step")
        off = step["offload"]
        assert off["overlapped"] == overlap
        assert {"d2h_ms", "host_norm_ms", "host_step_ms",
                "overlap_fraction", "num_buckets"} <= set(off)
        # per-bucket spans synthesized from the fenced timings
        trace = json.load(open(os.path.join(str(tmp_path), "trace.json")))
        names = {ev["name"] for ev in trace}
        assert any(n.startswith("offload_adam") for n in names)


# --------------------------------------------------------------------- #
# End-to-end acceptance: dp=8 run -> JSONL + trace -> TELEMETRY.json
# --------------------------------------------------------------------- #
class TestEndToEndReport:
    def test_dp8_run_report_validates(self, tmp_path, mesh8):
        trace_path = os.path.join(str(tmp_path), "trace.json")
        cfg = base_config(**{
            "zero_optimization": {"stage": 2},
            "steps_per_print": 4})
        cfg["telemetry"] = telemetry_config(tmp_path, report_steps=4,
                                            trace_path=trace_path)
        engine = DeepSpeedEngine(
            model=simple_loss_fn,
            model_params=simple_model_params(jax.random.PRNGKey(0)),
            config=cfg, mesh=mesh8)
        assert engine.dp_size == 8
        batch = random_batch(n=16)
        for _ in range(12):
            engine.train_batch(batch=batch)
        engine.save_checkpoint(str(tmp_path / "ckpt"))
        engine.load_checkpoint(str(tmp_path / "ckpt"))
        engine.telemetry.close()

        # --- wire bytes: validated against the hlo_audit wire model --- #
        from deepspeed_tpu.parallel import hlo_audit
        model = hlo_audit.grad_sync_wire_model(engine.state.params, 8)
        mode = engine._grad_sync_mode
        declared = hlo_audit.zero2_grad_sync_lowering(engine.mesh, "data")
        if mode == "allreduce" or (mode == "declarative"
                                   and declared == "all-reduce"):
            expected_wire = model["all_reduce_wire_bytes"]
        else:
            expected_wire = model["reduce_scatter_wire_bytes"]

        report_tool = load_report_tool()
        jsonl = os.path.join(str(tmp_path), "run.jsonl")
        out = str(tmp_path / "TELEMETRY.json")
        assert report_tool.main([jsonl, "-o", out]) == 0
        summary = json.load(open(out))

        assert summary["steps_recorded"] == 12
        assert summary["dropped_records"] == 0
        st = summary["step_time_ms"]
        assert st["n"] == 12 and 0 < st["p50"] <= st["p95"]
        assert summary["wire_bytes_per_step"] == expected_wire
        assert summary["wire_bytes_consistent"]
        assert summary["recompiles"]["count"] == 0
        # throughput window closed (steps_per_print=4 over 12 steps)
        assert summary["throughput"]["window_valid"]
        assert summary["throughput"]["samples_per_sec"] > 0
        # memory vs memory_stats() ground truth: on this backend (CPU)
        # stats are unavailable and the report must say so; on a real
        # TPU the same field carries the peak/analytic comparison.
        ground_truth = jax.local_devices()[0].memory_stats()
        if ground_truth is None:
            assert summary["memory"]["available"] is False
        else:   # pragma: no cover - device-backend runs
            assert summary["memory"]["peak_bytes_in_use_max"] >= \
                ground_truth.get("peak_bytes_in_use", 0)
        assert summary["memory"]["analytic_state_bytes"] == \
            engine.telemetry.meta["analytic_state_bytes"]
        assert summary["meta"]["dp"] == 8
        assert summary["skipped_steps"] == 0

        # --- roofline cost model: one cost_model record, per-path
        # verdicts validated against the wire model --- #
        recs = read_jsonl(tmp_path)
        cms = [r for r in recs if r["kind"] == "cost_model"]
        assert len(cms) == 1
        cm = cms[0]
        train = cm["paths"]["train_step"]
        assert train["available"]
        assert train["bound"] in ("compute", "hbm", "interconnect")
        # comm priced from the PR-3 wire model at the RESOLVED lowering.
        assert train["comm_bytes"] == expected_wire
        assert train["analytic_flops"] > 0
        assert cm["step"]["floor_ms"] > 0
        assert cm["chip"]["assumed"]   # CPU mesh: v5e peaks, flagged

        # --- per-step MFU + fenced window MFU --- #
        step_recs = [r for r in recs if r["kind"] == "step"]
        assert all(0 < s["mfu"] < 1 for s in step_recs)
        report_recs = [r for r in recs if r["kind"] == "report"]
        assert any(0 < r.get("window_mfu", 0) < 1 for r in report_recs)

        # --- goodput ledger: every settled window sums to its wall
        # within 1% and is consistent; the post-step checkpoint wall
        # lands in the close-drain window --- #
        gp_windows = [r["goodput"] for r in report_recs
                      if isinstance(r.get("goodput"), dict)]
        assert gp_windows
        for w in gp_windows:
            total = sum(w[f"{b}_s"] for b in GOODPUT_BUCKETS)
            assert abs(total - w["window_s"]) <= 0.01 * w["window_s"] \
                + 1e-9
            assert w["consistent"]
        assert sum(w["checkpoint_s"] for w in gp_windows) > 0
        # cold-start compile wall is attributed, not hidden
        assert sum(w["recompile_s"] for w in gp_windows) > 0

        # --- TELEMETRY.json grew the three sections --- #
        assert summary["mfu"]["available"]
        assert summary["mfu"]["peak_assumed"]
        assert 0 < summary["mfu"]["window_mfu"] < 1
        assert summary["roofline"]["available"]
        assert summary["roofline"]["step_bound"] in (
            "compute", "hbm", "interconnect")
        assert summary["roofline"]["paths"]["train_step"]["bound"] == \
            train["bound"]
        assert summary["roofline"]["measured_p50_over_floor"] > 0
        assert summary["goodput"]["available"]
        assert summary["goodput"]["consistent"]
        assert summary["goodput"]["accounted_fraction"] == \
            pytest.approx(1.0, abs=0.01)
        assert summary["goodput"]["windows"] == len(gp_windows)

        # --- Chrome-trace pair: valid JSON (array form, terminated at
        # close) with the expected spans --- #
        trace = json.load(open(trace_path))
        assert isinstance(trace, list)
        names = {ev["name"] for ev in trace}
        assert {"train_batch", "data_prep", "step_dispatch",
                "checkpoint_save", "checkpoint_load"} <= names
        for ev in trace:
            assert ev["ph"] in ("X", "i")
            assert ev["ts"] >= 0

    def test_trained_loss_still_falls(self, tmp_path):
        """Telemetry must not perturb training itself."""
        engine = make_engine(tmp_path, tel_knobs={"report_steps": 3})
        batch = random_batch(n=16)
        losses = [float(engine.train_batch(batch=batch))
                  for _ in range(15)]
        assert losses[-1] < losses[0] * 0.8


# --------------------------------------------------------------------- #
# Goodput ledger wired through the engine
# --------------------------------------------------------------------- #
class SlowDataset:
    """Indexable dataset whose item access sleeps — the injected data
    stall the goodput ledger must see."""

    def __init__(self, n=64, dim=8, delay_s=0.002):
        rng = np.random.default_rng(0)
        self.x = rng.normal(size=(n, dim)).astype(np.float32)
        self.y = (self.x.sum(axis=1) > 0).astype(np.int32)
        self.delay_s = delay_s

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        time.sleep(self.delay_s)
        return self.x[i], self.y[i]


def _assert_sums_to_wall(window):
    """The acceptance identity: buckets sum to window wall within 1%."""
    total = sum(window[f"{b}_s"] for b in GOODPUT_BUCKETS)
    assert abs(total - window["window_s"]) <= \
        0.01 * window["window_s"] + 1e-9
    assert window["consistent"]


class TestGoodputEngine:
    def test_slow_dataset_stall_lands_in_ledger(self, tmp_path):
        delay = 0.002
        cfg = base_config()
        cfg["telemetry"] = telemetry_config(tmp_path, report_steps=5)
        engine = DeepSpeedEngine(
            model=simple_loss_fn,
            model_params=simple_model_params(jax.random.PRNGKey(0)),
            config=cfg, training_data=SlowDataset(delay_s=delay))
        for _ in range(5):
            engine.train_batch()
        engine.telemetry.close()
        recs = read_jsonl(tmp_path)
        w = next(r["goodput"] for r in recs if r["kind"] == "report")
        # 5 steps x 16 samples x injected sleep: sleep() only ever
        # overshoots, so the stall floor is exact.
        expected = 5 * 16 * delay
        assert w["data_stall_s"] >= expected
        assert w["data_stall_s"] < w["window_s"]
        assert w["useful_compute_s"] >= 0
        _assert_sums_to_wall(w)
        assert w["accounted_fraction"] == pytest.approx(1.0)
        # the loader-local counter sees the same stall (dataset access
        # + collate happen inside the loader's __next__)
        assert engine.training_dataloader.cumulative_fetch_wait_s() >= \
            expected

    def test_recompile_wall_attributed(self, tmp_path):
        engine = make_engine(tmp_path, tel_knobs={"report_steps": 3})
        for _ in range(3):
            engine.train_batch(batch=random_batch(n=16))  # cold compile
        for _ in range(3):
            engine.train_batch(batch=random_batch(n=32))  # induced retrace
        engine.telemetry.close()
        recs = read_jsonl(tmp_path)
        gps = [r["goodput"] for r in recs if r["kind"] == "report"]
        assert len(gps) >= 2
        assert gps[0]["recompile_s"] > 0    # cold start is real lost wall
        assert gps[1]["recompile_s"] > 0    # the retrace window
        for w in gps:
            _assert_sums_to_wall(w)
        # ledger windows partition the sentinel's cumulative compile wall
        total = sum(g["recompile_s"] for g in gps)
        assert total == pytest.approx(
            engine.telemetry.sentinel.compile_wall_s, rel=1e-3, abs=1e-5)

    def test_overflow_skipped_steps_attributed(self, tmp_path):
        engine = make_engine(
            tmp_path, tel_knobs={"report_steps": 4},
            fp16={"enabled": True, "initial_scale_power": 8,
                  "hysteresis": 1})
        x, y = random_batch(n=16)
        bad = (np.full_like(x, np.nan), y)
        for batch in [(x, y), bad, bad, (x, y)]:
            engine.train_batch(batch=batch)
        engine.telemetry.close()
        recs = read_jsonl(tmp_path)
        steps = [r for r in recs if r["kind"] == "step"]
        assert [s["overflow"] for s in steps] == [False, True, True, False]
        w = next(r["goodput"] for r in recs if r["kind"] == "report")
        # overflow-skipped wall == exactly the overflow steps' wall
        # (work executed, result discarded — not useful compute)
        expected = sum(s["wall_ms"] for s in steps if s["overflow"]) / 1e3
        assert w["overflow_skipped_s"] == pytest.approx(
            expected, rel=1e-3, abs=1e-6)
        assert w["overflow_skipped_s"] > 0
        _assert_sums_to_wall(w)

    def test_first_step_overflow_during_cold_compile(self, tmp_path):
        """The first step both cold-compiles AND overflows: the compile
        wall (inside that step's wall) must land in recompile, not be
        double-counted against the overflow bucket — the window stays
        consistent and useful_compute non-negative."""
        engine = make_engine(
            tmp_path, tel_knobs={"report_steps": 3},
            fp16={"enabled": True, "initial_scale_power": 8,
                  "hysteresis": 1})
        x, y = random_batch(n=16)
        bad = (np.full_like(x, np.nan), y)
        for batch in [bad, (x, y), (x, y)]:
            engine.train_batch(batch=batch)
        engine.telemetry.close()
        recs = read_jsonl(tmp_path)
        steps = [r for r in recs if r["kind"] == "step"]
        assert steps[0]["overflow"] and not steps[1]["overflow"]
        w = next(r["goodput"] for r in recs if r["kind"] == "report")
        assert w["recompile_s"] > 0
        assert w["overflow_skipped_s"] >= 0
        assert w["useful_compute_s"] >= 0
        _assert_sums_to_wall(w)

    def test_trailing_checkpoint_settles_at_close(self, tmp_path):
        """A checkpoint saved after the last report boundary must not
        vanish: close() settles the ledger even with an empty ring."""
        engine = make_engine(tmp_path, tel_knobs={"report_steps": 2})
        for _ in range(2):
            engine.train_batch(batch=random_batch(n=16))  # drains at 2
        engine.save_checkpoint(str(tmp_path / "ckpt"))
        engine.telemetry.close()
        recs = read_jsonl(tmp_path)
        gps = [r["goodput"] for r in recs if r["kind"] == "report"]
        assert len(gps) == 2            # boundary + close settlement
        assert gps[-1]["steps"] == 0
        assert gps[-1]["checkpoint_s"] > 0
        _assert_sums_to_wall(gps[-1])


# --------------------------------------------------------------------- #
# Roofline cost model wired through the engine
# --------------------------------------------------------------------- #
class TestCostModelEngine:
    def test_disabled_knob_writes_no_record(self, tmp_path):
        engine = make_engine(tmp_path, tel_knobs={"report_steps": 2,
                                                  "cost_model": False})
        for _ in range(2):
            engine.train_batch(batch=random_batch(n=16))
        engine.telemetry.close()
        recs = read_jsonl(tmp_path)
        assert not [r for r in recs if r["kind"] == "cost_model"]
        assert all("mfu" not in r for r in recs if r["kind"] == "step")

    def test_build_failure_degrades_to_event(self, tmp_path, monkeypatch):
        """Observability must never kill training: a cost-model build
        crash becomes a structured event and the run continues."""
        import deepspeed_tpu.monitor.cost_model as cm_mod

        def boom(*a, **k):
            raise RuntimeError("synthetic cost-model failure")

        monkeypatch.setattr(cm_mod, "build_cost_model", boom)
        engine = make_engine(tmp_path, tel_knobs={"report_steps": 2})
        losses = [float(engine.train_batch(batch=random_batch(n=16)))
                  for _ in range(4)]
        assert all(np.isfinite(losses))
        engine.telemetry.close()
        recs = read_jsonl(tmp_path)
        evs = [r for r in recs if r["kind"] == "event"
               and r["event"] == "cost_model_error"]
        assert len(evs) == 1            # built once, failed once
        assert "synthetic cost-model failure" in evs[0]["error"]
        assert not [r for r in recs if r["kind"] == "cost_model"]

    def test_offload_path_priced(self, tmp_path):
        engine = TestOffloadTelemetry().make_offload_engine(
            tmp_path, overlap=False)
        engine.train_batch(batch=random_batch(n=4))   # report_steps=1
        engine.telemetry.close()
        recs = read_jsonl(tmp_path)
        cm = next(r for r in recs if r["kind"] == "cost_model")
        assert cm["step"]["paths"] == {"offload_grad_step": 1.0}
        p = cm["paths"]["offload_grad_step"]
        assert p["available"] and p["analytic_flops"] > 0
        assert cm["step"]["missing_paths"] == []

    def test_trio_path_priced_with_gas_weighting(self, tmp_path):
        """forward/backward/step trio: grad_step priced gas x, the apply
        once — the fused step total reconciles both programs."""
        cfg = base_config(train_batch_size=16,
                          gradient_accumulation_steps=2)
        cfg["telemetry"] = telemetry_config(tmp_path, report_steps=1)
        engine = DeepSpeedEngine(
            model=simple_loss_fn,
            model_params=simple_model_params(jax.random.PRNGKey(0)),
            config=cfg)
        x, y = random_batch(n=16)
        for mb in [(x[:8], y[:8]), (x[8:], y[8:])]:
            loss = engine.forward(mb)
            engine.backward(loss)
            engine.step()
        engine.telemetry.close()
        recs = read_jsonl(tmp_path)
        cm = next(r for r in recs if r["kind"] == "cost_model")
        assert cm["step"]["paths"] == {"grad_step": 2.0, "apply_grads": 1.0}
        assert cm["paths"]["grad_step"]["available"]
        assert cm["paths"]["apply_grads"]["available"]
        assert cm["step"]["missing_paths"] == []
        # fused flops: gas x grad program + 1 x apply program
        expected = 2 * cm["paths"]["grad_step"]["analytic_flops"] + \
            cm["paths"]["apply_grads"]["analytic_flops"]
        assert cm["step"]["flops_per_step"] == pytest.approx(expected)

    def test_build_adds_no_device_fences(self, tmp_path):
        """The cost-model build is host-side AOT work: re-lowering every
        registered path must issue ZERO device fences — asserted with
        the instrumented counter, not trusted."""
        engine = make_engine(tmp_path, tel_knobs={"report_steps": 10 ** 9})
        engine.train_batch(batch=random_batch(n=16))
        before = timer_mod.device_sync_count()
        engine._maybe_build_cost_model()
        assert engine.telemetry.cost_model_payload is not None
        assert timer_mod.device_sync_count() == before

    def test_wire_bytes_priced_on_grad_path(self, tmp_path, mesh8):
        """The cost model prices the PR-3 wire model's resolved bytes on
        the grad-computing path — interconnect ceiling is wire-model
        ground truth, not a guess."""
        cfg = base_config(**{"zero_optimization": {"stage": 2}})
        cfg["telemetry"] = telemetry_config(tmp_path, report_steps=2)
        engine = DeepSpeedEngine(
            model=simple_loss_fn,
            model_params=simple_model_params(jax.random.PRNGKey(0)),
            config=cfg, mesh=mesh8)
        for _ in range(2):
            engine.train_batch(batch=random_batch(n=16))
        engine.telemetry.close()
        recs = read_jsonl(tmp_path)
        cm = next(r for r in recs if r["kind"] == "cost_model")
        meta = next(r for r in recs if r["kind"] == "meta")
        assert cm["paths"]["train_step"]["comm_bytes"] == \
            meta["wire_bytes_per_step"]
        assert cm["n_devices"] == 8


# --------------------------------------------------------------------- #
# Pipeline engine: per-stage cost attribution
# --------------------------------------------------------------------- #
class TestPipelineCostModel:
    def test_per_stage_attribution(self, tmp_path):
        from deepspeed_tpu.parallel.topology import build_mesh
        from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
        from deepspeed_tpu.runtime.pipe.module import PipelineModule

        def block(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])

        L, D = 4, 8
        params = {
            f"layer_{i}": {
                "w": jax.random.normal(jax.random.PRNGKey(i), (D, D)) * 0.3,
                "b": jnp.zeros((D,))}
            for i in range(L)}
        module = PipelineModule(
            [block] * L, num_stages=2,
            loss_fn=lambda x, labels: jnp.mean(
                (x.sum(axis=(-1, -2)) - labels) ** 2),
            partition_method="uniform")
        spec = module.to_pipe_spec(params)
        cfg = {"train_batch_size": 4, "train_micro_batch_size_per_gpu": 2,
               "gradient_accumulation_steps": 2,
               "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
               "steps_per_print": 10 ** 9,
               "telemetry": telemetry_config(tmp_path, report_steps=1)}
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 4, D)).astype(np.float32)
        y = x.sum(axis=(-1, -2))
        # pp=2 x dp=1: stays inside this jax's shard_map capability
        # envelope (pp>1 x dp>1 needs partial-auto — see capability.py)
        mesh_pp = build_mesh(pp=2, devices=jax.devices()[:2])
        engine = PipelineEngine(model=spec, config=cfg, mesh=mesh_pp)
        engine.train_batch((x, y))
        engine.telemetry.close()
        recs = read_jsonl(tmp_path)
        cm = next(r for r in recs if r["kind"] == "cost_model")
        pipe = cm["pipeline"]
        assert pipe["stages"] == 2 and pipe["layers"] == L
        # uniform SPMD split: per-stage flops sum back to the analytic
        # total of the whole pipelined step program
        assert len(pipe["flops_per_stage"]) == 2
        assert sum(pipe["flops_per_stage"]) == pytest.approx(
            cm["paths"]["train_step"]["analytic_flops"])
        assert pipe["schedule"] in ("gpipe", "1f1b")
        assert pipe["micro_batches"] >= 1
        # module-level breakdown from the same jaxpr walk
        assert pipe["top_modules"]
        assert all(m["flops"] >= 0 for m in pipe["top_modules"])
