"""Activation checkpointing: remat correctness + partitioning + CPU
offload (reference test_activation_checkpointing.py: checkpoint-vs-plain
forward/grad parity incl. RNG reproducibility)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.activation_checkpointing import checkpointing as ck
from deepspeed_tpu.parallel.topology import build_mesh


@pytest.fixture(autouse=True)
def _reset():
    ck.reset()
    yield
    ck.reset()


def _fn(w, x, key):
    h = jnp.tanh(x @ w)
    h = h * jax.random.bernoulli(key, 0.8, h.shape)   # dropout-like RNG use
    return jnp.sum(h ** 2)


def _data(seed=0, b=8, d=16):
    k = jax.random.PRNGKey(seed)
    k1, k2, k3 = jax.random.split(k, 3)
    return (jax.random.normal(k1, (d, d)), jax.random.normal(k2, (b, 4, d)),
            k3)


def test_checkpoint_matches_plain():
    w, x, key = _data()
    plain = jax.grad(_fn)(w, x, key)
    wrapped = ck.checkpoint_wrapper(_fn)
    remat = jax.grad(wrapped)(w, x, key)
    # Not bitwise: remat recompiles the backward as a different fusion, and
    # XLA's FMA contraction choices differ per program — last-ulp effects
    # only (see tests/test_fused_update.py's parity note), so ulp-scale rtol.
    np.testing.assert_allclose(np.asarray(remat), np.asarray(plain),
                               rtol=1e-4)


def test_rng_replay_reproducible():
    """The recompute in backward must see the same dropout mask — explicit
    key inputs make this structural; verify grads are deterministic."""
    w, x, key = _data(1)
    wrapped = ck.checkpoint_wrapper(_fn)
    g1 = jax.grad(wrapped)(w, x, key)
    g2 = jax.grad(wrapped)(w, x, key)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))


def test_partitioned_checkpoint_under_mesh():
    """partition_activations: saved inputs carry an mp sharding constraint;
    grads still match the plain function on a dp x mp mesh."""
    mesh = build_mesh(mp=2, devices=jax.devices()[:4])
    ck.configure(partition_activations=True)
    w, x, key = _data(2)
    with jax.set_mesh(mesh) if hasattr(jax, "set_mesh") else mesh:
        wrapped = ck.checkpoint_wrapper(_fn)
        remat = jax.jit(jax.grad(wrapped))(w, x, key)
        plain = jax.jit(jax.grad(_fn))(w, x, key)
    np.testing.assert_allclose(np.asarray(remat), np.asarray(plain),
                               rtol=1e-5, atol=1e-6)


def test_cpu_offload_checkpoint():
    """cpu_checkpointing: residuals tagged for host offload; numerics
    unchanged."""
    ck.configure(checkpoint_in_cpu=True)
    w, x, key = _data(3)
    wrapped = ck.checkpoint_wrapper(_fn)
    try:
        remat = jax.jit(jax.grad(wrapped))(w, x, key)
    except Exception as e:     # backend without host-memory support
        pytest.skip(f"host offload unsupported on this backend: {e}")
    plain = jax.grad(_fn)(w, x, key)
    np.testing.assert_allclose(np.asarray(remat), np.asarray(plain),
                               rtol=1e-5, atol=1e-6)


def test_module_level_checkpoint_api():
    w, x, key = _data(4)
    ck.configure()
    out = ck.checkpoint(_fn, w, x, key)
    np.testing.assert_allclose(float(out), float(_fn(w, x, key)), rtol=1e-6)
    assert ck.is_configured()
