"""Test harness: run everything on a virtual 8-device CPU mesh.

The reference's tests fork N processes with real NCCL (tests/unit/common.py);
on TPU we can do better — XLA's host platform simulates N devices in one
process, so sharding/collective tests run anywhere. Must set flags before
jax initializes.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The axon sitecustomize force-sets jax.config jax_platforms="axon,cpu" at
# interpreter startup, which overrides the env var — push it back to cpu
# before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

if not hasattr(jax, "set_mesh"):
    # jax<0.6 compat: tests use the newer ``with jax.set_mesh(mesh):``
    # context; a Mesh is itself the legacy context manager with the same
    # effect, so the shim just returns it.
    jax.set_mesh = lambda mesh: mesh

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tier — convergence runs, pipeline "
        "engine end-to-ends, HF-parity suites (run by default; the fast "
        "tier is -m 'not slow', ~3 min on the 8-device CPU mesh)")


@pytest.fixture
def mesh8():
    from deepspeed_tpu.parallel.topology import build_mesh
    return build_mesh()  # 8-way data parallel by default


@pytest.fixture
def tmp_ds_config(tmp_path):
    """Write a ds_config dict to a json file, return its path."""
    import json

    def _write(config: dict) -> str:
        p = tmp_path / "ds_config.json"
        p.write_text(json.dumps(config))
        return str(p)

    return _write
