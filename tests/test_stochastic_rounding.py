"""Stochastic rounding: the master-free bf16 mode (reference
``stochastic_mode``, ops/transformer/transformer.py:39-151, re-done as the
TPU add-noise-and-truncate bit trick in ops/stochastic_rounding.py).

Tier 1: the rounding primitive is unbiased and lands only on the two
neighboring bf16 values. Tier 2: an engine in master-free mode follows the
fp32-master engine's loss curve over a few hundred steps — while
round-to-nearest master-free updates visibly stall (the failure mode the
mode exists to avoid).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.stochastic_rounding import (stochastic_round_bf16,
                                                   tree_stochastic_round_bf16)
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.parallel.topology import build_mesh


class TestPrimitive:
    def test_lands_on_bf16_neighbors(self):
        x = jnp.float32(1.0 + 1 / 512)   # strictly between bf16(1.0), next
        lo = jnp.bfloat16(1.0)
        hi = (lo.astype(jnp.float32) + 1 / 128).astype(jnp.bfloat16)
        keys = jax.random.split(jax.random.PRNGKey(0), 256)
        vals = {float(stochastic_round_bf16(x, k)) for k in keys}
        assert vals <= {float(lo), float(hi)}
        assert len(vals) == 2            # both neighbors occur

    def test_unbiased(self):
        # x sits 1/4 of the way from 1.0 to the next bf16 (step 1/128):
        # the high neighbor must be drawn with p ~= 0.25.
        x = jnp.float32(1.0 + 1 / 512)
        keys = jax.random.split(jax.random.PRNGKey(1), 4096)
        draws = jax.vmap(lambda k: stochastic_round_bf16(x, k))(keys)
        mean = float(jnp.mean(draws.astype(jnp.float32)))
        np.testing.assert_allclose(mean, float(x), rtol=2e-4)

    def test_exact_values_fixed(self):
        # Representable values never move, whatever the key.
        for v in (0.0, 1.0, -3.5, 256.0):
            x = jnp.bfloat16(v).astype(jnp.float32)
            out = stochastic_round_bf16(x, jax.random.PRNGKey(7))
            assert float(out) == float(x)

    def test_nonfinite_passthrough(self):
        x = jnp.asarray([jnp.inf, -jnp.inf, jnp.nan], jnp.float32)
        out = stochastic_round_bf16(x, jax.random.PRNGKey(3))
        assert np.isposinf(float(out[0])) and np.isneginf(float(out[1]))
        assert np.isnan(float(out[2]))

    def test_tree_variant_distinct_keys(self):
        t = {"a": jnp.full((64,), 1.0 + 1 / 512, jnp.float32),
             "b": jnp.full((64,), 1.0 + 1 / 512, jnp.float32)}
        out = tree_stochastic_round_bf16(t, jax.random.PRNGKey(0))
        assert not np.array_equal(np.asarray(out["a"], np.float32),
                                  np.asarray(out["b"], np.float32))


# ------------------------------------------------------------------ #
# Engine tier
# ------------------------------------------------------------------ #
DIM = 32
_W_TRUE = np.random.default_rng(0).standard_normal(DIM).astype(np.float32)


def loss_fn(params, batch, rng):
    pred = batch["x"] @ params["w"] + params["b"]
    return jnp.mean((pred - batch["y"]) ** 2)


def make_batch(i, n=64):
    r = np.random.default_rng(i)
    x = r.standard_normal((n, DIM)).astype(np.float32)
    y = x @ _W_TRUE
    return {"x": jnp.asarray(x), "y": jnp.asarray(y)}


def _params():
    return {"w": jnp.zeros((DIM,), jnp.float32),
            "b": jnp.zeros((), jnp.float32)}


def _cfg(**bf16):
    return {
        "train_batch_size": 64,
        "train_micro_batch_size_per_gpu": 64,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "bf16": dict({"enabled": True}, **bf16),
        "steps_per_print": 10 ** 9,
    }


def _run(cfg, steps=300):
    eng = DeepSpeedEngine(model=loss_fn, model_params=_params(),
                          config=cfg, mesh=build_mesh(devices=jax.devices()[:1]))
    return eng, [float(jax.device_get(eng.train_batch(make_batch(i))))
                 for i in range(steps)]


def test_config_gate():
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 4,
                         "train_micro_batch_size_per_gpu": 4,
                         "gradient_accumulation_steps": 1,
                         "bf16": {"enabled": False,
                                  "stochastic_rounding": True}},
                        world_size=1)


@pytest.mark.slow
def test_master_free_matches_fp32_masters():
    """Loss parity over a few hundred steps: bf16 params + stochastic
    rounding tracks the fp32-master curve."""
    eng_sr, l_sr = _run(_cfg(stochastic_rounding=True))
    eng_ms, l_ms = _run(_cfg())
    # master-free state really is bf16 (no fp32 copy anywhere)
    assert eng_sr.state.params["w"].dtype == jnp.bfloat16
    assert eng_ms.state.params["w"].dtype == jnp.float32
    # late-training averages agree (per-step curves are noisy in bf16)
    tail_sr = float(np.mean(l_sr[-50:]))
    tail_ms = float(np.mean(l_ms[-50:]))
    assert tail_sr < 0.05 * l_sr[0], (l_sr[0], tail_sr)
    np.testing.assert_allclose(tail_sr, tail_ms, atol=0.02, rtol=0.5)


@pytest.mark.slow
def test_stochastic_beats_nearest_rounding():
    """The reason the mode exists: with lr small enough that updates drop
    below half a bf16 ulp, round-to-nearest master-free training stalls
    while stochastic rounding keeps making progress."""
    lr = 3e-4
    w0 = jnp.full((DIM,), 0.5, jnp.bfloat16)

    def run(round_fn, steps=600):
        w = w0
        m = jax.jit(lambda w, x, y: jax.grad(
            lambda w: jnp.mean((x @ w - y) ** 2))(w.astype(jnp.float32)))
        key = jax.random.PRNGKey(0)
        for i in range(steps):
            b = make_batch(i)
            g = m(w, b["x"], b["y"])
            key, k = jax.random.split(key)
            w = round_fn(w.astype(jnp.float32) - lr * g, k)
        b = make_batch(10 ** 6)
        return float(jnp.mean((b["x"] @ w.astype(jnp.float32) - b["y"]) ** 2))

    loss_sr = run(stochastic_round_bf16)
    loss_rn = run(lambda x, k: x.astype(jnp.bfloat16))
    assert loss_sr < loss_rn * 0.9, (loss_sr, loss_rn)
