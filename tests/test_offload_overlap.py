"""Overlapped bucketed ZeRO-Offload: the concurrent pipeline must be
bit-identical to the serial path.

The overlap executor (runtime/zero/offload.py run_bucketed_step) streams
D2H waits against pooled norm kernels, resolves one global overflow vote,
then runs pooled per-bucket Adam with immediate per-bucket H2D. Nothing in
that concurrency may perturb the math: norm partials reduce in bucket
order, every bucket shares one bias-correction tick, and no master or
moment may mutate before the vote. These tests pin all of it, bit-exact,
on the virtual 8-device CPU mesh.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.zero.offload import (ZeroOffloadOptimizer,
                                                run_bucketed_step)
from deepspeed_tpu.parallel.topology import build_mesh

from simple_model import simple_loss_fn, simple_model_params, random_batch


def _engine(overlap, gas=2, dp=8, bf16=True, fp16=False, threads=4,
            bucket_bytes=256, clip=1.0, seed=0):
    """Tiny bucket size so the 4-leaf model splits into 3 buckets — the
    pipeline actually pipelines."""
    mesh = build_mesh(devices=jax.devices()[:dp])
    cfg = {
        "train_batch_size": 8 * dp * gas,
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": gas,
        "zero_optimization": {"stage": 2, "cpu_offload": True,
                              "overlap_comm": overlap,
                              "offload_bucket_size": bucket_bytes,
                              "offload_host_threads": threads},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "gradient_clipping": clip,
        "steps_per_print": 10 ** 9,
    }
    if bf16:
        cfg["bf16"] = {"enabled": True}
    if fp16:
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 8,
                       "hysteresis": 1, "loss_scale_window": 4}
    return DeepSpeedEngine(model=simple_loss_fn,
                           model_params=simple_model_params(
                               jax.random.PRNGKey(seed)),
                           config=cfg, mesh=mesh)


def _assert_state_bit_equal(a: DeepSpeedEngine, b: DeepSpeedEngine):
    for x, y in zip(a._offload.masters, b._offload.masters):
        np.testing.assert_array_equal(x, y)
    for x, y in zip(a._offload.opt.exp_avg + a._offload.opt.exp_avg_sq,
                    b._offload.opt.exp_avg + b._offload.opt.exp_avg_sq):
        np.testing.assert_array_equal(x, y)
    pa, pb = jax.device_get(a.state.params), jax.device_get(b.state.params)
    for x, y in zip(jax.tree_util.tree_leaves(pa),
                    jax.tree_util.tree_leaves(pb)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# --------------------------------------------------------------------- #
# Engine-level parity on the 8-device mesh
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("bf16", [True, False])
@pytest.mark.parametrize("gas", [1, 2])
def test_overlap_matches_serial_bit_exact(bf16, gas):
    """gas=1 and gas>1, bf16 wire and fp32 wire: overlapped and serial
    engines produce bit-identical losses, masters, moments, and device
    params across 4 steps with clipping active."""
    ser = _engine(False, gas=gas, bf16=bf16)
    ovl = _engine(True, gas=gas, bf16=bf16)
    assert ovl._offload_overlap and not ser._offload_overlap
    assert ovl._offload.num_buckets() >= 3
    for i in range(4):
        b = random_batch(8 * 8 * gas, seed=i)
        l0 = float(jax.device_get(ser.train_batch(b)))
        l1 = float(jax.device_get(ovl.train_batch(b)))
        assert l0 == l1, (i, l0, l1)
    _assert_state_bit_equal(ser, ovl)
    t = ovl.offload_timings
    assert t["overlapped"] and t["num_buckets"] == ovl._offload.num_buckets()
    for key in ("d2h_ms", "norm_ms", "adam_ms", "h2d_ms"):
        assert len(t["per_bucket"][key]) == t["num_buckets"]
    assert 0.0 <= t["overlap_fraction"] < 1.0


def test_overlap_fp16_overflow_mid_pipeline_parity():
    """An inf gradient landing in ONE bucket mid-pipeline must skip the
    step on both paths: identical loss-scale halving, no master or moment
    mutated in ANY bucket, and identical recovery afterwards."""
    ser = _engine(False, bf16=False, fp16=True)
    ovl = _engine(True, bf16=False, fp16=True)

    for eng in (ser, ovl):
        eng.train_batch(random_batch(8 * 8 * 2, seed=0))
        orig = eng._offload_grad_fn

        def poisoned(params, mb, rng, step, scale, _orig=orig):
            grads, loss = _orig(params, mb, rng, step, scale)
            # Poison only the LAST leaf — under overlap that is the last
            # bucket, so the overflow verdict arrives after earlier
            # buckets' norms already landed (mid-pipeline vote).
            leaves, tdef = jax.tree_util.tree_flatten(grads)
            leaves[-1] = jnp.full_like(leaves[-1], jnp.inf)
            return jax.tree_util.tree_unflatten(tdef, leaves), loss

        eng._offload_grad_fn = poisoned

    masters_before = [m.copy() for m in ovl._offload.masters]
    moments_before = [m.copy() for m in
                      ovl._offload.opt.exp_avg + ovl._offload.opt.exp_avg_sq]
    scale_before = ovl._offload.loss_scale
    b = random_batch(8 * 8 * 2, seed=1)
    ser.train_batch(b)
    ovl.train_batch(b)
    assert ovl.skipped_steps == ser.skipped_steps == 1
    assert ovl._offload.loss_scale == scale_before / 2
    for got, want in zip(ovl._offload.masters, masters_before):
        np.testing.assert_array_equal(got, want)
    for got, want in zip(
            ovl._offload.opt.exp_avg + ovl._offload.opt.exp_avg_sq,
            moments_before):
        np.testing.assert_array_equal(got, want)
    # recovery: clean steps stay bit-identical
    for eng in (ser, ovl):
        eng._offload_grad_fn = None
    for i in range(2):
        b = random_batch(8 * 8 * 2, seed=2 + i)
        assert float(jax.device_get(ser.train_batch(b))) == \
            float(jax.device_get(ovl.train_batch(b)))
    _assert_state_bit_equal(ser, ovl)


def test_overlap_checkpoint_roundtrip(tmp_path):
    """Save under the overlapped engine, drift, load — device weights and
    host state return to the checkpoint, and resumed training matches the
    serial engine bit-for-bit."""
    ser = _engine(False)
    ovl = _engine(True)
    batches = [random_batch(8 * 8 * 2, seed=i) for i in range(6)]
    for b in batches[:3]:
        ser.train_batch(b)
        ovl.train_batch(b)
    ovl.save_checkpoint(str(tmp_path), tag="ck")
    saved = [m.copy() for m in ovl._offload.masters]
    for b in batches[3:]:
        ovl.train_batch(b)
    ovl.load_checkpoint(str(tmp_path), tag="ck")
    for got, want in zip(ovl._offload.masters, saved):
        np.testing.assert_array_equal(got, want)
    assert ovl._offload.step_count == 3
    # resume: the reloaded overlapped engine tracks the serial one exactly
    for b in batches[3:]:
        l0 = float(jax.device_get(ser.train_batch(b)))
        l1 = float(jax.device_get(ovl.train_batch(b)))
        assert l0 == l1
    _assert_state_bit_equal(ser, ovl)


# --------------------------------------------------------------------- #
# Executor-level: partition_num > 1 through the overlapped pipeline
# --------------------------------------------------------------------- #
def _tree(seed=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    return {"w": jax.random.normal(k1, (64, 32), jnp.float32),
            "b": jax.random.normal(k2, (32,), jnp.float32),
            "v": jax.random.normal(k3, (16, 8), jnp.float32)}


def _drive(off, grads, overlap):
    g_leaves = [off.slice_leaf(i, np.asarray(g, np.float32))
                for i, g in enumerate(jax.tree_util.tree_leaves(grads))]
    return run_bucketed_step(
        off, lambda b: [g_leaves[i] for i in off.buckets[b]],
        overlap=overlap)[0]


def test_partitioned_overlap_matches_partitioned_serial():
    """partition_num=2 ranks, clipping + cross-rank sumsq reduction: the
    overlapped executor is bit-identical to the serial executor on every
    rank, and both agree with the unpartitioned optimizer."""
    params = _tree(3)
    rng = np.random.default_rng(9)
    grads = [{"w": (rng.standard_normal((64, 32)) * 10).astype(np.float32),
              "b": (rng.standard_normal((32,)) * 10).astype(np.float32),
              "v": (rng.standard_normal((16, 8)) * 10).astype(np.float32)}
             for _ in range(5)]

    def mk(rank, num, cb=None):
        return ZeroOffloadOptimizer(
            params, "Adam", {"lr": 1e-2}, lambda s: 1e-2, jnp.float32,
            gradient_clipping=1.0, partition_rank=rank, partition_num=num,
            sumsq_allreduce=cb, bucket_bytes=2048, host_threads=4)

    def mk_cb():
        def cb(local_sumsq):
            return cb.total
        return cb

    full = ZeroOffloadOptimizer(params, "Adam", {"lr": 1e-2},
                                lambda s: 1e-2, jnp.float32,
                                gradient_clipping=1.0, bucket_bytes=2048)
    assert full.num_buckets() >= 2
    serial = [(mk(r, 2, mk_cb()), ) for r in range(2)]
    over = [(mk(r, 2, mk_cb()), ) for r in range(2)]

    for g in grads:
        m_full = full.host_step(g)
        total = sum(float(np.sum(np.square(np.asarray(v, np.float64))))
                    for v in g.values())
        for (off,) in serial + over:
            off.sumsq_allreduce.total = total
        metrics = []
        for (off,) in serial:
            metrics.append(_drive(off, g, overlap=False))
        for (off,) in over:
            metrics.append(_drive(off, g, overlap=True))
        # every rank, both modes, report the same global norm; full agrees
        # to fp tolerance (different partition/accumulation grouping)
        for m in metrics[1:]:
            assert m["grad_norm"] == metrics[0]["grad_norm"]
        np.testing.assert_allclose(metrics[0]["grad_norm"],
                                   m_full["grad_norm"], rtol=1e-5)

    for r in range(2):
        for a, b in zip(serial[r][0].masters, over[r][0].masters):
            np.testing.assert_array_equal(a, b)    # overlap == serial: bits
    for i in range(len(full.masters)):
        got = np.concatenate([over[r][0].masters[i] for r in range(2)],
                             axis=full._axes[i] if full._axes[i] is not None
                             else 0)
        np.testing.assert_allclose(got, full.masters[i], rtol=1e-5,
                                   atol=1e-6)


def test_overflow_votes_resolve_before_any_apply():
    """Executor-level guard: with fp16 and an inf in the FIRST bucket, the
    overlapped run must not let any later bucket apply early — resolve_vote
    gates phase 2 on the full vote."""
    params = _tree(4)
    off = ZeroOffloadOptimizer(
        params, "Adam", {"lr": 1e-2}, lambda s: 1e-2, jnp.float32,
        fp16=True, scaler_cfg={"static": False, "init_scale": 64.0,
                               "hysteresis": 1, "scale_window": 100,
                               "min_scale": 1.0},
        bucket_bytes=2048, host_threads=4)
    assert off.num_buckets() >= 2
    masters0 = [m.copy() for m in off.masters]
    g = {"w": np.full((64, 32), np.inf, np.float32),
         "b": np.zeros((32,), np.float32),
         "v": np.zeros((16, 8), np.float32)}
    m = _drive(off, g, overlap=True)
    assert m["overflow"]
    assert off.skipped_steps == 1 and off.step_count == 0
    assert off.loss_scale == 32.0
    for a, b in zip(off.masters, masters0):
        np.testing.assert_array_equal(a, b)
    for mom in off.opt.exp_avg + off.opt.exp_avg_sq:
        assert not mom.any()      # moments never initialized-then-mutated
