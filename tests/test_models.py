"""Model family tests: shapes, determinism, loss decrease, TP shardings.

Mirrors the reference's kernel-test style (tests/unit/test_cuda_forward.py):
parametrized forward shape/grad checks against a small config, plus
sharding-compilation checks the reference cannot do without GPUs.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.models import (BERT_CONFIGS, GPT2_CONFIGS, bert_apply,
                                  bert_init, bert_mlm_loss_fn, gpt2_apply,
                                  gpt2_init, gpt2_loss_fn,
                                  gpt2_param_shardings)
from deepspeed_tpu.models.gpt2 import gpt2_num_params
from deepspeed_tpu.models.transformer import count_params


@pytest.fixture(scope="module")
def tiny_gpt2():
    cfg = GPT2_CONFIGS["gpt2-tiny"]
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    return cfg, params


class TestGPT2:
    def test_param_count_formula(self, tiny_gpt2):
        cfg, params = tiny_gpt2
        assert count_params(params) == gpt2_num_params(cfg)

    def test_forward_shape(self, tiny_gpt2):
        cfg, params = tiny_gpt2
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = gpt2_apply(params, tokens, cfg)
        assert logits.shape == (2, 16, cfg.vocab_size)

    def test_causality(self, tiny_gpt2):
        """Changing a future token must not change past logits."""
        cfg, params = tiny_gpt2
        rng = jax.random.PRNGKey(1)
        t1 = jax.random.randint(rng, (1, 16), 0, cfg.vocab_size)
        t2 = t1.at[0, 10].set((t1[0, 10] + 1) % cfg.vocab_size)
        l1 = gpt2_apply(params, t1, cfg)
        l2 = gpt2_apply(params, t2, cfg)
        np.testing.assert_allclose(np.asarray(l1[0, :10], np.float32),
                                   np.asarray(l2[0, :10], np.float32),
                                   rtol=2e-2, atol=2e-2)
        assert not np.allclose(np.asarray(l1[0, 10], np.float32),
                               np.asarray(l2[0, 10], np.float32))

    @pytest.mark.slow
    def test_loss_decreases(self, tiny_gpt2):
        cfg, params = tiny_gpt2
        loss_fn = gpt2_loss_fn(cfg)
        tx = optax.adam(1e-3)
        opt_state = tx.init(params)
        batch = jax.random.randint(jax.random.PRNGKey(2), (4, 17), 0,
                                   cfg.vocab_size)

        @jax.jit
        def step(params, opt_state, rng):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch, rng)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        losses = []
        for i in range(8):
            params, opt_state, loss = step(params, opt_state,
                                           jax.random.PRNGKey(i))
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_remat_matches_plain(self):
        import dataclasses
        cfg = GPT2_CONFIGS["gpt2-tiny"]
        cfg_remat = dataclasses.replace(cfg, remat_policy="full")
        params = gpt2_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        l1 = gpt2_apply(params, tokens, cfg)
        l2 = gpt2_apply(params, tokens, cfg_remat)
        np.testing.assert_allclose(np.asarray(l1, np.float32),
                                   np.asarray(l2, np.float32), rtol=1e-5)

    def test_tensor_parallel_matches_single(self, tiny_gpt2):
        """TP over a (1 dp, 4 mp) mesh must reproduce unsharded logits."""
        cfg, params = tiny_gpt2
        devices = np.array(jax.devices()[:4]).reshape(1, 4)
        mesh = Mesh(devices, ("data", "model"))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        expect = np.asarray(gpt2_apply(params, tokens, cfg), np.float32)

        shardings = jax.tree_util.tree_map(
            lambda spec: NamedSharding(mesh, spec),
            gpt2_param_shardings(cfg), is_leaf=lambda x: isinstance(x, P))
        sharded_params = jax.device_put(params, shardings)
        fn = jax.jit(lambda p, t: gpt2_apply(p, t, cfg))
        with mesh:
            got = np.asarray(fn(sharded_params, tokens), np.float32)
        np.testing.assert_allclose(got, expect, rtol=5e-2, atol=5e-2)


class TestBert:
    def test_forward_and_mask(self):
        cfg = BERT_CONFIGS["bert-tiny"]
        params = bert_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        h = bert_apply(params, tokens, cfg)
        assert h.shape == (2, 16, cfg.hidden_size)
        # Padding mask: masked-out key positions shouldn't affect kept ones...
        mask = jnp.ones((2, 16), jnp.int32).at[:, 12:].set(0)
        h1 = bert_apply(params, tokens, cfg, attention_mask=mask)
        tokens2 = tokens.at[:, 12:].set(0)
        h2 = bert_apply(params, tokens2, cfg, attention_mask=mask)
        np.testing.assert_allclose(np.asarray(h1[:, :12], np.float32),
                                   np.asarray(h2[:, :12], np.float32),
                                   rtol=2e-2, atol=2e-2)

    def test_mlm_loss(self):
        cfg = BERT_CONFIGS["bert-tiny"]
        params = bert_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        labels = jnp.full((2, 16), -100).at[:, 3].set(tokens[:, 3])
        loss = bert_mlm_loss_fn(cfg)(params, (tokens, labels),
                                     jax.random.PRNGKey(2))
        assert np.isfinite(float(loss))

    def test_preln_variant(self):
        import dataclasses
        cfg = dataclasses.replace(BERT_CONFIGS["bert-tiny"],
                                  pre_layer_norm=True)
        params = bert_init(jax.random.PRNGKey(0), cfg)
        tokens = jnp.zeros((1, 8), jnp.int32)
        h = bert_apply(params, tokens, cfg)
        assert h.shape == (1, 8, cfg.hidden_size)
