"""Elasticity tests — parity with reference tests/unit/test_elastic.py,
plus the ISSUE-15 kill/resume acceptance gate: the crash/kill/resume
harness (tools/crashkill.py) driven end to end with REAL signals."""
import os
import subprocess
import sys

import pytest

from deepspeed_tpu.elasticity import (compute_elastic_config, get_valid_gpus,
                                      get_candidate_batch_sizes)
from deepspeed_tpu.elasticity.config import (ElasticityConfigError,
                                             ElasticityIncompatibleWorldSize)
from deepspeed_tpu.runtime.config import DeepSpeedConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def base_ds_config(**elastic_overrides):
    elastic = {"enabled": True, "max_train_batch_size": 10000,
               "micro_batch_sizes": [8, 12, 16, 17], "min_gpus": 32,
               "max_gpus": 1500, "min_time": 20, "version": 0.1}
    elastic.update(elastic_overrides)
    return {"elasticity": elastic}


class TestCandidates:
    def test_candidate_batches(self):
        cands = get_candidate_batch_sizes([8, 12, 16], 720)
        # Each base times the largest HCN that fits under max/base.
        assert 720 in cands   # 8 * 90? No—8*60=480; but 12*60=720 and 16*36=576
        assert all(c <= 720 * 1 or c in (8, 12, 16) for c in cands)

    def test_valid_gpus(self):
        valid = get_valid_gpus(batch_size=24, micro_batches=[2, 3], min_valid_gpus=1,
                               max_valid_gpus=12)
        # batch 24: micro 2 → up to 12 devices (divisors of 12); micro 3 → divisors of 8.
        assert set(valid) == {1, 2, 3, 4, 6, 8, 12}


class TestComputeElasticConfig:
    def test_basic(self):
        batch, valid_gpus, micro = compute_elastic_config(base_ds_config(), "0.1.0")
        assert micro is None
        assert batch > 0
        assert len(valid_gpus) > 0
        assert all(32 <= g <= 1500 for g in valid_gpus)

    def test_with_world_size(self):
        _, valid_gpus, _ = compute_elastic_config(base_ds_config(), "0.1.0")
        ws = valid_gpus[len(valid_gpus) // 2]
        batch, valid_gpus, micro = compute_elastic_config(base_ds_config(), "0.1.0",
                                                          world_size=ws)
        assert ws in valid_gpus
        assert micro in [8, 12, 16, 17]
        assert (batch // ws) % micro == 0

    def test_incompatible_world_size(self):
        cfg = base_ds_config()
        _, valid_gpus, _ = compute_elastic_config(cfg, "0.1.0")
        bad = max(valid_gpus) + 1
        while bad in valid_gpus:
            bad += 1
        with pytest.raises(ElasticityIncompatibleWorldSize):
            compute_elastic_config(cfg, "0.1.0", world_size=bad)

    def test_future_version_rejected(self):
        with pytest.raises(ElasticityConfigError):
            compute_elastic_config(base_ds_config(version=0.2), "0.1.0")

    def test_empty_micro_batches(self):
        with pytest.raises(ElasticityConfigError):
            compute_elastic_config(base_ds_config(micro_batch_sizes=[]), "0.1.0")

    def test_negative_micro_batches(self):
        with pytest.raises(ElasticityConfigError):
            compute_elastic_config(base_ds_config(micro_batch_sizes=[-1, 4]), "0.1.0")


class TestConfigIntegration:
    def test_batch_params_conflict(self):
        ds = base_ds_config()
        ds["train_batch_size"] = 128
        with pytest.raises(ElasticityConfigError):
            DeepSpeedConfig(ds, world_size=48)

    def test_elastic_config_drives_batch(self):
        ds = base_ds_config()
        cfg = DeepSpeedConfig(ds, world_size=48)
        assert cfg.elasticity_enabled
        assert cfg.train_batch_size == cfg.train_micro_batch_size_per_gpu * \
            cfg.gradient_accumulation_steps * 48


class TestKillResumeTrajectory:
    """The r5 resume test (test_checkpoint_sharded.py::
    test_resume_continues_training_trajectory) extended to REAL process
    death: tools/crashkill.py trains with auto-saves, lands a SIGTERM
    (preemption final-save) and a SIGKILL (fall back to the last
    auto-save, including mid-write under a slowed writer) at random
    steps, probes that `latest` loads after every kill, resumes from
    `latest`, and compares the final params+moments against an
    uninterrupted run — BIT-identical at the same dp world size, and
    within 10x the measured dp=8-vs-dp=4 reduction-order floor when the
    resume cycles through DIFFERENT world sizes (the harness measures
    that floor from two uninterrupted runs, so the elastic bound is the
    unavoidable cross-world float noise, not a made-up tolerance)."""

    def test_crashkill_harness_same_dp_bit_exact(self, tmp_path):
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "crashkill.py"),
             "run", "--steps", "120", "--snapshot-every", "20",
             "--kills", "2", "--no-elastic",
             "--workdir", str(tmp_path)],
            capture_output=True, text=True, timeout=540)
        assert p.returncode == 0, p.stdout[-4000:] + p.stderr[-2000:]
        assert "kill #2" in p.stdout          # both kills actually landed
        assert "same-dp trajectory: BIT-IDENTICAL" in p.stdout
        assert "crashkill: PASS" in p.stdout

    @pytest.mark.slow
    def test_crashkill_harness_elastic_within_floor(self, tmp_path):
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "crashkill.py"),
             "run", "--steps", "120", "--snapshot-every", "20",
             "--kills", "2", "--workdir", str(tmp_path)],
            capture_output=True, text=True, timeout=540)
        assert p.returncode == 0, p.stdout[-4000:] + p.stderr[-2000:]
        assert "crashkill: PASS" in p.stdout
