"""Training health monitor (monitor/health.py + flight.py): anomaly
provenance, hang watchdog, crash flight recorder, per-host telemetry
shards + aggregation, and the truncated-segment verdict.

Acceptance gates from the PR issue:
- an induced-NaN fp16 run on the dp=8 mesh emits an anomaly event
  naming the FIRST non-finite gradient leaf and its layer;
- a SIGTERM'd run leaves a parseable FLIGHT.json with the last-N step
  records and the unsettled goodput window;
- an induced stall fires the watchdog with an all-thread stack dump;
- the health layer adds ZERO hot-path device syncs (enabled-vs-disabled
  ``device_sync_count`` fence assertion).
"""
import importlib.util
import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

import deepspeed_tpu.utils.timer as timer_mod
from deepspeed_tpu.monitor import (EwmaDetector, FlightRecorder,
                                   HangWatchdog, JsonlSink, TapSpec,
                                   Telemetry, TraceWriter, leaf_sq_taps,
                                   resolve_writer, shard_path)
from deepspeed_tpu.monitor.health import HealthMonitor
from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                          DeepSpeedConfigError)
from deepspeed_tpu.runtime.engine import DeepSpeedEngine

from simple_model import (simple_model_params, simple_loss_fn, random_batch,
                          base_config)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def make_engine(tmp_path, tel_knobs=None, **cfg_overrides):
    cfg = base_config(**cfg_overrides)
    tel = {"enabled": True, "output_path": str(tmp_path), "job_name": "run"}
    tel.update(tel_knobs or {})
    cfg["telemetry"] = tel
    params = simple_model_params(jax.random.PRNGKey(0))
    return DeepSpeedEngine(model=simple_loss_fn, model_params=params,
                           config=cfg)


def read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def run_jsonl(tmp_path, job="run"):
    return read_jsonl(os.path.join(str(tmp_path), f"{job}.jsonl"))


# --------------------------------------------------------------------- #
# Config surface
# --------------------------------------------------------------------- #
class TestHealthConfig:
    def test_defaults(self):
        cfg = DeepSpeedConfig(base_config(telemetry={"enabled": True}))
        h = cfg.telemetry_config.health
        assert h.enabled and h.grad_taps and h.flight_recorder
        assert not h.watchdog            # daemon thread is opt-in
        assert not cfg.telemetry_config.per_host_shards

    def test_knobs_parse(self):
        cfg = DeepSpeedConfig(base_config(telemetry={
            "enabled": True, "per_host_shards": True,
            "health": {"z_threshold": 4.0, "ewma_alpha": 0.2,
                       "warmup_steps": 5, "watchdog": True,
                       "watchdog_factor": 3.0, "watchdog_min_s": 1.5,
                       "flight_window": 16, "grad_taps": False}}))
        h = cfg.telemetry_config.health
        assert h.z_threshold == 4.0 and h.ewma_alpha == 0.2
        assert h.warmup_steps == 5 and h.watchdog
        assert h.watchdog_factor == 3.0 and h.watchdog_min_s == 1.5
        assert h.flight_window == 16 and not h.grad_taps
        assert cfg.telemetry_config.per_host_shards

    @pytest.mark.parametrize("bad", [
        {"z_threshold": 0}, {"ewma_alpha": 0.0}, {"ewma_alpha": 1.5},
        {"warmup_steps": -1}, {"watchdog_factor": -2},
        {"watchdog_min_s": 0}, {"flight_window": 0},
        {"enabled": "yes"}])
    def test_invalid_raises(self, bad):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig(base_config(telemetry={"enabled": True,
                                                   "health": bad}))

    def test_per_host_type_checked(self):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig(base_config(
                telemetry={"enabled": True, "per_host_shards": "all"}))


# --------------------------------------------------------------------- #
# EWMA z-score detector
# --------------------------------------------------------------------- #
class TestEwmaDetector:
    def test_warmup_never_fires(self):
        det = EwmaDetector(alpha=0.3, z_threshold=3.0, warmup=10)
        assert all(det.update(v) is None
                   for v in [1.0, 100.0, -50.0, 1.0, 2.0])

    def test_spike_fires_and_absorbs(self):
        det = EwmaDetector(alpha=0.2, z_threshold=4.0, warmup=5)
        rng = np.random.default_rng(0)
        for _ in range(50):
            assert det.update(2.0 + 0.05 * rng.standard_normal()) is None
        z = det.update(10.0)
        assert z is not None and z > 4.0
        # The baseline absorbs the shift instead of firing forever.
        fired = sum(det.update(10.0 + 0.05 * rng.standard_normal())
                    is not None for _ in range(50))
        assert fired < 10

    def test_constant_series_no_division_blowup(self):
        det = EwmaDetector(alpha=0.2, z_threshold=6.0, warmup=3)
        for _ in range(20):
            assert det.update(1.0) is None
        # A genuine jump off the flat baseline SHOULD fire.
        assert det.update(2.0) is not None

    def test_nonfinite_skipped(self):
        det = EwmaDetector(warmup=0)
        assert det.update(float("nan")) is None
        assert det.update(float("inf")) is None
        assert det.n == 0


# --------------------------------------------------------------------- #
# Tap spec + in-graph taps
# --------------------------------------------------------------------- #
class TestTaps:
    def test_spec_layers_and_paths(self):
        tree = {"block0": {"w": np.ones((2, 2)), "b": np.ones(2)},
                "head": np.ones(3)}
        spec = TapSpec.from_tree(tree)
        assert spec.num_leaves == 3
        assert set(spec.layer_names) == {"block0", "head"}
        assert any("w" in p for p in spec.leaf_paths)
        for i in range(spec.num_leaves):
            assert spec.layer_of(i) in spec.layer_names

    def test_leaf_sq_values_and_provenance(self):
        tree = {"a": np.array([1.0, 2.0], np.float32),
                "b": np.array([np.nan, 1.0], np.float32),
                "c": np.array([3.0], np.float32)}
        spec = TapSpec.from_tree(tree)
        sq = np.asarray(leaf_sq_taps(tree))
        assert sq.shape == (3,)
        assert sq[0] == pytest.approx(5.0)
        assert not np.isfinite(sq[1])
        mon = HealthMonitor(spec=spec)
        prov = mon._provenance(sq)
        assert "b" in prov["first_nonfinite_leaf"]
        assert prov["first_nonfinite_layer"] == "b"
        assert prov["nonfinite_leaves"] == 1
        assert prov["layer_grad_norms"]["b"] == "non-finite"
        assert prov["layer_grad_norms"]["a"] == pytest.approx(
            np.sqrt(5.0), abs=1e-5)

    def test_monitor_counts_and_spikes(self):
        mon = HealthMonitor(z_threshold=4.0, ewma_alpha=0.2,
                            warmup_steps=5)
        for i in range(30):
            assert mon.check_step(i, {"loss": 1.0 + 0.001 * (i % 3),
                                      "grad_norm": 0.5}) == []
        evs = mon.check_step(30, {"loss": 50.0, "grad_norm": 0.5})
        assert [e["anomaly"] for e in evs] == ["loss_spike"]
        evs = mon.check_step(31, {"loss": float("nan"),
                                  "grad_norm": float("inf"),
                                  "overflow": True})
        kinds = {e["anomaly"] for e in evs}
        assert kinds == {"nonfinite_loss", "nonfinite_grad"}
        assert mon.summary()["total"] == 3
        # -1.0 is the "norm not computed" sentinel, never an anomaly.
        assert mon.check_step(32, {"loss": 50.0, "grad_norm": -1.0}) == []


# --------------------------------------------------------------------- #
# Shared writer resolver (the deduplicated is_writer guard)
# --------------------------------------------------------------------- #
class TestWriterResolver:
    def test_explicit_override_wins(self):
        assert resolve_writer(False, rank=0)[0] is False
        assert resolve_writer(True, rank=5)[0] is True

    def test_rank_policy(self):
        assert resolve_writer(None, per_host=False, rank=0, world=4)[0]
        assert not resolve_writer(None, per_host=False, rank=3, world=4)[0]
        assert resolve_writer(None, per_host=True, rank=3, world=4)[0]

    def test_shard_path(self):
        assert shard_path("/runs/job.jsonl", 0) == "/runs/job.jsonl"
        assert shard_path("/runs/job.jsonl", 3) == "/runs/job.rank3.jsonl"
        assert shard_path("/t/trace.json", 2) == "/t/trace.rank2.json"

    def test_sink_per_host_shard_file(self, tmp_path):
        sink = JsonlSink(str(tmp_path), "job", per_host=True, rank=2,
                         world=4)
        sink.write({"kind": "step", "step": 1})
        sink.close()
        assert os.path.exists(tmp_path / "job.rank2.jsonl")
        recs = read_jsonl(tmp_path / "job.rank2.jsonl")
        assert recs[0]["step"] == 1

    def test_sink_nonwriter_drop_unchanged_without_per_host(self, tmp_path):
        sink = JsonlSink(str(tmp_path), "job", per_host=False, rank=2,
                         world=4)
        sink.write({"kind": "step", "step": 1})
        sink.close()
        assert not list(tmp_path.glob("*.jsonl"))

    def test_trace_writer_same_resolver(self, tmp_path):
        tw = TraceWriter(str(tmp_path / "trace.json"), per_host=True,
                         rank=1, world=2)
        with tw.span("x"):
            pass
        tw.close()
        assert os.path.exists(tmp_path / "trace.rank1.json")
        tw2 = TraceWriter(str(tmp_path / "t2.json"), rank=1, world=2)
        assert not tw2.is_writer


# --------------------------------------------------------------------- #
# Engine acceptance: induced-NaN provenance on the dp=8 mesh
# --------------------------------------------------------------------- #
class TestNanProvenance:
    def test_fp16_nan_names_leaf_and_layer(self, tmp_path):
        engine = make_engine(tmp_path, tel_knobs={"report_steps": 50},
                             fp16={"enabled": True,
                                   "initial_scale_power": 4})
        x, y = random_batch(n=16)
        for _ in range(3):
            engine.train_batch(batch=(x, y))
        bad = x.copy()
        bad[0, 0] = np.nan
        engine.train_batch(batch=(bad, y))
        engine.train_batch(batch=(x, y))
        engine.telemetry.close()
        recs = run_jsonl(tmp_path)
        anomalies = [r for r in recs if r.get("event") == "anomaly"]
        grads = [a for a in anomalies
                 if a["anomaly"] == "nonfinite_grad"]
        assert grads, f"no nonfinite_grad anomaly in {anomalies}"
        ev = grads[0]
        leaf_names = {"w1", "b1", "w2", "b2"}
        assert any(n in ev["first_nonfinite_leaf"] for n in leaf_names)
        assert ev["first_nonfinite_layer"] in leaf_names
        assert ev["anomaly_step"] == 4
        assert ev["overflow"] is True
        assert ev["nonfinite_leaves"] >= 1
        # Per-step JSONL keeps its scalar shape: the tap never lands in
        # the step records.
        for s in (r for r in recs if r["kind"] == "step"):
            assert "health_leaf_sq" not in s
        # The flight recorder carries the anomaly summary.
        flight = json.load(open(tmp_path / "FLIGHT.json"))
        assert flight["anomalies"]["counts"]["nonfinite_grad"] >= 1

    def test_trio_path_taps(self, tmp_path):
        engine = make_engine(tmp_path, tel_knobs={"report_steps": 50})
        x, y = random_batch(n=16)
        for _ in range(2):
            loss = engine.forward((x, y))
            engine.backward(loss)
            engine.step()
        bad = x.copy()
        bad[:, :] = np.inf
        loss = engine.forward((bad, y))
        engine.backward(loss)
        engine.step()
        engine.telemetry.close()
        recs = run_jsonl(tmp_path)
        anomalies = [r for r in recs if r.get("event") == "anomaly"
                     and r.get("first_nonfinite_leaf")]
        assert anomalies, "trio apply path produced no provenance"

    def test_tap_norms_are_unscaled_under_fp16(self, tmp_path):
        """The tap rides loss-SCALED grads in-graph but must report
        true magnitudes: sqrt(sum(leaf_sq)) == the step's (unscaled)
        grad_norm, even at a 2^12 loss scale."""
        engine = make_engine(tmp_path, tel_knobs={"report_steps": 50},
                             fp16={"enabled": True,
                                   "initial_scale_power": 12})
        captured = []
        health = engine.telemetry.health
        orig = health.check_step
        health.check_step = lambda step, rec, leaf_sq=None: (
            captured.append((dict(rec), np.asarray(leaf_sq))),
            orig(step, rec, leaf_sq))[1]
        x, y = random_batch(n=16)
        for _ in range(3):
            engine.train_batch(batch=(x, y))
        engine.telemetry.close()
        rec, leaf_sq = captured[-1]
        assert rec["grad_norm"] == pytest.approx(
            float(np.sqrt(leaf_sq.sum())), rel=1e-3)

    def test_fp32_noclip_nan_still_detected(self, tmp_path):
        """fp32 without clipping computes no grad norm and has no
        overflow vote — the per-leaf tap is the ONLY detector, and a
        poisoned step must still fire (found driving a saturating-tanh
        model: inf input -> finite loss, NaN grads, silent poisoning)."""
        engine = make_engine(tmp_path, tel_knobs={"report_steps": 50})
        x, y = random_batch(n=16)
        engine.train_batch(batch=(x, y))
        bad = x.copy()
        bad[0, 0] = np.inf      # tanh saturates: loss stays finite
        engine.train_batch(batch=(bad, y))
        engine.telemetry.close()
        recs = run_jsonl(tmp_path)
        grads = [r for r in recs if r.get("event") == "anomaly"
                 and r["anomaly"] == "nonfinite_grad"]
        assert grads and grads[0]["overflow"] is False
        assert grads[0]["first_nonfinite_leaf"]

    def test_taps_off_knob(self, tmp_path):
        engine = make_engine(
            tmp_path, tel_knobs={"health": {"grad_taps": False}})
        assert engine._health_tap_fn is None
        x, y = random_batch(n=16)
        engine.train_batch(batch=(x, y))
        engine.telemetry.close()


# --------------------------------------------------------------------- #
# Hang watchdog
# --------------------------------------------------------------------- #
class TestWatchdog:
    def test_unit_fire_and_rearm(self, tmp_path):
        fired = []
        wd = HangWatchdog(factor=2.0, min_timeout_s=0.2, poll_s=0.05,
                          on_fire=fired.append, dump_dir=str(tmp_path),
                          memory_sampler=lambda: None)
        wd.start()
        try:
            wd.pending("train_step")
            for _ in range(3):
                wd.beat(0.01)
                time.sleep(0.02)
            time.sleep(0.5)           # induced stall
            assert wd.fires == 1      # once per stall, not per poll
            ev = fired[0]
            assert ev["pending_fn"] == "train_step"
            assert ev["phase"] == "steady"
            assert ev["elapsed_s"] > 0.2
            dump = open(ev["stack_dump_path"]).read()
            assert "Thread" in dump and "watchdog" in dump
            wd.beat(0.01)             # re-arm
            time.sleep(0.5)
            assert wd.fires == 2
        finally:
            wd.stop()

    def test_timeout_scales_with_p95(self):
        wd = HangWatchdog(factor=5.0, min_timeout_s=0.1)
        assert wd.timeout_s() == pytest.approx(0.1)
        for _ in range(20):
            wd.beat(1.0)
        assert wd.timeout_s() == pytest.approx(5.0)

    def test_engine_stall_fires_with_thread_dump(self, tmp_path):
        engine = make_engine(tmp_path, tel_knobs={
            "report_steps": 50,
            "health": {"watchdog": True, "watchdog_min_s": 0.3,
                       "watchdog_factor": 2.0}})
        batch = random_batch(n=16)
        for _ in range(3):
            engine.train_batch(batch=batch)
        time.sleep(1.0)               # the induced stall
        engine.telemetry.close()
        recs = run_jsonl(tmp_path)
        fires = [r for r in recs if r.get("event") == "watchdog"]
        assert fires, "stall did not fire the watchdog"
        ev = fires[-1]
        assert ev["pending_fn"] == "train_step"
        assert os.path.exists(ev["stack_dump_path"])
        assert "Thread" in open(ev["stack_dump_path"]).read()
        flight = json.load(open(tmp_path / "FLIGHT.json"))
        assert flight["watchdog_fires"] >= 1

    def test_instrumented_fn_keeps_raw_unwrapped(self, tmp_path):
        engine = make_engine(tmp_path, tel_knobs={
            "health": {"watchdog": True, "watchdog_min_s": 60.0}})
        batch = random_batch(n=16)
        engine.train_batch(batch=batch)
        raw = engine._train_step_fn.__wrapped__
        # One unwrap must reach the raw jitted fn the sentinel
        # registered (flops profiler / hlo audit contract) — not the
        # intermediate sentinel wrapper.
        assert raw is engine.telemetry.sentinel._fns["train_step"]["fn"]
        engine.telemetry.close()


# --------------------------------------------------------------------- #
# Flight recorder
# --------------------------------------------------------------------- #
class TestFlightRecorder:
    def test_clean_close_artifact(self, tmp_path):
        engine = make_engine(tmp_path, tel_knobs={"report_steps": 3})
        batch = random_batch(n=16)
        for _ in range(7):
            engine.train_batch(batch=batch)
        engine.telemetry.close()
        flight = json.load(open(tmp_path / "FLIGHT.json"))
        assert flight["reason"] == "close"
        assert flight["closed_clean"] is True
        assert [s["step"] for s in flight["last_steps"]] == \
            list(range(1, 8))
        assert flight["final_step"] == 7
        assert flight["last_report"]["kind"] == "report"
        assert "goodput_totals" in flight
        assert flight["snapshot"]["env"]["jax"]
        assert flight["snapshot"]["dp"] == 8

    def test_window_bounds_last_steps(self, tmp_path):
        engine = make_engine(tmp_path, tel_knobs={
            "report_steps": 2, "health": {"flight_window": 4}})
        batch = random_batch(n=16)
        for _ in range(10):
            engine.train_batch(batch=batch)
        engine.telemetry.close()
        flight = json.load(open(tmp_path / "FLIGHT.json"))
        assert [s["step"] for s in flight["last_steps"]] == [7, 8, 9, 10]

    def test_close_reentrancy_from_signal_handler(self, tmp_path):
        """Satellite gate: Telemetry.close() must be safe when a signal
        handler lands on top of the atexit-driven close."""
        engine = make_engine(tmp_path)
        batch = random_batch(n=16)
        engine.train_batch(batch=batch)
        tl = engine.telemetry
        calls = []
        orig_drain = tl.drain

        def draining(extra=None):
            # Simulate the signal arriving MID-close: re-enter close().
            calls.append(1)
            if len(calls) == 1:
                tl.close()
            return orig_drain(extra)

        tl.drain = draining
        tl.close()
        assert len(calls) == 1        # the re-entrant close was a no-op
        tl.close()                    # idempotent afterwards too
        recs = run_jsonl(tmp_path)
        assert [r["kind"] for r in recs].count("final") == 1

    def test_in_process_sigterm_chain(self, tmp_path):
        """SIGTERM with a prior handler installed: ours persists, closes
        telemetry, chains, and restores."""
        seen = []
        prev = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
        try:
            engine = make_engine(tmp_path, tel_knobs={"report_steps": 50})
            batch = random_batch(n=16)
            for _ in range(4):
                engine.train_batch(batch=batch)
            os.kill(os.getpid(), signal.SIGTERM)
            assert seen == [signal.SIGTERM]   # chained to prior handler
            flight = json.load(open(tmp_path / "FLIGHT.json"))
            assert flight["reason"] == "SIGTERM"
            assert flight["closed_clean"] is True   # close ran in-handler
            assert len(flight["last_steps"]) == 4
            assert flight["at_signal"]["undrained_steps"] == [1, 2, 3, 4]
            gp = flight["goodput_unsettled"]
            assert gp["open_window_s"] > 0 and gp["windows_closed"] == 0
            assert engine.telemetry._closed
            # Handler restored itself: ours is gone.
            assert signal.getsignal(signal.SIGTERM) not in \
                (signal.SIG_DFL,)
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_stale_chained_recorder_does_not_clobber(self, tmp_path):
        """Two engines sharing an output dir: the CLOSED engine's
        handler stays linked in the live engine's signal chain — a
        stale invocation must pass the signal through without
        overwriting the live run's FLIGHT.json."""
        seen = []
        prev = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
        try:
            eng_a = make_engine(tmp_path, tel_knobs={"report_steps": 50})
            batch = random_batch(n=16)
            eng_a.train_batch(batch=batch)
            eng_b = make_engine(tmp_path, tel_knobs={"report_steps": 50})
            for _ in range(3):
                eng_b.train_batch(batch=batch)
            eng_a.telemetry.close()   # A's handler is now a stale link
            os.kill(os.getpid(), signal.SIGTERM)
            assert seen == [signal.SIGTERM]
            flight = json.load(open(tmp_path / "FLIGHT.json"))
            # B's signal-time artifact survived; A (1 step, closed)
            # did not overwrite it.
            assert flight["reason"] == "SIGTERM"
            assert len(flight["last_steps"]) == 3
        finally:
            signal.signal(signal.SIGTERM, prev)

    @pytest.mark.slow
    def test_subprocess_sigterm_mid_run(self, tmp_path):
        """The acceptance gate end to end: a real process killed mid-run
        dies BY SIGTERM and leaves a parseable FLIGHT.json."""
        script = tmp_path / "child.py"
        script.write_text(f"""
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {str(os.path.join(REPO, 'tests'))!r})
sys.path.insert(0, {REPO!r})
from simple_model import (simple_model_params, simple_loss_fn,
                          random_batch, base_config)
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
out = {str(tmp_path)!r}
cfg = base_config(telemetry={{"enabled": True, "output_path": out,
                             "job_name": "run", "report_steps": 1000}})
eng = DeepSpeedEngine(model=simple_loss_fn,
                      model_params=simple_model_params(
                          jax.random.PRNGKey(0)), config=cfg)
batch = random_batch(n=16)
for i in range(2000):
    eng.train_batch(batch=batch)
    if i == 4:
        open(os.path.join(out, "READY"), "w").write("1")
    time.sleep(0.05)
""")
        proc = subprocess.Popen([sys.executable, str(script)],
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        try:
            t0 = time.time()
            ready = str(tmp_path / "READY")
            while not os.path.exists(ready):
                time.sleep(0.1)
                assert proc.poll() is None, "child died before READY"
                assert time.time() - t0 < 180, "child never became ready"
            proc.send_signal(signal.SIGTERM)
            rc = proc.wait(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
        assert rc == -signal.SIGTERM     # true termination signal
        flight = json.load(open(tmp_path / "FLIGHT.json"))
        assert flight["reason"] == "SIGTERM"
        assert len(flight["last_steps"]) >= 5
        assert flight["goodput_unsettled"]["open_window_s"] > 0
        assert flight["at_signal"]["undrained_steps"]
        recs = run_jsonl(tmp_path)
        assert [r["kind"] for r in recs][-1] == "final"


# --------------------------------------------------------------------- #
# Per-host shards + aggregation + truncation (tools/telemetry_report.py)
# --------------------------------------------------------------------- #
def _write_stream(path, rank, losses, wall_ms, last_step=None,
                  final=True):
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "meta", "process_index": rank,
                            "process_count": 2, "emits_final": True,
                            "health_enabled": True}) + "\n")
        for i, loss in enumerate(losses, start=1):
            if last_step is not None and i > last_step:
                break
            f.write(json.dumps({"kind": "step", "step": i, "loss": loss,
                                "wall_ms": wall_ms}) + "\n")
        f.write(json.dumps({"kind": "report", "records": len(losses)})
                + "\n")
        if final:
            f.write(json.dumps({"kind": "final", "step": len(losses)})
                    + "\n")


class TestMultiHostReport:
    def test_engine_per_host_shard_and_aggregation(self, tmp_path,
                                                   monkeypatch):
        """A rank-1 engine (identity faked via DS_PROC_INDEX) writes its
        own shard instead of dropping records; the report aggregates it
        against the primary."""
        rep = load_tool("telemetry_report")
        # Primary (rank 0 of a faked 2-process world, like a real pod).
        monkeypatch.setenv("DS_PROC_INDEX", "0")
        monkeypatch.setenv("DS_PROC_COUNT", "2")
        engine = make_engine(tmp_path, tel_knobs={"report_steps": 3})
        batch = random_batch(n=16)
        for _ in range(6):
            engine.train_batch(batch=batch)
        engine.telemetry.close()
        # Rank 1: same run shape through the faked identity.
        monkeypatch.setenv("DS_PROC_INDEX", "1")
        engine1 = make_engine(tmp_path, tel_knobs={
            "report_steps": 3, "per_host_shards": True})
        for _ in range(6):
            engine1.train_batch(batch=batch)
        engine1.telemetry.close()
        monkeypatch.delenv("DS_PROC_INDEX")
        shard = tmp_path / "run.rank1.jsonl"
        assert shard.exists()
        assert len([r for r in read_jsonl(shard)
                    if r["kind"] == "step"]) == 6
        summary = rep.summarize(str(tmp_path / "run.jsonl"))
        hosts = summary["health"]["hosts"]
        assert hosts["available"] and hosts["n_hosts"] == 2
        assert {e["rank"] for e in hosts["per_host"]} == {0, 1}
        assert hosts["step_count_desync"] is False
        # Identical data + seed on both "hosts" -> identical loss hash.
        assert hosts["loss_desync"] is False

    def test_explicit_flight_path_shards_per_rank(self, tmp_path,
                                                  monkeypatch):
        """per_host + an explicit flight_path: ranks must not share one
        FLIGHT.json (the last handler would clobber the primary's
        postmortem)."""
        monkeypatch.setenv("DS_PROC_INDEX", "1")
        monkeypatch.setenv("DS_PROC_COUNT", "2")
        fp = str(tmp_path / "FL.json")
        engine = make_engine(tmp_path, tel_knobs={
            "per_host_shards": True, "health": {"flight_path": fp}})
        assert engine.telemetry.flight.path == str(tmp_path /
                                                   "FL.rank1.json")
        engine.telemetry.close()

    def test_stale_flight_artifact_not_attributed(self, tmp_path):
        """A segment that never armed a flight recorder must not adopt
        a previous run's FLIGHT.json sitting in the same directory."""
        rep = load_tool("telemetry_report")
        (tmp_path / "FLIGHT.json").write_text(json.dumps(
            {"reason": "SIGTERM", "last_steps": []}))
        _write_stream(tmp_path / "clean.jsonl", 0, [1.0], wall_ms=5.0)
        fr = rep.summarize(str(tmp_path / "clean.jsonl"))["health"][
            "flight_recorder"]
        assert fr == {"present": False}

    def test_nonwriter_without_per_host_still_drops(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.setenv("DS_PROC_INDEX", "1")
        monkeypatch.setenv("DS_PROC_COUNT", "2")
        engine = make_engine(tmp_path)
        engine.train_batch(batch=random_batch(n=16))
        engine.telemetry.close()
        assert not list(tmp_path.glob("*.jsonl"))

    def test_straggler_and_desync_detection(self, tmp_path):
        rep = load_tool("telemetry_report")
        losses = [1.0, 0.9, 0.8, 0.7]
        _write_stream(tmp_path / "job.jsonl", 0, losses, wall_ms=10.0)
        # Rank 1: 2x slower, diverged losses, stopped one step early.
        _write_stream(tmp_path / "job.rank1.jsonl", 1,
                      [1.0, 0.9, 0.85, 0.7], wall_ms=20.0, last_step=3,
                      final=False)
        summary = rep.summarize(str(tmp_path / "job.jsonl"))
        hosts = summary["health"]["hosts"]
        assert hosts["n_hosts"] == 2
        assert hosts["straggler_skew_rel"] == pytest.approx(1.0)
        assert hosts["slowest_rank"] == 1
        assert hosts["step_count_desync"] is True
        assert hosts["loss_desync"] is True

    def test_stale_shards_excluded(self, tmp_path):
        """Orphaned rank files from a previous (larger-world) run must
        not fabricate desync verdicts against a relaunch."""
        rep = load_tool("telemetry_report")
        losses = [1.0, 0.9]
        _write_stream(tmp_path / "job.jsonl", 0, losses, wall_ms=10.0)
        # process_count in the stream meta is 2: rank 5 is topology from
        # a dead, larger run.
        _write_stream(tmp_path / "job.rank5.jsonl", 5,
                      [2.0, 1.5, 1.1], wall_ms=99.0, final=False)
        hosts = rep.summarize(str(tmp_path / "job.jsonl"))["health"][
            "hosts"]
        assert hosts["available"] is False and hosts["n_hosts"] == 1
        assert hosts["stale_shards"][0]["rank"] == 5

    def test_truncated_verdict(self, tmp_path):
        rep = load_tool("telemetry_report")
        _write_stream(tmp_path / "ok.jsonl", 0, [1.0, 0.9], wall_ms=5.0)
        assert rep.summarize(str(tmp_path / "ok.jsonl"))["truncated"] \
            is False
        _write_stream(tmp_path / "cut.jsonl", 0, [1.0, 0.9], wall_ms=5.0,
                      final=False)
        cut = rep.summarize(str(tmp_path / "cut.jsonl"))
        assert cut["truncated"] is True
        assert cut["goodput"].get("truncated") is True
        assert cut["health"]["truncated"] is True

    def test_pre_marker_stream_unknown_not_false_verdict(self, tmp_path):
        rep = load_tool("telemetry_report")
        with open(tmp_path / "old.jsonl", "w") as f:
            f.write(json.dumps({"kind": "meta"}) + "\n")
            f.write(json.dumps({"kind": "step", "step": 1, "loss": 1.0,
                                "wall_ms": 5.0}) + "\n")
        assert rep.summarize(str(tmp_path / "old.jsonl"))["truncated"] \
            is None

    def test_engine_run_reports_health_section(self, tmp_path):
        rep = load_tool("telemetry_report")
        engine = make_engine(tmp_path, tel_knobs={"report_steps": 50},
                             fp16={"enabled": True,
                                   "initial_scale_power": 4})
        x, y = random_batch(n=16)
        for _ in range(3):
            engine.train_batch(batch=(x, y))
        bad = x.copy()
        bad[0, 0] = np.nan
        engine.train_batch(batch=(bad, y))
        engine.train_batch(batch=(x, y))   # drain happens later, at close
        engine.telemetry.close()
        summary = rep.summarize(str(tmp_path / "run.jsonl"))
        h = summary["health"]
        assert h["available"]
        assert h["anomalies"]["nonfinite"] >= 1
        # Skipped-overflow NaN is routine fp16 mechanics, not the
        # gate-failing class.
        assert h["anomalies"]["nonfinite_unskipped"] == 0
        ev = h["anomalies"]["events"][0]
        assert ev["first_nonfinite_leaf"]
        # The listed step is the anomaly's OWN step, not the drain-time
        # counter (drain ran at close, step 5).
        assert ev["step"] == 4
        assert h["flight_recorder"]["present"]
        assert h["flight_recorder"]["reason"] == "close"
        assert summary["truncated"] is False


# --------------------------------------------------------------------- #
# bench_gate health validation
# --------------------------------------------------------------------- #
class TestBenchGateHealth:
    def _telemetry_doc(self, **health_over):
        h = {"available": True, "watchdog_fires": 0,
             "anomalies": {"total": 0, "nonfinite": 0,
                           "nonfinite_unskipped": 0},
             "truncated": False}
        h.update(health_over)
        return {"mfu": {"window_mfu": 0.5}, "goodput":
                {"goodput_fraction": 0.9}, "health": h,
                "truncated": h["truncated"]}

    def _gate(self, tmp_path, old, new):
        bg = load_tool("bench_gate")
        po, pn = tmp_path / "old.json", tmp_path / "new.json"
        po.write_text(json.dumps(old))
        pn.write_text(json.dumps(new))
        return bg.gate(str(po), str(pn), 0.10, 0.05)

    def test_healthy_round_passes(self, tmp_path):
        assert self._gate(tmp_path, self._telemetry_doc(),
                          self._telemetry_doc()) == 0

    def test_watchdog_fire_fails(self, tmp_path):
        assert self._gate(tmp_path, self._telemetry_doc(),
                          self._telemetry_doc(watchdog_fires=2)) == 1

    def test_unskipped_nonfinite_anomaly_fails(self, tmp_path):
        bad = self._telemetry_doc(
            anomalies={"total": 1, "nonfinite": 1,
                       "nonfinite_unskipped": 1})
        assert self._gate(tmp_path, self._telemetry_doc(), bad) == 1

    def test_overflow_skipped_nonfinite_passes(self, tmp_path):
        # Routine fp16 loss-scale backoff: the overflow vote skipped the
        # update, so the anomaly is signal, not a gate failure.
        ok = self._telemetry_doc(
            anomalies={"total": 2, "nonfinite": 2,
                       "nonfinite_unskipped": 0})
        assert self._gate(tmp_path, self._telemetry_doc(), ok) == 0

    def test_truncated_fails(self, tmp_path):
        assert self._gate(tmp_path, self._telemetry_doc(),
                          self._telemetry_doc(truncated=True)) == 1

    def test_pre_health_round_skips(self, tmp_path):
        old = {"mfu": {"window_mfu": 0.5},
               "goodput": {"goodput_fraction": 0.9}}
        assert self._gate(tmp_path, old, dict(old)) == 0

    def test_spike_anomalies_do_not_fail(self, tmp_path):
        # Spikes are signal, not defects: only non-finite events gate.
        doc = self._telemetry_doc(anomalies={"total": 3, "nonfinite": 0})
        assert self._gate(tmp_path, self._telemetry_doc(), doc) == 0


# --------------------------------------------------------------------- #
# The zero-added-syncs fence (enabled-vs-disabled device_sync_count)
# --------------------------------------------------------------------- #
class TestHealthFence:
    def _run(self, tmp_path, telemetry: bool):
        cfg = base_config(fp16={"enabled": True,
                                "initial_scale_power": 4})
        if telemetry:
            cfg["telemetry"] = {"enabled": True,
                                "output_path": str(tmp_path),
                                "job_name": "fence", "report_steps": 4}
        engine = DeepSpeedEngine(
            model=simple_loss_fn,
            model_params=simple_model_params(jax.random.PRNGKey(0)),
            config=cfg)
        x, y = random_batch(n=16)
        bad = x.copy()
        bad[0, 0] = np.nan
        engine.train_batch(batch=(x, y))    # compiles outside the fence
        before = timer_mod.device_sync_count()
        for _ in range(6):
            engine.train_batch(batch=(x, y))
        engine.train_batch(batch=(bad, y))
        delta = timer_mod.device_sync_count() - before
        engine.telemetry.close()
        return delta

    def test_health_adds_no_hot_path_syncs(self, tmp_path):
        off = self._run(tmp_path / "off", telemetry=False)
        on = self._run(tmp_path / "on", telemetry=True)
        assert on == off, (
            f"health-enabled run issued {on} device-sync fences vs "
            f"{off} disabled — the zero-added-syncs contract broke")
