"""Ring attention (sequence parallelism): exactness vs dense attention on
the virtual multi-chip mesh, causal + bidirectional, gradients included."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.ring_attention import ring_attention, ring_attention_fn
from deepspeed_tpu.models.transformer import dense_attention
from deepspeed_tpu.parallel.topology import build_mesh

from capability import (PARTIAL_AUTO_SKIP_REASON,
                        partial_auto_shard_map_supported)

# The sp>1 meshes below all carry a dp axis > 1 alongside the manual seq
# axis — a partially-manual shard_map old jax cannot compile.
needs_partial_auto = pytest.mark.skipif(
    not partial_auto_shard_map_supported(), reason=PARTIAL_AUTO_SKIP_REASON)


def _qkv(seed, B=2, S=32, nH=2, D=16):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, S, nH, D), jnp.float32) * 0.4
                 for k in ks)


@needs_partial_auto
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sp", [2, 4])
def test_ring_matches_dense(causal, sp):
    mesh = build_mesh(sp=sp, devices=jax.devices()[:sp * 2])  # dp=2 x sp
    q, k, v = _qkv(0)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, causal=causal))(q, k, v)
    ref = dense_attention(q, k, v, mask=None, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@needs_partial_auto
@pytest.mark.parametrize("causal", [False, True])
def test_ring_grads_match_dense(causal):
    mesh = build_mesh(sp=4, devices=jax.devices()[:8])
    q, k, v = _qkv(1)
    probe = jax.random.normal(jax.random.PRNGKey(9), q.shape) * 0.1

    def loss_ring(q, k, v):
        o = ring_attention(q, k, v, mesh, causal=causal)
        return jnp.sum(o * probe)

    def loss_dense(q, k, v):
        o = dense_attention(q, k, v, mask=None, causal=causal)
        return jnp.sum(o * probe)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd, n in zip(g_ring, g_dense, "qkv"):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=5e-5, atol=5e-5,
                                   err_msg=f"d{n}")


@needs_partial_auto
def test_ring_in_transformer_block():
    """ring_attention_fn plugs into apply_blocks as the attention_fn."""
    from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                  apply_blocks,
                                                  init_block_params)
    mesh = build_mesh(sp=4, devices=jax.devices()[:8])
    cfg = TransformerConfig(hidden_size=32, num_heads=2, num_layers=2,
                            max_seq_length=32, hidden_dropout=0.0,
                            attn_dropout=0.0, causal=True)
    p = init_block_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32), jnp.float32)
    ring = jax.jit(lambda p, x: apply_blocks(
        p, x, cfg, deterministic=True,
        attention_fn=ring_attention_fn(mesh)))(p, x)
    ref = apply_blocks(p, x, cfg, deterministic=True,
                       attention_fn=dense_attention)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_sp1_fallback():
    mesh = build_mesh(devices=jax.devices()[:2])   # no seq axis
    q, k, v = _qkv(2, S=16)
    out = ring_attention(q, k, v, mesh, causal=True)
    ref = dense_attention(q, k, v, mask=None, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)
