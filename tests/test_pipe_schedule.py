"""Schedule instruction-sequence tests (reference test_pipe_schedule.py)."""
import pytest

from deepspeed_tpu.runtime.pipe.schedule import (
    BackwardPass, ForwardPass, InferenceSchedule, LoadMicroBatch,
    OptimizerStep, RecvActivation, RecvGrad, ReduceGrads, ReduceTiedGrads,
    SendActivation, SendGrad, TrainSchedule)


def _flat(schedule):
    return [cmd for step in schedule for cmd in step]


class TestInferenceSchedule:
    def test_first_stage_loads_last_sends_nothing(self):
        sched = InferenceSchedule(micro_batches=4, stages=2, stage_id=0)
        cmds = _flat(sched)
        assert sum(isinstance(c, LoadMicroBatch) for c in cmds) == 4
        assert sum(isinstance(c, ForwardPass) for c in cmds) == 4
        assert sum(isinstance(c, SendActivation) for c in cmds) == 4
        assert not any(isinstance(c, RecvActivation) for c in cmds)

        last = InferenceSchedule(micro_batches=4, stages=2, stage_id=1)
        cmds = _flat(last)
        assert sum(isinstance(c, RecvActivation) for c in cmds) == 4
        assert not any(isinstance(c, SendActivation) for c in cmds)

    def test_total_steps(self):
        sched = InferenceSchedule(micro_batches=4, stages=3, stage_id=1)
        assert len(list(sched.steps())) == 4 + 3 - 1


class TestTrainSchedule:
    @pytest.mark.parametrize("stages,micro", [(2, 4), (4, 8), (4, 4), (1, 2)])
    def test_every_micro_batch_forward_and_backward(self, stages, micro):
        for sid in range(stages):
            sched = TrainSchedule(micro_batches=micro, stages=stages,
                                  stage_id=sid)
            cmds = _flat(sched)
            fwd = [c for c in cmds if isinstance(c, ForwardPass)]
            bwd = [c for c in cmds if isinstance(c, BackwardPass)]
            assert len(fwd) == micro, f"stage {sid}"
            assert len(bwd) == micro, f"stage {sid}"

    def test_forward_precedes_backward_per_buffer(self):
        sched = TrainSchedule(micro_batches=4, stages=2, stage_id=0)
        seen_fwd = set()
        for step in sched:
            for cmd in step:
                if isinstance(cmd, ForwardPass):
                    seen_fwd.add(cmd.buffer_id)
                if isinstance(cmd, BackwardPass):
                    assert cmd.buffer_id in seen_fwd
                    seen_fwd.discard(cmd.buffer_id)

    def test_single_optimizer_step_at_end(self):
        sched = TrainSchedule(micro_batches=4, stages=2, stage_id=1)
        steps = list(sched.steps())
        cmds = _flat(steps)
        assert sum(isinstance(c, OptimizerStep) for c in cmds) == 1
        assert any(isinstance(c, OptimizerStep) for c in steps[-1])
        assert sum(isinstance(c, ReduceGrads) for c in cmds) == 1
        assert sum(isinstance(c, ReduceTiedGrads) for c in cmds) == 1

    def test_comm_pairing_across_stages(self):
        """Every SendActivation on stage s has a RecvActivation on s+1, and
        every SendGrad on s a RecvGrad on s-1 (same totals)."""
        stages, micro = 3, 6
        send_act = {s: 0 for s in range(stages)}
        recv_act = {s: 0 for s in range(stages)}
        send_grad = {s: 0 for s in range(stages)}
        recv_grad = {s: 0 for s in range(stages)}
        for s in range(stages):
            for c in _flat(TrainSchedule(micro, stages, s)):
                send_act[s] += isinstance(c, SendActivation)
                recv_act[s] += isinstance(c, RecvActivation)
                send_grad[s] += isinstance(c, SendGrad)
                recv_grad[s] += isinstance(c, RecvGrad)
        for s in range(stages - 1):
            assert send_act[s] == recv_act[s + 1] == micro
            assert send_grad[s + 1] == recv_grad[s] == micro
        assert send_act[stages - 1] == 0 and recv_grad[stages - 1] == 0
        assert recv_act[0] == 0 and send_grad[0] == 0

    def test_1f1b_buffer_bound(self):
        """In-flight forwards never exceed num_pipe_buffers (the 1F1B
        memory guarantee, schedule.py:237-242)."""
        stages, micro = 4, 16
        for sid in range(stages):
            sched = TrainSchedule(micro, stages, sid)
            bound = sched.num_pipe_buffers()
            in_flight = 0
            peak = 0
            for step in sched:
                for cmd in step:
                    if isinstance(cmd, ForwardPass):
                        in_flight += 1
                    if isinstance(cmd, BackwardPass):
                        in_flight -= 1
                peak = max(peak, in_flight)
            assert peak <= bound, f"stage {sid}: {peak} > {bound}"


class TestScheduleExecution:
    """The 1F1B instruction program EXECUTES and matches plain autodiff —
    upgrading the schedule from specification to validated semantics
    (reference pipe/engine.py:1135-1161 interpreter parity)."""

    def _setup(self, P, M, D=6):
        import jax
        import jax.numpy as jnp

        def stage_fn(p, x):
            return x + jnp.tanh(x @ p["w"] + p["b"])

        params = [{"w": jax.random.normal(jax.random.PRNGKey(s), (D, D)) * 0.4,
                   "b": jnp.zeros((D,))} for s in range(P)]
        xs = [jax.random.normal(jax.random.PRNGKey(100 + m), (3, D))
              for m in range(M)]
        ts = [jax.random.normal(jax.random.PRNGKey(200 + m), (3,))
              for m in range(M)]

        def loss_fn(y, t):
            return jnp.mean((y.sum(-1) - t) ** 2)

        return [stage_fn] * P, params, xs, ts, loss_fn

    @pytest.mark.parametrize("P,M", [(2, 4), (3, 5), (4, 4), (1, 3)])
    def test_1f1b_matches_autodiff(self, P, M):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from deepspeed_tpu.runtime.pipe.schedule import (
            execute_train_schedule)
        fns, params, xs, ts, loss_fn = self._setup(P, M)
        loss, grads = execute_train_schedule(fns, params, xs, ts, loss_fn)

        def full_loss(params):
            total = 0.0
            for m in range(M):
                h = xs[m]
                for s in range(P):
                    h = fns[s](params[s], h)
                total = total + loss_fn(h, ts[m])
            return total / M

        ref_loss = full_loss(params)
        ref_grads = jax.grad(full_loss)(params)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
        for g, rg in zip(grads, ref_grads):
            for a, b in zip(jax.tree_util.tree_leaves(g),
                            jax.tree_util.tree_leaves(rg)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-5, atol=1e-6)

    def test_buffer_overwrite_detected(self):
        """Shrinking num_pipe_buffers below the 1F1B requirement trips the
        live-buffer assertion — the memory claim is load-bearing."""
        import jax.numpy as jnp
        from deepspeed_tpu.runtime.pipe import schedule as S

        class Tight(S.TrainSchedule):
            def num_pipe_buffers(self):
                return 1     # below min(P - s, M)

        fns, params, xs, ts, loss_fn = self._setup(3, 4)
        with pytest.raises(AssertionError, match="live buffer|recv"):
            S.execute_train_schedule(fns, params, xs, ts, loss_fn,
                                     schedule_cls=Tight)
