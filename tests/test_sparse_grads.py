"""Sparse (CSR) embedding gradients, wired end-to-end through the engine.

Reference: engine.py:179-186 (detect torch.nn.Embedding modules when
``sparse_gradients`` is set) and :1197-1253 (route their grads through a
values+indices allgather + densify instead of the dense allreduce).

Here: ``sparse_gradients: true`` marks embedding-shaped param leaves (path
contains "embed"/"wte"), the engine computes per-rank grads under shard_map,
ships the embedding grads row-sparse via the host CSR exchange, and the
optimizer applies the combined (mean) grads — parity with the dense path.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.parallel.topology import build_mesh

VOCAB, HID = 512, 8


def model_params(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "embedding": jnp.asarray(
            rng.standard_normal((VOCAB, HID)).astype(np.float32) * 0.1),
        "out_w": jnp.asarray(
            rng.standard_normal((HID, 1)).astype(np.float32) * 0.1),
    }


def loss_fn(params, batch, rng):
    emb = params["embedding"][batch["ids"]]        # [B, L, H]
    pooled = jnp.mean(emb, axis=1)                 # [B, H]
    pred = pooled @ params["out_w"]                # [B, 1]
    return jnp.mean((pred - batch["y"]) ** 2)


_TRUE = np.random.default_rng(1234).standard_normal(VOCAB).astype(np.float32)


def make_batch(i, n=32, rows=8):
    """Each batch touches only ``rows`` distinct vocab rows — the regime
    sparse gradients exist for. Targets are a learnable function of the
    touched rows."""
    r = np.random.default_rng(i)
    ids = r.integers(0, rows, size=(n, 4))
    y = _TRUE[ids].mean(axis=1, keepdims=True)
    return {"ids": jnp.asarray(ids), "y": jnp.asarray(y)}


def _cfg(sparse, **over):
    cfg = {
        "train_batch_size": 32,
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "sparse_gradients": sparse,
        "steps_per_print": 10 ** 9,
    }
    cfg.update(over)
    return cfg


def test_engine_detects_embedding_leaves():
    eng = DeepSpeedEngine(model=loss_fn, model_params=model_params(),
                          config=_cfg(True), mesh=build_mesh())
    assert eng._sparse_names and "embedding" in eng._sparse_names[0]
    # 1-D / non-embedding leaves are not marked
    flat = jax.tree_util.tree_leaves(eng._sparse_mask)
    assert sum(flat) == 1


def test_sparse_parity_with_dense_allreduce():
    """N steps with the CSR path == N steps with dense allreduce."""
    mesh = build_mesh()
    eng_s = DeepSpeedEngine(model=loss_fn, model_params=model_params(),
                            config=_cfg(True), mesh=mesh)
    eng_d = DeepSpeedEngine(model=loss_fn, model_params=model_params(),
                            config=_cfg(False), mesh=mesh)
    for i in range(5):
        b = make_batch(i)
        ls = float(jax.device_get(eng_s.train_batch(b)))
        ld = float(jax.device_get(eng_d.train_batch(b)))
        np.testing.assert_allclose(ls, ld, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(
                        jax.device_get(eng_s.state.params)),
                    jax.tree_util.tree_leaves(
                        jax.device_get(eng_d.state.params))):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_sparse_comm_volume_savings():
    """The shipped CSR payload is a fraction of the dense tensor when the
    batch touches few rows (reference's raison d'être for the path)."""
    eng = DeepSpeedEngine(model=loss_fn, model_params=model_params(),
                          config=_cfg(True), mesh=build_mesh())
    eng.train_batch(make_batch(0, rows=8))
    st = eng.sparse_comm_stats
    assert st["sparse_elements"] > 0
    # 8 touched rows of 512 -> ~1/64 of the elements (plus index overhead)
    assert st["sparse_elements"] < 0.25 * st["dense_elements"]


def test_sparse_grad_norm_and_clip_reported():
    eng = DeepSpeedEngine(model=loss_fn, model_params=model_params(),
                          config=_cfg(True, gradient_clipping=1.0),
                          mesh=build_mesh())
    eng.train_batch(make_batch(0))
    # metrics come back through _maybe_log's contract: loss finite
    loss = float(jax.device_get(eng.train_batch(make_batch(1))))
    assert np.isfinite(loss)


def test_sparse_gradients_gates():
    mesh = build_mesh()
    with pytest.raises(ValueError):
        DeepSpeedEngine(model=loss_fn, model_params=model_params(),
                        config=_cfg(True, zero_optimization={"stage": 1}),
                        mesh=mesh)
    with pytest.raises(ValueError):
        DeepSpeedEngine(
            model=loss_fn, model_params=model_params(),
            config=_cfg(True, optimizer={"type": "OneBitAdam",
                                         "params": {"lr": 1e-3}}),
            mesh=mesh)


def test_sparse_custom_filter():
    eng = DeepSpeedEngine(
        model=loss_fn, model_params=model_params(), config=_cfg(True),
        mesh=build_mesh(),
        sparse_grad_filter=lambda path, leaf: "out_w" in path)
    assert eng._sparse_names == ["['out_w']"] or "out_w" in eng._sparse_names[0]
    loss = float(jax.device_get(eng.train_batch(make_batch(0))))
    assert np.isfinite(loss)


def test_sparse_fp16_parity_with_dense_fp16():
    """fp16 x sparse_gradients (reference runs its CSR allreduce in its
    default fp16 world, engine.py:1197-1253): the host exchange unscales
    the CSR values and the apply step unscales the dense leaves — N steps
    of the fp16 CSR path == N steps of the fp16 dense path."""
    mesh = build_mesh()
    fp16 = {"enabled": True, "loss_scale": 1024}
    eng_s = DeepSpeedEngine(model=loss_fn, model_params=model_params(),
                            config=_cfg(True, fp16=fp16), mesh=mesh)
    eng_d = DeepSpeedEngine(model=loss_fn, model_params=model_params(),
                            config=_cfg(False, fp16=fp16), mesh=mesh)
    for i in range(5):
        b = make_batch(i)
        ls = float(jax.device_get(eng_s.train_batch(b)))
        ld = float(jax.device_get(eng_d.train_batch(b)))
        np.testing.assert_allclose(ls, ld, rtol=5e-3, atol=5e-4)
    for a, b in zip(jax.tree_util.tree_leaves(
                        jax.device_get(eng_s.state.params)),
                    jax.tree_util.tree_leaves(
                        jax.device_get(eng_d.state.params))):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-5)


def test_sparse_fp16_overflow_votes_and_skips():
    """A loss scale far beyond fp16 range produces inf in the backward;
    the overflow vote must include the sparse (host-exchanged) leaves and
    the step must be skipped with params untouched."""
    eng = DeepSpeedEngine(
        model=loss_fn, model_params=model_params(),
        config=_cfg(True, fp16={"enabled": True, "loss_scale": 2 ** 32}),
        mesh=build_mesh())
    p0 = jax.device_get(eng.state.params)
    eng.train_batch(make_batch(0))
    p1 = jax.device_get(eng.state.params)
    for a, b in zip(jax.tree_util.tree_leaves(p0),
                    jax.tree_util.tree_leaves(p1)):
        np.testing.assert_array_equal(a, b)
    assert int(jax.device_get(eng.state.skipped_steps)) == 1


def test_sparse_trains_to_convergence():
    eng = DeepSpeedEngine(model=loss_fn, model_params=model_params(),
                          config=_cfg(True), mesh=build_mesh())
    losses = [float(jax.device_get(eng.train_batch(make_batch(i))))
              for i in range(30)]
    assert losses[-1] < 0.5 * losses[0]


def test_sparse_logging_every_step():
    """Regression (round-5 advisor, high): the sparse apply DONATES the
    engine state, and metrics['loss_scale'] used to return the donated
    (deleted) loss-scale buffer — any sparse run with steps_per_print=1
    crashed inside _maybe_log's device_get. The scale must come back as a
    traced output of the jitted apply, like the main train step."""
    eng = DeepSpeedEngine(model=loss_fn, model_params=model_params(),
                          config=_cfg(True, steps_per_print=1),
                          mesh=build_mesh())
    for i in range(2):
        loss = eng.train_batch(make_batch(i))
    assert np.isfinite(float(jax.device_get(loss)))
    assert eng.loss_scale() == 1.0
