"""ZeRO stage 3: parameter partitioning with prefetch-overlapped gathers.

The reference hard-stops at stage 2 (engine.py:707-708); this suite pins
the TPU-native stage 3 (runtime/zero/stage3.py):

- params (and cast cache) born dp-sharded on the grad/moment-aligned
  rule, so the optimizer apply is shard-local;
- one-step parity with stage 2 is BIT-identical at prefetch_depth=0
  (params AND moments) across fp32 / fp16 masters / master-free bf16 /
  gas>1 — the explicit gather's custom transpose performs the same
  widen-then-f32-reduce-scatter as the stage-2 explicit path;
- the stacked-layer scan gathers each layer one-ahead INSIDE the loop
  (compiled-HLO placement), prefetch depths are bit-identical to each
  other, and the trajectory matches stage 2 to the documented
  cross-program f32-ulp class (PR-1/PR-3 precedent);
- the analysis/ materialization pass is the correctness gate: the
  stage-3 programs audit clean against declared state + the bounded
  gather working set, and a seeded violation (the gathered tree
  concatenated into one buffer) fires it;
- the HLO audit prices per-step gather bytes on the (g-1)/g ring model
  and confirms grads lower to reduce-scatter, never a grad-sized
  all-reduce.
"""
import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.parallel import hlo_audit
from deepspeed_tpu.runtime.zero.config import ZeroConfig
from deepspeed_tpu.runtime.zero.partition import stage3_param_specs
from deepspeed_tpu.runtime.zero.stage3 import (Zero3Scan,
                                               gather_working_set_bytes)

from simple_model import (simple_model_params, simple_loss_fn, random_batch,
                          base_config)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _engine(stage, gas=1, seed=0, zextra=None, extra_cfg=None):
    params = simple_model_params(jax.random.PRNGKey(seed))
    z = {"stage": stage}
    if zextra:
        z.update(zextra)
    cfg = base_config(zero_optimization=z,
                      gradient_accumulation_steps=gas,
                      train_batch_size=16 * gas)
    if extra_cfg:
        cfg.update(extra_cfg)
    engine, *_ = deepspeed_tpu.initialize(
        model=simple_loss_fn, model_params=params, config=cfg)
    return engine


def _traj(engine, n=5):
    gas = engine.gradient_accumulation_steps()
    return [float(engine.train_batch(batch=random_batch(n=16 * gas,
                                                        seed=100 + i)))
            for i in range(n)]


def _params_bit_equal(a, b):
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree_util.tree_leaves(jax.device_get(a)),
                               jax.tree_util.tree_leaves(jax.device_get(b))))


# ------------------------------------------------------------------ #
# Config surface
# ------------------------------------------------------------------ #
class TestStage3Config:
    def test_stage3_accepted(self):
        zc = ZeroConfig({"zero_optimization": {"stage": 3}})
        assert zc.stage == 3
        assert zc.prefetch_depth == 1     # default

    def test_prefetch_depth_validated(self):
        zc = ZeroConfig({"zero_optimization": {"stage": 3,
                                               "prefetch_depth": 0}})
        assert zc.prefetch_depth == 0
        with pytest.raises(ValueError, match="prefetch_depth"):
            ZeroConfig({"zero_optimization": {"stage": 3,
                                              "prefetch_depth": -1}})

    def test_stage3_requires_reduce_scatter(self):
        with pytest.raises(ValueError, match="reduce_scatter"):
            ZeroConfig({"zero_optimization": {"stage": 3,
                                              "reduce_scatter": False}})

    def test_stage4_still_rejected(self):
        with pytest.raises(ValueError, match="stage"):
            ZeroConfig({"zero_optimization": {"stage": 4}})


# ------------------------------------------------------------------ #
# Born-sharded layout
# ------------------------------------------------------------------ #
class TestStage3Layout:
    def test_params_born_dp_sharded(self):
        e = _engine(3)
        w1 = e.state.params["w1"]            # [8, 16]
        assert "data" in str(w1.sharding.spec)
        assert w1.addressable_shards[0].data.shape == (1, 16)
        # non-divisible leaf stays replicated
        assert "data" not in str(e.state.params["b2"].sharding.spec)

    def test_grads_moments_params_element_aligned(self):
        """Grad shardings == param shardings (the shard-local-update
        invariant), and param-structured moments mirror them."""
        e = _engine(3)
        gsh = e._grad_shardings()
        psh = e._state_shardings.params
        for g, p in zip(jax.tree_util.tree_leaves(gsh),
                        jax.tree_util.tree_leaves(psh)):
            assert g.spec == p.spec

    def test_analytic_state_prices_sharded_params(self):
        """monitor/memory.analytic_state_bytes prices stage-3 params at
        1/dp, not the replicated figure (the watermark satellite)."""
        from deepspeed_tpu.monitor.memory import analytic_state_bytes
        e3, e0 = _engine(3), _engine(0)
        w1_full = 8 * 16 * 4
        b3 = analytic_state_bytes(e3.state)
        b0 = analytic_state_bytes(e0.state)
        # stage 0 replicates everything; stage 3 shards params+moments.
        assert b3 < b0
        # spot check: w1's contribution is exactly its shard
        s3 = analytic_state_bytes({"w": e3.state.params["w1"]})
        assert s3 == w1_full // 8
        # the gather working set rides on top
        assert analytic_state_bytes(e3.state, gather_working_set=123) == \
            b3 + 123

    def test_watermark_meta_carries_gather_working_set(self):
        e = _engine(3, extra_cfg={"telemetry": {"enabled": False}})
        # meta only exists with telemetry on; check the engine-side math
        ws = gather_working_set_bytes(
            e.state.params, e._stage3_specs, "data", 4, prefetch_depth=0)
        # every sharded float leaf gathers at full size (generic path)
        expect = (8 * 16 + 16 + 16 * 4) * 4
        assert ws == expect

    def test_scan_paths_avoid_layer_axis(self):
        """stage3_param_specs keeps dim 0 of covered (stacked) leaves
        unsharded so per-layer slices stay dp-sharded."""
        params = {"blocks": {"k": jnp.zeros((8, 16, 16))},
                  "emb": jnp.zeros((8, 16))}
        specs = stage3_param_specs(params, 8, "data",
                                   scan_paths=lambda p: "blocks" in p)
        assert specs["blocks"]["k"] == P(None, "data", None)
        assert specs["emb"] == P("data", None)


# ------------------------------------------------------------------ #
# Parity with stage 2 (the acceptance gate)
# ------------------------------------------------------------------ #
class TestStage3Parity:
    @pytest.mark.parametrize("extra_cfg", [
        {},                                              # fp32
        {"fp16": {"enabled": True}},                     # fp16 masters
        {"bf16": {"enabled": True,
                  "stochastic_rounding": True}},         # master-free
    ], ids=["fp32", "fp16", "bf16_master_free"])
    def test_one_step_and_trajectory_bit_identical(self, extra_cfg):
        """Same seed/batches: stage-3 params AND moments are
        BIT-identical to stage 2's, across the precision matrix — the
        gather's custom transpose performs the same
        widen-then-f32-reduce-scatter the stage-2 explicit path does."""
        e3 = _engine(3, extra_cfg=extra_cfg)
        e2 = _engine(2, extra_cfg=extra_cfg)
        t3, t2 = _traj(e3, 4), _traj(e2, 4)
        assert t3 == t2
        assert _params_bit_equal(e3.state.params, e2.state.params)
        assert _params_bit_equal(e3.state.opt_state, e2.state.opt_state)

    def test_gas_accumulation_parity(self):
        e3, e2 = _engine(3, gas=2), _engine(2, gas=2)
        assert _traj(e3, 3) == _traj(e2, 3)
        assert _params_bit_equal(e3.state.params, e2.state.params)

    def test_declarative_mode_close(self):
        """Forced-declarative stage 3 (the GSPMD path this backend
        regresses for grads but still runs correctly) tracks stage 2 to
        the cross-program tolerance."""
        e3 = _engine(3, zextra={"grad_sync": "declarative"})
        assert e3._grad_sync_mode == "declarative"
        e2 = _engine(2, zextra={"grad_sync": "declarative"})
        np.testing.assert_allclose(_traj(e3, 3), _traj(e2, 3), rtol=1e-6)

    def test_trio_forward_backward_step(self):
        """The torch-style trio runs the stage-3 gather path too."""
        e3, e2 = _engine(3), _engine(2)
        for e in (e3, e2):
            for i in range(2):
                b = random_batch(n=16, seed=200 + i)
                e.forward(b)
                e.backward()
                e.step()
        assert _params_bit_equal(e3.state.params, e2.state.params)

    def test_pipeline_grads_fn_rejected(self):
        with pytest.raises(ValueError, match="stage 3"):
            deepspeed_tpu.runtime.engine.DeepSpeedEngine(
                model=simple_loss_fn,
                model_params=simple_model_params(jax.random.PRNGKey(0)),
                config=base_config(zero_optimization={"stage": 3}),
                grads_fn=lambda p, b, r, s: (jnp.asarray(0.0), p))


# ------------------------------------------------------------------ #
# Offload composition (+ the retired waiver)
# ------------------------------------------------------------------ #
class TestStage3Offload:
    def test_offload_grad_sync_now_explicit(self):
        """The offload grad pass routes through the explicit
        psum_scatter builder — the regression the last lint waiver
        covered no longer compiles (the waiver file is empty)."""
        e = _engine(2, zextra={"cpu_offload": True})
        assert e._grad_sync_mode == "explicit"
        with open(os.path.join(REPO, "tools", "lint_waivers.json")) as f:
            assert json.load(f)["waivers"] == []

    def test_offload_stage3_device_params_sharded(self):
        """offload + stage 3: host-resident masters AND dp-sharded
        device params — the headline memory composition."""
        e3 = _engine(3, zextra={"cpu_offload": True})
        assert "data" in str(e3.state.params["w1"].sharding.spec)
        e2 = _engine(2, zextra={"cpu_offload": True})
        np.testing.assert_allclose(_traj(e3, 3), _traj(e2, 3), rtol=1e-6)


# ------------------------------------------------------------------ #
# The stacked-layer prefetched scan (gpt2)
# ------------------------------------------------------------------ #
def _gpt2_engine(stage, prefetch=1, with_spec=True, seed=0, layers=4):
    from deepspeed_tpu.models.gpt2 import (GPT2_CONFIGS, gpt2_init,
                                           gpt2_loss_fn)
    cfg = dataclasses.replace(
        GPT2_CONFIGS["gpt2-tiny"], num_layers=layers, dtype=jnp.float32,
        hidden_dropout=0.0, attn_dropout=0.0, fused_kernels=False)
    spec = Zero3Scan() if (with_spec and stage >= 3) else None
    params = gpt2_init(jax.random.PRNGKey(seed), cfg)
    ds_cfg = {"train_batch_size": 16, "gradient_accumulation_steps": 1,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
              "zero_optimization": {"stage": stage,
                                    "prefetch_depth": prefetch},
              "steps_per_print": 10 ** 9}
    engine, *_ = deepspeed_tpu.initialize(
        model=gpt2_loss_fn(cfg, zero3=spec), model_params=params,
        config=ds_cfg, zero3_scan=spec)
    return engine, spec


def _gpt2_tokens(seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 512, size=(16, 33)).astype(np.int32)


class TestZero3LayerScan:
    def test_spec_binding(self):
        e, spec = _gpt2_engine(3, prefetch=1)
        assert spec.mode == "explicit"
        assert spec.prefetch_depth == 1
        # stacked [L, H, 3H] sharded on H -> per-layer gather dim 0
        assert spec.layer_info["qkv_kernel"][0] == 0
        assert e.state.params["blocks"]["qkv_kernel"].sharding.spec == \
            P(None, "data", None)

    def test_prefetch_depths_bit_identical(self):
        """prefetch_depth is pure schedule: 0, 1 and 2 produce
        bit-identical trajectories and params (a gather moves values,
        never arithmetic)."""
        tokens = _gpt2_tokens()
        engines = [_gpt2_engine(3, prefetch=d)[0] for d in (0, 1, 2)]
        trajs = [[float(e.train_batch(batch=tokens)) for _ in range(3)]
                 for e in engines]
        assert trajs[0] == trajs[1] == trajs[2]
        assert _params_bit_equal(engines[0].state.params,
                                 engines[1].state.params)
        assert _params_bit_equal(engines[0].state.params,
                                 engines[2].state.params)

    def test_trajectory_matches_stage2(self):
        """Stage 3 layer scan vs stage 2 on the same model: ≤1e-7 — the
        manual-VJP scan recomputes each layer's forward (remat), which
        re-associates fusions; the documented PR-1/PR-3 cross-program
        f32-ulp class, not a numerics change."""
        tokens = _gpt2_tokens()
        e3, _ = _gpt2_engine(3, prefetch=0)
        e2, _ = _gpt2_engine(2)
        t3 = [float(e3.train_batch(batch=tokens)) for _ in range(3)]
        t2 = [float(e2.train_batch(batch=tokens)) for _ in range(3)]
        np.testing.assert_allclose(t3, t2, rtol=1e-7)
        for a, b in zip(
                jax.tree_util.tree_leaves(jax.device_get(e3.state.params)),
                jax.tree_util.tree_leaves(jax.device_get(e2.state.params))):
            # Adam's sqrt(v) normalization amplifies ulp-level grad
            # differences into lr-scale update differences wherever v is
            # still near zero (a handful of elements in early steps), so
            # the param bound is a few lr quanta, not grad ulp — the
            # loss-trajectory 1e-7 assertion above is the tight gate.
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       rtol=1e-4, atol=3e-5)

    def test_layer_gathers_inside_scan_loop(self):
        """Compiled-HLO placement: the per-layer all-gathers run inside
        the while body (once per layer trip), grads reduce-scatter in
        the backward scan, and NO gather ever carries a full stacked
        tensor."""
        e, _ = _gpt2_engine(3, prefetch=1)
        tokens = _gpt2_tokens()
        mb = e._stack_micro_batches(tokens)
        mb = jax.device_put(mb, e._batch_sharding(mb, leading_dims=2))
        audit = hlo_audit.audit_jit(e._build_train_step(), e.state, mb,
                                    e._base_rng)
        ag = audit.of_kind("all-gather")
        assert any(o.in_loop for o in ag)
        assert any(o.in_loop for o in audit.of_kind("reduce-scatter"))
        blocks = jax.device_get(e.state.params)["blocks"]
        biggest_stacked = max(int(np.prod(l.shape)) * 4
                              for l in jax.tree_util.tree_leaves(blocks))
        assert all(o.payload_bytes < biggest_stacked for o in ag)

    def test_unbound_spec_falls_back_to_normal_scan(self):
        """A loss built with a Zero3Scan that the engine never bound
        (e.g. the same loss_fn run at stage 2) takes the normal layer
        scan — the spec only reroutes once an engine binds it."""
        from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                      apply_blocks,
                                                      init_block_params)
        cfg = TransformerConfig(hidden_size=32, num_heads=2, num_layers=2,
                                max_seq_length=16, vocab_size=64,
                                dtype=jnp.float32, fused_kernels=False)
        stacked = init_block_params(jax.random.PRNGKey(0), cfg)
        x = jnp.ones((2, 8, 32), jnp.float32)
        plain = apply_blocks(stacked, x, cfg)
        with_spec = apply_blocks(stacked, x, cfg, zero3=Zero3Scan())
        np.testing.assert_array_equal(np.asarray(plain),
                                      np.asarray(with_spec))

    def test_pld_rejected_under_zero3_scan(self):
        from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                      apply_blocks,
                                                      init_block_params)
        cfg = TransformerConfig(hidden_size=32, num_heads=2, num_layers=2,
                                max_seq_length=16, vocab_size=64,
                                dtype=jnp.float32, fused_kernels=False)
        spec = Zero3Scan()
        spec.bind(mode="explicit", mesh=None, axis_name="data",
                  compute_dtype=jnp.float32, prefetch_depth=1,
                  layer_info={})
        stacked = init_block_params(jax.random.PRNGKey(0), cfg)
        x = jnp.zeros((2, 8, 32), jnp.float32)
        with pytest.raises(ValueError, match="layer drop"):
            apply_blocks(stacked, x, cfg, zero3=spec,
                         rng=jax.random.PRNGKey(0), deterministic=False,
                         pld_theta=jnp.asarray(0.5))


# ------------------------------------------------------------------ #
# The materialization gate (acceptance) + HLO audit pricing
# ------------------------------------------------------------------ #
class TestZero3Audits:
    def _lint(self, engine):
        rep = engine.lint_audit()
        assert not rep.errors, rep.errors
        return rep

    def test_stage3_lints_clean(self, tmp_path):
        """No compiled stage-3 path materializes more than declared
        state + the bounded gather working set (and every donation
        aliases, no host syncs, collectives placed right)."""
        params = simple_model_params(jax.random.PRNGKey(0))
        cfg = base_config(
            zero_optimization={"stage": 3},
            telemetry={"enabled": True, "output_path": str(tmp_path),
                       "job_name": "z3", "report_steps": 10 ** 9})
        e, *_ = deepspeed_tpu.initialize(model=simple_loss_fn,
                                         model_params=params, config=cfg)
        for i in range(2):
            e.train_batch(batch=random_batch(n=16, seed=i))
        rep = self._lint(e)
        assert not rep.findings, [f.fingerprint for f in rep.findings]
        meta = e._lint_path_meta("train_step")
        assert meta["zero3"] and meta["zero3_gather_bytes"] > 0
        e.telemetry.close()

    def test_seeded_tree_scale_gather_fires_gate(self, mesh8):
        """The gate can fire: gathering every shard and CONCATENATING
        into one tree-scale buffer (the 'XLA materialized the full
        tree' failure) is flagged even with the stage-3 gather budget in
        meta — the budget covers per-leaf gathers, not tree-scale
        concats."""
        from deepspeed_tpu.analysis.auditor import lint_jit
        sh = NamedSharding(mesh8, P("data"))
        leaves = [jax.device_put(jnp.ones((4096,), jnp.float32), sh)
                  for _ in range(4)]

        def gather_concat(*ls):
            full = jnp.concatenate([
                lax.with_sharding_constraint(l, NamedSharding(mesh8, P()))
                for l in ls])
            return full * 2.0

        nbytes = 4096 * 4
        meta = {"declared_state_bytes": 4 * nbytes // 8,
                "largest_leaf_bytes": nbytes,
                "zero3": True,
                # budget: every leaf gathered at use — but NOT concat'd
                "zero3_gather_bytes": nbytes}
        res = lint_jit(jax.jit(gather_concat), *leaves,
                       name="seeded_zero3_gather", meta=meta,
                       passes=["materialization"])
        assert not res.errors, res.errors
        assert any(f.lint == "materialization" and f.bytes >= 4 * nbytes
                   for f in res.findings), \
            [f.fingerprint for f in res.findings]

    def test_gather_bytes_priced_within_5pct(self):
        """Compiled all-gather wire vs the analytic (g-1)/g model."""
        e = _engine(3)
        mb = e._stack_micro_batches(random_batch(n=16))
        mb = jax.device_put(mb, e._batch_sharding(mb, leading_dims=2))
        audit = hlo_audit.audit_jit(e._build_train_step(), e.state, mb,
                                    e._base_rng)
        model = hlo_audit.grad_sync_wire_model(
            jax.device_get(e.state.params), e.dp_size, zero3=True,
            param_bytes_per_el=4, gas=1, param_specs=e._stage3_specs)
        ag_wire = sum(o.wire_bytes for o in audit.of_kind("all-gather"))
        ag_payload = sum(o.payload_bytes
                         for o in audit.of_kind("all-gather"))
        one = hlo_audit.ring_wire_bytes(
            "all-gather", model["param_gather_payload_bytes"], e.dp_size)
        gathers = round(ag_payload /
                        max(1, model["param_gather_payload_bytes"]))
        # Declared schedule: 2 gathers (fwd + bwd re-gather); XLA may
        # CSE the pair into one held buffer. Either way the wire prices
        # on the ring model to 5%.
        assert 1 <= gathers <= model["param_gathers_per_step"]
        assert abs(ag_wire - gathers * one) <= 0.05 * max(1, ag_wire)

    def test_grads_lower_to_reduce_scatter_not_allreduce(self):
        e = _engine(3)
        mb = e._stack_micro_batches(random_batch(n=16))
        mb = jax.device_put(mb, e._batch_sharding(mb, leading_dims=2))
        audit = hlo_audit.audit_jit(e._build_train_step(), e.state, mb,
                                    e._base_rng)
        model = hlo_audit.grad_sync_wire_model(
            jax.device_get(e.state.params), e.dp_size, zero3=True,
            param_specs=e._stage3_specs)
        rs_payload = sum(o.payload_bytes
                         for o in audit.of_kind("reduce-scatter"))
        assert rs_payload == model["scatterable_bytes"]
        biggest = max(int(np.prod(l.shape)) * 4 for l in
                      jax.tree_util.tree_leaves(
                          jax.device_get(e.state.params)))
        assert not [o for o in audit.of_kind("all-reduce")
                    if o.payload_bytes >= biggest]

    def test_wire_model_zero3_terms(self):
        params = {"w": jnp.zeros((64, 64)), "b": jnp.zeros((3,))}
        m = hlo_audit.grad_sync_wire_model(params, 8, zero3=True,
                                           param_bytes_per_el=2, gas=2)
        one_gather = hlo_audit.ring_wire_bytes(
            "all-gather", 64 * 64 * 2, 8)
        assert m["param_gather_payload_bytes"] == 64 * 64 * 2
        assert m["param_gathers_per_step"] == 4          # 2 per micro-step
        assert m["param_gather_wire_bytes"] == 4 * one_gather
        assert m["zero3_wire_bytes"] == \
            2 * (m["reduce_scatter_wire_bytes"] + 2 * one_gather)


# ------------------------------------------------------------------ #
# The bench record (tooling satellite)
# ------------------------------------------------------------------ #
class TestZero3Bench:
    def test_zero3_bench_shape_and_gate(self):
        """ZERO3_BENCH.json parses through bench_gate's extractor and
        self-gates OK (the CI shape contract)."""
        path = os.path.join(REPO, "ZERO3_BENCH.json")
        assert os.path.isfile(path), "run ablate_zero3_prefetch.py --record"
        with open(path) as f:
            doc = json.load(f)
        assert doc["measured_cpu"]["parity"] is True
        assert 0.0 <= doc["zero3"]["overlap_fraction"] <= 1.0
        assert doc["zero3"]["memory_headroom_fraction"] > 0
        assert doc["projected"] is True        # honestly labeled
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "bench_gate", os.path.join(REPO, "tools", "bench_gate.py"))
        bg = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bg)
        m = bg.extract_metrics(doc)
        assert m["zero3_overlap"] == doc["zero3"]["overlap_fraction"]
        assert bg.gate(path, path, 0.10, 0.05) == 0

    def test_gather_working_set_scales_with_prefetch(self):
        params = {"blocks": {"k": jnp.zeros((4, 16, 16))},
                  "emb": jnp.zeros((8, 16))}
        specs = stage3_param_specs(params, 8, "data",
                                   scan_paths=lambda p: "blocks" in p)
        ws0 = gather_working_set_bytes(params, specs, "data", 4,
                                       prefetch_depth=0,
                                       scan_paths=lambda p: "blocks" in p)
        ws2 = gather_working_set_bytes(params, specs, "data", 4,
                                       prefetch_depth=2,
                                       scan_paths=lambda p: "blocks" in p)
        layer = 16 * 16 * 4
        emb = 8 * 16 * 4
        assert ws0 == emb + layer
        assert ws2 == emb + 3 * layer
