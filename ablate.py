"""Perf ablation harness (dev tool, not shipped API).

Times one train-step variant on the real chip and prints ms/step + TFLOPs.
Usage: python ablate.py <variant>
variants: base | remat_none | lse_ce | chunk_ce | chunk_ce_none | dense_attn
"""
import dataclasses
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deepspeed_tpu.models import GPT2_CONFIGS
from deepspeed_tpu.models.gpt2 import (gpt2_apply, gpt2_init,
                                       gpt2_flops_per_token)
from deepspeed_tpu.models.transformer import dense_attention

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "base"
MODEL = sys.argv[2] if len(sys.argv) > 2 else "gpt2-medium"
MBS = int(sys.argv[3]) if len(sys.argv) > 3 else 4

remat = "none" if VARIANT in ("remat_none", "chunk_ce_none") else "dots"
if VARIANT.endswith("_full"):
    remat = "full"
cfg = dataclasses.replace(GPT2_CONFIGS[MODEL], max_seq_length=1024,
                          remat_policy=remat, hidden_dropout=0.0,
                          attn_dropout=0.0,
                          scan_layers="unroll" not in VARIANT)

attention_fn = dense_attention if VARIANT == "dense_attn" else None


def ce_full(logits, targets):
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def ce_lse(logits, targets):
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - tgt.astype(jnp.float32))


from deepspeed_tpu.ops.cross_entropy import chunked_softmax_xent


def make_loss(variant):
    def loss_fn(params, batch, rng):
        tokens, targets = batch[:, :-1], batch[:, 1:]
        if variant.startswith("chunk_ce"):
            B, S = tokens.shape
            x = params["wte"].astype(cfg.dtype)[tokens] + \
                params["wpe"].astype(cfg.dtype)[None, :S]
            from deepspeed_tpu.models.transformer import apply_blocks, layer_norm
            x = apply_blocks(params["blocks"], x, cfg, rng=rng,
                             deterministic=False, attention_fn=attention_fn)
            x = layer_norm(x, params["ln_f_scale"], params["ln_f_bias"],
                           cfg.layer_norm_eps)
            return chunked_softmax_xent(x.reshape(B * S, -1),
                                        params["wte"].astype(cfg.dtype),
                                        targets.reshape(-1), 4)
        logits = gpt2_apply(params, tokens, cfg, rng=rng, deterministic=False,
                            attention_fn=attention_fn)
        if variant == "lse_ce":
            return ce_lse(logits, targets)
        return ce_full(logits, targets)
    return loss_fn


def main():
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    tx = optax.adamw(1e-4)
    opt_state = tx.init(params)
    loss_fn = make_loss(VARIANT)

    def cast(p):
        return jax.tree_util.tree_map(
            lambda a: a.astype(cfg.dtype) if a.dtype == jnp.float32 else a, p)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, batch, rng):
        def scaled(p):
            return loss_fn(cast(p), batch, rng)
        loss, grads = jax.value_and_grad(scaled)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    S = cfg.max_seq_length
    batch = jnp.asarray(np.random.randint(0, cfg.vocab_size,
                                          size=(MBS, S + 1), dtype=np.int32))
    rng = jax.random.PRNGKey(1)
    t0 = time.perf_counter()
    params, opt_state, loss = step(params, opt_state, batch, rng)
    print(f"compile+1st: {time.perf_counter()-t0:.1f}s loss={float(loss):.3f}")
    n = 20
    t0 = time.perf_counter()
    for _ in range(n):
        params, opt_state, loss = step(params, opt_state, batch, rng)
    _ = float(loss)
    dt = (time.perf_counter() - t0) / n
    tok = MBS * S
    tf = tok / dt * gpt2_flops_per_token(cfg, S) / 1e12
    from bench import chip_peak_tflops
    peak = chip_peak_tflops()
    print(f"{VARIANT} {MODEL} mbs={MBS}: {dt*1000:.1f} ms/step, "
          f"{tf:.1f} TFLOPs ({tf/peak*100:.1f}% peak)")


if __name__ == "__main__":
    main()
