"""Dev tool: differential component timing of the bench train step.

Measures full step, no-optimizer, fwd-only, attention-stubbed, and
headless variants (all with the chunked CE, so gpt2-large fits HBM) and
reports the deltas: optimizer, backward, attention, CE-head shares.
Usage: python ablate_parts.py [model] [mbs]
"""
import dataclasses
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deepspeed_tpu.models import GPT2_CONFIGS
from deepspeed_tpu.models.gpt2 import (gpt2_flops_per_token, gpt2_init,
                                       gpt2_loss_fn)

MODEL = sys.argv[1] if len(sys.argv) > 1 else "gpt2-large"
MBS = int(sys.argv[2]) if len(sys.argv) > 2 else 4

cfg = dataclasses.replace(GPT2_CONFIGS[MODEL], max_seq_length=1024,
                          remat_policy="dots", hidden_dropout=0.0,
                          attn_dropout=0.0, scan_layers=False)
S = cfg.max_seq_length
tx = optax.adamw(1e-4)


def attn_stub(q, k, v, **kw):
    # Stand-in with ~zero FLOPs but the right shape/dtype; keeps qkv+proj
    # matmuls so the delta vs base isolates the attention inner product.
    return v


def make_loss(attention_fn=None, headless=False):
    base = gpt2_loss_fn(cfg, attention_fn=attention_fn)
    if not headless:
        return base

    from deepspeed_tpu.models.gpt2 import gpt2_hidden

    def loss_fn(params, batch, rng):
        tokens = batch[:, :-1]
        x = gpt2_hidden(params, tokens, cfg, rng=rng, deterministic=False,
                        attention_fn=attention_fn)
        return jnp.mean(x.astype(jnp.float32) ** 2)
    return loss_fn


def cast(p):
    return jax.tree_util.tree_map(
        lambda a: a.astype(cfg.dtype) if a.dtype == jnp.float32 else a, p)


def sync(out):
    # Tunneled backends can return early from block_until_ready; a host
    # read of a scalar leaf cannot (same trick as bench.py).
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(jax.device_get(jnp.sum(leaf) if leaf.ndim else leaf))


def timeit(fn, args, n=20):
    out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / n * 1000


def main():
    # NOTE: no optimizer state here — adamw state (2x fp32 params) plus the
    # non-donated step double-buffers would OOM gpt2-large on one chip.
    # Optimizer time = (full-step time from ablate_flash/bench) - fwd+bwd.
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    batch = jnp.asarray(np.random.randint(0, cfg.vocab_size,
                                          size=(MBS, S + 1), dtype=np.int32))
    rng = jax.random.PRNGKey(1)

    def gradonly(loss_fn):
        @jax.jit
        def step(params, batch, rng):
            return jax.value_and_grad(
                lambda p: loss_fn(cast(p), batch, rng))(params)
        return step

    def fwdonly(loss_fn):
        @jax.jit
        def step(params, batch, rng):
            return loss_fn(cast(params), batch, rng)
        return step

    base_loss = make_loss()
    stub_loss = make_loss(attention_fn=attn_stub)
    head_loss = make_loss(headless=True)

    t_grad = timeit(gradonly(base_loss), (params, batch, rng))
    t_fwd = timeit(fwdonly(base_loss), (params, batch, rng))
    t_grad_stub = timeit(gradonly(stub_loss), (params, batch, rng))
    t_grad_head = timeit(gradonly(head_loss), (params, batch, rng))

    tok = MBS * S
    fl = tok * gpt2_flops_per_token(cfg, S) / 1e12
    print(f"{MODEL} mbs={MBS} ({fl:.1f} TF/step)")
    print(f"  fwd+bwd          : {t_grad:7.1f} ms")
    print(f"  fwd only         : {t_fwd:7.1f} ms   -> backward  {t_grad-t_fwd:6.1f} ms")
    print(f"  fwd+bwd attn-stub: {t_grad_stub:7.1f} ms   -> attention {t_grad-t_grad_stub:6.1f} ms")
    print(f"  fwd+bwd headless : {t_grad_head:7.1f} ms   -> CE head   {t_grad-t_grad_head:6.1f} ms")


if __name__ == "__main__":
    main()
