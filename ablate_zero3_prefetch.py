"""ZeRO-3 prefetch ablation: prefetch_depth 0 vs 1 (ISSUE 11, dev tool).

Runs the stage-3 engine with the prefetched layer scan
(runtime/zero/stage3.py) on the dp=8 CPU mesh at ``prefetch_depth`` 0
(gather at use — the parity baseline) and 1 (the scan carries one
gathered layer so layer i+1's all-gather overlaps layer i's compute),
and records:

- **measured** CPU wall times for both depths — honestly labeled: on
  the emulated mesh the "interconnect" is memcpy, so the measured delta
  exercises the schedule, not ICI latency hiding. Parity (identical
  losses across depths) is asserted here, because a prefetch knob that
  changes numerics is a bug, not a tuning.
- the **analytic overlap fraction** on the target chip: per layer, the
  gather moves ``(g-1)/g · layer_bytes`` (compute dtype) over ICI while
  the previous layer computes ``layer_flops`` on the MXU; depth 1 hides
  ``min(t_gather, t_compute) / t_gather`` of the gather wall, depth 0
  hides nothing. Chip peaks come from monitor/peaks.py (v5e default on
  CPU, labeled assumed).
- the **analytic memory headroom**: per-device state bytes under stage
  2 vs stage 3 (+ the bounded gather working set), i.e. how much of the
  replicated-param footprint stage 3 returns — the capacity that lets a
  single slice hold past-10B-param models (ROADMAP item 1).

``--record`` writes ZERO3_BENCH.json; ``tools/bench_gate.py`` parses
its ``zero3.overlap_fraction`` (shape-tested in tests/test_zero3.py).

Usage: python ablate_zero3_prefetch.py [--layers N] [--record]
"""
import dataclasses
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = \
        _flags + " --xla_force_host_platform_device_count=8"

import jax                     # noqa: E402
import jax.numpy as jnp        # noqa: E402
import numpy as np             # noqa: E402

import deepspeed_tpu           # noqa: E402
from deepspeed_tpu.models.gpt2 import (GPT2_CONFIGS, gpt2_init,  # noqa: E402
                                       gpt2_loss_fn)
from deepspeed_tpu.monitor.memory import analytic_state_bytes  # noqa: E402
from deepspeed_tpu.monitor.peaks import chip_peaks  # noqa: E402
from deepspeed_tpu.runtime.zero.stage3 import (Zero3Scan,  # noqa: E402
                                               gather_working_set_bytes)

REPO = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(REPO, "ZERO3_BENCH.json")
RECORD = "--record" in sys.argv
LAYERS = 4
if "--layers" in sys.argv:
    LAYERS = int(sys.argv[sys.argv.index("--layers") + 1])


def build_engine(depth: int, stage: int = 3):
    cfg = dataclasses.replace(
        GPT2_CONFIGS["gpt2-tiny"], num_layers=LAYERS, dtype=jnp.float32,
        hidden_dropout=0.0, attn_dropout=0.0, fused_kernels=False)
    spec = Zero3Scan() if stage >= 3 else None
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    ds_cfg = {"train_batch_size": 16, "gradient_accumulation_steps": 1,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
              "zero_optimization": {"stage": stage,
                                    "prefetch_depth": depth},
              "steps_per_print": 10 ** 9}
    engine, *_ = deepspeed_tpu.initialize(
        model=gpt2_loss_fn(cfg, zero3=spec), model_params=params,
        config=ds_cfg, zero3_scan=spec)
    return engine, cfg


def measure(depth: int, steps: int = 8):
    engine, cfg = build_engine(depth)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size,
                          size=(16, 33)).astype(np.int32)
    losses = [float(engine.train_batch(batch=tokens))
              for _ in range(2)]           # compile + warm
    t0 = time.perf_counter()
    for _ in range(steps):
        losses.append(float(engine.train_batch(batch=tokens)))
    wall = (time.perf_counter() - t0) / steps
    return {"prefetch_depth": depth, "step_ms": round(wall * 1e3, 3),
            "losses": losses}, engine, cfg


def analytic(engine, cfg):
    """Chip-model overlap + memory headroom (no measurement)."""
    peaks = chip_peaks()
    dp = engine.dp_size
    blocks = jax.device_get(engine.state.params)["blocks"]
    layer_bytes = sum(int(np.prod(l.shape)) // l.shape[0] * 4
                      for l in jax.tree_util.tree_leaves(blocks))
    gather_bytes = (dp - 1) * layer_bytes // dp
    # Per-layer forward matmul FLOPs (the compute the depth-1 gather
    # overlaps): 2 * tokens * per-layer matmul params.
    H, F = cfg.hidden_size, cfg.ffn_size
    layer_mm_params = 4 * H * H + 2 * H * F
    tokens_per_dev = 16 * 32 // dp
    layer_flops = 2 * tokens_per_dev * layer_mm_params
    t_gather = gather_bytes / peaks.ici_bytes_per_sec
    t_compute = layer_flops / peaks.flops_per_sec
    overlap = {0: 0.0,
               1: round(min(t_gather, t_compute) / max(t_gather, 1e-12),
                        4)}
    # Memory headroom: stage-2 per-device state vs stage-3 (+ gather
    # working set at depth 1).
    e2, _ = build_engine(1, stage=2)
    s2 = analytic_state_bytes(e2.state)
    spec = engine._zero3_scan_spec
    ws = gather_working_set_bytes(
        engine.state.params, engine._stage3_specs, "data",
        jnp.dtype(engine.compute_dtype).itemsize, prefetch_depth=1,
        scan_paths=spec.covers if spec is not None else None)
    s3 = analytic_state_bytes(engine.state, gather_working_set=ws)
    return {
        "chip": {"name": peaks.name, "assumed": peaks.assumed},
        "per_layer_gather_bytes": int(gather_bytes),
        "per_layer_compute_flops": int(layer_flops),
        "t_gather_us": round(t_gather * 1e6, 3),
        "t_compute_us": round(t_compute * 1e6, 3),
        "overlap_fraction_by_depth": overlap,
        "memory": {
            "stage2_state_bytes_per_device": int(s2),
            "stage3_state_bytes_per_device": int(s3),
            "gather_working_set_bytes": int(ws),
            "headroom_fraction": round(1.0 - s3 / max(1, s2), 4),
        },
    }


def production_projection(model: str = "gpt2-large", mbs: int = 4,
                          dp: int = 8):
    """Pure config arithmetic at a production shape: per-layer bf16
    gather vs per-layer fwd compute at the chip peaks — the overlap the
    depth-1 prefetch buys on real hardware (the toy mesh above cannot
    show it: its per-layer compute is microseconds)."""
    cfg = GPT2_CONFIGS[model]
    peaks = chip_peaks()
    H, F = cfg.hidden_size, cfg.ffn_size
    layer_params = 4 * H * H + 2 * H * F
    gather_bytes = (dp - 1) * layer_params * 2 // dp    # bf16 wire
    tokens = mbs * cfg.max_seq_length
    layer_flops = 2 * tokens * layer_params
    t_gather = gather_bytes / peaks.ici_bytes_per_sec
    t_compute = layer_flops / peaks.flops_per_sec
    return {
        "model": model, "micro_batch": mbs, "dp": dp,
        "chip": {"name": peaks.name, "assumed": peaks.assumed},
        "per_layer_gather_bytes_bf16": int(gather_bytes),
        "t_gather_us": round(t_gather * 1e6, 2),
        "t_compute_us": round(t_compute * 1e6, 2),
        "overlap_fraction_depth1":
            round(min(t_gather, t_compute) / max(t_gather, 1e-12), 4),
    }


def main():
    r0, _, _ = measure(0)
    r1, engine, cfg = measure(1)
    if r0["losses"] != r1["losses"]:
        print("PARITY FAILURE: prefetch_depth changed the trajectory",
              r0["losses"], r1["losses"])
        return 1
    ana = analytic(engine, cfg)
    proj = production_projection()
    record = {
        "generated_by": "ablate_zero3_prefetch.py",
        "mesh": {"devices": jax.device_count(),
                 "backend": jax.devices()[0].platform},
        "layers": LAYERS,
        "measured_cpu": {
            "note": "CPU-mesh walls exercise the schedule, not ICI "
                    "latency hiding; parity (bit-identical losses "
                    "across depths) is the load-bearing assertion here",
            "depth0": {k: r0[k] for k in ("prefetch_depth", "step_ms")},
            "depth1": {k: r1[k] for k in ("prefetch_depth", "step_ms")},
            "parity": True,
        },
        "analytic": ana,
        "production_projection": proj,
        "zero3": {
            "overlap_fraction": proj["overlap_fraction_depth1"],
            "memory_headroom_fraction":
                ana["memory"]["headroom_fraction"],
        },
        "projected": True,
    }
    print(json.dumps(record, indent=1))
    if RECORD:
        with open(OUT, "w") as f:
            json.dump(record, f, indent=1)
        print(f"wrote {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
