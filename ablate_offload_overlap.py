"""Ablation: serial vs overlapped bucketed ZeRO-Offload, thread sweep.

Runs a CPU-sized GPT-2 through the offload engine in every mode the new
pipeline exposes and prints one JSON line per configuration:

    python ablate_offload_overlap.py              # sweep, print lines
    python ablate_offload_overlap.py --record     # + merge the measured
                                                  #   overlap into
                                                  #   OFFLOAD_BENCH.json

What it measures (all on THIS host, same model, same seed):
  - serial wall/step (overlap_comm: false — the parity baseline),
  - overlapped wall/step at host_threads in {1, 2, cpu_count}, with the
    engine's per-step overlap_fraction (1 - pipeline_span/pipeline_work:
    the fraction of host-pipeline work hidden by concurrency),
  - the speedup serial/overlap the record derives its projection from.

Honest-methodology note (what --record writes): the 1.5B component
measurements in OFFLOAD_BENCH.json (device-only step, host Adam, transfer
bytes) come from the one-shot tunneled-chip run and are NOT touched. The
ablation contributes the measured overlap_fraction and host-pipeline
speedup of the SAME engine code on this host, and the projection becomes
``device + max(host/threads, transfers)`` instead of the serial sum —
with the measured speedup recorded next to the assumed thread count so
the reader can discount the ideal-scaling part. The C++ Adam kernel is
itself OpenMP-parallel, so on many-core TPU-VM hosts the host term shrinks
with cores even at one pipeline thread; the pipeline's own win (measured
here) is hiding D2H/H2D behind the kernels.
"""
import argparse
import dataclasses
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np


def build_engine(overlap, threads, gas=2, bucket_mb=8):
    from deepspeed_tpu.models import GPT2_CONFIGS, gpt2_init, gpt2_loss_fn
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.parallel.topology import build_mesh

    # Params-heavy, token-light: host Adam work scales with params, device
    # compute with tokens — this shape keeps the host pipeline a visible
    # slice of the step on a CPU "device".
    cfg = dataclasses.replace(
        GPT2_CONFIGS["gpt2-tiny"], hidden_size=512, num_heads=8,
        num_layers=6, max_seq_length=64, vocab_size=2048,
        hidden_dropout=0.0, attn_dropout=0.0)
    micro_bs = 2
    ds = {
        "train_batch_size": micro_bs * gas,
        "train_micro_batch_size_per_gpu": micro_bs,
        "gradient_accumulation_steps": gas,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2, "cpu_offload": True,
                              "overlap_comm": overlap,
                              "offload_bucket_size": bucket_mb * 2 ** 20,
                              "offload_host_threads": threads},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "steps_per_print": 10 ** 9,
    }
    mesh = build_mesh(devices=jax.devices()[:1])
    engine = DeepSpeedEngine(model=gpt2_loss_fn(cfg),
                             model_params=gpt2_init(jax.random.PRNGKey(0),
                                                    cfg),
                             config=ds, mesh=mesh)
    batch = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(micro_bs * gas, cfg.max_seq_length + 1),
        dtype=np.int32))
    return engine, batch


def run(overlap, threads, steps=8):
    engine, batch = build_engine(overlap, threads)
    for _ in range(2):                      # compile + staging warmup
        engine.train_batch(batch)
    jax.block_until_ready(engine.state.params)
    keys = ("pipeline_span_ms", "pipeline_work_ms", "d2h_ms",
            "host_norm_ms", "host_step_ms", "h2d_dispatch_ms")
    acc = {k: 0.0 for k in keys}
    t0 = time.perf_counter()
    for _ in range(steps):
        engine.train_batch(batch)
        for k in keys:
            acc[k] += engine.offload_timings[k]
    jax.block_until_ready(engine.state.params)
    wall_ms = (time.perf_counter() - t0) / steps * 1e3
    avg = {k: v / steps for k, v in acc.items()}
    # Averaged over the measured steps (a single step's span/work ratio is
    # noisy at CPU scale where the whole host pipeline is tens of ms).
    frac = max(0.0, 1.0 - avg["pipeline_span_ms"] / avg["pipeline_work_ms"]) \
        if overlap and avg["pipeline_work_ms"] > 0 else 0.0
    rec = {
        "mode": "overlap" if overlap else "serial",
        "gas": engine.gradient_accumulation_steps(),
        "host_threads": engine._offload.host_threads if overlap else 0,
        "num_buckets": engine.offload_timings["num_buckets"],
        "step_wall_ms": round(wall_ms, 2),
        "host_pipeline_span_ms": round(avg["pipeline_span_ms"], 2),
        "host_pipeline_work_ms": round(avg["pipeline_work_ms"], 2),
        "overlap_fraction": round(frac, 4),
        "d2h_ms": round(avg["d2h_ms"], 2),
        "host_norm_ms": round(avg["host_norm_ms"], 2),
        "host_step_ms": round(avg["host_step_ms"], 2),
        "h2d_dispatch_ms": round(avg["h2d_dispatch_ms"], 2),
    }
    print(json.dumps(rec), flush=True)
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--record", action="store_true",
                    help="merge measured overlap into OFFLOAD_BENCH.json")
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    cores = os.cpu_count() or 1
    serial = run(False, 0, args.steps)
    sweep = [run(True, t, args.steps)
             for t in sorted({1, 2, cores})]
    best = min(sweep, key=lambda r: r["step_wall_ms"])
    speedup = serial["step_wall_ms"] / best["step_wall_ms"]
    best_frac = max(r["overlap_fraction"] for r in sweep)
    summary = {
        "mode": "summary", "cores": cores,
        "serial_step_ms": serial["step_wall_ms"],
        "best_overlap_step_ms": best["step_wall_ms"],
        "best_host_threads": best["host_threads"],
        "measured_step_speedup": round(speedup, 3),
        "best_overlap_fraction": best_frac,
    }
    print(json.dumps(summary), flush=True)

    if args.record:
        here = os.path.dirname(os.path.abspath(__file__))
        path = os.path.join(here, "OFFLOAD_BENCH.json")
        with open(path) as f:
            rec = json.load(f)
        # The 1.5B serial component measurements stay untouched; the
        # overlapped projection re-shapes them with the measured pipeline.
        device_ms = rec["offload_device_only_step_ms"]
        host_ms = rec["offload_components_ms"]["host_step_ms"]
        gbs = rec["projected_tpu_vm"]["assumed_host_link_gb_s"]
        xfer_ms = 2 * rec["offload_transfer_bytes_each_way"] / (gbs * 1e9) \
            * 1e3
        serial_ms = device_ms + xfer_ms + host_ms
        threads = best["host_threads"] or cores
        proj_ms = device_ms + max(host_ms / max(1, threads), xfer_ms)
        tokens = rec["offload_grad_accum_steps"] * 4 * 1024
        rec["offload_overlap"] = {
            "enabled": True,
            "host_threads": threads,
            "num_buckets_ablation": best["num_buckets"],
            "overlap_fraction": best_frac,
            "measured_step_speedup_this_host": summary[
                "measured_step_speedup"],
            "ablation_cores": cores,
            # gas>1 evidence lives in the ablation runs (gas=2 pipeline,
            # overlap vs serial); the preserved 1.5B component record
            # above is the original gas=1 one-shot.
            "ablation_gas": best["gas"],
            "ablation": {"serial": serial, "sweep": sweep},
        }
        rec["projected_tpu_vm"] = {
            "assumed_host_link_gb_s": gbs,
            "step_ms": round(proj_ms, 1),
            "tokens_per_sec": round(tokens / (proj_ms / 1e3), 1),
            "serial_step_ms": round(serial_ms, 1),
            "serial_tokens_per_sec": round(tokens / (serial_ms / 1e3), 1),
            "formula": "device + max(host/threads, transfers)",
            "host_threads_assumed": threads,
        }
        rec["note_overlap"] = (
            "overlap fields measured by ablate_offload_overlap.py on this "
            f"host ({cores} cores) with the same engine code at CPU scale "
            f"(gas={best['gas']}, overlap vs serial, thread sweep); the "
            "1.5B device/host/transfer components above are the original "
            "tunneled-chip gas=1 one-shot. projected_tpu_vm now uses the "
            "overlapped shape device + max(host/threads, transfers); "
            "serial_step_ms preserves the old serial sum for comparison. "
            "The SIMD Adam kernel is OpenMP-parallel, so host/threads "
            "models TPU-VM many-core hosts; the measured per-step speedup "
            f"and overlap_fraction on this {cores}-core box (where device "
            "compute and host kernels contend for the same cores) are "
            "recorded alongside as the honest lower bound.")
        with open(path, "w") as f:
            json.dump(rec, f, indent=1)
        print(f"recorded -> {path}", flush=True)


if __name__ == "__main__":
    main()
