"""Dev tool: sweep (fwd, bwd) flash block pairs on the fwd+bwd step only
(no optimizer state, so gpt2-large fits).  Usage: python ablate_flash2.py
"""
import dataclasses
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models import GPT2_CONFIGS
from deepspeed_tpu.models.gpt2 import gpt2_flops_per_token, gpt2_init, gpt2_loss_fn
import deepspeed_tpu.ops.flash_attention as fa

MODEL = sys.argv[1] if len(sys.argv) > 1 else "gpt2-large"
MBS = int(sys.argv[2]) if len(sys.argv) > 2 else 4

cfg = dataclasses.replace(GPT2_CONFIGS[MODEL], max_seq_length=1024,
                          remat_policy="dots", hidden_dropout=0.0,
                          attn_dropout=0.0, scan_layers=False)
S = cfg.max_seq_length
loss_fn = gpt2_loss_fn(cfg)


def cast(p):
    return jax.tree_util.tree_map(
        lambda a: a.astype(cfg.dtype) if a.dtype == jnp.float32 else a, p)


params = gpt2_init(jax.random.PRNGKey(0), cfg)
batch = jnp.asarray(np.random.randint(0, cfg.vocab_size,
                                      size=(MBS, S + 1), dtype=np.int32))
rng = jax.random.PRNGKey(1)


def run(bf, bb):
    fa._BLOCK_TARGET = bf
    fa._BLOCK_TARGET_BWD = bb

    @jax.jit
    def step(params, batch, rng):
        return jax.value_and_grad(
            lambda p: loss_fn(cast(p), batch, rng))(params)

    out = step(params, batch, rng)
    _ = float(out[0])
    n = 20
    t0 = time.perf_counter()
    for _ in range(n):
        out = step(params, batch, rng)
    _ = float(out[0])
    dt = (time.perf_counter() - t0) / n
    print(f"fwd_block={bf:4d} bwd_block={bb:4d}: {dt*1000:7.1f} ms fwd+bwd",
          flush=True)


for bf, bb in [(1024, 1024), (512, 1024), (1024, 512), (512, 512),
               (256, 1024)]:
    run(bf, bb)
