// Host-resident SIMD Adam/AdamW for ZeRO-Offload.
//
// TPU-native counterpart of the reference's csrc/adam/cpu_adam.cpp
// (AVX512/AVX256 intrinsics + OpenMP tiling, Step/Step_4/Step_8 unrolls,
// ds_adam_step / ds_adam_step_plus_copy at :602,:634). Rather than
// hand-unrolled intrinsics bound to one ISA, the hot loop is written as a
// restrict-qualified fused multiply-add chain under
// `#pragma omp parallel for simd`, which gcc/clang vectorize to
// AVX2/AVX-512 on x86 TPU-VM hosts and NEON/SVE on ARM hosts — the same
// machine code the reference gets, portable across both host ISAs.
//
// The "_plus_copy" variant fuses the bf16 down-cast of the updated master
// weights into the update loop (single pass over memory), standing in for
// the reference's fused H2D fp16 param copy (cpu_adam.cpp:634,
// launch_param_update): the bf16 staging buffer is what jax.device_put
// ships to HBM, so the fp32 masters are never re-read for the cast.

#include <cstdint>
#include <cmath>

extern "C" {

// One Adam/AdamW step over a contiguous fp32 span.
//
//  params/grads/exp_avg/exp_avg_sq : length-n fp32 arrays (params, moments
//                                    updated in place)
//  step        : 1-based optimizer step (bias correction)
//  grad_scale  : multiplied into every gradient read — carries the
//                combined loss-scale inverse and clip coefficient so no
//                separate pass over the gradients is needed
//  adamw_mode  : 1 = decoupled weight decay (AdamW), 0 = coupled L2 folded
//                into the gradient (classic Adam, reference FusedAdam
//                adam_w_mode=False)
void ds_adam_step(float* __restrict params,
                  const float* __restrict grads,
                  float* __restrict exp_avg,
                  float* __restrict exp_avg_sq,
                  int64_t n, int32_t step,
                  float lr, float beta1, float beta2, float eps,
                  float weight_decay, int32_t adamw_mode, float grad_scale) {
  const float bc1 = 1.0f - std::pow(beta1, (float)step);
  const float bc2 = 1.0f - std::pow(beta2, (float)step);
  const float step_size = lr / bc1;
  const float inv_bc2_sqrt = 1.0f / std::sqrt(bc2);
  const float omb1 = 1.0f - beta1;
  const float omb2 = 1.0f - beta2;
  const float decay = weight_decay;

#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grads[i] * grad_scale;
    float p = params[i];
    if (!adamw_mode && decay != 0.0f) g += decay * p;
    float m = exp_avg[i] * beta1 + g * omb1;
    float v = exp_avg_sq[i] * beta2 + g * g * omb2;
    exp_avg[i] = m;
    exp_avg_sq[i] = v;
    float denom = std::sqrt(v) * inv_bc2_sqrt + eps;
    // AdamW: decoupled decay applied directly to p (p -= lr * wd * p).
    params[i] = p - step_size * (m / denom) -
                (adamw_mode ? lr * decay * p : 0.0f);
  }
}

// fp32 -> bf16 with round-to-nearest-even (matching XLA's convert).
static inline uint16_t f32_to_bf16(float f) {
  uint32_t x;
  __builtin_memcpy(&x, &f, 4);
  uint32_t lsb = (x >> 16) & 1u;
  x += 0x7fffu + lsb;
  return (uint16_t)(x >> 16);
}

// Adam step fused with the bf16 staging copy of the updated params
// (reference ds_adam_step_plus_copy, cpu_adam.cpp:634).
void ds_adam_step_plus_copy(float* __restrict params,
                            const float* __restrict grads,
                            float* __restrict exp_avg,
                            float* __restrict exp_avg_sq,
                            uint16_t* __restrict params_bf16,
                            int64_t n, int32_t step,
                            float lr, float beta1, float beta2, float eps,
                            float weight_decay, int32_t adamw_mode,
                            float grad_scale) {
  const float bc1 = 1.0f - std::pow(beta1, (float)step);
  const float bc2 = 1.0f - std::pow(beta2, (float)step);
  const float step_size = lr / bc1;
  const float inv_bc2_sqrt = 1.0f / std::sqrt(bc2);
  const float omb1 = 1.0f - beta1;
  const float omb2 = 1.0f - beta2;
  const float decay = weight_decay;

#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grads[i] * grad_scale;
    float p = params[i];
    if (!adamw_mode && decay != 0.0f) g += decay * p;
    float m = exp_avg[i] * beta1 + g * omb1;
    float v = exp_avg_sq[i] * beta2 + g * g * omb2;
    exp_avg[i] = m;
    exp_avg_sq[i] = v;
    float denom = std::sqrt(v) * inv_bc2_sqrt + eps;
    float newp = p - step_size * (m / denom) -
                 (adamw_mode ? lr * decay * p : 0.0f);
    params[i] = newp;
    params_bf16[i] = f32_to_bf16(newp);
  }
}

// bf16 -> f32 (the exact widening XLA's convert performs).
static inline float bf16_to_f32(uint16_t h) {
  uint32_t x = ((uint32_t)h) << 16;
  float f;
  __builtin_memcpy(&f, &x, 4);
  return f;
}

// Adam step consuming BF16 gradients directly (the dtype ZeRO-Offload
// grads arrive in from the device): kills the separate host-side
// bf16->f32 cast pass AND halves the gradient memory traffic. Fused with
// the bf16 staging copy like ds_adam_step_plus_copy.
void ds_adam_step_plus_copy_bf16g(float* __restrict params,
                                  const uint16_t* __restrict grads_bf16,
                                  float* __restrict exp_avg,
                                  float* __restrict exp_avg_sq,
                                  uint16_t* __restrict params_bf16,
                                  int64_t n, int32_t step,
                                  float lr, float beta1, float beta2,
                                  float eps, float weight_decay,
                                  int32_t adamw_mode, float grad_scale) {
  const float bc1 = 1.0f - std::pow(beta1, (float)step);
  const float bc2 = 1.0f - std::pow(beta2, (float)step);
  const float step_size = lr / bc1;
  const float inv_bc2_sqrt = 1.0f / std::sqrt(bc2);
  const float omb1 = 1.0f - beta1;
  const float omb2 = 1.0f - beta2;
  const float decay = weight_decay;

#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = bf16_to_f32(grads_bf16[i]) * grad_scale;
    float p = params[i];
    if (!adamw_mode && decay != 0.0f) g += decay * p;
    float m = exp_avg[i] * beta1 + g * omb1;
    float v = exp_avg_sq[i] * beta2 + g * g * omb2;
    exp_avg[i] = m;
    exp_avg_sq[i] = v;
    float denom = std::sqrt(v) * inv_bc2_sqrt + eps;
    float newp = p - step_size * (m / denom) -
                 (adamw_mode ? lr * decay * p : 0.0f);
    params[i] = newp;
    params_bf16[i] = f32_to_bf16(newp);
  }
}

// Same, without the staging copy.
void ds_adam_step_bf16g(float* __restrict params,
                        const uint16_t* __restrict grads_bf16,
                        float* __restrict exp_avg,
                        float* __restrict exp_avg_sq,
                        int64_t n, int32_t step,
                        float lr, float beta1, float beta2, float eps,
                        float weight_decay, int32_t adamw_mode,
                        float grad_scale) {
  const float bc1 = 1.0f - std::pow(beta1, (float)step);
  const float bc2 = 1.0f - std::pow(beta2, (float)step);
  const float step_size = lr / bc1;
  const float inv_bc2_sqrt = 1.0f / std::sqrt(bc2);
  const float omb1 = 1.0f - beta1;
  const float omb2 = 1.0f - beta2;
  const float decay = weight_decay;

#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = bf16_to_f32(grads_bf16[i]) * grad_scale;
    float p = params[i];
    if (!adamw_mode && decay != 0.0f) g += decay * p;
    float m = exp_avg[i] * beta1 + g * omb1;
    float v = exp_avg_sq[i] * beta2 + g * g * omb2;
    exp_avg[i] = m;
    exp_avg_sq[i] = v;
    float denom = std::sqrt(v) * inv_bc2_sqrt + eps;
    params[i] = p - step_size * (m / denom) -
                (adamw_mode ? lr * decay * p : 0.0f);
  }
}

double ds_grad_norm_sq_bf16(const uint16_t* __restrict grads_bf16, int64_t n,
                            float grad_scale) {
  double acc = 0.0;
#pragma omp parallel for simd reduction(+ : acc) schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    double g = (double)(bf16_to_f32(grads_bf16[i]) * grad_scale);
    acc += g * g;
  }
  return acc;
}

// L2 norm of a scaled gradient span (overflow/clip decision happens on the
// host for offloaded steps; one pass, reduction vectorized).
double ds_grad_norm_sq(const float* __restrict grads, int64_t n,
                       float grad_scale) {
  double acc = 0.0;
#pragma omp parallel for simd reduction(+ : acc) schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    double g = (double)(grads[i] * grad_scale);
    acc += g * g;
  }
  return acc;
}

}  // extern "C"
