"""Dev tool: micro-benchmark attention kernels standalone.

Single-dispatch timing: N iterations are chained inside one jitted
lax.scan (output feeds the next call's q), so per-dispatch tunnel
overhead (~2.5 ms on axon) doesn't swamp the kernel time.
Usage: python ablate_attn.py
"""
import math
import time

import jax
import jax.numpy as jnp

import deepspeed_tpu.ops.flash_attention as fa

B, S, NH, D = 4, 1024, 20, 64
L = 36
N = 20

key = jax.random.PRNGKey(0)
q = jax.random.normal(key, (B * NH, S, D), jnp.bfloat16)
k = jax.random.normal(jax.random.fold_in(key, 1), (B * NH, S, D), jnp.bfloat16)
v = jax.random.normal(jax.random.fold_in(key, 2), (B * NH, S, D), jnp.bfloat16)
seed = jnp.zeros((), jnp.int32)
scale = 1.0 / math.sqrt(D)

fl_fwd_full = 4 * B * NH * S * S * D / 1e12


def timeit_chained(one, qinit, *rest):
    """one(q, *rest) -> same-shape-as-q; runs N chained iterations."""
    @jax.jit
    def many(q):
        def body(c, _):
            return one(c, *rest), None
        out, _ = jax.lax.scan(body, q, None, length=N)
        return out

    out = many(qinit)
    _ = float(jnp.sum(out[0, 0].astype(jnp.float32)))
    t0 = time.perf_counter()
    out = many(q)
    _ = float(jnp.sum(out[0, 0].astype(jnp.float32)))
    return (time.perf_counter() - t0) / N * 1000


def report(name, t_fwd, t_fb):
    print(f"{name:28s}: fwd {t_fwd:6.2f} ms ({fl_fwd_full/t_fwd*1000:6.1f} TF-equiv)"
          f"   fwd+bwd {t_fb:7.2f} ms   per-model {t_fb*L:6.1f} ms", flush=True)


def bench_ours(block):
    fa._BLOCK_TARGET = block

    def fwd_one(q, k, v):
        return fa._flash(q, k, v, seed, scale, True, 0.0).astype(q.dtype)

    def fb_one(q, k, v):
        def f(qq, kk, vv):
            o = fa._flash(qq, kk, vv, seed, scale, True, 0.0)
            return jnp.sum(o.astype(jnp.float32) ** 2)
        dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        return (dq + dk + dv).astype(q.dtype)

    report(f"ours block={block}", timeit_chained(fwd_one, q, k, v),
           timeit_chained(fb_one, q, k, v))


def bench_xla_dense():
    from deepspeed_tpu.models.transformer import dense_attention
    q4 = q.reshape(B, NH, S, D).transpose(0, 2, 1, 3)

    def fwd_one(q, k, v):
        return dense_attention(q, k, v, mask=None, causal=True).astype(q.dtype)

    def fb_one(q, k, v):
        def f(qq, kk, vv):
            o = dense_attention(qq, kk, vv, mask=None, causal=True)
            return jnp.sum(o.astype(jnp.float32) ** 2)
        dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        return (dq + dk + dv).astype(q.dtype)

    report("xla dense", timeit_chained(fwd_one, q4, q4, q4),
           timeit_chained(fb_one, q4, q4, q4))


def bench_jax_flash(bq, bkmaj, bk):
    from jax.experimental.pallas.ops.tpu import flash_attention as jfa
    bs = jfa.BlockSizes(block_q=bq, block_k_major=bkmaj, block_k=bk, block_b=1,
                        block_q_major_dkv=bq, block_k_major_dkv=bkmaj,
                        block_k_dkv=bk, block_q_dkv=bq,
                        block_k_major_dq=bkmaj, block_k_dq=bk, block_q_dq=bq)
    q4 = q.reshape(B, NH, S, D)

    def fwd_one(q, k, v):
        return jfa.flash_attention(q, k, v, causal=True, sm_scale=scale,
                                   block_sizes=bs).astype(q.dtype)

    def fb_one(q, k, v):
        def f(qq, kk, vv):
            o = jfa.flash_attention(qq, kk, vv, causal=True, sm_scale=scale,
                                    block_sizes=bs)
            return jnp.sum(o.astype(jnp.float32) ** 2)
        dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        return (dq + dk + dv).astype(q.dtype)

    report(f"jax flash q{bq}/k{bkmaj}/{bk}", timeit_chained(fwd_one, q4, q4, q4),
           timeit_chained(fb_one, q4, q4, q4))


for blk in (1024, 512, 256):
    try:
        bench_ours(blk)
    except Exception as e:
        print("ours", blk, "failed:", str(e)[:150], flush=True)
try:
    bench_xla_dense()
except Exception as e:
    print("xla dense failed:", str(e)[:300], flush=True)
for cfgs in ((512, 1024, 512), (512, 512, 512), (256, 512, 256)):
    try:
        bench_jax_flash(*cfgs)
    except Exception as e:
        print("jax flash", cfgs, "failed:", str(e)[:120])
