"""MoE ablation: dense FFN vs 8-expert top-2 expert parallelism (dev tool).

Runs gpt2-tiny dense and its 8-expert top-2 MoE twin (ep=4 x dp=2)
through the full engine on the 8-device CPU mesh and records:

- **measured** CPU wall per step for both — honestly labeled: on the
  emulated mesh the all-to-all is memcpy, so the delta exercises the
  dispatch/bucketing/exchange STRUCTURE, not ICI latency (the
  ZERO3_BENCH/OFFLOAD_BENCH convention). Measured drop fraction and
  expert load imbalance ride along (bench_gate parses the drop p95).
- the **params-per-step-FLOP headline** — the reason MoE exists: total
  trainable parameters grow ~E x on the FFN tree while per-token step
  FLOPs grow only ~top_k x on the same tree (+ the router's H*E
  logits), analytically derived from the actual param trees.
- the **analytic all-to-all wire bytes** (hlo_audit.moe_alltoall_wire_
  model — the same model COMM_AUDIT.json verifies against the compiled
  program to 5%) vs the FFN FLOP delta: what the expert-parallel wire
  costs against the compute it unlocks on the target chip.

- the **expert-compute ablation** — the einsum FFN pair vs the
  grouped-GEMM Pallas kernel (ops/grouped_gemm) at the exact dispatched
  shapes: both walls measured on TPU; on the CPU dev box the einsum
  wall is measured and the kernel's win is the structural HBM-byte
  projection (fused epilogue drops the [E,C,F] round-trip), honestly
  labeled ``projected``.

``--record`` writes MOE_BENCH.json; ``tools/bench_gate.py`` gates its
``moe.drop_fraction`` across rounds (pre-MoE rounds skip, never fail).

Usage: python ablate_moe.py [--steps N] [--record]
"""
import dataclasses
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = \
        _flags + " --xla_force_host_platform_device_count=8"

import jax                     # noqa: E402
import jax.numpy as jnp        # noqa: E402
import numpy as np             # noqa: E402

import deepspeed_tpu           # noqa: E402
from deepspeed_tpu.models.gpt2 import (GPT2_CONFIGS, gpt2_init,  # noqa: E402
                                       gpt2_loss_fn)
from deepspeed_tpu.models.transformer import count_params  # noqa: E402
from deepspeed_tpu.moe import (MoEConfig,  # noqa: E402
                               gpt2_moe_param_shardings)
from deepspeed_tpu.parallel import hlo_audit  # noqa: E402
from deepspeed_tpu.parallel.topology import build_mesh  # noqa: E402

REPO = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(REPO, "MOE_BENCH.json")
RECORD = "--record" in sys.argv
STEPS = 30
if "--steps" in sys.argv:
    STEPS = int(sys.argv[sys.argv.index("--steps") + 1])

E, K, CF, EP = 8, 2, 1.5, 4
B, SEQ = 32, 33


def _cfg(moe=None):
    return dataclasses.replace(
        GPT2_CONFIGS["gpt2-tiny"], vocab_size=64, max_seq_length=SEQ,
        hidden_dropout=0.0, attn_dropout=0.0, dtype=jnp.float32,
        fused_kernels=False, moe=moe)


def _engine(moe_cfg=None):
    ep = moe_cfg.expert_parallel_size if moe_cfg else 1
    mesh = build_mesh(ep=ep)
    cfg = _cfg(moe_cfg)
    ds = {"train_batch_size": B, "train_micro_batch_size_per_gpu": 4,
          "gradient_accumulation_steps": 1,
          "zero_optimization": {"stage": 1}, "gradient_clipping": 1.0,
          "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
          "steps_per_print": 10 ** 9}
    kw = {}
    if moe_cfg is not None:
        ds["moe"] = {"num_experts": moe_cfg.num_experts,
                     "top_k": moe_cfg.top_k,
                     "capacity_factor": moe_cfg.capacity_factor,
                     "expert_parallel_size": ep}
        kw["param_shardings"] = gpt2_moe_param_shardings(cfg)
    engine, *_ = deepspeed_tpu.initialize(
        model=gpt2_loss_fn(cfg, mesh=mesh),
        model_params=gpt2_init(jax.random.PRNGKey(0), cfg),
        config=ds, mesh=mesh, **kw)
    return engine, cfg


def _run(engine, steps):
    rng = np.random.default_rng(0)
    batches = [rng.integers(0, 64, size=(B, SEQ + 1)).astype(np.int32)
               for _ in range(steps + 3)]
    for b in batches[:3]:                       # warmup / compile
        engine.train_batch(b)
    jax.block_until_ready(engine.state.params)
    t0 = time.perf_counter()
    for b in batches[3:]:
        engine.train_batch(b)
    jax.block_until_ready(engine.state.params)
    wall = (time.perf_counter() - t0) / steps
    return wall


def main():
    moe_cfg = MoEConfig(num_experts=E, top_k=K, capacity_factor=CF,
                        expert_parallel_size=EP)
    dense_engine, dense_model = _engine(None)
    dense_wall = _run(dense_engine, STEPS)
    dense_params = count_params(jax.device_get(
        dense_engine.state.params))

    moe_engine, moe_model = _engine(moe_cfg)
    moe_wall = _run(moe_engine, STEPS)
    moe_params = count_params(jax.device_get(moe_engine.state.params))
    # Last step's stats via one extra recorded step.
    metrics = None
    rng = np.random.default_rng(1)
    moe_engine.train_batch(rng.integers(0, 64, size=(B, SEQ + 1))
                           .astype(np.int32))
    # metrics dict of the last step is not retained by train_batch;
    # recompute from a fresh step fn call
    mb = moe_engine._stack_micro_batches(
        rng.integers(0, 64, size=(B, SEQ + 1)).astype(np.int32))
    mb = jax.device_put(mb, moe_engine._batch_sharding(mb, leading_dims=2))
    moe_engine.state, metrics = moe_engine._train_step_fn(
        moe_engine.state, mb, moe_engine._base_rng)
    drop = float(jax.device_get(metrics["moe_drop_fraction"]))
    counts = np.asarray(jax.device_get(metrics["moe_expert_tokens"]))
    imbalance = float(counts.max() / max(1e-9, counts.mean()))

    # Analytic FFN tree: params grow ~E x, per-token FLOPs ~k x.
    H, F = dense_model.hidden_size, dense_model.ffn_size
    L = dense_model.num_layers
    ffn_dense = 2 * H * F
    router = H * E
    flops_ratio = (K * ffn_dense + router) / ffn_dense
    tokens_per_device = (B // moe_engine.replica_size) * SEQ
    wire = hlo_audit.moe_alltoall_wire_model(
        hidden=H, num_experts=E, top_k=K, capacity_factor=CF, ep=EP,
        n_moe_layers=L, bytes_per_el=4,
        tokens_per_device=tokens_per_device)
    # FFN matmul FLOPs the experts add per device per step (fwd+bwd, 6x
    # multiply-add accounting) vs the wire those tokens cost.
    ffn_flops_per_step = 6 * K * ffn_dense * L * tokens_per_device

    # --- Expert compute: einsum pair vs the grouped-GEMM kernel ------- #
    # The shard-local [E,C,H]x[E,H,F] FFN at the exact shapes the moe
    # engine above dispatches. On TPU both paths are timed; on the CPU
    # dev box only the einsum pair is timed (interpret-mode Pallas
    # measures the interpreter, not the kernel) and the grouped-GEMM win
    # is the structural HBM-byte projection (the BENCH_r06 convention).
    from deepspeed_tpu.ops.grouped_gemm import grouped_ffn
    Cap = int(wire["capacity"])
    rr = np.random.default_rng(2)
    xb = jnp.asarray(rr.standard_normal((E, Cap, H)), jnp.float32)
    ew1 = jnp.asarray(rr.standard_normal((E, H, F)) * H ** -0.5,
                      jnp.float32)
    eb1 = jnp.zeros((E, F), jnp.float32)
    ew2 = jnp.asarray(rr.standard_normal((E, F, H)) * F ** -0.5,
                      jnp.float32)
    eb2 = jnp.zeros((E, H), jnp.float32)

    def einsum_ffn(x, w1, b1, w2, b2):
        h = jnp.einsum("ech,ehf->ecf", x, w1) + b1[:, None, :]
        h = jax.nn.gelu(h, approximate=True)
        return jnp.einsum("ecf,efh->ech", h, w2) + b2[:, None, :]

    def _time_fn(fn, *a):
        f = jax.jit(fn)
        jax.block_until_ready(f(*a))
        reps = 100
        t0 = time.perf_counter()
        out = None
        for _ in range(reps):
            out = f(*a)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / reps

    einsum_layer_wall = _time_fn(einsum_ffn, xb, ew1, eb1, ew2, eb2)
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        grouped_layer_wall = _time_fn(
            lambda *a: grouped_ffn(*a, False), xb, ew1, eb1, ew2, eb2)
        expert_speedup = einsum_layer_wall / grouped_layer_wall
        grouped_step = round(grouped_layer_wall * L, 6)
    else:
        # Fwd epilogue fusion drops the [E,C,F] pre-activation HBM
        # round-trip (1 write + 1 read per layer, f32); the backward's
        # recompute trades one extra grouped GEMM for not HOLDING that
        # residual across fwd->bwd (peak activation memory, not time).
        hbm_gb_s = 819.0
        saved_bytes = L * 2 * E * Cap * F * 4
        einsum_step = einsum_layer_wall * L
        # Projection target is the v5e HBM clock, not the CPU wall:
        # report the byte delta and its v5e-seconds, never a CPU ratio.
        expert_speedup = None
        grouped_step = None
    expert_compute = {
        "shapes": {"E": E, "C": Cap, "H": H, "F": F, "layers": L},
        "einsum_wall_s_per_step": round(einsum_layer_wall * L, 6),
        "grouped_gemm_wall_s_per_step": grouped_step,
        "measured_on": jax.default_backend(),
        "projected": not on_tpu,
    }
    if on_tpu:
        expert_compute["grouped_over_einsum_speedup"] = round(
            expert_speedup, 4)
    else:
        expert_compute.update({
            "projected_saved_hbm_bytes_per_step": int(saved_bytes),
            "projected_saved_s_per_step_v5e": round(
                saved_bytes / (hbm_gb_s * 1e9), 9),
            "assumptions": {
                "hbm_gb_s": hbm_gb_s,
                "model": ("fused bias+GELU epilogue removes the "
                          "[E,C,F] f32 pre-activation write+read per "
                          "layer fwd; bwd recompute is byte-neutral "
                          "(re-materializes what the einsum path saved)"
                          " but frees the held residual"),
            },
            "note": ("PROJECTED on the CPU dev box: the einsum wall is "
                     "the CPU structural figure; the grouped-GEMM win "
                     "is the analytic HBM-byte delta at v5e bandwidth. "
                     "A TPU session re-records both walls measured "
                     "(python ablate_moe.py --record)."),
        })

    record = {
        "generated_by": "ablate_moe.py",
        "methodology": (
            "8-device CPU host mesh (ep=4 x dp=2): walls exercise the "
            "dispatch/bucketing/all-to-all STRUCTURE, not ICI latency — "
            "the emulated interconnect is memcpy. Wire bytes are the "
            "analytic ring model COMM_AUDIT.json verifies against the "
            "compiled program; params/FLOP ratios are exact tree "
            "arithmetic. Same convention as ZERO3_BENCH/OFFLOAD_BENCH."),
        "config": {"model": "gpt2-tiny", "num_experts": E, "top_k": K,
                   "capacity_factor": CF, "ep": EP, "batch": B,
                   "seq": SEQ, "steps": STEPS},
        "measured": {
            "dense_wall_s_per_step": round(dense_wall, 4),
            "moe_wall_s_per_step": round(moe_wall, 4),
            "moe_over_dense_wall": round(moe_wall / dense_wall, 3),
            "drop_fraction": round(drop, 5),
            "expert_imbalance_max_over_mean": round(imbalance, 3),
        },
        "headline": {
            "total_params_dense": int(dense_params),
            "total_params_moe": int(moe_params),
            "params_ratio": round(moe_params / dense_params, 3),
            "ffn_params_ratio": float(E),
            "ffn_flops_per_token_ratio": round(flops_ratio, 3),
            "note": (
                "the MoE scaling trade: the FFN parameter tree grows "
                f"{E}x while its per-token step FLOPs grow only "
                f"~{flops_ratio:.2f}x (top-{K} routing + the H*E "
                "router) — params per step-FLOP up "
                f"{E / flops_ratio:.1f}x on the FFN tree"),
        },
        "wire": {
            **{k: wire[k] for k in
               ("wire_bytes_per_token", "wire_bytes_per_step",
                "dispatch_buffer_bytes", "capacity")},
            "ffn_expert_flops_per_step_per_device":
                int(ffn_flops_per_step),
            "alltoall_bytes_per_expert_flop": round(
                wire["wire_bytes_per_step"] / ffn_flops_per_step, 6),
            "note": (
                "per optimizer step per device: 4 all-to-alls per MoE "
                "layer x (ep-1)/ep of the [E,C,H] buffer, vs the k x "
                "FFN matmul FLOPs those routed tokens execute"),
        },
        "expert_compute": expert_compute,
        # bench_gate parses this shape (drop-fraction ceiling gate).
        "moe": {"available": True,
                "drop_fraction": {"p95": round(drop, 5),
                                  "p50": round(drop, 5)}},
    }
    print(json.dumps(record, indent=1))
    if RECORD:
        with open(OUT, "w") as f:
            json.dump(record, f, indent=1)
        print(f"wrote {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
