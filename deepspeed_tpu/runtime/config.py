"""The DeepSpeed-style JSON config system, TPU edition.

Parity with reference ``runtime/config.py`` (DeepSpeedConfig, config.py:515):
- accepts a path to a JSON file or an already-parsed dict
- rejects duplicate JSON keys (config_utils)
- elasticity pre-pass rewrites the batch keys before the solver runs
  (config.py:537-588)
- batch triple inference: train_batch_size =
  micro_batch_per_device * gradient_accumulation_steps * dp_world_size, with
  any one/two of the three inferable from the others (config.py:655-725)
- ~50 typed getters with defaults (config.py:48-491)
- error checks for missing/conflicting batch info (config.py:746-782)

TPU deltas: ``bf16`` section is first-class; ``world_size`` is the number of
*data-parallel replicas* (mesh dp-axis size), not processes, since one JAX
process drives many chips.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Union

from . import config_utils
from .. import constants as C
from .zero.config import ZeroConfig
from .activation_checkpointing.config import ActivationCheckpointingConfig
from ..utils.logging import logger


class DeepSpeedConfigError(Exception):
    pass


class FlopsProfilerConfig:
    def __init__(self, param_dict: Optional[Dict[str, Any]] = None):
        d = (param_dict or {}).get(C.FLOPS_PROFILER, {})
        get = config_utils.get_scalar_param
        self.enabled = get(d, C.FLOPS_PROFILER_ENABLED, C.FLOPS_PROFILER_ENABLED_DEFAULT)
        self.profile_step = get(d, C.FLOPS_PROFILER_PROFILE_STEP,
                                C.FLOPS_PROFILER_PROFILE_STEP_DEFAULT)
        self.module_depth = get(d, C.FLOPS_PROFILER_MODULE_DEPTH,
                                C.FLOPS_PROFILER_MODULE_DEPTH_DEFAULT)
        self.top_modules = get(d, C.FLOPS_PROFILER_TOP_MODULES,
                               C.FLOPS_PROFILER_TOP_MODULES_DEFAULT)
        self.detailed = get(d, C.FLOPS_PROFILER_DETAILED, C.FLOPS_PROFILER_DETAILED_DEFAULT)


class ProgressiveLayerDropConfig:
    def __init__(self, param_dict: Optional[Dict[str, Any]] = None):
        d = (param_dict or {}).get(C.PROGRESSIVE_LAYER_DROP, {})
        get = config_utils.get_scalar_param
        self.enabled = get(d, C.PLD_ENABLED, C.PLD_ENABLED_DEFAULT)
        self.theta = get(d, C.PLD_THETA, C.PLD_THETA_DEFAULT)
        self.gamma = get(d, C.PLD_GAMMA, C.PLD_GAMMA_DEFAULT)


class PipelineConfig:
    def __init__(self, param_dict: Optional[Dict[str, Any]] = None):
        d = (param_dict or {}).get(C.PIPELINE, {})
        get = config_utils.get_scalar_param
        self.stages = get(d, C.PIPELINE_STAGES, C.PIPELINE_STAGES_DEFAULT)
        self.partition = get(d, C.PIPELINE_PARTITION, C.PIPELINE_PARTITION_DEFAULT)
        self.seed_layers = get(d, C.PIPELINE_SEED_LAYERS, C.PIPELINE_SEED_LAYERS_DEFAULT)
        self.activation_checkpoint_interval = get(
            d, C.PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL,
            C.PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL_DEFAULT)
        self.schedule = get(d, C.PIPELINE_SCHEDULE,
                            C.PIPELINE_SCHEDULE_DEFAULT)


class TensorboardConfig:
    def __init__(self, param_dict: Optional[Dict[str, Any]] = None):
        d = (param_dict or {}).get(C.TENSORBOARD, {})
        get = config_utils.get_scalar_param
        self.enabled = get(d, C.TENSORBOARD_ENABLED, C.TENSORBOARD_ENABLED_DEFAULT)
        self.output_path = get(d, C.TENSORBOARD_OUTPUT_PATH, C.TENSORBOARD_OUTPUT_PATH_DEFAULT)
        self.job_name = get(d, C.TENSORBOARD_JOB_NAME, C.TENSORBOARD_JOB_NAME_DEFAULT)


class TelemetryHealthConfig:
    """The ``telemetry.health`` block (monitor/health.py + flight.py):
    anomaly detection with NaN/Inf provenance, the hang watchdog, and
    the crash flight recorder. Enabled by default whenever telemetry is
    on — detection is drain-time host work; the watchdog (a daemon
    thread) is the one opt-in."""

    def __init__(self, d: Optional[Dict[str, Any]] = None):
        d = d or {}
        get = config_utils.get_scalar_param
        self.enabled = get(d, C.TELEMETRY_HEALTH_ENABLED,
                           C.TELEMETRY_HEALTH_ENABLED_DEFAULT)
        self.grad_taps = get(d, C.TELEMETRY_HEALTH_GRAD_TAPS,
                             C.TELEMETRY_HEALTH_GRAD_TAPS_DEFAULT)
        self.z_threshold = get(d, C.TELEMETRY_HEALTH_Z_THRESHOLD,
                               C.TELEMETRY_HEALTH_Z_THRESHOLD_DEFAULT)
        self.ewma_alpha = get(d, C.TELEMETRY_HEALTH_EWMA_ALPHA,
                              C.TELEMETRY_HEALTH_EWMA_ALPHA_DEFAULT)
        self.warmup_steps = get(d, C.TELEMETRY_HEALTH_WARMUP_STEPS,
                                C.TELEMETRY_HEALTH_WARMUP_STEPS_DEFAULT)
        self.watchdog = get(d, C.TELEMETRY_HEALTH_WATCHDOG,
                            C.TELEMETRY_HEALTH_WATCHDOG_DEFAULT)
        self.watchdog_factor = get(
            d, C.TELEMETRY_HEALTH_WATCHDOG_FACTOR,
            C.TELEMETRY_HEALTH_WATCHDOG_FACTOR_DEFAULT)
        self.watchdog_min_s = get(
            d, C.TELEMETRY_HEALTH_WATCHDOG_MIN_S,
            C.TELEMETRY_HEALTH_WATCHDOG_MIN_S_DEFAULT)
        self.flight_recorder = get(d, C.TELEMETRY_HEALTH_FLIGHT,
                                   C.TELEMETRY_HEALTH_FLIGHT_DEFAULT)
        self.flight_path = get(d, C.TELEMETRY_HEALTH_FLIGHT_PATH,
                               C.TELEMETRY_HEALTH_FLIGHT_PATH_DEFAULT)
        self.flight_window = get(d, C.TELEMETRY_HEALTH_FLIGHT_WINDOW,
                                 C.TELEMETRY_HEALTH_FLIGHT_WINDOW_DEFAULT)
        self._validate()

    def _validate(self) -> None:
        blk = f"{C.TELEMETRY}.{C.TELEMETRY_HEALTH}"
        for name, v in ((C.TELEMETRY_HEALTH_ENABLED, self.enabled),
                        (C.TELEMETRY_HEALTH_GRAD_TAPS, self.grad_taps),
                        (C.TELEMETRY_HEALTH_WATCHDOG, self.watchdog),
                        (C.TELEMETRY_HEALTH_FLIGHT, self.flight_recorder)):
            if not isinstance(v, bool):
                raise DeepSpeedConfigError(
                    f"{blk}.{name} must be a bool, got {v!r}")
        if not isinstance(self.z_threshold, (int, float)) or \
                isinstance(self.z_threshold, bool) or self.z_threshold <= 0:
            raise DeepSpeedConfigError(
                f"{blk}.{C.TELEMETRY_HEALTH_Z_THRESHOLD} must be a "
                f"positive number, got {self.z_threshold!r}")
        if not isinstance(self.ewma_alpha, (int, float)) or \
                isinstance(self.ewma_alpha, bool) or \
                not (0.0 < float(self.ewma_alpha) <= 1.0):
            raise DeepSpeedConfigError(
                f"{blk}.{C.TELEMETRY_HEALTH_EWMA_ALPHA} must be in "
                f"(0, 1], got {self.ewma_alpha!r}")
        if not isinstance(self.warmup_steps, int) or self.warmup_steps < 0:
            raise DeepSpeedConfigError(
                f"{blk}.{C.TELEMETRY_HEALTH_WARMUP_STEPS} must be a "
                f"non-negative int, got {self.warmup_steps!r}")
        for name, v in ((C.TELEMETRY_HEALTH_WATCHDOG_FACTOR,
                         self.watchdog_factor),
                        (C.TELEMETRY_HEALTH_WATCHDOG_MIN_S,
                         self.watchdog_min_s)):
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v <= 0:
                raise DeepSpeedConfigError(
                    f"{blk}.{name} must be a positive number, got {v!r}")
        if not isinstance(self.flight_window, int) or \
                self.flight_window <= 0:
            raise DeepSpeedConfigError(
                f"{blk}.{C.TELEMETRY_HEALTH_FLIGHT_WINDOW} must be a "
                f"positive int, got {self.flight_window!r}")


class CheckpointConfig:
    """The ``checkpoint`` block (runtime/async_ckpt.py + the engine's
    save/load paths): async snapshot-to-host saving, the auto-save
    cadence, and the preemption (SIGTERM) final-save handler. Tag
    validation stays on the DeepSpeedConfig top level for
    compatibility."""

    def __init__(self, param_dict: Optional[Dict[str, Any]] = None):
        d = (param_dict or {}).get(C.CHECKPOINT, {})
        get = config_utils.get_scalar_param
        self.async_save = get(d, C.CHECKPOINT_ASYNC,
                              C.CHECKPOINT_ASYNC_DEFAULT)
        self.snapshot_every = get(d, C.CHECKPOINT_SNAPSHOT_EVERY,
                                  C.CHECKPOINT_SNAPSHOT_EVERY_DEFAULT)
        self.save_dir = get(d, C.CHECKPOINT_SAVE_DIR,
                            C.CHECKPOINT_SAVE_DIR_DEFAULT)
        self.preempt_save = get(d, C.CHECKPOINT_PREEMPT_SAVE,
                                C.CHECKPOINT_PREEMPT_SAVE_DEFAULT)
        self.max_pending_snapshots = get(d, C.CHECKPOINT_MAX_PENDING,
                                         C.CHECKPOINT_MAX_PENDING_DEFAULT)
        self.writer_timeout_s = get(d, C.CHECKPOINT_WRITER_TIMEOUT_S,
                                    C.CHECKPOINT_WRITER_TIMEOUT_S_DEFAULT)
        self.fsync = get(d, C.CHECKPOINT_FSYNC, C.CHECKPOINT_FSYNC_DEFAULT)
        self._validate()

    def _validate(self) -> None:
        blk = C.CHECKPOINT
        for name, v in ((C.CHECKPOINT_ASYNC, self.async_save),
                        (C.CHECKPOINT_PREEMPT_SAVE, self.preempt_save),
                        (C.CHECKPOINT_FSYNC, self.fsync)):
            if not isinstance(v, bool):
                raise DeepSpeedConfigError(
                    f"{blk}.{name} must be a bool, got {v!r}")
        if not isinstance(self.snapshot_every, int) or \
                isinstance(self.snapshot_every, bool) or \
                self.snapshot_every < 0:
            raise DeepSpeedConfigError(
                f"{blk}.{C.CHECKPOINT_SNAPSHOT_EVERY} must be a "
                f"non-negative int (0 = no auto-save), got "
                f"{self.snapshot_every!r}")
        if not isinstance(self.save_dir, str):
            raise DeepSpeedConfigError(
                f"{blk}.{C.CHECKPOINT_SAVE_DIR} must be a string path, "
                f"got {self.save_dir!r}")
        if self.snapshot_every > 0 and not self.save_dir:
            raise DeepSpeedConfigError(
                f"{blk}.{C.CHECKPOINT_SNAPSHOT_EVERY} > 0 needs "
                f"{blk}.{C.CHECKPOINT_SAVE_DIR}: auto-saves have to land "
                "somewhere")
        if not isinstance(self.max_pending_snapshots, int) or \
                isinstance(self.max_pending_snapshots, bool) or \
                self.max_pending_snapshots < 1:
            raise DeepSpeedConfigError(
                f"{blk}.{C.CHECKPOINT_MAX_PENDING} must be an int >= 1 "
                f"(each pending snapshot is a full host state copy), got "
                f"{self.max_pending_snapshots!r}")
        if not isinstance(self.writer_timeout_s, (int, float)) or \
                isinstance(self.writer_timeout_s, bool) or \
                self.writer_timeout_s <= 0:
            raise DeepSpeedConfigError(
                f"{blk}.{C.CHECKPOINT_WRITER_TIMEOUT_S} must be a "
                f"positive number, got {self.writer_timeout_s!r}")


class TelemetryProfileConfig:
    """The ``telemetry.profile`` block (monitor/profile_ingest.py +
    reconcile.py): the jax.profiler capture window, trace ingestion, and
    measured-vs-floor reconciliation thresholds. The legacy flat
    ``telemetry.profile_start_step``/``profile_num_steps``/``profile_dir``
    keys remain as aliases; an explicit nested block wins."""

    def __init__(self, d: Optional[Dict[str, Any]] = None,
                 legacy_start: int = C.TELEMETRY_PROFILE_START_STEP_DEFAULT,
                 legacy_steps: int = C.TELEMETRY_PROFILE_NUM_STEPS_DEFAULT,
                 legacy_dir: str = C.TELEMETRY_PROFILE_DIR_DEFAULT):
        d = d or {}
        get = config_utils.get_scalar_param
        self.start_step = get(d, C.TELEMETRY_PROFILE_BLOCK_START,
                              legacy_start)
        legacy_armed = isinstance(legacy_start, int) and \
            not isinstance(legacy_start, bool) and legacy_start >= 0
        self.window_steps = get(
            d, C.TELEMETRY_PROFILE_BLOCK_STEPS,
            legacy_steps if legacy_armed
            else C.TELEMETRY_PROFILE_BLOCK_STEPS_DEFAULT)
        self.out_dir = get(d, C.TELEMETRY_PROFILE_BLOCK_DIR, legacy_dir)
        self.divergence_threshold = get(
            d, C.TELEMETRY_PROFILE_THRESHOLD,
            C.TELEMETRY_PROFILE_THRESHOLD_DEFAULT)
        self.host_frac = get(d, C.TELEMETRY_PROFILE_HOST_FRAC,
                             C.TELEMETRY_PROFILE_HOST_FRAC_DEFAULT)
        self._validate()

    def _validate(self) -> None:
        blk = f"{C.TELEMETRY}.{C.TELEMETRY_PROFILE}"
        if not isinstance(self.start_step, int) or \
                isinstance(self.start_step, bool):
            raise DeepSpeedConfigError(
                f"{blk}.{C.TELEMETRY_PROFILE_BLOCK_START} must be an int "
                f"(-1 = off), got {self.start_step!r}")
        if not isinstance(self.window_steps, int) or \
                isinstance(self.window_steps, bool) or \
                self.window_steps <= 0:
            raise DeepSpeedConfigError(
                f"{blk}.{C.TELEMETRY_PROFILE_BLOCK_STEPS} must be a "
                f"positive int, got {self.window_steps!r}")
        for name, v in ((C.TELEMETRY_PROFILE_THRESHOLD,
                         self.divergence_threshold),
                        (C.TELEMETRY_PROFILE_HOST_FRAC, self.host_frac)):
            if not isinstance(v, (int, float)) or isinstance(v, bool) or \
                    v <= 0:
                raise DeepSpeedConfigError(
                    f"{blk}.{name} must be a positive number, got {v!r}")
        if not isinstance(self.out_dir, str):
            raise DeepSpeedConfigError(
                f"{blk}.{C.TELEMETRY_PROFILE_BLOCK_DIR} must be a string, "
                f"got {self.out_dir!r}")


class TelemetryConfig:
    """The ``telemetry`` block (monitor/ subsystem).

    Subsumes the ``tensorboard`` block, which stays as an alias: a config
    with only ``tensorboard.enabled`` gets an enabled telemetry sink with
    the tensorboard block's output_path/job_name (and the tensorboard
    writer itself, when importable). An explicit ``telemetry`` key always
    wins over the alias.
    """

    def __init__(self, param_dict: Optional[Dict[str, Any]] = None,
                 tensorboard: Optional[TensorboardConfig] = None):
        d = (param_dict or {}).get(C.TELEMETRY, {})
        tb = tensorboard or TensorboardConfig(param_dict)
        get = config_utils.get_scalar_param
        self.enabled = get(d, C.TELEMETRY_ENABLED, bool(tb.enabled))
        self.output_path = get(d, C.TELEMETRY_OUTPUT_PATH,
                               tb.output_path or
                               C.TELEMETRY_OUTPUT_PATH_DEFAULT)
        self.job_name = get(d, C.TELEMETRY_JOB_NAME,
                            tb.job_name if tb.enabled
                            else C.TELEMETRY_JOB_NAME_DEFAULT)
        self.tensorboard = bool(tb.enabled)
        self.buffer_size = get(d, C.TELEMETRY_BUFFER_SIZE,
                               C.TELEMETRY_BUFFER_SIZE_DEFAULT)
        self.report_steps = get(d, C.TELEMETRY_REPORT_STEPS,
                                C.TELEMETRY_REPORT_STEPS_DEFAULT)
        self.trace_path = get(d, C.TELEMETRY_TRACE_PATH,
                              C.TELEMETRY_TRACE_PATH_DEFAULT)
        self.fail_on_recompile = get(d, C.TELEMETRY_FAIL_ON_RECOMPILE,
                                     C.TELEMETRY_FAIL_ON_RECOMPILE_DEFAULT)
        self.recompile_warmup_calls = get(d, C.TELEMETRY_RECOMPILE_WARMUP,
                                          C.TELEMETRY_RECOMPILE_WARMUP_DEFAULT)
        self.memory_watermarks = get(d, C.TELEMETRY_MEMORY_WATERMARKS,
                                     C.TELEMETRY_MEMORY_WATERMARKS_DEFAULT)
        self.watermark_ratio = get(d, C.TELEMETRY_WATERMARK_RATIO,
                                   C.TELEMETRY_WATERMARK_RATIO_DEFAULT)
        self.watermark_slack_bytes = get(
            d, C.TELEMETRY_WATERMARK_SLACK_BYTES,
            C.TELEMETRY_WATERMARK_SLACK_BYTES_DEFAULT)
        legacy_start = get(d, C.TELEMETRY_PROFILE_START_STEP,
                           C.TELEMETRY_PROFILE_START_STEP_DEFAULT)
        legacy_steps = get(d, C.TELEMETRY_PROFILE_NUM_STEPS,
                           C.TELEMETRY_PROFILE_NUM_STEPS_DEFAULT)
        legacy_dir = get(d, C.TELEMETRY_PROFILE_DIR,
                         C.TELEMETRY_PROFILE_DIR_DEFAULT)
        self.profile = TelemetryProfileConfig(
            d.get(C.TELEMETRY_PROFILE), legacy_start=legacy_start,
            legacy_steps=legacy_steps, legacy_dir=legacy_dir)
        # Flat aliases kept in sync with the resolved block (telemetry.py
        # and older callers read these).
        self.profile_start_step = self.profile.start_step
        self.profile_num_steps = self.profile.window_steps
        self.profile_dir = self.profile.out_dir
        self.cost_model = get(d, C.TELEMETRY_COST_MODEL,
                              C.TELEMETRY_COST_MODEL_DEFAULT)
        self.per_host_shards = get(d, C.TELEMETRY_PER_HOST,
                                   C.TELEMETRY_PER_HOST_DEFAULT)
        self.health = TelemetryHealthConfig(d.get(C.TELEMETRY_HEALTH))
        self._validate()

    def _validate(self) -> None:
        if not isinstance(self.buffer_size, int) or self.buffer_size <= 0:
            raise DeepSpeedConfigError(
                f"{C.TELEMETRY}.{C.TELEMETRY_BUFFER_SIZE} must be a "
                f"positive int, got {self.buffer_size!r}")
        if not isinstance(self.report_steps, int) or self.report_steps < 0:
            raise DeepSpeedConfigError(
                f"{C.TELEMETRY}.{C.TELEMETRY_REPORT_STEPS} must be a "
                f"non-negative int (0 = follow steps_per_print), got "
                f"{self.report_steps!r}")
        if not isinstance(self.recompile_warmup_calls, int) or \
                self.recompile_warmup_calls < 0:
            raise DeepSpeedConfigError(
                f"{C.TELEMETRY}.{C.TELEMETRY_RECOMPILE_WARMUP} must be a "
                f"non-negative int, got {self.recompile_warmup_calls!r}")
        if not isinstance(self.watermark_ratio, (int, float)) or \
                self.watermark_ratio <= 0:
            raise DeepSpeedConfigError(
                f"{C.TELEMETRY}.{C.TELEMETRY_WATERMARK_RATIO} must be a "
                f"positive number, got {self.watermark_ratio!r}")
        if not isinstance(self.cost_model, bool):
            raise DeepSpeedConfigError(
                f"{C.TELEMETRY}.{C.TELEMETRY_COST_MODEL} must be a bool, "
                f"got {self.cost_model!r}")
        if not isinstance(self.per_host_shards, bool):
            raise DeepSpeedConfigError(
                f"{C.TELEMETRY}.{C.TELEMETRY_PER_HOST} must be a bool, "
                f"got {self.per_host_shards!r}")


class InferenceSloConfig:
    """The ``inference.slo`` block (monitor/serving_slo.py): TTFT/TPOT
    targets, availability target, and the trailing attainment window.
    Both latency targets unset (0) leaves the tracker off — snapshots
    then omit the ``slo`` section entirely."""

    def __init__(self, d: Optional[Dict[str, Any]] = None):
        d = d or {}
        get = config_utils.get_scalar_param
        self.ttft_ms = get(d, C.INFERENCE_SLO_TTFT_MS,
                           C.INFERENCE_SLO_TTFT_MS_DEFAULT)
        self.tpot_ms = get(d, C.INFERENCE_SLO_TPOT_MS,
                           C.INFERENCE_SLO_TPOT_MS_DEFAULT)
        self.availability = get(d, C.INFERENCE_SLO_AVAILABILITY,
                                C.INFERENCE_SLO_AVAILABILITY_DEFAULT)
        self.window_s = get(d, C.INFERENCE_SLO_WINDOW_S,
                            C.INFERENCE_SLO_WINDOW_S_DEFAULT)
        self._validate()

    @property
    def enabled(self) -> bool:
        return self.ttft_ms > 0 or self.tpot_ms > 0

    def _validate(self) -> None:
        blk = f"{C.INFERENCE}.{C.INFERENCE_SLO}"
        for name, v in ((C.INFERENCE_SLO_TTFT_MS, self.ttft_ms),
                        (C.INFERENCE_SLO_TPOT_MS, self.tpot_ms)):
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v < 0:
                raise DeepSpeedConfigError(
                    f"{blk}.{name} must be a non-negative number "
                    f"(0 = target unset), got {v!r}")
        if not isinstance(self.availability, (int, float)) \
                or isinstance(self.availability, bool) \
                or not (0.0 < self.availability < 1.0):
            raise DeepSpeedConfigError(
                f"{blk}.{C.INFERENCE_SLO_AVAILABILITY} must be a number "
                f"in (0, 1), got {self.availability!r}")
        if not isinstance(self.window_s, (int, float)) \
                or isinstance(self.window_s, bool) or self.window_s <= 0:
            raise DeepSpeedConfigError(
                f"{blk}.{C.INFERENCE_SLO_WINDOW_S} must be a positive "
                f"number of seconds, got {self.window_s!r}")


class InferenceConfig:
    """The ``inference`` block (inference/ serving subsystem).

    Every knob here is STATIC compiled-program shape: slot count, cache
    sequence capacity, weight quantization mode, prefill chunk length.
    The continuous-batching scheduler varies the ACTIVE request set at
    run time without touching any of them — that is what keeps the
    decode step at one compilation for the whole serve.
    """

    def __init__(self, param_dict: Optional[Dict[str, Any]] = None):
        d = (param_dict or {}).get(C.INFERENCE, {})
        get = config_utils.get_scalar_param
        self.max_slots = get(d, C.INFERENCE_MAX_SLOTS,
                             C.INFERENCE_MAX_SLOTS_DEFAULT)
        self.max_seq_len = get(d, C.INFERENCE_MAX_SEQ_LEN,
                               C.INFERENCE_MAX_SEQ_LEN_DEFAULT)
        self.quantize = get(d, C.INFERENCE_QUANTIZE,
                            C.INFERENCE_QUANTIZE_DEFAULT)
        self.prefill_chunk = get(d, C.INFERENCE_PREFILL_CHUNK,
                                 C.INFERENCE_PREFILL_CHUNK_DEFAULT)
        self.block_size = get(d, C.INFERENCE_BLOCK_SIZE,
                              C.INFERENCE_BLOCK_SIZE_DEFAULT)
        self.num_blocks = get(d, C.INFERENCE_NUM_BLOCKS,
                              C.INFERENCE_NUM_BLOCKS_DEFAULT)
        self.spec_k = get(d, C.INFERENCE_SPEC_K, C.INFERENCE_SPEC_K_DEFAULT)
        self.spec_ngram = get(d, C.INFERENCE_SPEC_NGRAM,
                              C.INFERENCE_SPEC_NGRAM_DEFAULT)
        self.kv_cache_dtype = get(d, C.INFERENCE_KV_DTYPE,
                                  C.INFERENCE_KV_DTYPE_DEFAULT)
        self.replica = get(d, C.INFERENCE_REPLICA,
                           C.INFERENCE_REPLICA_DEFAULT)
        self.paged_kernel = get(d, C.INFERENCE_PAGED_KERNEL,
                                C.INFERENCE_PAGED_KERNEL_DEFAULT)
        slo_d = d.get(C.INFERENCE_SLO)
        if slo_d is not None and not isinstance(slo_d, dict):
            raise DeepSpeedConfigError(
                f"{C.INFERENCE}.{C.INFERENCE_SLO} must be a dict block, "
                f"got {slo_d!r}")
        self.slo = InferenceSloConfig(slo_d)
        self._validate()

    def _validate(self) -> None:
        if not isinstance(self.max_slots, int) or self.max_slots <= 0:
            raise DeepSpeedConfigError(
                f"{C.INFERENCE}.{C.INFERENCE_MAX_SLOTS} must be a positive "
                f"int, got {self.max_slots!r}")
        if not isinstance(self.max_seq_len, int) or self.max_seq_len < 0:
            raise DeepSpeedConfigError(
                f"{C.INFERENCE}.{C.INFERENCE_MAX_SEQ_LEN} must be a "
                f"non-negative int (0 = model max), got "
                f"{self.max_seq_len!r}")
        if self.quantize not in C.INFERENCE_QUANTIZE_MODES:
            raise DeepSpeedConfigError(
                f"{C.INFERENCE}.{C.INFERENCE_QUANTIZE} must be one of "
                f"{C.INFERENCE_QUANTIZE_MODES}, got {self.quantize!r}")
        if not isinstance(self.prefill_chunk, int) or self.prefill_chunk < 0:
            raise DeepSpeedConfigError(
                f"{C.INFERENCE}.{C.INFERENCE_PREFILL_CHUNK} must be a "
                f"non-negative int (0 = whole-prompt prefill), got "
                f"{self.prefill_chunk!r}")
        if not isinstance(self.block_size, int) or self.block_size < 0:
            raise DeepSpeedConfigError(
                f"{C.INFERENCE}.{C.INFERENCE_BLOCK_SIZE} must be a "
                f"non-negative int (0 = slot-major layout), got "
                f"{self.block_size!r}")
        if not isinstance(self.num_blocks, int) or self.num_blocks < 0:
            raise DeepSpeedConfigError(
                f"{C.INFERENCE}.{C.INFERENCE_NUM_BLOCKS} must be a "
                f"non-negative int (0 = full provisioning), got "
                f"{self.num_blocks!r}")
        if not isinstance(self.spec_k, int) or self.spec_k < 0:
            raise DeepSpeedConfigError(
                f"{C.INFERENCE}.{C.INFERENCE_SPEC_K} must be a "
                f"non-negative int (0 = speculative decoding off), got "
                f"{self.spec_k!r}")
        if self.spec_k > 0 and self.block_size == 0:
            raise DeepSpeedConfigError(
                f"{C.INFERENCE}.{C.INFERENCE_SPEC_K} requires the paged "
                f"cache ({C.INFERENCE_BLOCK_SIZE} > 0) — the verify step "
                "writes draft K/V through the block table")
        if not isinstance(self.spec_ngram, int) or self.spec_ngram < 1:
            raise DeepSpeedConfigError(
                f"{C.INFERENCE}.{C.INFERENCE_SPEC_NGRAM} must be a "
                f"positive int, got {self.spec_ngram!r}")
        if self.kv_cache_dtype not in C.INFERENCE_KV_DTYPE_MODES:
            raise DeepSpeedConfigError(
                f"{C.INFERENCE}.{C.INFERENCE_KV_DTYPE} must be one of "
                f"{C.INFERENCE_KV_DTYPE_MODES}, got "
                f"{self.kv_cache_dtype!r}")
        if not isinstance(self.replica, str):
            raise DeepSpeedConfigError(
                f"{C.INFERENCE}.{C.INFERENCE_REPLICA} must be a string "
                f"label, got {self.replica!r}")
        if self.paged_kernel not in (True, False, "auto"):
            raise DeepSpeedConfigError(
                f"{C.INFERENCE}.{C.INFERENCE_PAGED_KERNEL} must be true, "
                f"false, or \"auto\", got {self.paged_kernel!r}")


class MoeConfig:
    """The ``moe`` block (deepspeed_tpu/moe/ expert parallelism).

    ``num_experts == 0`` (the default) leaves the block inert. The
    engine reads it for the `expert` mesh axis, the MoE metrics schema,
    and the all-to-all wire model; build the model's
    ``TransformerConfig.moe`` from it via ``MoEConfig.from_ds_config``.
    """

    def __init__(self, param_dict: Optional[Dict[str, Any]] = None):
        d = (param_dict or {}).get(C.MOE, {})
        get = config_utils.get_scalar_param
        self.num_experts = get(d, C.MOE_NUM_EXPERTS,
                               C.MOE_NUM_EXPERTS_DEFAULT)
        self.top_k = get(d, C.MOE_TOP_K, C.MOE_TOP_K_DEFAULT)
        self.capacity_factor = get(d, C.MOE_CAPACITY_FACTOR,
                                   C.MOE_CAPACITY_FACTOR_DEFAULT)
        self.aux_loss_weight = get(d, C.MOE_AUX_LOSS_WEIGHT,
                                   C.MOE_AUX_LOSS_WEIGHT_DEFAULT)
        self.z_loss_weight = get(d, C.MOE_Z_LOSS_WEIGHT,
                                 C.MOE_Z_LOSS_WEIGHT_DEFAULT)
        self.expert_parallel_size = get(d, C.MOE_EXPERT_PARALLEL_SIZE,
                                        C.MOE_EXPERT_PARALLEL_SIZE_DEFAULT)
        self.grouped_gemm = get(d, C.MOE_GROUPED_GEMM,
                                C.MOE_GROUPED_GEMM_DEFAULT)
        self._validate()

    def _validate(self) -> None:
        blk = C.MOE
        if not isinstance(self.num_experts, int) or self.num_experts < 0:
            raise DeepSpeedConfigError(
                f"{blk}.{C.MOE_NUM_EXPERTS} must be a non-negative int "
                f"(0 = disabled), got {self.num_experts!r}")
        if not isinstance(self.expert_parallel_size, int) or \
                self.expert_parallel_size < 1:
            raise DeepSpeedConfigError(
                f"{blk}.{C.MOE_EXPERT_PARALLEL_SIZE} must be a positive "
                f"int, got {self.expert_parallel_size!r}")
        if self.grouped_gemm not in (True, False, "auto"):
            raise DeepSpeedConfigError(
                f"{blk}.{C.MOE_GROUPED_GEMM} must be true/false/"
                f"\"auto\", got {self.grouped_gemm!r}")
        if self.num_experts == 0:
            if self.expert_parallel_size > 1:
                raise DeepSpeedConfigError(
                    f"{blk}.{C.MOE_EXPERT_PARALLEL_SIZE} > 1 needs "
                    f"{C.MOE_NUM_EXPERTS} > 0")
            return
        if self.top_k not in (1, 2):
            raise DeepSpeedConfigError(
                f"{blk}.{C.MOE_TOP_K} must be 1 or 2, got {self.top_k!r}")
        if self.top_k > self.num_experts:
            raise DeepSpeedConfigError(
                f"{blk}.{C.MOE_TOP_K}={self.top_k} exceeds "
                f"{C.MOE_NUM_EXPERTS}={self.num_experts}")
        cf = self.capacity_factor
        if isinstance(cf, bool) or not isinstance(cf, (int, float)) or \
                not cf > 0:
            raise DeepSpeedConfigError(
                f"{blk}.{C.MOE_CAPACITY_FACTOR} must be a positive "
                f"number (inf = never drop), got {cf!r}")
        for name, v in ((C.MOE_AUX_LOSS_WEIGHT, self.aux_loss_weight),
                        (C.MOE_Z_LOSS_WEIGHT, self.z_loss_weight)):
            if isinstance(v, bool) or not isinstance(v, (int, float)) \
                    or v < 0:
                raise DeepSpeedConfigError(
                    f"{blk}.{name} must be a non-negative number, "
                    f"got {v!r}")
        if self.num_experts % self.expert_parallel_size != 0:
            raise DeepSpeedConfigError(
                f"{blk}.{C.MOE_NUM_EXPERTS}={self.num_experts} not "
                f"divisible by {C.MOE_EXPERT_PARALLEL_SIZE}="
                f"{self.expert_parallel_size}")


class MeshConfig:
    """TPU-native extension: requested logical mesh axis sizes.

    Sizes of -1 / None are inferred (dp absorbs the remainder of the device
    count after mp/pp/sp are fixed).
    """

    def __init__(self, param_dict: Optional[Dict[str, Any]] = None):
        d = (param_dict or {}).get(C.MESH, {})
        get = config_utils.get_scalar_param
        self.data_parallel_size = get(d, C.MESH_DATA_PARALLEL_SIZE, None)
        self.model_parallel_size = get(d, C.MESH_MODEL_PARALLEL_SIZE, 1)
        self.pipe_parallel_size = get(d, C.MESH_PIPE_PARALLEL_SIZE, 1)
        self.sequence_parallel_size = get(d, C.MESH_SEQUENCE_PARALLEL_SIZE, 1)
        # Multi-slice scale-out: ICI domains joined by DCN; the `slice`
        # mesh axis is OUTERMOST and dp factors within a slice.
        self.num_slices = get(d, C.MESH_NUM_SLICES, 1)
        if not isinstance(self.num_slices, int) or self.num_slices < 1:
            raise DeepSpeedConfigError(
                f"{C.MESH}.{C.MESH_NUM_SLICES} must be a positive int "
                f"(ICI domains the mesh spans), got {self.num_slices!r}")


class DeepSpeedConfig:
    def __init__(self, config: Union[str, Dict[str, Any]], mpu=None,
                 param_dict: Optional[Dict[str, Any]] = None,
                 world_size: Optional[int] = None):
        if param_dict is not None:
            self._param_dict = param_dict
        elif isinstance(config, dict):
            self._param_dict = config
        else:
            self._param_dict = config_utils.load_config_json(config)

        # Data-parallel world size for the batch solver: the mesh dp-axis
        # size. Resolution order mirrors the reference's mpu override
        # (config.py:523-535).
        if world_size is not None:
            self.world_size = world_size
        elif mpu is not None:
            self.world_size = mpu.get_data_parallel_world_size()
        else:
            self.world_size = self._infer_default_world_size()

        # Elasticity pre-pass (reference config.py:537-588).
        self.elasticity_enabled = False
        self._configure_elasticity()

        self._initialize_params(self._param_dict)
        self._configure_train_batch_size()
        self._do_sanity_check()

    # ------------------------------------------------------------------ #
    def _infer_default_world_size(self) -> int:
        import os
        if "WORLD_SIZE" in os.environ:
            return int(os.environ["WORLD_SIZE"])
        try:
            import jax
            mesh = self._param_dict.get(C.MESH, {})
            mp = mesh.get(C.MESH_MODEL_PARALLEL_SIZE, 1) or 1
            pp = mesh.get(C.MESH_PIPE_PARALLEL_SIZE, 1) or 1
            sp = mesh.get(C.MESH_SEQUENCE_PARALLEL_SIZE, 1) or 1
            return max(1, jax.device_count() // (mp * pp * sp))
        except Exception:
            return 1

    def _configure_elasticity(self) -> None:
        from ..elasticity import elasticity_enabled, compute_elastic_config
        if not elasticity_enabled(self._param_dict):
            return
        from ..elasticity.config import ElasticityConfigError
        elastic_dict = self._param_dict[C.ELASTICITY]
        ignore_non_elastic = elastic_dict.get(
            C.IGNORE_NON_ELASTIC_BATCH_INFO, C.IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)
        if not ignore_non_elastic:
            batch_params = (C.TRAIN_BATCH_SIZE, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                            C.GRADIENT_ACCUMULATION_STEPS)
            if any(self._param_dict.get(k) is not None for k in batch_params):
                raise ElasticityConfigError(
                    "One or more batch related parameters were found in your ds_config "
                    f"({', '.join(batch_params)}). These parameters *will not be used* since "
                    "elastic training is enabled, which takes control of these parameters. "
                    f"If you want to supress this error set '{C.IGNORE_NON_ELASTIC_BATCH_INFO}':true "
                    "in your elasticity config.")
        final_batch_size, valid_gpus, micro_batch_size = compute_elastic_config(
            ds_config=self._param_dict, target_deepspeed_version="0.1.0",
            world_size=self.world_size)
        self.elastic_train_batch_size = final_batch_size
        self.elastic_valid_gpus = valid_gpus
        self.elasticity_enabled = True
        self._param_dict[C.TRAIN_BATCH_SIZE] = final_batch_size
        self._param_dict[C.TRAIN_MICRO_BATCH_SIZE_PER_GPU] = micro_batch_size
        self._param_dict[C.GRADIENT_ACCUMULATION_STEPS] = None

    # ------------------------------------------------------------------ #
    def _initialize_params(self, d: Dict[str, Any]) -> None:
        get = config_utils.get_scalar_param

        self.train_batch_size = get(d, C.TRAIN_BATCH_SIZE, C.TRAIN_BATCH_SIZE_DEFAULT)
        self.train_micro_batch_size_per_gpu = get(
            d, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)
        self.gradient_accumulation_steps = get(
            d, C.GRADIENT_ACCUMULATION_STEPS, C.GRADIENT_ACCUMULATION_STEPS_DEFAULT)
        self.steps_per_print = get(d, C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT)
        self.dump_state = get(d, C.DUMP_STATE, C.DUMP_STATE_DEFAULT)
        self.disable_allgather = get(d, C.DISABLE_ALLGATHER, C.DISABLE_ALLGATHER_DEFAULT)

        self.prescale_gradients = get(d, C.PRESCALE_GRADIENTS, C.PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_predivide_factor = get(
            d, C.GRADIENT_PREDIVIDE_FACTOR, C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT)
        self.sparse_gradients_enabled = get(d, C.SPARSE_GRADIENTS, C.SPARSE_GRADIENTS_DEFAULT)
        self.allreduce_always_fp32 = get(d, C.ALLREDUCE_ALWAYS_FP32,
                                         C.ALLREDUCE_ALWAYS_FP32_DEFAULT)

        self.zero_config = ZeroConfig(d)
        self.zero_optimization_stage = self.zero_config.stage
        self.zero_enabled = self.zero_optimization_stage > 0

        self.activation_checkpointing_config = ActivationCheckpointingConfig(d)
        self.flops_profiler_config = FlopsProfilerConfig(d)
        self.pld_config = ProgressiveLayerDropConfig(d)
        self.pipeline_config = PipelineConfig(d)
        self.tensorboard_config = TensorboardConfig(d)
        self.telemetry_config = TelemetryConfig(
            d, tensorboard=self.tensorboard_config)
        self.inference_config = InferenceConfig(d)
        self.mesh_config = MeshConfig(d)
        self.moe_config = MoeConfig(d)

        fp16 = d.get(C.FP16, {})
        self.fp16_enabled = get(fp16, C.FP16_ENABLED, C.FP16_ENABLED_DEFAULT)
        self.fp16_loss_scale = get(fp16, C.FP16_LOSS_SCALE, C.FP16_LOSS_SCALE_DEFAULT)
        self.fp16_initial_scale_power = get(fp16, C.FP16_INITIAL_SCALE_POWER,
                                            C.FP16_INITIAL_SCALE_POWER_DEFAULT)
        self.fp16_loss_scale_window = get(fp16, C.FP16_LOSS_SCALE_WINDOW,
                                          C.FP16_LOSS_SCALE_WINDOW_DEFAULT)
        self.fp16_hysteresis = get(fp16, C.FP16_HYSTERESIS, C.FP16_HYSTERESIS_DEFAULT)
        self.fp16_min_loss_scale = get(fp16, C.FP16_MIN_LOSS_SCALE,
                                       C.FP16_MIN_LOSS_SCALE_DEFAULT)

        bf16 = d.get(C.BF16, {})
        self.bf16_enabled = get(bf16, C.BF16_ENABLED, C.BF16_ENABLED_DEFAULT)
        self.bf16_stochastic_rounding = get(
            bf16, C.BF16_STOCHASTIC_ROUNDING,
            C.BF16_STOCHASTIC_ROUNDING_DEFAULT)

        amp = d.get(C.AMP, {})
        self.amp_enabled = get(amp, C.AMP_ENABLED, C.AMP_ENABLED_DEFAULT)
        self.amp_params = {k: v for k, v in amp.items() if k != C.AMP_ENABLED}
        # amp acts or raises — silent-ignore is the one unacceptable state
        # (reference engine.py:630-668 wraps apex amp). On TPU the amp
        # semantic (mixed-precision compute, fp32 masters) IS the bf16
        # path, so "amp": {"enabled": true} maps onto it with a notice;
        # combined with fp16 it raises instead of guessing.
        if self.amp_enabled:
            if self.fp16_enabled:
                raise DeepSpeedConfigError(
                    "amp and fp16 cannot both be enabled: on TPU amp maps "
                    "to the bf16 mixed-precision path — pick `bf16` (or "
                    "`amp` alone) or `fp16`")
            if not self.bf16_enabled:
                self.bf16_enabled = True
                logger.info(
                    "amp: enabled -> mapped to the bf16 mixed-precision "
                    "path (TPU has no apex; bf16 is the amp-equivalent "
                    "O1 mode). Set bf16.enabled directly to silence this.")

        self.gradient_clipping = get(d, C.GRADIENT_CLIPPING, C.GRADIENT_CLIPPING_DEFAULT)

        optimizer = d.get(C.OPTIMIZER)
        if optimizer is not None:
            self.optimizer_name = optimizer.get(C.TYPE, C.OPTIMIZER_TYPE_DEFAULT)
            if self.optimizer_name is not None:
                self.optimizer_name = self.optimizer_name.lower()
            self.optimizer_params = optimizer.get(C.OPTIMIZER_PARAMS, {})
            self.optimizer_legacy_fusion = optimizer.get(C.LEGACY_FUSION,
                                                         C.LEGACY_FUSION_DEFAULT)
        else:
            self.optimizer_name = None
            self.optimizer_params = {}
            self.optimizer_legacy_fusion = False
        # optimizer.params.fused: the Pallas single-pass multi-tensor apply
        # (ops/fused_update.py). Default on; build_optimizer only honors it
        # for the Adam family, and the engine falls back to the optax chain
        # where fusion does not compose (TP param layouts).
        self.optimizer_fused = bool((self.optimizer_params or {}).get(
            C.OPTIMIZER_FUSED, C.OPTIMIZER_FUSED_DEFAULT))

        scheduler = d.get(C.SCHEDULER)
        if scheduler is not None:
            self.scheduler_name = scheduler.get(C.TYPE, C.SCHEDULER_TYPE_DEFAULT)
            self.scheduler_params = scheduler.get(C.SCHEDULER_PARAMS, {})
        else:
            self.scheduler_name = None
            self.scheduler_params = {}

        self.wall_clock_breakdown = get(d, C.WALL_CLOCK_BREAKDOWN,
                                        C.WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = get(d, C.MEMORY_BREAKDOWN, C.MEMORY_BREAKDOWN_DEFAULT)

        # Normalized like the reference's get_sparse_attention
        # (config.py:192-362): mode-specific defaults filled, unknown modes
        # rejected at config time. sparsity_config_from_dict() turns this
        # into the layout object SparseSelfAttention consumes.
        from ..ops.sparse_attention.config_factory import \
            normalize_sparse_attention
        self.sparse_attention = normalize_sparse_attention(
            d.get(C.SPARSE_ATTENTION))

        ckpt = d.get(C.CHECKPOINT, {})
        self.checkpoint_config = CheckpointConfig(d)
        self.checkpoint_tag_validation_mode = get(
            ckpt, C.CHECKPOINT_TAG_VALIDATION, C.CHECKPOINT_TAG_VALIDATION_DEFAULT)
        if isinstance(self.checkpoint_tag_validation_mode, str):
            self.checkpoint_tag_validation_mode = self.checkpoint_tag_validation_mode.capitalize()
        self.checkpoint_tag_validation_enabled = \
            self.checkpoint_tag_validation_mode != "Ignore"
        self.checkpoint_tag_validation_fail = self.checkpoint_tag_validation_mode == "Fail"

    # ------------------------------------------------------------------ #
    def _configure_train_batch_size(self) -> None:
        """Solve train_batch = micro_batch * grad_accum * world (config.py:655-725)."""
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps
        world = self.world_size

        if train_batch is not None and micro_batch is not None and grad_acc is not None:
            pass  # all set; verified in sanity check
        elif train_batch is not None and micro_batch is not None:
            grad_acc = train_batch // micro_batch
            grad_acc //= world
            self.gradient_accumulation_steps = grad_acc
        elif train_batch is not None and grad_acc is not None:
            micro_batch = train_batch // world
            micro_batch //= grad_acc
            self.train_micro_batch_size_per_gpu = micro_batch
        elif micro_batch is not None and grad_acc is not None:
            self.train_batch_size = micro_batch * grad_acc * world
        elif train_batch is not None:
            self.gradient_accumulation_steps = 1
            self.train_micro_batch_size_per_gpu = train_batch // world
        elif micro_batch is not None:
            self.gradient_accumulation_steps = 1
            self.train_batch_size = micro_batch * world
        # else: all None → sanity check raises

    def _batch_assertion(self) -> None:
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps
        assert train_batch > 0, f"Train batch size: {train_batch} has to be greater than 0"
        assert micro_batch > 0, f"Micro batch size per device: {micro_batch} has to be greater than 0"
        assert grad_acc > 0, f"Gradient accumulation steps: {grad_acc} has to be greater than 0"
        assert train_batch == micro_batch * grad_acc * self.world_size, (
            f"Check batch related parameters. train_batch_size is not equal to "
            f"micro_batch_per_gpu * gradient_acc_step * world_size "
            f"{train_batch} != {micro_batch} * {grad_acc} * {self.world_size}")

    def _do_sanity_check(self) -> None:
        if self.train_batch_size is None and self.train_micro_batch_size_per_gpu is None:
            raise DeepSpeedConfigError(
                f"Either {C.TRAIN_BATCH_SIZE} or {C.TRAIN_MICRO_BATCH_SIZE_PER_GPU} "
                "must be set in the DeepSpeed config")
        self._batch_assertion()
        if self.fp16_enabled and self.bf16_enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")
        if self.bf16_stochastic_rounding and not self.bf16_enabled:
            raise DeepSpeedConfigError(
                "bf16.stochastic_rounding requires bf16.enabled (it is the "
                "master-free bf16 update mode)")
        if self.zero_enabled and self.zero_optimization_stage > C.MAX_STAGE_ZERO_OPTIMIZATION:
            raise DeepSpeedConfigError(
                f"ZeRO stage {self.zero_optimization_stage} > max "
                f"{C.MAX_STAGE_ZERO_OPTIMIZATION}")
        if self.zero_config.overlap_comm and not self.zero_enabled:
            logger.warning(
                f"{C.ZERO_OVERLAP_COMM} is set but zero_optimization is "
                "disabled — it only affects the ZeRO paths (for "
                "cpu_offload it selects the bucketed overlapped pipeline)")
        if self.optimizer_name is not None and \
                self.optimizer_name not in C.DEEPSPEED_OPTIMIZERS:
            logger.warning(
                f"Optimizer '{self.optimizer_name}' is not a built-in optimizer; "
                "it will be resolved against optax at engine construction.")

    # ------------------------------------------------------------------ #
    @property
    def precision_dtype(self) -> str:
        if self.bf16_enabled:
            return "bfloat16"
        if self.fp16_enabled:
            return "float16"
        return "float32"

    def print(self, name: str = "DeepSpeedConfig") -> None:
        logger.info(f"{name}:")
        for k in sorted(self.__dict__):
            if k.startswith("_"):
                continue
            logger.info(f"  {k} = {self.__dict__[k]}")
