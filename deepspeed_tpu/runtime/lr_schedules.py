"""Learning-rate schedules.

Parity with reference ``runtime/lr_schedules.py``: ``LRRangeTest``
(lr_schedules.py:301), ``OneCycle`` (:408), ``WarmupLR`` (:677),
``WarmupDecayLR`` (end of file), selected by name from the ds_config
``scheduler`` section with identical param keys.

TPU-native design: every schedule is fundamentally a *pure function of the
global step* (``as_schedule_fn()``), so the engine can close over it inside a
jitted train step (an ``optax``-style schedule). The stateful class API
(``step()``, ``get_lr()``, ``state_dict()``) is kept for reference parity and
host-side logging.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Union

import jax.numpy as jnp

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]

# ds_config scheduler param keys (names identical to the reference).
LR_RANGE_TEST_MIN_LR = "lr_range_test_min_lr"
LR_RANGE_TEST_STEP_RATE = "lr_range_test_step_rate"
LR_RANGE_TEST_STEP_SIZE = "lr_range_test_step_size"
LR_RANGE_TEST_STAIRCASE = "lr_range_test_staircase"

CYCLE_FIRST_STEP_SIZE = "cycle_first_step_size"
CYCLE_FIRST_STAIR_COUNT = "cycle_first_stair_count"
CYCLE_SECOND_STEP_SIZE = "cycle_second_step_size"
CYCLE_SECOND_STAIR_COUNT = "cycle_second_stair_count"
DECAY_STEP_SIZE = "decay_step_size"
CYCLE_MIN_LR = "cycle_min_lr"
CYCLE_MAX_LR = "cycle_max_lr"
DECAY_LR_RATE = "decay_lr_rate"
CYCLE_MIN_MOM = "cycle_min_mom"
CYCLE_MAX_MOM = "cycle_max_mom"
DECAY_MOM_RATE = "decay_mom_rate"
CYCLE_MOMENTUM = "cycle_momentum"

WARMUP_MIN_LR = "warmup_min_lr"
WARMUP_MAX_LR = "warmup_max_lr"
WARMUP_NUM_STEPS = "warmup_num_steps"
WARMUP_TYPE = "warmup_type"
WARMUP_LOG_RATE = "log"
WARMUP_LINEAR_RATE = "linear"

TOTAL_NUM_STEPS = "total_num_steps"


class _ScheduleBase:
    """Stateful wrapper around a pure step→lr function."""

    def __init__(self, optimizer=None, last_batch_iteration: int = -1):
        self.optimizer = optimizer
        self.last_batch_iteration = last_batch_iteration

    # -- pure API ------------------------------------------------------- #
    def lr_at(self, step):
        raise NotImplementedError

    def as_schedule_fn(self) -> Callable[[Any], Any]:
        """Return a jit-safe fn(step) → lr for use inside the train step."""
        return self.lr_at

    # -- stateful parity API ------------------------------------------- #
    def get_lr(self) -> List[float]:
        return [float(self.lr_at(max(0, self.last_batch_iteration)))]

    def get_last_lr(self) -> List[float]:
        return self.get_lr()

    def step(self, last_batch_iteration: Optional[int] = None) -> None:
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        if self.optimizer is not None and hasattr(self.optimizer, "set_lr"):
            self.optimizer.set_lr(self.get_lr()[0])

    def state_dict(self) -> Dict[str, Any]:
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.last_batch_iteration = sd["last_batch_iteration"]


class LRRangeTest(_ScheduleBase):
    """LR range test (Smith 2017): ramp lr by step_rate every step_size steps.

    lr(t) = min_lr * (1 + (t/step_size) * step_rate), continuous or staircase.
    """

    def __init__(self, optimizer=None, lr_range_test_min_lr: float = 1e-3,
                 lr_range_test_step_size: int = 2000,
                 lr_range_test_step_rate: float = 1.0,
                 lr_range_test_staircase: bool = False,
                 last_batch_iteration: int = -1):
        super().__init__(optimizer, last_batch_iteration)
        if lr_range_test_step_size <= 0:
            raise ValueError(f"step_size must be positive, got {lr_range_test_step_size}")
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase

    def lr_at(self, step):
        # Reference interval is (last_batch_iteration + 1) / step_size
        # (lr_schedules.py:369-373); `step` here is that +1-shifted count.
        ratio = step / self.step_size
        if self.staircase:
            ratio = jnp.floor(ratio) if not isinstance(step, int) else math.floor(ratio)
        return self.min_lr * (1.0 + ratio * self.step_rate)

    def get_lr(self) -> List[float]:
        return [float(self.lr_at(self.last_batch_iteration + 1))]


class OneCycle(_ScheduleBase):
    """1-cycle policy: ramp min→max over the first phase, back down over the
    second, then decay below min. Momentum optionally cycled inversely."""

    def __init__(self, optimizer=None, cycle_min_lr: float = 1e-3,
                 cycle_max_lr: float = 1e-2, decay_lr_rate: float = 0.0,
                 cycle_first_step_size: int = 2000,
                 cycle_second_step_size: Optional[int] = None,
                 cycle_first_stair_count: int = 0,
                 cycle_second_stair_count: Optional[int] = None,
                 decay_step_size: int = 0, cycle_momentum: bool = True,
                 cycle_min_mom: float = 0.85, cycle_max_mom: float = 0.99,
                 decay_mom_rate: float = 0.0, last_batch_iteration: int = -1):
        super().__init__(optimizer, last_batch_iteration)
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first_step_size = cycle_first_step_size
        self.second_step_size = cycle_second_step_size or cycle_first_step_size
        self.decay_step_size = decay_step_size
        self.total_cycle_size = self.first_step_size + self.second_step_size
        self.cycle_momentum = cycle_momentum
        self.cycle_min_mom = cycle_min_mom
        self.cycle_max_mom = cycle_max_mom
        self.decay_mom_rate = decay_mom_rate

    def _decay_interval(self, step):
        """Reference decay iteration: step - total_size + 1, scaled by
        decay_step_size (lr_schedules.py:615-625, 643)."""
        decay_iter = step - self.total_cycle_size + 1
        return decay_iter / max(1, self.decay_step_size)

    def lr_at(self, step):
        in_cycle_lr = self._cycle_lr(step)
        decayed = self._decay_lr(step)
        if isinstance(step, int):
            return in_cycle_lr if step < self.total_cycle_size else decayed
        return jnp.where(step < self.total_cycle_size, in_cycle_lr, decayed)

    def _cycle_lr(self, step):
        # Piecewise-linear triangle over [0, first+second].
        up = self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * (
            step / self.first_step_size)
        down = self.cycle_max_lr - (self.cycle_max_lr - self.cycle_min_lr) * (
            (step - self.first_step_size) / self.second_step_size)
        if isinstance(step, int):
            return up if step <= self.first_step_size else down
        return jnp.where(step <= self.first_step_size, up, down)

    def _decay_lr(self, step):
        # lr = cycle_min_lr / (1 + decay_lr_rate * decay_interval)
        return self.cycle_min_lr / (1.0 + self.decay_lr_rate * self._decay_interval(step))

    def mom_at(self, step):
        """Cycled momentum (inverse triangle), decaying upward after the cycle
        by decay_mom_rate (reference _get_decay_mom, lr_schedules.py:609-613)."""
        if not self.cycle_momentum:
            return self.cycle_max_mom
        up = self.cycle_max_mom - (self.cycle_max_mom - self.cycle_min_mom) * (
            step / self.first_step_size)
        down = self.cycle_min_mom + (self.cycle_max_mom - self.cycle_min_mom) * (
            (step - self.first_step_size) / self.second_step_size)
        decayed = self.cycle_max_mom * (1.0 + self.decay_mom_rate * self._decay_interval(step))
        if isinstance(step, int):
            if step >= self.total_cycle_size:
                return decayed
            return up if step <= self.first_step_size else down
        return jnp.where(step >= self.total_cycle_size, decayed,
                         jnp.where(step <= self.first_step_size, up, down))


class WarmupLR(_ScheduleBase):
    """Warm up from min_lr to max_lr over warmup_num_steps, then hold.

    warmup_type 'log' uses a logarithmic ramp (reference default), 'linear'
    a linear one.
    """

    def __init__(self, optimizer=None, warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                 warmup_type: str = WARMUP_LOG_RATE, last_batch_iteration: int = -1):
        super().__init__(optimizer, last_batch_iteration)
        self.min_lr = warmup_min_lr
        self.max_lr = warmup_max_lr
        self.warmup_num_steps = max(2, warmup_num_steps)
        if warmup_type not in (WARMUP_LOG_RATE, WARMUP_LINEAR_RATE):
            raise ValueError(f"Unknown warmup_type {warmup_type}")
        self.warmup_type = warmup_type
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)

    def _gamma(self, step):
        if self.warmup_type == WARMUP_LOG_RATE:
            if isinstance(step, int):
                return self.inverse_log_warm_up * math.log(max(0, step) + 1)
            return self.inverse_log_warm_up * jnp.log(jnp.maximum(step, 0) + 1.0)
        return step / self.warmup_num_steps

    def lr_at(self, step):
        gamma = self._gamma(step)
        warm = self.min_lr + (self.max_lr - self.min_lr) * gamma
        if isinstance(step, int):
            return warm if step < self.warmup_num_steps else self.max_lr
        return jnp.where(step < self.warmup_num_steps, warm, self.max_lr)


class WarmupDecayLR(WarmupLR):
    """WarmupLR followed by linear decay to 0 at total_num_steps."""

    def __init__(self, optimizer=None, total_num_steps: int = 10000,
                 warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
                 warmup_num_steps: int = 1000, warmup_type: str = WARMUP_LOG_RATE,
                 last_batch_iteration: int = -1):
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr, warmup_num_steps,
                         warmup_type, last_batch_iteration)
        self.total_num_steps = total_num_steps
        if self.total_num_steps < self.warmup_num_steps:
            from ..utils.logging import logger
            logger.warning(
                f"total_num_steps {total_num_steps} < warmup_num_steps {warmup_num_steps}")

    def lr_at(self, step):
        # Reference: lr = min_lr + delta_lr * gamma with post-warmup
        # gamma = max(0, (total - step)/(total - warmup)) — decays to
        # min_lr, never below it (lr_schedules.py:802-809).
        warm = super().lr_at(step)
        denom = max(1.0, self.total_num_steps - self.warmup_num_steps)
        frac = (self.total_num_steps - step) / denom
        delta = self.max_lr - self.min_lr
        if isinstance(step, int):
            if step < self.warmup_num_steps:
                return warm
            return self.min_lr + delta * max(0.0, frac)
        decay = self.min_lr + delta * jnp.maximum(0.0, frac)
        return jnp.where(step < self.warmup_num_steps, warm, decay)


SCHEDULE_CLASSES = {
    LR_RANGE_TEST: LRRangeTest,
    ONE_CYCLE: OneCycle,
    WARMUP_LR: WarmupLR,
    WARMUP_DECAY_LR: WarmupDecayLR,
}


def get_lr_schedule(name: str, params: Dict[str, Any], optimizer=None) -> _ScheduleBase:
    """Instantiate a schedule by ds_config name with its param dict."""
    if name not in SCHEDULE_CLASSES:
        raise ValueError(f"Unknown lr schedule {name!r}; valid: {VALID_LR_SCHEDULES}")
    return SCHEDULE_CLASSES[name](optimizer=optimizer, **params)


def add_tuning_arguments(parser):
    """Argparse plumbing parity (lr_schedules.py:54-298)."""
    group = parser.add_argument_group("Convergence Tuning", "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help="LR schedule for training.")
    group.add_argument("--lr_range_test_min_lr", type=float, default=0.001)
    group.add_argument("--lr_range_test_step_rate", type=float, default=1.0)
    group.add_argument("--lr_range_test_step_size", type=int, default=1000)
    group.add_argument("--lr_range_test_staircase", type=bool, default=False)
    group.add_argument("--cycle_first_step_size", type=int, default=1000)
    group.add_argument("--cycle_first_stair_count", type=int, default=1)
    group.add_argument("--cycle_second_step_size", type=int, default=-1)
    group.add_argument("--cycle_second_stair_count", type=int, default=-1)
    group.add_argument("--decay_step_size", type=int, default=1000)
    group.add_argument("--cycle_min_lr", type=float, default=0.01)
    group.add_argument("--cycle_max_lr", type=float, default=0.1)
    group.add_argument("--decay_lr_rate", type=float, default=0.0)
    group.add_argument("--cycle_momentum", type=bool, default=False)
    group.add_argument("--cycle_min_mom", type=float, default=0.8)
    group.add_argument("--cycle_max_mom", type=float, default=0.9)
    group.add_argument("--decay_mom_rate", type=float, default=0.0)
    group.add_argument("--warmup_min_lr", type=float, default=0)
    group.add_argument("--warmup_max_lr", type=float, default=0.001)
    group.add_argument("--warmup_num_steps", type=int, default=1000)
    group.add_argument("--warmup_type", type=str, default=WARMUP_LOG_RATE)
    return parser
