"""Config helpers.

Parity with reference ``runtime/config_utils.py``: scalar/dict param getters
with defaults and duplicate-key-rejecting JSON loading (config_utils.py:20-33).
"""
from __future__ import annotations

import json
from typing import Any, Dict, List


def get_scalar_param(param_dict: Dict[str, Any], param_name: str, param_default_value: Any) -> Any:
    return param_dict.get(param_name, param_default_value)

def get_list_param(param_dict: Dict[str, Any], param_name: str, param_default_value: Any) -> Any:
    return param_dict.get(param_name, param_default_value)

def get_dict_param(param_dict: Dict[str, Any], param_name: str, param_default_value: Any) -> Any:
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs: List[tuple]) -> Dict[str, Any]:
    """Reject duplicate keys while parsing JSON (reference config_utils.py:20)."""
    d = dict(ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter: Dict[str, int] = {}
        for k, _ in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError(f"Duplicate keys in DeepSpeed config: {keys}")
    return d


def load_config_json(path: str) -> Dict[str, Any]:
    with open(path, "r") as f:
        return json.load(f, object_pairs_hook=dict_raise_error_on_duplicate_keys)


def loads_config_json(text: str) -> Dict[str, Any]:
    return json.loads(text, object_pairs_hook=dict_raise_error_on_duplicate_keys)
