"""Progressive layer drop.

Parity with reference ``runtime/progressive_layer_drop.py``: per-step keep
probability theta(t) = (1 - theta_f) * exp(-gamma * t) + theta_f
(progressive_layer_drop.py:29-37). The engine advances it each step and
models consume ``get_theta()`` (jit-safe pure form: ``theta_at(step)``).
"""
from __future__ import annotations

import math

import jax.numpy as jnp


class ProgressiveLayerDrop:
    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def theta_at(self, step):
        """Jit-safe keep-prob at a given global step."""
        if isinstance(step, int):
            return (1.0 - self.theta) * math.exp(-self.gamma * step) + self.theta
        return (1.0 - self.theta) * jnp.exp(-self.gamma * step) + self.theta

    def update_state(self, global_step) -> None:
        self.current_theta = float(self.theta_at(int(global_step)))

    def get_state(self) -> dict:
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self) -> float:
        return self.current_theta
