"""Async preemption-safe checkpointing: snapshot-to-host, background
writer, and a two-phase atomic commit.

The reference engine's ``save_checkpoint`` (engine.py:1472-1572) is a
stop-the-world path: every serialized byte is wall-clock the training
loop pays for. On preemptible pods that stall is the dominant goodput
loss, and an ungraceful SIGTERM mid-write used to be able to leave a
half-written tag dir behind a ``latest`` pointer that named it. This
module splits the save into the three pieces the reference conflated:

1. **Snapshot** (in the step window, exposed): the engine fetches the
   sharded state into host buffers with ONE batched ``jax.device_get``
   — the telemetry drain's batched-fetch discipline, fence-asserted in
   tier-1 — and builds a :class:`CheckpointSnapshot`: host arrays plus
   lazy blob builders. No serialization happens here.
2. **Write** (background, overlapped): :class:`AsyncCheckpointer`'s
   writer thread serializes the blobs and runs the commit off the
   critical path, guarded by a dedicated hang watchdog
   (monitor/health.py) and priced into the goodput ledger's
   ``checkpoint_write`` BACKGROUND bucket (reported, but not counted
   against the window wall — it overlaps useful compute).
3. **Commit** (:func:`commit_snapshot`, shared with the sync path): a
   two-phase atomic protocol. Blobs land in ``<tag>.tmp``;
   ``engine_meta.json`` is written LAST and seals the dir (its presence
   is the completeness marker the load path checks); the sealed dir
   renames to ``<tag>`` in one ``os.rename``; ``latest`` flips via a
   tmp file + ``os.replace``. A kill at ANY byte offset leaves either
   the previous or the new checkpoint fully loadable — never a torn
   one.

Preemption-safety end to end: :class:`PreemptSaver` hooks SIGTERM
(chaining with the flight recorder's handler exactly like
monitor/flight.py chains with whatever preceded it) and asks the engine
for a final snapshot+commit when one isn't already in flight, then
re-raises so the exit code stays honest. ``tools/crashkill.py`` is the
proof harness: train, kill at a random step (including mid-write),
auto-resume from ``latest`` at a different world size, assert the
trajectory against an uninterrupted run.

Crash-point injection (``DS_CKPT_CRASH_POINT``) lets the crash-matrix
tests SIGKILL the process at exact protocol offsets — a real kill, not
a mocked one, so the atomicity claim is subprocess-tested with honest
exit codes. ``DS_CKPT_TEST_WRITE_DELAY_S`` slows the writer so external
kills can land mid-write deterministically.
"""
from __future__ import annotations

import atexit
import glob
import json
import os
import queue
import shutil
import signal
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from ..utils.logging import log_dist, logger

LATEST_FILE = "latest"
META_FILE = "engine_meta.json"
TMP_SUFFIX = ".tmp"

# Crash-matrix injection points, in protocol order. Each names an exact
# byte offset in the commit; setting DS_CKPT_CRASH_POINT to one makes
# the process SIGKILL ITSELF there (no cleanup, no atexit — the honest
# simulation of a preemption landing at that instant).
CRASH_POINTS = ("after_snapshot", "mid_blob_write", "pre_seal",
                "pre_commit", "pre_latest", "mid_latest")


def crash_point(name: str) -> None:
    if os.environ.get("DS_CKPT_CRASH_POINT") == name:
        os.kill(os.getpid(), signal.SIGKILL)


# A blob is (filename, bytes | zero-arg builder returning bytes). The
# builder form defers serialization to the writer thread — the snapshot
# phase only captures host arrays.
Blob = Tuple[str, Union[bytes, Callable[[], bytes]]]


@dataclass
class CheckpointSnapshot:
    """Host-side capture of one checkpoint: everything the writer needs,
    nothing that can touch a device."""
    save_dir: str
    tag: str
    save_latest: bool
    meta: Dict[str, Any]
    blobs: List[Blob]
    is_writer: bool = True
    fsync: bool = False
    created_ts: float = field(default_factory=time.time)

    @property
    def path(self) -> str:
        return os.path.join(self.save_dir, str(self.tag))


def is_complete(path: str) -> bool:
    """The completeness marker: ``engine_meta.json`` is written last
    inside the tmp dir, so a committed tag dir always carries it and a
    torn one never does."""
    return os.path.isfile(os.path.join(path, META_FILE))


def _fsync_file(f) -> None:
    f.flush()
    os.fsync(f.fileno())


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_blob(path: str, data: bytes, fsync: bool) -> None:
    """Write one blob with a mid-write crash point: the first half lands
    and is flushed before the (armed) kill, so 'kill at any byte offset'
    is tested against a genuinely half-written file."""
    with open(path, "wb") as f:
        half = len(data) // 2
        f.write(data[:half])
        f.flush()
        crash_point("mid_blob_write")
        f.write(data[half:])
        if fsync:
            _fsync_file(f)


def _tmp_pid(path: str) -> Optional[int]:
    """The pid embedded in a ``<tag>.tmp.<pid>.<tid>`` staging-dir name
    (None for legacy/unparsable names)."""
    parts = path.rsplit(TMP_SUFFIX + ".", 1)
    if len(parts) != 2:
        return None
    try:
        return int(parts[1].split(".", 1)[0])
    except ValueError:
        return None


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        pass
    return True


def write_latest(save_dir: str, tag: str, fsync: bool = False) -> None:
    """Flip the ``latest`` pointer atomically: tmp file + ``os.replace``.
    A reader never observes a partial pointer."""
    tmp = os.path.join(
        save_dir,
        f"{LATEST_FILE}.tmp.{os.getpid()}.{threading.get_ident()}")
    # Sweep pointer tmp files orphaned by a kill between write and
    # os.replace (same dead-pid rule as the staging-dir sweep) so a
    # long-lived save_dir doesn't accumulate junk across preemptions.
    for stale in glob.glob(os.path.join(save_dir, LATEST_FILE + ".tmp*")):
        pid = _tmp_pid(stale)
        if stale != tmp and (pid is None or not _pid_alive(pid)):
            try:
                os.remove(stale)
            except OSError:
                pass
    with open(tmp, "w") as f:
        f.write(str(tag))
        if fsync:
            _fsync_file(f)
    crash_point("mid_latest")
    os.replace(tmp, os.path.join(save_dir, LATEST_FILE))
    if fsync:
        _fsync_dir(save_dir)


def commit_snapshot(snap: CheckpointSnapshot) -> str:
    """Serialize and commit a snapshot with the two-phase protocol.
    Host-only — safe to run on the writer thread or inline (the sync
    path and the async path share this byte-for-byte, which is what the
    async-vs-sync artifact bit-identity test checks)."""
    final = snap.path
    if not snap.is_writer:
        # Non-writer SPMD processes participated in the snapshot fetch
        # (the device_get is collective-shaped) but write nothing.
        return final
    delay = float(os.environ.get("DS_CKPT_TEST_WRITE_DELAY_S", "0") or 0)
    # The tmp dir is pid+thread-unique: a preemption-save racing a
    # wedged background writer on the SAME tag must not share (and
    # rmtree-stomp) the writer's staging dir — each commit stages in
    # its own dir, each rename publishes an internally-complete dir,
    # and the last rename wins whole.
    tmp_dir = f"{final}{TMP_SUFFIX}.{os.getpid()}.{threading.get_ident()}"
    for stale in glob.glob(final + TMP_SUFFIX + "*"):
        # Stale tmp dirs (a killed writer's — never renamed, garbage by
        # construction) are cleared; a LIVE process's staging dir is
        # left alone. Legacy/unparsable names count as stale.
        pid = _tmp_pid(stale)
        if stale == tmp_dir or pid is None or not _pid_alive(pid):
            shutil.rmtree(stale, ignore_errors=True)
    os.makedirs(tmp_dir)
    for fname, builder in snap.blobs:
        data = builder() if callable(builder) else builder
        _write_blob(os.path.join(tmp_dir, fname), data, snap.fsync)
        if delay > 0:
            time.sleep(delay)
    crash_point("pre_seal")
    # The seal: meta is written LAST, so its presence certifies every
    # blob above it landed whole (within this tmp dir).
    meta_tmp = os.path.join(tmp_dir, META_FILE)
    with open(meta_tmp, "w") as f:
        json.dump(snap.meta, f)
        if snap.fsync:
            _fsync_file(f)
    if snap.fsync:
        _fsync_dir(tmp_dir)
    crash_point("pre_commit")
    # Publish: swing the sealed staging dir in. When the tag already
    # exists (same-tag overwrite, or a racing commit of the same tag
    # just published), park the old dir under a unique trash name and
    # retry — each published dir is internally complete, so whichever
    # rename lands last wins whole. The only non-atomic window is
    # between the two renames of a same-tag overwrite; the auto-save /
    # preemption cycle always uses fresh global_stepN tags and never
    # enters it.
    trash = f"{final}.old.{os.getpid()}.{threading.get_ident()}"
    for _ in range(8):
        if os.path.exists(final):
            if os.path.isdir(trash):
                shutil.rmtree(trash)
            try:
                os.rename(final, trash)
            except FileNotFoundError:
                pass          # a racing commit moved it first
        try:
            os.rename(tmp_dir, final)
            break
        except OSError:
            continue          # final reappeared under the race; re-park
    else:
        raise OSError(f"could not publish checkpoint {final}")
    shutil.rmtree(trash, ignore_errors=True)
    if snap.fsync:
        _fsync_dir(snap.save_dir)
    crash_point("pre_latest")
    if snap.save_latest:
        write_latest(snap.save_dir, snap.tag, fsync=snap.fsync)
    return final


class AsyncCheckpointer:
    """Single background writer serializing/committing snapshots off the
    critical path.

    - Submission order IS commit order (one thread, one queue), so
      ``latest`` only ever moves forward.
    - ``wait_below(n)`` bounds host memory: the engine blocks (exposed,
      counted in the goodput ``checkpoint`` bucket via the enclosing
      snapshot span) until fewer than ``n`` snapshots are pending.
    - A dedicated hang watchdog (factor=1, min timeout =
      ``writer_timeout_s``) guards each write: a wedged writer fires an
      all-thread stack dump + telemetry event instead of silently
      stalling the next snapshot forever.
    - Write wall is reported to the goodput ledger's BACKGROUND
      ``checkpoint_write`` bucket — visible, but not charged against
      the window (it overlaps the step stream).
    """

    def __init__(self, telemetry=None, writer_timeout_s: float = 300.0,
                 dump_dir: str = "."):
        self._telemetry = telemetry
        self.writer_timeout_s = float(writer_timeout_s)
        self.dump_dir = dump_dir
        self._q: "queue.Queue[Optional[CheckpointSnapshot]]" = queue.Queue()
        # RLock, not Lock: preempt_save runs in a SIGNAL HANDLER on the
        # main thread, which may have been interrupted INSIDE submit()/
        # wait_below() while holding this lock — a non-reentrant lock
        # would deadlock the handler (and lose the final preemption
        # save). Condition handles the recursive hold via
        # _release_save/_acquire_restore.
        self._lock = threading.RLock()
        self._idle = threading.Condition(self._lock)
        self._pending = 0
        self._thread: Optional[threading.Thread] = None
        self._watchdog = None
        self.writes = 0
        self.write_wall_s = 0.0
        self.last_error: Optional[BaseException] = None
        self._closed = False
        atexit.register(self.close)

    # ------------------------------------------------------------------ #
    @property
    def in_flight(self) -> bool:
        with self._lock:
            return self._pending > 0

    def submit(self, snap: CheckpointSnapshot) -> None:
        if self._closed:
            raise RuntimeError("AsyncCheckpointer is closed")
        with self._lock:
            self._pending += 1
        self._q.put(snap)
        self._ensure_thread()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted snapshot has committed (or failed
        — check ``last_error``). True when drained."""
        return self.wait_below(1, timeout=timeout)

    def wait_below(self, n: int, timeout: Optional[float] = None) -> bool:
        with self._idle:
            return self._idle.wait_for(lambda: self._pending < n,
                                       timeout=timeout)

    def close(self, flush: bool = True) -> None:
        """Flush pending writes and stop the thread. Registered atexit
        (AFTER the engine's Telemetry, so LIFO ordering settles the last
        write's background seconds before telemetry's final drain)."""
        if self._closed:
            return
        self._closed = True
        atexit.unregister(self.close)
        if flush and self._thread is not None:
            self.wait(timeout=self.writer_timeout_s)
        if self._thread is not None:
            self._q.put(None)
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None

    # ------------------------------------------------------------------ #
    def _ensure_thread(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ds-ckpt-writer")
        self._thread.start()

    def _ensure_watchdog(self):
        if self._watchdog is None and self.writer_timeout_s > 0:
            from ..monitor.health import HangWatchdog
            self._watchdog = HangWatchdog(
                factor=1.0, min_timeout_s=self.writer_timeout_s,
                dump_dir=self.dump_dir, on_fire=self._on_watchdog_fire)
            self._watchdog.start()
        return self._watchdog

    def _on_watchdog_fire(self, event: Dict[str, Any]) -> None:
        logger.warning(
            "checkpoint writer exceeded its timeout "
            f"({self.writer_timeout_s:.0f}s) — stacks at "
            f"{event.get('stack_dump_path')}")
        tl = self._telemetry
        if tl is not None:
            try:
                tl.event("watchdog", {**event, "source": "checkpoint_writer"})
            except Exception:
                pass

    def _run(self) -> None:
        while True:
            snap = self._q.get()
            if snap is None:
                return
            wd = self._ensure_watchdog()
            if wd is not None:
                wd.pending(f"checkpoint_write:{snap.tag}")
                wd.beat()
            t0 = time.perf_counter()
            err: Optional[BaseException] = None
            try:
                commit_snapshot(snap)
                self.writes += 1
            except BaseException as e:   # the writer must never die silently
                err = e
                self.last_error = e
                logger.error(
                    f"background checkpoint write of tag '{snap.tag}' "
                    f"failed: {type(e).__name__}: {e}")
            finally:
                if wd is not None:
                    wd.disarm()
                dt = time.perf_counter() - t0
                self.write_wall_s += dt
                tl = self._telemetry
                if tl is not None:
                    try:
                        tl.note_checkpoint_write_bg(dt)
                        if err is None:
                            tl.event("checkpoint_commit", {
                                "tag": str(snap.tag),
                                "write_s": round(dt, 6),
                                "queued_s": round(
                                    t0 - snap.created_ts, 6)})
                        else:
                            tl.event("checkpoint_write_error", {
                                "tag": str(snap.tag),
                                "error":
                                    f"{type(err).__name__}: {err}"[:300]})
                    except Exception:
                        pass
                with self._idle:
                    self._pending -= 1
                    self._idle.notify_all()


class PreemptSaver:
    """SIGTERM → final snapshot+commit, then chain.

    Installed AFTER the engine's Telemetry builds its flight recorder,
    so on a preemption this handler runs FIRST (last installed wins the
    dispatch), saves the final checkpoint, and then chains to the flight
    recorder's handler — which persists FLIGHT.json and re-raises under
    the default disposition, keeping the exit code honest
    (``-SIGTERM``). The stale-chain passthrough mirrors
    monitor/flight.py: a newer handler may still point at us after
    uninstall, and a dead engine must not block the signal."""

    def __init__(self, engine, save_dir: str):
        self._ref = weakref.ref(engine)
        self.save_dir = save_dir
        self.fired = False
        self._installed = False
        self._prev: Dict[int, Any] = {}
        self._chain_prev: Dict[int, Any] = {}

    def install(self) -> None:
        if self._installed:
            return
        self._installed = True
        signum = getattr(signal, "SIGTERM", None)
        if signum is None:
            return
        try:
            self._prev[int(signum)] = signal.signal(signum, self._on_signal)
        except (ValueError, OSError):
            # Not the main thread / restricted env: preemption saving is
            # best-effort; periodic auto-saves still bound the loss.
            pass

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        self._chain_prev.update(self._prev)
        for signum, prev in self._prev.items():
            try:
                if signal.getsignal(signum) == self._on_signal:
                    signal.signal(signum, signal.SIG_DFL
                                  if prev is None else prev)
            except (ValueError, OSError, TypeError):
                pass
        self._prev = {}

    def _on_signal(self, signum, frame) -> None:
        from ..monitor.flight import dispatch_prev_handler
        if not self._installed:
            dispatch_prev_handler(
                self._chain_prev.get(int(signum), signal.SIG_DFL),
                signum, frame, self._on_signal)
            return
        self.fired = True
        prev = self._prev.get(int(signum), signal.SIG_DFL)
        eng = self._ref()
        if eng is not None:
            try:
                eng.preempt_save(reason="SIGTERM")
            except Exception as e:
                # A failed final save must not mask the preemption.
                try:
                    logger.error(f"preemption save failed: "
                                 f"{type(e).__name__}: {e}")
                except Exception:
                    pass
        self.uninstall()
        dispatch_prev_handler(prev, signum, frame, self._on_signal)


__all__ = ["CheckpointSnapshot", "AsyncCheckpointer", "PreemptSaver",
           "commit_snapshot", "write_latest", "is_complete", "crash_point",
           "CRASH_POINTS", "LATEST_FILE", "META_FILE"]
