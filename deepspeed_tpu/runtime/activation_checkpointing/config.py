"""Activation checkpointing configuration.

Parity with reference ``runtime/activation_checkpointing/config.py``.

On TPU these knobs map onto ``jax.checkpoint`` (remat) policies:
``partition_activations`` shards saved residuals over the model-parallel axis,
``cpu_checkpointing`` offloads them to host memory
(``jax.ad_checkpoint.checkpoint_policies.offload_*``), and
``number_checkpoints`` bounds how many boundaries are saved.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from .. import config_utils
from ... import constants as C


class ActivationCheckpointingConfig:
    def __init__(self, param_dict: Optional[Dict[str, Any]] = None):
        self.partition_activations = C.ACT_CHKPT_PARTITION_ACTIVATIONS_DEFAULT
        self.contiguous_memory_optimization = C.ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION_DEFAULT
        self.cpu_checkpointing = C.ACT_CHKPT_CPU_CHECKPOINTING_DEFAULT
        self.number_checkpoints = C.ACT_CHKPT_NUMBER_CHECKPOINTS_DEFAULT
        self.synchronize_checkpoint_boundary = C.ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY_DEFAULT
        self.profile = C.ACT_CHKPT_PROFILE_DEFAULT

        if param_dict is not None and C.ACTIVATION_CHECKPOINTING in param_dict:
            d = param_dict[C.ACTIVATION_CHECKPOINTING]
            get = config_utils.get_scalar_param
            self.partition_activations = get(d, C.ACT_CHKPT_PARTITION_ACTIVATIONS,
                                             C.ACT_CHKPT_PARTITION_ACTIVATIONS_DEFAULT)
            self.contiguous_memory_optimization = get(
                d, C.ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION,
                C.ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION_DEFAULT)
            self.cpu_checkpointing = get(d, C.ACT_CHKPT_CPU_CHECKPOINTING,
                                         C.ACT_CHKPT_CPU_CHECKPOINTING_DEFAULT)
            self.number_checkpoints = get(d, C.ACT_CHKPT_NUMBER_CHECKPOINTS,
                                          C.ACT_CHKPT_NUMBER_CHECKPOINTS_DEFAULT)
            self.synchronize_checkpoint_boundary = get(
                d, C.ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY,
                C.ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY_DEFAULT)
            self.profile = get(d, C.ACT_CHKPT_PROFILE, C.ACT_CHKPT_PROFILE_DEFAULT)

    def __repr__(self) -> str:
        return f"ActivationCheckpointingConfig({self.__dict__})"
