"""Activation checkpointing with partitioning and CPU offload.

Parity target: reference ``runtime/activation_checkpointing/
checkpointing.py:370-417`` — CheckpointFunction saves each checkpointed
function's inputs, optionally PARTITIONED across model-parallel ranks
(:370-417 partition + :281-312 re-gather at backward) and optionally
OFFLOADED to CPU memory (``cpu_checkpointing``), replaying RNG states in
backward (:114-263).

TPU-native mechanics — each reference knob maps to a first-class XLA
facility instead of hand-managed buffers:

- checkpointing itself  -> ``jax.checkpoint`` (remat): inputs are saved,
  the body recomputes in backward. RNG "replay" is free: dropout keys are
  explicit fn inputs, so the recompute sees identical randomness by
  construction (no get_rng_state/set_rng_state juggling).
- partition_activations -> the saved inputs carry a
  ``with_sharding_constraint`` over the model-parallel mesh axis, so XLA
  stores 1/mp of each residual per chip and re-gathers when the backward
  recompute consumes it — the reference's partition + gather pair,
  scheduled by the compiler.
- cpu_checkpointing     -> ``save_and_offload_only_these_names``: the
  tagged residuals live in host ("pinned_host") memory between forward
  and backward; XLA inserts the D2H/H2D copies and overlaps them.

The reference's module-level API shape (configure() once, then
``checkpoint(function, *args)`` everywhere) is preserved so ported client
code keeps its call sites.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from ...parallel.topology import MP_AXIS
from ...utils.logging import logger

_CKPT_NAME = "ds_actckpt_input"

# module state set by configure() (reference checkpointing.py:558-604)
_config = {
    "partition_activations": False,
    "cpu_checkpointing": False,
    "mp_axis": MP_AXIS,
    "partition_spec": None,       # override: PartitionSpec for saved inputs
    "configured": False,
}


def configure(mpu=None, deepspeed_config=None,
              partition_activations: Optional[bool] = None,
              contiguous_checkpointing: Optional[bool] = None,
              checkpoint_in_cpu: Optional[bool] = None,
              synchronize: Optional[bool] = None,
              profile: Optional[bool] = None,
              mp_axis: Optional[str] = None,
              partition_spec=None) -> None:
    """Reference-shaped configure (checkpointing.py:558): reads the
    activation_checkpointing section of a DeepSpeedConfig or explicit
    flags. ``contiguous_checkpointing``/``synchronize``/``profile`` are
    accepted for call-site parity; XLA's allocator already packs saved
    residuals contiguously and there are no streams to synchronize."""
    ac = getattr(deepspeed_config, "activation_checkpointing_config", None)
    if ac is not None:
        _config["partition_activations"] = bool(ac.partition_activations)
        _config["cpu_checkpointing"] = bool(ac.cpu_checkpointing)
    if partition_activations is not None:
        _config["partition_activations"] = bool(partition_activations)
    if checkpoint_in_cpu is not None:
        _config["cpu_checkpointing"] = bool(checkpoint_in_cpu)
    if mp_axis is not None:
        _config["mp_axis"] = mp_axis
    if partition_spec is not None:
        _config["partition_spec"] = partition_spec
    _config["configured"] = True


def is_configured() -> bool:
    return bool(_config["configured"])


def _default_spec(ndim: int, mp_axis: str) -> P:
    """Shard the sequence dim ([B, S, ...] activations): batch stays on dp,
    so the mp partition rides dim 1; 1-D/2-D tensors shard dim 0."""
    if ndim >= 3:
        return P(*([None, mp_axis] + [None] * (ndim - 2)))
    return P(*([mp_axis] + [None] * (ndim - 1)))


def checkpoint_wrapper(fn: Callable,
                       partition_activations: Optional[bool] = None,
                       cpu_checkpointing: Optional[bool] = None,
                       mp_axis: Optional[str] = None,
                       partition_spec=None) -> Callable:
    """Wrap ``fn(*args)`` with remat; per-call flags override configure().

    Saved residuals = the float array inputs of ``fn`` (everything else
    recomputes). With partitioning they are stored mp-sharded; with
    cpu_checkpointing they are stored in host memory.
    """
    part = _config["partition_activations"] if partition_activations is None \
        else partition_activations
    cpu = _config["cpu_checkpointing"] if cpu_checkpointing is None \
        else cpu_checkpointing
    axis = mp_axis or _config["mp_axis"]
    spec = partition_spec if partition_spec is not None \
        else _config["partition_spec"]

    if cpu:
        policy = jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=[_CKPT_NAME],
            offload_src="device", offload_dst="pinned_host")
    else:
        policy = jax.checkpoint_policies.save_only_these_names(_CKPT_NAME)

    def tag(x):
        if not hasattr(x, "dtype") or not jnp.issubdtype(x.dtype,
                                                         jnp.floating):
            return x
        if part:
            s = spec if spec is not None else _default_spec(x.ndim, axis)
            x = lax.with_sharding_constraint(x, s)
        return checkpoint_name(x, _CKPT_NAME)

    def inner(*args):
        return fn(*jax.tree_util.tree_map(tag, args))

    return jax.checkpoint(inner, policy=policy)


def checkpoint(function: Callable, *args) -> Any:
    """Reference call-site parity (checkpointing.py CheckpointFunction
    usage: ``checkpoint(fn, *inputs)``)."""
    if not is_configured():
        logger.warning("activation checkpointing used before configure(); "
                       "using defaults")
    return checkpoint_wrapper(function)(*args)


def reset() -> None:
    """Test hook: restore defaults."""
    _config.update(partition_activations=False, cpu_checkpointing=False,
                   mp_axis=MP_AXIS, partition_spec=None, configured=False)
