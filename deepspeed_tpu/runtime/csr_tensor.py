"""CSR (compressed sparse row) tensor for sparse embedding gradients.

Parity with reference ``runtime/csr_tensor.py:11-59`` (CSRTensor.from_dense
/ to_dense / sparse_size) and the engine's sparse allreduce path
(engine.py:1197-1253): embedding-bag gradients touch only the rows whose
tokens appeared in the batch, so shipping (row_indices, row_values) instead
of the dense [vocab, hidden] tensor cuts comm volume by
``batch_rows / vocab``.

TPU posture: inside jit, XLA reduces dense gradients over ICI and fuses the
scatter-add — there is no sparse-collective primitive to target, and the
dense psum is usually faster on-chip. This utility is for the HOST side:
multi-slice DCN parameter sync, checkpoint delta encoding, and the
launcher's elastic state shipping, where wire bytes are the bottleneck.
Row extraction is numpy (data-dependent nnz is untraceable anyway).
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np


class CSRTensor:
    """Row-sparse view of a 2-D tensor (the embedding-gradient shape)."""

    def __init__(self, row_indices: np.ndarray, values: np.ndarray,
                 dense_shape: Tuple[int, int]):
        assert values.ndim == 2 and len(dense_shape) == 2
        assert row_indices.shape[0] == values.shape[0]
        assert values.shape[1] == dense_shape[1]
        self.row_indices = np.asarray(row_indices, np.int64)
        self.values = np.asarray(values)
        self.dense_shape = tuple(dense_shape)

    @classmethod
    def from_dense(cls, dense) -> "CSRTensor":
        dense = np.asarray(dense)
        nz = np.flatnonzero(np.any(dense != 0, axis=1))
        return cls(nz, dense[nz], dense.shape)

    def to_dense(self) -> np.ndarray:
        out = np.zeros(self.dense_shape, self.values.dtype)
        # duplicate rows accumulate (scatter-add semantics, matching the
        # reference's sparse grad coalescing)
        np.add.at(out, self.row_indices, self.values)
        return out

    def sparse_size(self) -> int:
        """Elements stored sparse vs dense (reference csr_tensor.py:52)."""
        return int(self.values.size + self.row_indices.size)

    @property
    def dense_size(self) -> int:
        return int(np.prod(self.dense_shape))

    def add(self, other: "CSRTensor") -> "CSRTensor":
        """Sparse accumulate (the engine's grad-accumulation step for
        sparse grads)."""
        assert self.dense_shape == other.dense_shape
        return CSRTensor(
            np.concatenate([self.row_indices, other.row_indices]),
            np.concatenate([self.values, other.values]), self.dense_shape)

    def coalesce(self) -> "CSRTensor":
        """Merge duplicate rows (sum) and sort indices."""
        uniq, inv = np.unique(self.row_indices, return_inverse=True)
        vals = np.zeros((uniq.size, self.dense_shape[1]), self.values.dtype)
        np.add.at(vals, inv, self.values)
        return CSRTensor(uniq, vals, self.dense_shape)


def all_gather_csr(shards: List[CSRTensor]) -> CSRTensor:
    """Host-side sparse allreduce: concatenate every rank's rows and
    coalesce — semantically the reference's all_gather of CSR halves
    (engine.py:1212-1233) followed by densify-and-sum."""
    assert shards, "need at least one shard"
    out = shards[0]
    for s in shards[1:]:
        out = out.add(s)
    return out.coalesce()
