from .loss_scaler import (LossScaler, DynamicLossScaler, LossScaleState,
                          make_loss_scale_state, update_loss_scale)
