"""Loss scaling.

Parity with reference ``runtime/fp16/loss_scaler.py``: ``LossScaler``
(static, loss_scaler.py:34), ``DynamicLossScaler`` (loss_scaler.py:79-166):
×2 every ``scale_window`` clean steps, ÷2 on overflow with a ``min_scale``
floor and ``delayed_shift`` hysteresis.

TPU-native design: the scaler state is a small pytree of arrays
(``LossScaleState``) carried through the jitted train step; ``update`` is a
pure function the engine calls under ``lax.cond``-free arithmetic (all
branches are ``jnp.where``). The classes below wrap the pure core for
reference-API parity. bf16 training needs none of this and uses scale 1.
"""
from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax.numpy as jnp


class LossScaleState(NamedTuple):
    loss_scale: jnp.ndarray      # f32 scalar
    growth_count: jnp.ndarray    # i32: clean steps since last change
    hysteresis: jnp.ndarray      # i32: remaining tolerated overflows
    dynamic: bool                # static python flag
    scale_window: int
    min_scale: float
    hysteresis_init: int
    scale_factor: float


def make_loss_scale_state(initial_scale: float = 2.0 ** 32, dynamic: bool = True,
                          scale_window: int = 1000, min_scale: float = 1.0,
                          hysteresis: int = 2, scale_factor: float = 2.0) -> LossScaleState:
    return LossScaleState(
        loss_scale=jnp.asarray(initial_scale, jnp.float32),
        growth_count=jnp.asarray(0, jnp.int32),
        hysteresis=jnp.asarray(hysteresis, jnp.int32),
        dynamic=dynamic, scale_window=scale_window, min_scale=min_scale,
        hysteresis_init=hysteresis, scale_factor=scale_factor)


def update_loss_scale(state: LossScaleState, overflow: jnp.ndarray) -> LossScaleState:
    """Pure jit-safe update (reference loss_scaler.py:120-146 semantics):

    - overflow & hysteresis exhausted → scale = max(scale/factor, min_scale)
    - overflow & hysteresis left → consume one hysteresis credit
    - clean step → growth_count+=1; at scale_window, scale *= factor
    """
    if not state.dynamic:
        return state
    overflow = overflow.astype(jnp.bool_)
    hys_left = state.hysteresis > 1
    new_scale_on_overflow = jnp.where(
        hys_left, state.loss_scale,
        jnp.maximum(state.loss_scale / state.scale_factor, state.min_scale))
    new_hys_on_overflow = jnp.where(hys_left, state.hysteresis - 1, state.hysteresis)

    grown = (state.growth_count + 1) % state.scale_window == 0
    new_scale_clean = jnp.where(grown, state.loss_scale * state.scale_factor,
                                state.loss_scale)
    # Growth window also restores hysteresis credits (reference
    # DynamicLossScaler resets cur_hysteresis = delayed_shift at the window,
    # loss_scaler.py:137-146).
    new_hys_clean = jnp.where(grown, state.hysteresis_init, state.hysteresis)

    return state._replace(
        loss_scale=jnp.where(overflow, new_scale_on_overflow, new_scale_clean),
        growth_count=jnp.where(overflow, 0, state.growth_count + 1).astype(jnp.int32),
        hysteresis=jnp.where(overflow, new_hys_on_overflow, new_hys_clean)
        .astype(jnp.int32))


# --------------------------------------------------------------------- #
# Reference-parity class API
# --------------------------------------------------------------------- #
class LossScalerBase:
    def __init__(self, cur_scale: float):
        self.cur_scale = cur_scale

    @property
    def loss_scale(self) -> float:
        return self.cur_scale

    def scale_gradient(self, grads):
        import jax
        return jax.tree_util.tree_map(lambda g: g * self.cur_scale, grads)

    def update_scale(self, overflow: bool) -> None:
        pass

    def backward(self, loss):
        return loss * self.cur_scale


class LossScaler(LossScalerBase):
    """Static loss scale (loss_scaler.py:34)."""

    def __init__(self, scale: float = 1.0):
        super().__init__(scale)

    def has_overflow(self, params) -> bool:
        return False


class DynamicLossScaler(LossScalerBase):
    """Dynamic loss scale (loss_scaler.py:79)."""

    def __init__(self, init_scale: float = 2.0 ** 32, scale_factor: float = 2.0,
                 scale_window: int = 1000, min_scale: float = 1.0,
                 delayed_shift: int = 1, consecutive_hysteresis: bool = False):
        super().__init__(init_scale)
        self.cur_iter = 0
        self.last_overflow_iter = -1
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.min_scale = min_scale
        self.delayed_shift = delayed_shift
        self.cur_hysteresis = delayed_shift
        self.consecutive_hysteresis = consecutive_hysteresis

    def has_overflow_serial(self, tree) -> bool:
        from ..utils import tree_has_inf_or_nan
        import jax
        return bool(jax.device_get(tree_has_inf_or_nan(tree)))

    def update_scale(self, overflow: bool) -> None:
        if overflow:
            if self.delayed_shift == 1 or self.cur_hysteresis == 1:
                self.cur_scale = max(self.cur_scale / self.scale_factor, self.min_scale)
            else:
                self.cur_hysteresis -= 1
            self.last_overflow_iter = self.cur_iter
        else:
            if self.consecutive_hysteresis:
                self.cur_hysteresis = self.delayed_shift
            if (self.cur_iter - self.last_overflow_iter) % self.scale_window == 0:
                if not self.consecutive_hysteresis:
                    self.cur_hysteresis = self.delayed_shift
                self.cur_scale *= self.scale_factor
        self.cur_iter += 1


# ds_config key names (reference loss_scaler.py:170-221 CreateLossScaler)
INITIAL_LOSS_SCALE = "init_scale"
SCALE_WINDOW = "scale_window"
DELAYED_SHIFT = "delayed_shift"
MIN_LOSS_SCALE = "min_scale"
