"""ZeRO partitioning as sharding specs.

The reference implements optimizer-state partitioning (stage1.py:348-458) and
gradient partitioning (stage2.py:583-738) with manual flatten/bucket/
reduce-to-owner machinery. On TPU the same placement is *declared*: each
optimizer-state leaf gets a NamedSharding that splits it across the dp mesh
axis, and XLA's SPMD partitioner compiles the training step into
reduce-scatter(grads) → sharded update → all-gather(params) — the exact
communication schedule of ZeRO-2 (cf. SURVEY §2.9), chosen automatically and
overlapped by the latency-hiding scheduler instead of hand-managed CUDA
streams.

Stage 3 extends the same declaration to the PARAMETER tree itself
(``stage3_param_specs``): params are born dp-sharded on the same
first-divisible-dim rule grads and moments follow (element alignment — the
optimizer apply stays shard-local), gathered just-in-time for use, and
re-sharded after (runtime/zero/stage3.py holds the gather machinery).
"""
from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _leaf_spec(shape, axis_size: int, axis_name: str) -> P:
    """Shard the first dimension divisible by the axis; else replicate.

    The reference pads flattened groups to make them divisible
    (stage1.py:32-78); we instead keep natural array shapes and replicate the
    (rare, small) leaves that don't divide — same memory story for the bulky
    moment tensors, no repacking.
    """
    for i, d in enumerate(shape):
        if d >= axis_size and d % axis_size == 0:
            return P(*([None] * i + [axis_name]))
    return P()


def _layer_dp(base: P, shape, axis_size: int, axis_name: str) -> P:
    """Add the dp axis onto the first unsharded divisible dim of ``base``."""
    parts = list(base) + [None] * (len(shape) - len(base))
    for i, d in enumerate(shape):
        if parts[i] is None and d >= axis_size and d % axis_size == 0:
            parts[i] = axis_name
            break
    return P(*parts)


_NO_BASE = object()     # sentinel: leaf is NOT param-structured


def base_spec_leaves(opt_state: Any, params: Any, param_specs: Any,
                     default: Any = P()):
    """Per-leaf base (TP) PartitionSpecs for an optimizer-state pytree.

    Optimizer moments mirror the param tree *structurally* (optax states
    nest copies of the param pytree), so subtrees whose treedef equals the
    param treedef inherit ``param_specs`` wholesale; all other leaves
    (step counters etc.) get ``default`` (replicated by default;
    stage3_state_shardings passes the ``_NO_BASE`` sentinel to tell
    "not param-structured" apart from "replicated param"). Structural
    matching avoids the shape-collision trap of keying by array shape
    (two same-shaped params with different specs).
    """
    p_def = jax.tree_util.tree_structure(params)

    def params_like(node) -> bool:
        try:
            return jax.tree_util.tree_structure(node) == p_def
        except Exception:
            return False

    base_tree = jax.tree_util.tree_map(
        lambda node: param_specs if params_like(node) else default,
        opt_state, is_leaf=params_like)
    # Flatten with P treated as a leaf (P is a tuple subclass, so a plain
    # flatten would descend into it).
    return jax.tree_util.tree_leaves(
        base_tree, is_leaf=lambda x: isinstance(x, P) or x is _NO_BASE)


def _leaf_sharding(leaf, base: Optional[P], mesh: Mesh, axis_size: int,
                   axis_name: Optional[str]) -> NamedSharding:
    """The single per-leaf dispatch shared by grads and optimizer moments —
    one implementation so their layouts stay element-aligned by
    construction (no resharding inside the optimizer math)."""
    if not hasattr(leaf, "shape") or getattr(leaf, "ndim", 0) < 1:
        return NamedSharding(mesh, P())
    if base is not None:
        spec = _layer_dp(base, leaf.shape, axis_size, axis_name) \
            if axis_name else base
        return NamedSharding(mesh, spec)
    if axis_name:
        return NamedSharding(
            mesh, _leaf_spec(leaf.shape, axis_size, axis_name))
    return NamedSharding(mesh, P())


def zero_shardings(opt_state: Any, mesh: Mesh, axis_name: Optional[str],
                   params: Any = None, param_specs: Any = None) -> Any:
    """NamedShardings for an optax state pytree.

    ``axis_name`` (usually the dp axis) is layered onto each leaf's first
    still-unsharded divisible dimension — ZeRO partitioning. With tensor
    parallelism, pass ``params`` + ``param_specs``: moments keep the TP
    sharding and dp is layered on top — the reference's ZeRO-under-Megatron
    configuration (stage2.py:162-167). ``axis_name=None`` applies only the
    TP layout (no ZeRO).
    """
    axis_size = mesh.shape[axis_name] if axis_name else 1
    leaves, treedef = jax.tree_util.tree_flatten(opt_state)

    if params is not None and param_specs is not None:
        bases = base_spec_leaves(opt_state, params, param_specs)
    else:
        bases = [None] * len(leaves)

    out = [_leaf_sharding(leaf, base, mesh, axis_size, axis_name)
           for leaf, base in zip(leaves, bases)]
    return jax.tree_util.tree_unflatten(treedef, out)


def grad_shardings(params: Any, mesh: Mesh, axis_name: str,
                   param_specs: Any = None) -> Any:
    """ZeRO-2: NamedShardings for the gradient-accumulation buffer.

    The reference's stage 2 never materializes an unpartitioned gradient:
    per-param hooks copy grads into an IPG bucket and reduce each slice to
    its owner rank (stage2.py:613-738). The TPU equivalent is declarative —
    constrain the accumulated grads to be dp-sharded, and XLA compiles the
    cross-dp gradient reduction as reduce-scatter with each chip holding
    1/dp of every gradient, which the sharded optimizer update consumes
    in place before the updated params all-gather.

    With TP (``param_specs``), dp is layered onto each leaf's first free
    divisible dim, mirroring ``zero_shardings`` for the moments so grads,
    moments, and updates are element-aligned (no resharding inside the
    optimizer math).
    """
    axis_size = mesh.shape[axis_name]
    if param_specs is None:
        return jax.tree_util.tree_map(
            lambda p: _leaf_sharding(p, None, mesh, axis_size, axis_name),
            params)
    # tree_map uses params' structure; the matching param_specs subtree at
    # each param leaf is the P itself (flatten_up_to stops at leaves).
    return jax.tree_util.tree_map(
        lambda p, base: _leaf_sharding(p, base, mesh, axis_size, axis_name),
        params, param_specs)


def stage3_param_specs(params: Any, axis_size: int, axis_name: str,
                       param_specs: Any = None,
                       scan_paths: Optional[Any] = None) -> Any:
    """ZeRO-3: per-leaf ``PartitionSpec``s for the PARAMETER tree itself.

    The rule is ``_leaf_spec`` — the same first-divisible-dim rule grads
    (``grad_shardings``) and moments (``zero_shardings``) follow, so
    params, grads and optimizer state stay element-aligned and the
    shard-local optimizer apply needs no resharding.

    ``scan_paths``: predicate ``(path_str) -> bool`` marking leaves the
    model gathers ITSELF per layer inside its stacked-layer scan
    (runtime/zero/stage3.py). For those leaves dim 0 is the layer axis —
    sharding it would turn the per-layer gather into a one-owner
    broadcast and break the scan's layer slicing — so the dp axis goes on
    the first divisible dim >= 1 instead (replicated when none divides).

    With tensor parallelism pass ``param_specs`` (the TP base): dp is
    layered onto each leaf's first free divisible dim, mirroring
    ``grad_shardings``.
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    bases = None
    if param_specs is not None:
        bases = treedef.flatten_up_to(param_specs)

    def spec_for(i: int, path, leaf) -> P:
        shape = getattr(leaf, "shape", None)
        if shape is None or getattr(leaf, "ndim", 0) < 1:
            return P() if bases is None else bases[i]
        scanned = scan_paths is not None and \
            scan_paths(jax.tree_util.keystr(path))
        base = bases[i] if bases is not None else P()
        parts = list(base) + [None] * (len(shape) - len(base))
        start = 1 if scanned else 0
        for d in range(start, len(shape)):
            if parts[d] is None and shape[d] >= axis_size \
                    and shape[d] % axis_size == 0:
                parts[d] = axis_name
                break
        # No divisible dim (scanned leaves additionally skip the layer
        # axis): stays replicated over dp — correct, just unpartitioned.
        return P(*parts)

    specs = [spec_for(i, path, leaf) for i, (path, leaf) in enumerate(flat)]
    return jax.tree_util.tree_unflatten(treedef, specs)


def stage3_state_shardings(opt_state: Any, mesh: Mesh, axis_name: str,
                           params: Any, stage3_specs: Any) -> Any:
    """Stage-3 optimizer-state shardings: moments MIRROR the stage-3
    param layout wherever the state is param-structured (so the
    shard-local update needs no resharding between grad, param and
    moment), and non-param-structured leaves (the fused optimizer's flat
    moment buffers) fall back to the plain ``_leaf_spec`` dp rule —
    their V-interleaved rows stay dp-sharded exactly as under stage
    1/2."""
    axis_size = int(mesh.shape[axis_name])
    bases = base_spec_leaves(opt_state, params, stage3_specs,
                             default=_NO_BASE)
    leaves, treedef = jax.tree_util.tree_flatten(opt_state)
    out = []
    for leaf, base in zip(leaves, bases):
        if not hasattr(leaf, "shape") or getattr(leaf, "ndim", 0) < 1:
            out.append(NamedSharding(mesh, P()))
        elif base is not _NO_BASE:
            out.append(NamedSharding(mesh, base))
        else:
            out.append(NamedSharding(
                mesh, _leaf_spec(leaf.shape, axis_size, axis_name)))
    return jax.tree_util.tree_unflatten(treedef, out)


def spec_dp_dim(spec: P, axis_name: str) -> Optional[int]:
    """Index of the dimension ``spec`` partitions over ``axis_name``
    (None when unsharded on that axis)."""
    for i, entry in enumerate(spec):
        if entry == axis_name or (isinstance(entry, (tuple, list)) and
                                  axis_name in entry):
            return i
    return None


def describe_sharding(opt_state: Any, shardings: Any) -> str:
    """Human-readable partition report (parity with stage1's logging)."""
    lines = []
    leaves, _ = jax.tree_util.tree_flatten(opt_state)
    shard_leaves, _ = jax.tree_util.tree_flatten(shardings)
    sharded = replicated = 0
    for leaf, sh in zip(leaves, shard_leaves):
        if hasattr(leaf, "shape") and any(s is not None for s in sh.spec):
            sharded += getattr(leaf, "size", 0)
        else:
            replicated += getattr(leaf, "size", 0)
    total = max(1, sharded + replicated)
    lines.append(f"ZeRO sharding: {sharded/total:.1%} of optimizer-state "
                 f"elements partitioned, {replicated/total:.1%} replicated")
    return "\n".join(lines)
