"""ZeRO partitioning as sharding specs.

The reference implements optimizer-state partitioning (stage1.py:348-458) and
gradient partitioning (stage2.py:583-738) with manual flatten/bucket/
reduce-to-owner machinery. On TPU the same placement is *declared*: each
optimizer-state leaf gets a NamedSharding that splits it across the dp mesh
axis, and XLA's SPMD partitioner compiles the training step into
reduce-scatter(grads) → sharded update → all-gather(params) — the exact
communication schedule of ZeRO-2 (cf. SURVEY §2.9), chosen automatically and
overlapped by the latency-hiding scheduler instead of hand-managed CUDA
streams.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _leaf_spec(shape, axis_size: int, axis_name: str) -> P:
    """Shard the first dimension divisible by the axis; else replicate.

    The reference pads flattened groups to make them divisible
    (stage1.py:32-78); we instead keep natural array shapes and replicate the
    (rare, small) leaves that don't divide — same memory story for the bulky
    moment tensors, no repacking.
    """
    for i, d in enumerate(shape):
        if d >= axis_size and d % axis_size == 0:
            return P(*([None] * i + [axis_name]))
    return P()


def _layer_dp(base: P, shape, axis_size: int, axis_name: str) -> P:
    """Add the dp axis onto the first unsharded divisible dim of ``base``."""
    parts = list(base) + [None] * (len(shape) - len(base))
    for i, d in enumerate(shape):
        if parts[i] is None and d >= axis_size and d % axis_size == 0:
            parts[i] = axis_name
            break
    return P(*parts)


def base_spec_leaves(opt_state: Any, params: Any, param_specs: Any):
    """Per-leaf base (TP) PartitionSpecs for an optimizer-state pytree.

    Optimizer moments mirror the param tree *structurally* (optax states
    nest copies of the param pytree), so subtrees whose treedef equals the
    param treedef inherit ``param_specs`` wholesale; all other leaves
    (step counters etc.) are replicated. Structural matching avoids the
    shape-collision trap of keying by array shape (two same-shaped params
    with different specs).
    """
    p_def = jax.tree_util.tree_structure(params)

    def params_like(node) -> bool:
        try:
            return jax.tree_util.tree_structure(node) == p_def
        except Exception:
            return False

    base_tree = jax.tree_util.tree_map(
        lambda node: param_specs if params_like(node) else P(),
        opt_state, is_leaf=params_like)
    # Flatten with P treated as a leaf (P is a tuple subclass, so a plain
    # flatten would descend into it).
    return jax.tree_util.tree_leaves(
        base_tree, is_leaf=lambda x: isinstance(x, P))


def _leaf_sharding(leaf, base: Optional[P], mesh: Mesh, axis_size: int,
                   axis_name: Optional[str]) -> NamedSharding:
    """The single per-leaf dispatch shared by grads and optimizer moments —
    one implementation so their layouts stay element-aligned by
    construction (no resharding inside the optimizer math)."""
    if not hasattr(leaf, "shape") or getattr(leaf, "ndim", 0) < 1:
        return NamedSharding(mesh, P())
    if base is not None:
        spec = _layer_dp(base, leaf.shape, axis_size, axis_name) \
            if axis_name else base
        return NamedSharding(mesh, spec)
    if axis_name:
        return NamedSharding(
            mesh, _leaf_spec(leaf.shape, axis_size, axis_name))
    return NamedSharding(mesh, P())


def zero_shardings(opt_state: Any, mesh: Mesh, axis_name: Optional[str],
                   params: Any = None, param_specs: Any = None) -> Any:
    """NamedShardings for an optax state pytree.

    ``axis_name`` (usually the dp axis) is layered onto each leaf's first
    still-unsharded divisible dimension — ZeRO partitioning. With tensor
    parallelism, pass ``params`` + ``param_specs``: moments keep the TP
    sharding and dp is layered on top — the reference's ZeRO-under-Megatron
    configuration (stage2.py:162-167). ``axis_name=None`` applies only the
    TP layout (no ZeRO).
    """
    axis_size = mesh.shape[axis_name] if axis_name else 1
    leaves, treedef = jax.tree_util.tree_flatten(opt_state)

    if params is not None and param_specs is not None:
        bases = base_spec_leaves(opt_state, params, param_specs)
    else:
        bases = [None] * len(leaves)

    out = [_leaf_sharding(leaf, base, mesh, axis_size, axis_name)
           for leaf, base in zip(leaves, bases)]
    return jax.tree_util.tree_unflatten(treedef, out)


def grad_shardings(params: Any, mesh: Mesh, axis_name: str,
                   param_specs: Any = None) -> Any:
    """ZeRO-2: NamedShardings for the gradient-accumulation buffer.

    The reference's stage 2 never materializes an unpartitioned gradient:
    per-param hooks copy grads into an IPG bucket and reduce each slice to
    its owner rank (stage2.py:613-738). The TPU equivalent is declarative —
    constrain the accumulated grads to be dp-sharded, and XLA compiles the
    cross-dp gradient reduction as reduce-scatter with each chip holding
    1/dp of every gradient, which the sharded optimizer update consumes
    in place before the updated params all-gather.

    With TP (``param_specs``), dp is layered onto each leaf's first free
    divisible dim, mirroring ``zero_shardings`` for the moments so grads,
    moments, and updates are element-aligned (no resharding inside the
    optimizer math).
    """
    axis_size = mesh.shape[axis_name]
    if param_specs is None:
        return jax.tree_util.tree_map(
            lambda p: _leaf_sharding(p, None, mesh, axis_size, axis_name),
            params)
    # tree_map uses params' structure; the matching param_specs subtree at
    # each param leaf is the P itself (flatten_up_to stops at leaves).
    return jax.tree_util.tree_map(
        lambda p, base: _leaf_sharding(p, base, mesh, axis_size, axis_name),
        params, param_specs)


def describe_sharding(opt_state: Any, shardings: Any) -> str:
    """Human-readable partition report (parity with stage1's logging)."""
    lines = []
    leaves, _ = jax.tree_util.tree_flatten(opt_state)
    shard_leaves, _ = jax.tree_util.tree_flatten(shardings)
    sharded = replicated = 0
    for leaf, sh in zip(leaves, shard_leaves):
        if hasattr(leaf, "shape") and any(s is not None for s in sh.spec):
            sharded += getattr(leaf, "size", 0)
        else:
            replicated += getattr(leaf, "size", 0)
    total = max(1, sharded + replicated)
    lines.append(f"ZeRO sharding: {sharded/total:.1%} of optimizer-state "
                 f"elements partitioned, {replicated/total:.1%} replicated")
    return "\n".join(lines)
