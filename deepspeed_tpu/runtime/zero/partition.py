"""ZeRO partitioning as sharding specs.

The reference implements optimizer-state partitioning (stage1.py:348-458) and
gradient partitioning (stage2.py:583-738) with manual flatten/bucket/
reduce-to-owner machinery. On TPU the same placement is *declared*: each
optimizer-state leaf gets a NamedSharding that splits it across the dp mesh
axis, and XLA's SPMD partitioner compiles the training step into
reduce-scatter(grads) → sharded update → all-gather(params) — the exact
communication schedule of ZeRO-2 (cf. SURVEY §2.9), chosen automatically and
overlapped by the latency-hiding scheduler instead of hand-managed CUDA
streams.
"""
from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _leaf_spec(shape, axis_size: int, axis_name: str) -> P:
    """Shard the first dimension divisible by the axis; else replicate.

    The reference pads flattened groups to make them divisible
    (stage1.py:32-78); we instead keep natural array shapes and replicate the
    (rare, small) leaves that don't divide — same memory story for the bulky
    moment tensors, no repacking.
    """
    for i, d in enumerate(shape):
        if d >= axis_size and d % axis_size == 0:
            return P(*([None] * i + [axis_name]))
    return P()


def zero_shardings(opt_state: Any, mesh: Mesh, axis_name: str) -> Any:
    """NamedShardings for an optax state pytree, ZeRO-partitioned over dp."""
    axis_size = mesh.shape[axis_name]

    def spec(leaf):
        if hasattr(leaf, "shape") and getattr(leaf, "ndim", 0) >= 1:
            return NamedSharding(mesh, _leaf_spec(leaf.shape, axis_size, axis_name))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(spec, opt_state)


def describe_sharding(opt_state: Any, shardings: Any) -> str:
    """Human-readable partition report (parity with stage1's logging)."""
    lines = []
    leaves, _ = jax.tree_util.tree_flatten(opt_state)
    shard_leaves, _ = jax.tree_util.tree_flatten(shardings)
    sharded = replicated = 0
    for leaf, sh in zip(leaves, shard_leaves):
        if hasattr(leaf, "shape") and any(s is not None for s in sh.spec):
            sharded += getattr(leaf, "size", 0)
        else:
            replicated += getattr(leaf, "size", 0)
    total = max(1, sharded + replicated)
    lines.append(f"ZeRO sharding: {sharded/total:.1%} of optimizer-state "
                 f"elements partitioned, {replicated/total:.1%} replicated")
    return "\n".join(lines)
