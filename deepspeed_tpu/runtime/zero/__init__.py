from .config import ZeroConfig
