"""ZeRO-Offload: optimizer state and master weights in TPU-VM host RAM.

Parity target: reference stage2 ``cpu_offload`` (stage2.py:156,326-342,
775-873,1416-1427) + ``DeepSpeedCPUAdam`` (csrc/adam/cpu_adam.cpp). The
device keeps only compute-dtype params; fp32 masters and both Adam moments
live in host numpy arrays, updated by the C++ SIMD kernel
(ops/cpu_adam.py), and the updated params return to HBM as a bf16 staging
buffer produced in the same pass (ds_adam_step_plus_copy parity).

Per step: device computes loss-scaled fp32 grads (dp-sharded under stage 2)
→ D2H → host computes the global grad norm (overflow vote + clip coeff,
stage2.py:1371-1411 semantics) → SIMD Adam on the masters → H2D of the
compute-dtype params. The H2D transfer is dispatched asynchronously
(jax.device_put returns immediately); the next step's forward overlaps it.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ... import constants as C
from ...ops.cpu_adam import (DeepSpeedCPUAdam, _f32_to_bf16_np, _is_bf16,
                             host_f32)
from ...utils.logging import log_dist

# Optimizers that may drive offloaded state (reference zero/utils.py:41
# restricts ZeRO wrapping to known-compatible optimizers the same way).
SUPPORTED = (C.ADAM_OPTIMIZER, C.ADAMW_OPTIMIZER)


def _partition_axis(shape, num: int) -> Optional[int]:
    """First axis divisible by ``num`` — the SAME rule zero/partition.py's
    _leaf_spec uses for grad/moment shardings, so host shards and device
    grad shards are element-aligned by construction."""
    for i, d in enumerate(shape):
        if d >= num and d % num == 0:
            return i
    return None


class ZeroOffloadOptimizer:
    """Host-side optimizer state + step for the engine's offload path.

    ``partition_rank``/``partition_num`` partition the host masters AND
    moments across dp ranks (reference stage2.py:326-342: each rank's host
    buffers hold only its partition): each leaf is sliced along its
    partition axis; leaves with no divisible axis are replicated (every
    rank applies the identical update — same result everywhere). Host RSS
    for the sharded leaves scales as 1/partition_num.
    """

    def __init__(self, master_params: Any, opt_name: str,
                 opt_params: Dict[str, Any], schedule_fn: Callable,
                 compute_dtype, gradient_clipping: float = 0.0,
                 fp16: bool = False, scaler_cfg: Optional[Dict] = None,
                 partition_rank: int = 0, partition_num: int = 1,
                 axis_divisor: Optional[int] = None,
                 sumsq_allreduce: Optional[Callable[[float], float]] = None):
        """``axis_divisor``: divisibility used to PICK each leaf's partition
        axis (defaults to partition_num). The multi-host engine passes the
        dp degree here so the host partition axis coincides with the axis
        zero/partition.py shards the device grads on (dp is a multiple of
        the process count, so the same axis divides both ways).

        ``sumsq_allreduce``: cross-rank sum of the partition-local squared
        grad norm; required for correct clipping when partition_num > 1
        (each rank sees only its shard — without the reduction the clip
        coefficients diverge and replicated leaves drift)."""
        name = (opt_name or C.ADAM_OPTIMIZER).lower()
        if name not in SUPPORTED:
            raise ValueError(
                f"zero_optimization.cpu_offload supports {SUPPORTED}, got "
                f"'{opt_name}' (reference gate: zero/utils.py:41)")
        p = dict(opt_params or {})
        adamw_mode = p.get("adam_w_mode", name == C.ADAMW_OPTIMIZER)

        self.partition_rank = int(partition_rank)
        self.partition_num = int(partition_num)
        self.sumsq_allreduce = sumsq_allreduce
        divisor = int(axis_divisor or self.partition_num)
        if divisor % self.partition_num != 0:
            raise ValueError(f"axis_divisor {divisor} must be a multiple of "
                             f"partition_num {self.partition_num}")
        leaves, self.treedef = jax.tree_util.tree_flatten(master_params)
        self.full_shapes = [np.shape(l) for l in leaves]
        self._axes = [
            _partition_axis(s, divisor)
            if self.partition_num > 1 else None for s in self.full_shapes]
        self.masters = [
            host_f32(self.slice_leaf(i, np.asarray(l, np.float32)))
            for i, l in enumerate(leaves)]
        self.shapes = [m.shape for m in self.masters]
        local_tree = jax.tree_util.tree_unflatten(self.treedef, self.masters)
        self.opt = DeepSpeedCPUAdam(
            local_tree, lr=p.get("lr", 1e-3),
            betas=tuple(p.get("betas", (0.9, 0.999))), eps=p.get("eps", 1e-8),
            weight_decay=p.get("weight_decay", 0.0), adamw_mode=adamw_mode)
        self.schedule_fn = schedule_fn
        self.clip = float(gradient_clipping or 0.0)
        self.compute_dtype = compute_dtype
        self._bf16_staging = None
        if compute_dtype == jnp.bfloat16:
            self._bf16_staging = [np.empty(m.shape, np.uint16)
                                  for m in self.masters]

        # Host-side loss-scale state machine (fp16 offload): mirrors
        # fp16/loss_scaler.py dynamics without device round-trips.
        self.fp16 = fp16
        sc = scaler_cfg or {}
        self.loss_scale = float(sc.get("init_scale", 1.0))
        self.static_scale = bool(sc.get("static", True))
        self.scale_window = int(sc.get("scale_window", 1000))
        self.min_scale = float(sc.get("min_scale", 1.0))
        self.hysteresis_init = int(sc.get("hysteresis", 2))
        self.hysteresis = self.hysteresis_init
        self.growth_count = 0
        self.step_count = 0
        self.skipped_steps = 0

        nbytes = sum(m.nbytes for m in self.masters) + \
            sum(a.nbytes for a in self.opt.exp_avg) + \
            sum(a.nbytes for a in self.opt.exp_avg_sq)
        log_dist(f"ZeRO-Offload: {len(self.masters)} tensors, "
                 f"{nbytes / 2**20:.1f} MiB optimizer state in host RAM "
                 f"(native SIMD: {self.opt.native})", ranks=[0])

    # ------------------------------------------------------------------ #
    def local_param_leaves(self):
        """Compute-dtype param leaves, partition-local, as host arrays
        (bf16 via the fused staging copy — zero additional cast)."""
        import ml_dtypes
        if self.compute_dtype == jnp.bfloat16:
            if self._bf16_staging is not None and self.step_count > 0:
                # zero-copy view of the kernel's fused down-cast output
                return [s.view(ml_dtypes.bfloat16)
                        for s in self._bf16_staging]
            return [m.astype(ml_dtypes.bfloat16) for m in self.masters]
        return [m.astype(np.dtype(self.compute_dtype))
                for m in self.masters]

    def device_params(self, shardings=None) -> Any:
        """Compute-dtype params for HBM. With partition_num > 1 the
        returned leaves are partition-local; the multi-host engine instead
        assembles via _assemble_offload_params (process-sharded upload +
        XLA all-gather)."""
        tree = jax.tree_util.tree_unflatten(self.treedef,
                                            self.local_param_leaves())
        if shardings is not None:
            return jax.device_put(tree, shardings)
        return jax.device_put(tree)

    def master_tree(self) -> Any:
        return jax.tree_util.tree_unflatten(self.treedef, self.masters)

    def slice_leaf(self, i: int, leaf: np.ndarray) -> np.ndarray:
        """Full leaf -> this rank's partition (identity when unsharded or
        already local-shaped)."""
        ax = self._axes[i]
        if ax is None or leaf.shape != self.full_shapes[i]:
            return leaf
        d = leaf.shape[ax] // self.partition_num
        sl = [slice(None)] * leaf.ndim
        sl[ax] = slice(self.partition_rank * d, (self.partition_rank + 1) * d)
        return leaf[tuple(sl)]

    # ------------------------------------------------------------------ #
    def host_step(self, grads: Any) -> Dict[str, float]:
        """One optimizer step from device-computed (loss-scaled) grads.

        Grad leaves may be full-shaped (sliced here to the local partition)
        or already partition-local."""
        # bf16 grads stay bf16: the native Adam/norm kernels widen inline
        # (ops/cpu_adam.py), which removes a full-tree host cast pass and
        # halves the gradient read traffic on the offload host.
        def to_host(g):
            a = np.asarray(g)
            return a if _is_bf16(a) else np.asarray(a, np.float32)

        g_leaves = [self.slice_leaf(i, to_host(g))
                    for i, g in enumerate(jax.tree_util.tree_leaves(grads))]
        inv_scale = 1.0 / self.loss_scale
        if self.partition_num > 1:
            # Partitioned leaves: every rank holds a DISJOINT shard, so the
            # local squared norms sum across ranks. Replicated leaves are
            # identical everywhere and contribute once, outside the
            # reduction. Same decomposition as reference
            # stage2.py:1371-1411's partition-then-allreduce norm.
            part = [g for i, g in enumerate(g_leaves)
                    if self._axes[i] is not None]
            repl = [g for i, g in enumerate(g_leaves)
                    if self._axes[i] is None]
            local_sumsq = self.opt.grad_norm(part, inv_scale) ** 2
            if self.sumsq_allreduce is not None:
                total_sumsq = float(self.sumsq_allreduce(local_sumsq))
            elif self.clip > 0 or self.fp16:
                # Norm DRIVES behavior (clip coeff / overflow vote): a
                # partition-local value would diverge across ranks and
                # drift the replicated leaves apart.
                raise RuntimeError(
                    "partition_num > 1 with gradient clipping or fp16 "
                    "requires sumsq_allreduce (cross-rank norm reduction)")
            else:
                total_sumsq = local_sumsq      # metric-only
            total_sumsq += self.opt.grad_norm(repl, inv_scale) ** 2
            grad_norm = float(np.sqrt(total_sumsq))
        else:
            grad_norm = self.opt.grad_norm(g_leaves, inv_scale)
        overflow = self.fp16 and not np.isfinite(grad_norm)

        if overflow:
            self.skipped_steps += 1
            self._scale_down()
            return {"loss_scale": self.loss_scale, "grad_norm": grad_norm,
                    "overflow": True, "lr": self._lr()}

        coeff = 1.0
        if self.clip > 0 and np.isfinite(grad_norm) and grad_norm > self.clip:
            coeff = self.clip / (grad_norm + 1e-6)
        lr = self._lr()
        self.opt.step(self.masters, g_leaves, lr=lr,
                      grad_scale=inv_scale * coeff,
                      bf16_out=self._bf16_staging)
        self.step_count += 1
        self._scale_up()
        return {"loss_scale": self.loss_scale, "grad_norm": grad_norm,
                "overflow": False, "lr": lr}

    def _lr(self) -> float:
        return float(self.schedule_fn(self.step_count))

    def _scale_down(self) -> None:
        if self.static_scale or not self.fp16:
            return
        if self.hysteresis > 1:
            self.hysteresis -= 1
        else:
            self.loss_scale = max(self.loss_scale / 2.0, self.min_scale)
            self.hysteresis = self.hysteresis_init
        self.growth_count = 0

    def _scale_up(self) -> None:
        if self.static_scale or not self.fp16:
            return
        self.growth_count += 1
        if self.growth_count >= self.scale_window:
            self.loss_scale *= 2.0
            self.growth_count = 0

    # ------------------------------------------------------------------ #
    def state_dict(self) -> Dict[str, Any]:
        return {"optimizer": self.opt.state_dict(),
                "masters": list(self.masters),
                "loss_scale": self.loss_scale,
                "growth_count": self.growth_count,
                "hysteresis": self.hysteresis,
                "step_count": self.step_count,
                "skipped_steps": self.skipped_steps}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.opt.load_state_dict(sd["optimizer"])
        self.set_masters(sd["masters"])
        self.loss_scale = float(sd.get("loss_scale", self.loss_scale))
        self.growth_count = int(sd.get("growth_count", 0))
        self.hysteresis = int(sd.get("hysteresis", self.hysteresis_init))
        self.step_count = int(sd.get("step_count", 0))
        self.skipped_steps = int(sd.get("skipped_steps", 0))

    def set_masters(self, leaves) -> None:
        """Replace the fp32 masters (checkpoint load; full or local-shaped
        leaves). ALWAYS goes through here so the bf16 staging buffers can
        never serve stale weights: device_params() reads staging whenever
        step_count > 0, including on the load_optimizer_states=False path
        that bypasses load_state_dict."""
        self.masters = [
            host_f32(self.slice_leaf(i, np.asarray(m, np.float32)))
            for i, m in enumerate(leaves)]
        self._sync_staging()

    def _sync_staging(self) -> None:
        if self._bf16_staging is not None:
            for buf, m in zip(self._bf16_staging, self.masters):
                buf[...] = _f32_to_bf16_np(m)
